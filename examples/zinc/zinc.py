"""ZINC example: drug-like molecule graph-property regression with GPS
global attention over SchNet (reference: examples/zinc/zinc.py — the ZINC
subset with constrained-solubility target, trained with GPS multihead
attention and Laplacian PE, zinc.json).

The real ZINC download is unavailable here (zero egress); the dataset is
the ZINC-*shaped* generator (``zinc_shaped_dataset``: molecules in the
ZINC size range with an atom-type-index node feature and a
penalized-logP-like closed-form target).

    python examples/zinc/zinc.py [--num_samples 512]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import hydragnn_tpu
from hydragnn_tpu.data import ColumnarWriter, zinc_shaped_dataset

_HERE = os.path.dirname(os.path.abspath(__file__))


def build_dataset(path, num_samples, radius, max_neighbours):
    if os.path.isdir(path):
        return
    graphs = zinc_shaped_dataset(
        number_configurations=num_samples, radius=radius,
        max_neighbours=max_neighbours,
    )
    ColumnarWriter(path).add(graphs).save()
    print(f"wrote {len(graphs)} ZINC-shaped molecules -> {path}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mpnn_type", default=None)
    ap.add_argument("--global_attn_engine", default=None)
    ap.add_argument("--global_attn_type", default=None)
    ap.add_argument("--num_epoch", type=int, default=None)
    ap.add_argument("--num_samples", type=int, default=512)
    args = ap.parse_args()

    with open(os.path.join(_HERE, "zinc.json")) as f:
        config = json.load(f)
    arch = config["NeuralNetwork"]["Architecture"]
    if args.mpnn_type:
        arch["mpnn_type"] = args.mpnn_type
    if args.global_attn_engine is not None:
        arch["global_attn_engine"] = args.global_attn_engine or None
    if args.global_attn_type:
        arch["global_attn_type"] = args.global_attn_type
    if args.num_epoch:
        config["NeuralNetwork"]["Training"]["num_epoch"] = args.num_epoch

    data_path = os.path.join(os.getcwd(), config["Dataset"]["path"]["total"])
    config["Dataset"]["path"]["total"] = data_path
    build_dataset(
        data_path, args.num_samples, arch["radius"], arch["max_neighbours"]
    )

    model, state, hist, config, loaders, mm = hydragnn_tpu.run_training(config)
    tot, tasks, preds, trues = hydragnn_tpu.run_prediction(config, model_state=state)
    mae = float(np.mean(np.abs(preds["free_energy"] - trues["free_energy"])))
    print(f"test loss {tot:.5f}; free_energy MAE {mae:.5f}")


if __name__ == "__main__":
    main()
