"""Multi-dataset "graph foundation model" training with branch-parallel
decoders — the flagship GFM flow
(reference: examples/multibranch/train.py:48-516: several chemistry datasets
train one shared encoder with one decoder branch per dataset, encoder
gradients all-reduced over the world, decoder gradients over per-branch
process groups via MultiTaskModelMP).

TPU-native version: the datasets are concatenated with per-graph
``dataset_id``; every branch decoder computes densely and the output is
selected by dataset id (masked dense compute instead of uneven process
groups — models/base.py _graph_head), so one jitted SPMD program over a
``(branch, data)`` mesh covers the whole fleet: unused branches receive
zero gradients for a given sample, which reproduces the reference's
per-branch gradient flow without MPMD.

    python examples/multibranch/train.py [--epochs N] [--branch_size B]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import dataclasses

import jax
import numpy as np

from hydragnn_tpu.config import update_config
from hydragnn_tpu.data import GraphLoader, MinMax, VariablesOfInterest, \
    branch_sample_weights, deterministic_graph_dataset, extract_variables, \
    split_dataset
from hydragnn_tpu.models import create_model, init_model
from hydragnn_tpu.parallel import make_mesh, replicate_state
from hydragnn_tpu.parallel.dp import (
    ensure_stacked,
    make_parallel_eval_step,
    make_parallel_train_step,
)
from hydragnn_tpu.train import TrainState, make_optimizer


def build_datasets():
    """Two synthetic 'chemistry datasets' with distinct target semantics:
    branch 0 predicts sum(x+x2+x3); branch 1 the linear-only sum."""
    voi = VariablesOfInterest([0], ["target"], ["graph"], [0], [1, 1, 1], [1])
    out = []
    for ds_id, linear in ((0, False), (1, True)):
        raw = deterministic_graph_dataset(160, seed=11 + ds_id, linear_only=linear)
        raw = MinMax.fit(raw).apply(raw)
        graphs = [
            dataclasses.replace(extract_variables(g, voi), dataset_id=ds_id)
            for g in raw
        ]
        out.append(graphs)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--branch_size", type=int, default=1)
    ap.add_argument(
        "--branch_parallel", action="store_true",
        help="shard decoder banks over a (branch=2, data) mesh with "
             "branch-routed loaders (parallel/branch.py)",
    )
    ap.add_argument("--batch_size", type=int, default=32)
    ap.add_argument(
        "--branch_weights", default=None,
        help="comma-separated per-dataset sampling shares, e.g. '2,1' — the "
        "uneven-branch analog (reference sizes branch process groups by "
        "dataset, examples/multibranch/train.py:166-213)",
    )
    args = ap.parse_args()

    datasets = build_datasets()
    merged = [g for ds in datasets for g in ds]
    tr, va, te = split_dataset(merged, 0.8, seed=0)

    head_arch = {
        "num_sharedlayers": 2,
        "dim_sharedlayers": 16,
        "num_headlayers": 2,
        "dim_headlayers": [32, 32],
    }
    config = {
        "Verbosity": {"level": 1},
        "Dataset": {"node_features": {"dim": [1, 1, 1]}, "graph_features": {"dim": [1]}},
        "NeuralNetwork": {
            "Architecture": {
                "mpnn_type": "SAGE",
                "hidden_dim": 32,
                "num_conv_layers": 3,
                "task_weights": [1.0],
                # one decoder branch per dataset (reference:
                # update_multibranch_heads list form, model.py:152-187)
                "output_heads": {
                    "graph": [
                        {"type": "branch-0", "architecture": dict(head_arch)},
                        {"type": "branch-1", "architecture": dict(head_arch)},
                    ]
                },
            },
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_names": ["target"],
                "output_index": [0],
                "type": ["graph"],
            },
            "Training": {
                "num_epoch": args.epochs,
                "batch_size": args.batch_size,
                "Optimizer": {"type": "AdamW", "learning_rate": 0.01},
            },
        },
    }
    config = update_config(config, tr, va, te)

    n_dev = len(jax.devices())
    model = create_model(config)
    tx = make_optimizer(config["NeuralNetwork"]["Training"]["Optimizer"])
    if args.branch_parallel:
        # REAL decoder branch-parallelism (MultiTaskModelMP analog): decoder
        # banks sharded P('branch'), data routed by branch, per-device
        # decoder FLOPs independent of branch count (parallel/branch.py)
        from hydragnn_tpu.parallel.branch import (
            BranchRoutedLoader,
            make_branch_parallel_eval_step,
            make_branch_parallel_train_step,
            place_branch_state,
        )

        mesh = make_mesh(branch_size=2)
        loader = BranchRoutedLoader(
            tr, args.batch_size, branch_count=2, num_shards=n_dev, seed=0
        )
        val_loader = BranchRoutedLoader(
            va, args.batch_size, branch_count=2, num_shards=n_dev,
            shuffle=False, oversampling=False, spec=loader.spec,
        )
        first = next(iter(loader))
        one = jax.tree_util.tree_map(lambda x: np.asarray(x)[0], first)
        variables = init_model(model, one)
        state = place_branch_state(TrainState.create(variables, tx), tx, mesh)
        step = make_branch_parallel_train_step(model, tx, mesh)
        evalf = make_branch_parallel_eval_step(model, mesh)
    else:
        mesh = make_mesh(branch_size=args.branch_size)
        sampling = {}
        if args.branch_weights:
            shares = [float(s) for s in args.branch_weights.split(",")]
            sampling = dict(
                oversampling=True,
                sample_weights=branch_sample_weights(tr, dict(enumerate(shares))),
            )
        loader = GraphLoader(
            tr, args.batch_size, seed=0, num_shards=n_dev, drop_last=True, **sampling
        )
        val_loader = GraphLoader(
            va, args.batch_size, spec=loader.spec, shuffle=False, num_shards=n_dev
        )
        first = ensure_stacked(next(iter(loader)))
        one = jax.tree_util.tree_map(lambda x: np.asarray(x)[0], first)
        variables = init_model(model, one)
        state = replicate_state(TrainState.create(variables, tx), mesh)
        step = make_parallel_train_step(model, tx, mesh)
        evalf = make_parallel_eval_step(model, mesh)

    rng = jax.random.PRNGKey(0)
    for epoch in range(args.epochs):
        loader.set_epoch(epoch)
        for batch in loader:
            rng, sub = jax.random.split(rng)
            state, tot, tasks = step(state, ensure_stacked(batch), sub)
        va_loss, _ = evalf(state, ensure_stacked(next(iter(val_loader))))
        print(f"epoch {epoch}: train {float(tot):.5f} val {float(va_loss):.5f}")


if __name__ == "__main__":
    main()
