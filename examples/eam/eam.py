"""NiNb EAM example: embedded-atom-model alloy training through the
columnar format (reference: examples/eam/eam.py + NiNb_EAM_*.json — Ni/Nb
bulk configurations with per-atom EAM energies from LAMMPS tables; graph
total-energy, node atomic-energy, and node multitask variants).

The real LAMMPS dumps are not shipped here; the dataset is the EAM-shaped
generator (``eam_bulk_dataset``: binary Ni/Nb BCC supercells under a
Finnis-Sinclair embedded-atom functional with per-atom energies and
*analytic* forces — gradient-checked in tests/test_shaped.py).

    python examples/eam/eam.py [--config NiNb_EAM_energy|NiNb_EAM_bulk|NiNb_EAM_multitask]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import hydragnn_tpu
from hydragnn_tpu.data import ColumnarWriter, eam_bulk_dataset

_HERE = os.path.dirname(os.path.abspath(__file__))


def build_dataset(path, num_samples, radius, max_neighbours):
    if os.path.isdir(path):
        return
    graphs = eam_bulk_dataset(
        number_configurations=num_samples, radius=radius,
        max_neighbours=max_neighbours,
    )
    ColumnarWriter(path).add(graphs).save()
    print(f"wrote {len(graphs)} NiNb EAM bulk samples -> {path}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--config", default="NiNb_EAM_energy",
        choices=["NiNb_EAM_energy", "NiNb_EAM_bulk", "NiNb_EAM_multitask"],
    )
    ap.add_argument("--mpnn_type", default=None)
    ap.add_argument("--num_epoch", type=int, default=None)
    ap.add_argument("--num_samples", type=int, default=128)
    args = ap.parse_args()

    with open(os.path.join(_HERE, f"{args.config}.json")) as f:
        config = json.load(f)
    arch = config["NeuralNetwork"]["Architecture"]
    if args.mpnn_type:
        arch["mpnn_type"] = args.mpnn_type
    if args.num_epoch:
        config["NeuralNetwork"]["Training"]["num_epoch"] = args.num_epoch

    data_path = os.path.join(os.getcwd(), config["Dataset"]["path"]["total"])
    config["Dataset"]["path"]["total"] = data_path
    build_dataset(
        data_path, args.num_samples, arch["radius"], arch["max_neighbours"]
    )

    model, state, hist, config, loaders, mm = hydragnn_tpu.run_training(config)
    tot, tasks, preds, trues = hydragnn_tpu.run_prediction(config, model_state=state)
    for name in config["NeuralNetwork"]["Variables_of_interest"]["output_names"]:
        mae = float(np.mean(np.abs(preds[name] - trues[name])))
        print(f"{name} MAE {mae:.5f}")
    print(f"test loss {tot:.5f}")


if __name__ == "__main__":
    main()
