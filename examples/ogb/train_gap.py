"""OGB example: SMILES -> HOMO-LUMO-gap-style regression through the
in-tree SMILES reader (reference: examples/ogb/train_gap.py — PCQM4Mv2-like
SMILES csv with gap labels, rdkit-parsed, PNA model).

Real data: a CSV with ``smiles,gap`` columns via ``--csv``; otherwise the
OGB-*shaped* generator (``smiles_table_dataset`` with a distinct seed).

    python examples/ogb/train_gap.py [--csv FILE] [--num_samples 256]
"""

import argparse
import csv
import json
import os
import shutil
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import hydragnn_tpu
from hydragnn_tpu.data import ColumnarWriter, smiles_table_dataset
from hydragnn_tpu.data.smiles import SmilesError, smiles_to_graph

_HERE = os.path.dirname(os.path.abspath(__file__))


def build_dataset(path, num_samples, csv_file=None):
    if os.path.isdir(path):
        # serve the cache only when its feature table matches the current
        # reader (see csce/train_gap.py); unreadable metadata raises
        # instead of deleting real data.
        from hydragnn_tpu.data.smiles import columnar_schema_current

        if columnar_schema_current(path):
            return
        print(f"rebuilding {path}: cached feature schema is outdated")
        shutil.rmtree(path)
    if csv_file:
        graphs = []
        with open(csv_file) as f:
            for row in csv.DictReader(f):
                try:
                    g = smiles_to_graph(row["smiles"])
                except SmilesError as e:
                    print(f"skipping {row['smiles']!r}: {e}")
                    continue
                g.graph_y = np.asarray([float(row["gap"])], np.float32)
                graphs.append(g)
    else:
        graphs = smiles_table_dataset(number_configurations=num_samples, seed=4)
    ColumnarWriter(path).add(graphs).save()
    print(f"wrote {len(graphs)} OGB gap molecules -> {path}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--csv", default=None, help="real data: smiles,gap CSV")
    ap.add_argument("--mpnn_type", default=None)
    ap.add_argument("--num_epoch", type=int, default=None)
    ap.add_argument("--num_samples", type=int, default=256)
    args = ap.parse_args()

    with open(os.path.join(_HERE, "ogb_gap.json")) as f:
        config = json.load(f)
    arch = config["NeuralNetwork"]["Architecture"]
    if args.mpnn_type:
        arch["mpnn_type"] = args.mpnn_type
    if args.num_epoch:
        config["NeuralNetwork"]["Training"]["num_epoch"] = args.num_epoch

    data_path = os.path.join(os.getcwd(), config["Dataset"]["path"]["total"])
    config["Dataset"]["path"]["total"] = data_path
    build_dataset(data_path, args.num_samples, csv_file=args.csv)

    model, state, hist, config, loaders, mm = hydragnn_tpu.run_training(config)
    tot, tasks, preds, trues = hydragnn_tpu.run_prediction(config, model_state=state)
    mae = float(np.mean(np.abs(preds["gap"] - trues["gap"])))
    print(f"test loss {tot:.5f}; gap MAE {mae:.5f}")


if __name__ == "__main__":
    main()
