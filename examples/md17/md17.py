"""MD17 example: energy + force training through the columnar dataset
format (reference: examples/md17/md17.py — MD17 aspirin energy training;
extended here to the energy+force objective the MD17 benchmark is actually
scored on, via ``compute_grad_energy`` second-order AD).

The real MD17 download is unavailable in this image (zero egress), so the
dataset builder takes one of two sources:

- ``--xyz_dir DIR``: a directory of .xyz files (real MD17 frames; comment
  line = energy, columns 5-7 = forces), parsed by the raw XYZ loader, or
- the default MD17-*shaped* generator (``md17_shaped_dataset``): thermal
  perturbations of a fixed 21-atom aspirin-composition molecule with
  physically-consistent energies/forces.

Either source is written once through ``ColumnarWriter`` and read back via
``Dataset.format: "columnar"``. Prints the test-set force MAE — the
BASELINE.md "MD17-shaped force MAE" row.

    python examples/md17/md17.py [--mpnn_type SchNet] [--num_samples 512]
"""

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import hydragnn_tpu
from hydragnn_tpu.data import ColumnarWriter, md17_shaped_dataset
from hydragnn_tpu.data.raw import finalize_graphs, load_xyz_file

_HERE = os.path.dirname(os.path.abspath(__file__))

# bump when md17_shaped_dataset's distribution changes (v2 = the round-5
# Boltzmann-style force-cap acceptance): a stale shard must not silently
# produce numbers that don't correspond to the BASELINE.md recipe
_GEN_VERSION = 2


def _shard_meta(path):
    metas = sorted(glob.glob(os.path.join(path, "shard*", "meta.json")))
    if not metas:
        return {}
    with open(metas[0]) as fh:
        return json.load(fh)


def build_dataset(path, num_samples, radius, max_neighbours, xyz_dir=None):
    """Write the columnar shard once; later runs reuse it (synthetic shards
    are regenerated when the generator version or sample count changed)."""
    if os.path.isdir(path):
        if xyz_dir:
            print(f"reusing existing shard at {path}")
            return
        meta = _shard_meta(path)
        if (
            meta.get("num_samples") == num_samples
            and meta.get("attrs", {}).get("md17_gen_version") == _GEN_VERSION
        ):
            print(f"reusing {num_samples}-sample v{_GEN_VERSION} shard at {path}")
            return
        import shutil

        print(
            f"regenerating {path}: existing shard is "
            f"v{meta.get('attrs', {}).get('md17_gen_version')} with "
            f"{meta.get('num_samples')} samples, want v{_GEN_VERSION} with "
            f"{num_samples}"
        )
        shutil.rmtree(path)
    if xyz_dir:
        graphs = []
        for f in sorted(glob.glob(os.path.join(xyz_dir, "*.xyz"))):
            g = load_xyz_file(f)
            # columns after x,y,z are forces; comment line is the energy
            if g.x.shape[1] < 4:
                raise ValueError(
                    f"{f}: expected 'Symbol x y z fx fy fz' rows (3 force "
                    f"columns after the position); found {g.x.shape[1] - 1} "
                    "extra column(s)"
                )
            if g.graph_y is None or len(g.graph_y) < 1:
                raise ValueError(f"{f}: comment line must carry the energy value")
            g.node_targets = {"forces": np.asarray(g.x[:, 1:4], np.float32)}
            g.graph_targets = {"energy": np.asarray(g.graph_y[:1], np.float32)}
            g.x = g.x[:, :1]
            g.graph_y = None
            graphs.append(g)
        graphs = finalize_graphs(graphs, radius=radius, max_neighbours=max_neighbours)
    else:
        graphs = md17_shaped_dataset(
            number_configurations=num_samples,
            radius=radius,
            max_neighbours=max_neighbours,
        )
    writer = ColumnarWriter(path).add(graphs)
    if not xyz_dir:
        writer.add_global("md17_gen_version", _GEN_VERSION)
    writer.save()
    print(f"wrote {len(graphs)} samples -> {path}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mpnn_type", default=None)
    ap.add_argument("--num_epoch", type=int, default=None)
    ap.add_argument("--num_samples", type=int, default=512)
    ap.add_argument("--xyz_dir", default=None, help="optional real-data xyz directory")
    args = ap.parse_args()

    with open(os.path.join(_HERE, "md17.json")) as f:
        config = json.load(f)
    arch = config["NeuralNetwork"]["Architecture"]
    if args.mpnn_type:
        arch["mpnn_type"] = args.mpnn_type
    if args.num_epoch:
        config["NeuralNetwork"]["Training"]["num_epoch"] = args.num_epoch

    data_path = os.path.join(os.getcwd(), config["Dataset"]["path"]["total"])
    config["Dataset"]["path"]["total"] = data_path
    build_dataset(
        data_path, args.num_samples, arch["radius"], arch["max_neighbours"],
        xyz_dir=args.xyz_dir,
    )

    model, state, hist, config, loaders, mm = hydragnn_tpu.run_training(config)
    tot, tasks, preds, trues = hydragnn_tpu.run_prediction(config, model_state=state)
    force_mae = float(np.mean(np.abs(preds["forces"] - trues["forces"])))
    energy_mae = float(np.mean(np.abs(preds["graph_energy"] - trues["graph_energy"])))
    # NaN-safe: a degenerate run predicting constant forces has zero
    # variance and np.corrcoef would print "corr nan", breaking the
    # regression test's parse exactly when it should fail on the bound
    pf, tf = preds["forces"].ravel(), trues["forces"].ravel()
    if pf.std() > 0 and tf.std() > 0:
        force_corr = float(np.corrcoef(pf, tf)[0, 1])
    else:
        force_corr = 0.0
    # trivial-predictor baselines: any committed number must be read against
    # these (zero force / test-mean energy), so a run that learned nothing
    # cannot masquerade as a measurement
    zero_force_mae = float(np.mean(np.abs(trues["forces"])))
    mean_energy_mae = float(
        np.mean(np.abs(trues["graph_energy"] - trues["graph_energy"].mean()))
    )
    print(
        f"test loss {tot:.5f}; energy MAE {energy_mae:.5f} "
        f"(test-mean predictor {mean_energy_mae:.5f}); "
        f"force MAE {force_mae:.5f} (zero predictor {zero_force_mae:.5f}, "
        f"corr {force_corr:.3f})"
    )


if __name__ == "__main__":
    main()
