"""OC22 example: oxide-catalyst slab training through the columnar format
(reference: examples/open_catalyst_2022/train.py — the Open Catalyst 2022
total-energy dataset; unlike OC20's adsorption energies, OC22 trains on
*total* DFT energies of oxide surfaces).

The real OC22 LMDBs are not downloadable here (zero egress); the dataset is
the slab-shaped generator (``oc20_shaped_dataset`` with an oxide element
pool and a distinct seed): lognormal slab sizes, degree capped at 20, LJ
total energy + forces. Total (not per-atom) energy matches OC22 semantics.

    python examples/open_catalyst_2022/train.py [--train_mode energy|forces]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import hydragnn_tpu
from hydragnn_tpu.data import ColumnarWriter, oc20_shaped_dataset

_HERE = os.path.dirname(os.path.abspath(__file__))


def build_dataset(path, num_samples, radius, max_neighbours):
    if os.path.isdir(path):
        return
    import dataclasses

    graphs = oc20_shaped_dataset(
        number_configurations=num_samples, radius=radius,
        max_neighbours=max_neighbours, seed=2022,
    )
    # table form for supervised training: x = [Z, pos, forces], graph_y =
    # [total energy] (OC22 trains *total* DFT energies, not adsorption
    # deltas; oc20_shaped stores per-atom energy in graph_targets)
    graphs = [
        dataclasses.replace(
            g,
            x=np.concatenate(
                [g.x, g.node_targets["forces"]], axis=1
            ).astype(np.float32),
            graph_y=np.asarray(
                [g.graph_targets["energy"][0] * g.num_nodes], np.float32
            ),
            graph_targets=None,
            node_targets=None,
        )
        for g in graphs
    ]
    ColumnarWriter(path).add(graphs).save()
    print(f"wrote {len(graphs)} OC22-shaped oxide slabs -> {path}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--train_mode", choices=["energy", "forces"], default="energy")
    ap.add_argument("--mpnn_type", default=None)
    ap.add_argument("--num_epoch", type=int, default=None)
    ap.add_argument("--num_samples", type=int, default=128)
    args = ap.parse_args()

    with open(os.path.join(_HERE, f"open_catalyst_{args.train_mode}.json")) as f:
        config = json.load(f)
    arch = config["NeuralNetwork"]["Architecture"]
    if args.mpnn_type:
        arch["mpnn_type"] = args.mpnn_type
    if args.num_epoch:
        config["NeuralNetwork"]["Training"]["num_epoch"] = args.num_epoch

    data_path = os.path.join(os.getcwd(), config["Dataset"]["path"]["total"])
    config["Dataset"]["path"]["total"] = data_path
    build_dataset(
        data_path, args.num_samples, arch["radius"], arch["max_neighbours"]
    )

    model, state, hist, config, loaders, mm = hydragnn_tpu.run_training(config)
    tot, tasks, preds, trues = hydragnn_tpu.run_prediction(config, model_state=state)
    name = config["NeuralNetwork"]["Variables_of_interest"]["output_names"][0]
    mae = float(np.mean(np.abs(preds[name] - trues[name])))
    print(f"test loss {tot:.5f}; {name} MAE {mae:.5f}")


if __name__ == "__main__":
    main()
