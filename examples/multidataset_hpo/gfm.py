"""Multidataset GFM HPO example: hyperparameter search over the merged
five-dataset GFM flow (reference: examples/multidataset_hpo/gfm.py +
gfm_deephyper_multi.py — DeepHyper searches over the multidataset config,
one SLURM allocation carved per trial; the TPU analog of the per-trial
node carving is the per-trial ``trial_offset`` seed plus the launch
recipes in run-scripts/).

    python examples/multidataset_hpo/gfm.py [--num_trials 3]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from hydragnn_tpu.hpo import run_hpo

_HERE = os.path.dirname(os.path.abspath(__file__))
_MULTIDATASET = os.path.join(_HERE, "..", "multidataset")
sys.path.insert(0, _MULTIDATASET)

SEARCH_SPACE = {
    "NeuralNetwork/Training/Optimizer/learning_rate": ("loguniform", 3e-4, 3e-2),
    "NeuralNetwork/Architecture/hidden_dim": [32, 50, 64],
    "NeuralNetwork/Architecture/mpnn_type": ["EGNN", "SchNet", "PNA"],
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num_trials", type=int, default=3)
    ap.add_argument("--num_per_dataset", type=int, default=32)
    ap.add_argument("--num_epoch", type=int, default=3)
    ap.add_argument("--trial_offset", type=int, default=0,
                    help="offset into the search (parallel HPO shards)")
    args = ap.parse_args()

    import train as multidataset_train  # examples/multidataset/train.py

    with open(os.path.join(_MULTIDATASET, "gfm_multitasking.json")) as f:
        base_config = json.load(f)
    base_config["NeuralNetwork"]["Training"]["num_epoch"] = args.num_epoch

    arch = base_config["NeuralNetwork"]["Architecture"]
    merged = multidataset_train.build_merged(
        args.num_per_dataset, arch["radius"], arch["max_neighbours"]
    )
    from hydragnn_tpu.data import split_dataset

    datasets = split_dataset(merged, 0.8, seed=0)

    def objective(config):
        import hydragnn_tpu

        _, _, hist, *_ = hydragnn_tpu.run_training(config, datasets=datasets)
        return float(np.min(hist["val"]))

    best, trials = run_hpo(
        base_config,
        SEARCH_SPACE,
        num_trials=args.num_trials,
        trial_offset=args.trial_offset,
        objective=objective,
    )
    for i, t in enumerate(trials):
        a = t["config"]["NeuralNetwork"]["Architecture"]
        print(f"trial {i}: loss {t['loss']:.5f} {a['mpnn_type']} hidden {a['hidden_dim']}")
    a = best["NeuralNetwork"]["Architecture"]
    print(f"best: {a['mpnn_type']} hidden {a['hidden_dim']}")


if __name__ == "__main__":
    main()
