"""Multidataset GFM HPO example: hyperparameter search over the merged
five-dataset GFM flow (reference: examples/multidataset_hpo/gfm.py +
gfm_deephyper_multi.py — DeepHyper searches over the multidataset config,
one SLURM allocation carved per trial; the TPU analog of the per-trial
node carving is the per-trial ``trial_offset`` seed plus the launch
recipes in run-scripts/).

    python examples/multidataset_hpo/gfm.py [--num_trials 3]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from hydragnn_tpu.hpo import run_hpo

_HERE = os.path.dirname(os.path.abspath(__file__))
_MULTIDATASET = os.path.join(_HERE, "..", "multidataset")
sys.path.insert(0, _MULTIDATASET)

SEARCH_SPACE = {
    "NeuralNetwork/Training/Optimizer/learning_rate": ("loguniform", 3e-4, 3e-2),
    "NeuralNetwork/Architecture/hidden_dim": [32, 50, 64],
    "NeuralNetwork/Architecture/mpnn_type": ["EGNN", "SchNet", "PNA"],
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num_trials", type=int, default=3)
    ap.add_argument("--num_per_dataset", type=int, default=32)
    ap.add_argument("--num_epoch", type=int, default=3)
    ap.add_argument("--trial_offset", type=int, default=0,
                    help="offset into the search (parallel HPO shards)")
    ap.add_argument("--results", default=None,
                    help="append trial records to this JSONL (worker mode)")
    ap.add_argument("--workers", type=int, default=1,
                    help=">1: orchestrate N parallel worker subprocesses "
                         "(DeepHyper-analog, hpo.launch_hpo_workers) and "
                         "merge their shards")
    args = ap.parse_args()

    if args.workers > 1:
        from hydragnn_tpu.hpo import launch_hpo_workers

        best, trials = launch_hpo_workers(
            [
                sys.executable, os.path.abspath(__file__),
                "--num_trials", "{num_trials}",
                "--trial_offset", "{trial_offset}",
                "--results", "{results}",
                "--num_per_dataset", str(args.num_per_dataset),
                "--num_epoch", str(args.num_epoch),
            ],
            num_workers=args.workers,
            num_trials=args.num_trials,
            workdir=os.path.join(os.getcwd(), "hpo_workers"),
            # independent studies on other machines shard disjointly by
            # passing distinct base offsets (worker i draws offset+i)
            trial_offset=args.trial_offset,
            # HPO_HOSTS="host1 host2 ..." carves one worker per node over
            # ssh (run-scripts/hpo-parallel.sh; the DeepHyper node-carving
            # analog) — workdir must be on a shared filesystem then
            hosts=os.environ.get("HPO_HOSTS", "").split() or None,
        )
        a = best["NeuralNetwork"]["Architecture"]
        print(
            f"parallel study: {len(trials)} trials over {args.workers} "
            f"workers; best {a['mpnn_type']} hidden {a['hidden_dim']}"
        )
        return

    import train as multidataset_train  # examples/multidataset/train.py

    with open(os.path.join(_MULTIDATASET, "gfm_multitasking.json")) as f:
        base_config = json.load(f)
    base_config["NeuralNetwork"]["Training"]["num_epoch"] = args.num_epoch

    arch = base_config["NeuralNetwork"]["Architecture"]
    merged = multidataset_train.build_merged(
        args.num_per_dataset, arch["radius"], arch["max_neighbours"]
    )
    from hydragnn_tpu.data import split_dataset

    datasets = split_dataset(merged, 0.8, seed=0)

    def objective(config):
        import hydragnn_tpu

        # the search draws both equivariant and invariant conv types over a
        # base config with equivariance on — follow the drawn model
        arch = config["NeuralNetwork"]["Architecture"]
        arch["equivariance"] = arch["mpnn_type"] in (
            "EGNN", "SchNet", "PNAEq", "PAINN", "MACE"
        )
        _, _, hist, *_ = hydragnn_tpu.run_training(config, datasets=datasets)
        return float(np.min(hist["val"]))

    best, trials = run_hpo(
        base_config,
        SEARCH_SPACE,
        num_trials=args.num_trials,
        trial_offset=args.trial_offset,
        objective=objective,
    )
    if args.results:
        from hydragnn_tpu.hpo import append_trial_records

        append_trial_records(args.results, trials)
    for i, t in enumerate(trials):
        a = t["config"]["NeuralNetwork"]["Architecture"]
        print(f"trial {i}: loss {t['loss']:.5f} {a['mpnn_type']} hidden {a['hidden_dim']}")
    a = best["NeuralNetwork"]["Architecture"]
    print(f"best: {a['mpnn_type']} hidden {a['hidden_dim']}")


if __name__ == "__main__":
    main()
