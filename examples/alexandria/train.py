"""Alexandria example: periodic-crystal energy or force training through the
columnar format (reference: examples/alexandria/train.py,
find_json_files.py, generate_dictionaries_pure_elements.py — the Alexandria
DFT database of inorganic crystals; one of the five SC25 GFM datasets).

The real Alexandria JSON archives are not downloadable here (zero egress);
the dataset is the Alexandria-*shaped* generator
(``alexandria_shaped_dataset``: ternary perturbed periodic crystals, PBC
radius graphs with shift vectors, LJ energy-per-atom + forces on the
periodic displacements).

    python examples/alexandria/train.py [--train_mode energy|forces]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import hydragnn_tpu
from hydragnn_tpu.data import ColumnarWriter, alexandria_shaped_dataset

_HERE = os.path.dirname(os.path.abspath(__file__))


def build_dataset(path, num_samples, radius, max_neighbours):
    if os.path.isdir(path):
        return
    graphs = alexandria_shaped_dataset(
        number_configurations=num_samples, radius=radius,
        max_neighbours=max_neighbours,
    )
    ColumnarWriter(path).add(graphs).save()
    print(f"wrote {len(graphs)} Alexandria-shaped crystals -> {path}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--train_mode", choices=["energy", "forces"], default="energy")
    ap.add_argument("--mpnn_type", default=None)
    ap.add_argument("--num_epoch", type=int, default=None)
    ap.add_argument("--num_samples", type=int, default=128)
    args = ap.parse_args()

    with open(os.path.join(_HERE, f"alexandria_{args.train_mode}.json")) as f:
        config = json.load(f)
    arch = config["NeuralNetwork"]["Architecture"]
    if args.mpnn_type:
        arch["mpnn_type"] = args.mpnn_type
    if args.num_epoch:
        config["NeuralNetwork"]["Training"]["num_epoch"] = args.num_epoch

    data_path = os.path.join(os.getcwd(), config["Dataset"]["path"]["total"])
    config["Dataset"]["path"]["total"] = data_path
    build_dataset(
        data_path, args.num_samples, arch["radius"], arch["max_neighbours"]
    )

    model, state, hist, config, loaders, mm = hydragnn_tpu.run_training(config)
    tot, tasks, preds, trues = hydragnn_tpu.run_prediction(config, model_state=state)
    name = config["NeuralNetwork"]["Variables_of_interest"]["output_names"][0]
    mae = float(np.mean(np.abs(preds[name] - trues[name])))
    print(f"test loss {tot:.5f}; {name} MAE {mae:.5f}")


if __name__ == "__main__":
    main()
