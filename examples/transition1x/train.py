"""Transition1x example: reaction-path energy training through the columnar
format (reference: examples/transition1x/train.py + dataloader.py — NEB
reaction-path configurations near transition states, energy regression).

The real Transition1x HDF5 is not downloadable here (zero egress); the
dataset is the Transition1x-*shaped* generator
(``transition1x_shaped_dataset``: interpolated reactant->product paths with
an activation-barrier energy bump — the defining structure of the real
dataset, which samples geometries *around* transition states).

    python examples/transition1x/train.py [--num_samples 256]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import hydragnn_tpu
from hydragnn_tpu.data import ColumnarWriter, transition1x_shaped_dataset

_HERE = os.path.dirname(os.path.abspath(__file__))


def build_dataset(path, num_samples, radius, max_neighbours):
    if os.path.isdir(path):
        return
    graphs = transition1x_shaped_dataset(
        number_configurations=num_samples, radius=radius,
        max_neighbours=max_neighbours,
    )
    ColumnarWriter(path).add(graphs).save()
    print(f"wrote {len(graphs)} Transition1x-shaped path samples -> {path}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mpnn_type", default=None)
    ap.add_argument("--num_epoch", type=int, default=None)
    ap.add_argument("--num_samples", type=int, default=256)
    args = ap.parse_args()

    with open(os.path.join(_HERE, "transition1x_energy.json")) as f:
        config = json.load(f)
    arch = config["NeuralNetwork"]["Architecture"]
    if args.mpnn_type:
        arch["mpnn_type"] = args.mpnn_type
    if args.num_epoch:
        config["NeuralNetwork"]["Training"]["num_epoch"] = args.num_epoch

    data_path = os.path.join(os.getcwd(), config["Dataset"]["path"]["total"])
    config["Dataset"]["path"]["total"] = data_path
    build_dataset(
        data_path, args.num_samples, arch["radius"], arch["max_neighbours"]
    )

    model, state, hist, config, loaders, mm = hydragnn_tpu.run_training(config)
    tot, tasks, preds, trues = hydragnn_tpu.run_prediction(config, model_state=state)
    mae = float(np.mean(np.abs(preds["energy"] - trues["energy"])))
    print(f"test loss {tot:.5f}; energy MAE {mae:.5f}")


if __name__ == "__main__":
    main()
