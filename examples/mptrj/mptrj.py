"""MPTrj example: periodic-crystal energy+force training with MACE through
the columnar format (reference: examples/mptrj — Materials Project
trajectory data feeding the MACE/GFM models; one of the five SC25
multibranch datasets, run-scripts/SC25-multibranch.sh:50-54).

The real MPTrj download is unavailable in this image (zero egress), so the
dataset is the MPTrj-*shaped* generator (``mptrj_shaped_dataset``:
perturbed BCC/FCC/SC supercells, random binary compositions, PBC
radius-graph edges with shift vectors, physically-consistent LJ
energy/forces on the periodic displacements), written once through
``ColumnarWriter`` — cell and edge_shifts round-trip through the columnar
layout.

    python examples/mptrj/mptrj.py [--mpnn_type MACE] [--num_samples 96]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import hydragnn_tpu
from hydragnn_tpu.data import ColumnarWriter, mptrj_shaped_dataset

_HERE = os.path.dirname(os.path.abspath(__file__))


def build_dataset(path, num_samples, radius, max_neighbours):
    if os.path.isdir(path):
        return
    graphs = mptrj_shaped_dataset(
        number_configurations=num_samples, radius=radius,
        max_neighbours=max_neighbours,
    )
    ColumnarWriter(path).add(graphs).save()
    print(f"wrote {len(graphs)} MPTrj-shaped periodic samples -> {path}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mpnn_type", default=None)
    ap.add_argument("--num_epoch", type=int, default=None)
    ap.add_argument("--num_samples", type=int, default=96)
    args = ap.parse_args()

    with open(os.path.join(_HERE, "mptrj.json")) as f:
        config = json.load(f)
    arch = config["NeuralNetwork"]["Architecture"]
    if args.mpnn_type:
        arch["mpnn_type"] = args.mpnn_type
    if args.num_epoch:
        config["NeuralNetwork"]["Training"]["num_epoch"] = args.num_epoch

    data_path = os.path.join(os.getcwd(), config["Dataset"]["path"]["total"])
    config["Dataset"]["path"]["total"] = data_path
    build_dataset(
        data_path, args.num_samples, arch["radius"], arch["max_neighbours"]
    )

    model, state, hist, config, loaders, mm = hydragnn_tpu.run_training(config)
    tot, tasks, preds, trues = hydragnn_tpu.run_prediction(config, model_state=state)
    force_mae = float(np.mean(np.abs(preds["forces"] - trues["forces"])))
    energy_mae = float(
        np.mean(np.abs(preds["graph_energy"] - trues["graph_energy"]))
    )
    print(
        f"test loss {tot:.5f}; energy MAE {energy_mae:.5f}; "
        f"force MAE {force_mae:.5f}"
    )


if __name__ == "__main__":
    main()
