"""CSCE example: SMILES -> electronic-gap regression through the in-tree
SMILES reader and the columnar format (reference: examples/csce/
train_gap.py — the CSCE GDB-9-Ex dataset of SMILES strings with computed
excitation gaps, parsed with rdkit's smiles_utils).

rdkit is not in this image, so SMILES go through the dependency-free
reader (``hydragnn_tpu.data.smiles``). Provide real data as a CSV with
``smiles,gap`` columns via ``--csv``; otherwise the CSCE-*shaped*
generator (``smiles_table_dataset``: random drug-like SMILES with a
closed-form gap target) is used.

    python examples/csce/train_gap.py [--csv FILE] [--num_samples 256]
"""

import argparse
import csv
import json
import os
import shutil
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import hydragnn_tpu
from hydragnn_tpu.data import ColumnarWriter, smiles_table_dataset
from hydragnn_tpu.data.smiles import SmilesError, smiles_to_graph

_HERE = os.path.dirname(os.path.abspath(__file__))


def build_dataset(path, num_samples, csv_file=None):
    if os.path.isdir(path):
        # serve the cache only when its feature table matches the current
        # reader; a confirmed-stale schema (e.g. pre-hybridization 5-column
        # layout) is rebuilt. Unreadable metadata raises instead of
        # deleting — the cache may hold real --csv data.
        from hydragnn_tpu.data.smiles import columnar_schema_current

        if columnar_schema_current(path):
            return
        print(f"rebuilding {path}: cached feature schema is outdated")
        shutil.rmtree(path)
    smiles = None
    if csv_file:
        graphs, smiles = [], []
        with open(csv_file) as f:
            for row in csv.DictReader(f):
                try:
                    g = smiles_to_graph(row["smiles"])
                except SmilesError as e:
                    print(f"skipping {row['smiles']!r}: {e}")
                    continue
                g.graph_y = np.asarray([float(row["gap"])], np.float32)
                graphs.append(g)
                smiles.append(row["smiles"])
    else:
        graphs = smiles_table_dataset(number_configurations=num_samples)
    w = ColumnarWriter(path).add(graphs)
    if smiles:
        # source strings ride along per sample, like the reference's
        # SMILES packing into the .bp (adiosdataset.py:334-389)
        w.add_string("smiles", smiles)
    w.save()
    print(f"wrote {len(graphs)} CSCE gap molecules -> {path}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--csv", default=None, help="real data: smiles,gap CSV")
    ap.add_argument("--mpnn_type", default=None)
    ap.add_argument("--num_epoch", type=int, default=None)
    ap.add_argument("--num_samples", type=int, default=256)
    args = ap.parse_args()

    with open(os.path.join(_HERE, "csce_gap.json")) as f:
        config = json.load(f)
    arch = config["NeuralNetwork"]["Architecture"]
    if args.mpnn_type:
        arch["mpnn_type"] = args.mpnn_type
    if args.num_epoch:
        config["NeuralNetwork"]["Training"]["num_epoch"] = args.num_epoch

    data_path = os.path.join(os.getcwd(), config["Dataset"]["path"]["total"])
    config["Dataset"]["path"]["total"] = data_path
    build_dataset(data_path, args.num_samples, csv_file=args.csv)

    model, state, hist, config, loaders, mm = hydragnn_tpu.run_training(config)
    tot, tasks, preds, trues = hydragnn_tpu.run_prediction(config, model_state=state)
    mae = float(np.mean(np.abs(preds["gap"] - trues["gap"])))
    print(f"test loss {tot:.5f}; gap MAE {mae:.5f}")


if __name__ == "__main__":
    main()
