"""QM7-X inference: load a trained checkpoint and predict on the test split
(reference: examples/qm7x/inference.py — standalone prediction driver).

Run train.py first so logs/<name>/ holds a checkpoint, then:

    python examples/qm7x/inference.py [--single_tasking]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import hydragnn_tpu

_HERE = os.path.dirname(os.path.abspath(__file__))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--single_tasking", action="store_true")
    ap.add_argument("--num_epoch", type=int, default=None,
                    help="must match the training run: the checkpoint's "
                    "log-dir name embeds num_epoch (get_log_name_config)")
    args = ap.parse_args()

    cfg = "qm7x_single_tasking.json" if args.single_tasking else "qm7x.json"
    with open(os.path.join(_HERE, cfg)) as f:
        config = json.load(f)
    if args.num_epoch:
        config["NeuralNetwork"]["Training"]["num_epoch"] = args.num_epoch
    data_path = os.path.join(os.getcwd(), config["Dataset"]["path"]["total"])
    config["Dataset"]["path"]["total"] = data_path
    if not os.path.isdir(data_path):
        raise SystemExit("dataset missing - run examples/qm7x/train.py first")

    # loads the checkpoint saved by run_training from logs/<log_name>/
    tot, tasks, preds, trues = hydragnn_tpu.run_prediction(config)
    for name in config["NeuralNetwork"]["Variables_of_interest"]["output_names"]:
        mae = float(np.mean(np.abs(preds[name] - trues[name])))
        print(f"{name} MAE {mae:.5f}")
    print(f"test loss {tot:.5f}")


if __name__ == "__main__":
    main()
