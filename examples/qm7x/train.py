"""QM7-X example: five-target multitask training (graph HLGAP + node
forces/hCHG/hVDIP/hRAT) through the columnar format (reference:
examples/qm7x/train.py + qm7x.json — QM7-X's multi-property surface over
up-to-7-heavy-atom molecules).

The real QM7-X HDF5 is not downloadable here (zero egress); the dataset is
the QM7-X-*shaped* generator (``qm7x_shaped_dataset``: C/N/O/S/Cl + H
molecules with closed-form geometric analogs of each target).

    python examples/qm7x/train.py [--single_tasking]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import hydragnn_tpu
from hydragnn_tpu.data import ColumnarWriter, qm7x_shaped_dataset

_HERE = os.path.dirname(os.path.abspath(__file__))


def build_dataset(path, num_samples, radius, max_neighbours):
    if os.path.isdir(path):
        return
    graphs = qm7x_shaped_dataset(
        number_configurations=num_samples, radius=radius,
        max_neighbours=max_neighbours,
    )
    ColumnarWriter(path).add(graphs).save()
    print(f"wrote {len(graphs)} QM7-X-shaped molecules -> {path}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--single_tasking", action="store_true",
                    help="HLGAP-only variant (qm7x_single_tasking.json)")
    ap.add_argument("--mpnn_type", default=None)
    ap.add_argument("--num_epoch", type=int, default=None)
    ap.add_argument("--num_samples", type=int, default=256)
    args = ap.parse_args()

    cfg = "qm7x_single_tasking.json" if args.single_tasking else "qm7x.json"
    with open(os.path.join(_HERE, cfg)) as f:
        config = json.load(f)
    arch = config["NeuralNetwork"]["Architecture"]
    if args.mpnn_type:
        arch["mpnn_type"] = args.mpnn_type
    if args.num_epoch:
        config["NeuralNetwork"]["Training"]["num_epoch"] = args.num_epoch

    data_path = os.path.join(os.getcwd(), config["Dataset"]["path"]["total"])
    config["Dataset"]["path"]["total"] = data_path
    build_dataset(
        data_path, args.num_samples, arch["radius"], arch["max_neighbours"]
    )

    model, state, hist, config, loaders, mm = hydragnn_tpu.run_training(config)
    tot, tasks, preds, trues = hydragnn_tpu.run_prediction(config, model_state=state)
    for name in config["NeuralNetwork"]["Variables_of_interest"]["output_names"]:
        mae = float(np.mean(np.abs(preds[name] - trues[name])))
        print(f"{name} MAE {mae:.5f}")
    print(f"test loss {tot:.5f}")


if __name__ == "__main__":
    main()
