"""DFTB UV spectrum example (smooth): molecule -> Gaussian-broadened
excitation spectrum regression through the columnar format (reference:
examples/dftb_uv_spectrum/train_smooth_uv_spectrum.py — DFTB+ computed UV
spectra of organic molecules; the real smooth target is a 37,500-point
grid, shaped here to a 37-bin grid).

The real DFTB+ outputs are not shipped; the dataset is the UV-*shaped*
generator (``uv_spectrum_shaped_dataset``: organic molecules whose
spectrum is a Gaussian-broadened function of the pair-distance spectrum).

    python examples/dftb_uv_spectrum/train_smooth_uv_spectrum.py
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import hydragnn_tpu
from hydragnn_tpu.data import ColumnarWriter, uv_spectrum_shaped_dataset

_HERE = os.path.dirname(os.path.abspath(__file__))
SMOOTH = True


def build_dataset(path, num_samples, radius, max_neighbours, num_bins):
    if os.path.isdir(path):
        return
    graphs = uv_spectrum_shaped_dataset(
        number_configurations=num_samples, num_bins=num_bins, smooth=SMOOTH,
        radius=radius, max_neighbours=max_neighbours,
    )
    ColumnarWriter(path).add(graphs).save()
    kind = "smooth" if SMOOTH else "discrete"
    print(f"wrote {len(graphs)} {kind} UV-spectrum molecules -> {path}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mpnn_type", default=None)
    ap.add_argument("--num_epoch", type=int, default=None)
    ap.add_argument("--num_samples", type=int, default=256)
    args = ap.parse_args()

    kind = "smooth" if SMOOTH else "discrete"
    with open(os.path.join(_HERE, f"dftb_{kind}_uv_spectrum.json")) as f:
        config = json.load(f)
    arch = config["NeuralNetwork"]["Architecture"]
    if args.mpnn_type:
        arch["mpnn_type"] = args.mpnn_type
    if args.num_epoch:
        config["NeuralNetwork"]["Training"]["num_epoch"] = args.num_epoch

    data_path = os.path.join(os.getcwd(), config["Dataset"]["path"]["total"])
    config["Dataset"]["path"]["total"] = data_path
    num_bins = config["Dataset"]["graph_features"]["dim"][0]
    build_dataset(
        data_path, args.num_samples, arch["radius"], arch["max_neighbours"],
        num_bins,
    )

    model, state, hist, config, loaders, mm = hydragnn_tpu.run_training(config)
    tot, tasks, preds, trues = hydragnn_tpu.run_prediction(config, model_state=state)
    mae = float(np.mean(np.abs(preds["spectrum"] - trues["spectrum"])))
    print(f"test loss {tot:.5f}; spectrum MAE {mae:.5f}")


if __name__ == "__main__":
    main()
