"""DFTB UV spectrum example (discrete): molecule -> binned excitation
intensities (reference: examples/dftb_uv_spectrum/
train_discrete_uv_spectrum.py). Same flow as the smooth variant with
histogram binning instead of Gaussian broadening.

    python examples/dftb_uv_spectrum/train_discrete_uv_spectrum.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(__file__))

import train_smooth_uv_spectrum as smooth_mod

smooth_mod.SMOOTH = False

if __name__ == "__main__":
    smooth_mod.main()
