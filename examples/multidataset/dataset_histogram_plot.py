"""Per-family dataset statistics plots for the GFM fleet (reference:
examples/multidataset/dataset_histogram_plot.py — node-count histograms of
the five datasets side by side).

    python examples/multidataset/dataset_histogram_plot.py [--num_per_dataset 64]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num_per_dataset", type=int, default=64)
    ap.add_argument("--out", default="dataset_histograms.png")
    args = ap.parse_args()

    import train as multidataset_train  # examples/multidataset/train.py

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fams = list(multidataset_train.FAMILIES.items())
    fig, axs = plt.subplots(2, len(fams), figsize=(3.2 * len(fams), 5.6))
    for col, (name, (maker, _)) in enumerate(fams):
        graphs = maker(number_configurations=args.num_per_dataset)
        sizes = [g.num_nodes for g in graphs]
        degrees = np.concatenate([
            np.bincount(g.receivers, minlength=g.num_nodes) for g in graphs
        ])
        axs[0][col].hist(sizes, bins=20)
        axs[0][col].set_title(f"{name}: atoms/graph", fontsize=9)
        axs[1][col].hist(degrees, bins=20)
        axs[1][col].set_title(f"{name}: in-degree", fontsize=9)
    fig.tight_layout()
    fig.savefig(args.out, dpi=120)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
