"""Multidataset GFM example: one model trained over the five-dataset
chemistry fleet (ANI1x + QM7x + MPTrj + Alexandria + Transition1x shaped
analogs) — the single-branch "graph foundation model" flow (reference:
examples/multidataset/train.py + gfm_multitasking.json: merged ADIOS
datasets, energy + force multitask, proportional sampling;
the branch-parallel variant lives in examples/multibranch).

Each family generator contributes graphs re-tagged with ``dataset_id``;
targets are normalized per-dataset (energy per atom, centered) so one
energy head can serve all five — the reference's
energy_linear_regression.py pre-transform plays the same role.
``--balance`` draws samples with per-family weights so small families get
equal step budget (the uneven-branch analog, data.branch_sample_weights).

    python examples/multidataset/train.py [--num_per_dataset 64] [--balance]
"""

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import hydragnn_tpu
from hydragnn_tpu.data import (
    alexandria_shaped_dataset,
    ani1x_shaped_dataset,
    mptrj_shaped_dataset,
    qm7x_shaped_dataset,
    split_dataset,
    transition1x_shaped_dataset,
)

_HERE = os.path.dirname(os.path.abspath(__file__))

# maker + how each family stores its graph energy target: "total" (divide
# by num_nodes here), "per_atom" (already E/n), or "scalar" (a non-energy
# graph property, HLGAP for qm7x — used as-is, no per-atom scaling)
FAMILIES = {
    "ani1x": (ani1x_shaped_dataset, "total"),
    "qm7x": (qm7x_shaped_dataset, "scalar"),
    "mptrj": (mptrj_shaped_dataset, "per_atom"),
    "alexandria": (alexandria_shaped_dataset, "per_atom"),
    "transition1x": (transition1x_shaped_dataset, "total"),
}


def build_merged(num_per_dataset, radius, max_neighbours):
    merged = []
    for ds_id, (name, (maker, energy_kind)) in enumerate(FAMILIES.items()):
        graphs = maker(
            number_configurations=num_per_dataset, radius=radius,
            max_neighbours=max_neighbours,
        )
        # uniform contract across families: input x = [Z], graph target =
        # centered per-atom energy (or the family's scalar property),
        # node target = forces (zero where the family has none)
        out = []
        energies = []
        for g in graphs:
            e = g.graph_targets["energy"][0] if g.graph_targets else g.graph_y[0]
            if energy_kind == "total":
                e = e / g.num_nodes
            energies.append(e)
        e_mean = float(np.mean(energies))
        for g, e in zip(graphs, energies):
            forces = (
                g.node_targets["forces"]
                if g.node_targets and "forces" in g.node_targets
                else np.zeros((g.num_nodes, 3), np.float32)
            )
            out.append(dataclasses.replace(
                g,
                x=np.asarray(g.z, np.float32)[:, None],
                graph_y=None,
                graph_targets={"energy": np.asarray([e - e_mean], np.float32)},
                node_targets={"force": forces.astype(np.float32)},
                dataset_id=ds_id,
                # molecular families carry no PBC shifts; zero-fill so the
                # batch stacker sees a uniform schema across the fleet
                edge_shifts=(
                    g.edge_shifts
                    if g.edge_shifts is not None
                    else np.zeros((g.num_edges, 3), np.float32)
                ),
            ))
        print(f"{name}: {len(out)} graphs (dataset_id={ds_id})")
        merged += out
    return merged


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num_per_dataset", type=int, default=64)
    ap.add_argument("--num_epoch", type=int, default=None)
    ap.add_argument("--mpnn_type", default=None)
    ap.add_argument("--balance", action="store_true",
                    help="equal per-family step budget via weighted draws")
    ap.add_argument("--ref_energy", action="store_true",
                    help="subtract least-squares per-element reference "
                    "energies before training (the reference's "
                    "energy_linear_regression.py preprocessing)")
    args = ap.parse_args()

    with open(os.path.join(_HERE, "gfm_multitasking.json")) as f:
        config = json.load(f)
    arch = config["NeuralNetwork"]["Architecture"]
    if args.mpnn_type:
        arch["mpnn_type"] = args.mpnn_type
    if args.num_epoch:
        config["NeuralNetwork"]["Training"]["num_epoch"] = args.num_epoch
    if args.balance:
        config["NeuralNetwork"]["Training"]["balance_branch_sampling"] = True

    merged = build_merged(
        args.num_per_dataset, arch["radius"], arch["max_neighbours"]
    )
    tr, va, te = split_dataset(merged, 0.8, seed=0)
    if args.ref_energy:
        from hydragnn_tpu.data import (
            fit_reference_energies,
            subtract_reference_energies,
        )

        # one table per dataset (offsets are DFT-setting specific), fit on
        # the TRAIN split only, and only for true-energy families (qm7x's
        # graph scalar is HLGAP — not an energy, FAMILIES kind "scalar")
        energy_ids = {
            i for i, (_, (_, kind)) in enumerate(FAMILIES.items())
            if kind != "scalar"
        }
        fit_set = [g for g in tr if g.dataset_id in energy_ids]
        tables = fit_reference_energies(fit_set, per_atom=True, by_dataset=True)
        tr, va, te = (
            subtract_reference_energies(s, tables, per_atom=True)
            for s in (tr, va, te)
        )
        print(f"reference energies fit per dataset: {sorted(tables)}")

    model, state, hist, config, loaders, mm = hydragnn_tpu.run_training(
        config, datasets=(tr, va, te)
    )
    tot, tasks, preds, trues = hydragnn_tpu.run_prediction(
        config, model_state=state, datasets=(tr, va, te)
    )
    for name in ("energy", "force"):
        mae = float(np.mean(np.abs(preds[name] - trues[name])))
        print(f"{name} MAE {mae:.5f}")
    print(f"test loss {tot:.5f}")


if __name__ == "__main__":
    main()
