"""QM9 HPO example: hyperparameter search over the QM9 flow (reference:
examples/qm9_hpo/qm9_optuna.py and qm9_deephyper.py — Optuna / DeepHyper
searches over learning rate, conv-layer count, and hidden dim on QM9).

Uses the framework's HPO driver (``hydragnn_tpu.hpo.run_hpo``): Optuna TPE
when optuna is importable, pure random search otherwise — same search
space either way.

    python examples/qm9_hpo/qm9_hpo.py [--num_trials 4] [--num_samples 200]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from hydragnn_tpu.data import (
    MinMax,
    VariablesOfInterest,
    extract_variables,
    qm9_shaped_dataset,
    split_dataset,
)
from hydragnn_tpu.hpo import run_hpo

_HERE = os.path.dirname(os.path.abspath(__file__))

SEARCH_SPACE = {
    # path into the config -> categorical list or ("loguniform", lo, hi)
    "NeuralNetwork/Training/Optimizer/learning_rate": ("loguniform", 1e-4, 1e-2),
    "NeuralNetwork/Architecture/hidden_dim": [32, 64],
    "NeuralNetwork/Architecture/num_conv_layers": [2, 3, 4],
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num_trials", type=int, default=4)
    ap.add_argument("--num_samples", type=int, default=200)
    ap.add_argument("--num_epoch", type=int, default=4)
    ap.add_argument("--no_optuna", action="store_true",
                    help="force pure random search")
    args = ap.parse_args()

    with open(os.path.join(_HERE, "qm9.json")) as f:
        base_config = json.load(f)
    base_config["NeuralNetwork"]["Training"]["num_epoch"] = args.num_epoch

    graphs = qm9_shaped_dataset(number_configurations=args.num_samples)
    # the explicit-datasets path takes model-ready graphs: normalize and
    # extract the free_energy target up front (shared across all trials)
    graphs = MinMax.fit(graphs).apply(graphs)
    voi = VariablesOfInterest([0], ["free_energy"], ["graph"], [0], [1], [1])
    graphs = [extract_variables(g, voi) for g in graphs]
    datasets = split_dataset(graphs, 0.7, seed=0)

    def objective(config):
        import hydragnn_tpu

        _, _, hist, *_ = hydragnn_tpu.run_training(config, datasets=datasets)
        return float(np.min(hist["val"]))

    best, trials = run_hpo(
        base_config,
        SEARCH_SPACE,
        num_trials=args.num_trials,
        objective=objective,
        use_optuna=False if args.no_optuna else None,
    )
    for i, t in enumerate(trials):
        arch = t["config"]["NeuralNetwork"]["Architecture"]
        lr = t["config"]["NeuralNetwork"]["Training"]["Optimizer"]["learning_rate"]
        print(
            f"trial {i}: loss {t['loss']:.5f} hidden {arch['hidden_dim']} "
            f"convs {arch['num_conv_layers']} lr {lr:.2e}"
        )
    arch = best["NeuralNetwork"]["Architecture"]
    print(
        f"best: hidden {arch['hidden_dim']} convs {arch['num_conv_layers']} "
        f"lr {best['NeuralNetwork']['Training']['Optimizer']['learning_rate']:.2e}"
    )


if __name__ == "__main__":
    main()
