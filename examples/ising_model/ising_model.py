"""3D Ising model example: lattice spin configurations -> total energy
(reference: examples/ising_model/create_configurations.py + train_ising.py —
L^3 spin lattices written as LSMS-format text files, graph head on the
dimensionless total energy, node feature = spin).

The energy here is the standard nearest-neighbor Ising Hamiltonian
``H = -J * sum_<ij> s_i s_j`` with periodic boundaries (vectorized with
np.roll; the reference's loop form folds in a self-term and a /6 scale —
same physics up to normalization). Configurations sweep magnetization so
the energies span a learnable range.

    python examples/ising_model/ising_model.py [--L 4] [--num_configs 100]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import hydragnn_tpu

_HERE = os.path.dirname(os.path.abspath(__file__))


def ising_energy(spins: np.ndarray, j_coupling: float = 1.0) -> float:
    """H = -J * sum over the 3 positive lattice directions (each bond once)."""
    e = 0.0
    for axis in range(3):
        e += float(np.sum(spins * np.roll(spins, 1, axis=axis)))
    return -j_coupling * e


def generate_configurations(dir_path, num_configs, L, seed=13):
    """LSMS-format files: header = total energy; one row per site
    [occupancy, 0, x, y, z, spin] (reference: write_to_file,
    create_configurations.py:10-26)."""
    os.makedirs(dir_path)
    rng = np.random.default_rng(seed)
    xs, ys, zs = np.meshgrid(range(L), range(L), range(L), indexing="ij")
    pos = np.stack([xs.ravel(), ys.ravel(), zs.ravel()], axis=1).astype(float)
    for i in range(num_configs):
        # sweep order parameter so energies cover the full range
        p_up = rng.uniform(0.05, 0.95)
        spins = np.where(rng.random((L, L, L)) < p_up, 1.0, -1.0)
        energy = ising_energy(spins)
        flat = spins.ravel()
        with open(os.path.join(dir_path, f"output{i}.txt"), "w") as f:
            f.write(f"{energy!r}\n")
            for k in range(flat.size):
                f.write(
                    f"1.0 0.0 {pos[k, 0]:.1f} {pos[k, 1]:.1f} {pos[k, 2]:.1f} "
                    f"{flat[k]:.1f}\n"
                )
    print(f"wrote {num_configs} Ising configurations (L={L}) -> {dir_path}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mpnn_type", default=None)
    ap.add_argument("--num_epoch", type=int, default=None)
    ap.add_argument("--num_configs", type=int, default=100)
    ap.add_argument("--L", type=int, default=4)
    args = ap.parse_args()

    with open(os.path.join(_HERE, "ising_model.json")) as f:
        config = json.load(f)
    if args.mpnn_type:
        config["NeuralNetwork"]["Architecture"]["mpnn_type"] = args.mpnn_type
    if args.num_epoch:
        config["NeuralNetwork"]["Training"]["num_epoch"] = args.num_epoch

    data_dir = os.path.join(os.getcwd(), "dataset", "ising_model")
    if not os.path.isdir(data_dir):
        generate_configurations(data_dir, args.num_configs, args.L)
    config["Dataset"]["path"]["total"] = data_dir

    model, state, hist, config, loaders, mm = hydragnn_tpu.run_training(config)
    tot, tasks, preds, trues = hydragnn_tpu.run_prediction(config, model_state=state)
    mae = float(np.mean(np.abs(preds["total_energy"] - trues["total_energy"])))
    print(f"test loss {tot:.5f}; total_energy MAE {mae:.5f}")


if __name__ == "__main__":
    main()
