"""LSMS example: binary-alloy (FePt) multi-task training on formation Gibbs
energy + nodal charge density / magnetic moment (reference:
examples/lsms/lsms.py + lsms.json — FePt_32atoms multihead PNA run).

Pipeline (all framework components, no downloads):
  1. generate synthetic FePt LSMS raw files (BCC supercells, random
     occupations, physically-shaped targets) unless the directory exists,
  2. convert total energies to formation Gibbs energies
     (``convert_total_energy_to_formation_gibbs``),
  3. optionally downselect by composition histogram
     (``--histogram_cutoff N``),
  4. train the multihead model with compositional stratified splitting and
     charge-density correction through ``Dataset.format: "LSMS"``.

    python examples/lsms/lsms.py [--num_configs 96] [--num_epoch 20]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import hydragnn_tpu
from hydragnn_tpu.data import (
    compositional_histogram_cutoff,
    convert_total_energy_to_formation_gibbs,
)

_HERE = os.path.dirname(os.path.abspath(__file__))
Z_FE, Z_PT = 26.0, 78.0
E_FE, E_PT = -3.2, -5.1  # per-atom pure-phase energies (Rydberg-ish scale)


def generate_raw(dir_path, num_configs, seed=11):
    """BCC FePt supercells in LSMS text format: header = total energy, atom
    rows [Z, q, x, y, z, charge_density, magnetic_moment]. Targets are
    closed-form so the example is learnable: formation enthalpy follows a
    regular-solution curve -4*w*x*(1-x), charge density is Z plus a
    composition-dependent net transfer, moments are element-specific."""
    os.makedirs(dir_path)
    rng = np.random.default_rng(seed)
    # 2x2x2 BCC supercell -> 16 sites
    a = 2.85
    cells = np.array(
        [(x, y, z) for x in range(2) for y in range(2) for z in range(2)], float
    )
    sites = np.concatenate([cells, cells + 0.5]) * a
    n = sites.shape[0]
    for i in range(num_configs):
        if i == 0:
            zs = np.full(n, Z_FE)
        elif i == 1:
            zs = np.full(n, Z_PT)
        else:
            zs = np.where(rng.random(n) < rng.uniform(0.1, 0.9), Z_FE, Z_PT)
        x_fe = float(np.mean(zs == Z_FE))
        enthalpy = -4.0 * 0.8 * x_fe * (1.0 - x_fe) * n / 16.0
        total = float(np.sum(np.where(zs == Z_FE, E_FE, E_PT))) + enthalpy
        pos = sites + rng.normal(0.0, 0.03, sites.shape)
        # net charge transfer Fe->Pt grows with the partner concentration
        q_net = np.where(zs == Z_FE, -0.2 * (1 - x_fe), 0.2 * x_fe)
        rho = zs + q_net  # raw charge density includes the proton count
        moment = np.where(zs == Z_FE, 2.2, 0.35)
        with open(os.path.join(dir_path, f"config_{i:04d}.txt"), "w") as f:
            f.write(f"{total!r} 0.0\n")
            for k in range(n):
                f.write(
                    f"{zs[k]:.1f} 0.0 {pos[k, 0]:.6f} {pos[k, 1]:.6f} "
                    f"{pos[k, 2]:.6f} {rho[k]:.6f} {moment[k]:.4f}\n"
                )
    print(f"wrote {num_configs} LSMS samples -> {dir_path}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mpnn_type", default=None)
    ap.add_argument("--num_epoch", type=int, default=None)
    ap.add_argument("--num_configs", type=int, default=96)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--histogram_cutoff", type=int, default=0,
                    help="max samples per composition bin (0 = off)")
    args = ap.parse_args()

    with open(os.path.join(_HERE, "lsms.json")) as f:
        config = json.load(f)
    if args.mpnn_type:
        config["NeuralNetwork"]["Architecture"]["mpnn_type"] = args.mpnn_type
    if args.num_epoch:
        config["NeuralNetwork"]["Training"]["num_epoch"] = args.num_epoch

    raw_dir = os.path.join(os.getcwd(), "dataset", "FePt_raw")
    data_dir = raw_dir + "_gibbs_energy"
    # gate on the *converted* dir so a partial first run (generation ok,
    # conversion failed) is retried rather than skipped forever
    if not os.path.isdir(data_dir):
        if not os.path.isdir(raw_dir):
            generate_raw(raw_dir, args.num_configs)
        res = convert_total_energy_to_formation_gibbs(
            raw_dir, [Z_FE, Z_PT], temperature_kelvin=args.temperature,
            overwrite_data=True,
        )
        print(
            f"formation Gibbs range: [{res.formation_gibbs_energies.min():.4f}, "
            f"{res.formation_gibbs_energies.max():.4f}] Ry"
        )
    if args.histogram_cutoff:
        kept = compositional_histogram_cutoff(
            data_dir, [Z_FE, Z_PT], args.histogram_cutoff, num_bins=10,
            overwrite_data=True,
        )
        print(f"histogram cutoff kept {len(kept)} samples")
        data_dir = data_dir + "_histogram_cutoff"
    config["Dataset"]["path"]["total"] = data_dir

    model, state, hist, config, loaders, mm = hydragnn_tpu.run_training(config)
    tot, tasks, preds, trues = hydragnn_tpu.run_prediction(config, model_state=state)
    mae = {
        k: float(np.mean(np.abs(preds[k] - trues[k]))) for k in preds
    }
    print(
        "test loss "
        f"{tot:.5f}; MAE "
        + ", ".join(f"{k}={v:.4f}" for k, v in mae.items())
    )


if __name__ == "__main__":
    main()
