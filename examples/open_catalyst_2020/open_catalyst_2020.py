"""OC20 S2EF example at the SC25 production shape (reference:
examples/open_catalyst_2020/ + the SC25 model config
examples/multibranch/multibranch_GFM260_SC25.json — EGNN hidden 866,
4 conv layers, radius 5, max 20 neighbors, energy+force objective).

The real OC20 download is unavailable in this image (zero egress), so the
dataset is the OC20-*shaped* generator (``oc20_shaped_dataset``: lognormal
slab sizes ~73 atoms, degree capped at 20, physically-consistent LJ
energy/forces), written once through ``ColumnarWriter``. Defaults are
scaled down for a quick run; pass ``--production`` for the full SC25 shape
(the workload bench.py measures).

    python examples/open_catalyst_2020/open_catalyst_2020.py [--production]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import hydragnn_tpu
from hydragnn_tpu.data import ColumnarWriter, oc20_shaped_dataset

_HERE = os.path.dirname(os.path.abspath(__file__))


def build_dataset(path, num_samples, radius, max_neighbours):
    if os.path.isdir(path):
        return
    graphs = oc20_shaped_dataset(
        number_configurations=num_samples, radius=radius,
        max_neighbours=max_neighbours,
    )
    ColumnarWriter(path).add(graphs).save()
    print(f"wrote {len(graphs)} OC20-shaped samples -> {path}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mpnn_type", default=None)
    ap.add_argument("--num_epoch", type=int, default=None)
    ap.add_argument("--num_samples", type=int, default=128)
    ap.add_argument("--production", action="store_true",
                    help="full SC25 shape: EGNN hidden 866, 4 conv layers")
    args = ap.parse_args()

    with open(os.path.join(_HERE, "open_catalyst_2020.json")) as f:
        config = json.load(f)
    arch = config["NeuralNetwork"]["Architecture"]
    if args.production:
        arch["hidden_dim"] = 866
        arch["num_conv_layers"] = 4
    if args.mpnn_type:
        arch["mpnn_type"] = args.mpnn_type
    if args.num_epoch:
        config["NeuralNetwork"]["Training"]["num_epoch"] = args.num_epoch

    data_path = os.path.join(os.getcwd(), config["Dataset"]["path"]["total"])
    config["Dataset"]["path"]["total"] = data_path
    build_dataset(
        data_path, args.num_samples, arch["radius"], arch["max_neighbours"]
    )

    t0 = time.time()
    model, state, hist, config, loaders, mm = hydragnn_tpu.run_training(config)
    wall = time.time() - t0
    tot, tasks, preds, trues = hydragnn_tpu.run_prediction(config, model_state=state)
    force_mae = float(np.mean(np.abs(preds["forces"] - trues["forces"])))
    n_train = int(args.num_samples * 0.7)
    epochs = config["NeuralNetwork"]["Training"]["num_epoch"]
    print(
        f"test loss {tot:.5f}; force MAE {force_mae:.5f}; "
        f"~{n_train * epochs / wall:.1f} graphs/sec incl. compile"
    )


if __name__ == "__main__":
    main()
