"""QM9 example: single-head graph-property training through the columnar
dataset format (reference: examples/qm9/qm9.py:1-160 — QM9 free-energy
prediction with GPS global attention over SchNet).

The real QM9 download is unavailable in this image (zero egress), so the
dataset builder takes one of two sources:

- ``--xyz_dir DIR``: a directory of .xyz files (real QM9 geometries exported
  to plain xyz; the comment line must carry the free-energy value), parsed by
  the framework's raw XYZ loader, or
- the default QM9-*shaped* generator (``qm9_shaped_dataset``): molecules with
  QM9's size/composition statistics and a closed-form geometric target.

Either source is written once through ``ColumnarWriter`` (the ADIOS-writer
analog) and training then reads it back with ``Dataset.format: "columnar"`` —
the same at-scale path a real dataset would use.

    python examples/qm9/qm9.py [--mpnn_type SchNet] [--num_samples 1000]
"""

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import hydragnn_tpu
from hydragnn_tpu.data import ColumnarWriter, qm9_shaped_dataset
from hydragnn_tpu.data.raw import finalize_graphs, load_xyz_file

_HERE = os.path.dirname(os.path.abspath(__file__))


def build_dataset(path, num_samples, radius, max_neighbours, xyz_dir=None):
    """Write the columnar shard once; later runs reuse it."""
    if os.path.isdir(path):
        return
    if xyz_dir:
        graphs = []
        for f in sorted(glob.glob(os.path.join(xyz_dir, "*.xyz"))):
            g = load_xyz_file(f)
            if g.graph_y is None or len(g.graph_y) < 1:
                raise ValueError(
                    f"{f}: comment line must be numeric graph target(s) "
                    "(free energy first); raw QM9/GDB9 comment lines like "
                    "'gdb N ...' need the target values extracted first"
                )
            graphs.append(g)
        graphs = finalize_graphs(graphs, radius=radius, max_neighbours=max_neighbours)
        # free energy per atom, matching the reference pre-transform
        # (examples/qm9/qm9.py:27: data.y = data.y[:, 10] / len(data.x))
        for g in graphs:
            g.graph_y = (g.graph_y[:1] / g.num_nodes).astype(np.float32)
    else:
        graphs = qm9_shaped_dataset(
            number_configurations=num_samples,
            radius=radius,
            max_neighbours=max_neighbours,
        )
    ColumnarWriter(path).add(graphs).save()
    print(f"wrote {len(graphs)} samples -> {path}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mpnn_type", default=None)
    ap.add_argument("--global_attn_engine", default=None)
    ap.add_argument("--global_attn_type", default=None)
    ap.add_argument("--num_epoch", type=int, default=None)
    ap.add_argument("--num_samples", type=int, default=1000)
    ap.add_argument("--xyz_dir", default=None, help="optional real-data xyz directory")
    args = ap.parse_args()

    with open(os.path.join(_HERE, "qm9.json")) as f:
        config = json.load(f)
    arch = config["NeuralNetwork"]["Architecture"]
    if args.mpnn_type:
        arch["mpnn_type"] = args.mpnn_type
    if args.global_attn_engine is not None:
        arch["global_attn_engine"] = args.global_attn_engine or None
    if args.global_attn_type:
        arch["global_attn_type"] = args.global_attn_type
    if args.num_epoch:
        config["NeuralNetwork"]["Training"]["num_epoch"] = args.num_epoch

    data_path = os.path.join(os.getcwd(), config["Dataset"]["path"]["total"])
    config["Dataset"]["path"]["total"] = data_path
    build_dataset(
        data_path, args.num_samples, arch["radius"], arch["max_neighbours"],
        xyz_dir=args.xyz_dir,
    )

    model, state, hist, config, loaders, mm = hydragnn_tpu.run_training(config)
    tot, tasks, preds, trues = hydragnn_tpu.run_prediction(config, model_state=state)
    err = preds["free_energy"] - trues["free_energy"]
    mae = float(np.mean(np.abs(err)))
    print(f"test loss {tot:.5f}; free_energy MAE {mae:.5f}")


if __name__ == "__main__":
    main()
