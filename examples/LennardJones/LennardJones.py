"""Energy + force training example
(reference: examples/LennardJones/LennardJones.py — energy/force training
with ``compute_grad_energy`` over force-capable models). Forces come from
``-dE/dpos`` via JAX second-order AD; the dataset is generated analytically.

    python examples/LennardJones/LennardJones.py --mpnn_type SchNet
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import hydragnn_tpu

MODEL_OVERRIDES = {
    "MACE": dict(num_radial=6, max_ell=2, node_max_ell=1, correlation=2,
                 radial_type="bessel", envelope_exponent=5),
    "DimeNet": dict(num_radial=6, num_spherical=3, envelope_exponent=5,
                    basis_emb_size=8, int_emb_size=16, out_emb_size=16,
                    num_before_skip=1, num_after_skip=1),
    "PNAPlus": dict(num_radial=5, envelope_exponent=5),
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mpnn_type", default="SchNet")
    ap.add_argument("--num_epoch", type=int, default=30)
    ap.add_argument("--num_configs", type=int, default=128)
    args = ap.parse_args()

    arch = {
        "mpnn_type": args.mpnn_type,
        "radius": 2.5,
        "max_neighbours": 32,
        "hidden_dim": 32,
        "num_conv_layers": 3,
        "task_weights": [1.0],
        "output_heads": {
            "node": {"num_headlayers": 2, "dim_headlayers": [32, 32], "type": "mlp"}
        },
    }
    arch.update(MODEL_OVERRIDES.get(args.mpnn_type, {}))
    config = {
        "Verbosity": {"level": 1},
        "Dataset": {
            "name": "LJ_example",
            "format": "lennard_jones",
            "lennard_jones": {"number_configurations": args.num_configs},
            "node_features": {"name": ["type"], "dim": [1]},
        },
        "NeuralNetwork": {
            "Architecture": arch,
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_names": ["graph_energy"],
                "output_index": [0],
                "type": ["node"],
                "output_dim": [1],
            },
            "Training": {
                "num_epoch": args.num_epoch,
                "batch_size": 32,
                "compute_grad_energy": True,
                "Optimizer": {"type": "AdamW", "learning_rate": 0.005},
            },
        },
    }
    model, state, hist, config, loaders, _ = hydragnn_tpu.run_training(config)
    tot, tasks, preds, trues = hydragnn_tpu.run_prediction(config, model_state=state)
    corr = np.corrcoef(preds["forces"].ravel(), trues["forces"].ravel())[0, 1]
    print(f"energy loss {float(tasks['graph_energy']):.5f}; force corr {corr:.3f}")


if __name__ == "__main__":
    main()
