"""Multidataset GFM training under ZeRO-3/FSDP sharding — the
``multidataset_deepspeed`` analog (reference:
examples/multidataset_deepspeed/train.py: the merged-dataset GFM flow run
under DeepSpeed with a ds_config zero stage; its ``zero_opt_stage`` maps
here to ``Training.Optimizer.zero_stage``, docs/CONFIG.md).

TPU-native version: one multibranch model (one decoder branch per
chemistry family, list-form ``output_heads.graph``) trained over merged
shaped datasets with per-graph ``dataset_id`` routing, while
``zero_stage: 3`` keeps parameters, gradients, AND optimizer moments
sharded ``P(data)`` over the mesh between steps — full copies exist only
transiently inside the jitted step (parallel/mesh.py
``shard_params_zero3``; stage semantics in docs/PERFORMANCE.md). The
whole recipe is config-driven through ``hydragnn_tpu.run_training``: no
engine wrapper, no ds_config file.

    python examples/multidataset_zero/train.py [--num_per_dataset 48]
                                               [--zero_stage 3]
"""

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import hydragnn_tpu
from hydragnn_tpu.data import (
    alexandria_shaped_dataset,
    ani1x_shaped_dataset,
    split_dataset,
    transition1x_shaped_dataset,
)

_HERE = os.path.dirname(os.path.abspath(__file__))

# one decoder branch per family; energies are centered per-atom so every
# branch trains on the same scale (the reference's
# energy_linear_regression.py preprocessing plays this role)
FAMILIES = {
    "ani1x": ani1x_shaped_dataset,
    "alexandria": alexandria_shaped_dataset,
    "transition1x": transition1x_shaped_dataset,
}


def build_merged(num_per_dataset, radius, max_neighbours):
    merged = []
    for ds_id, (name, maker) in enumerate(FAMILIES.items()):
        graphs = maker(
            number_configurations=num_per_dataset, radius=radius,
            max_neighbours=max_neighbours,
        )
        energies = []
        for g in graphs:
            e = g.graph_targets["energy"][0] if g.graph_targets else g.graph_y[0]
            energies.append(e / g.num_nodes)
        e_mean = float(np.mean(energies))
        for g, e in zip(graphs, energies):
            forces = (
                g.node_targets["forces"]
                if g.node_targets and "forces" in g.node_targets
                else np.zeros((g.num_nodes, 3), np.float32)
            )
            merged.append(dataclasses.replace(
                g,
                x=np.asarray(g.z, np.float32)[:, None],
                graph_y=None,
                graph_targets={"energy": np.asarray([e - e_mean], np.float32)},
                node_targets={"force": forces.astype(np.float32)},
                dataset_id=ds_id,
                edge_shifts=(
                    g.edge_shifts
                    if g.edge_shifts is not None
                    else np.zeros((g.num_edges, 3), np.float32)
                ),
            ))
        print(f"{name}: {num_per_dataset} graphs (dataset_id={ds_id})")
    return merged


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num_per_dataset", type=int, default=48)
    ap.add_argument("--num_epoch", type=int, default=None)
    ap.add_argument("--zero_stage", type=int, default=None,
                    help="override Optimizer.zero_stage (1/2/3)")
    args = ap.parse_args()

    with open(os.path.join(_HERE, "gfm_zero3.json")) as f:
        config = json.load(f)
    arch = config["NeuralNetwork"]["Architecture"]
    training = config["NeuralNetwork"]["Training"]
    if args.num_epoch:
        training["num_epoch"] = args.num_epoch
    if args.zero_stage is not None:
        training["Optimizer"]["zero_stage"] = args.zero_stage

    merged = build_merged(
        args.num_per_dataset, arch["radius"], arch["max_neighbours"]
    )
    tr, va, te = split_dataset(merged, 0.8, seed=0)
    model, state, hist, config, loaders, mm = hydragnn_tpu.run_training(
        config, datasets=(tr, va, te)
    )

    # prove the stage actually engaged: with >1 device, ZeRO-3 leaves the
    # params (and moments) P(data)-sharded BETWEEN steps
    import jax

    stage = int(training["Optimizer"].get("zero_stage", 0))
    sharded_params = [
        leaf for leaf in jax.tree_util.tree_leaves(state.params)
        if hasattr(leaf, "sharding") and not leaf.sharding.is_fully_replicated
    ]
    sharded_moments = [
        leaf for leaf in jax.tree_util.tree_leaves(state.opt_state)
        if hasattr(leaf, "sharding") and not leaf.sharding.is_fully_replicated
    ]
    if len(jax.devices()) > 1 and stage >= 3:
        assert sharded_params, "zero_stage 3 left params replicated"
    print(
        f"zero_stage={stage}: {len(sharded_params)} sharded param leaves, "
        f"{len(sharded_moments)} sharded moment leaves "
        f"across {len(jax.devices())} devices"
    )

    tot, tasks, preds, trues = hydragnn_tpu.run_prediction(
        config, model_state=state, datasets=(tr, va, te)
    )
    for name in ("energy", "force"):
        mae = float(np.mean(np.abs(preds[name] - trues[name])))
        print(f"{name} MAE {mae:.5f}")
    print(f"test loss {tot:.5f}")


if __name__ == "__main__":
    main()
