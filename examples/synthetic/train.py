"""Minimal single-dataset example — the qm9-style flow
(reference: examples/qm9/qm9.py:1-160: load -> update_config -> create ->
train -> predict) on the deterministic synthetic dataset, so it runs with
zero downloads on any backend (TPU or CPU).

    python examples/synthetic/train.py [--mpnn_type PNA] [--num_epoch N]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import hydragnn_tpu


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mpnn_type", default=None)
    ap.add_argument("--num_epoch", type=int, default=None)
    args = ap.parse_args()

    config_path = os.path.join(os.path.dirname(__file__), "synthetic.json")
    with open(config_path) as f:
        config = json.load(f)
    if args.mpnn_type:
        config["NeuralNetwork"]["Architecture"]["mpnn_type"] = args.mpnn_type
    if args.num_epoch:
        config["NeuralNetwork"]["Training"]["num_epoch"] = args.num_epoch

    model, state, hist, config, loaders, mm = hydragnn_tpu.run_training(config)
    tot, tasks, preds, trues = hydragnn_tpu.run_prediction(config, model_state=state)
    print(f"test loss {tot:.5f}; tasks {({k: round(float(v), 5) for k, v in tasks.items()})}")


if __name__ == "__main__":
    main()
