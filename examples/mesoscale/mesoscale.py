"""Mesoscale supercell example: ONE periodic graph larger than any per-chip
attention bound, trained with GPS ring attention over a node-sharded mesh.

The reference's GPS requires the whole graph dense on one device
(hydragnn/globalAtt/gps.py:125-141); this example exercises the regime the
TPU framework adds: the supercell's nodes are sharded ``P('data')`` over the
mesh, GPS global attention runs EXACT ring attention (K/V blocks rotate over
ICI, flash-style online softmax — parallel/ring_attention.py), and every
other op is partitioned by XLA from the input shardings. Per-chip attention
memory is O(N * N/devices) blockwise instead of O(N^2).

    python examples/mesoscale/mesoscale.py [--cells 6] [--num_epoch 20]

On the CPU-mesh smoke tier this runs a small supercell over 8 virtual
devices; on a TPU pod slice the same script scales the cell count.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

from hydragnn_tpu.config import update_config
from hydragnn_tpu.data import MinMax, VariablesOfInterest, extract_variables
from hydragnn_tpu.data.graph import Graph, PadSpec, batch_graphs
from hydragnn_tpu.data.lappe import add_dataset_pe
from hydragnn_tpu.data.neighbors import radius_graph_pbc
from hydragnn_tpu.models import create_model, init_model
from hydragnn_tpu.parallel.sp import (
    make_sp_eval_step,
    make_sp_mesh,
    make_sp_train_step,
    shard_sp_batch,
)
from hydragnn_tpu.train import TrainState, make_optimizer


def build_supercell(cells: int, jitter: float, seed: int) -> Graph:
    """BCC supercell with thermal jitter under periodic boundary conditions;
    per-atom scalar feature and a closed-form global target."""
    rng = np.random.default_rng(seed)
    a = 1.0
    base = np.array([[0.0, 0.0, 0.0], [0.5, 0.5, 0.5]]) * a
    pos = []
    for i in range(cells):
        for j in range(cells):
            for k in range(cells):
                pos.append(base + np.array([i, j, k], float) * a)
    pos = np.concatenate(pos) + rng.normal(0.0, jitter, (2 * cells**3, 3))
    cell = np.eye(3) * (a * cells)
    senders, receivers, shifts = radius_graph_pbc(
        pos, cell, radius=1.1 * a, max_neighbours=12
    )
    x = rng.uniform(0.2, 1.0, (pos.shape[0], 1)).astype(np.float32)
    feats = np.concatenate([x, x**2, x**3], axis=1).astype(np.float32)
    target = np.asarray([feats.sum()], np.float32)
    return Graph(
        x=feats,
        pos=pos.astype(np.float32),
        senders=senders.astype(np.int32),
        receivers=receivers.astype(np.int32),
        edge_shifts=shifts.astype(np.float32),
        graph_y=target,
    )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cells", type=int, default=4, help="supercell repeats per axis")
    ap.add_argument("--num_graphs", type=int, default=6)
    ap.add_argument("--num_epoch", type=int, default=20)
    ap.add_argument("--hidden_dim", type=int, default=16)
    ap.add_argument("--heads", type=int, default=4)
    args = ap.parse_args()

    graphs = [
        build_supercell(args.cells, jitter=0.03, seed=7 + i)
        for i in range(args.num_graphs)
    ]
    n_atoms = graphs[0].num_nodes
    graphs = MinMax.fit(graphs).apply(graphs)
    voi = VariablesOfInterest([0], ["total"], ["graph"], [0], [1, 1, 1], [1])
    graphs = [extract_variables(g, voi) for g in graphs]
    graphs = add_dataset_pe(graphs, 1)
    tr, te = graphs[:-1], graphs[-1:]

    config = {
        "NeuralNetwork": {
            "Architecture": {
                "mpnn_type": "GIN",
                "hidden_dim": args.hidden_dim,
                "num_conv_layers": 2,
                "global_attn_engine": "GPS",
                "global_attn_type": "ring",
                "global_attn_heads": args.heads,
                "pe_dim": 1,
                "output_heads": {
                    "graph": {
                        "num_sharedlayers": 1,
                        "dim_sharedlayers": args.hidden_dim,
                        "num_headlayers": 2,
                        "dim_headlayers": [args.hidden_dim, args.hidden_dim],
                    }
                },
                "task_weights": [1.0],
            },
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_names": ["total"],
                "output_index": [0],
                "type": ["graph"],
            },
            "Training": {
                "batch_size": 1,
                "num_epoch": args.num_epoch,
                "Optimizer": {"type": "AdamW", "learning_rate": 3e-3},
            },
        },
        "Dataset": {
            "node_features": {"dim": [1, 1, 1]},
            "graph_features": {"dim": [1]},
        },
    }
    config = update_config(config, tr, te, te)
    model = create_model(config)

    mesh = make_sp_mesh()
    n_dev = mesh.size
    n_pad = (max(g.num_nodes for g in graphs) // n_dev + 2) * n_dev
    e_pad = (max(g.num_edges for g in graphs) // n_dev + 2) * n_dev
    spec = PadSpec(n_nodes=n_pad, n_edges=e_pad, n_graphs=2)
    batches = [shard_sp_batch(batch_graphs([g], spec), mesh) for g in tr]
    test_batch = shard_sp_batch(batch_graphs([te[0]], spec), mesh)

    # init under the SP context too: the dense fallback would materialize
    # the full [H, N, N] attention on one device during the init trace —
    # exactly the memory wall ring attention removes
    from hydragnn_tpu.parallel.sp import sp_context

    with sp_context(mesh):
        variables = init_model(model, batches[0], seed=0)
    tx = make_optimizer(config["NeuralNetwork"]["Training"]["Optimizer"])
    state = TrainState.create(variables, tx)
    step = make_sp_train_step(model, tx, mesh)
    evalf = make_sp_eval_step(model, mesh)

    print(
        f"mesoscale: {n_atoms} atoms/supercell, {len(tr)} train graphs, "
        f"mesh={n_dev} devices, node shard={n_pad // n_dev}"
    )
    rng = jax.random.PRNGKey(0)
    first = None
    for epoch in range(args.num_epoch):
        tots = []
        for b in batches:
            rng, sub = jax.random.split(rng)
            state, tot, _ = step(state, b, sub)
            tots.append(tot)
        tr_loss = float(np.mean(jax.device_get(tots)))
        first = tr_loss if first is None else first
        if epoch % 5 == 0 or epoch == args.num_epoch - 1:
            te_loss, _, _ = evalf(state, test_batch)
            print(f"epoch {epoch}: train {tr_loss:.5f} test {float(te_loss):.5f}")
    assert np.isfinite(tr_loss) and (tr_loss < first or args.num_epoch < 3)
    print(f"mesoscale ring-attention loss {first:.5f} -> {tr_loss:.5f}")


if __name__ == "__main__":
    main()
