"""Fault-tolerant micro-batched graph inference server.

The serving plane the ROADMAP's "millions of users" north star needs, built
on the robustness substrate of the training side (docs/SERVING.md is the
operator doc):

- **admission + validation gate**: a bounded request queue with per-request
  deadlines; every request passes ``data/validate.validate_graph`` plus a
  channel-signature check at the door, so one malformed/NaN request gets a
  typed per-request error (serve/errors.py) instead of poisoning the
  co-batched requests beside it;
- **micro-batcher**: admitted graphs are packed into the run's existing
  ``SpecLadder`` pad buckets (``select_for`` picks the smallest warmed
  level), so the device only ever sees shapes that were AOT-warmed at
  startup — zero-retrace *and* latency-bounded by construction. Readiness
  flips only after warm-up covers the whole ladder; the retrace sentinel
  (train/compile_plane.py) then runs in ``error`` mode as the
  serving-correctness guard;
- **overload behavior**: load shedding with a typed ``SheddedError`` when
  the projected queue wait exceeds the configured p99 SLO, and a
  device-step watchdog that fails a wedged batch's requests with a bounded
  ``WedgedStepError`` and recycles the step runner instead of hanging the
  server;
- **hot checkpoint reload** (serve/reload.py): the run dir's ``latest``
  pointer is watched; candidates restore through the digest-verified
  walk-back chain into a standby state and swap in atomically between
  batches — a corrupt candidate is rejected and the current weights keep
  serving;
- **graceful drain**: ``initiate_drain`` (wired to SIGTERM by
  ``install_sigterm``) stops admissions with a typed ``ServerDrainingError``
  while every in-flight request still completes;
- **observability** (obs/; docs/OBSERVABILITY.md): every lifecycle counter,
  queue depth, readiness, and batch/request latency histograms publish into
  the process metrics registry, scraped at the server's mandatory
  ``/metrics`` + ``/healthz``/``/readyz`` endpoint (``Serving.http_port``,
  default ephemeral loopback) — ``/readyz`` IS the warm-up flip, and goes
  not-ready again the instant a drain starts.

Chaos hooks (exact no-ops unarmed) live in utils/faultinject.py:
``HYDRAGNN_FAULT_SERVE_REQ_NAN`` / ``HYDRAGNN_FAULT_SERVE_WEDGE`` /
``HYDRAGNN_FAULT_SERVE_SLOW_CLIENT``; tests/test_serve.py and
run-scripts/serve_chaos_smoke.py drive every path.
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
import time
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..data.graph import Graph, SpecLadder, batch_graphs
from ..data.validate import R_CHANNELS, describe_reason, validate_graph
from ..obs.events import (
    EV_DEADLINE,
    EV_DRAIN,
    EV_QUEUE_FULL,
    EV_SHED,
    EV_WEDGE,
)
from ..obs.events import emit as _emit_event
from ..obs.trace import STATUS_ERROR, STATUS_OK
from ..utils import faultinject
from .config import ServeConfig
from .errors import (
    DeadlineExceededError,
    InvalidRequestError,
    QueueFullError,
    RequestError,
    ServerClosedError,
    ServerDrainingError,
    SheddedError,
    WedgedStepError,
)

# consumer/waiter wake-up cadence (module-level so tests can pin it)
_TICK_S = 0.02
_JOIN_TIMEOUT_S = 5.0


def _emit_serve_event(kind, severity=None, trace_id=None, **attrs):
    """Typed incident record (obs/events.py), exception-proof: an event
    emission must never fail the request path it describes. ``severity``
    defaults through the per-kind DEFAULT_SEVERITY table (shed/queue-full
    rank warn, wedge error, drain info) so doctor rules and the flight
    recorder's census rank serve incidents without kind-name heuristics."""
    try:
        _emit_event(kind, severity=severity, trace_id=trace_id, **attrs)
    except Exception:
        pass


class PredictionHandle:
    """Client-side handle for one submitted request. ``result()`` blocks for
    the outcome and re-raises the request's typed error; ``error()`` returns
    it as a value instead (the response-object style the chaos smoke and
    ``GraphServer.predict`` use)."""

    __slots__ = (
        "request_id", "deadline", "submitted_at", "done_at", "_event",
        "_result", "_error", "trace",
    )

    def __init__(self, request_id: int, deadline: float):
        self.request_id = request_id
        self.deadline = deadline
        # monotonic admission/completion stamps (perf_counter): done_at is
        # set with the outcome so latency harnesses (BENCH_SERVE) and the
        # serve latency histogram compute per-request latency without a
        # waiter thread per request
        self.submitted_at: float = time.perf_counter()
        self.done_at: Optional[float] = None
        self._event = threading.Event()
        self._result: Optional[Dict[str, np.ndarray]] = None
        self._error: Optional[RequestError] = None
        # head-sampled tracing (obs/trace.py): the open serve/request root
        # span of this request's trace, or None (unsampled/no tracer)
        self.trace = None

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def error(self, timeout: Optional[float] = None) -> Optional[RequestError]:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} has no outcome after {timeout}s"
            )
        return self._error

    def result(self, timeout: Optional[float] = None) -> Dict[str, np.ndarray]:
        err = self.error(timeout)
        if err is not None:
            raise err
        return self._result

    # -- server side --------------------------------------------------------
    def _resolve(self, result: Dict[str, np.ndarray]) -> None:
        self._result = result
        self.done_at = time.perf_counter()
        self._event.set()

    def _fail(self, err: RequestError) -> None:
        err.request_id = self.request_id
        self._error = err
        self.done_at = time.perf_counter()
        self._event.set()


@dataclasses.dataclass
class _Request:
    graph: Graph
    handle: PredictionHandle


def _strip_targets(g: Graph) -> Graph:
    """Serving inputs carry no supervision: drop target tables (and the raw
    graph feature table) so request batches share one pytree structure with
    the warmed templates regardless of where the client got the graph."""
    if g.graph_targets is None and g.node_targets is None and g.graph_y is None:
        return g
    return dataclasses.replace(
        g, graph_targets=None, node_targets=None, graph_y=None
    )


def _channel_signature(g: Graph) -> Tuple[Tuple[str, int], ...]:
    """(field, width) census of the channels that shape a batch pytree. Two
    graphs with equal signatures batch into abstractly identical arrays; a
    mismatch would force a new jit specialization (or crash batching), so it
    is rejected at admission instead."""
    sig: List[Tuple[str, int]] = []
    for name in ("x", "pos", "edge_attr", "edge_shifts", "pe", "rel_pe", "z"):
        v = getattr(g, name)
        if v is None:
            continue
        arr = np.asarray(v)
        sig.append((name, int(arr.shape[1]) if arr.ndim > 1 else 1))
    return tuple(sig)


class _StepTimeout(Exception):
    """Internal: the step runner exceeded its watchdog budget."""


class _StepRunner:
    """One daemon worker executing device steps, replaceable on a wedge: a
    step that blows ``step_timeout_s`` leaves its thread abandoned (daemon —
    it cannot block process exit) and a fresh runner takes over, so the
    serve loop never queues behind a hung XLA program."""

    def __init__(self, name: str = "serve-step"):
        self._in: "queue.Queue" = queue.Queue(maxsize=1)
        self._out: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(target=self._main, daemon=True, name=name)
        self._thread.start()

    def _main(self) -> None:
        while True:
            thunk = self._in.get()  # graftlint: disable=threads -- daemon runner's idle loop: blocking for the next thunk IS the design; the wedge watchdog bounds run() on the consumer side and recycles the runner
            if thunk is None:
                return
            try:
                self._out.put(("ok", thunk()))
            except BaseException as e:  # surfaced in run()
                self._out.put(("err", e))

    def run(self, thunk, timeout: float):
        self._in.put(thunk)
        try:
            kind, val = self._out.get(timeout=timeout if timeout > 0 else None)
        except queue.Empty:
            raise _StepTimeout() from None
        if kind == "err":
            raise val
        return val

    def stop(self) -> None:
        try:
            self._in.put_nowait(None)
        except queue.Full:
            pass  # wedged mid-step; the daemon thread is simply abandoned


class GraphServer:
    """Micro-batched ``run_prediction`` with a full request lifecycle.

    Construct directly from (model, state, ladder, template graphs) or via
    ``api.run_server`` (which restores the run's verified checkpoint and
    reuses the data pipeline's ladder). ``state`` only needs a
    ``variables()`` method — ``train.state.InferenceState`` is the intended
    (optimizer-free) carrier, a full ``TrainState`` also works.
    """

    def __init__(
        self,
        model,
        state,
        ladder: SpecLadder,
        serve_config: Optional[ServeConfig] = None,
        *,
        template_graphs: Sequence[Graph],
        mixed_precision: bool = False,
        sort_edges: bool = False,
        log_name: str = "serve",
        checkpoint_label: Optional[str] = None,
        checkpoint_dir: Optional[str] = None,
        restore_template=None,
        tracer=None,
        flight_recorder=None,
    ):
        self.model = model
        self.cfg = serve_config or ServeConfig()
        # tracing plane (obs/trace.py; docs/OBSERVABILITY.md "Tracing"):
        # sampled requests get a serve/request trace covering admit ->
        # queue_wait -> (linked serve/step) -> respond. The server OWNS a
        # tracer/flight recorder handed to it (api.run_server builds them
        # from Telemetry.trace*): close() tears them down.
        self._tracer = tracer
        self._flight = flight_recorder
        self.ladder = ladder
        self.log_name = log_name
        self.mixed_precision = mixed_precision
        self.sort_edges = sort_edges
        self.current_checkpoint = checkpoint_label
        templates = [_strip_targets(g) for g in template_graphs]
        clean = [g for g in templates if validate_graph(g) is None]
        if not clean:
            raise ValueError(
                "GraphServer needs at least one valid template graph to warm "
                "the pad-bucket ladder"
            )
        self._template_graphs = clean
        self._channel_sig = _channel_signature(clean[0])
        # int8 plane wiring (serve/quantize.py): checkpoint_dir locates the
        # pre-quantized snapshot artifacts beside the run's checkpoints;
        # restore_template keeps the PRE-cast state tree — hot reload
        # restores msgpack subtrees into it (a quantized state's structure
        # cannot template a checkpoint restore); _quant_report is the
        # accuracy-gate verdict stats() exposes.
        self._checkpoint_dir = checkpoint_dir
        self.restore_template = (
            restore_template if restore_template is not None else state
        )
        self._quant_report: Optional[Dict[str, Any]] = None
        # cast AFTER the template/ladder fields above: int8 quantization
        # calibrates and gates on the warmed ladder's template batches
        self._state = self._cast_weights(state, entry=checkpoint_label)
        self._worst = ladder.specs[-1]
        # real-graph slots are bounded by the worst spec too (n_graphs
        # includes the +1 dummy slot): a Serving.micro_batch_graphs above
        # the ladder's batch size would make every full batch overflow
        # batch_graphs, failing its co-batched requests
        self._batch_cap = min(
            int(self.cfg.micro_batch_graphs), self._worst.n_graphs - 1
        )

        self._queue: "queue.Queue[_Request]" = queue.Queue(
            maxsize=max(int(self.cfg.max_queue_requests), 0)
        )
        self._holdover: Optional[_Request] = None
        self._form_started: Optional[float] = None
        self._submit_seq = itertools.count()
        self._batch_seq = itertools.count()
        self._inflight_graphs = 0
        self._per_graph_s = float(self.cfg.expected_latency_per_graph_s)
        self._swap_lock = threading.Lock()
        self._pending_state: Optional[Tuple[Any, Optional[str]]] = None
        self._ready = threading.Event()
        self._draining = threading.Event()
        # admissions stay open until this monotonic stamp once _draining is
        # set (Serving.drain_grace_s): /readyz flips not-ready immediately,
        # so a load balancer stops routing BEFORE clients start eating
        # ServerDrainingError. 0.0 default = reject the instant drain
        # starts (grace 0 keeps pre-fleet behavior exactly).
        self._drain_admit_deadline = 0.0
        self._drained = threading.Event()
        self._stop = threading.Event()
        self._closed = False
        self._armed = False
        # stats() reports violations as a delta against this launch-time
        # baseline of the process-global sentinel — a warn-policy training
        # run earlier in the process must not bleed into this server's count
        from ..train.compile_plane import sentinel

        self._violations_at_launch = len(sentinel().violations())
        self.failed: Optional[Exception] = None
        self.warmup_compiled: List[Tuple[str, float]] = []
        self._stats_lock = threading.Lock()
        self._stats: Dict[str, int] = {
            "submitted": 0,
            "admitted": 0,
            "completed": 0,
            "rejected": 0,
            "shed": 0,
            "queue_full": 0,
            "deadline_expired": 0,
            "wedged_batches": 0,
            "failed_batches": 0,
            "batches": 0,
            "reloads": 0,
        }
        # telemetry plane (obs/): every counter _bump touches is mirrored
        # into the process registry, plus queue depth / readiness gauges and
        # batch / per-request latency histograms — the scrapeable SLO
        # surface behind /metrics (Serving.http_port). Series materialize
        # at 0 so a scrape is schema-complete before the first request.
        # Scope: these are PROCESS metrics (one serving instance per
        # process is the run_server deployment model) — counters span every
        # instance's lifetime, gauges are last-writer; construction uses
        # set_default so building a standby server never clobbers a live
        # one's readiness.
        from ..obs.registry import registry as _obs_registry

        _reg = _obs_registry()
        self._m_events = _reg.counter(
            "hydragnn_serve_events_total",
            "Serving request-lifecycle event counts (GraphServer.stats keys)",
            labelnames=("event",),
        )
        for key in self._stats:
            self._m_events.inc(0, event=key)
        self._m_queue = _reg.gauge(
            "hydragnn_serve_queue_depth",
            "Admitted requests waiting in the micro-batcher queue",
        )
        self._m_ready = _reg.gauge(
            "hydragnn_serve_ready",
            "1 once the full ladder is warmed and admissions are open",
        )
        self._m_batch_lat = _reg.histogram(
            "hydragnn_serve_batch_latency_seconds",
            "Device micro-batch service time (form -> outputs on host)",
        )
        self._m_req_lat = _reg.histogram(
            "hydragnn_serve_request_latency_seconds",
            "Per-request latency, admission to delivered outcome (outcome="
            "error covers deadline/wedge/batch failures — without it the "
            "p99 would be survivorship-biased exactly under overload)",
            labelnames=("outcome",),
        )
        self._m_queue.set_default(0)
        self._m_ready.set_default(0)
        self._predict_fn = self._build_predict_fn()
        self._runner: Optional[_StepRunner] = None
        self._serve_thread: Optional[threading.Thread] = None
        self._warm_thread: Optional[threading.Thread] = None
        self._watcher = None  # serve/reload.CheckpointWatcher
        self._prev_sigterm = None
        self._http = None  # obs/prometheus.TelemetryHTTPServer

    # -- construction helpers ------------------------------------------------

    def _build_predict_fn(self):
        import jax

        from ..train.compile_plane import note_trace
        from ..train.loop import mp_cast_eval

        model = self.model
        quantized = self.cfg.weights_dtype == "int8"
        w8a8 = bool(
            quantized
            and self.cfg.quantization is not None
            and self.cfg.quantization.mode == "w8a8"
        )
        # int8 states define their own precision story: mp_cast_eval would
        # cast the fp32 dequant scales (and the quant collection) to bf16,
        # silently shifting exactly the values the accuracy gate certified
        mixed_precision = self.mixed_precision and not quantized
        if w8a8:
            from flax import linen as nn

            from .quantize import w8a8_interceptor

        @jax.jit
        def predict_step(state, batch):
            # retrace sentinel census: runs once per jit trace
            note_trace("serve_predict", (state, batch))
            variables = state.variables()
            if mixed_precision:
                variables, batch = mp_cast_eval(variables, batch, False)
            if w8a8:
                with nn.intercept_methods(w8a8_interceptor):
                    return model.apply(variables, batch, train=False)
            return model.apply(variables, batch, train=False)

        return predict_step

    # -- lifecycle -----------------------------------------------------------

    def start(self, install_sigterm: bool = False) -> "GraphServer":
        """Launch warm-up + the serve loop (and the checkpoint watcher when
        ``Serving.hot_reload`` and a run dir are configured by the caller via
        ``attach_watcher``). Admission opens immediately — requests queue
        while the ladder warms; readiness (``wait_ready``) flips only once
        every servable specialization is compiled and the sentinel is armed."""
        if self._closed:
            raise ServerClosedError("server is closed")
        if self._serve_thread is not None:
            return self
        if int(self.cfg.http_port) >= 0:
            # mandatory observability surface (docs/SERVING.md
            # "Endpoints"): /metrics + /healthz + /readyz. Readiness IS the
            # full-ladder warm-up flip that opens the serve loop — a load
            # balancer routing on /readyz only ever sends traffic to a
            # zero-retrace server that is accepting admissions. Best-effort
            # bind: an occupied port warns instead of failing the server.
            from ..obs.prometheus import start_endpoint

            self._http = start_endpoint(
                int(self.cfg.http_port),
                ready_fn=lambda: (
                    self._ready.is_set()
                    and self.failed is None
                    and not self._closed
                    and not self._draining.is_set()
                ),
                health_fn=lambda: (
                    (True, "serving")
                    if self.failed is None and not self._closed
                    else (
                        False,
                        "closed"
                        if self.failed is None
                        else f"warm-up failed: {self.failed}",
                    )
                ),
                label=f"serve[{self.log_name}]",
                host=self.cfg.http_host,
            )
        if install_sigterm:
            import signal

            def _on_sigterm(signum, frame):
                # async-signal-safe: only flags; the serve loop finishes
                # in-flight + queued work and then exits (graceful drain)
                self.initiate_drain()
                prev = self._prev_sigterm
                if callable(prev):
                    prev(signum, frame)

            try:
                self._prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
            except ValueError:
                pass  # not the main thread; the caller wires drain itself
        self._warm_thread = threading.Thread(
            target=self._warmup, daemon=True, name="serve-warmup"
        )
        self._warm_thread.start()
        self._runner = _StepRunner()
        self._serve_thread = threading.Thread(
            target=self._serve_loop, daemon=True, name="serve-loop"
        )
        self._serve_thread.start()
        return self

    def attach_watcher(self, watcher) -> None:
        """Register a started CheckpointWatcher so close() tears it down."""
        self._watcher = watcher

    def _warmup(self) -> None:
        from ..data.pipeline import spec_template_batches
        from ..train.compile_plane import serve_warmup

        try:
            templates = spec_template_batches(
                self._template_graphs, self.ladder, sort_edges=self.sort_edges
            )
            if not templates:
                raise ValueError(
                    "no template graph fits any ladder level — the ladder "
                    "does not describe the template dataset"
                )
            compiled, errors, exec_s = serve_warmup(
                self._predict_fn,
                self._state,
                templates,
                policy=self.cfg.retrace_policy,
                label="serve",
            )
            self.warmup_compiled = compiled
            if errors:
                raise RuntimeError(
                    f"serve warm-up failed for {len(errors)} specialization(s): "
                    f"{errors}"
                )
            if self._stop.is_set():
                # close() raced warm-up: it already evaluated (and skipped)
                # its _armed disarm, so the sentinel serve_warmup just armed
                # would leak error-mode into the rest of the process
                from ..train.compile_plane import sentinel

                sentinel().disarm()
                return
            self._armed = True
            if self._per_graph_s <= 0 and exec_s > 0:
                # seed the shed estimator with the measured worst-level
                # execution time (one real graph per template batch)
                self._per_graph_s = exec_s
        except Exception as e:  # noqa: BLE001 — the server must fail typed
            self.failed = e
            self._stop.set()
            self._drained.set()
            self._fail_queued(
                ServerClosedError(f"serve warm-up failed: {e}")
            )
            return
        self._ready.set()
        self._m_ready.set(1)

    @property
    def ready(self) -> bool:
        return self._ready.is_set()

    @property
    def http_port(self) -> Optional[int]:
        """Port of the /metrics//healthz//readyz endpoint, or None when
        disabled (``Serving.http_port`` < 0) or the bind failed."""
        return self._http.port if self._http is not None else None

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        """Block until warm-up completes (True) or fails/times out (False;
        ``self.failed`` carries the warm-up error)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self._ready.is_set():
            if self.failed is not None:
                return False
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(_TICK_S)
        return True

    def initiate_drain(self) -> None:
        """Stop admitting (async-signal-safe: only sets a flag); in-flight
        and queued requests still complete. The SIGTERM hook. (The ready
        gauge/endpoint report not-ready from here on — a draining server
        must fall out of its load balancer; the gauge write is a plain
        dict store, still async-signal-safe. Only the instance that
        reported ready may zero the shared gauge — draining a never-ready
        standby must not clobber a live server's readiness.)

        Drain ordering (docs/SERVING.md "Fleet"): /readyz keys off
        ``_draining`` and flips 503 the moment it is set, but ``submit``
        keeps admitting for ``Serving.drain_grace_s`` more — the window in
        which a load balancer observes the not-ready flip and stops
        routing here, so well-behaved clients never see a
        ServerDrainingError. The stamp is arithmetic + a float store,
        still async-signal-safe."""
        self._drain_admit_deadline = time.monotonic() + float(
            self.cfg.drain_grace_s
        )
        self._draining.set()
        if self._ready.is_set():
            self._m_ready.set(0)
        # typed drain record (signal-safe like the gauge write: the event
        # log's RLock allows same-thread re-entry, and the emit is a deque
        # append + counter inc)
        _emit_serve_event(EV_DRAIN, severity="info", queued=self._queue.qsize())

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Initiate + wait for the drain to finish. Returns True when every
        admitted request was answered."""
        self.initiate_drain()
        if timeout is None:
            timeout = self.cfg.drain_timeout_s or None
        return self._drained.wait(timeout)

    def close(self, drain: bool = True, timeout: Optional[float] = None) -> None:
        """Shut down: optionally drain, stop every thread, disarm the
        sentinel, and fail whatever is still queued with a typed error."""
        if self._closed:
            return
        if drain and self._serve_thread is not None and self.failed is None:
            self.drain(timeout)
        self._closed = True
        self._stop.set()
        # drop any staged reload the serve loop will never swap in — a
        # watcher poll that staged between drain and here must not leak
        # the standby state past the server's lifetime
        with self._swap_lock:
            self._pending_state = None
        if self._ready.is_set():
            # same standby guard as initiate_drain: only a server that
            # reported ready un-reports on close
            self._m_ready.set(0)
        if self._http is not None:
            self._http.close()
            self._http = None
        if self._watcher is not None:
            self._watcher.stop()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=_JOIN_TIMEOUT_S)
            if self._serve_thread.is_alive():
                warnings.warn(
                    "serve loop still alive at close(); leaking the daemon "
                    "thread",
                    RuntimeWarning,
                    stacklevel=2,
                )
        if self._runner is not None:
            self._runner.stop()
        self._fail_queued(ServerClosedError("server closed"))
        if self._armed:
            from ..train.compile_plane import sentinel

            sentinel().disarm()
        if self._prev_sigterm is not None:
            import signal

            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass
            self._prev_sigterm = None
        # tracing-plane teardown (the server owns what it was handed)
        if self._flight is not None:
            try:
                self._flight.uninstall()
            except Exception:
                pass
        if self._tracer is not None:
            from ..obs import trace as _obs_trace

            try:
                _obs_trace.uninstall(self._tracer)
                self._tracer.close()
            except Exception:
                pass
        self._drained.set()

    def __enter__(self) -> "GraphServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close(drain=exc == (None, None, None))

    # -- admission -----------------------------------------------------------

    def submit(
        self,
        graph: Graph,
        deadline_s: Optional[float] = None,
        ) -> PredictionHandle:
        """Admit one request. Admission-time rejections raise the typed
        error directly (invalid request, queue full, shed, draining/closed);
        an admitted request's later failures are delivered on the handle."""
        idx = next(self._submit_seq)
        t_admit_wall = time.time()
        self._bump("submitted")
        # chaos hook: a slow client holding the admission door (no-op unarmed)
        faultinject.maybe_slow_client(idx)
        if self._closed or self.failed is not None:
            self._bump("rejected")
            raise ServerClosedError(
                "server is closed"
                if self.failed is None
                else f"server failed at warm-up: {self.failed}",
                request_id=idx,
            )
        # grace window (initiate_drain): /readyz is already 503, but
        # admissions stay open until the stamped deadline so the LB can
        # stop routing before clients see the typed rejection
        if self._draining.is_set() and (
            time.monotonic() >= self._drain_admit_deadline
        ):
            self._bump("rejected")
            raise ServerDrainingError(
                "server is draining (SIGTERM or drain()); request not admitted",
                request_id=idx,
            )
        g = _strip_targets(graph)
        # chaos hook: corrupt-request injection by submission index
        g = faultinject.poison_request(g, idx)
        if _channel_signature(g) != self._channel_sig:
            self._bump("rejected")
            raise InvalidRequestError(
                f"request {idx} channel layout {_channel_signature(g)} does "
                f"not match the served model's {self._channel_sig} — "
                f"{describe_reason(R_CHANNELS)}",
                request_id=idx,
                reason=R_CHANNELS,
            )
        reason = validate_graph(
            g, max_nodes=self._worst.n_nodes - 1, max_edges=self._worst.n_edges
        )
        if reason is not None:
            self._bump("rejected")
            raise InvalidRequestError(
                f"request {idx} rejected: {reason} ({describe_reason(reason)})",
                request_id=idx,
                reason=reason,
            )
        # load shedding: admit only what can plausibly meet the p99 SLO
        if self.cfg.slo_p99_s > 0 and self._per_graph_s > 0:
            backlog = self._queue.qsize() + self._inflight_graphs + (
                1 if self._holdover is not None else 0
            )
            projected = backlog * self._per_graph_s
            if projected > self.cfg.slo_p99_s:
                self._bump("shed")
                _emit_serve_event(
                    EV_SHED,
                    request_id=idx,
                    projected_wait_s=round(projected, 6),
                    slo_s=self.cfg.slo_p99_s,
                )
                raise SheddedError(
                    f"request {idx} shed: projected queue wait "
                    f"{projected:.3f}s exceeds the p99 SLO "
                    f"{self.cfg.slo_p99_s:.3f}s",
                    request_id=idx,
                    projected_wait_s=projected,
                    slo_s=self.cfg.slo_p99_s,
                )
        if deadline_s is None:
            deadline_s = self.cfg.default_deadline_s
        deadline = (
            time.monotonic() + float(deadline_s) if deadline_s else float("inf")
        )
        handle = PredictionHandle(idx, deadline)
        # head-sampling decision at the trace root, BEFORE the enqueue: the
        # serve loop could dequeue (and look for the trace context) the
        # instant the request lands in the queue
        if self._tracer is not None and self._tracer.sample_request():
            # backdated to submit ENTRY: the root's duration is the full
            # admission-to-outcome latency, and the admit child nests
            # inside it temporally
            root = self._tracer.begin("serve/request", start_unix=t_admit_wall)
            root.set_attribute("request_id", idx)
            handle.trace = root
            self._tracer.emit_completed(
                "serve/admit",
                t_admit_wall,
                time.time() - t_admit_wall,
                parent=root,
            )
        try:
            self._queue.put_nowait(_Request(g, handle))
        except queue.Full:
            self._bump("queue_full")
            _emit_serve_event(
                EV_QUEUE_FULL,
                trace_id=(
                    handle.trace.trace_id if handle.trace is not None else None
                ),
                request_id=idx,
                bound=self.cfg.max_queue_requests,
            )
            self._end_request_trace(handle, error="queue_full")
            raise QueueFullError(
                f"request {idx} rejected: admission queue is at its bound "
                f"({self.cfg.max_queue_requests} requests)",
                request_id=idx,
            ) from None
        self._bump("admitted")
        self._m_queue.set(self._queue.qsize())
        return handle

    def predict(
        self,
        graphs: Sequence[Graph],
        deadline_s: Optional[float] = None,
        timeout: Optional[float] = None,
    ) -> List[Union[Dict[str, np.ndarray], RequestError]]:
        """Blocking convenience: one outcome per input graph — a per-head
        prediction dict, or the request's typed ``RequestError`` as a value
        (admission rejections included), so one bad request never hides the
        results of the good ones beside it."""
        handles: List[Union[PredictionHandle, RequestError]] = []
        for g in graphs:
            try:
                handles.append(self.submit(g, deadline_s=deadline_s))
            except RequestError as e:
                handles.append(e)
        out: List[Union[Dict[str, np.ndarray], RequestError]] = []
        for h in handles:
            if isinstance(h, RequestError):
                out.append(h)
                continue
            err = h.error(timeout)
            out.append(err if err is not None else h.result(0))
        return out

    # -- serve loop ----------------------------------------------------------

    def _take_request(self, timeout: float) -> Optional[_Request]:
        """Next admitted request, honoring the holdover slot and failing
        deadline-expired requests at dequeue (never wasting batch slots on
        answers nobody is waiting for)."""
        deadline = time.monotonic() + max(timeout, 0.0)
        while not self._stop.is_set():
            if self._holdover is not None:
                req, self._holdover = self._holdover, None
            else:
                remaining = deadline - time.monotonic()
                if remaining <= 0 and timeout > 0:
                    return None
                try:
                    req = self._queue.get(
                        timeout=min(max(remaining, 0.0), _TICK_S)
                        if timeout > 0
                        else _TICK_S
                    )
                except queue.Empty:
                    if timeout > 0:
                        continue
                    return None
            if time.monotonic() > req.handle.deadline:
                self._bump("deadline_expired")
                _emit_serve_event(
                    EV_DEADLINE,
                    trace_id=(
                        req.handle.trace.trace_id
                        if req.handle.trace is not None
                        else None
                    ),
                    request_id=req.handle.request_id,
                    waited_s=round(
                        time.perf_counter() - req.handle.submitted_at, 6
                    ),
                )
                self._fail_request(
                    req.handle,
                    DeadlineExceededError(
                        "deadline expired while queued (waited past the "
                        "request's budget)"
                    ),
                )
                continue
            if req.handle.trace is not None:
                # queue-wait span, retroactive at dequeue: THE latency
                # explainer under pressure (admission -> this dequeue)
                wait = time.perf_counter() - req.handle.submitted_at
                self._tracer.emit_completed(
                    "serve/queue_wait",
                    time.time() - wait,
                    wait,
                    parent=req.handle.trace,
                )
            return req
        return None

    def _collect_batch(self) -> Optional[List[_Request]]:
        """Form one micro-batch: wait for a first request, then fill from
        the queue until the graph-count cap, the worst-spec pad budget, or
        the batch window closes. A request that does not fit is held over to
        lead the next batch."""
        first = self._take_request(timeout=0.0)
        if first is None:
            return None
        # batch-formation clock starts at the leading request's dequeue
        # (the serve/batch_form span; idle waiting before it is queue time)
        self._form_started = time.perf_counter()
        reqs = [first]
        n = first.graph.num_nodes
        e = first.graph.num_edges
        window_ends = time.monotonic() + self.cfg.batch_window_s
        while len(reqs) < self._batch_cap:
            remaining = window_ends - time.monotonic()
            if remaining <= 0 and self._queue.qsize() == 0 and self._holdover is None:
                break
            req = self._take_request(timeout=max(remaining, _TICK_S / 10))
            if req is None:
                break
            gn, ge = req.graph.num_nodes, req.graph.num_edges
            if n + gn > self._worst.n_nodes - 1 or e + ge > self._worst.n_edges:
                self._holdover = req
                break
            reqs.append(req)
            n, e = n + gn, e + ge
        return reqs

    def _serve_loop(self) -> None:
        import jax

        # process nothing before the ladder is warm: the first organic batch
        # must already be a cache hit (readiness == zero-retrace)
        while not self._ready.is_set():
            if self._stop.is_set():
                return
            time.sleep(_TICK_S)
        while not self._stop.is_set():
            reqs = self._collect_batch()
            # hot-reload swap point: AFTER batch formation, before dispatch —
            # between batches, never mid-flight, and a state installed while
            # the loop was blocked waiting for requests is guaranteed to
            # serve the very next batch (not the one after)
            with self._swap_lock:
                if self._pending_state is not None:
                    self._state, self.current_checkpoint = self._pending_state
                    self._pending_state = None
                    self._bump("reloads")
            if reqs is None:
                # exit only once the admission grace window has also passed
                # — a request legitimately admitted during drain_grace_s
                # must not race a loop that already quit
                if self._draining.is_set() and self._queue.qsize() == 0 and (
                    self._holdover is None
                ) and time.monotonic() >= self._drain_admit_deadline:
                    break
                continue
            self._inflight_graphs = len(reqs)
            batch_index = next(self._batch_seq)
            state = self._state
            graphs = [r.graph for r in reqs]
            step_span = self._begin_step_span(reqs, batch_index)
            t0 = time.perf_counter()
            try:
                spec = self.ladder.select_for(graphs)
                if step_span is not None:
                    sel_dt = time.perf_counter() - t0
                    self._tracer.emit_completed(
                        "serve/bucket_select",
                        time.time() - sel_dt,
                        sel_dt,
                        parent=step_span,
                        attributes={
                            "level": f"{spec.n_nodes}n/{spec.n_edges}e"
                        },
                    )
                batch = batch_graphs(graphs, spec, sort_edges=self.sort_edges)

                def step(_state=state, _batch=batch, _bi=batch_index):
                    # chaos hook: a wedged device step (no-op unarmed)
                    faultinject.maybe_serve_wedge(_bi)
                    return jax.device_get(self._predict_fn(_state, _batch))

                t_dev = time.perf_counter()
                outputs = self._runner.run(step, self.cfg.step_timeout_s)
                if step_span is not None:
                    dev_dt = time.perf_counter() - t_dev
                    self._tracer.emit_completed(
                        "serve/device_step",
                        time.time() - dev_dt,
                        dev_dt,
                        parent=step_span,
                    )
            except _StepTimeout:
                self._bump("wedged_batches")
                _emit_serve_event(
                    EV_WEDGE,
                    severity="error",
                    trace_id=(
                        step_span.trace_id if step_span is not None else None
                    ),
                    batch_index=batch_index,
                    graphs=len(reqs),
                    step_timeout_s=self.cfg.step_timeout_s,
                )
                # the wedged runner thread is abandoned (daemon); recycle
                self._runner = _StepRunner()
                for r in reqs:
                    self._fail_request(
                        r.handle,
                        WedgedStepError(
                            f"device step for batch {batch_index} exceeded "
                            f"step_timeout_s={self.cfg.step_timeout_s}s; the "
                            "batch was abandoned and the step runner recycled"
                        )
                    )
                self._finish_step_span(step_span, error="wedged_step")
                # black-box dump: a wedged device step is a flight-recorder
                # trigger point — the dump carries the wedge event (with its
                # trace_id), the abandoned batch's spans, and the registry
                self._flight_dump("serve_wedge")
                self._inflight_graphs = 0
                continue
            except Exception as e:  # noqa: BLE001 — batch-level failure
                self._bump("failed_batches")
                for r in reqs:
                    self._fail_request(
                        r.handle,
                        RequestError(
                            f"batch {batch_index} failed: "
                            f"{type(e).__name__}: {e}"
                        ),
                    )
                self._finish_step_span(
                    step_span, error=f"{type(e).__name__}: {e}"
                )
                self._inflight_graphs = 0
                continue
            dt = time.perf_counter() - t0
            self._m_batch_lat.observe(dt)
            self._m_queue.set(self._queue.qsize())
            t_resp = time.perf_counter()
            self._deliver(reqs, batch, outputs)
            if step_span is not None:
                resp_dt = time.perf_counter() - t_resp
                self._tracer.emit_completed(
                    "serve/respond",
                    time.time() - resp_dt,
                    resp_dt,
                    parent=step_span,
                )
            self._finish_step_span(step_span)
            self._bump("batches")
            self._bump("completed", len(reqs))
            # EMA service-time estimate drives the shed projection
            per_graph = dt / len(reqs)
            self._per_graph_s = (
                per_graph
                if self._per_graph_s <= 0
                else 0.8 * self._per_graph_s + 0.2 * per_graph
            )
            self._inflight_graphs = 0
        self._drained.set()

    def _deliver(self, reqs: List[_Request], batch, outputs: Dict[str, Any]) -> None:
        """Slice the padded batch outputs back into per-request, per-head
        host arrays: graph-level heads by graph row, node-level heads by the
        request's node span."""
        node_offsets = np.cumsum([0] + [r.graph.num_nodes for r in reqs])
        n_graphs = batch.num_graphs
        n_nodes = batch.num_nodes
        for i, r in enumerate(reqs):
            result: Dict[str, np.ndarray] = {}
            for name, arr in outputs.items():
                a = np.asarray(arr)
                if a.ndim and a.shape[0] == n_graphs:
                    result[name] = a[i]
                elif a.ndim and a.shape[0] == n_nodes:
                    result[name] = a[node_offsets[i] : node_offsets[i + 1]]
                else:  # scalar/aux output: handed through as-is
                    result[name] = a
            r.handle._resolve(result)
            self._m_req_lat.observe(
                r.handle.done_at - r.handle.submitted_at, outcome="ok"
            )
            self._end_request_trace(r.handle)

    # -- tracing helpers -----------------------------------------------------

    def _begin_step_span(self, reqs: List[_Request], batch_index: int):
        """Open the shared device-step span for a batch holding sampled
        requests: the span lives in the LEAD sampled request's trace and is
        cross-linked with every other sampled request in the batch (OTLP
        links), so one trace explains the whole co-batched step. Includes
        the retroactive serve/batch_form child (lead dequeue -> now)."""
        if self._tracer is None:
            return None
        sampled = [r.handle.trace for r in reqs if r.handle.trace is not None]
        if not sampled:
            return None
        sp = self._tracer.begin("serve/step", parent=sampled[0])
        sp.set_attribute("batch_index", batch_index)
        sp.set_attribute("graphs", len(reqs))
        for other in sampled[1:]:
            sp.add_link(other.trace_id, other.span_id)
            other.add_link(sp.trace_id, sp.span_id)
        if self._form_started is not None:
            form_dt = time.perf_counter() - self._form_started
            self._tracer.emit_completed(
                "serve/batch_form",
                time.time() - form_dt,
                form_dt,
                parent=sp,
            )
        return sp

    def _finish_step_span(self, span, error: Optional[str] = None) -> None:
        if span is None:
            return
        try:
            span.set_status(
                STATUS_ERROR if error is not None else STATUS_OK,
                error or "",
            )
            self._tracer.finish(span)
        except Exception:
            pass  # tracing must never fail the serve loop

    def _end_request_trace(
        self, handle: PredictionHandle, error: Optional[str] = None
    ) -> None:
        """Close a sampled request's root span with its outcome; the span's
        duration IS the request's admission-to-outcome latency."""
        root = handle.trace
        if root is None:
            return
        handle.trace = None
        try:
            root.set_status(
                STATUS_ERROR if error is not None else STATUS_OK,
                error or "",
            )
            self._tracer.finish(root)
        except Exception:
            pass

    def _flight_dump(self, reason: str) -> None:
        """Dump the black box (the server's own recorder when it was handed
        one, else whatever recorder is process-active)."""
        try:
            if self._flight is not None:
                self._flight.dump(reason)
            else:
                from ..obs import flightrec as _flightrec

                _flightrec.trigger(reason)
        except Exception:
            pass

    # -- bookkeeping ---------------------------------------------------------

    def _fail_request(self, handle: PredictionHandle, err: RequestError) -> None:
        """Fail one admitted request AND observe its latency with the error
        outcome — failed requests (deadline, wedge, batch error, drain) are
        precisely the slow tail, so excluding them would make the scraped
        p99 improve as the server violates its SLO harder."""
        handle._fail(err)
        self._m_req_lat.observe(
            handle.done_at - handle.submitted_at, outcome="error"
        )
        self._end_request_trace(
            handle, error=getattr(err, "code", type(err).__name__)
        )

    def _fail_queued(self, err: RequestError) -> None:
        if self._holdover is not None:
            self._fail_request(self._holdover.handle, err)
            self._holdover = None
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            self._fail_request(req.handle, err)

    def _cast_weights(self, state, entry: Optional[str] = None):
        """Apply ``Serving.weights_dtype`` to an incoming state — the one
        precision gate for both the startup restore and every hot-reload
        swap, so a reloaded checkpoint cannot silently revert the server
        to f32 weights. ``int8`` routes through the quantization plane
        (calibration + the accuracy gate; ``entry`` names the checkpoint
        for snapshot lookup and drift attribution) and may raise
        :class:`~hydragnn_tpu.serve.quantize.QuantizationDriftError`."""
        if self.cfg.weights_dtype == "float32":
            return state
        if self.cfg.weights_dtype == "int8":
            return self._quantize_state(state, entry)
        from ..train.state import cast_inference_weights

        return cast_inference_weights(state, self.cfg.weights_dtype)

    def _quant_batches(self) -> list:
        """The calibration/gate batches: the warmed ladder's template
        batches (the exact shapes serving runs), capped at
        ``Serving.quantization.calibration_batches``."""
        from ..data.pipeline import spec_template_batches

        templates = spec_template_batches(
            self._template_graphs, self.ladder, sort_edges=self.sort_edges
        )
        batches = [b for _, b in templates]
        if not batches:
            raise ValueError(
                "int8 quantization needs at least one template batch to "
                "calibrate and gate on — the ladder does not describe the "
                "template dataset"
            )
        cap = int(self.cfg.quantization.calibration_batches)
        return batches[: max(1, cap)]

    def _quantize_state(self, state, entry: Optional[str]):
        """The int8 install pipeline: pre-quantized snapshot fast path
        (no re-quantization, no calibration — the artifact banked its
        gate report where it was produced), else quantize + calibrate +
        gate, then publish the snapshot beside the checkpoint for the
        rest of the fleet."""
        from ..utils import faultinject
        from . import quantize as qz

        spec = self.cfg.quantization
        if isinstance(state, qz.QuantizedInferenceState):
            # already-quantized state handed in directly (embedding
            # callers/tests): same trust story as the snapshot path
            self._quant_report = {
                "source": "prequantized", "mode": state.mode,
            }
            return state
        if entry and self._checkpoint_dir:
            loaded = qz.load_snapshot(
                self.log_name, entry, spec.mode, self._checkpoint_dir
            )
            if loaded is not None:
                qstate, report = loaded
                self._quant_report = dict(
                    report, source="snapshot", mode=qstate.mode,
                )
                return qstate
        batches = self._quant_batches()
        qstate = qz.quantize_state(
            self.model, state, batches, spec.mode, spec.exclude
        )
        factor = faultinject.maybe_quant_drift(entry)
        if factor:
            qstate = qz.apply_scale_drift(qstate, factor)
        report = qz.gate_or_raise(
            self.model, state, qstate, batches, spec.max_error,
            run=self.log_name, entry=entry,
        )
        self._quant_report = dict(report, source="calibrated")
        if entry and self._checkpoint_dir:
            try:
                qz.save_snapshot(
                    qstate, self._quant_report, self.log_name, entry,
                    self._checkpoint_dir,
                )
            except OSError:
                pass  # the artifact is an accelerator, not a dependency
        return qstate

    def _install_state(self, state, label: Optional[str]) -> bool:
        """Stage a reloaded state; the serve loop swaps it in at the next
        batch boundary (in-flight batches keep the weights they started
        with). Refused (returns False) on a draining/stopping/closed
        server: a CheckpointWatcher poll racing close() must neither swap
        a new state into a server that is winding down nor leak the
        standby state past close()'s pending-state clear.

        The precision cast runs BEFORE the lock: int8 quantization
        (eager calibration + the accuracy gate) takes seconds, and the
        serve loop checks this lock at every batch boundary — staging
        must never stall traffic. A gate refusal
        (QuantizationDriftError) propagates to the caller; nothing was
        staged."""
        prepared = self._cast_weights(state, entry=label)
        with self._swap_lock:
            if self._closed or self._stop.is_set() or self._draining.is_set():
                return False
            self._pending_state = (prepared, label)
            return True

    def _bump(self, key: str, by: int = 1) -> None:
        with self._stats_lock:
            self._stats[key] = self._stats.get(key, 0) + by
        self._m_events.inc(by, event=key)

    def stats(self) -> Dict[str, Any]:
        """Structured serving counters + the current policy/observability
        snapshot (the chaos smoke and BENCH_SERVE parse this)."""
        from ..train.compile_plane import sentinel

        with self._stats_lock:
            out: Dict[str, Any] = dict(self._stats)
        out.update(
            ready=self.ready,
            draining=self.draining,
            closed=self._closed,
            queued=self._queue.qsize(),
            per_graph_latency_s=round(self._per_graph_s, 6),
            ladder_levels=len(self.ladder.specs),
            warmed_specializations=len(self.warmup_compiled),
            retrace_violations=max(
                len(sentinel().violations()) - self._violations_at_launch, 0
            ),
            current_checkpoint=self.current_checkpoint,
            http_port=self.http_port,
            weights_dtype=self.cfg.weights_dtype,
        )
        if self._quant_report is not None:
            out["quantization"] = dict(self._quant_report)
        return out
