"""Typed serving-plane errors — the request-lifecycle failure vocabulary.

Every way a request can fail maps to exactly one exception class with a
stable ``code`` string, so clients (and the chaos smoke) can branch on the
failure *kind* without parsing messages. Admission-time failures (invalid
request, queue full, shed, draining) are raised synchronously from
``GraphServer.submit``; in-flight failures (deadline expiry at dequeue, a
wedged device step, server shutdown) are delivered through the request's
``PredictionHandle`` — the handle's ``result()`` re-raises them, ``error()``
returns them as values.

The failure model + policy matrix lives in docs/SERVING.md.
"""

from __future__ import annotations

from typing import Optional


class ServeError(RuntimeError):
    """Base class of every serving-plane error."""

    code = "serve_error"


class RequestError(ServeError):
    """A per-request failure: exactly one request is affected, and its
    co-batched neighbors (if any) are not. Carries the request id when the
    request got far enough to have one."""

    code = "request_error"

    def __init__(self, message: str, request_id: Optional[int] = None):
        super().__init__(message)
        self.request_id = request_id


class InvalidRequestError(RequestError):
    """The request graph failed the admission validation gate
    (data/validate.validate_graph + the channel-signature check): NaN/Inf
    channels, degenerate edge indices, an empty graph, a graph exceeding the
    worst-case pad budget, or feature channels that do not match the model's
    warmed batch layout. ``reason`` is the validator's rejection-reason key."""

    code = "invalid_request"

    def __init__(self, message: str, request_id: Optional[int] = None,
                 reason: Optional[str] = None):
        super().__init__(message, request_id)
        self.reason = reason


class QueueFullError(RequestError):
    """The bounded admission queue is at ``Serving.max_queue_requests`` —
    backpressure, distinct from SLO-based shedding."""

    code = "queue_full"


class SheddedError(RequestError):
    """Load shed: the projected queue wait at admission time exceeded the
    configured p99 SLO (``Serving.slo_p99_s``), so accepting the request
    would blow its latency budget anyway. Carries the projection so clients
    can implement informed backoff."""

    code = "shed"

    def __init__(self, message: str, request_id: Optional[int] = None,
                 projected_wait_s: float = 0.0, slo_s: float = 0.0):
        super().__init__(message, request_id)
        self.projected_wait_s = projected_wait_s
        self.slo_s = slo_s


class DeadlineExceededError(RequestError):
    """The request's deadline expired while it was still queued — it is
    failed at dequeue time instead of wasting a batch slot on an answer the
    client has already given up on."""

    code = "deadline_exceeded"


class WedgedStepError(RequestError):
    """The device step serving this request's batch exceeded
    ``Serving.step_timeout_s``. The batch's requests are failed with this
    bounded error and the server recycles its step executor rather than
    hanging every later request behind a wedged program."""

    code = "wedged_step"


class ServerDrainingError(RequestError):
    """The server is draining (SIGTERM or an explicit ``drain()``): no new
    admissions; in-flight requests still complete."""

    code = "draining"


class ServerClosedError(RequestError):
    """The server is closed (or its warm-up failed); the request cannot be
    served by this process."""

    code = "closed"


class ReplicaUnavailableError(RequestError):
    """A fleet replica could not take the request at the transport level:
    connection refused/reset, the replica process died mid-request, or its
    /predict endpoint returned a non-protocol failure. Retryable on a
    different replica — the request never entered a device batch."""

    code = "replica_unavailable"


class BreakerOpenError(RequestError):
    """The target replica's circuit breaker is open (too many consecutive
    typed failures); the router refuses to send it traffic until the
    half-open probe recloses it. Raised to callers only when *every*
    candidate replica is broken or benched."""

    code = "breaker_open"


class NoReplicasError(RequestError):
    """The router exhausted its retry budget without finding a replica that
    could serve the request: all replicas dead, benched, breaker-open, or
    failing. Carries the per-attempt failure codes for forensics."""

    code = "no_replicas"

    def __init__(self, message: str, request_id: Optional[int] = None,
                 attempts: Optional[list] = None):
        super().__init__(message, request_id)
        self.attempts = list(attempts or [])


#: Stable error-code table (docs/SERVING.md "Fleet" cross-links here): the
#: wire codec (serve/wire.py) serializes failures as these codes and the
#: client side reconstructs the *typed* exception from the code, so a
#: router retrying against a remote replica branches on the same vocabulary
#: as an in-process caller. Codes are append-only: renaming or removing one
#: breaks deployed clients.
ERROR_CODES = {
    cls.code: cls
    for cls in (
        ServeError,
        RequestError,
        InvalidRequestError,
        QueueFullError,
        SheddedError,
        DeadlineExceededError,
        WedgedStepError,
        ServerDrainingError,
        ServerClosedError,
        ReplicaUnavailableError,
        BreakerOpenError,
        NoReplicasError,
    )
}

#: Codes safe to retry on a *different* replica: the request provably never
#: produced (partial) effects on the failing one — it was rejected at
#: admission or failed at the transport/lifecycle layer. ``shed`` and
#: ``queue_full`` are deliberately absent: those are backpressure signals,
#: and retrying them elsewhere amplifies an overload instead of routing
#: around a fault. ``invalid_request`` is absent because it fails the same
#: way everywhere.
RETRYABLE_CODES = frozenset(
    (
        ReplicaUnavailableError.code,
        ServerDrainingError.code,
        ServerClosedError.code,
        WedgedStepError.code,
        BreakerOpenError.code,
    )
)


def error_from_code(code: str, message: str) -> ServeError:
    """Reconstruct a typed serving error from its stable wire code.

    Unknown codes (a newer server than client) degrade to the base
    ``ServeError`` — the message still carries the detail."""
    cls = ERROR_CODES.get(code, ServeError)
    try:
        err = cls(message)
    except TypeError:  # pragma: no cover - all current ctors take (message)
        err = ServeError(message)
    return err
