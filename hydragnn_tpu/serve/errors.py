"""Typed serving-plane errors — the request-lifecycle failure vocabulary.

Every way a request can fail maps to exactly one exception class with a
stable ``code`` string, so clients (and the chaos smoke) can branch on the
failure *kind* without parsing messages. Admission-time failures (invalid
request, queue full, shed, draining) are raised synchronously from
``GraphServer.submit``; in-flight failures (deadline expiry at dequeue, a
wedged device step, server shutdown) are delivered through the request's
``PredictionHandle`` — the handle's ``result()`` re-raises them, ``error()``
returns them as values.

The failure model + policy matrix lives in docs/SERVING.md.
"""

from __future__ import annotations

from typing import Optional


class ServeError(RuntimeError):
    """Base class of every serving-plane error."""

    code = "serve_error"


class RequestError(ServeError):
    """A per-request failure: exactly one request is affected, and its
    co-batched neighbors (if any) are not. Carries the request id when the
    request got far enough to have one."""

    code = "request_error"

    def __init__(self, message: str, request_id: Optional[int] = None):
        super().__init__(message)
        self.request_id = request_id


class InvalidRequestError(RequestError):
    """The request graph failed the admission validation gate
    (data/validate.validate_graph + the channel-signature check): NaN/Inf
    channels, degenerate edge indices, an empty graph, a graph exceeding the
    worst-case pad budget, or feature channels that do not match the model's
    warmed batch layout. ``reason`` is the validator's rejection-reason key."""

    code = "invalid_request"

    def __init__(self, message: str, request_id: Optional[int] = None,
                 reason: Optional[str] = None):
        super().__init__(message, request_id)
        self.reason = reason


class QueueFullError(RequestError):
    """The bounded admission queue is at ``Serving.max_queue_requests`` —
    backpressure, distinct from SLO-based shedding."""

    code = "queue_full"


class SheddedError(RequestError):
    """Load shed: the projected queue wait at admission time exceeded the
    configured p99 SLO (``Serving.slo_p99_s``), so accepting the request
    would blow its latency budget anyway. Carries the projection so clients
    can implement informed backoff."""

    code = "shed"

    def __init__(self, message: str, request_id: Optional[int] = None,
                 projected_wait_s: float = 0.0, slo_s: float = 0.0):
        super().__init__(message, request_id)
        self.projected_wait_s = projected_wait_s
        self.slo_s = slo_s


class DeadlineExceededError(RequestError):
    """The request's deadline expired while it was still queued — it is
    failed at dequeue time instead of wasting a batch slot on an answer the
    client has already given up on."""

    code = "deadline_exceeded"


class WedgedStepError(RequestError):
    """The device step serving this request's batch exceeded
    ``Serving.step_timeout_s``. The batch's requests are failed with this
    bounded error and the server recycles its step executor rather than
    hanging every later request behind a wedged program."""

    code = "wedged_step"


class ServerDrainingError(RequestError):
    """The server is draining (SIGTERM or an explicit ``drain()``): no new
    admissions; in-flight requests still complete."""

    code = "draining"


class ServerClosedError(RequestError):
    """The server is closed (or its warm-up failed); the request cannot be
    served by this process."""

    code = "closed"
