"""Replica supervisor for the serving fleet (docs/SERVING.md "Fleet").

``ReplicaManager`` owns the *process-side* half of the fleet's fault model
(the router in serve/router.py owns the request-side half): N
``serve/replica.py`` subprocesses, each a full GraphServer with its own
device set (per-replica env overlays reach ``setup_distributed`` and the
rule-table sharding engine, so a replica can be pinned to its own slice),
supervised through three signals:

- **process liveness** — a dead worker (``proc.poll()``) is restarted with
  exponential backoff (``fleet_restart_backoff_s`` doubling up to the
  cap); a replica that dies ``fleet_flap_max_restarts`` times inside
  ``fleet_flap_window_s`` is BENCHED with a typed ``replica_benched``
  event and never restarted again — a flapping process is a config or
  hardware problem restarts cannot fix, and restart loops hide it;
- **readiness** — ``/readyz`` per replica (LB-safe by construction: a
  draining or warming replica reports 503);
- **heartbeats** — every replica pushes its registry (queue depth, shed
  counters, per-graph latency) to the manager's FleetCollector ~1/s; a
  replica whose heartbeat goes stale while its process is alive is WEDGED
  and gets SIGKILLed into the normal restart path.

The manager aggregates the fleet view two ways: live gauges
(``hydragnn_fleet_serve_*`` on its own /metrics endpoint, per-replica
queue depth mirrored from the collector) and ~1/s ``fleet_serve`` records
appended to the run dir's metrics.jsonl — the stream the run doctor's
``queue_saturation``/``shed_spiral`` rules consume so fleet-wide
saturation is ONE finding, not N.

Rolling reload (``rolling_reload``): replicas swap one at a time, each
gated on the fleet's ready count staying at or above
``ceil(fleet_ready_floor x N)``. After the FIRST replica swaps, it is
probed with ``reload_probe_requests`` real requests; an error rate >=
``reload_error_spike`` rolls that replica back to its prior checkpoint
(typed ``reload_rollback`` event) and aborts the rollout — a regressed
checkpoint reaches at most one replica.

Host-index convention: the manager is fleet host 0; replicas are hosts
1..N. That gives each process its own ``events-h<i>.jsonl``/
``metrics-h<i>.jsonl`` stream (the doctor merges them) and leaves the
unsuffixed host-0 streams to the manager's aggregate records.
"""

from __future__ import annotations

import json
import math
import os
import signal
import subprocess
import sys
import threading
import time
import warnings
from collections import deque
from typing import Any, Dict, List, Optional

from ..data.graph import Graph
from .cache import PredictionCache
from .config import ServeConfig
from .router import FleetRouter, HTTPReplicaClient

_SUPERVISE_TICK_S = 0.2
_METRICS_PERIOD_S = 1.0
_SPAWN_READY_TIMEOUT_S = 600.0
# floor on how soon after (re)start wedge detection may judge a replica.
# The real gate is per-incarnation: _spawn() forgets the collector's host
# entry, so staleness can only be measured against heartbeats the NEW
# process pushed (warm-up may legitimately push nothing for minutes).
_WEDGE_GRACE_S = 10.0
# how often the manager re-derives the prediction-cache context (installed
# checkpoint digest x serve config) from replica /stats
_CACHE_CTX_REFRESH_S = 5.0
# replicas heartbeat ~1/s, so a 5 s silence is a wedge, not jitter (the
# collector's adaptive threshold still stretches this for slow pushers)
_STALE_AFTER_S = 5.0


def _emit_event(kind: str, **attrs: Any) -> None:
    try:
        from ..obs.events import emit

        emit(kind, **attrs)
    except Exception:
        pass


class _Replica:
    """Supervisor-side record of one worker (not the transport — that is
    the router's HTTPReplicaClient, rebuilt on every restart)."""

    def __init__(self, index: int):
        self.index = index  # fleet host index, 1-based
        self.name = f"replica{index}"
        self.proc: Optional[subprocess.Popen] = None
        self.port: Optional[int] = None
        self.pid: Optional[int] = None
        self.log_fh = None
        self.benched = False
        self.deaths: "deque[float]" = deque()
        self.consecutive_restarts = 0
        self.restart_at: Optional[float] = None
        self.started_at = 0.0
        self.restarts = 0

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class ReplicaManager:
    """Spawn, health-gate, restart/bench, and roll-reload N replica
    workers; expose the fleet through ``router()``.

    ``config`` is a run config dict or a JSON config path (dicts are
    written to ``<run_dir>/serve_fleet_config.json`` for the children).
    ``per_replica_env`` maps a replica index (1-based) to extra env for
    that worker — the hook that pins each replica to its own device set
    (e.g. distinct ``XLA_FLAGS``/platform overrides consumed by
    ``setup_distributed`` and the sharding rule table).
    """

    def __init__(
        self,
        config,
        serve_cfg: Optional[ServeConfig] = None,
        path: str = "./logs",
        log_name: Optional[str] = None,
        per_replica_env: Optional[Dict[int, Dict[str, str]]] = None,
        replicas: Optional[int] = None,
    ):
        from ..config.config import get_log_name_config, load_config

        if isinstance(config, str):
            config_dict = load_config(config)
        else:
            config_dict = json.loads(json.dumps(dict(config)))
        self.cfg = serve_cfg or ServeConfig.from_config(config_dict)
        n = replicas if replicas is not None else self.cfg.fleet_replicas
        self.n = int(n)
        if self.n < 1:
            raise ValueError(
                f"fleet needs at least 1 replica (Serving.fleet_replicas or "
                f"replicas=), got {self.n}"
            )
        self.path = path
        self.log_name = log_name or get_log_name_config(config_dict)
        self.run_dir = os.path.join(path, self.log_name)
        os.makedirs(self.run_dir, exist_ok=True)
        # children always run a manager-authored config: every replica must
        # bind an ephemeral port (a pinned http_port would collide N ways),
        # and reloads are manager-orchestrated — hot_reload stays on so the
        # watcher exists for /reload {"poll": true}, but its own poll loop
        # is parked far in the future so it cannot race the rollout stagger
        serving = dict(config_dict.get("Serving") or {})
        serving["http_port"] = 0
        serving["hot_reload"] = True
        serving["reload_poll_s"] = 10.0 ** 9
        config_dict["Serving"] = serving
        self._config_path = os.path.join(
            self.run_dir, "serve_fleet_config.json"
        )
        tmp = f"{self._config_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(config_dict, f)
        os.replace(tmp, self._config_path)
        self.rendezvous_dir = os.path.join(self.run_dir, "fleet_rendezvous")
        os.makedirs(self.rendezvous_dir, exist_ok=True)
        self._per_replica_env = dict(per_replica_env or {})
        self._replicas = {i: _Replica(i) for i in range(1, self.n + 1)}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._router: Optional[FleetRouter] = None
        self._cache: Optional[PredictionCache] = None
        self._metrics_fh = None
        self._last_metrics = 0.0
        self._supervisor: Optional[threading.Thread] = None
        self._ctx_thread: Optional[threading.Thread] = None
        self._reloading = False
        self._closed = False

        # collector + push endpoint: the manager is fleet host 0
        from ..obs.events import attach_stream
        from ..obs.fleet import FleetCollector
        from ..obs.prometheus import TelemetryHTTPServer
        from ..obs.registry import registry

        attach_stream(self.run_dir)
        self.collector = FleetCollector(stale_after_s=_STALE_AFTER_S)
        self._http = TelemetryHTTPServer(
            reg=registry(),
            port=0,
            ready_fn=lambda: self.ready_count() > 0,
            health_fn=lambda: (not self._closed, "fleet manager"),
            post_routes={"/fleet/push": self._handle_push},
        )
        self.push_url = f"{self._http.url}/fleet/push"
        reg = registry()
        self._g_replicas = reg.gauge(
            "hydragnn_fleet_serve_replicas",
            "Serving replicas configured (fleet manager)",
        )
        self._g_ready = reg.gauge(
            "hydragnn_fleet_serve_ready",
            "Serving replicas currently ready (/readyz)",
        )
        self._g_benched = reg.gauge(
            "hydragnn_fleet_serve_benched",
            "Serving replicas benched by the flap breaker",
        )
        self._g_depth = reg.gauge(
            "hydragnn_fleet_serve_queue_depth",
            "Per-replica serve queue depth (heartbeat mirror)",
            labelnames=("replica",),
        )
        self._g_replicas.set(self.n)
        self._g_benched.set(0)

    # -- spawning ------------------------------------------------------------

    def _child_env(self, index: int) -> Dict[str, str]:
        env = dict(os.environ)
        env["HYDRAGNN_FLEET_HOST_INDEX"] = str(index)
        env["HYDRAGNN_FLEET_HOST_COUNT"] = str(self.n + 1)
        env["HYDRAGNN_SERVE_RENDEZVOUS"] = self.rendezvous_dir
        env["HYDRAGNN_SERVE_FLEET_PUSH"] = self.push_url
        env.update(self._per_replica_env.get(index, {}))
        return env

    def _spawn(self, rep: _Replica) -> None:
        # stale rendezvous from a previous life must not be mistaken for
        # the new worker — remove before spawn, then poll for the rewrite
        rv = os.path.join(self.rendezvous_dir, f"replica_{rep.index}.json")
        try:
            os.remove(rv)
        except OSError:
            pass
        # same for the heartbeat state: the dead incarnation's collector
        # entry goes stale within seconds, and the new process does not
        # push until its warm-up completes (up to _SPAWN_READY_TIMEOUT_S)
        # — judged against the old entry, every restart would be SIGKILLed
        # as "wedged" ~10s in and flap-benched after one real crash.
        # Forgetting the entry means staleness is only ever measured
        # against heartbeats this incarnation actually sent.
        self.collector.forget(rep.index)
        if rep.log_fh is None:
            rep.log_fh = open(
                os.path.join(self.run_dir, f"replica_{rep.index}.log"), "ab"
            )
        rep.proc = subprocess.Popen(
            [sys.executable, "-m", "hydragnn_tpu.serve.replica",
             self._config_path],
            env=self._child_env(rep.index),
            stdout=rep.log_fh,
            stderr=subprocess.STDOUT,
            cwd=os.getcwd(),
        )
        rep.started_at = time.monotonic()
        rep.restart_at = None
        rep.port = None
        rep.pid = rep.proc.pid

    def _read_rendezvous(self, rep: _Replica) -> bool:
        """Pick up the worker's published port once it appears; returns
        True when the client transport is (re)built."""
        rv = os.path.join(self.rendezvous_dir, f"replica_{rep.index}.json")
        try:
            with open(rv) as f:
                info = json.load(f)
        except (OSError, json.JSONDecodeError):
            return False
        if rep.proc is None or int(info.get("pid", -1)) != rep.proc.pid:
            return False  # a previous life's file
        rep.port = int(info["port"])
        self._rebuild_router_clients()
        return True

    def start(self) -> "ReplicaManager":
        for rep in self._replicas.values():
            self._spawn(rep)
        self._supervisor = threading.Thread(
            target=self._supervise, daemon=True, name="fleet-supervisor"
        )
        self._supervisor.start()
        return self

    def wait_ready(self, timeout: Optional[float] = None,
                   min_ready: Optional[int] = None) -> bool:
        """Block until ``min_ready`` (default: all non-benched) replicas
        report /readyz."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                target = min_ready if min_ready is not None else sum(
                    1 for r in self._replicas.values() if not r.benched
                )
            if target <= 0:
                return False
            if self.ready_count() >= target:
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.2)

    # -- fleet views ---------------------------------------------------------

    def clients(self) -> Dict[str, HTTPReplicaClient]:
        with self._lock:
            reps = [
                r for r in self._replicas.values()
                if not r.benched and r.port is not None
            ]
        return {
            r.name: HTTPReplicaClient(
                f"http://127.0.0.1:{r.port}", name=r.name
            )
            for r in reps
        }

    def router(self) -> FleetRouter:
        """The fleet's front door (one per manager; cached). Wires the
        collector's per-replica queue-depth gauges in as the balancing
        signal and the prediction cache when configured. The cache starts
        DISABLED (context None) and only serves once every reachable
        replica agrees on its installed checkpoint — the context (that
        checkpoint's digest x ``weights_dtype``) namespaces every key, so
        a rolling reload can never surface a prior checkpoint's cached
        prediction as a hit."""
        if self._router is None:
            cache = None
            pc = self.cfg.prediction_cache
            if pc:
                cache_dir = (
                    pc if isinstance(pc, str)
                    else os.path.join(self.run_dir, "pred_cache")
                )
                self._cache = cache = PredictionCache(cache_dir, context=None)
                self._refresh_cache_context()
                self._ctx_thread = threading.Thread(
                    target=self._cache_ctx_loop, daemon=True,
                    name="fleet-cache-ctx",
                )
                self._ctx_thread.start()
            self._router = FleetRouter(
                self.clients(), cfg=self.cfg, cache=cache,
                depth_fn=self._depth_of,
            )
        return self._router

    def _cache_context(self) -> Optional[str]:
        """The non-graph component of a prediction-cache key, or ``None``
        (cache disabled) while it cannot be pinned down: the sha256 of the
        checkpoint every reachable replica currently serves (its sidecar
        digest when present, the entry name otherwise) plus the
        prediction-affecting serve config. Replicas disagreeing — a
        rollout in flight, or a restart that restored a newer pointer —
        means NO shared entry is safe, so the cache sits out."""
        with self._lock:
            reps = [
                r for r in self._replicas.values()
                if not r.benched and r.port is not None
            ]
        entries = set()
        for rep in reps:
            try:
                entries.add(str(self._replica_stat(rep, "current_checkpoint")))
            except Exception:  # noqa: BLE001 — unreachable: just excluded
                continue
        if len(entries) != 1:
            return None
        entry = entries.pop()
        ident = entry
        try:
            # the checkpoint plane writes a sha256 sidecar next to every
            # entry (train/checkpoint.py) — key on content, not filename
            with open(os.path.join(self.run_dir, entry + ".sha256")) as f:
                ident = f"{entry}:{f.read().strip()}"
        except OSError:
            pass
        ctx = f"ckpt={ident};weights_dtype={self.cfg.weights_dtype}"
        if self.cfg.weights_dtype == "int8" and self.cfg.quantization:
            # int8 predictions depend on the quantization recipe too — a
            # weight_only fleet and a w8a8 fleet must never share entries
            ctx += f";quant={self.cfg.quantization.mode}"
        return ctx

    def _refresh_cache_context(self) -> None:
        if self._cache is None or self._reloading:
            return
        ctx = self._cache_context()
        if not self._reloading:
            self._cache.set_context(ctx)

    def _cache_ctx_loop(self) -> None:
        # off the supervisor thread: deriving the context blocks on
        # replica /stats HTTP calls, and restarts/wedge checks must not
        # wait behind a dead replica's connect timeout
        while not self._stop.wait(_CACHE_CTX_REFRESH_S):
            try:
                self._refresh_cache_context()
            except Exception:  # noqa: BLE001 — cache is an accelerator
                pass

    def _depth_of(self, name: str) -> Optional[float]:
        try:
            index = int(name.replace("replica", ""))
        except ValueError:
            return None
        series = self.collector.host_series(index)
        return series.get("hydragnn_serve_queue_depth")

    def _rebuild_router_clients(self) -> None:
        if self._router is not None:
            self._router.set_clients(self.clients())

    def ready_count(self) -> int:
        count = 0
        for name, client in self.clients().items():
            try:
                if client.ready():
                    count += 1
            except Exception:
                pass
        return count

    def replica_state(self) -> Dict[int, Dict[str, Any]]:
        with self._lock:
            return {
                r.index: {
                    "alive": r.alive(),
                    "benched": r.benched,
                    "port": r.port,
                    "pid": r.pid,
                    "restarts": r.restarts,
                }
                for r in self._replicas.values()
            }

    # -- supervision ---------------------------------------------------------

    def _handle_push(self, body: bytes):
        payload = json.loads(body.decode("utf-8"))
        return 200, self.collector.absorb(payload)

    def _backoff_s(self, rep: _Replica) -> float:
        base = float(self.cfg.fleet_restart_backoff_s) or 0.05
        return min(
            base * (2 ** rep.consecutive_restarts),
            float(self.cfg.fleet_restart_backoff_max_s),
        )

    def _on_death(self, rep: _Replica, now: float) -> None:
        code = rep.proc.poll() if rep.proc is not None else None
        _emit_event(
            "replica_exit", replica=rep.index, returncode=code,
            restarts=rep.restarts,
        )
        rep.deaths.append(now)
        window = float(self.cfg.fleet_flap_window_s)
        while rep.deaths and now - rep.deaths[0] > window:
            rep.deaths.popleft()
        if len(rep.deaths) >= int(self.cfg.fleet_flap_max_restarts):
            rep.benched = True
            rep.proc = None
            rep.port = None
            _emit_event(
                "replica_benched", replica=rep.index,
                deaths_in_window=len(rep.deaths), window_s=window,
                remediation="inspect replica_<i>.log; the flap breaker "
                "never restarts a benched replica — fix and restart the "
                "fleet",
            )
            self._g_benched.set(
                sum(1 for r in self._replicas.values() if r.benched)
            )
            self._rebuild_router_clients()
            return
        delay = self._backoff_s(rep)
        rep.restart_at = now + delay
        rep.proc = None
        rep.port = None
        self._rebuild_router_clients()

    def _supervise(self) -> None:
        while not self._stop.is_set():
            now = time.monotonic()
            with self._lock:
                reps = list(self._replicas.values())
            for rep in reps:
                if rep.benched:
                    continue
                if rep.proc is not None and rep.proc.poll() is not None:
                    self._on_death(rep, now)
                elif rep.proc is None and rep.restart_at is not None:
                    if now >= rep.restart_at:
                        rep.consecutive_restarts += 1
                        rep.restarts += 1
                        _emit_event(
                            "replica_restart", replica=rep.index,
                            restarts=rep.restarts,
                            backoff_s=round(self._backoff_s(rep), 3),
                        )
                        self._spawn(rep)
                elif rep.proc is not None:
                    if rep.port is None:
                        self._read_rendezvous(rep)
                    # a stable stretch clears the backoff escalation
                    if rep.consecutive_restarts and (
                        now - rep.started_at
                        > float(self.cfg.fleet_flap_window_s)
                    ):
                        rep.consecutive_restarts = 0
                    self._check_wedged(rep, now)
            self._publish(now)
            self._stop.wait(_SUPERVISE_TICK_S)

    def _check_wedged(self, rep: _Replica, now: float) -> None:
        """A live process whose heartbeat went stale is wedged (device
        hang, GIL-holding bug): SIGKILL it into the normal death path —
        the restart gets a fresh runner, and repeated wedges hit the flap
        breaker like any other crash loop. Staleness is judged strictly
        per incarnation: ``_spawn`` forgets the collector's host entry,
        so until THIS process heartbeats there is no entry to go stale
        and a slow warm-up can never be mistaken for a wedge."""
        if now - rep.started_at < _WEDGE_GRACE_S:
            return
        # the collector only sweeps staleness inside absorb(); with every
        # replica wedged nobody pushes, so the supervisor drives the sweep
        self.collector.sweep()
        hosts = self.collector.hosts()
        st = hosts.get(rep.index)
        if st is not None and st.get("stale") and rep.alive():
            _emit_event(
                "replica_exit", replica=rep.index, returncode=None,
                cause="wedged (stale heartbeat); killed by supervisor",
            )
            try:
                rep.proc.kill()
            except OSError:
                pass

    # -- aggregation ---------------------------------------------------------

    def _publish(self, now: float) -> None:
        ready = 0
        depth_sum = 0.0
        depth_max = 0.0
        shed_total = 0.0
        queue_full_total = 0.0
        completed_total = 0.0
        per_replica: Dict[str, Dict[str, float]] = {}
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            series = self.collector.host_series(rep.index)
            depth = float(series.get("hydragnn_serve_queue_depth", 0.0))
            shed = float(series.get(
                'hydragnn_serve_events_total{event="shed"}', 0.0
            ))
            qfull = float(series.get(
                'hydragnn_serve_events_total{event="queue_full"}', 0.0
            ))
            completed = float(series.get(
                'hydragnn_serve_events_total{event="completed"}', 0.0
            ))
            rdy = float(series.get("hydragnn_serve_ready", 0.0))
            if not rep.benched and rep.alive() and rdy >= 1.0:
                ready += 1
            depth_sum += depth
            depth_max = max(depth_max, depth)
            shed_total += shed
            queue_full_total += qfull
            completed_total += completed
            self._g_depth.set(depth, replica=str(rep.index))
            per_replica[str(rep.index)] = {
                "queue_depth": depth, "shed": shed,
                "queue_full": qfull, "ready": rdy,
            }
        self._g_ready.set(ready)
        if now - self._last_metrics >= _METRICS_PERIOD_S:
            self._last_metrics = now
            self._write_metrics_record(
                ready, depth_sum, depth_max, shed_total, queue_full_total,
                completed_total, per_replica,
            )

    def _write_metrics_record(self, ready, depth_sum, depth_max, shed,
                              qfull, completed, per_replica) -> None:
        from ..obs.schema import METRICS_SCHEMA_VERSION

        live = max(
            sum(1 for r in self._replicas.values() if not r.benched), 1
        )
        rec = {
            "v": METRICS_SCHEMA_VERSION,
            "ts": round(time.time(), 3),
            "kind": "fleet_serve",
            "host": 0,
            "replicas": self.n,
            "ready": int(ready),
            "benched": sum(
                1 for r in self._replicas.values() if r.benched
            ),
            "queue_depth_mean": round(depth_sum / live, 3),
            "queue_depth_max": depth_max,
            "shed_total": shed,
            "queue_full_total": qfull,
            "completed_total": completed,
            "per_replica": per_replica,
        }
        cache = getattr(self, "_cache", None)
        if cache is not None:
            # prediction-cache efficacy ride-along (optional schema
            # fields): the doctor's cache_ineffective rule reads these
            cs = cache.stats()
            rec["cache_enabled"] = cache.context is not None
            rec["cache_hits"] = cs["hits"]
            rec["cache_misses"] = cs["misses"]
            rec["cache_stores"] = cs["stores"]
            rec["cache_entries"] = cs["entries"]
            rec["cache_bytes"] = cs["bytes"]
        try:
            if self._metrics_fh is None:
                self._metrics_fh = open(
                    os.path.join(self.run_dir, "metrics.jsonl"), "a"
                )
            self._metrics_fh.write(json.dumps(rec) + "\n")
            self._metrics_fh.flush()
        except (OSError, ValueError):
            self._metrics_fh = None

    # -- rolling reload ------------------------------------------------------

    def _replica_stat(self, rep: _Replica, field: str) -> Any:
        client = HTTPReplicaClient(f"http://127.0.0.1:{rep.port}")
        import urllib.request

        req = urllib.request.Request(
            client.base_url + "/stats", data=b"{}",
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=5.0) as resp:
            return json.loads(resp.read().decode("utf-8")).get(field)

    def _post_reload(self, rep: _Replica, body: Dict[str, Any]
                     ) -> Dict[str, Any]:
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{rep.port}/reload",
            data=json.dumps(body).encode("utf-8"),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=30.0) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def rolling_reload(self, probe_graphs: List[Graph],
                       timeout_s: float = 120.0) -> Dict[str, Any]:
        """Stagger checkpoint reloads across the fleet, one replica at a
        time, capacity-floor gated, with first-replica regression probing
        and automatic rollback. Always returns a status dict
        (``{"status": "done"|"rolled_back"|"aborted", ...}``) — a replica
        that becomes unreachable mid-roll is skipped with a warning, never
        surfaced as a raw transport error, and a rollback whose POST fails
        reports ``rollback_ok: False`` + ``rollback_error``. While the
        rollout is in flight the prediction cache is disabled (mixed-fleet
        window); it re-enables under the new checkpoint's context once the
        fleet agrees again."""
        if not probe_graphs:
            raise ValueError(
                "rolling_reload needs probe graphs to verify the first "
                "reloaded replica"
            )
        floor = math.ceil(float(self.cfg.fleet_ready_floor) * self.n)
        deadline = time.monotonic() + float(timeout_s)
        installed = 0
        first_probed = False
        min_ready_seen = self.n
        with self._lock:
            reps = [
                r for r in self._replicas.values()
                if not r.benched and r.port is not None
            ]
        # mid-rollout the fleet serves two checkpoints at once: no shared
        # cache entry is safe, so the cache sits out until the rollout
        # settles and the context is re-derived from the fleet's agreement
        self._reloading = True
        if self._cache is not None:
            self._cache.set_context(None)
        try:
            return self._rolling_reload(
                reps, probe_graphs, floor, deadline, installed,
                first_probed, min_ready_seen,
            )
        finally:
            self._reloading = False
            self._refresh_cache_context()

    def _rolling_reload(self, reps, probe_graphs, floor, deadline,
                        installed, first_probed, min_ready_seen
                        ) -> Dict[str, Any]:
        for rep in reps:
            # capacity gate: proceed only while the REST of the fleet
            # keeps aggregate ready capacity at/above the floor (the
            # reloading replica itself stays ready — swaps are staged
            # between batches — but a concurrently crashed replica must
            # pause the rollout)
            while True:
                ready = self.ready_count()
                min_ready_seen = min(min_ready_seen, ready)
                if ready >= floor:
                    break
                if time.monotonic() >= deadline:
                    return {
                        "status": "aborted",
                        "reason": f"ready count {ready} below floor "
                                  f"{floor}; rollout timed out",
                        "installed": installed,
                        "min_ready_seen": min_ready_seen,
                    }
                time.sleep(0.2)
            try:
                prior = self._replica_stat(rep, "current_checkpoint")
                out = self._post_reload(rep, {"poll": True})
            except Exception as e:  # noqa: BLE001 — replica died mid-roll
                # an unreachable replica is the supervisor's problem (it
                # restarts on the LATEST pointer anyway); the rollout
                # skips it instead of leaking a transport error to the
                # caller in place of the documented status dict
                warnings.warn(
                    f"rolling reload: replica {rep.index} unreachable "
                    f"({type(e).__name__}: {e}); skipping",
                    RuntimeWarning, stacklevel=2,
                )
                continue
            if out.get("status") != "installed":
                # unchanged pointer or rejected candidate: nothing swapped
                continue
            # the serve loop takes the staged swap at the next batch
            # boundary (~one tick); wait for the visible flip
            entry = self._wait_checkpoint_change(rep, prior, deadline)
            installed += 1
            if not first_probed:
                first_probed = True
                verdict = self._probe_first(rep, probe_graphs)
                if verdict["error_rate"] >= float(
                    self.cfg.reload_error_spike
                ):
                    rollback_error = None
                    try:
                        self._post_reload(rep, {"entry": prior})
                    except Exception as e:  # noqa: BLE001 — died mid-roll
                        # the regressed checkpoint may still be installed
                        # on this replica: report it, never swallow it —
                        # the caller (and the doctor) must know the
                        # rollback did not land
                        rollback_error = f"{type(e).__name__}: {e}"
                        warnings.warn(
                            f"rolling reload: rollback POST to replica "
                            f"{rep.index} failed ({rollback_error}); the "
                            f"regressed checkpoint may still be serving "
                            f"there until the supervisor restarts it",
                            RuntimeWarning, stacklevel=2,
                        )
                    _emit_event(
                        "reload_rollback", replica=rep.index,
                        rolled_back_to=prior, regressed=entry,
                        error_rate=verdict["error_rate"],
                        probes=verdict["probes"],
                        rollback_error=rollback_error,
                    )
                    return {
                        "status": "rolled_back",
                        "replica": rep.index,
                        "prior": prior,
                        "regressed": entry,
                        "error_rate": verdict["error_rate"],
                        "installed": installed,
                        "min_ready_seen": min_ready_seen,
                        "rollback_ok": rollback_error is None,
                        "rollback_error": rollback_error,
                    }
        return {
            "status": "done",
            "installed": installed,
            "min_ready_seen": min_ready_seen,
            "floor": floor,
        }

    def _wait_checkpoint_change(self, rep: _Replica, prior: Any,
                                deadline: float) -> Any:
        while time.monotonic() < deadline:
            try:
                cur = self._replica_stat(rep, "current_checkpoint")
            except Exception as e:  # noqa: BLE001 — replica died mid-swap
                # do not stall the whole rollout polling a dead replica:
                # the supervisor restarts it on the latest pointer anyway
                warnings.warn(
                    f"rolling reload: replica {rep.index} unreachable "
                    f"while awaiting its swap ({type(e).__name__}: {e})",
                    RuntimeWarning, stacklevel=2,
                )
                return prior
            if cur != prior:
                return cur
            time.sleep(0.1)
        return prior

    def _probe_first(self, rep: _Replica,
                     probe_graphs: List[Graph]) -> Dict[str, Any]:
        client = HTTPReplicaClient(
            f"http://127.0.0.1:{rep.port}", name=rep.name
        )
        probes = max(int(self.cfg.reload_probe_requests), 1)
        errors = 0
        for k in range(probes):
            g = probe_graphs[k % len(probe_graphs)]
            try:
                client.predict(g, timeout_s=30.0)
            except Exception:  # noqa: BLE001 — any failure counts against
                # the canary (typed serve errors AND transport loss: a
                # replica that died under probing is a regression signal)
                errors += 1
        return {"probes": probes, "errors": errors,
                "error_rate": errors / probes}

    def poll_reload_once(self) -> Dict[int, str]:
        """Deterministic per-replica single poll (tests/smokes): no
        capacity gating, no probing — just ask each replica to take one
        watcher poll and report the outcome."""
        out: Dict[int, str] = {}
        with self._lock:
            reps = [
                r for r in self._replicas.values()
                if not r.benched and r.port is not None
            ]
        for rep in reps:
            try:
                out[rep.index] = self._post_reload(
                    rep, {"poll": True}
                ).get("status", "unreachable")
            except Exception:
                out[rep.index] = "unreachable"
        return out

    # -- teardown ------------------------------------------------------------

    def close(self, timeout_s: float = 30.0) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._supervisor is not None:
            self._supervisor.join(timeout=5.0)
        if self._ctx_thread is not None:
            self._ctx_thread.join(timeout=5.0)
        if self._router is not None:
            self._router.close()
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            if rep.alive():
                try:
                    rep.proc.send_signal(signal.SIGTERM)  # graceful drain
                except OSError:
                    pass
        deadline = time.monotonic() + timeout_s
        for rep in reps:
            if rep.proc is None:
                continue
            remaining = max(deadline - time.monotonic(), 0.1)
            try:
                rep.proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                rep.proc.kill()
                try:
                    rep.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass
            if rep.log_fh is not None:
                try:
                    rep.log_fh.close()
                except OSError:
                    pass
                rep.log_fh = None
        self._http.close()
        if self._metrics_fh is not None:
            try:
                self._metrics_fh.close()
            except OSError:
                pass
            self._metrics_fh = None
        from ..obs.events import detach_stream

        detach_stream()

    def __enter__(self) -> "ReplicaManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
