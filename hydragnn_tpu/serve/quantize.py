"""Int8 quantized inference plane (docs/SERVING.md "Quantization").

Post-training int8 for the serving stack, built from three planes this repo
already trusts:

- **weights**: per-channel symmetric int8 with fp32 scales (ops/quant.py) —
  every dense kernel becomes an int8 array + a ``[1, out]`` scale. In
  ``weight_only`` mode the dequant runs inside the jitted predict where XLA
  fuses it into the matmul, so the kernels stay int8 in HBM (4x smaller than
  f32) and the model code is untouched;
- **activations** (``w8a8``): static activation scales calibrated from the
  numerics observatory's max-abs statistics (obs/numerics.py probes) over
  ``Serving.quantization.calibration_batches`` warmed template batches.
  Serving intercepts ``nn.Dense.__call__`` (flax ``intercept_methods``) for
  the calibrated layers and runs int8 x int8 ``lax.dot_general`` with an
  int32 accumulator; layers the calibration never observed (branch-banked
  vmapped heads, fused-kernel paths that read params directly) fall back to
  weight-only dequant — quantization must never change which code path a
  layer executes;
- **the gate**: every state-install point (server warm-up, CheckpointWatcher
  swap, rolling-reload canary) compares quantized vs full-precision
  predictions on the warmed ladder's template batches and REFUSES the swap
  when the relative max error crosses ``Serving.quantization.max_error`` —
  a typed :class:`QuantizationDriftError` plus a ``quant_drift`` event the
  doctor maps to a finding. A drifted candidate keeps the previous weights
  serving, exactly like a corrupt checkpoint.

Exclusions: only ``kernel`` leaves quantize, so LayerNorm/BatchNorm scales,
biases, and running statistics stay f32 structurally; each head's output
layer (the highest-indexed Dense under a ``heads*`` scope) is excluded by
default, and ``Serving.quantization.exclude`` adds substring patterns.

Snapshot artifact: ``<entry>.quant-<mode>.npz`` beside the checkpoint, with
the checkpoint plane's atomic-write + sha256-sidecar discipline — N fleet
replicas load int8 directly (no per-process re-quantization or calibration)
and a torn/corrupt snapshot falls back to quantizing from the checkpoint.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from flax import struct
from flax.traverse_util import flatten_dict, unflatten_dict

from ..ops.quant import (
    INT8_MAX,
    dequantize,
    int8_matmul,
    quantize_activations,
    quantize_per_channel,
)
from .errors import ServeError

#: bump on any change to the snapshot layout — a loader seeing a different
#: version treats the artifact as absent and re-quantizes from the checkpoint
SNAPSHOT_FORMAT_VERSION = 1

MODES = ("weight_only", "w8a8")


class QuantizationDriftError(ServeError):
    """The accuracy gate refused a quantized state: its predictions drifted
    past ``Serving.quantization.max_error`` relative to full precision on
    the template batches. Raised at install time — the current weights keep
    serving, the candidate never reaches traffic."""

    code = "quant_drift"

    def __init__(self, message: str, max_error: float = 0.0,
                 limit: float = 0.0,
                 per_head: Optional[Dict[str, float]] = None):
        super().__init__(message)
        self.max_error = float(max_error)
        self.limit = float(limit)
        self.per_head = dict(per_head or {})


@struct.dataclass
class QuantizedInferenceState:
    """An ``InferenceState`` whose dense kernels are int8.

    ``params`` mirrors the original tree with int8 arrays at quantized
    kernel leaves; ``scales`` maps each quantized leaf's ``/``-joined path
    to its fp32 per-channel scale; ``quant`` is the side ``"quant"``
    variables collection for w8a8 (per intercepted Dense scope:
    ``kernel_scale`` + calibrated ``act_scale``) — empty in weight-only
    mode. ``w8a8`` (static) names the intercepted scopes: their kernels
    stay int8 through ``variables()`` and the serve-side interceptor
    consumes them; every other quantized kernel is dequantized at trace
    time so model code that reads params directly always sees floats."""

    params: Any
    scales: Dict[str, Any]
    quant: Dict[str, Any]
    batch_stats: Any
    step: Any = 0
    mode: str = struct.field(pytree_node=False, default="weight_only")
    w8a8: Tuple[str, ...] = struct.field(pytree_node=False, default=())

    def variables(self) -> Dict[str, Any]:
        flat = flatten_dict(self.params)
        keep = set(self.w8a8)
        out = {}
        for key, leaf in flat.items():
            path = "/".join(key)
            if path in self.scales and "/".join(key[:-1]) not in keep:
                out[key] = dequantize(leaf, self.scales[path])
            else:
                out[key] = leaf
        v: Dict[str, Any] = {"params": unflatten_dict(out)}
        if self.batch_stats:
            v["batch_stats"] = self.batch_stats
        if self.quant:
            v["quant"] = self.quant
        return v

    def weight_nbytes(self) -> int:
        """Resident weight bytes (params + scales) — the BENCH_SERVE HBM
        cell; int8 kernels count 1 byte/element."""
        total = 0
        for leaf in jax.tree_util.tree_leaves(
            {"p": self.params, "s": self.scales, "q": self.quant}
        ):
            total += int(getattr(leaf, "nbytes", 0) or 0)
        return total


# ---------------------------------------------------------------------------
# kernel selection
# ---------------------------------------------------------------------------


def _head_output_paths(flat_params) -> set:
    """The highest-indexed ``Dense_k`` kernel under each top-level
    ``heads*`` scope — the per-head output layer, excluded by default
    (its error lands directly on the prediction with no later layer to
    absorb it)."""
    best: Dict[str, Tuple[int, Tuple[str, ...]]] = {}
    for key in flat_params:
        if len(key) < 3 or key[-1] != "kernel":
            continue
        if not str(key[0]).startswith("heads"):
            continue
        parent = str(key[-2])
        if not parent.startswith("Dense_"):
            continue
        try:
            idx = int(parent.split("_")[-1])
        except ValueError:
            continue
        scope = "/".join(key[:-2])
        if scope not in best or idx > best[scope][0]:
            best[scope] = (idx, key)
    return {key for _, key in best.values()}


def quantizable_paths(params, exclude: Sequence[str] = ()
                      ) -> List[Tuple[str, ...]]:
    """Param-tree paths of the kernels the quantizer touches: floating
    ``kernel`` leaves of rank >= 2, minus the per-head output layers and
    any path matching an ``exclude`` substring. Norm scales/biases and
    running statistics are structurally excluded (they are not named
    ``kernel``)."""
    flat = flatten_dict(params)
    head_out = _head_output_paths(flat)
    out = []
    for key, leaf in sorted(flat.items()):
        if key[-1] != "kernel":
            continue
        if getattr(leaf, "ndim", 0) < 2:
            continue
        if not jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
            continue
        if key in head_out:
            continue
        path = "/".join(key)
        if any(pat and pat in path for pat in exclude):
            continue
        out.append(key)
    return out


# ---------------------------------------------------------------------------
# quantization + calibration
# ---------------------------------------------------------------------------


def quantize_weights(state, exclude: Sequence[str] = ()
                     ) -> QuantizedInferenceState:
    """Weight-only quantization of an ``InferenceState``/``TrainState``:
    pure tree transform, no model or data needed (``cast_inference_weights
    (state, "int8")`` lands here). Calibration/gating are the serving
    layer's job (:func:`quantize_state`)."""
    flat = dict(flatten_dict(state.params))
    scales: Dict[str, Any] = {}
    for key in quantizable_paths(state.params, exclude):
        q, scale = quantize_per_channel(flat[key])
        flat[key] = q
        scales["/".join(key)] = scale
    return QuantizedInferenceState(
        params=unflatten_dict(flat),
        scales=scales,
        quant={},
        batch_stats=getattr(state, "batch_stats", {}) or {},
        step=getattr(state, "step", 0),
        mode="weight_only",
        w8a8=(),
    )


def _calibration_interceptor(record):
    """Probe every eagerly-visible ``nn.Dense`` input through the numerics
    observatory (obs/numerics probe/collecting). Inputs that are tracers
    (the lifted-vmap branch heads batch-trace even in eager mode) are
    skipped — those layers cannot be intercepted at serve time either, so
    skipping them here is exactly what makes the observed-scope set the
    authoritative w8a8 eligibility set."""
    from ..obs.numerics import probe

    observed = set()

    def interceptor(next_fun, args, kwargs, context):
        mod = context.module
        if (
            context.method_name == "__call__"
            and isinstance(mod, nn.Dense)
            and args
            and not isinstance(args[0], jax.core.Tracer)
        ):
            scope = "/".join(str(p) for p in mod.path)
            observed.add(scope)
            probe(f"quant_calib/{scope}", args[0])
        return next_fun(*args, **kwargs)

    return interceptor, observed


def calibrate_activations(model, state, batches: Sequence[Any]
                          ) -> Tuple[Dict[str, float], set]:
    """Eager forward passes over the template batches with a probing
    interceptor: per-Dense-scope max-abs input statistics -> static
    activation scales (``max_abs / 127``). Returns (scales by scope,
    observed scope set). Eager on purpose — jitting would both hide the
    per-layer values behind tracers and burn a compile for a one-shot
    pass."""
    from ..obs.numerics import STAT_FIELDS, ProbeRecord, collecting

    maxabs_col = STAT_FIELDS.index("max_abs")
    variables = state.variables()
    record = ProbeRecord()
    interceptor, observed = _calibration_interceptor(record)
    with collecting(record):
        with nn.intercept_methods(interceptor):
            for batch in batches:
                model.apply(variables, batch, train=False)
    names, stats = record.stack()
    stats = np.asarray(stats)
    peaks: Dict[str, float] = {}
    for name, row in zip(names, stats):
        base = name.split("#")[0]
        if not base.startswith("quant_calib/"):
            continue
        scope = base[len("quant_calib/"):]
        peaks[scope] = max(peaks.get(scope, 0.0), float(row[maxabs_col]))
    scales = {
        scope: (peak / INT8_MAX if peak > 0.0 else 1.0)
        for scope, peak in peaks.items()
    }
    return scales, observed


def quantize_state(model, state, batches: Sequence[Any], mode: str,
                   exclude: Sequence[str] = ()) -> QuantizedInferenceState:
    """The full serving-side pipeline: weight-only quantize, then (w8a8)
    calibrate activation scales and promote every calibrated 2D-kernel
    Dense to int8 x int8 execution via the side ``quant`` collection."""
    if mode not in MODES:
        raise ValueError(f"quantization mode {mode!r} must be one of {MODES}")
    q = quantize_weights(state, exclude)
    if mode != "w8a8":
        return q
    act_scales, observed = calibrate_activations(model, state, batches)
    flat = flatten_dict(q.params)
    quant_flat: Dict[Tuple[str, ...], Any] = {}
    w8a8: List[str] = []
    for path, scale in q.scales.items():
        key = tuple(path.split("/"))
        scope = "/".join(key[:-1])
        if scope not in act_scales:
            continue
        if flat[key].ndim != 2:
            # branch-banked (vmapped) kernels keep weight-only dequant:
            # the lifted transform won't carry the side collection
            continue
        quant_flat[key[:-1] + ("kernel_scale",)] = scale
        quant_flat[key[:-1] + ("act_scale",)] = jnp.asarray(
            act_scales[scope], jnp.float32
        )
        w8a8.append(scope)
    return q.replace(
        quant=unflatten_dict(quant_flat) if quant_flat else {},
        mode="w8a8",
        w8a8=tuple(sorted(w8a8)),
    )


# ---------------------------------------------------------------------------
# w8a8 execution
# ---------------------------------------------------------------------------


def w8a8_interceptor(next_fun, args, kwargs, context):
    """Serve-time ``nn.Dense.__call__`` interceptor: layers carrying a
    ``quant`` collection entry run int8 x int8 with the calibrated static
    activation scale; every other call falls through untouched."""
    mod = context.module
    if (
        context.method_name != "__call__"
        or not isinstance(mod, nn.Dense)
        or not args
        or not mod.has_variable("quant", "kernel_scale")
    ):
        return next_fun(*args, **kwargs)
    kernel = mod.get_variable("params", "kernel")
    if kernel.dtype != jnp.int8:
        return next_fun(*args, **kwargs)
    x = args[0]
    w_scale = mod.get_variable("quant", "kernel_scale")  # [1, out]
    a_scale = mod.get_variable("quant", "act_scale")  # scalar
    x_q = quantize_activations(x, a_scale)
    y = int8_matmul(x_q, kernel).astype(jnp.float32) * (a_scale * w_scale)
    if mod.use_bias:
        y = y + mod.get_variable("params", "bias")
    return y


def apply_quantized(model, state, batch):
    """``model.apply`` for any inference state, quantized or not —
    w8a8 states run under the interceptor. This is the one call the gate,
    the warm-up and the jitted predict share, so gated accuracy is
    measured on exactly the program that serves."""
    variables = state.variables() if hasattr(state, "variables") else state
    if getattr(state, "mode", None) == "w8a8" and getattr(state, "w8a8", ()):
        with nn.intercept_methods(w8a8_interceptor):
            return model.apply(variables, batch, train=False)
    return model.apply(variables, batch, train=False)


# ---------------------------------------------------------------------------
# accuracy gate
# ---------------------------------------------------------------------------


def _as_output_dict(out) -> Dict[str, Any]:
    if isinstance(out, dict):
        return out
    if isinstance(out, (list, tuple)):
        return {f"head_{i}": o for i, o in enumerate(out)}
    return {"output": out}


def accuracy_report(model, fp_state, q_state,
                    batches: Sequence[Any]) -> Dict[str, Any]:
    """Relative max error of quantized vs full-precision predictions over
    the template batches, per head and overall — the gate's evidence,
    also banked into BENCH_SERVE int8 cells and ``stats()``."""
    per_head: Dict[str, float] = {}
    for batch in batches:
        fp_out = _as_output_dict(apply_quantized(model, fp_state, batch))
        q_out = _as_output_dict(apply_quantized(model, q_state, batch))
        for name, ref in fp_out.items():
            ref = np.asarray(ref, np.float32)
            got = np.asarray(q_out[name], np.float32)
            denom = float(np.max(np.abs(ref))) + 1e-8
            err = float(np.max(np.abs(got - ref))) / denom
            per_head[str(name)] = max(per_head.get(str(name), 0.0), err)
    max_error = max(per_head.values()) if per_head else 0.0
    return {
        "max_error": round(max_error, 8),
        "per_head": {k: round(v, 8) for k, v in per_head.items()},
        "batches": len(batches),
    }


def gate_or_raise(model, fp_state, q_state, batches: Sequence[Any],
                  max_error: float, *, run: str = "",
                  entry: Optional[str] = None) -> Dict[str, Any]:
    """Run the accuracy gate; past ``max_error`` emit the typed
    ``quant_drift`` event and raise :class:`QuantizationDriftError` —
    install points let it propagate, so a drifted candidate can never
    reach traffic through warm-up, a watcher swap, or a rolling reload."""
    report = dict(accuracy_report(model, fp_state, q_state, batches))
    report["limit"] = float(max_error)
    report["mode"] = getattr(q_state, "mode", "weight_only")
    if report["max_error"] > float(max_error):
        try:
            from ..obs.events import EV_QUANT_DRIFT, emit

            emit(
                EV_QUANT_DRIFT,
                run=run,
                candidate=entry or "",
                mode=report["mode"],
                max_error=report["max_error"],
                limit=float(max_error),
                per_head=report["per_head"],
            )
        except Exception:  # noqa: BLE001 — observability must not mask
            pass
        raise QuantizationDriftError(
            f"quantized predictions drifted {report['max_error']:.4g} "
            f"(relative max error) past Serving.quantization.max_error="
            f"{float(max_error):.4g} on {report['batches']} template "
            f"batch(es); refusing the swap (per head: {report['per_head']})",
            max_error=report["max_error"],
            limit=float(max_error),
            per_head=report["per_head"],
        )
    return report


def apply_scale_drift(q_state: QuantizedInferenceState,
                      factor: float) -> QuantizedInferenceState:
    """Distort every weight scale by ``factor`` — the deterministic
    drifted-candidate drill (utils/faultinject.py maybe_quant_drift): the
    dequantized weights all shift by ``factor``, so the gate must refuse.
    Test/chaos surface only; never called on the healthy path."""
    scales = {k: v * float(factor) for k, v in q_state.scales.items()}
    quant = jax.tree_util.tree_map(lambda x: x, q_state.quant)
    if quant:
        flat = {
            k: (v * float(factor) if k[-1] == "kernel_scale" else v)
            for k, v in flatten_dict(quant).items()
        }
        quant = unflatten_dict(flat)
    return q_state.replace(scales=scales, quant=quant)


# ---------------------------------------------------------------------------
# snapshot artifact
# ---------------------------------------------------------------------------

_SECTION_PREFIXES = ("params", "scales", "quant", "batch_stats")


def snapshot_name(entry: str, mode: str) -> str:
    return f"{entry}.quant-{mode}.npz"


def snapshot_path(log_name: str, entry: str, mode: str,
                  path: str = "./logs") -> str:
    """The pre-quantized artifact's location: beside the checkpoint entry
    it was quantized from, keyed by entry AND mode so a w8a8 fleet never
    loads a weight-only artifact (or vice versa)."""
    return os.path.join(path, log_name, snapshot_name(entry, mode))


def save_snapshot(q_state: QuantizedInferenceState,
                  report: Dict[str, Any], log_name: str, entry: str,
                  path: str = "./logs") -> str:
    """Write the int8 artifact with the checkpoint plane's durability
    discipline: single atomic replace + a sha256 sidecar, so a replica
    racing the writer sees either nothing or a verified-complete file.
    Concurrent writers (N replicas quantizing the same entry) are safe:
    quantization is deterministic, so last-replace-wins is idempotent."""
    from ..train.checkpoint import _sha256_path, atomic_write

    payload: Dict[str, Any] = {}
    tree = {
        "params": q_state.params,
        "scales": q_state.scales,
        "quant": q_state.quant,
        "batch_stats": q_state.batch_stats or {},
    }
    for section in _SECTION_PREFIXES:
        sub = tree[section]
        if not sub:
            continue
        for key, leaf in flatten_dict(sub).items():
            payload[f"{section}:{'/'.join(key)}"] = np.asarray(leaf)
    payload["__manifest__"] = np.asarray(json.dumps({
        "format_version": SNAPSHOT_FORMAT_VERSION,
        "mode": q_state.mode,
        "w8a8": list(q_state.w8a8),
        "step": int(np.asarray(q_state.step)),
        "entry": entry,
        "report": report,
    }))
    buf = io.BytesIO()
    np.savez(buf, **payload)
    blob = buf.getvalue()
    full = snapshot_path(log_name, entry, q_state.mode, path)
    os.makedirs(os.path.dirname(full), exist_ok=True)
    atomic_write(full, blob)
    atomic_write(
        _sha256_path(full), hashlib.sha256(blob).hexdigest().encode()
    )
    return full


def load_snapshot(log_name: str, entry: str, mode: str, path: str = "./logs"
                  ) -> Optional[Tuple[QuantizedInferenceState,
                                      Dict[str, Any]]]:
    """Load a pre-quantized artifact, digest-verified. Returns ``(state,
    banked gate report)`` or ``None`` on ANY trouble (absent, torn,
    sidecar mismatch, wrong mode/format) — the caller falls back to
    quantizing from the checkpoint; a broken snapshot costs startup time,
    never correctness."""
    full = snapshot_path(log_name, entry, mode, path)
    if not os.path.exists(full):
        return None
    tried: List[str] = []
    try:
        from ..train.checkpoint import _verified_read

        blob = _verified_read(full, tried)
        if blob is None:
            return None
        with np.load(io.BytesIO(blob), allow_pickle=False) as z:
            manifest = json.loads(str(z["__manifest__"]))
            if int(manifest.get("format_version", -1)) != \
                    SNAPSHOT_FORMAT_VERSION:
                return None
            if manifest.get("mode") != mode or manifest.get("entry") != entry:
                return None
            sections: Dict[str, Dict[Tuple[str, ...], Any]] = {
                s: {} for s in _SECTION_PREFIXES
            }
            for name in z.files:
                if name == "__manifest__":
                    continue
                section, _, flat_key = name.partition(":")
                if section not in sections:
                    return None
                sections[section][tuple(flat_key.split("/"))] = jnp.asarray(
                    z[name]
                )
    except (OSError, ValueError, KeyError, json.JSONDecodeError):
        return None
    state = QuantizedInferenceState(
        params=unflatten_dict(sections["params"]),
        scales={
            "/".join(k): v for k, v in sections["scales"].items()
        },
        quant=(
            unflatten_dict(sections["quant"]) if sections["quant"] else {}
        ),
        batch_stats=(
            unflatten_dict(sections["batch_stats"])
            if sections["batch_stats"] else {}
        ),
        step=int(manifest.get("step", 0)),
        mode=str(manifest.get("mode", "weight_only")),
        w8a8=tuple(manifest.get("w8a8", ())),
    )
    return state, dict(manifest.get("report", {}))
