"""Serving configuration: the ``Serving`` section of the JSON config.

Same surface philosophy as the rest of the config system (config/config.py):
a plain JSON section with complete defaults, validated eagerly so a typo'd
policy fails at load time, not mid-traffic. ``update_config`` validates the
section when present; ``config.lint`` knows every key. The full key table
lives in docs/CONFIG.md ("Serving") and the semantics in docs/SERVING.md.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Any, Dict, Tuple

#: int8 quantization modes (docs/SERVING.md "Quantization"): weight_only
#: keeps activations in the model's own precision and fuses the dequant
#: into the matmul; w8a8 also quantizes activations against static scales
#: calibrated from the numerics observatory's max-abs statistics.
QUANT_MODES = ("weight_only", "w8a8")


@dataclasses.dataclass(frozen=True)
class QuantizationSpec:
    """Resolved ``Serving.quantization`` sub-config (only meaningful with
    ``weights_dtype: int8``): the mode, how many warmed template batches
    feed activation calibration, the accuracy gate's relative max-error
    bound, and extra per-layer exclude substrings (head output layers and
    norm parameters are excluded structurally either way)."""

    mode: str = "weight_only"
    calibration_batches: int = 2
    max_error: float = 0.05
    exclude: Tuple[str, ...] = ()

    _KNOWN = ("mode", "calibration_batches", "max_error", "exclude")

    def __post_init__(self):
        if self.mode not in QUANT_MODES:
            raise ValueError(
                f"Serving.quantization.mode {self.mode!r} must be one of "
                f"{QUANT_MODES}"
            )
        if int(self.calibration_batches) < 1:
            raise ValueError(
                f"Serving.quantization.calibration_batches must be >= 1, "
                f"got {self.calibration_batches!r}"
            )
        if not (float(self.max_error) > 0.0):
            raise ValueError(
                f"Serving.quantization.max_error must be > 0 (relative max "
                f"error the accuracy gate tolerates), got "
                f"{self.max_error!r}"
            )
        if not isinstance(self.exclude, tuple) or not all(
            isinstance(p, str) and p for p in self.exclude
        ):
            raise ValueError(
                f"Serving.quantization.exclude must be a list of non-empty "
                f"layer-path substrings, got {self.exclude!r}"
            )

    @staticmethod
    def resolve(section: Any) -> "QuantizationSpec":
        """Normalize the config's ``Serving.quantization`` value (None =
        all defaults, a dict validates each key, a spec passes through).
        Unknown keys FAIL here (unlike top-level Serving keys, which only
        warn): a typo'd ``max_eror`` silently serving ungated int8 is
        exactly the accident the gate exists to prevent."""
        if section is None:
            return QuantizationSpec()
        if isinstance(section, QuantizationSpec):
            return section
        if not isinstance(section, dict):
            raise ValueError(
                f"Serving.quantization must be an object of "
                f"{list(QuantizationSpec._KNOWN)}, got {section!r}"
            )
        unknown = sorted(set(section) - set(QuantizationSpec._KNOWN))
        if unknown:
            raise ValueError(
                f"Serving.quantization keys {unknown} are unknown (known: "
                f"{list(QuantizationSpec._KNOWN)})"
            )
        kw = dict(section)
        if "calibration_batches" in kw:
            kw["calibration_batches"] = int(kw["calibration_batches"])
        if "max_error" in kw:
            kw["max_error"] = float(kw["max_error"])
        if "exclude" in kw:
            ex = kw["exclude"]
            kw["exclude"] = tuple(
                str(p) for p in (ex if isinstance(ex, (list, tuple)) else [ex])
            )
        return QuantizationSpec(**kw)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Resolved serving policy knobs (all times in seconds).

    - admission: ``max_queue_requests`` bounds the queue (0/negative =
      unbounded), ``default_deadline_s`` is the per-request deadline when the
      client does not set one (0 disables deadlines);
    - batching: ``micro_batch_graphs`` caps graphs per device batch,
      ``batch_window_s`` is how long the batcher waits to fill a batch after
      the first request arrives;
    - overload: ``slo_p99_s`` > 0 sheds admissions whose projected queue
      wait exceeds it; ``expected_latency_per_graph_s`` seeds the wait
      estimator before the first measured batch (0 = no shedding until the
      warm-up measurement lands);
    - fault tolerance: ``step_timeout_s`` bounds one device step (0 disables
      the watchdog), ``retrace_policy`` is the sentinel mode once the warmed
      ladder is armed (``error`` is the serving default: an unknown
      specialization in steady state is a correctness bug, not a warning);
    - lifecycle: ``hot_reload`` watches the run dir's ``latest`` pointer and
      swaps verified checkpoints in between batches (``reload_poll_s``
      cadence); ``drain_timeout_s`` bounds how long ``close()`` waits for
      in-flight work;
    - observability: ``http_port`` mounts the Prometheus ``/metrics`` +
      ``/healthz``/``/readyz`` endpoint (obs/prometheus.py) on the server —
      0 (the default) binds an ephemeral loopback port (read it back from
      ``GraphServer.http_port``), a positive value pins the port, a
      negative value disables the endpoint (embedded/test servers);
      ``http_host`` is the bind interface (default loopback — metrics are
      not public by default; set ``"0.0.0.0"`` for off-host scrapers and
      load-balancer readiness probes).
    """

    max_queue_requests: int = 256
    micro_batch_graphs: int = 32
    batch_window_s: float = 0.005
    default_deadline_s: float = 30.0
    slo_p99_s: float = 0.0
    expected_latency_per_graph_s: float = 0.0
    step_timeout_s: float = 60.0
    retrace_policy: str = "error"
    hot_reload: bool = False
    reload_poll_s: float = 2.0
    drain_timeout_s: float = 30.0
    http_port: int = 0
    http_host: str = "127.0.0.1"
    # reduced-precision serving (docs/SERVING.md): "bfloat16" casts the
    # restored InferenceState's floating params once at install (halved
    # weight HBM + bf16 MXU streams); batch stats stay f32. "int8" routes
    # through the quantization plane (serve/quantize.py): per-channel
    # symmetric int8 kernels + fp32 scales, gated at every install by the
    # quantization.max_error accuracy check. Applied to hot reloads too.
    # Default keeps the checkpoint's own precision.
    weights_dtype: str = "float32"
    # int8 sub-config (QuantizationSpec; only consulted when weights_dtype
    # is "int8"): mode weight_only|w8a8, calibration batch count, accuracy
    # gate bound, per-layer exclude substrings. None = spec defaults.
    quantization: Any = None
    # drain ordering (docs/SERVING.md "Drain"): on SIGTERM /readyz flips
    # not-ready immediately, but admissions stay open for drain_grace_s so
    # a load balancer observes the flip and stops routing *before* clients
    # start eating ServerDrainingError. 0 (the default) rejects immediately
    # — the pre-fleet behavior.
    drain_grace_s: float = 0.0
    # fleet supervision (serve/fleet.py; docs/SERVING.md "Fleet"):
    # fleet_replicas > 0 is the ReplicaManager's worker count; crashed
    # replicas restart with exponential backoff (base doubling up to the
    # cap) and a replica dying fleet_flap_max_restarts times inside
    # fleet_flap_window_s is benched (typed replica_benched event), not
    # restarted forever. fleet_ready_floor is the fraction of replicas that
    # must stay ready during a rolling reload.
    fleet_replicas: int = 0
    fleet_restart_backoff_s: float = 0.5
    fleet_restart_backoff_max_s: float = 10.0
    fleet_flap_window_s: float = 60.0
    fleet_flap_max_restarts: int = 5
    fleet_ready_floor: float = 0.5
    # front router (serve/router.py): per-request end-to-end timeout,
    # bounded retries of retryable failures on a different replica
    # (router_backoff_s base, doubling), tail hedging past
    # max(router_hedge_min_s, router_hedge_factor x EMA latency) for
    # interactive traffic, and a per-replica circuit breaker that opens
    # after breaker_failures consecutive typed failures and half-open
    # probes after breaker_cooldown_s.
    router_timeout_s: float = 30.0
    router_retries: int = 2
    router_backoff_s: float = 0.05
    router_hedge_factor: float = 3.0
    router_hedge_min_s: float = 0.05
    breaker_failures: int = 3
    breaker_cooldown_s: float = 5.0
    # content-addressed prediction cache (serve/cache.py): False disables,
    # True uses <run dir>/pred_cache, a string is an explicit directory.
    # Hits are bit-identical to misses by construction (lossless .npz +
    # digest-verified load).
    prediction_cache: Any = False
    # rolling-reload regression guard: after the first replica swaps, the
    # manager probes it with reload_probe_requests requests; an error rate
    # >= reload_error_spike rolls that replica back to the prior checkpoint
    # (typed reload_rollback event) and aborts the rollout.
    reload_error_spike: float = 0.5
    reload_probe_requests: int = 8

    _KNOWN = (
        "max_queue_requests",
        "micro_batch_graphs",
        "batch_window_s",
        "default_deadline_s",
        "slo_p99_s",
        "expected_latency_per_graph_s",
        "step_timeout_s",
        "retrace_policy",
        "hot_reload",
        "reload_poll_s",
        "drain_timeout_s",
        "http_port",
        "http_host",
        "weights_dtype",
        "quantization",
        "drain_grace_s",
        "fleet_replicas",
        "fleet_restart_backoff_s",
        "fleet_restart_backoff_max_s",
        "fleet_flap_window_s",
        "fleet_flap_max_restarts",
        "fleet_ready_floor",
        "router_timeout_s",
        "router_retries",
        "router_backoff_s",
        "router_hedge_factor",
        "router_hedge_min_s",
        "breaker_failures",
        "breaker_cooldown_s",
        "prediction_cache",
        "reload_error_spike",
        "reload_probe_requests",
    )

    WEIGHTS_DTYPES = ("float32", "bfloat16", "int8")

    def __post_init__(self):
        from ..train.compile_plane import RETRACE_POLICIES

        if self.micro_batch_graphs < 1:
            raise ValueError(
                f"Serving.micro_batch_graphs must be >= 1, got "
                f"{self.micro_batch_graphs}"
            )
        if self.retrace_policy not in RETRACE_POLICIES:
            raise ValueError(
                f"Serving.retrace_policy {self.retrace_policy!r} must be one "
                f"of {RETRACE_POLICIES}"
            )
        for key in ("batch_window_s", "default_deadline_s", "slo_p99_s",
                    "expected_latency_per_graph_s", "step_timeout_s",
                    "reload_poll_s", "drain_timeout_s", "drain_grace_s",
                    "fleet_restart_backoff_s", "fleet_restart_backoff_max_s",
                    "fleet_flap_window_s", "router_timeout_s",
                    "router_backoff_s", "router_hedge_min_s",
                    "breaker_cooldown_s"):
            if float(getattr(self, key)) < 0:
                raise ValueError(
                    f"Serving.{key} must be >= 0 (seconds; 0 disables), got "
                    f"{getattr(self, key)!r}"
                )
        for key in ("fleet_replicas", "fleet_flap_max_restarts",
                    "router_retries", "breaker_failures",
                    "reload_probe_requests"):
            if int(getattr(self, key)) < 0:
                raise ValueError(
                    f"Serving.{key} must be >= 0, got {getattr(self, key)!r}"
                )
        if not (0.0 <= float(self.fleet_ready_floor) <= 1.0):
            raise ValueError(
                f"Serving.fleet_ready_floor must be a fraction in [0, 1], "
                f"got {self.fleet_ready_floor!r}"
            )
        if not (0.0 <= float(self.reload_error_spike) <= 1.0):
            raise ValueError(
                f"Serving.reload_error_spike must be a fraction in [0, 1], "
                f"got {self.reload_error_spike!r}"
            )
        if float(self.router_hedge_factor) < 1.0:
            raise ValueError(
                f"Serving.router_hedge_factor must be >= 1 (multiple of the "
                f"EMA latency), got {self.router_hedge_factor!r}"
            )
        if not isinstance(self.prediction_cache, (bool, str)) or (
            isinstance(self.prediction_cache, str)
            and not self.prediction_cache
        ):
            raise ValueError(
                f"Serving.prediction_cache must be False, True, or a "
                f"non-empty cache directory path, got "
                f"{self.prediction_cache!r}"
            )
        if int(self.http_port) > 65535:
            raise ValueError(
                f"Serving.http_port must be <= 65535 (0 = ephemeral, "
                f"negative disables), got {self.http_port!r}"
            )
        if not isinstance(self.http_host, str) or not self.http_host:
            raise ValueError(
                f"Serving.http_host must be a non-empty bind address, got "
                f"{self.http_host!r}"
            )
        if self.weights_dtype not in ServeConfig.WEIGHTS_DTYPES:
            raise ValueError(
                f"Serving.weights_dtype {self.weights_dtype!r} must be one "
                f"of {ServeConfig.WEIGHTS_DTYPES}"
            )
        if self.quantization is not None or self.weights_dtype == "int8":
            # normalize once here so every consumer (server, fleet, bench)
            # reads a validated QuantizationSpec, never a raw dict
            object.__setattr__(
                self, "quantization",
                QuantizationSpec.resolve(self.quantization),
            )

    @staticmethod
    def from_config(config: Dict[str, Any]) -> "ServeConfig":
        """Resolve from a full run config's ``Serving`` section (missing
        section = all defaults; ``micro_batch_graphs`` falls back to
        ``Training.batch_size`` so the served shapes are the trained pad
        buckets). Unknown keys warn — matching config completion's
        ignore-unknown behavior — rather than failing the server."""
        section = dict(config.get("Serving", {}) or {})
        unknown = sorted(set(section) - set(ServeConfig._KNOWN))
        if unknown:
            warnings.warn(
                f"Serving config keys {unknown} are not consumed (known keys: "
                f"{list(ServeConfig._KNOWN)}); check docs/CONFIG.md for the "
                "serving surface",
                stacklevel=2,
            )
            for k in unknown:
                section.pop(k)
        if "micro_batch_graphs" not in section:
            bs = (
                config.get("NeuralNetwork", {})
                .get("Training", {})
                .get("batch_size")
            )
            if bs:
                section["micro_batch_graphs"] = int(bs)
        return ServeConfig(**section)
