"""Content-addressed prediction cache (docs/SERVING.md "Fleet").

Same storage discipline as the LapPE eigenvector cache (data/lappe.py), the
repo's proven on-disk memoization scheme, applied to inference results:

- the key is a sha256 over the graph's *input content* — every inference
  input array's name, dtype, shape, and raw bytes, plus ``dataset_id`` —
  mixed with the cache ``context``: everything BESIDES the graph that
  determines a prediction (the installed checkpoint's digest and the
  prediction-affecting serve config, e.g. ``weights_dtype``). Two
  bit-identical graphs share an entry, any single-bit input difference
  misses, and a hot-reloaded checkpoint changes the context so entries
  computed by the old weights can never be served as hits for the new
  ones. A context of ``None`` disables the cache entirely (``key_for``
  returns None) — the fleet manager parks it there while replicas
  disagree mid-rollout;
- entries are ``.npz`` files sharded by the first two hex digits
  (``cache_dir/ab/abcdef....npz``) to keep directory fan-out flat;
- stores are atomic: write to ``<path>.tmp.<pid>`` then ``os.replace`` —
  concurrent replicas racing on the same key both win, torn writes are
  impossible, and a reader never sees a partial file;
- loads are digest-verified: the entry records a sha256 over the stored
  prediction arrays, recomputed at load; any mismatch (corrupt file,
  truncation that survived the zip CRC) is treated as a miss and the
  prediction recomputed — a broken cache can cost latency, never
  correctness.

Bit-identity of hits is by construction, not best-effort: ``.npz`` is a
lossless container, so the arrays handed back on a hit are byte-for-byte
the arrays that were stored on the miss. tests/test_serve_fleet.py asserts
it with ``np.array_equal`` on exact dtypes.
"""

from __future__ import annotations

import hashlib
import io
import os
import threading
import zipfile
from typing import Dict, Optional

import numpy as np

from ..data.graph import Graph

# Graph fields that are inference *inputs* — targets deliberately excluded
# (they do not influence the prediction, and keying on them would split
# entries for identical inputs). Mirrors Graph.float_channels plus the
# integer topology/identity fields.
_KEY_FIELDS = (
    "x", "pos", "senders", "receivers", "edge_attr", "edge_shifts",
    "pe", "rel_pe", "z", "graph_y", "cell",
)


def graph_key(graph: Graph) -> str:
    """sha256 hex key over the graph's inference-input content."""
    h = hashlib.sha256()
    for name in _KEY_FIELDS:
        v = getattr(graph, name, None)
        if v is None:
            continue
        a = np.ascontiguousarray(np.asarray(v))
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(repr(a.shape).encode())
        h.update(a.tobytes())
    h.update(f"dataset_id={int(graph.dataset_id)}".encode())
    return h.hexdigest()


def _result_digest(result: Dict[str, np.ndarray]) -> str:
    """sha256 over the prediction arrays, order-independent."""
    h = hashlib.sha256()
    for name in sorted(result):
        a = np.ascontiguousarray(np.asarray(result[name]))
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(repr(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


class PredictionCache:
    """Sharded on-disk prediction cache; safe for concurrent processes.

    ``get`` returns the cached head->array dict on a verified hit and
    ``None`` on any miss (absent, unreadable, digest mismatch); ``put``
    stores atomically and never raises on I/O failure — the cache is an
    accelerator, not a dependency. ``stats()`` exposes hit/miss/store/
    corrupt counters plus the on-disk entry/byte census for the fleet
    gauges and bench cells; the same numbers land in the process registry
    as ``hydragnn_serve_cache_{hits,misses,entries,bytes}``, so /metrics
    scrapes see cache efficacy live.

    ``context`` namespaces every key with the non-graph prediction inputs
    (checkpoint digest + serve config). The default ``""`` keys on graph
    content alone (standalone/bench use where the weights never change);
    ``None`` disables the cache until ``set_context`` supplies an
    identity — the fleet manager's mid-rollout state, where replicas
    serve different checkpoints and no shared entry is safe.
    """

    def __init__(self, cache_dir: str, context: Optional[str] = ""):
        self.cache_dir = cache_dir
        os.makedirs(cache_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._context = context
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.corrupt = 0
        # entry census, seeded from disk so a restarted fleet reports the
        # cache it inherited, then maintained incrementally by put/removal
        self.entries, self.bytes = self._scan()
        # telemetry plane: counters absorb the lookup tallies (set_total —
        # idempotent, so N replicas sharing one process never double
        # count), gauges carry the census; /metrics and the fleet's
        # metrics.jsonl window both render from these
        from ..obs.registry import registry as _obs_registry

        _reg = _obs_registry()
        self._m_hits = _reg.counter(
            "hydragnn_serve_cache_hits",
            "Prediction-cache lookups answered from a verified entry",
        )
        self._m_misses = _reg.counter(
            "hydragnn_serve_cache_misses",
            "Prediction-cache lookups that fell through to the model "
            "(absent, unreadable, or digest-mismatched entry)",
        )
        self._m_entries = _reg.gauge(
            "hydragnn_serve_cache_entries",
            "Prediction-cache entries currently on disk",
        )
        self._m_bytes = _reg.gauge(
            "hydragnn_serve_cache_bytes",
            "Prediction-cache bytes currently on disk",
        )
        self._publish()

    def _scan(self) -> "tuple[int, int]":
        """Count the .npz entries (and their bytes) already in the shard
        dirs — in-flight ``.tmp.<pid>`` files excluded."""
        entries = 0
        size = 0
        try:
            with os.scandir(self.cache_dir) as shards:
                shard_names = [d.name for d in shards if d.is_dir()]
            for shard in shard_names:
                with os.scandir(os.path.join(self.cache_dir, shard)) as it:
                    for f in it:
                        if f.name.endswith(".npz") and f.is_file():
                            entries += 1
                            size += f.stat().st_size
        except OSError:
            pass
        return entries, size

    def _publish(self) -> None:
        """Mirror the counters/census into the process registry. Callers
        hold ``self._lock``-free state reads only — counter absorption is
        max-merge and gauges are last-writer, so racing publishes are
        harmless."""
        self._m_hits.set_total(self.hits)
        self._m_misses.set_total(self.misses)
        self._m_entries.set(max(0, self.entries))
        self._m_bytes.set(max(0, self.bytes))

    @property
    def context(self) -> Optional[str]:
        with self._lock:
            return self._context

    def set_context(self, context: Optional[str]) -> None:
        """Swap the non-graph key component (checkpoint digest + config).
        Existing entries stay on disk under their old context — they are
        simply unreachable until the same context returns (a rollback
        re-hits them), so no eviction pass is needed for correctness."""
        with self._lock:
            self._context = context

    def key_for(self, graph: Graph, base: Optional[str] = None
                ) -> Optional[str]:
        """The effective cache key for ``graph`` under the current
        context, or ``None`` while the cache is disabled (context None).
        ``base`` short-circuits the graph hash when the caller already
        computed ``graph_key(graph)``."""
        with self._lock:
            ctx = self._context
        if ctx is None:
            return None
        base = base if base is not None else graph_key(graph)
        if not ctx:
            return base
        return hashlib.sha256(f"{base}|ctx={ctx}".encode()).hexdigest()

    def _path(self, key: str) -> str:
        return os.path.join(self.cache_dir, key[:2], key + ".npz")

    def get(self, graph: Graph, key: Optional[str] = None
            ) -> Optional[Dict[str, np.ndarray]]:
        key = key if key is not None else self.key_for(graph)
        if key is None:
            return None
        path = self._path(key)
        try:
            with np.load(path, allow_pickle=False) as z:
                stored_digest = str(z["__digest__"])
                result = {
                    n: np.asarray(z[n]) for n in z.files if n != "__digest__"
                }
        except (OSError, ValueError, KeyError, zipfile.BadZipFile):
            with self._lock:
                self.misses += 1
            # an unreadable file that EXISTS will never become readable:
            # evict it (and its census share) instead of re-missing on it
            # forever; an absent file (the cold-miss case) raises on
            # getsize and stays a plain miss
            try:
                size = os.path.getsize(path)
                os.remove(path)
                with self._lock:
                    self.corrupt += 1
                    self.entries -= 1
                    self.bytes -= size
            except OSError:
                pass
            self._publish()
            return None
        if _result_digest(result) != stored_digest:
            # Corrupt entry that survived the zip CRC: drop it and recompute.
            with self._lock:
                self.corrupt += 1
                self.misses += 1
            try:
                size = os.path.getsize(path)
                os.remove(path)
                with self._lock:
                    self.entries -= 1
                    self.bytes -= size
            except OSError:
                pass
            self._publish()
            return None
        with self._lock:
            self.hits += 1
        self._publish()
        return result

    def put(self, graph: Graph, result: Dict[str, np.ndarray],
            key: Optional[str] = None) -> Optional[str]:
        key = key if key is not None else self.key_for(graph)
        if key is None:
            return None
        path = self._path(key)
        arrays = {n: np.asarray(v) for n, v in result.items()}
        payload = dict(arrays)
        payload["__digest__"] = np.asarray(_result_digest(arrays))
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            buf = io.BytesIO()
            np.savez(buf, **payload)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(buf.getvalue())
            # census delta: a replace of an existing entry (two replicas
            # racing the same key) swaps bytes, not entries
            try:
                prior = os.path.getsize(path)
                fresh = False
            except OSError:
                prior = 0
                fresh = True
            os.replace(tmp, path)
        except OSError:
            return None
        with self._lock:
            self.stores += 1
            self.entries += 1 if fresh else 0
            self.bytes += len(buf.getvalue()) - prior
        self._publish()
        return key

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "stores": self.stores,
                "corrupt": self.corrupt,
                "entries": max(0, self.entries),
                "bytes": max(0, self.bytes),
            }
