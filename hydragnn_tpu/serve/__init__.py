"""Serving plane: fault-tolerant micro-batched graph inference
(docs/SERVING.md). ``api.run_server`` is the config-driven entry point;
``GraphServer`` the direct constructor."""

from .config import ServeConfig
from .errors import (
    DeadlineExceededError,
    InvalidRequestError,
    QueueFullError,
    RequestError,
    ServeError,
    ServerClosedError,
    ServerDrainingError,
    SheddedError,
    WedgedStepError,
)
from .reload import CheckpointWatcher
from .server import GraphServer, PredictionHandle

__all__ = [
    "CheckpointWatcher",
    "DeadlineExceededError",
    "GraphServer",
    "InvalidRequestError",
    "PredictionHandle",
    "QueueFullError",
    "RequestError",
    "ServeConfig",
    "ServeError",
    "ServerClosedError",
    "ServerDrainingError",
    "SheddedError",
    "WedgedStepError",
]
