"""Serving plane: fault-tolerant micro-batched graph inference
(docs/SERVING.md). ``api.run_server`` is the config-driven entry point;
``GraphServer`` the direct constructor. ``api.run_server_fleet`` starts the
multi-process fleet (``ReplicaManager`` supervising replica workers behind
a ``FleetRouter`` with retries, hedging, circuit breakers, and an optional
content-addressed ``PredictionCache``)."""

from .cache import PredictionCache, graph_key
from .config import QuantizationSpec, ServeConfig
from .errors import (
    ERROR_CODES,
    RETRYABLE_CODES,
    BreakerOpenError,
    DeadlineExceededError,
    InvalidRequestError,
    NoReplicasError,
    QueueFullError,
    ReplicaUnavailableError,
    RequestError,
    ServeError,
    ServerClosedError,
    ServerDrainingError,
    SheddedError,
    WedgedStepError,
    error_from_code,
)
from .reload import CheckpointWatcher
from .router import (
    CircuitBreaker,
    FleetRouter,
    HTTPReplicaClient,
    LocalReplicaClient,
    ReplicaClient,
)
from .server import GraphServer, PredictionHandle


def __getattr__(name):
    # ReplicaManager imports api machinery transitively, and the
    # quantization plane pulls flax/jax numerics; keep both lazy so
    # `from hydragnn_tpu.serve import ServeConfig` stays light.
    if name == "ReplicaManager":
        from .fleet import ReplicaManager

        return ReplicaManager
    if name in ("QuantizationDriftError", "QuantizedInferenceState",
                "quantize_state", "quantize_weights"):
        from . import quantize

        return getattr(quantize, name)
    raise AttributeError(name)


__all__ = [
    "BreakerOpenError",
    "CheckpointWatcher",
    "CircuitBreaker",
    "DeadlineExceededError",
    "ERROR_CODES",
    "FleetRouter",
    "GraphServer",
    "HTTPReplicaClient",
    "InvalidRequestError",
    "LocalReplicaClient",
    "NoReplicasError",
    "PredictionCache",
    "PredictionHandle",
    "QuantizationDriftError",
    "QuantizationSpec",
    "QuantizedInferenceState",
    "QueueFullError",
    "ReplicaClient",
    "ReplicaManager",
    "ReplicaUnavailableError",
    "RequestError",
    "RETRYABLE_CODES",
    "ServeConfig",
    "ServeError",
    "ServerClosedError",
    "ServerDrainingError",
    "SheddedError",
    "WedgedStepError",
    "error_from_code",
    "graph_key",
    "quantize_state",
    "quantize_weights",
]
