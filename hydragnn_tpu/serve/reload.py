"""Hot checkpoint reload: watch a run dir's ``latest`` pointer and swap
verified checkpoints into a live server without dropping requests.

A training run (or a continuous-training fleet, ROADMAP item 5) keeps
publishing checkpoints through the atomic pointer-commit protocol
(train/checkpoint.py); the watcher polls the pointer and, on change,
restores the candidate through the digest-verified walk-back chain into a
standby state (``load_inference_state`` — params/batch-stats only, no
optimizer allocation). The swap is staged via ``GraphServer._install_state``
and taken by the serve loop *between* batches, so in-flight batches keep the
weights they started with.

Failure policy: a corrupt candidate (sha256 mismatch, torn write,
deserialization failure) is REJECTED and the current weights keep serving —
the walk-back chain restoring an *older* file than the pointer names is
treated the same (installing it would silently downgrade the server). Every
rejection is counted and warned once; the next pointer change triggers a
fresh attempt. Exercised by tests/test_serve.py and
run-scripts/serve_chaos_smoke.py (flip_bit on the candidate).
"""

from __future__ import annotations

import threading
import time
import warnings
from typing import Optional


class CheckpointWatcher:
    """Daemon poller: ``latest`` pointer -> verified standby restore ->
    atomic between-batch swap. ``stats`` counts installs and rejections."""

    def __init__(
        self,
        server,
        log_name: str,
        path: str = "./logs",
        poll_s: float = 2.0,
        initial_entry: Optional[str] = None,
    ):
        self.server = server
        self.log_name = log_name
        self.path = path
        self.poll_s = max(float(poll_s), 0.05)
        self._last_entry = initial_entry
        self._stop = threading.Event()
        self.installed = 0
        self.rejected = 0
        self._thread = threading.Thread(
            target=self._main, daemon=True, name="serve-ckpt-watch"
        )

    def start(self) -> "CheckpointWatcher":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def poll_once(self) -> Optional[str]:
        """One poll step (also the test hook): returns ``installed``,
        ``rejected``, or None when the pointer is unchanged/absent."""
        from ..train.checkpoint import latest_checkpoint_entry, load_inference_state

        entry = latest_checkpoint_entry(self.log_name, self.path)
        if entry is None or entry == self._last_entry:
            return None
        # one attempt per pointer value: a corrupt candidate will not heal,
        # so re-trying it every poll would just spam the log
        self._last_entry = entry
        try:
            # restore into the PRE-cast template: a bf16/int8-cast (or
            # quantized) serving state's tree cannot template a msgpack
            # restore; _install_state re-applies the precision gate
            state, loaded_from = load_inference_state(
                getattr(self.server, "restore_template", None)
                or self.server._state,
                self.log_name, self.path,
            )
        except Exception as e:  # noqa: BLE001 — keep serving current weights
            self.rejected += 1
            self._emit_event("reject", entry, detail=f"{type(e).__name__}: {e}")
            warnings.warn(
                f"hot reload: candidate {entry!r} of run {self.log_name!r} "
                f"failed to restore ({type(e).__name__}: {e}); keeping the "
                f"current weights ({self.server.current_checkpoint})",
                RuntimeWarning,
                stacklevel=2,
            )
            return "rejected"
        if loaded_from != entry:
            # the verified walk-back chain fell PAST the candidate: the
            # pointer names a corrupt file. Installing the older file it
            # found instead would be a silent downgrade — keep current.
            self.rejected += 1
            self._emit_event(
                "reject", entry, detail=f"walk-back restored {loaded_from!r}"
            )
            warnings.warn(
                f"hot reload: candidate {entry!r} failed verification (the "
                f"restore chain fell back to {loaded_from!r}); keeping the "
                f"current weights ({self.server.current_checkpoint})",
                RuntimeWarning,
                stacklevel=2,
            )
            return "rejected"
        try:
            installed = self.server._install_state(state, entry)
        except Exception as e:  # noqa: BLE001 — gate refusals keep serving
            # the install-time precision gate refused the candidate (int8
            # accuracy drift past Serving.quantization.max_error): keep
            # the current weights, same verdict as a corrupt candidate.
            # The gate already emitted its own typed quant_drift event.
            self.rejected += 1
            self._emit_event(
                "reject", entry, detail=f"{type(e).__name__}: {e}"
            )
            warnings.warn(
                f"hot reload: candidate {entry!r} refused at install "
                f"({type(e).__name__}: {e}); keeping the current weights "
                f"({self.server.current_checkpoint})",
                RuntimeWarning,
                stacklevel=2,
            )
            return "rejected"
        if not installed:
            # the server refused the stage: it is draining/closing and the
            # serve loop will never take another swap. Count a rejection
            # (not an install — nothing was staged) and let the standby
            # state drop here instead of leaking it past close().
            self.rejected += 1
            self._emit_event(
                "reject", entry, detail="server draining/closed at install"
            )
            return "rejected"
        self.installed += 1
        self._emit_event("swap", entry)
        return "installed"

    def _emit_event(self, outcome: str, entry: str, detail: str = "") -> None:
        """Typed reload incident (obs/events.py) — swap/reject verdicts in
        the flight-recorder window; never allowed to fail the watcher."""
        try:
            from ..obs.events import EV_RELOAD_REJECT, EV_RELOAD_SWAP
            from ..obs.events import emit as _emit

            kind = EV_RELOAD_SWAP if outcome == "swap" else EV_RELOAD_REJECT
            attrs = {"candidate": entry, "run": self.log_name}
            if detail:
                attrs["detail"] = detail
            _emit(
                kind,
                severity="info" if outcome == "swap" else "warn",
                **attrs,
            )
        except Exception:
            pass

    def _main(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — the watcher must survive
                warnings.warn(
                    f"hot reload watcher error: {type(e).__name__}: {e}",
                    RuntimeWarning,
                    stacklevel=2,
                )
            self._stop.wait(self.poll_s)
