"""Front router for the serving fleet: failover, retries, hedging, circuit
breakers, priority classes, and the prediction cache (docs/SERVING.md
"Fleet").

The router owns the *request-side* half of the fleet's fault model (the
ReplicaManager in serve/fleet.py owns the process-side half): every replica
is addressed through a ``ReplicaClient`` (HTTP for subprocess workers,
in-process for tests and BENCH cells), and one ``predict`` call survives any
single-replica failure mode:

- **load balancing** — replicas are scored on live queue depth (the
  collector substrate's per-replica gauges via ``depth_fn``, plus the
  router's own in-flight count) and EMA latency; lowest score wins;
- **retries** — a typed retryable failure (``RETRYABLE_CODES``; plus
  router-observed timeouts, safe because graph inference is pure — no
  side effects to double-apply) is re-issued on a *different* replica
  with bounded exponential backoff, up to ``router_retries`` times;
- **hedging** — an interactive request still unanswered past
  ``max(router_hedge_min_s, router_hedge_factor x EMA latency)`` is
  duplicated to a second replica; the first answer wins and the loser is
  abandoned (a blocking HTTP read cannot be cancelled; its late result is
  discarded and counted);
- **circuit breakers** — ``breaker_failures`` consecutive typed failures
  open a per-replica breaker (typed ``breaker_open`` event); after
  ``breaker_cooldown_s`` one half-open probe is admitted, and its success
  recloses the breaker (``breaker_close``);
- **priority classes** — ``"interactive"`` (default) gets the full
  treatment; ``"batch"`` is never hedged and is shed *at the router* when
  the chosen replica's projected wait exceeds the SLO, so background
  traffic yields capacity to interactive traffic first;
- **prediction cache** — an optional content-addressed
  ``PredictionCache``; hits skip the fleet entirely and are bit-identical
  to misses by construction. Keys mix the graph content with the cache's
  *context* (installed checkpoint digest + prediction-affecting serve
  config, maintained by the ReplicaManager), so a hot-reloaded fleet can
  never serve a prior checkpoint's cached prediction as a hit.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..data.graph import Graph
from .cache import PredictionCache, graph_key
from .config import ServeConfig
from .errors import (
    BreakerOpenError,
    DeadlineExceededError,
    NoReplicasError,
    ReplicaUnavailableError,
    RETRYABLE_CODES,
    ServeError,
    SheddedError,
)

# Codes the router re-issues on a different replica. Extends the wire-level
# retryable set with router-observed timeouts: inference is pure, so a
# timed-out attempt (which may still complete uselessly on the wedged
# replica) is safe to re-issue — there is no side effect to double-apply.
_ROUTER_RETRYABLE = frozenset(RETRYABLE_CODES) | {DeadlineExceededError.code}

# Codes that count against a replica's circuit breaker: transport loss,
# lifecycle rejections, wedges, and timeouts are *replica-health* signals.
# invalid_request fails identically everywhere (client bug), and
# shed/queue_full are load signals — breaking on them would amputate
# capacity exactly when it is scarcest.
_BREAKER_COUNTED = frozenset(_ROUTER_RETRYABLE)

_PRIORITIES = ("interactive", "batch")


def _emit_event(kind: str, **attrs: Any) -> None:
    try:
        from ..obs.events import emit

        emit(kind, **attrs)
    except Exception:
        pass


class ReplicaClient:
    """Uniform replica handle: blocking typed-error predict + health
    introspection. ``predict`` either returns the head->array dict or
    raises a ``ServeError`` subclass (never a transport exception — HTTP
    clients map those to ``ReplicaUnavailableError``)."""

    name: str = "replica"

    def predict(self, graph: Graph,
                timeout_s: Optional[float] = None) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def ready(self) -> bool:
        raise NotImplementedError

    def queue_depth(self) -> Optional[float]:
        """Live queue depth when the client can see it cheaply, else None
        (the router falls back to its own in-flight tracking)."""
        return None

    def close(self) -> None:
        pass


class LocalReplicaClient(ReplicaClient):
    """In-process client over a ``GraphServer`` — the test/BENCH transport
    (no sockets, no serialization; latency numbers are the server's own)."""

    def __init__(self, server, name: Optional[str] = None):
        self.server = server
        self.name = name or f"local:{id(server):x}"

    def predict(self, graph: Graph,
                timeout_s: Optional[float] = None) -> Dict[str, np.ndarray]:
        handle = self.server.submit(graph, deadline_s=timeout_s)
        return handle.result(timeout=timeout_s)

    def ready(self) -> bool:
        return bool(self.server.ready and not self.server.draining
                    and self.server.failed is None)

    def queue_depth(self) -> Optional[float]:
        try:
            return float(self.server._queue.qsize())
        except Exception:
            return None


class HTTPReplicaClient(ReplicaClient):
    """HTTP client for a subprocess replica (serve/replica.py): POST
    /predict with the wire codec, GET /readyz for health. Transport
    failures (refused/reset/dead process) map to
    ``ReplicaUnavailableError``; protocol failures re-raise the replica's
    typed error reconstructed from its stable code."""

    def __init__(self, base_url: str, name: Optional[str] = None):
        self.base_url = base_url.rstrip("/")
        self.name = name or self.base_url

    def _post(self, path: str, payload: bytes,
              timeout_s: Optional[float]) -> bytes:
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            self.base_url + path,
            data=payload,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            # the replica answered with a typed error body: not a
            # transport failure — surface the body for decoding
            try:
                return e.read()
            except Exception:
                raise ReplicaUnavailableError(
                    f"replica {self.name}: HTTP {e.code} with unreadable "
                    f"body"
                )
        except Exception as e:
            raise ReplicaUnavailableError(
                f"replica {self.name}: {type(e).__name__}: {e}"
            )

    def predict(self, graph: Graph,
                timeout_s: Optional[float] = None) -> Dict[str, np.ndarray]:
        from . import wire

        payload = wire.encode_graph(graph)
        if timeout_s:
            # server-side deadline: urllib's timeout is socket-inactivity
            # only, and an abandoned request (router timeout, retry, lost
            # hedge) would otherwise run handle.result(timeout=None) and
            # park a replica HTTP thread forever. With deadline_s on the
            # wire the replica bounds the request itself and frees the
            # handler for work someone still wants.
            payload["deadline_s"] = float(timeout_s)
        body = self._post("/predict", wire.dumps(payload), timeout_s)
        obj = wire.loads(body)
        if wire.is_error(obj):
            raise wire.decode_error(obj)
        return wire.decode_prediction(obj)

    def ready(self) -> bool:
        import urllib.request

        try:
            with urllib.request.urlopen(
                self.base_url + "/readyz", timeout=2.0
            ) as resp:
                return resp.status == 200
        except Exception:
            return False


class CircuitBreaker:
    """Per-replica failure gate: ``failures`` consecutive counted failures
    open it; after ``cooldown_s`` exactly one half-open probe is admitted,
    and its outcome closes or re-opens. Thread-safe; time injectable for
    tests via ``now_fn``."""

    def __init__(self, replica: str, failures: int = 3,
                 cooldown_s: float = 5.0,
                 now_fn: Callable[[], float] = time.monotonic):
        self.replica = replica
        self.failures = max(int(failures), 1)
        self.cooldown_s = float(cooldown_s)
        self._now = now_fn
        self._lock = threading.Lock()
        self.state = "closed"
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_out = False
        self.opens = 0
        self.closes = 0

    def allow(self) -> bool:
        """Whether a request may be sent to this replica right now. In
        half-open, admits exactly one probe at a time."""
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                if self._now() - self._opened_at >= self.cooldown_s:
                    self.state = "half_open"
                    self._probe_out = False
                else:
                    return False
            # half_open: one outstanding probe
            if self._probe_out:
                return False
            self._probe_out = True
            return True

    def record_success(self) -> None:
        with self._lock:
            was = self.state
            self._consecutive = 0
            self._probe_out = False
            if was != "closed":
                self.state = "closed"
                self.closes += 1
        if was != "closed":
            from ..obs.events import EV_BREAKER_CLOSE

            _emit_event(EV_BREAKER_CLOSE, replica=self.replica)

    def record_failure(self, code: str = "") -> None:
        opened = False
        with self._lock:
            if self.state == "half_open":
                # failed probe: straight back to open, fresh cooldown
                self.state = "open"
                self._opened_at = self._now()
                self._probe_out = False
                self.opens += 1
                opened = True
            else:
                self._consecutive += 1
                if self.state == "closed" and (
                    self._consecutive >= self.failures
                ):
                    self.state = "open"
                    self._opened_at = self._now()
                    self.opens += 1
                    opened = True
        if opened:
            from ..obs.events import EV_BREAKER_OPEN

            _emit_event(
                EV_BREAKER_OPEN, replica=self.replica, code=code,
                consecutive=self._consecutive, cooldown_s=self.cooldown_s,
            )


class FleetRouter:
    """Failover front door over a set of ``ReplicaClient``s.

    ``depth_fn(name) -> Optional[float]`` is the collector-substrate hook:
    the ReplicaManager wires it to the aggregated per-replica queue-depth
    gauges so balancing sees queue pressure the router did not itself
    create. ``clients`` may be mutated via ``set_clients`` as the manager
    restarts/benches replicas.
    """

    def __init__(
        self,
        clients: Dict[str, ReplicaClient],
        cfg: Optional[ServeConfig] = None,
        cache: Optional[PredictionCache] = None,
        depth_fn: Optional[Callable[[str], Optional[float]]] = None,
    ):
        self.cfg = cfg or ServeConfig()
        self._lock = threading.Lock()
        self._clients: Dict[str, ReplicaClient] = dict(clients)
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._inflight: Dict[str, int] = {}
        self._lat_ema: Dict[str, float] = {}
        self.cache = cache
        self._depth_fn = depth_fn
        self._stats = {
            "requests": 0,
            "succeeded": 0,
            "failed": 0,
            "retries": 0,
            "hedges": 0,
            "hedge_wins": 0,
            "hedge_wasted": 0,
            "cache_hits": 0,
            "cache_misses": 0,
            "router_shed": 0,
        }
        for name in clients:
            self._ensure_replica(name)

    # -- replica bookkeeping -------------------------------------------------

    def _ensure_replica(self, name: str) -> None:
        with self._lock:
            if name not in self._breakers:
                self._breakers[name] = CircuitBreaker(
                    name,
                    failures=self.cfg.breaker_failures,
                    cooldown_s=self.cfg.breaker_cooldown_s,
                )
            self._inflight.setdefault(name, 0)

    def set_clients(self, clients: Dict[str, ReplicaClient]) -> None:
        """Replace the replica set (manager restart/bench churn). Breakers
        and latency history persist across a same-name replacement — a
        restarted replica starts half-trusted, which is exactly right."""
        with self._lock:
            self._clients = dict(clients)
        for name in clients:
            self._ensure_replica(name)

    def replicas(self) -> List[str]:
        with self._lock:
            return sorted(self._clients)

    def breaker(self, name: str) -> CircuitBreaker:
        self._ensure_replica(name)
        return self._breakers[name]

    def ready_count(self) -> int:
        with self._lock:
            clients = list(self._clients.values())
        return sum(1 for c in clients if _safe_ready(c))

    # -- balancing -----------------------------------------------------------

    def _score(self, name: str, client: ReplicaClient) -> float:
        depth = None
        if self._depth_fn is not None:
            try:
                depth = self._depth_fn(name)
            except Exception:
                depth = None
        if depth is None:
            depth = client.queue_depth()
        with self._lock:
            inflight = self._inflight.get(name, 0)
            lat = self._lat_ema.get(name, 0.0)
        # queued work dominates; the latency term breaks ties toward the
        # historically faster replica (normalized so 10ms of EMA ~ one
        # queued request)
        return float(depth or 0.0) + float(inflight) + lat * 100.0

    def _pick(self, exclude: set) -> Optional[str]:
        """Choose the lowest-scored breaker-admitted replica not in
        ``exclude``. Half-open probe slots are handed out by ``allow()``;
        to avoid consuming a probe slot for a replica we do not pick, probe
        admission is re-checked only for the winner and losers' slots are
        released."""
        with self._lock:
            names = list(self._clients)
        scored: List[tuple] = []
        for n in names:
            if n in exclude:
                continue
            br = self.breaker(n)
            with br._lock:
                state = br.state
                if state == "open" and (
                    br._now() - br._opened_at < br.cooldown_s
                ):
                    continue  # hard-open: not a candidate
                if state == "half_open" and br._probe_out:
                    continue  # someone is already probing it
            with self._lock:
                client = self._clients.get(n)
            if client is None:
                continue
            scored.append((self._score(n, client), n))
        if not scored:
            return None
        scored.sort()
        for _, n in scored:
            if self.breaker(n).allow():
                return n
        return None

    # -- dispatch ------------------------------------------------------------

    def _attempt(self, name: str, graph: Graph, timeout_s: float):
        """One dispatch to one replica: returns ``("ok", result, dt)`` or
        ``("err", exc, dt)`` — never raises. Updates in-flight counts, the
        latency EMA, and the breaker."""
        with self._lock:
            client = self._clients.get(name)
            self._inflight[name] = self._inflight.get(name, 0) + 1
        t0 = time.perf_counter()
        try:
            if client is None:
                raise ReplicaUnavailableError(
                    f"replica {name} left the fleet"
                )
            result = client.predict(graph, timeout_s=timeout_s)
            dt = time.perf_counter() - t0
            with self._lock:
                prev = self._lat_ema.get(name)
                self._lat_ema[name] = (
                    dt if prev is None else 0.8 * prev + 0.2 * dt
                )
            self.breaker(name).record_success()
            return ("ok", result, dt)
        except BaseException as e:  # noqa: BLE001 — typed below
            dt = time.perf_counter() - t0
            code = getattr(e, "code", None)
            if code is None:
                e = ReplicaUnavailableError(
                    f"replica {name}: {type(e).__name__}: {e}"
                )
                code = e.code
            if code in _BREAKER_COUNTED:
                self.breaker(name).record_failure(code=code)
            return ("err", e, dt)
        finally:
            with self._lock:
                self._inflight[name] = max(
                    self._inflight.get(name, 1) - 1, 0
                )

    def _hedge_delay(self, name: str) -> float:
        with self._lock:
            ema = self._lat_ema.get(name, 0.0)
        return max(
            float(self.cfg.router_hedge_min_s),
            float(self.cfg.router_hedge_factor) * ema,
        )

    def _dispatch(self, graph: Graph, primary: str, timeout_s: float,
                  hedge: bool, tried: set):
        """Dispatch to ``primary``; optionally hedge to a second replica
        past the hedge deadline. Returns ``("ok", result, winner)`` or
        ``("err", first_error)``. Replicas used are added to ``tried``."""
        out: "queue.Queue" = queue.Queue()

        def run(name: str) -> None:
            status, payload, dt = self._attempt(name, graph, timeout_s)
            out.put((status, payload, name))

        tried.add(primary)
        threading.Thread(
            target=run, args=(primary,), daemon=True,
            name=f"router-req-{primary}",
        ).start()
        outstanding = 1
        deadline = time.monotonic() + timeout_s
        hedge_at = (
            time.monotonic() + self._hedge_delay(primary) if hedge else None
        )
        first_err: Optional[BaseException] = None
        while outstanding > 0:
            now = time.monotonic()
            if now >= deadline:
                break
            wait_until = deadline
            if hedge_at is not None:
                wait_until = min(wait_until, hedge_at)
            try:
                status, payload, name = out.get(
                    timeout=max(wait_until - now, 0.001)
                )
            except queue.Empty:
                if hedge_at is not None and time.monotonic() >= hedge_at:
                    hedge_at = None
                    mate = self._pick(exclude=tried)
                    if mate is not None:
                        tried.add(mate)
                        self._bump("hedges")
                        threading.Thread(
                            target=run, args=(mate,), daemon=True,
                            name=f"router-hedge-{mate}",
                        ).start()
                        outstanding += 1
                continue
            outstanding -= 1
            if status == "ok":
                if name != primary:
                    self._bump("hedge_wins")
                if outstanding > 0:
                    # the loser's eventual answer is discarded
                    self._bump("hedge_wasted")
                return ("ok", payload, name)
            if first_err is None:
                first_err = payload
        if first_err is None:
            first_err = DeadlineExceededError(
                f"router timeout after {timeout_s:.3f}s on {sorted(tried)}"
            )
        return ("err", first_err)

    # -- public API ----------------------------------------------------------

    def predict(
        self,
        graph: Graph,
        timeout_s: Optional[float] = None,
        priority: str = "interactive",
    ) -> Dict[str, np.ndarray]:
        """Route one prediction through the fleet. Raises a typed
        ``ServeError``; transient single-replica failures are absorbed by
        retries/hedging and never reach the caller."""
        if priority not in _PRIORITIES:
            raise ValueError(
                f"priority {priority!r} must be one of {_PRIORITIES}"
            )
        self._bump("requests")
        timeout_s = float(
            timeout_s if timeout_s is not None else self.cfg.router_timeout_s
        )
        key = None
        gk = None
        if self.cache is not None:
            # key = graph content x cache context (checkpoint digest +
            # serve config); key_for returns None while the context is
            # unknown/mixed (mid-rollout) and the cache sits out entirely
            gk = graph_key(graph)
            key = self.cache.key_for(graph, base=gk)
            if key is not None:
                hit = self.cache.get(graph, key=key)
                if hit is not None:
                    self._bump("cache_hits")
                    self._bump("succeeded")
                    return hit
                self._bump("cache_misses")

        deadline = time.monotonic() + timeout_s
        tried: set = set()
        attempts: List[str] = []
        last_err: Optional[BaseException] = None
        for attempt in range(int(self.cfg.router_retries) + 1):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            name = self._pick(exclude=tried)
            if name is None and tried:
                # every distinct replica was tried: allow a second pass
                # over the fleet rather than failing with capacity idle
                name = self._pick(exclude=set())
            if name is None:
                if not attempts:
                    self._bump("failed")
                    raise BreakerOpenError(
                        "no replica available: all breakers open or fleet "
                        "empty"
                    )
                attempts.append("no_candidate")
                break
            if priority == "batch" and self._batch_shed(name):
                self._bump("router_shed")
                raise SheddedError(
                    f"batch-priority request shed at the router: replica "
                    f"{name} projected wait exceeds the SLO",
                    projected_wait_s=self._projected_wait(name),
                    slo_s=self.cfg.slo_p99_s,
                )
            status, payload, *rest = self._dispatch(
                graph, name, min(remaining, timeout_s),
                hedge=(priority == "interactive"), tried=tried,
            )
            if status == "ok":
                self._bump("succeeded")
                if self.cache is not None and key is not None and (
                    # the context may have moved while the request was in
                    # flight (a reload finished): a prediction keyed under
                    # the old checkpoint must not land under the new one
                    self.cache.key_for(graph, base=gk) == key
                ):
                    self.cache.put(graph, payload, key=key)
                return payload
            last_err = payload
            code = getattr(payload, "code", ServeError.code)
            attempts.append(f"{name}:{code}")
            if code not in _ROUTER_RETRYABLE:
                self._bump("failed")
                raise payload
            if attempt < int(self.cfg.router_retries):
                self._bump("retries")
                backoff = float(self.cfg.router_backoff_s) * (2 ** attempt)
                time.sleep(min(backoff, max(deadline - time.monotonic(), 0)))
        self._bump("failed")
        if isinstance(last_err, ServeError) and not attempts:
            raise last_err
        raise NoReplicasError(
            f"prediction failed after {len(attempts)} attempt(s): "
            f"{attempts} (last: {last_err})",
            attempts=attempts,
        )

    def _projected_wait(self, name: str) -> float:
        with self._lock:
            client = self._clients.get(name)
            inflight = self._inflight.get(name, 0)
            lat = self._lat_ema.get(name, 0.0)
        depth = 0.0
        if client is not None:
            depth = float(client.queue_depth() or 0.0)
        return (depth + inflight) * lat

    def _batch_shed(self, name: str) -> bool:
        """Router-side shedding for batch priority: when an SLO is
        configured and the chosen replica's projected wait already blows
        it, background traffic yields instead of queueing."""
        slo = float(self.cfg.slo_p99_s)
        return slo > 0 and self._projected_wait(name) > slo

    def _bump(self, key: str, by: int = 1) -> None:
        with self._lock:
            self._stats[key] = self._stats.get(key, 0) + by

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = dict(self._stats)
            out["replicas"] = sorted(self._clients)
            out["inflight"] = dict(self._inflight)
            out["latency_ema_s"] = {
                k: round(v, 6) for k, v in self._lat_ema.items()
            }
        out["breakers"] = {
            n: b.state for n, b in list(self._breakers.items())
        }
        if self.cache is not None:
            out["cache"] = self.cache.stats()
        return out

    def close(self) -> None:
        with self._lock:
            clients = list(self._clients.values())
        for c in clients:
            try:
                c.close()
            except Exception:
                pass


def _safe_ready(client: ReplicaClient) -> bool:
    try:
        return bool(client.ready())
    except Exception:
        return False
