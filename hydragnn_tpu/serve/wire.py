"""JSON wire codec for the serving fleet (replica /predict protocol).

The router (serve/router.py) and replica workers (serve/replica.py) speak
plain JSON over HTTP — no new dependencies — but predictions must survive
the trip *bit-exactly* (the prediction cache asserts hit/miss identity, and
BENCH numbers comparing local vs fleet serving are only meaningful if the
wire is lossless). Arrays are therefore encoded as raw little-endian bytes
(base64) plus dtype and shape, never as JSON float literals: a float32
round-tripped through decimal text is not the same float32.

Failure payloads carry the stable ``code`` from serve/errors.py so the
client side reconstructs the *typed* exception — a router branching on
``RETRYABLE_CODES`` behaves identically against a remote replica and an
in-process server.

Wire format (version ``WIRE_V``):

- array:      ``{"__nd__": 1, "dtype": "<f4", "shape": [n, d], "b64": "..."}``
- ``None``:   JSON null; scalars/str/bool pass through natively
- graph:      ``{"v": 1, "fields": {name: array-or-null, ...},
                "dataset_id": int}``
- prediction: ``{"v": 1, "result": {head: array, ...}}``
- error:      ``{"v": 1, "error": {"code": "...", "message": "..."}}``
"""

from __future__ import annotations

import base64
import binascii
import json
from typing import Any, Dict, Optional

import numpy as np

from ..data.graph import Graph
from .errors import InvalidRequestError, ServeError, error_from_code

WIRE_V = 1

# Every array-bearing Graph field the codec ships (the non-array fields are
# dataset_id, handled explicitly, and the target dicts, which inference
# requests do not carry but the codec tolerates).
_GRAPH_ARRAY_FIELDS = (
    "x", "pos", "senders", "receivers", "edge_attr", "edge_shifts",
    "pe", "rel_pe", "z", "graph_y", "cell",
)
_GRAPH_DICT_FIELDS = ("graph_targets", "node_targets")


def encode_array(a: np.ndarray) -> Dict[str, Any]:
    a = np.ascontiguousarray(np.asarray(a))
    return {
        "__nd__": 1,
        "dtype": a.dtype.str,
        "shape": list(a.shape),
        "b64": base64.b64encode(a.tobytes()).decode("ascii"),
    }


def decode_array(obj: Dict[str, Any]) -> np.ndarray:
    try:
        dtype = np.dtype(obj["dtype"])
        shape = tuple(int(s) for s in obj["shape"])
        raw = base64.b64decode(obj["b64"].encode("ascii"))
    except (KeyError, TypeError, ValueError, binascii.Error) as e:
        raise InvalidRequestError(
            f"wire array field undecodable: {type(e).__name__}: {e}",
            reason="wire_truncated",
        )
    expected = dtype.itemsize * int(np.prod(shape, dtype=np.int64)) if shape \
        else dtype.itemsize
    if len(raw) != expected:
        raise InvalidRequestError(
            f"wire array payload is {len(raw)} bytes, expected {expected} "
            f"for dtype {dtype} shape {shape}",
            reason="wire_truncated",
        )
    return np.frombuffer(raw, dtype=dtype).reshape(shape).copy()


def _maybe_array(v: Any) -> Any:
    return None if v is None else encode_array(v)


def encode_graph(graph: Graph) -> Dict[str, Any]:
    fields: Dict[str, Any] = {
        name: _maybe_array(getattr(graph, name, None))
        for name in _GRAPH_ARRAY_FIELDS
    }
    for name in _GRAPH_DICT_FIELDS:
        table = getattr(graph, name, None)
        fields[name] = (
            None if table is None
            else {k: encode_array(v) for k, v in table.items()}
        )
    return {"v": WIRE_V, "fields": fields,
            "dataset_id": int(graph.dataset_id)}


def decode_graph(obj: Dict[str, Any]) -> Graph:
    try:
        fields = obj["fields"]
        kwargs: Dict[str, Any] = {}
        for name in _GRAPH_ARRAY_FIELDS:
            v = fields.get(name)
            kwargs[name] = None if v is None else decode_array(v)
        for name in _GRAPH_DICT_FIELDS:
            table = fields.get(name)
            kwargs[name] = (
                None if table is None
                else {k: decode_array(v) for k, v in table.items()}
            )
        kwargs["dataset_id"] = int(obj.get("dataset_id", 0))
    except InvalidRequestError:
        raise
    except (KeyError, TypeError, ValueError) as e:
        raise InvalidRequestError(
            f"malformed wire graph: {e}", reason="wire_malformed"
        )
    for required in ("x", "pos", "senders", "receivers"):
        if kwargs.get(required) is None:
            raise InvalidRequestError(
                f"wire graph missing required field {required!r}",
                reason="wire_missing_field",
            )
    return Graph(**kwargs)


def encode_prediction(result: Dict[str, np.ndarray]) -> Dict[str, Any]:
    return {
        "v": WIRE_V,
        "result": {k: encode_array(v) for k, v in result.items()},
    }


def decode_prediction(obj: Dict[str, Any]) -> Dict[str, np.ndarray]:
    return {k: decode_array(v) for k, v in obj["result"].items()}


def encode_error(err: BaseException) -> Dict[str, Any]:
    code = getattr(err, "code", None) or ServeError.code
    return {"v": WIRE_V, "error": {"code": code, "message": str(err)}}


def decode_error(obj: Dict[str, Any]) -> ServeError:
    e = obj.get("error") or {}
    return error_from_code(str(e.get("code", ServeError.code)),
                           str(e.get("message", "")))


def dumps(obj: Dict[str, Any]) -> bytes:
    return json.dumps(obj, separators=(",", ":")).encode("utf-8")


def loads(payload: bytes) -> Dict[str, Any]:
    try:
        obj = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise InvalidRequestError(
            f"wire payload is not JSON: {e}", reason="wire_not_json"
        )
    if not isinstance(obj, dict):
        raise InvalidRequestError(
            "wire payload must be a JSON object", reason="wire_not_object"
        )
    return obj


def is_error(obj: Dict[str, Any]) -> bool:
    return isinstance(obj.get("error"), dict)


__all__ = [
    "WIRE_V",
    "decode_array", "decode_error", "decode_graph", "decode_prediction",
    "dumps", "encode_array", "encode_error", "encode_graph",
    "encode_prediction", "is_error", "loads",
]
