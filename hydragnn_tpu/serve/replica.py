"""Serving-fleet replica worker: one GraphServer process addressable over
HTTP (docs/SERVING.md "Fleet").

``python -m hydragnn_tpu.serve.replica <config.json>`` builds a server via
``api.run_server`` (same checkpoint restore, ladder warm-up, sentinel, and
telemetry wiring as a standalone server) and then mounts the fleet protocol
on the telemetry endpoint the server already opened:

- ``POST /predict`` — wire-codec graph in, wire-codec prediction out;
  typed failures return their stable error code (serve/errors.py) with an
  HTTP status in the matching class, so transport-level and protocol-level
  failures stay distinguishable at the router;
- ``POST /reload`` — ``{"poll": true}`` takes one CheckpointWatcher poll
  (the ReplicaManager staggers these across the fleet for rolling
  reloads); ``{"entry": "..."}`` force-installs one specific verified
  checkpoint (the rollback path); ``{}`` reports the current checkpoint;
- ``POST /stats`` — the server's ``stats()`` dict (the smoke and the
  manager's reload probe read ``current_checkpoint`` and error counters
  here);
- ``GET /readyz`` / ``/healthz`` / ``/metrics`` — unchanged from the
  single-server deployment; the manager health-gates on /readyz.

Identity and wiring come from the environment the ReplicaManager sets:
``HYDRAGNN_FLEET_HOST_INDEX``/``_COUNT`` (the replica's fleet identity —
events land host-suffixed in ``events-h<i>.jsonl`` and the doctor merges
them), ``HYDRAGNN_SERVE_RENDEZVOUS`` (directory to publish
``replica_<i>.json`` with the bound port, tmp+rename atomic), and
``HYDRAGNN_SERVE_FLEET_PUSH`` (the manager's collector URL; a FleetPusher
heartbeat carries this replica's serve gauges there ~1/s).

Chaos drills (utils/faultinject.py): ``maybe_replica_kill`` /
``maybe_replica_wedge`` / ``maybe_replica_slow`` run at the top of every
/predict, keyed by this replica's fleet index and a per-process request
counter.
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import threading
import time
from typing import Any, Dict, Tuple

from ..utils import faultinject
from ..utils.envflags import env_str
from .errors import (
    DeadlineExceededError,
    InvalidRequestError,
    QueueFullError,
    ServeError,
    ServerClosedError,
    ServerDrainingError,
    SheddedError,
)

# stable code -> HTTP status for /predict failures: 4xx = the request (or
# its timing) is the problem, 503 = this replica cannot take it (retry
# elsewhere), 500 = the serving step itself failed
_STATUS_BY_CODE = {
    InvalidRequestError.code: 400,
    QueueFullError.code: 429,
    SheddedError.code: 429,
    DeadlineExceededError.code: 504,
    ServerDrainingError.code: 503,
    ServerClosedError.code: 503,
}

_READY_TIMEOUT_S = 600.0
_HEARTBEAT_S = 1.0


def _error_response(err: BaseException) -> Tuple[int, Dict[str, Any]]:
    from . import wire

    status = _STATUS_BY_CODE.get(getattr(err, "code", ""), 500)
    return status, wire.encode_error(err)


class ReplicaApp:
    """The fleet protocol mounted over one started GraphServer. Separated
    from ``main()`` so tests can drive the handlers in-process without a
    subprocess or a real config."""

    def __init__(self, server, watcher, replica_index: int):
        self.server = server
        self.watcher = watcher
        self.index = int(replica_index)
        self._req_seq = itertools.count()

    # -- handlers (TelemetryHTTPServer post routes) --------------------------

    def handle_predict(self, body: bytes) -> Tuple[int, Dict[str, Any]]:
        from . import wire

        idx = next(self._req_seq)
        # chaos drills: dead / wedged / slow replica models (no-op unarmed)
        faultinject.maybe_replica_kill(self.index, idx)
        faultinject.maybe_replica_wedge(self.index, idx)
        faultinject.maybe_replica_slow(self.index)
        try:
            obj = wire.loads(body)
            graph = wire.decode_graph(obj)
            deadline_s = obj.get("deadline_s")
            handle = self.server.submit(
                graph,
                deadline_s=float(deadline_s) if deadline_s else None,
            )
            result = handle.result(
                timeout=float(deadline_s) if deadline_s else None
            )
            return 200, wire.encode_prediction(result)
        except ServeError as e:
            return _error_response(e)
        except Exception as e:  # noqa: BLE001 — must answer, not hang
            return _error_response(
                ServeError(f"replica {self.index}: {type(e).__name__}: {e}")
            )

    def handle_reload(self, body: bytes) -> Tuple[int, Dict[str, Any]]:
        try:
            req = json.loads(body.decode("utf-8")) if body.strip() else {}
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            return 400, {"error": {"code": "invalid_request",
                                   "message": f"reload body not JSON: {e}"}}
        try:
            if req.get("entry"):
                return self._reload_entry(str(req["entry"]))
            if req.get("poll"):
                outcome = (
                    self.watcher.poll_once()
                    if self.watcher is not None else None
                )
                return 200, {
                    "status": outcome or "unchanged",
                    "current": self.server.current_checkpoint,
                }
            return 200, {"status": "noop",
                         "current": self.server.current_checkpoint}
        except Exception as e:  # noqa: BLE001
            return 500, {"error": {"code": "serve_error",
                                   "message": f"{type(e).__name__}: {e}"}}

    def _reload_entry(self, entry: str) -> Tuple[int, Dict[str, Any]]:
        """Force-install one specific verified checkpoint — the rolling
        rollback. No walk-back: a rollback restores exactly the prior
        entry or fails loudly."""
        from ..train.checkpoint import load_inference_entry

        try:
            state = load_inference_entry(
                getattr(self.server, "restore_template", None)
                or self.server._state,
                self.server.log_name, entry,
            )
        except (FileNotFoundError, ValueError) as e:
            return 409, {"error": {"code": "serve_error",
                                   "message": str(e)}}
        try:
            installed = self.server._install_state(state, entry)
        except Exception as e:  # noqa: BLE001 — typed gate refusal
            # int8 accuracy gate refused the entry (QuantizationDriftError
            # et al): answer "rejected", keep the current weights serving
            return 409, {"status": "rejected", "error": {
                "code": getattr(e, "code", "serve_error"),
                "message": f"{type(e).__name__}: {e}",
            }}
        if not installed:
            return 503, {"error": {
                "code": ServerDrainingError.code,
                "message": "server draining/closed; reload refused",
            }}
        # NOTE: the watcher's _last_entry still holds the pointer value it
        # last attempted, so a poll will not re-install the rolled-back-from
        # candidate; the rollback holds until the pointer changes again.
        return 200, {"status": "installed", "current": entry}

    def handle_stats(self, body: bytes) -> Tuple[int, Dict[str, Any]]:
        try:
            stats = self.server.stats()
            stats["replica_index"] = self.index
            return 200, stats
        except Exception as e:  # noqa: BLE001
            return 500, {"error": {"code": "serve_error",
                                   "message": f"{type(e).__name__}: {e}"}}

    def mount(self) -> bool:
        http = getattr(self.server, "_http", None)
        if http is None:
            return False
        http.add_post_route("/predict", self.handle_predict)
        http.add_post_route("/reload", self.handle_reload)
        http.add_post_route("/stats", self.handle_stats)
        return True


def _write_rendezvous(rendezvous_dir: str, index: int,
                      port: int) -> None:
    """Atomically publish this replica's address for the manager
    (tmp+rename, the checkpoint pointer discipline — the manager must
    never read a torn JSON)."""
    os.makedirs(rendezvous_dir, exist_ok=True)
    path = os.path.join(rendezvous_dir, f"replica_{index}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"replica": index, "port": int(port),
                   "pid": os.getpid()}, f)
    os.replace(tmp, path)


def _heartbeat_loop(app: ReplicaApp, push_url: str, index: int,
                    count: int) -> None:
    """Push this replica's registry (serve gauges included) to the
    manager's collector ~1/s until the server stops — the liveness signal
    the manager's staleness sweep watches, and the queue-depth feed the
    router balances on."""
    from ..obs.fleet import FleetPusher

    pusher = FleetPusher(push_url, host=index, host_count=count)
    try:
        while not app.server._stop.is_set():
            stats_step = app.server._stats.get("completed", 0)
            pusher.on_window(
                step=int(stats_step),
                step_time_s=float(app.server._per_graph_s) or None,
            )
            time.sleep(_HEARTBEAT_S)
    finally:
        pusher.close()


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) != 1:
        print("usage: python -m hydragnn_tpu.serve.replica <config.json>",
              file=sys.stderr)
        return 2
    from .. import api
    from ..obs.fleet import host_identity

    index, count = host_identity()
    server = api.run_server(argv[0], install_sigterm=True)
    app = ReplicaApp(server, getattr(server, "_watcher", None), index)
    if not app.mount():
        print(
            f"replica {index}: no HTTP endpoint (Serving.http_port < 0 or "
            "bind failed); a fleet replica must be addressable",
            file=sys.stderr,
        )
        server.close(drain=False)
        return 1
    if not server.wait_ready(timeout=_READY_TIMEOUT_S):
        print(f"replica {index}: warm-up failed: {server.failed}",
              file=sys.stderr)
        server.close(drain=False)
        return 1
    rendezvous = env_str("HYDRAGNN_SERVE_RENDEZVOUS")
    if rendezvous:
        _write_rendezvous(rendezvous, index, server.http_port)
    push_url = env_str("HYDRAGNN_SERVE_FLEET_PUSH")
    if push_url:
        threading.Thread(
            target=_heartbeat_loop, args=(app, push_url, index, count),
            daemon=True, name=f"replica-{index}-heartbeat",
        ).start()
    print(f"REPLICA_READY index={index} port={server.http_port}",
          flush=True)
    # serve until SIGTERM (drain) or close; the drained event fires when
    # every admitted request was answered
    try:
        while not server._drained.wait(timeout=0.5):
            if server.failed is not None:
                print(f"replica {index}: serve loop failed: {server.failed}",
                      file=sys.stderr)
                server.close(drain=False)
                return 1
    except KeyboardInterrupt:
        pass
    server.close()
    print(f"REPLICA_EXIT index={index}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
