"""Deterministic synthetic graph dataset for CI-grade accuracy tests.

Behavioral equivalent of the reference's test fixture generator
(tests/deterministic_graph_data.py:20-66 and create_configuration :68-220):
BCC-lattice configurations with random per-node types and closed-form targets

    out1 = knn_smooth(type)        (k-nearest-neighbour average, simulating MP)
    out2 = out1**2 + type
    out3 = out1**3
    graph_target = sum(out1) + sum(out2) + sum(out3)

The node feature *table* exposed per node is ``[type, out2, out3]`` matching
the reference CI configs' column selection (tests/inputs/ci.json node_features
column_index [0, 6, 7]); the single graph feature is the total sum.
``linear_only=True`` mirrors the reference flag: out1 = type, graph target =
sum(out1).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .graph import Graph
from .neighbors import radius_graph, radius_graph_pbc


def knn_average(pos: np.ndarray, values: np.ndarray, k: int) -> np.ndarray:
    """Average of the k nearest samples (incl. self), like KNeighborsRegressor."""
    from scipy.spatial import cKDTree

    tree = cKDTree(pos)
    _, idx = tree.query(pos, k=k)
    if k == 1:
        idx = idx[:, None]
    return values[idx].mean(axis=1)


def deterministic_graph_dataset(
    number_configurations: int = 500,
    unit_cell_x_range: Sequence[int] = (1, 3),
    unit_cell_y_range: Sequence[int] = (1, 3),
    unit_cell_z_range: Sequence[int] = (1, 2),
    number_types: int = 3,
    types: Optional[Sequence[int]] = None,
    number_neighbors: int = 2,
    linear_only: bool = False,
    radius: float = 2.0,
    max_neighbours: int = 100,
    seed: int = 97,
) -> List[Graph]:
    """Generate BCC configurations with closed-form targets as ``Graph`` list.

    Unlike the reference (which writes LSMS-style text files and re-reads them
    through the raw loader, tests/test_graphs.py:91-126) this builds the graphs
    in memory; the text round-trip is exercised separately by the raw-loader
    tests.
    """
    if types is None:
        types = list(range(number_types))
    rng = np.random.default_rng(seed)
    graphs: List[Graph] = []
    for _ in range(number_configurations):
        uc = (
            rng.integers(unit_cell_x_range[0], unit_cell_x_range[1]),
            rng.integers(unit_cell_y_range[0], unit_cell_y_range[1]),
            rng.integers(unit_cell_z_range[0], unit_cell_z_range[1]),
        )
        graphs.append(
            _configuration(rng, uc, types, number_neighbors, linear_only, radius, max_neighbours)
        )
    return graphs


def bcc_positions(uc_x: int, uc_y: int, uc_z: int) -> np.ndarray:
    """Body-centered-cubic positions: corner + center atom per unit cell."""
    corners = np.array(
        [(x, y, z) for x in range(uc_x) for y in range(uc_y) for z in range(uc_z)],
        np.float64,
    )
    pos = np.empty((2 * corners.shape[0], 3), np.float64)
    pos[0::2] = corners
    pos[1::2] = corners + 0.5
    return pos


def _configuration(rng, uc, types, number_neighbors, linear_only, radius, max_neighbours):
    pos = bcc_positions(*uc)
    n = pos.shape[0]
    node_type = rng.integers(min(types), max(types) + 1, (n, 1)).astype(np.float64)

    if linear_only:
        out1 = node_type.copy()
    else:
        out1 = knn_average(pos, node_type, number_neighbors)
    out2 = out1**2 + node_type
    out3 = out1**3

    if linear_only:
        total = out1.sum(keepdims=False)
        x_table = node_type.astype(np.float32)
    else:
        total = out1.sum() + out2.sum() + out3.sum()
        # columns as selected by ci.json: [type, out2, out3]
        x_table = np.concatenate([node_type, out2, out3], axis=1).astype(np.float32)

    senders, receivers = radius_graph(pos, radius, max_neighbours)
    return Graph(
        x=x_table,
        pos=pos.astype(np.float32),
        senders=senders,
        receivers=receivers,
        graph_y=np.asarray([float(total)], np.float32),
        z=node_type[:, 0].astype(np.int32),
    )


def grow_molecule(rng, n: int, lo: float = 1.0, hi: float = 1.9,
                  step: float = 1.5, max_tries: int = 8000) -> np.ndarray:
    """Bonded-molecule geometry by rejection sampling at covalent distances:
    each new atom anchors off a random placed atom and must land within
    [lo, hi] of its nearest neighbor. Shared by the molecular generators
    (qm9 here; ani1x/qm7x/transition1x/omol25/uv in data/shaped.py)."""
    pos = np.zeros((n, 3))
    placed, tries = 1, 0
    while placed < n and tries < max_tries:
        tries += 1
        anchor = pos[int(rng.integers(placed))]
        cand = anchor + rng.normal(0.0, 1.0, 3) * step
        d = np.linalg.norm(pos[:placed] - cand, axis=1)
        if d.min() > lo and d.min() < hi:
            pos[placed] = cand
            placed += 1
    return pos[:placed]


def supercell_frac(basis: np.ndarray, reps: int) -> np.ndarray:
    """Fractional coordinates of a ``reps^3`` supercell of ``basis`` (one
    row per atom, x-major cell order) — shared by the periodic generators
    (mptrj/alexandria/omat24/eam)."""
    cells = np.array(
        [(x, y, z) for x in range(reps) for y in range(reps)
         for z in range(reps)],
        np.float64,
    )
    return (cells[:, None, :] + basis[None, :, :]).reshape(-1, 3) / reps


def _symmetrize_edges(senders: np.ndarray, receivers: np.ndarray):
    """Every pair must appear in both directions or the 0.5-per-edge energy
    sum and the receiver-side force accumulation break Newton's third law."""
    pairs = set(zip(senders.tolist(), receivers.tolist()))
    pairs |= {(i, j) for (j, i) in pairs}
    s, r = zip(*sorted(pairs))
    return np.asarray(s, np.int32), np.asarray(r, np.int32)


def _lj_targets(pos, senders, receivers, epsilon: float, sigma: float,
                shifts=None):
    """Closed-form Lennard-Jones total energy and per-atom forces over the
    edge list. Each pair of a symmetric list appears twice, so half the
    pair energy is charged per edge; forces are the exact gradient of that
    edge-restricted energy (half accumulated on each endpoint), so
    F = -dE/dpos holds for ANY edge list — including ones where a neighbor
    cap dropped one direction of a pair. ``shifts`` makes the displacements
    PBC-aware (minimum-image convention of the graph)."""
    diff = pos[receivers] - pos[senders]  # r_i - r_j for edge j->i
    if shifts is not None:
        diff = diff - shifts
    r = np.linalg.norm(diff, axis=1)
    s6 = (sigma / r) ** 6
    s12 = s6**2
    energy = float(np.sum(0.5 * 4.0 * epsilon * (s12 - s6)))
    # dE/dpos of the per-edge half energies: each edge pushes both endpoints
    coef = 0.5 * 24.0 * epsilon * (2.0 * s12 - s6) / r**2
    forces = np.zeros_like(pos)
    np.add.at(forces, receivers, coef[:, None] * diff)
    np.add.at(forces, senders, -coef[:, None] * diff)
    return energy, forces


def oc20_shaped_dataset(
    number_configurations: int = 64,
    mean_atoms: float = 73.0,
    min_atoms: int = 20,
    max_atoms: int = 225,
    radius: float = 5.0,
    max_neighbours: int = 20,
    lattice_constant: float = 3.8,
    jitter: float = 0.12,
    seed: int = 42,
) -> List[Graph]:
    """OC20-S2EF-*shaped* workload: catalyst-slab-like configurations whose
    node-count and degree distributions match the real benchmark target
    (BASELINE.md north star; the dataset itself cannot be downloaded in this
    image). Sizes are lognormal with mean ~73 atoms clipped to [20, 225]
    (the OC20 slab range); positions are FCC-packed at a metallic lattice
    constant so ``radius``/``max_neighbours`` produce the capped ~20-degree
    graphs of the SC25 production config
    (reference: examples/multibranch/multibranch_GFM260_SC25.json).
    Targets are physically-consistent LJ energies (graph) and forces (node);
    the node feature table is [Z, x, y, z] (input_dim 4, matching the SC25
    Variables_of_interest).
    """
    rng = np.random.default_rng(seed)
    mu = np.log(mean_atoms) - 0.35**2 / 2.0
    zs = np.array([1, 6, 8, 13, 26, 29, 46, 78])  # adsorbate + catalyst metals
    a = lattice_constant
    # FCC basis
    basis = np.array(
        [[0, 0, 0], [0.5, 0.5, 0], [0.5, 0, 0.5], [0, 0.5, 0.5]], np.float64
    )
    d_nn = a / np.sqrt(2.0)
    sigma = d_nn / 2.0 ** (1.0 / 6.0)  # LJ minimum at the nn distance
    graphs: List[Graph] = []
    for _ in range(number_configurations):
        n = int(np.clip(rng.lognormal(mu, 0.35), min_atoms, max_atoms))
        side = int(np.ceil((n / 4.0) ** (1.0 / 3.0))) + 1
        cells = np.array(
            [(x, y, z) for z in range(side) for y in range(side) for x in range(side)],
            np.float64,
        )
        pos = (cells[:, None, :] + basis[None, :, :]).reshape(-1, 3) * a
        pos = pos[:n] + rng.uniform(-jitter, jitter, (n, 3))
        senders, receivers = radius_graph(pos, radius, max_neighbours)
        senders, receivers = _symmetrize_edges(senders, receivers)
        energy, forces = _lj_targets(pos, senders, receivers, 1.0, sigma)
        z = rng.choice(zs, size=n).astype(np.int32)
        x = np.concatenate([z[:, None].astype(np.float32), pos.astype(np.float32)], axis=1)
        graphs.append(
            Graph(
                x=x,
                pos=pos.astype(np.float32),
                senders=senders,
                receivers=receivers,
                graph_targets={"energy": np.asarray([energy / n], np.float32)},
                node_targets={"forces": forces.astype(np.float32)},
                z=z,
            )
        )
    return graphs


def md17_shaped_dataset(
    number_configurations: int = 256,
    jitter: float = 0.12,
    radius: float = 5.0,
    max_neighbours: int = 32,
    seed: int = 7,
) -> List[Graph]:
    """MD17-(aspirin)-*shaped* workload: one fixed 21-atom molecule (the
    aspirin C9H8O4 composition) whose configurations are thermal perturbations
    of a common template — the structure of the real MD17 benchmark
    (BASELINE.md; reference: examples/md17). Targets are LJ energies/forces
    evaluated on each perturbed geometry, so force MAE measured on this task
    exercises exactly the energy+force training path at MD17's scale.
    """
    rng = np.random.default_rng(seed)
    z = np.array([6] * 9 + [1] * 8 + [8] * 4, np.int32)  # C9 H8 O4
    n = z.shape[0]
    # fixed template: min-distance rejection sampling inside a molecule-size ball
    template = np.zeros((n, 3))
    placed = 1
    while placed < n:
        cand = rng.uniform(-3.2, 3.2, 3)
        if np.linalg.norm(cand) > 3.4:
            continue
        if np.min(np.linalg.norm(template[:placed] - cand, axis=1)) > 1.25:
            template[placed] = cand
            placed += 1
    graphs: List[Graph] = []
    # Boltzmann-style acceptance (round 5): thermal sampling never visits
    # the LJ repulsive wall, but isotropic jitter does — measured on the
    # unfiltered generator, 17% of draws contained a near-contact pair with
    # per-atom |F| > 10 (up to ~250, vs a 0.59 mean |component|). Those
    # samples dominate any force objective: across a recipe sweep NO model
    # family learned forces (corr ~0.02). Rejecting draws whose max
    # per-atom |force| exceeds ``force_cap`` keeps ~3/4 of draws and
    # restores the near-equilibrium force distribution real MD17
    # trajectories have (a Boltzmann ensemble suppresses the wall
    # exponentially). Deterministic: same rng stream, draws until accepted.
    force_cap = 5.0
    attempts = 0
    max_attempts = 100 * number_configurations
    while len(graphs) < number_configurations:
        attempts += 1
        if attempts > max_attempts:
            # a jitter large enough to put ~every draw inside the LJ wall
            # must fail loudly, not spin forever
            raise ValueError(
                f"md17_shaped_dataset: acceptance rate "
                f"{len(graphs)}/{attempts} too low for jitter={jitter} "
                f"(force cap {force_cap}); reduce jitter"
            )
        pos = template + rng.normal(0.0, jitter, (n, 3))
        senders, receivers = radius_graph(pos, radius, max_neighbours)
        senders, receivers = _symmetrize_edges(senders, receivers)
        energy, forces = _lj_targets(pos, senders, receivers, 0.2, 1.1)
        if float(np.abs(forces).max()) > force_cap:
            continue
        graphs.append(
            Graph(
                x=z[:, None].astype(np.float32),
                pos=pos.astype(np.float32),
                senders=senders,
                receivers=receivers,
                graph_targets={"energy": np.asarray([energy], np.float32)},
                node_targets={"forces": forces.astype(np.float32)},
                z=z.copy(),
            )
        )
    # reference-energy centering (forces invariant)
    e_mean = float(np.mean([g.graph_targets["energy"][0] for g in graphs]))
    for g in graphs:
        g.graph_targets["energy"] = (g.graph_targets["energy"] - e_mean).astype(
            np.float32
        )
    return graphs


def qm9_shaped_dataset(
    number_configurations: int = 1000,
    radius: float = 7.0,
    max_neighbours: int = 5,
    seed: int = 0,
) -> List[Graph]:
    """QM9-*shaped* workload: small organic molecules with the size and
    composition statistics of the real QM9 benchmark (3-29 atoms, elements
    H/C/N/O/F, ~18 atoms on average), which cannot be downloaded in this
    image. Mirrors the reference example's data contract
    (examples/qm9/qm9.py:20-34): node feature table = [Z], graph feature
    table = [free_energy per atom] — a physically-consistent closed-form
    LJ energy so the target is learnable from geometry.
    """
    rng = np.random.default_rng(seed)
    graphs: List[Graph] = []
    heavy_choices = np.array([6, 7, 8, 9])  # C N O F
    heavy_probs = np.array([0.72, 0.12, 0.13, 0.03])
    for _ in range(number_configurations):
        n_heavy = int(rng.integers(1, 10))  # QM9: up to 9 heavy atoms
        # QM9's smallest molecules have 3 atoms (e.g. water): keep >= 2
        # hydrogens on a lone heavy atom so every graph has edges
        n_h = int(np.clip(rng.poisson(1.3 * n_heavy), 2 if n_heavy < 2 else 0, 20))
        z = np.concatenate(
            [
                rng.choice(heavy_choices, size=n_heavy, p=heavy_probs),
                np.ones(n_h, np.int64),
            ]
        ).astype(np.int32)
        n = z.shape[0]
        pos = grow_molecule(rng, n)
        z = z[: pos.shape[0]]
        n = pos.shape[0]
        senders, receivers = radius_graph(pos, radius, max_neighbours)
        senders, receivers = _symmetrize_edges(senders, receivers)
        energy, _ = _lj_targets(pos, senders, receivers, 0.15, 1.2)
        graphs.append(
            Graph(
                x=z[:, None].astype(np.float32),
                pos=pos.astype(np.float32),
                senders=senders,
                receivers=receivers,
                graph_y=np.asarray([energy / n], np.float32),
                z=z.copy(),
            )
        )
    return graphs


def mptrj_shaped_dataset(
    number_configurations: int = 128,
    radius: float = 5.0,
    max_neighbours: int = 20,
    seed: int = 23,
) -> List[Graph]:
    """MPTrj-*shaped* workload: perturbed periodic crystals with varied
    lattices, compositions, and cell sizes — the structure of the
    Materials-Project-trajectory benchmark the reference trains MACE/GFM
    models on (reference: examples/mptrj; the real download is unavailable
    in this image). Each sample is a BCC/FCC/SC supercell with a random
    binary composition, thermal rattling, PBC radius-graph edges with shift
    vectors, and physically-consistent LJ energy (graph, per atom) and
    force (node) targets evaluated on the periodic displacements.
    """
    rng = np.random.default_rng(seed)
    bases = {
        "sc": np.zeros((1, 3)),
        "bcc": np.array([[0, 0, 0], [0.5, 0.5, 0.5]], np.float64),
        "fcc": np.array(
            [[0, 0, 0], [0.5, 0.5, 0], [0.5, 0, 0.5], [0, 0.5, 0.5]], np.float64
        ),
    }
    element_pool = np.array([3, 8, 13, 14, 22, 26, 28, 29])  # Li O Al Si Ti Fe Ni Cu
    graphs: List[Graph] = []
    for _ in range(number_configurations):
        kind = ("sc", "bcc", "fcc")[int(rng.integers(3))]
        basis = bases[kind]
        a = float(rng.uniform(3.4, 4.4))
        reps = int(rng.integers(2, 4))
        frac = supercell_frac(basis, reps)
        cell = np.diag([a * reps] * 3)
        pos = frac @ cell + rng.normal(0.0, 0.08, (frac.shape[0], 3))
        n = pos.shape[0]
        zs = rng.choice(element_pool, size=2, replace=False)
        z = np.where(rng.random(n) < rng.uniform(0.2, 0.8), zs[0], zs[1]).astype(
            np.int32
        )
        senders, receivers, shifts = radius_graph_pbc(
            pos, cell, radius, max_neighbours
        )
        # LJ on the shift-corrected periodic displacements, via the shared
        # helper whose halving/receiver-only accumulation keeps F = -dE/dpos
        # exact on symmetric edge lists
        sigma = a / np.sqrt(2.0) / 2.0 ** (1.0 / 6.0)
        energy, forces = _lj_targets(
            pos, senders, receivers, 0.5, sigma, shifts=shifts
        )
        graphs.append(
            Graph(
                x=z[:, None].astype(np.float32),
                pos=pos.astype(np.float32),
                senders=senders,
                receivers=receivers,
                edge_shifts=shifts.astype(np.float32),
                cell=cell.astype(np.float32),
                graph_targets={"energy": np.asarray([energy / n], np.float32)},
                node_targets={"forces": forces.astype(np.float32)},
                z=z.copy(),
            )
        )
    return graphs


def lennard_jones_dataset(
    number_configurations: int = 200,
    supercell: Sequence[int] = (2, 2, 2),
    spacing: float = 1.2,
    jitter: float = 0.08,
    radius: float = 2.5,
    max_neighbours: int = 32,
    epsilon: float = 1.0,
    sigma: float = 1.0,
    seed: int = 17,
    center_energies: bool = True,
) -> List[Graph]:
    """Perturbed-lattice configurations with exact Lennard-Jones energies and
    analytic forces, for energy+force (``compute_grad_energy``) training.

    Behavioral analog of the reference's ``examples/LennardJones`` dataset
    (examples/LennardJones/LJ_data.py): graph target ``energy`` (total LJ
    energy within the cutoff) and node target ``forces`` (−∇E, closed form).

    ``center_energies`` subtracts the dataset-mean per-atom energy (the
    standard atomic-reference-energy shift; forces are invariant to it).
    """
    rng = np.random.default_rng(seed)
    graphs: List[Graph] = []
    for _ in range(number_configurations):
        base = np.array(
            [
                (x, y, z)
                for x in range(supercell[0])
                for y in range(supercell[1])
                for z in range(supercell[2])
            ],
            np.float64,
        )
        pos = base * spacing + rng.uniform(-jitter, jitter, base.shape)
        senders, receivers = radius_graph(pos, radius, max_neighbours)
        senders, receivers = _symmetrize_edges(senders, receivers)
        energy, forces = _lj_targets(pos, senders, receivers, epsilon, sigma)
        graphs.append(
            Graph(
                x=np.ones((pos.shape[0], 1), np.float32),
                pos=pos.astype(np.float32),
                senders=senders,
                receivers=receivers,
                graph_targets={"energy": np.asarray([energy], np.float32)},
                node_targets={"forces": forces.astype(np.float32)},
                z=np.ones((pos.shape[0],), np.int32),
            )
        )
    if center_energies:
        e_per_atom = float(
            np.mean(
                [g.graph_targets["energy"][0] / g.num_nodes for g in graphs]
            )
        )
        for g in graphs:
            g.graph_targets["energy"] = (
                g.graph_targets["energy"] - e_per_atom * g.num_nodes
            ).astype(np.float32)
    return graphs
