"""Deterministic synthetic graph dataset for CI-grade accuracy tests.

Behavioral equivalent of the reference's test fixture generator
(tests/deterministic_graph_data.py:20-66 and create_configuration :68-220):
BCC-lattice configurations with random per-node types and closed-form targets

    out1 = knn_smooth(type)        (k-nearest-neighbour average, simulating MP)
    out2 = out1**2 + type
    out3 = out1**3
    graph_target = sum(out1) + sum(out2) + sum(out3)

The node feature *table* exposed per node is ``[type, out2, out3]`` matching
the reference CI configs' column selection (tests/inputs/ci.json node_features
column_index [0, 6, 7]); the single graph feature is the total sum.
``linear_only=True`` mirrors the reference flag: out1 = type, graph target =
sum(out1).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .graph import Graph
from .neighbors import radius_graph


def knn_average(pos: np.ndarray, values: np.ndarray, k: int) -> np.ndarray:
    """Average of the k nearest samples (incl. self), like KNeighborsRegressor."""
    from scipy.spatial import cKDTree

    tree = cKDTree(pos)
    _, idx = tree.query(pos, k=k)
    if k == 1:
        idx = idx[:, None]
    return values[idx].mean(axis=1)


def deterministic_graph_dataset(
    number_configurations: int = 500,
    unit_cell_x_range: Sequence[int] = (1, 3),
    unit_cell_y_range: Sequence[int] = (1, 3),
    unit_cell_z_range: Sequence[int] = (1, 2),
    number_types: int = 3,
    types: Optional[Sequence[int]] = None,
    number_neighbors: int = 2,
    linear_only: bool = False,
    radius: float = 2.0,
    max_neighbours: int = 100,
    seed: int = 97,
) -> List[Graph]:
    """Generate BCC configurations with closed-form targets as ``Graph`` list.

    Unlike the reference (which writes LSMS-style text files and re-reads them
    through the raw loader, tests/test_graphs.py:91-126) this builds the graphs
    in memory; the text round-trip is exercised separately by the raw-loader
    tests.
    """
    if types is None:
        types = list(range(number_types))
    rng = np.random.default_rng(seed)
    graphs: List[Graph] = []
    for _ in range(number_configurations):
        uc = (
            rng.integers(unit_cell_x_range[0], unit_cell_x_range[1]),
            rng.integers(unit_cell_y_range[0], unit_cell_y_range[1]),
            rng.integers(unit_cell_z_range[0], unit_cell_z_range[1]),
        )
        graphs.append(
            _configuration(rng, uc, types, number_neighbors, linear_only, radius, max_neighbours)
        )
    return graphs


def bcc_positions(uc_x: int, uc_y: int, uc_z: int) -> np.ndarray:
    """Body-centered-cubic positions: corner + center atom per unit cell."""
    corners = np.array(
        [(x, y, z) for x in range(uc_x) for y in range(uc_y) for z in range(uc_z)],
        np.float64,
    )
    pos = np.empty((2 * corners.shape[0], 3), np.float64)
    pos[0::2] = corners
    pos[1::2] = corners + 0.5
    return pos


def _configuration(rng, uc, types, number_neighbors, linear_only, radius, max_neighbours):
    pos = bcc_positions(*uc)
    n = pos.shape[0]
    node_type = rng.integers(min(types), max(types) + 1, (n, 1)).astype(np.float64)

    if linear_only:
        out1 = node_type.copy()
    else:
        out1 = knn_average(pos, node_type, number_neighbors)
    out2 = out1**2 + node_type
    out3 = out1**3

    if linear_only:
        total = out1.sum(keepdims=False)
        x_table = node_type.astype(np.float32)
    else:
        total = out1.sum() + out2.sum() + out3.sum()
        # columns as selected by ci.json: [type, out2, out3]
        x_table = np.concatenate([node_type, out2, out3], axis=1).astype(np.float32)

    senders, receivers = radius_graph(pos, radius, max_neighbours)
    return Graph(
        x=x_table,
        pos=pos.astype(np.float32),
        senders=senders,
        receivers=receivers,
        graph_y=np.asarray([float(total)], np.float32),
        z=node_type[:, 0].astype(np.int32),
    )


def lennard_jones_dataset(
    number_configurations: int = 200,
    supercell: Sequence[int] = (2, 2, 2),
    spacing: float = 1.2,
    jitter: float = 0.08,
    radius: float = 2.5,
    max_neighbours: int = 32,
    epsilon: float = 1.0,
    sigma: float = 1.0,
    seed: int = 17,
    center_energies: bool = True,
) -> List[Graph]:
    """Perturbed-lattice configurations with exact Lennard-Jones energies and
    analytic forces, for energy+force (``compute_grad_energy``) training.

    Behavioral analog of the reference's ``examples/LennardJones`` dataset
    (examples/LennardJones/LJ_data.py): graph target ``energy`` (total LJ
    energy within the cutoff) and node target ``forces`` (−∇E, closed form).

    ``center_energies`` subtracts the dataset-mean per-atom energy (the
    standard atomic-reference-energy shift; forces are invariant to it).
    """
    rng = np.random.default_rng(seed)
    graphs: List[Graph] = []
    for _ in range(number_configurations):
        base = np.array(
            [
                (x, y, z)
                for x in range(supercell[0])
                for y in range(supercell[1])
                for z in range(supercell[2])
            ],
            np.float64,
        )
        pos = base * spacing + rng.uniform(-jitter, jitter, base.shape)
        senders, receivers = radius_graph(pos, radius, max_neighbours)
        # symmetrize after any per-receiver neighbour capping: every pair must
        # appear in both directions or the 0.5-per-edge energy sum and the
        # receiver-side force accumulation break Newton's third law
        pairs = set(zip(senders.tolist(), receivers.tolist()))
        pairs |= {(i, j) for (j, i) in pairs}
        senders, receivers = map(
            lambda a: np.asarray(a, np.int32), zip(*sorted(pairs))
        )
        diff = pos[receivers] - pos[senders]  # r_i - r_j for edge j->i
        r = np.linalg.norm(diff, axis=1)
        s6 = (sigma / r) ** 6
        s12 = s6**2
        # each pair appears twice (j->i and i->j): half the pair energy per edge
        energy = float(np.sum(0.5 * 4.0 * epsilon * (s12 - s6)))
        # F_i = sum_j 24 eps (2 s12 - s6) / r^2 * (r_i - r_j)
        coef = 24.0 * epsilon * (2.0 * s12 - s6) / r**2
        forces = np.zeros_like(pos)
        np.add.at(forces, receivers, coef[:, None] * diff)
        graphs.append(
            Graph(
                x=np.ones((pos.shape[0], 1), np.float32),
                pos=pos.astype(np.float32),
                senders=senders,
                receivers=receivers,
                graph_targets={"energy": np.asarray([energy], np.float32)},
                node_targets={"forces": forces.astype(np.float32)},
                z=np.ones((pos.shape[0],), np.int32),
            )
        )
    if center_energies:
        e_per_atom = float(
            np.mean(
                [g.graph_targets["energy"][0] / g.num_nodes for g in graphs]
            )
        )
        for g in graphs:
            g.graph_targets["energy"] = (
                g.graph_targets["energy"] - e_per_atom * g.num_nodes
            ).astype(np.float32)
    return graphs
