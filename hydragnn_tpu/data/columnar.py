"""Sharded columnar graph dataset — the ADIOS2 analog.

The reference stores datasets as ADIOS .bp files: for every sample key, all
samples' arrays are concatenated along the ragged axis with per-sample
``variable_count``/offset tables, written collectively over MPI and read
back per-sample, optionally into node-local shared memory
(reference: hydragnn/utils/datasets/adiosdataset.py:91-332 writer,
:594-689 shmem/ddstore read modes, :825-905 per-sample reconstruction).

TPU-native redesign, same ragged layout without the ADIOS C++ dependency:

- one directory per dataset; every field is a flat binary file (`<field>.bin`,
  C-order, concatenated along axis 0) plus an int64 per-sample counts table;
  `meta.json` records dtypes, trailing shapes and attributes;
- multi-process writes are shard subdirectories (`shard00000/…`), one per
  writer process — no collective I/O needed; the reader concatenates shards
  in shard order (per-host sharded writes suit TPU pods, where each host
  feeds its own devices over PCIe and there is no MPI plane);
- read modes: ``mmap`` (lazy np.memmap slices — the ADIOS direct-read mode),
  ``preload`` (everything in RAM), and ``shmem`` (one copy per host in POSIX
  shared memory, attached by every loader process — adiosdataset.py:594-644).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .datasets import AbstractBaseDataset
from .graph import Graph

_OPTIONAL_FIELDS = ("edge_attr", "edge_shifts", "pe", "rel_pe", "z", "graph_y", "cell")


def _graph_fields(g: Graph) -> Dict[str, np.ndarray]:
    out = {
        "x": np.asarray(g.x),
        "pos": np.asarray(g.pos),
        "senders": np.asarray(g.senders),
        "receivers": np.asarray(g.receivers),
        "dataset_id": np.asarray([g.dataset_id], np.int64),
    }
    for f in _OPTIONAL_FIELDS:
        v = getattr(g, f)
        if v is not None:
            out[f] = np.asarray(v)
    for name, v in (g.graph_targets or {}).items():
        out[f"graph_targets/{name}"] = np.atleast_1d(np.asarray(v))
    for name, v in (g.node_targets or {}).items():
        out[f"node_targets/{name}"] = np.asarray(v)
    return out


class ColumnarWriter:
    """Accumulate graphs and write one shard of a columnar dataset.

    ``shard_index`` plays the role of the MPI rank in the reference's
    collective AdiosWriter (adiosdataset.py:91-332): each writer process
    owns its own shard directory and no coordination is needed.
    """

    def __init__(self, path: str, shard_index: int = 0):
        self.path = path
        self.shard_dir = os.path.join(path, f"shard{shard_index:05d}")
        self._fields: Dict[str, List[np.ndarray]] = {}
        self._strings: Dict[str, List[str]] = {}
        self._attrs: Dict[str, Any] = {}
        self._n = 0

    def add(self, graphs) -> "ColumnarWriter":
        if isinstance(graphs, Graph):
            graphs = [graphs]
        for g in graphs:
            fields = _graph_fields(g)
            if self._n == 0 and not self._fields:
                known = set(fields)
            else:
                known = set(self._fields)
                if set(fields) != known:
                    raise ValueError(
                        f"inconsistent fields: {sorted(set(fields) ^ known)}"
                    )
            for k, v in fields.items():
                self._fields.setdefault(k, []).append(v)
            self._n += 1
        return self

    def add_global(self, name: str, value: Any) -> None:
        """(reference: AdiosWriter.add_global, adiosdataset.py:115-126)"""
        self._attrs[name] = value

    def add_string(self, name: str, values) -> "ColumnarWriter":
        """Per-sample ragged strings (reference: AdiosWriter's SMILES char
        packing with per-sample counts, adiosdataset.py:334-389). One value
        per added graph; stored as a UTF-8 uint8 column with the same
        counts/offset layout every array field uses."""
        if isinstance(values, str):
            values = [values]
        self._strings.setdefault(name, []).extend(str(v) for v in values)
        return self

    def save(self) -> str:
        os.makedirs(self.shard_dir, exist_ok=True)
        meta: Dict[str, Any] = {"num_samples": self._n, "fields": {}, "attrs": {}}
        # Merge string columns into a LOCAL map so save() stays idempotent:
        # mutating self._fields here would make a second save() see its own
        # "strings/..." columns and raise (or double-encode after add_string).
        merged: Dict[str, list] = dict(self._fields)
        for name, vals in self._strings.items():
            if len(vals) != self._n:
                raise ValueError(
                    f"string column {name!r} has {len(vals)} values for "
                    f"{self._n} samples"
                )
            key = f"strings/{name}"
            if key in merged:
                raise ValueError(f"duplicate column {key!r}")
            merged[key] = [
                np.frombuffer(v.encode("utf-8"), np.uint8) for v in vals
            ]
        for k, arrs in merged.items():
            a0 = arrs[0]
            suffix = list(a0.shape[1:])
            dtype = np.dtype(a0.dtype)
            if any(list(a.shape[1:]) != suffix or a.dtype != dtype for a in arrs):
                raise ValueError(f"field {k!r} has inconsistent trailing shape/dtype")
            counts = np.asarray([a.shape[0] for a in arrs], np.int64)
            flat = (
                np.concatenate(arrs, axis=0)
                if counts.sum() > 0
                else np.zeros((0, *suffix), dtype)
            )
            safe = k.replace("/", "__")
            flat.tofile(os.path.join(self.shard_dir, f"{safe}.bin"))
            np.save(os.path.join(self.shard_dir, f"{safe}.counts.npy"), counts)
            meta["fields"][k] = {"dtype": dtype.str, "suffix": suffix}
        for name, v in self._attrs.items():
            # np.generic covers numpy scalars (e.g. np.float32 minmax stats),
            # which json.dump rejects just like ndarrays
            meta["attrs"][name] = (
                v.tolist() if isinstance(v, (np.ndarray, np.generic)) else v
            )
        with open(os.path.join(self.shard_dir, "meta.json"), "w") as f:
            json.dump(meta, f)
        return self.shard_dir


class ColumnarDataset(AbstractBaseDataset):
    """Read a (multi-shard) columnar dataset as ``Graph`` samples.

    modes (reference read modes, adiosdataset.py:494-689):
    - ``mmap``: np.memmap per field, per-sample slices on demand;
    - ``preload``: load every field fully into process RAM;
    - ``shmem``: materialize each field once per host in POSIX shared memory
      (named after the dataset path) and attach read-only — many loader
      processes share one copy, like the reference's node-local shmem mode.
    """

    def __init__(self, path: str, mode: str = "mmap"):
        assert mode in ("mmap", "preload", "shmem"), mode
        self.path = path
        self.mode = mode
        self._shm_names: List[str] = []
        shards = sorted(
            d for d in os.listdir(path) if d.startswith("shard")
        )
        if not shards:
            raise FileNotFoundError(f"no shards under {path}")
        self._shards = []
        self.attrs: Dict[str, Any] = {}
        total = 0
        for s in shards:
            sdir = os.path.join(path, s)
            meta = json.load(open(os.path.join(sdir, "meta.json")))
            self.attrs.update(meta.get("attrs", {}))
            fields = {}
            for k, fmeta in meta["fields"].items():
                safe = k.replace("/", "__")
                counts = np.load(os.path.join(sdir, f"{safe}.counts.npy"))
                offsets = np.concatenate([[0], np.cumsum(counts)])
                arr = self._open_array(
                    os.path.join(sdir, f"{safe}.bin"),
                    np.dtype(fmeta["dtype"]),
                    tuple(fmeta["suffix"]),
                )
                fields[k] = (arr, counts, offsets)
            self._shards.append((total, meta["num_samples"], fields))
            total += meta["num_samples"]
        self._total = total

    def _open_array(self, path: str, dtype: np.dtype, suffix: tuple) -> np.ndarray:
        nbytes = os.path.getsize(path)
        width = int(np.prod(suffix)) if suffix else 1
        n = nbytes // (dtype.itemsize * max(width, 1))
        shape = (n, *suffix)
        if n == 0:  # a shard can legitimately have zero rows for a field
            return np.zeros(shape, dtype)
        if self.mode == "mmap":
            return np.memmap(path, dtype=dtype, mode="r", shape=shape)
        if self.mode == "preload":
            return np.fromfile(path, dtype=dtype).reshape(shape)
        arr, name = _shared_memory_array(path, dtype, shape)
        self._shm_names.append(name)
        return arr

    def close(self, unlink: bool = False) -> None:
        """Release the shared-memory segments backing this dataset (mirrors
        DDStore.close, data/ddstore.py). The creating process unlinks its
        segments so regenerated datasets don't accumulate /dev/shm residency;
        attachers only detach unless ``unlink=True`` forces removal. After
        close, arrays previously returned by ``get`` must not be used."""
        import gc

        # the dataset's own field arrays are np.frombuffer views into shm.buf;
        # they must be dropped before SharedMemory.close() or it raises
        # BufferError ("cannot close: exported pointers exist")
        self._shards = []
        gc.collect()
        for name in self._shm_names:
            entry = _SHM_CACHE.pop(name, None)
            if entry is None:
                continue
            shm, created = entry
            # unlink first so /dev/shm residency is reclaimed even if a caller
            # still holds array views (the OS frees the pages once every
            # attached process exits)
            if created or unlink:
                try:
                    shm.unlink()
                except FileNotFoundError:
                    pass
            try:
                shm.close()
            except BufferError:
                pass  # caller-held views keep the mapping alive until GC
        self._shm_names = []

    def __len__(self) -> int:
        return self._total

    def get(self, idx: int) -> Graph:
        if idx < 0:
            idx += self._total
        for start, n, fields in self._shards:
            if start <= idx < start + n:
                return self._build(fields, idx - start)
        raise IndexError(idx)

    def string_columns(self) -> List[str]:
        """Names of ragged per-sample string columns (ADIOS SMILES-packing
        analog, adiosdataset.py:334-389)."""
        names = set()
        for _, _, fields in self._shards:
            for k in fields:
                if k.startswith("strings/"):
                    names.add(k.split("/", 1)[1])
        return sorted(names)

    def get_string(self, name: str, idx: int) -> str:
        """Per-sample string from column ``name`` (UTF-8 decoded)."""
        if idx < 0:
            idx += self._total
        key = f"strings/{name}"
        for start, n, fields in self._shards:
            if start <= idx < start + n:
                if key not in fields:
                    raise KeyError(
                        f"no string column {name!r}; have {self.string_columns()}"
                    )
                arr, counts, offsets = fields[key]
                i = idx - start
                return bytes(
                    np.array(arr[offsets[i] : offsets[i + 1]])
                ).decode("utf-8")
        raise IndexError(idx)

    def _build(self, fields, i: int) -> Graph:
        def take(k):
            arr, counts, offsets = fields[k]
            return np.array(arr[offsets[i] : offsets[i + 1]])

        graph_targets = {}
        node_targets = {}
        opt: Dict[str, Optional[np.ndarray]] = {f: None for f in _OPTIONAL_FIELDS}
        for k in fields:
            if k.startswith("strings/"):
                continue  # ragged string columns are read via get_string
            if k.startswith("graph_targets/"):
                graph_targets[k.split("/", 1)[1]] = take(k)
            elif k.startswith("node_targets/"):
                node_targets[k.split("/", 1)[1]] = take(k)
            elif k in opt:
                opt[k] = take(k)
        z = opt.pop("z", None)
        return Graph(
            x=take("x"),
            pos=take("pos"),
            senders=take("senders").astype(np.int32),
            receivers=take("receivers").astype(np.int32),
            dataset_id=int(take("dataset_id")[0]),
            graph_targets=graph_targets or None,
            node_targets=node_targets or None,
            z=z if z is None else z.astype(np.int32),
            **{k: v for k, v in opt.items() if k != "graph_y"},
            graph_y=opt.get("graph_y"),
        )


# name -> (SharedMemory, created_by_this_process)
_SHM_CACHE: Dict[str, Any] = {}


def _shared_memory_array(path: str, dtype: np.dtype, shape: tuple):
    """One copy per host in POSIX shared memory, attached by name
    (reference: adiosdataset.py:594-644 SharedMemory + local-comm bcast).

    The segment name is a content-stable digest of the absolute path (str
    ``hash()`` is salted per process and would defeat sharing). The creator
    writes the data then flips a trailing sentinel byte; attachers spin on
    the sentinel so a partially copied buffer is never observed — the role
    the reference's local-comm barrier plays.
    """
    import hashlib
    import time
    from multiprocessing import shared_memory

    # key the segment on path + size + mtime so a regenerated dataset gets
    # a fresh segment instead of serving (or crashing on) a stale one
    st = os.stat(path)
    key = f"{os.path.abspath(path)}:{st.st_size}:{st.st_mtime_ns}"
    name = "hgnn_" + hashlib.sha1(key.encode()).hexdigest()[:24]
    nbytes = max(int(np.prod(shape)) * dtype.itemsize, 1)
    if name in _SHM_CACHE:
        shm, _ = _SHM_CACHE[name]
    else:
        created = False
        try:
            shm = shared_memory.SharedMemory(
                name=name, create=True, size=nbytes + 1
            )
            created = True
            data = np.fromfile(path, dtype=dtype).reshape(shape)
            np.frombuffer(shm.buf, dtype=dtype, count=data.size)[:] = data.ravel()
            shm.buf[nbytes] = 1  # readiness sentinel, set last
        except FileExistsError:
            shm = shared_memory.SharedMemory(name=name, create=False)
            # CPython's resource tracker registers attached segments too (on
            # <3.13) and would unlink them when *this* process exits, pulling
            # the segment out from under sibling loader processes — only the
            # creator should own cleanup
            try:
                from multiprocessing import resource_tracker

                resource_tracker.unregister(shm._name, "shared_memory")
            except Exception:
                pass
            deadline = time.monotonic() + 300.0
            while shm.buf[nbytes] != 1:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"shared segment {name!r} never became ready — a "
                        "creator likely crashed mid-copy; remove "
                        f"/dev/shm/{name} and retry"
                    )
                time.sleep(0.05)
        _SHM_CACHE[name] = (shm, created)
    arr = np.frombuffer(shm.buf, dtype=dtype, count=int(np.prod(shape))).reshape(
        shape
    )
    return arr, name
