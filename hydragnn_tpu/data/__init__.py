from .graph import Graph, GraphBatch, PadSpec, batch_graphs, batch_graphs_np, graph_batch_from_np
from .neighbors import radius_graph, radius_graph_pbc, edge_vectors_and_lengths
from .pipeline import (
    GraphLoader,
    branch_sample_weights,
    MinMax,
    VariablesOfInterest,
    extract_variables,
    select_input_columns,
    split_dataset,
)
from .columnar import ColumnarDataset, ColumnarWriter
from .datasets import AbstractBaseDataset, SimplePickleDataset, SimplePickleWriter
from .ddstore import (
    DDStore,
    DistDataset,
    MultiHostDistDataset,
    RemoteStoreClient,
)
from .descriptors import atomic_descriptors, smiles_to_graph
from .xyz2mol import perceive_molecule, xyz_to_graph
from .raw import (
    finalize_graphs,
    load_cfg_file,
    load_lsms_file,
    load_raw_dataset,
    load_xyz_file,
)
from .lappe import add_dataset_pe, add_graph_pe, laplacian_pe
from .lsms import (
    compositional_histogram_cutoff,
    compute_formation_enthalpy,
    convert_total_energy_to_formation_gibbs,
    mixing_entropy,
)
from .transforms import (
    add_edge_lengths,
    apply_dataset_transforms,
    wants_transforms,
    add_point_pair_features,
    add_spherical_descriptors,
    apply_post_edge_transforms,
    apply_pre_edge_transforms,
    estimate_normals,
    normalize_edge_attr,
    normalize_rotation,
    normalize_rotation_pos,
)
from .synthetic import (
    deterministic_graph_dataset,
    lennard_jones_dataset,
    md17_shaped_dataset,
    oc20_shaped_dataset,
    qm9_shaped_dataset,
)

__all__ = [
    "AbstractBaseDataset",
    "ColumnarDataset",
    "ColumnarWriter",
    "DDStore",
    "DistDataset",
    "MultiHostDistDataset",
    "RemoteStoreClient",
    "SimplePickleDataset",
    "SimplePickleWriter",
    "Graph",
    "GraphBatch",
    "PadSpec",
    "batch_graphs",
    "batch_graphs_np",
    "graph_batch_from_np",
    "radius_graph",
    "radius_graph_pbc",
    "edge_vectors_and_lengths",
    "GraphLoader",
    "branch_sample_weights",
    "MinMax",
    "VariablesOfInterest",
    "extract_variables",
    "select_input_columns",
    "split_dataset",
    "deterministic_graph_dataset",
    "lennard_jones_dataset",
    "md17_shaped_dataset",
    "oc20_shaped_dataset",
    "qm9_shaped_dataset",
    "atomic_descriptors",
    "smiles_to_graph",
    "perceive_molecule",
    "xyz_to_graph",
    "finalize_graphs",
    "load_cfg_file",
    "load_lsms_file",
    "load_raw_dataset",
    "load_xyz_file",
    "add_edge_lengths",
    "compositional_histogram_cutoff",
    "compute_formation_enthalpy",
    "convert_total_energy_to_formation_gibbs",
    "mixing_entropy",
    "apply_dataset_transforms",
    "wants_transforms",
    "add_point_pair_features",
    "add_spherical_descriptors",
    "apply_post_edge_transforms",
    "apply_pre_edge_transforms",
    "estimate_normals",
    "normalize_edge_attr",
    "normalize_rotation",
    "normalize_rotation_pos",
]
