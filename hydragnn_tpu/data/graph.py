"""Static-shape graph batch containers for TPU.

The reference (HydraGNN) batches graphs with PyG's ragged ``Batch`` object and
moves it host->device every step (reference: hydragnn/train/train_validate_test.py:514).
On TPU every array inside ``jit`` must have a static shape, so this module
replaces the ragged batch with a *padded* batch:

- all graphs in a batch are concatenated (nodes stacked, edges stacked with
  index offsets) exactly like PyG batching,
- the result is padded up to a fixed ``PadSpec`` (n_nodes, n_edges, n_graphs),
- padding nodes/edges are assigned to one trailing *dummy graph* whose mask is
  False, so segment reductions and pooling stay correct without any dynamic
  shapes.

Targets are stored per-head in a dict (graph-level heads: ``[G, d]``;
node-level heads: ``[N, d]``) instead of the reference's packed ``data.y`` +
``y_loc`` index table (reference: hydragnn/preprocess/graph_samples_checks_and_updates.py:493-534);
the packing existed to ship ragged multi-task targets through PyG, which a
static-shape design does not need.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np
from flax import struct

# Names of per-node / per-edge / per-graph optional fields, used by batching.
_NODE_FIELDS = ("x", "pos", "pe", "z")
_EDGE_FIELDS = ("edge_attr", "edge_shifts", "rel_pe")


@dataclasses.dataclass
class Graph:
    """A single host-side graph sample (numpy arrays, ragged shapes).

    Mirrors the information content of a PyG ``Data`` object as produced by the
    reference's serialized loader (hydragnn/preprocess/serialized_dataset_loader.py:110-212).
    """

    x: np.ndarray  # [n, Fx] node input features
    pos: np.ndarray  # [n, 3] positions
    senders: np.ndarray  # [e] int32 message source node
    receivers: np.ndarray  # [e] int32 message destination node
    edge_attr: Optional[np.ndarray] = None  # [e, Fe]
    edge_shifts: Optional[np.ndarray] = None  # [e, 3] PBC cartesian shifts
    pe: Optional[np.ndarray] = None  # [n, pe_dim] Laplacian PE
    rel_pe: Optional[np.ndarray] = None  # [e, pe_dim] |pe_src - pe_dst|
    z: Optional[np.ndarray] = None  # [n] int32 atomic numbers
    graph_y: Optional[np.ndarray] = None  # [Fg] raw graph feature table
    graph_targets: Optional[Dict[str, np.ndarray]] = None  # name -> [d]
    node_targets: Optional[Dict[str, np.ndarray]] = None  # name -> [n, d]
    dataset_id: int = 0
    cell: Optional[np.ndarray] = None  # [3, 3] lattice (PBC only)

    @property
    def num_nodes(self) -> int:
        return int(self.x.shape[0])

    @property
    def num_edges(self) -> int:
        return int(self.senders.shape[0])


@struct.dataclass
class GraphBatch:
    """Device-side padded batch of graphs (a pytree of fixed-shape arrays).

    Shapes: N = padded node count, E = padded edge count, G = padded graph
    count. The last graph slot(s) are dummy graphs holding all padding nodes
    and edges (``graph_mask`` False there).
    """

    # node-level
    x: jnp.ndarray  # [N, Fx] float
    pos: jnp.ndarray  # [N, 3] float
    node_graph: jnp.ndarray  # [N] int32: graph id of each node
    node_mask: jnp.ndarray  # [N] bool
    # edge-level
    senders: jnp.ndarray  # [E] int32
    receivers: jnp.ndarray  # [E] int32
    edge_mask: jnp.ndarray  # [E] bool
    # graph-level
    graph_mask: jnp.ndarray  # [G] bool
    dataset_id: jnp.ndarray  # [G] int32
    # optional channels
    edge_attr: Optional[jnp.ndarray] = None  # [E, Fe]
    edge_shifts: Optional[jnp.ndarray] = None  # [E, 3]
    pe: Optional[jnp.ndarray] = None  # [N, pe_dim]
    rel_pe: Optional[jnp.ndarray] = None  # [E, pe_dim]
    z: Optional[jnp.ndarray] = None  # [N] int32
    # targets: head name -> [G, d] (graph heads) or [N, d] (node heads)
    graph_targets: Dict[str, jnp.ndarray] = struct.field(default_factory=dict)
    node_targets: Dict[str, jnp.ndarray] = struct.field(default_factory=dict)

    @property
    def num_nodes(self) -> int:
        return self.x.shape[0]

    @property
    def num_edges(self) -> int:
        return self.senders.shape[0]

    @property
    def num_graphs(self) -> int:
        return self.graph_mask.shape[0]

    @property
    def num_real_graphs(self) -> jnp.ndarray:
        return jnp.sum(self.graph_mask.astype(jnp.int32))

    @property
    def nodes_per_graph(self) -> jnp.ndarray:
        """[G] number of real nodes in each graph."""
        seg = jnp.zeros((self.num_graphs,), jnp.int32)
        return seg.at[self.node_graph].add(self.node_mask.astype(jnp.int32))


@dataclasses.dataclass(frozen=True)
class PadSpec:
    """Static padding target for a batch. All jit specializations key on this."""

    n_nodes: int
    n_edges: int
    n_graphs: int  # includes the +1 dummy graph slot

    @staticmethod
    def for_dataset(
        graphs: List[Graph],
        batch_size: int,
        node_multiple: int = 8,
        edge_multiple: int = 128,
        slack: float = 1.0,
    ) -> "PadSpec":
        """Choose one spec covering any ``batch_size`` graphs from ``graphs``.

        Uses the max graph size times batch size (exact upper bound for the
        small molecular graphs this framework targets) rounded up to
        TPU-friendly multiples. ``slack`` can trim (<1) toward the sum of the
        largest-k sizes if memory is tight.
        """
        if not graphs:
            raise ValueError("empty dataset")
        n_sizes = sorted((g.num_nodes for g in graphs), reverse=True)
        e_sizes = sorted((g.num_edges for g in graphs), reverse=True)
        k = min(batch_size, len(n_sizes))
        n_bound = int(sum(n_sizes[:k]) * slack) + 1
        e_bound = int(sum(e_sizes[:k]) * slack) + 1
        return PadSpec(
            n_nodes=_round_up(n_bound + 1, node_multiple),
            n_edges=_round_up(e_bound, edge_multiple),
            n_graphs=batch_size + 1,
        )


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _stack_optional(graphs: List[Graph], field: str) -> Optional[np.ndarray]:
    vals = [getattr(g, field) for g in graphs]
    if all(v is None for v in vals):
        return None
    if any(v is None for v in vals):
        raise ValueError(f"field {field!r} present in some graphs but not all")
    return np.concatenate([np.asarray(v) for v in vals], axis=0)


def batch_graphs_np(
    graphs: List[Graph],
    spec: PadSpec,
    np_dtype=np.float32,
) -> Dict[str, np.ndarray]:
    """Concatenate + pad a list of host graphs into flat numpy arrays.

    Padding convention: padding nodes belong to the final (dummy) graph slot,
    padding edges connect the final padding node to itself. Runs entirely on
    host with numpy; ``GraphBatch`` construction from the result is a cheap
    device put.
    """
    G = len(graphs)
    n = sum(g.num_nodes for g in graphs)
    e = sum(g.num_edges for g in graphs)
    if G > spec.n_graphs - 1 or n > spec.n_nodes - 1 or e > spec.n_edges:
        raise ValueError(
            f"batch ({G} graphs, {n} nodes, {e} edges) exceeds pad spec {spec}"
        )

    out: Dict[str, np.ndarray] = {}

    # node features
    for field in _NODE_FIELDS:
        stacked = _stack_optional(graphs, field)
        if stacked is None:
            continue
        if stacked.ndim == 1:
            stacked = stacked[:, None]
        width = stacked.shape[1]
        dtype = np.int32 if field == "z" else np_dtype
        buf = np.zeros((spec.n_nodes, width), dtype)
        buf[:n] = stacked
        out[field] = buf if field != "z" else buf[:, 0]

    # edges with node-index offsets
    senders = np.full((spec.n_edges,), spec.n_nodes - 1, np.int32)
    receivers = np.full((spec.n_edges,), spec.n_nodes - 1, np.int32)
    off = 0
    eoff = 0
    node_graph = np.full((spec.n_nodes,), spec.n_graphs - 1, np.int32)
    for gi, g in enumerate(graphs):
        senders[eoff : eoff + g.num_edges] = g.senders + off
        receivers[eoff : eoff + g.num_edges] = g.receivers + off
        node_graph[off : off + g.num_nodes] = gi
        off += g.num_nodes
        eoff += g.num_edges
    out["senders"] = senders
    out["receivers"] = receivers
    out["node_graph"] = node_graph

    for field in _EDGE_FIELDS:
        stacked = _stack_optional(graphs, field)
        if stacked is None:
            continue
        if stacked.ndim == 1:
            stacked = stacked[:, None]
        buf = np.zeros((spec.n_edges, stacked.shape[1]), np_dtype)
        buf[:e] = stacked
        out[field] = buf

    # masks
    node_mask = np.zeros((spec.n_nodes,), bool)
    node_mask[:n] = True
    edge_mask = np.zeros((spec.n_edges,), bool)
    edge_mask[:e] = True
    graph_mask = np.zeros((spec.n_graphs,), bool)
    graph_mask[:G] = True
    out["node_mask"] = node_mask
    out["edge_mask"] = edge_mask
    out["graph_mask"] = graph_mask

    dataset_id = np.zeros((spec.n_graphs,), np.int32)
    dataset_id[:G] = [g.dataset_id for g in graphs]
    out["dataset_id"] = dataset_id

    # targets
    gt_names = set()
    nt_names = set()
    for g in graphs:
        gt_names.update((g.graph_targets or {}).keys())
        nt_names.update((g.node_targets or {}).keys())
    for name in sorted(gt_names):
        vals = [np.atleast_1d(np.asarray(g.graph_targets[name], np_dtype)) for g in graphs]
        width = vals[0].shape[-1]
        buf = np.zeros((spec.n_graphs, width), np_dtype)
        buf[:G] = np.stack(vals)
        out[f"graph_targets/{name}"] = buf
    for name in sorted(nt_names):
        vals = np.concatenate(
            [np.asarray(g.node_targets[name], np_dtype).reshape(g.num_nodes, -1) for g in graphs]
        )
        buf = np.zeros((spec.n_nodes, vals.shape[1]), np_dtype)
        buf[:n] = vals
        out[f"node_targets/{name}"] = buf

    return out


def graph_batch_from_np(arrs: Dict[str, np.ndarray]) -> GraphBatch:
    """Assemble a ``GraphBatch`` pytree from ``batch_graphs_np`` output."""
    graph_targets = {
        k.split("/", 1)[1]: jnp.asarray(v)
        for k, v in arrs.items()
        if k.startswith("graph_targets/")
    }
    node_targets = {
        k.split("/", 1)[1]: jnp.asarray(v)
        for k, v in arrs.items()
        if k.startswith("node_targets/")
    }
    kwargs = {
        k: jnp.asarray(v)
        for k, v in arrs.items()
        if "/" not in k
    }
    return GraphBatch(graph_targets=graph_targets, node_targets=node_targets, **kwargs)


def batch_graphs(graphs: List[Graph], spec: PadSpec) -> GraphBatch:
    return graph_batch_from_np(batch_graphs_np(graphs, spec))
