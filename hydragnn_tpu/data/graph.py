"""Static-shape graph batch containers for TPU.

The reference (HydraGNN) batches graphs with PyG's ragged ``Batch`` object and
moves it host->device every step (reference: hydragnn/train/train_validate_test.py:514).
On TPU every array inside ``jit`` must have a static shape, so this module
replaces the ragged batch with a *padded* batch:

- all graphs in a batch are concatenated (nodes stacked, edges stacked with
  index offsets) exactly like PyG batching,
- the result is padded up to a fixed ``PadSpec`` (n_nodes, n_edges, n_graphs),
- padding nodes/edges are assigned to one trailing *dummy graph* whose mask is
  False, so segment reductions and pooling stay correct without any dynamic
  shapes.

Targets are stored per-head in a dict (graph-level heads: ``[G, d]``;
node-level heads: ``[N, d]``) instead of the reference's packed ``data.y`` +
``y_loc`` index table (reference: hydragnn/preprocess/graph_samples_checks_and_updates.py:493-534);
the packing existed to ship ragged multi-task targets through PyG, which a
static-shape design does not need.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np
from flax import struct

# Names of per-node / per-edge / per-graph optional fields, used by batching.
_NODE_FIELDS = ("x", "pos", "pe", "z")
_EDGE_FIELDS = ("edge_attr", "edge_shifts", "rel_pe")


@dataclasses.dataclass
class Graph:
    """A single host-side graph sample (numpy arrays, ragged shapes).

    Mirrors the information content of a PyG ``Data`` object as produced by the
    reference's serialized loader (hydragnn/preprocess/serialized_dataset_loader.py:110-212).
    """

    x: np.ndarray  # [n, Fx] node input features
    pos: np.ndarray  # [n, 3] positions
    senders: np.ndarray  # [e] int32 message source node
    receivers: np.ndarray  # [e] int32 message destination node
    edge_attr: Optional[np.ndarray] = None  # [e, Fe]
    edge_shifts: Optional[np.ndarray] = None  # [e, 3] PBC cartesian shifts
    pe: Optional[np.ndarray] = None  # [n, pe_dim] Laplacian PE
    rel_pe: Optional[np.ndarray] = None  # [e, pe_dim] |pe_src - pe_dst|
    z: Optional[np.ndarray] = None  # [n] int32 atomic numbers
    graph_y: Optional[np.ndarray] = None  # [Fg] raw graph feature table
    graph_targets: Optional[Dict[str, np.ndarray]] = None  # name -> [d]
    node_targets: Optional[Dict[str, np.ndarray]] = None  # name -> [n, d]
    dataset_id: int = 0
    cell: Optional[np.ndarray] = None  # [3, 3] lattice (PBC only)

    @property
    def num_nodes(self) -> int:
        return int(self.x.shape[0])

    @property
    def num_edges(self) -> int:
        return int(self.senders.shape[0])

    def float_channels(self):
        """Yield ``(name, array)`` for every numeric payload channel of this
        sample — inputs, geometry, and targets alike. The single source of
        truth for "which arrays must be finite" used by the sample validator
        (data/validate.py); a new Graph field with numeric content should be
        added here so validation covers it automatically."""
        for name in ("x", "pos", "edge_attr", "edge_shifts", "pe", "rel_pe"):
            v = getattr(self, name)
            if v is not None:
                yield name, np.asarray(v)
        if self.graph_y is not None:
            yield "graph_y", np.asarray(self.graph_y)
        for table, label in ((self.graph_targets, "graph_target"),
                             (self.node_targets, "node_target")):
            for key, v in (table or {}).items():
                yield f"{label}:{key}", np.asarray(v)


@struct.dataclass
class GraphBatch:
    """Device-side padded batch of graphs (a pytree of fixed-shape arrays).

    Shapes: N = padded node count, E = padded edge count, G = padded graph
    count. The last graph slot(s) are dummy graphs holding all padding nodes
    and edges (``graph_mask`` False there).
    """

    # node-level
    x: jnp.ndarray  # [N, Fx] float
    pos: jnp.ndarray  # [N, 3] float
    node_graph: jnp.ndarray  # [N] int32: graph id of each node
    node_mask: jnp.ndarray  # [N] bool
    # edge-level
    senders: jnp.ndarray  # [E] int32
    receivers: jnp.ndarray  # [E] int32
    edge_mask: jnp.ndarray  # [E] bool
    # graph-level
    graph_mask: jnp.ndarray  # [G] bool
    dataset_id: jnp.ndarray  # [G] int32
    # optional channels
    edge_attr: Optional[jnp.ndarray] = None  # [E, Fe]
    edge_shifts: Optional[jnp.ndarray] = None  # [E, 3]
    pe: Optional[jnp.ndarray] = None  # [N, pe_dim]
    rel_pe: Optional[jnp.ndarray] = None  # [E, pe_dim]
    z: Optional[jnp.ndarray] = None  # [N] int32
    # optional statically padded triplets k->j->i for directional MP (DimeNet):
    # trip_kj/trip_ji index into the edge arrays (reference computes these
    # per-batch on device via SparseTensor, DIMEStack.py:233-258; here the
    # loader precomputes them on host, cf. SURVEY §3 hot-spot (d))
    trip_kj: Optional[jnp.ndarray] = None  # [T] int32 edge id of k->j
    trip_ji: Optional[jnp.ndarray] = None  # [T] int32 edge id of j->i
    trip_mask: Optional[jnp.ndarray] = None  # [T] bool
    # targets: head name -> [G, d] (graph heads) or [N, d] (node heads)
    graph_targets: Dict[str, jnp.ndarray] = struct.field(default_factory=dict)
    node_targets: Dict[str, jnp.ndarray] = struct.field(default_factory=dict)

    @property
    def num_nodes(self) -> int:
        return self.x.shape[0]

    @property
    def num_edges(self) -> int:
        return self.senders.shape[0]

    @property
    def num_graphs(self) -> int:
        return self.graph_mask.shape[0]

    @property
    def num_real_graphs(self) -> jnp.ndarray:
        return jnp.sum(self.graph_mask.astype(jnp.int32))

    @property
    def nodes_per_graph(self) -> jnp.ndarray:
        """[G] number of real nodes in each graph."""
        seg = jnp.zeros((self.num_graphs,), jnp.int32)
        return seg.at[self.node_graph].add(self.node_mask.astype(jnp.int32))


@dataclasses.dataclass(frozen=True)
class PadSpec:
    """Static padding target for a batch. All jit specializations key on this."""

    n_nodes: int
    n_edges: int
    n_graphs: int  # includes the +1 dummy graph slot
    n_triplets: int = 0  # 0 = no triplet channel

    @staticmethod
    def for_dataset(
        graphs: List[Graph],
        batch_size: int,
        node_multiple: int = 8,
        edge_multiple: int = 128,
        slack: float = 1.0,
        with_triplets: bool = False,
    ) -> "PadSpec":
        """Choose one spec covering any ``batch_size`` graphs from ``graphs``.

        Uses the max graph size times batch size (exact upper bound for the
        small molecular graphs this framework targets) rounded up to
        TPU-friendly multiples. ``slack`` can trim (<1) toward the sum of the
        largest-k sizes if memory is tight.
        """
        if not graphs:
            raise ValueError("empty dataset")
        n_sizes = sorted((g.num_nodes for g in graphs), reverse=True)
        e_sizes = sorted((g.num_edges for g in graphs), reverse=True)
        k = min(batch_size, len(n_sizes))
        n_bound = int(sum(n_sizes[:k]) * slack) + 1
        e_bound = int(sum(e_sizes[:k]) * slack) + 1
        n_triplets = 0
        if with_triplets:
            # exact per-graph triplet count: for each edge j->i, one triplet
            # per in-edge k->j with k != i
            t_sizes = sorted((_triplet_count(g) for g in graphs), reverse=True)
            n_triplets = _round_up(int(sum(t_sizes[:k]) * slack) + 1, edge_multiple)
        return PadSpec(
            n_nodes=_round_up(n_bound + 1, node_multiple),
            n_edges=_round_up(e_bound, edge_multiple),
            n_graphs=batch_size + 1,
            n_triplets=n_triplets,
        )


@dataclasses.dataclass(frozen=True)
class SpecLadder:
    """A small ascending set of pad specs — the variable-graph-size strategy
    (SURVEY §5.7; reference signal: ``check_if_graph_size_variable``,
    hydragnn/preprocess/graph_samples_checks_and_updates.py:32-87).

    One worst-case ``PadSpec`` pads every batch to the sum of the
    ``batch_size`` largest graphs; on heterogeneous size distributions
    (OC20/MPTrj-like) that multiplies most batches' cost. Instead: levels at
    empirical quantiles of simulated batch totals + the exact worst case on
    top. Each batch selects the smallest level that fits, so there are at
    most ``len(specs)`` jit specializations and typical padding waste stays
    bounded by the inter-quantile gap.
    """

    specs: Tuple[PadSpec, ...]  # ascending; last is the exact worst case

    @staticmethod
    def for_dataset(
        graphs: List[Graph],
        batch_size: int,
        num_buckets: int = 4,
        node_multiple: int = 8,
        edge_multiple: int = 128,
        with_triplets: bool = False,
        num_sim: int = 256,
        seed: int = 0,
        size_bucketing: bool = False,
        bucket_window: int = 16,
    ) -> "SpecLadder":
        # one scan of per-graph sizes serves both the worst-case spec and the
        # quantile levels (triplet counting in particular is O(E) per graph)
        n_sizes = np.asarray([g.num_nodes for g in graphs])
        e_sizes = np.asarray([g.num_edges for g in graphs])
        t_sizes = (
            np.asarray([_triplet_count(g) for g in graphs]) if with_triplets else None
        )
        k = min(batch_size, len(graphs))
        # exact worst case: sum of the k largest (same math as
        # PadSpec.for_dataset at slack=1.0)
        worst = PadSpec(
            n_nodes=_round_up(int(np.sort(n_sizes)[-k:].sum()) + 2, node_multiple),
            n_edges=_round_up(int(np.sort(e_sizes)[-k:].sum()) + 1, edge_multiple),
            n_graphs=batch_size + 1,
            n_triplets=(
                _round_up(int(np.sort(t_sizes)[-k:].sum()) + 1, edge_multiple)
                if t_sizes is not None
                else 0
            ),
        )
        if num_buckets <= 1 or len(graphs) <= batch_size:
            return SpecLadder((worst,))
        rng = np.random.default_rng(seed)
        if size_bucketing:
            # simulate the loader's size-bucketed batch composition
            # (pipeline.GraphLoader._bucket_order): levels must be quantiles
            # of the totals batches will ACTUALLY have — bucketed batches of
            # small graphs need levels far below the random-batch median
            picks_l: List[np.ndarray] = []
            w = max(bucket_window * k, k)
            while len(picks_l) < num_sim:
                order = rng.permutation(len(graphs))
                for s in range(0, len(order) - k + 1, w):
                    win = order[s : s + w]
                    win = win[np.argsort(n_sizes[win], kind="stable")]
                    picks_l.extend(
                        win[b : b + k]
                        for b in range(0, len(win) - k + 1, k)
                    )
            picks = np.stack(picks_l[:num_sim])
        else:
            picks = np.stack(
                [rng.choice(len(graphs), size=k, replace=False) for _ in range(num_sim)]
            )
        node_tot = n_sizes[picks].sum(axis=1)
        edge_tot = e_sizes[picks].sum(axis=1)
        trip_tot = t_sizes[picks].sum(axis=1) if t_sizes is not None else None
        # tail-halving quantiles (50, 75, 87.5, ...) plus a level just above
        # the largest simulated batch: the worst-case spec is the sum of the
        # batch_size LARGEST graphs, which on long-tailed distributions is
        # many times a typical batch — only batches beyond everything seen in
        # simulation should ever pay for it
        qs = [100.0 * (1.0 - 0.5 ** (i + 1)) for i in range(num_buckets - 1)]
        levels = [
            (
                int(np.percentile(node_tot, q)) + 2,
                int(np.percentile(edge_tot, q)) + 1,
                int(np.percentile(trip_tot, q)) + 1 if trip_tot is not None else 0,
            )
            for q in qs
        ]
        levels.append(
            (
                int(node_tot.max() * 1.05) + 2,
                int(edge_tot.max() * 1.05) + 1,
                int(trip_tot.max() * 1.05) + 1 if trip_tot is not None else 0,
            )
        )
        specs: List[PadSpec] = []
        for n_b, e_b, t_b in levels:
            spec = PadSpec(
                n_nodes=_round_up(n_b, node_multiple),
                n_edges=_round_up(e_b, edge_multiple),
                n_graphs=worst.n_graphs,
                n_triplets=_round_up(t_b, edge_multiple) if t_b else 0,
            )
            if (
                spec.n_nodes < worst.n_nodes
                and (not specs or spec != specs[-1])
            ):
                specs.append(spec)
        specs.append(worst)
        return SpecLadder(tuple(specs))

    def select(self, node_total: int, edge_total: int, trip_total: int = 0) -> PadSpec:
        """Smallest spec fitting the batch; the top (worst-case) level always
        fits any batch of at most ``batch_size`` dataset graphs."""
        for s in self.specs:
            if (
                node_total <= s.n_nodes - 1
                and edge_total <= s.n_edges
                and (s.n_triplets == 0 or trip_total <= s.n_triplets)
            ):
                return s
        return self.specs[-1]

    def select_for(self, graphs: List[Graph]) -> PadSpec:
        n = sum(g.num_nodes for g in graphs)
        e = sum(g.num_edges for g in graphs)
        t = (
            sum(_triplet_count(g) for g in graphs)
            if self.specs[-1].n_triplets
            else 0
        )
        return self.select(n, e, t)


def padding_waste(loader) -> float:
    """Fraction of padded node slots that hold no real node, over one epoch —
    the throughput-loss proxy the bucketing ladder is meant to bound."""
    real = 0
    padded = 0
    for batch in loader:
        mask = np.asarray(batch.node_mask)
        real += int(mask.sum())
        padded += int(mask.size)
    return 1.0 - real / max(padded, 1)


def _triplet_count(g: Graph) -> int:
    deg = np.bincount(g.receivers, minlength=g.num_nodes)
    total = int(deg[g.senders].sum())
    # subtract k == i cases: pairs of mutual edges j->i and i->j
    pairs = set(zip(g.senders.tolist(), g.receivers.tolist()))
    mutual = sum(1 for (j, i) in pairs if (i, j) in pairs)
    return total - mutual


def compute_triplets_np(
    senders: np.ndarray,
    receivers: np.ndarray,
    edge_mask: np.ndarray,
    n_triplets: int,
) -> Dict[str, np.ndarray]:
    """Vectorized k->j->i triplet enumeration over the real edges of a padded
    batch (reference: PyG-style ``triplets``, DIMEStack.py:233-258).

    Returns edge-index pairs (trip_kj, trip_ji) padded to ``n_triplets`` with
    the last edge slot and a validity mask.
    """
    real = np.nonzero(edge_mask)[0]
    n_nodes = int(senders.max(initial=0)) + 1 if senders.size else 1
    # in-edges grouped by receiver
    order = np.argsort(receivers[real], kind="stable")
    sorted_edges = real[order]
    deg = np.bincount(receivers[real], minlength=n_nodes)
    start = np.concatenate([[0], np.cumsum(deg)])
    # for each real edge e2 = j->i: a block of deg[j] candidate k->j edges
    j_of = senders[real]
    counts = deg[j_of]
    ji = np.repeat(real, counts)
    cum = np.concatenate([[0], np.cumsum(counts)])
    pos = np.arange(int(counts.sum())) - np.repeat(cum[:-1], counts)
    kj = sorted_edges[np.repeat(start[j_of], counts) + pos]
    keep = senders[kj] != receivers[ji]  # drop i == k triplets
    kj, ji = kj[keep], ji[keep]
    t = kj.shape[0]
    if t > n_triplets:
        raise ValueError(f"batch has {t} triplets, exceeds pad spec {n_triplets}")
    pad_edge = senders.shape[0] - 1
    out_kj = np.full((n_triplets,), pad_edge, np.int32)
    out_ji = np.full((n_triplets,), pad_edge, np.int32)
    out_kj[:t] = kj
    out_ji[:t] = ji
    mask = np.zeros((n_triplets,), bool)
    mask[:t] = True
    return {"trip_kj": out_kj, "trip_ji": out_ji, "trip_mask": mask}


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _stack_optional(graphs: List[Graph], field: str) -> Optional[np.ndarray]:
    vals = [getattr(g, field) for g in graphs]
    if all(v is None for v in vals):
        return None
    if any(v is None for v in vals):
        raise ValueError(f"field {field!r} present in some graphs but not all")
    return np.concatenate([np.asarray(v) for v in vals], axis=0)


def sort_edges_by_receiver(graph: Graph) -> Graph:
    """Reorder a graph's edges so receivers ascend (stable sort).

    Edge order is semantically irrelevant to message passing, but sorted
    receivers make the aggregation CSR-contiguous — the precondition of the
    Pallas sorted-segment-sum kernel (ops/pallas_segment.py) and friendlier
    to XLA's scatter as well. All per-edge arrays are permuted together.
    """
    perm = np.argsort(graph.receivers, kind="stable")
    rep = {
        "senders": np.asarray(graph.senders)[perm],
        "receivers": np.asarray(graph.receivers)[perm],
    }
    for field in _EDGE_FIELDS:
        v = getattr(graph, field)
        if v is not None:
            rep[field] = np.asarray(v)[perm]
    return dataclasses.replace(graph, **rep)


def batch_graphs_np(
    graphs: List[Graph],
    spec: PadSpec,
    np_dtype=np.float32,
    sort_edges: bool = False,
) -> Dict[str, np.ndarray]:
    """Concatenate + pad a list of host graphs into flat numpy arrays.

    Padding convention: padding nodes belong to the final (dummy) graph slot,
    padding edges connect the final padding node to itself. Runs entirely on
    host with numpy; ``GraphBatch`` construction from the result is a cheap
    device put.

    ``sort_edges=True`` sorts each graph's edges by receiver first; node
    offsets ascend across the batch and padding edges target the final
    node, so the batched receivers array comes out globally sorted.
    """
    if sort_edges:
        graphs = [sort_edges_by_receiver(g) for g in graphs]
    G = len(graphs)
    n = sum(g.num_nodes for g in graphs)
    e = sum(g.num_edges for g in graphs)
    if G > spec.n_graphs - 1 or n > spec.n_nodes - 1 or e > spec.n_edges:
        raise ValueError(
            f"batch ({G} graphs, {n} nodes, {e} edges) exceeds pad spec {spec}"
        )

    out: Dict[str, np.ndarray] = {}

    # node features
    for field in _NODE_FIELDS:
        stacked = _stack_optional(graphs, field)
        if stacked is None:
            continue
        if stacked.ndim == 1:
            stacked = stacked[:, None]
        width = stacked.shape[1]
        dtype = np.int32 if field == "z" else np_dtype
        buf = np.zeros((spec.n_nodes, width), dtype)
        buf[:n] = stacked
        out[field] = buf if field != "z" else buf[:, 0]

    # edges with node-index offsets
    senders = np.full((spec.n_edges,), spec.n_nodes - 1, np.int32)
    receivers = np.full((spec.n_edges,), spec.n_nodes - 1, np.int32)
    off = 0
    eoff = 0
    node_graph = np.full((spec.n_nodes,), spec.n_graphs - 1, np.int32)
    for gi, g in enumerate(graphs):
        senders[eoff : eoff + g.num_edges] = g.senders + off
        receivers[eoff : eoff + g.num_edges] = g.receivers + off
        node_graph[off : off + g.num_nodes] = gi
        off += g.num_nodes
        eoff += g.num_edges
    out["senders"] = senders
    out["receivers"] = receivers
    out["node_graph"] = node_graph

    for field in _EDGE_FIELDS:
        stacked = _stack_optional(graphs, field)
        if stacked is None:
            continue
        if stacked.ndim == 1:
            stacked = stacked[:, None]
        buf = np.zeros((spec.n_edges, stacked.shape[1]), np_dtype)
        buf[:e] = stacked
        out[field] = buf

    if spec.n_triplets:
        edge_mask_tmp = np.zeros((spec.n_edges,), bool)
        edge_mask_tmp[:e] = True
        out.update(
            compute_triplets_np(senders, receivers, edge_mask_tmp, spec.n_triplets)
        )

    # masks
    node_mask = np.zeros((spec.n_nodes,), bool)
    node_mask[:n] = True
    edge_mask = np.zeros((spec.n_edges,), bool)
    edge_mask[:e] = True
    graph_mask = np.zeros((spec.n_graphs,), bool)
    graph_mask[:G] = True
    out["node_mask"] = node_mask
    out["edge_mask"] = edge_mask
    out["graph_mask"] = graph_mask

    dataset_id = np.zeros((spec.n_graphs,), np.int32)
    dataset_id[:G] = [g.dataset_id for g in graphs]
    out["dataset_id"] = dataset_id

    # targets
    gt_names = set()
    nt_names = set()
    for g in graphs:
        gt_names.update((g.graph_targets or {}).keys())
        nt_names.update((g.node_targets or {}).keys())
    for name in sorted(gt_names):
        vals = [np.atleast_1d(np.asarray(g.graph_targets[name], np_dtype)) for g in graphs]
        width = vals[0].shape[-1]
        buf = np.zeros((spec.n_graphs, width), np_dtype)
        buf[:G] = np.stack(vals)
        out[f"graph_targets/{name}"] = buf
    for name in sorted(nt_names):
        vals = np.concatenate(
            [np.asarray(g.node_targets[name], np_dtype).reshape(g.num_nodes, -1) for g in graphs]
        )
        buf = np.zeros((spec.n_nodes, vals.shape[1]), np_dtype)
        buf[:n] = vals
        out[f"node_targets/{name}"] = buf

    return out


def graph_batch_from_np(arrs: Dict[str, np.ndarray]) -> GraphBatch:
    """Assemble a ``GraphBatch`` pytree from ``batch_graphs_np`` output."""
    graph_targets = {
        k.split("/", 1)[1]: jnp.asarray(v)
        for k, v in arrs.items()
        if k.startswith("graph_targets/")
    }
    node_targets = {
        k.split("/", 1)[1]: jnp.asarray(v)
        for k, v in arrs.items()
        if k.startswith("node_targets/")
    }
    kwargs = {
        k: jnp.asarray(v)
        for k, v in arrs.items()
        if "/" not in k
    }
    return GraphBatch(graph_targets=graph_targets, node_targets=node_targets, **kwargs)


def batch_graphs(
    graphs: List[Graph], spec: PadSpec, sort_edges: bool = False
) -> GraphBatch:
    return graph_batch_from_np(batch_graphs_np(graphs, spec, sort_edges=sort_edges))
