"""Host-side radius-graph construction (open and periodic boundary conditions).

TPU-native equivalent of the reference's graph builders
(hydragnn/preprocess/graph_samples_checks_and_updates.py:141-343, which wraps
torch_geometric ``RadiusGraph`` and the ASE neighborlist for PBC). This is
preprocessing — it runs once per sample on the host with numpy/scipy, never
inside the jitted step loop, so plain python is the right tool (cf. SURVEY §2.3
item 10).

Edge direction convention: an edge (sender j -> receiver i) carries a message
from j aggregated at i, matching PyG's ``edge_index = [source, target]``.
Edges are *directed*: both (j->i) and (i->j) are emitted, like RadiusGraph
with default symmetric output.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np
from scipy.spatial import cKDTree
from ..utils import envflags

# node count above which the C++ cell-list builder takes over from scipy.
# Measured on this image: the KD-tree (itself C) matches the cell list up
# to a few thousand atoms; at 100k atoms the cell list is ~1.4x faster and
# scales linearly in N while staying allocation-lean. Typical molecular /
# slab samples therefore stay on scipy; mesoscale systems switch over.
# HYDRAGNN_NATIVE_NEIGHBORS forces it on (=1) or off (=0).
_NATIVE_MIN_N = 4096
_native = None


def _native_lib():
    """Lazy-built cell-list library (native/neighbors.cpp); None when the
    toolchain is unavailable — callers fall back to scipy."""
    global _native
    if _native is not None:
        return _native or None
    try:
        import ctypes

        from ..native.build import build_library

        lib = ctypes.CDLL(build_library("neighbors"))
        lib.rg_open.restype = ctypes.c_long
        lib.rg_open.argtypes = [
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_long,
            ctypes.c_double,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_long,
        ]
        _native = lib
    except Exception:
        import warnings

        warnings.warn(
            "native cell-list neighbor builder unavailable "
            "(C++ toolchain missing?); falling back to scipy KD-tree"
        )
        _native = False
    return _native or None


def _radius_graph_native(pos: np.ndarray, radius: float):
    import ctypes

    lib = _native_lib()
    if lib is None:
        return None
    pos = np.ascontiguousarray(pos, np.float64)
    n = pos.shape[0]
    cap = max(64 * n, 1024)
    for _ in range(2):
        senders = np.empty(cap, np.int32)
        receivers = np.empty(cap, np.int32)
        m = lib.rg_open(
            pos.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            n,
            float(radius),
            senders.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            receivers.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            cap,
        )
        if m >= 0:
            return senders[:m].copy(), receivers[:m].copy()
        cap = -m  # exact size needed
    return None


def radius_graph(
    pos: np.ndarray,
    radius: float,
    max_neighbours: Optional[int] = None,
    loop: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """All directed edges (j -> i) with ||pos_j - pos_i|| <= radius.

    ``max_neighbours`` keeps only the nearest k incoming edges per receiver
    (reference: RadiusGraph(loop=False, max_num_neighbors=...) in
    hydragnn/preprocess/serialized_dataset_loader.py:134-141).
    Returns (senders, receivers) int32 arrays.

    Large systems route through the C++ cell-list builder
    (native/neighbors.cpp, the ASE-neighborlist analog); small ones stay on
    scipy's KD-tree. Both produce the same edge SET; ordering differs.
    """
    pos = np.asarray(pos, np.float64)
    native_pref = envflags.env_str("HYDRAGNN_NATIVE_NEIGHBORS")
    use_native = (
        native_pref == "1"
        or (native_pref != "0" and pos.shape[0] >= _NATIVE_MIN_N)
    )
    senders = receivers = None
    if use_native:
        built = _radius_graph_native(pos, radius)
        if built is not None:
            senders, receivers = built
    if senders is None:
        tree = cKDTree(pos)
        pairs = tree.query_pairs(r=radius, output_type="ndarray")  # unique i<j
        if pairs.size == 0:
            senders = np.zeros((0,), np.int32)
            receivers = np.zeros((0,), np.int32)
        else:
            senders = np.concatenate([pairs[:, 0], pairs[:, 1]]).astype(np.int32)
            receivers = np.concatenate([pairs[:, 1], pairs[:, 0]]).astype(np.int32)
    if loop:
        idx = np.arange(pos.shape[0], dtype=np.int32)
        senders = np.concatenate([senders, idx])
        receivers = np.concatenate([receivers, idx])
    if max_neighbours is not None:
        senders, receivers = _cap_neighbours(pos, senders, receivers, None, max_neighbours)[:2]
    return senders, receivers


def radius_graph_pbc(
    pos: np.ndarray,
    cell: np.ndarray,
    radius: float,
    max_neighbours: Optional[int] = None,
    pbc: Tuple[bool, bool, bool] = (True, True, True),
    max_attempts: int = 3,
    radius_multiplier: float = 1.25,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Radius graph under periodic boundary conditions.

    Replaces the reference's ``RadiusGraphPBC`` (ASE neighborlist,
    graph_samples_checks_and_updates.py:141-343). Periodic images are
    enumerated over the integer shifts needed to cover ``radius``; each edge
    carries the cartesian shift vector of the sender image so that
    ``pos[s] + shift - pos[r]`` is the true minimum-image displacement
    (the reference stores the same as ``edge_shifts``).

    When some node receives no edge, the radius is expanded by
    ``radius_multiplier`` and the build retried up to ``max_attempts``
    times; nodes still isolated after the last attempt get one artificial
    in-edge from a deterministic partner node (reference retry + fallback:
    graph_samples_checks_and_updates.py:163-222,284-307 — the reference
    picks the artificial partner with np.random; here the partner is
    ``(i + 1) % n`` so rebuilds are reproducible).

    Returns (senders, receivers, edge_shifts[e,3]).
    """
    n = np.asarray(pos).shape[0]
    r = float(radius)
    for attempt in range(max_attempts):
        senders, receivers, shifts = _radius_graph_pbc_once(
            pos, cell, r, max_neighbours, pbc
        )
        if np.unique(receivers).size == n:
            return senders, receivers, shifts
        if attempt < max_attempts - 1:
            r *= radius_multiplier
    # artificial fallback edges for still-isolated receivers
    missing = np.setdiff1d(np.arange(n), np.unique(receivers))
    add_s = np.array([(m + 1) % n if n > 1 else 0 for m in missing], np.int32)
    senders = np.concatenate([senders, add_s])
    receivers = np.concatenate([receivers, missing.astype(np.int32)])
    shifts = np.concatenate(
        [shifts, np.zeros((missing.size, 3), shifts.dtype)], axis=0
    )
    return senders, receivers, shifts


def _radius_graph_pbc_once(
    pos: np.ndarray,
    cell: np.ndarray,
    radius: float,
    max_neighbours: Optional[int],
    pbc: Tuple[bool, bool, bool],
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One PBC radius-graph build at a fixed radius."""
    pos = np.asarray(pos, np.float64)
    cell = np.asarray(cell, np.float64).reshape(3, 3)
    n = pos.shape[0]

    # number of repeats of each lattice vector needed to cover the radius
    inv = np.linalg.inv(cell)
    heights = 1.0 / np.linalg.norm(inv, axis=0)  # perpendicular cell heights
    reps = [int(np.ceil(radius / h)) if p else 0 for h, p in zip(heights, pbc)]

    shifts_frac = np.array(
        [
            (a, b, c)
            for a in range(-reps[0], reps[0] + 1)
            for b in range(-reps[1], reps[1] + 1)
            for c in range(-reps[2], reps[2] + 1)
        ],
        np.float64,
    )
    shifts_cart = shifts_frac @ cell  # [S, 3]

    senders_l, receivers_l, shift_l = [], [], []
    tree = cKDTree(pos)
    for sf, sc in zip(shifts_frac, shifts_cart):
        images = pos + sc  # senders shifted by this image vector
        itree = cKDTree(images)
        pairs = tree.query_ball_tree(itree, r=radius)  # receivers -> sender lists
        for i, js in enumerate(pairs):
            for j in js:
                if np.all(sf == 0) and i == j:
                    continue  # no self loops in the home cell
                senders_l.append(j)
                receivers_l.append(i)
                shift_l.append(sc)
    if senders_l:
        senders = np.asarray(senders_l, np.int32)
        receivers = np.asarray(receivers_l, np.int32)
        shifts = np.asarray(shift_l, np.float64)
    else:
        senders = np.zeros((0,), np.int32)
        receivers = np.zeros((0,), np.int32)
        shifts = np.zeros((0, 3), np.float64)
    if max_neighbours is not None:
        senders, receivers, shifts = _cap_neighbours(
            pos, senders, receivers, shifts, max_neighbours
        )
    return senders, receivers, shifts.astype(np.float32)


def _cap_neighbours(pos, senders, receivers, shifts, k):
    """Keep only the k nearest incoming edges per receiver node."""
    if senders.size == 0:
        return senders, receivers, shifts
    disp = pos[senders] - pos[receivers]
    if shifts is not None:
        disp = disp + shifts
    d = np.linalg.norm(disp, axis=1)
    keep = np.zeros(senders.shape[0], bool)
    # sender index as the final key breaks distance ties deterministically:
    # the native cell-list and scipy builders emit the same edge SET in
    # different orders, and without this the capped edge set would differ
    # between machines with and without a working C++ toolchain
    order = np.lexsort((senders, d, receivers))
    recv_sorted = receivers[order]
    start = 0
    while start < order.size:
        end = start
        while end < order.size and recv_sorted[end] == recv_sorted[start]:
            end += 1
        keep[order[start : min(start + k, end)]] = True
        start = end
    if shifts is None:
        return senders[keep], receivers[keep], None
    return senders[keep], receivers[keep], shifts[keep]


def edge_vectors_and_lengths(
    pos: np.ndarray,
    senders: np.ndarray,
    receivers: np.ndarray,
    shifts: Optional[np.ndarray] = None,
    eps: float = 1e-12,
) -> Tuple[np.ndarray, np.ndarray]:
    """Displacement sender->receiver and its length (host-side helper)."""
    vec = pos[receivers] - pos[senders]
    if shifts is not None:
        vec = vec - shifts
    length = np.sqrt(np.sum(vec * vec, axis=1) + eps)
    return vec, length
