"""Raw simulation-output loaders: LSMS, XYZ and AtomEye/extended CFG
(reference: hydragnn/preprocess/lsms_raw_dataset_loader.py:38-106,
cfg_raw_dataset_loader.py:30-106, utils/datasets/{lsmsdataset,cfgdataset,
xyzdataset}.py). The reference parses with ASE where available; here the
three text formats are parsed directly (ASE is not in the image) and edges
are built afterwards with the package's own radius-graph machinery.

All loaders return edge-less ``Graph`` records (senders/receivers empty);
``finalize_graphs`` attaches radius-graph connectivity (open or PBC), which
is the reference's serialized-loader step
(hydragnn/preprocess/serialized_dataset_loader.py:134-150).
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Optional, Sequence

import numpy as np

from .graph import Graph
from .neighbors import radius_graph, radius_graph_pbc

ATOMIC_SYMBOLS = (
    "H He Li Be B C N O F Ne Na Mg Al Si P S Cl Ar K Ca Sc Ti V Cr Mn Fe Co "
    "Ni Cu Zn Ga Ge As Se Br Kr Rb Sr Y Zr Nb Mo Tc Ru Rh Pd Ag Cd In Sn Sb "
    "Te I Xe Cs Ba La Ce Pr Nd Pm Sm Eu Gd Tb Dy Ho Er Tm Yb Lu Hf Ta W Re "
    "Os Ir Pt Au Hg Tl Pb Bi Po At Rn Fr Ra Ac Th Pa U Np Pu Am Cm Bk Cf Es "
    "Fm Md No Lr Rf Db Sg Bh Hs Mt Ds Rg Cn Nh Fl Mc Lv Ts Og"
).split()
SYMBOL_TO_Z = {s: i + 1 for i, s in enumerate(ATOMIC_SYMBOLS)}


def _empty_edges():
    return np.zeros((0,), np.int32), np.zeros((0,), np.int32)


def load_lsms_file(
    path: str,
    node_feature_dims: Sequence[int] = (1, 1),
    node_feature_cols: Sequence[int] = (0, 5),
    graph_feature_dims: Sequence[int] = (1,),
    graph_feature_cols: Sequence[int] = (0,),
    charge_density_correction: bool = False,
) -> Graph:
    """One LSMS text sample: line 0 = graph features, then one line per atom
    with columns [feat0, feat1, x, y, z, feat5, ...]
    (reference: lsms_raw_dataset_loader.py:38-88).

    ``charge_density_correction=True`` subtracts the proton count from the
    second selected feature (reference: :89-106) — only enable it when the
    selected columns are exactly [protons, charge density]. Atomic numbers
    ``z`` are taken from the first selected column only when that column is
    the proton column (index 0); otherwise ``z`` is left unset.
    """
    with open(path, encoding="utf-8") as f:
        lines = f.readlines()
    head = lines[0].split()
    g_feature = []
    for dim, col in zip(graph_feature_dims, graph_feature_cols):
        for icomp in range(dim):
            g_feature.append(float(head[col + icomp]))
    pos = []
    feats = []
    for line in lines[1:]:
        if not line.strip():
            continue
        tok = line.split()
        pos.append([float(tok[2]), float(tok[3]), float(tok[4])])
        row = []
        for dim, col in zip(node_feature_dims, node_feature_cols):
            for icomp in range(dim):
                row.append(float(tok[col + icomp]))
        feats.append(row)
    x = np.asarray(feats, np.float32)
    if charge_density_correction:
        assert x.shape[1] >= 2, (
            "charge_density_correction needs [protons, charge] columns"
        )
        # charge density -> net charge (reference: :89-106)
        x[:, 1] = x[:, 1] - x[:, 0]
    senders, receivers = _empty_edges()
    z = x[:, 0].astype(np.int32) if node_feature_cols[0] == 0 else None
    return Graph(
        x=x,
        pos=np.asarray(pos, np.float32),
        senders=senders,
        receivers=receivers,
        graph_y=np.asarray(g_feature, np.float32),
        z=z,
    )


def load_xyz_file(path: str) -> Graph:
    """Standard (ext)XYZ: natoms, comment (graph features as floats if
    parseable), then ``Symbol x y z [extra...]`` rows
    (reference: utils/datasets/xyzdataset.py)."""
    with open(path, encoding="utf-8") as f:
        lines = [l for l in f.read().splitlines()]
    n = int(lines[0].split()[0])
    comment = lines[1].split()
    # treat the comment as graph targets only when it is purely numeric —
    # extxyz metadata lines (Lattice=..., Properties=...) are not targets
    graph_y = []
    try:
        graph_y = [float(tok) for tok in comment]
    except ValueError:
        graph_y = []
    zs, pos, extras = [], [], []
    for line in lines[2 : 2 + n]:
        tok = line.split()
        sym = tok[0]
        z = SYMBOL_TO_Z.get(sym)
        if z is None:
            z = int(float(sym))
        zs.append(z)
        pos.append([float(tok[1]), float(tok[2]), float(tok[3])])
        extras.append([float(t) for t in tok[4:]])
    x = np.asarray(zs, np.float32)[:, None]
    if extras and extras[0]:
        x = np.concatenate([x, np.asarray(extras, np.float32)], axis=1)
    senders, receivers = _empty_edges()
    return Graph(
        x=x,
        pos=np.asarray(pos, np.float32),
        senders=senders,
        receivers=receivers,
        graph_y=np.asarray(graph_y, np.float32) if graph_y else None,
        z=np.asarray(zs, np.int32),
    )


def load_cfg_file(path: str) -> Graph:
    """AtomEye extended CFG: ``Number of particles``, ``H0(i,j)`` cell matrix,
    ``entry_count``, optional ``auxiliary[k]`` names, then per-species blocks
    of (mass line, symbol line, one scaled-coordinate row per atom)
    (reference reads it via ASE, cfg_raw_dataset_loader.py:66-106; node
    features follow the reference layout [Z, mass, aux...]). A sibling
    ``<name>.bulk`` file supplies graph features when present."""
    h0 = np.zeros((3, 3))
    n = None
    entry_count = 3
    aux_count = 0
    rows: List[List[float]] = []
    masses: List[float] = []
    zs: List[int] = []
    cur_mass = None
    cur_z = None
    with open(path, encoding="utf-8") as f:
        for raw_line in f:
            line = raw_line.strip()
            if not line or line.startswith("#"):
                continue
            if line.startswith("Number of particles"):
                n = int(line.split("=")[1])
            elif line.startswith("H0("):
                ij = line[3:6]
                i, j = int(ij[0]) - 1, int(ij[2]) - 1
                h0[i, j] = float(line.split("=")[1].split()[0])
            elif line.startswith("entry_count"):
                entry_count = int(line.split("=")[1])
                aux_count = entry_count - 3
            elif line.startswith((".NO_VELOCITY", "A =", "R =", "auxiliary")):
                continue
            else:
                tok = line.split()
                if len(tok) == 1 and tok[0] in SYMBOL_TO_Z:
                    cur_z = SYMBOL_TO_Z[tok[0]]
                elif len(tok) == 1:
                    cur_mass = float(tok[0])
                elif len(tok) >= 3:
                    assert cur_z is not None, "species symbol missing in CFG"
                    rows.append([float(t) for t in tok[: 3 + aux_count]])
                    masses.append(cur_mass if cur_mass is not None else 0.0)
                    zs.append(cur_z)
    assert n is not None and len(rows) == n, f"CFG parse failed for {path}"
    scaled = np.asarray(rows, np.float64)
    pos = scaled[:, :3] @ h0  # scaled -> cartesian
    aux = scaled[:, 3:]
    x = np.concatenate(
        [
            np.asarray(zs, np.float32)[:, None],
            np.asarray(masses, np.float32)[:, None],
            aux.astype(np.float32),
        ],
        axis=1,
    )
    graph_y = None
    bulk = os.path.splitext(path)[0] + ".bulk"
    if os.path.exists(bulk):
        graph_y = np.asarray(
            [float(open(bulk, encoding="utf-8").readline().split()[0])], np.float32
        )
    senders, receivers = _empty_edges()
    return Graph(
        x=x,
        pos=pos.astype(np.float32),
        senders=senders,
        receivers=receivers,
        graph_y=graph_y,
        z=np.asarray(zs, np.int32),
        cell=h0.astype(np.float32),
    )


_LOADERS = {"LSMS": load_lsms_file, "XYZ": load_xyz_file, "CFG": load_cfg_file}
# LSMS files carry no conventional extension, so every regular file is tried
_EXTS = {"XYZ": (".xyz", ".extxyz"), "CFG": (".cfg",)}


def raw_sample_files(path: str) -> List[str]:
    """Sorted raw-sample filenames under ``path``: regular files only,
    skipping ``.bulk`` sidecars (shared by the loaders here and the LSMS
    physics utilities in data/lsms.py)."""
    return sorted(
        name
        for name in os.listdir(path)
        if os.path.isfile(os.path.join(path, name)) and not name.endswith(".bulk")
    )


def load_raw_dataset(
    path: str, fmt: str, on_error: str = "raise", **loader_kwargs
) -> List[Graph]:
    """Load every raw file under ``path`` with the format's parser
    (reference: AbstractRawDataLoader.load_raw_data,
    preprocess/raw_dataset_loader.py:29-277). Raises when a directory mixes
    samples with and without graph targets — downstream normalization cannot
    represent that.

    ``on_error="skip"`` (wired from ``Dataset.bad_sample_policy`` by
    api.prepare_data) drops files the parser cannot read — truncated or
    garbled simulation outputs are routine in large raw dumps — warning
    with the filename and a final tally instead of killing the run on the
    first bad file."""
    import warnings

    if on_error not in ("raise", "skip"):
        raise ValueError(f"on_error must be 'raise' or 'skip', got {on_error!r}")
    fmt = fmt.upper()
    loader = _LOADERS[fmt]
    graphs = []
    skipped = []
    for name in raw_sample_files(path):
        if fmt in _EXTS and not name.lower().endswith(_EXTS[fmt]):
            continue
        try:
            graphs.append(loader(os.path.join(path, name), **loader_kwargs))
        except Exception as e:  # noqa: BLE001 — parser failure on one file
            if on_error == "raise":
                raise
            skipped.append(name)
            if len(skipped) <= 3:
                warnings.warn(
                    f"skipping unparseable {fmt} file {name!r}: "
                    f"{type(e).__name__}: {e}",
                    stacklevel=2,
                )
    if skipped:
        warnings.warn(
            f"{len(skipped)} of the {fmt} files under {path!r} failed to "
            f"parse and were skipped (first: {skipped[:5]})",
            stacklevel=2,
        )
    with_y = [g.graph_y is not None for g in graphs]
    if any(with_y) and not all(with_y):
        missing = [i for i, w in enumerate(with_y) if not w][:5]
        raise ValueError(
            f"{sum(not w for w in with_y)} of {len(graphs)} raw samples have "
            f"no graph targets (first sample indices {missing}); provide "
            "targets for every file or none"
        )
    return graphs


def finalize_graphs(
    graphs: Sequence[Graph],
    radius: float,
    max_neighbours: Optional[int] = None,
    periodic: bool = False,
) -> List[Graph]:
    """Attach radius-graph edges (open or PBC) to edge-less raw graphs
    (reference: serialized_dataset_loader.py:134-150)."""
    out = []
    for g in graphs:
        if periodic:
            assert g.cell is not None, "PBC radius graph needs a cell"
            senders, receivers, shifts = radius_graph_pbc(
                g.pos, g.cell, radius, max_neighbours or 1000
            )
            out.append(
                dataclasses.replace(
                    g, senders=senders, receivers=receivers, edge_shifts=shifts
                )
            )
        else:
            senders, receivers = radius_graph(
                g.pos, radius, max_neighbours or 1000
            )
            out.append(
                dataclasses.replace(g, senders=senders, receivers=receivers)
            )
    return out
