"""On-disk dataset classes: abstract base + per-sample pickle store.

TPU analogs of the reference's dataset classes
(hydragnn/utils/datasets/abstractbasedataset.py:6-60,
hydragnn/utils/datasets/pickledataset.py:14-182): an abstract get/len
interface, a per-sample pickle dataset with a metadata header, and a writer.
Multi-host: each host writes its own contiguous index range (the analog of
the reference's MPI-offset write, pickledataset.py:103-182).
"""

from __future__ import annotations

import json
import os
import pickle
from abc import ABC, abstractmethod
from typing import Any, Dict, Iterator, List, Optional

from .graph import Graph

# Known multi-dataset ids for GFM training
# (reference: abstractbasedataset.py:41-57 hardcoded dataset_name dict)
DATASET_NAME_IDS = {
    "ani1x": 0,
    "qm7x": 1,
    "mptrj": 2,
    "alexandria": 3,
    "transition1x": 4,
    "omat24": 5,
}


class AbstractBaseDataset(ABC):
    """(reference: AbstractBaseDataset, abstractbasedataset.py:6-60)"""

    @abstractmethod
    def get(self, idx: int) -> Graph:
        ...

    @abstractmethod
    def __len__(self) -> int:
        ...

    def __getitem__(self, idx: int) -> Graph:
        g = self.get(idx)
        name = getattr(self, "dataset_name", None)
        if name in DATASET_NAME_IDS and g.dataset_id == 0:
            g.dataset_id = DATASET_NAME_IDS[name]
        return g

    def __iter__(self) -> Iterator[Graph]:
        for i in range(len(self)):
            yield self[i]


class SimplePickleDataset(AbstractBaseDataset):
    """Per-sample .pkl files + a json meta header
    (reference: SimplePickleDataset, pickledataset.py:14-100)."""

    def __init__(self, basedir: str, label: str):
        self.basedir = basedir
        self.label = label
        self.dataset_name = label
        meta_path = os.path.join(basedir, f"{label}-meta.json")
        with open(meta_path) as f:
            self.meta: Dict[str, Any] = json.load(f)
        self.ntotal = int(self.meta["ntotal"])
        self.use_subdir = bool(self.meta.get("use_subdir", False))

    def _fname(self, idx: int) -> str:
        base = self.basedir
        if self.use_subdir:
            base = os.path.join(base, str(idx // 1000))
        return os.path.join(base, f"{self.label}-{idx}.pkl")

    def get(self, idx: int) -> Graph:
        with open(self._fname(idx), "rb") as f:
            return pickle.load(f)

    def __len__(self) -> int:
        return self.ntotal

    @property
    def minmax(self) -> Optional[Dict[str, Any]]:
        return self.meta.get("minmax")


class SimplePickleWriter:
    """(reference: SimplePickleWriter, pickledataset.py:103-182)"""

    def __init__(
        self,
        graphs: List[Graph],
        basedir: str,
        label: str,
        minmax: Optional[Dict[str, Any]] = None,
        use_subdir: bool = False,
        host_count: int = 1,
        host_index: int = 0,
        nglobal: Optional[int] = None,
        offset: Optional[int] = None,
    ):
        os.makedirs(basedir, exist_ok=True)
        ntotal = nglobal if nglobal is not None else len(graphs)
        start = offset if offset is not None else 0
        if host_index == 0:
            meta = {
                "ntotal": ntotal,
                "use_subdir": use_subdir,
                "minmax": minmax,
                "hosts": host_count,
            }
            with open(os.path.join(basedir, f"{label}-meta.json"), "w") as f:
                json.dump(meta, f)
        for i, g in enumerate(graphs):
            idx = start + i
            base = basedir
            if use_subdir:
                base = os.path.join(basedir, str(idx // 1000))
                os.makedirs(base, exist_ok=True)
            with open(os.path.join(base, f"{label}-{idx}.pkl"), "wb") as f:
                pickle.dump(g, f)
