"""Atomic descriptors and molecule-to-graph helpers
(reference: hydragnn/utils/descriptors_and_embeddings/atomicdescriptors.py
builds feature tables from mendeleev/pymatgen; smiles_utils.py:1-127 turns
SMILES strings into graphs via rdkit).

Neither mendeleev nor pymatgen is in this image, so the periodic-table
quantities used by the reference descriptors are embedded directly
(standard CODATA/Pauling values, Z <= 118, zero where undefined).
SMILES support degrades gracefully when rdkit is absent.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..ops.radial import COVALENT_RADII
from .graph import Graph
from .raw import ATOMIC_SYMBOLS, SYMBOL_TO_Z

# Pauling electronegativity per Z (0 where undefined / noble without value)
ELECTRONEGATIVITY = np.zeros(119, np.float32)
ELECTRONEGATIVITY[1:104] = [
    2.20, 0.0, 0.98, 1.57, 2.04, 2.55, 3.04, 3.44, 3.98, 0.0,
    0.93, 1.31, 1.61, 1.90, 2.19, 2.58, 3.16, 0.0, 0.82, 1.00,
    1.36, 1.54, 1.63, 1.66, 1.55, 1.83, 1.88, 1.91, 1.90, 1.65,
    1.81, 2.01, 2.18, 2.55, 2.96, 3.00, 0.82, 0.95, 1.22, 1.33,
    1.60, 2.16, 1.90, 2.20, 2.28, 2.20, 1.93, 1.69, 1.78, 1.96,
    2.05, 2.10, 2.66, 2.60, 0.79, 0.89,
    # 57-71 lanthanides
    1.10, 1.12, 1.13, 1.14, 1.13, 1.17, 1.20, 1.20, 1.10, 1.22,
    1.23, 1.24, 1.25, 1.10, 1.27,
    # 72-86 Hf..Rn
    1.30, 1.50, 2.36, 1.90, 2.20, 2.20, 2.28, 2.54, 2.00, 1.62,
    2.33, 2.02, 2.00, 2.20, 2.20,
    # 87-103 Fr..Lr
    0.70, 0.90, 1.10, 1.30, 1.50, 1.38, 1.36, 1.28, 1.13, 1.28,
    1.30, 1.30, 1.30, 1.30, 1.30, 1.30, 1.30,
]

# standard atomic weights (u), Z <= 94, zero beyond
ATOMIC_MASS = np.zeros(119, np.float32)
ATOMIC_MASS[1:95] = [
    1.008, 4.003, 6.94, 9.012, 10.81, 12.011, 14.007, 15.999, 18.998, 20.180,
    22.990, 24.305, 26.982, 28.085, 30.974, 32.06, 35.45, 39.948, 39.098,
    40.078, 44.956, 47.867, 50.942, 51.996, 54.938, 55.845, 58.933, 58.693,
    63.546, 65.38, 69.723, 72.630, 74.922, 78.971, 79.904, 83.798, 85.468,
    87.62, 88.906, 91.224, 92.906, 95.95, 97.0, 101.07, 102.906, 106.42,
    107.868, 112.414, 114.818, 118.710, 121.760, 127.60, 126.904, 131.293,
    132.905, 137.327, 138.905, 140.116, 140.908, 144.242, 145.0, 150.36,
    151.964, 157.25, 158.925, 162.500, 164.930, 167.259, 168.934, 173.045,
    174.967, 178.486, 180.948, 183.84, 186.207, 190.23, 192.217, 195.084,
    196.967, 200.592, 204.38, 207.2, 208.980, 209.0, 210.0, 222.0, 223.0,
    226.0, 227.0, 232.038, 231.036, 238.029, 237.0, 244.0,
]

_PERIOD_STARTS = np.array([1, 3, 11, 19, 37, 55, 87, 119])


def period_of(z: np.ndarray) -> np.ndarray:
    return np.searchsorted(_PERIOD_STARTS, np.asarray(z), side="right")


def group_of(z: np.ndarray) -> np.ndarray:
    """IUPAC group 1-18 (lanthanides/actinides mapped to group 3)."""
    z = np.asarray(z)
    out = np.zeros_like(z)
    for i, zi in np.ndenumerate(z):
        zi = int(zi)
        if zi in (1,):
            g = 1
        elif zi == 2:
            g = 18
        elif zi <= 18:
            off = zi - (3 if zi <= 10 else 11)
            g = off + 1 if off < 2 else off + 11
        elif zi <= 54:
            off = (zi - 19) % 18
            g = off + 1
        else:
            base = 55 if zi <= 86 else 87
            off = zi - base
            if off < 2:
                g = off + 1
            elif off < 17:
                g = 3  # f-block
            else:
                g = off - 13
        out[i] = min(max(g, 1), 18)
    return out


def atomic_descriptors(z, one_hot_period_group: bool = True) -> np.ndarray:
    """Per-atom descriptor rows for atomic numbers ``z``
    (reference: atomicdescriptors.get_atom_features — normalized scalar
    properties plus one-hot period/group encodings)."""
    z = np.clip(np.asarray(z, np.int64), 0, 118)
    cov = np.zeros(119, np.float32)
    cov[: len(COVALENT_RADII)] = COVALENT_RADII[:119]
    scalars = np.stack(
        [
            z / 118.0,
            ATOMIC_MASS[z] / ATOMIC_MASS.max(),
            ELECTRONEGATIVITY[z] / 4.0,
            cov[z] / max(cov.max(), 1e-6),
        ],
        axis=-1,
    ).astype(np.float32)
    if not one_hot_period_group:
        return scalars
    period = np.eye(8, dtype=np.float32)[np.clip(period_of(z) - 1, 0, 7)]
    group = np.eye(18, dtype=np.float32)[np.clip(group_of(z) - 1, 0, 17)]
    return np.concatenate([scalars, period, group], axis=-1)


def smiles_to_graph(smiles: str, radius: float = 10.0) -> Graph:
    """SMILES -> Graph with RDKit 3D embedding; falls back to the in-tree
    dependency-free SMILES reader (data/smiles.py) when rdkit is
    unavailable (reference: smiles_utils.generate_graphdata)."""
    try:
        from rdkit import Chem
        from rdkit.Chem import AllChem
    except ImportError:
        import warnings

        from .smiles import smiles_to_graph as _native

        warnings.warn(
            "rdkit unavailable: smiles_to_graph is using the in-tree SMILES "
            "reader, whose node-feature table ([Z, degree, charge, aromatic, "
            "n_H, sp, sp2, sp3] + bond-order edge_attr) differs from the rdkit path's "
            "atomic_descriptors table — datasets/checkpoints built with one "
            "path are not feature-compatible with the other",
            stacklevel=2,
        )
        return _native(smiles)
    mol = Chem.MolFromSmiles(smiles)
    mol = Chem.AddHs(mol)
    AllChem.EmbedMolecule(mol, randomSeed=0)
    conf = mol.GetConformer()
    zs = np.asarray([a.GetAtomicNum() for a in mol.GetAtoms()], np.int32)
    pos = np.asarray(
        [list(conf.GetAtomPosition(i)) for i in range(mol.GetNumAtoms())],
        np.float32,
    )
    senders, receivers = [], []
    for b in mol.GetBonds():
        i, j = b.GetBeginAtomIdx(), b.GetEndAtomIdx()
        senders += [i, j]
        receivers += [j, i]
    return Graph(
        x=atomic_descriptors(zs),
        pos=pos,
        senders=np.asarray(senders, np.int32),
        receivers=np.asarray(receivers, np.int32),
        z=zs,
    )
