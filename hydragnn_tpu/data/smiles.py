"""Dependency-free SMILES reader: string -> molecular ``Graph``.

The reference turns SMILES into PyG graphs with rdkit
(hydragnn/utils/descriptors_and_embeddings/smiles_utils.py:1-127:
``generate_graphdata_from_smilestr`` one-hot-encodes atom type, degree and
H-count into the node feature table; bonds become bidirectional edges).
rdkit is not in this image, so this module implements the needed subset of
the SMILES grammar directly — enough for the drug-like strings of the
ZINC / CSCE / OGB example datasets:

- organic-subset atoms (B C N O P S F Cl Br I), aromatic lowercase forms
- bracket atoms ``[...]`` with isotope / charge / explicit H (parsed,
  stereo ``@`` ignored)
- bonds ``- = # :``, ring-closure digits + ``%nn``, branches ``( )``
- implicit hydrogens by standard valence, made explicit as H nodes so the
  graph matches rdkit's ``AddHs`` convention used by the reference

A light 3D embedding (bonded-distance rejection sampling) gives each
molecule coordinates so geometric models (SchNet etc.) run on the result.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from .graph import Graph

_ORGANIC = ["Cl", "Br", "B", "C", "N", "O", "P", "S", "F", "I"]
_AROMATIC = {"b": "B", "c": "C", "n": "N", "o": "O", "p": "P", "s": "S"}
_Z = {"H": 1, "B": 5, "C": 6, "N": 7, "O": 8, "F": 9, "P": 15, "S": 16,
      "Cl": 17, "Br": 35, "I": 53, "Si": 14, "Se": 34, "As": 33}
# default valence for implicit-H counting (organic subset)
_VALENCE = {"B": 3, "C": 4, "N": 3, "O": 2, "P": 3, "S": 2, "F": 1,
            "Cl": 1, "Br": 1, "I": 1, "H": 1}

_BRACKET = re.compile(
    r"\[(?P<iso>\d+)?(?P<sym>[A-Z][a-z]?|[bcnops])(?P<chiral>@{0,2})"
    r"(?P<h>H\d*)?(?P<chg>[+-]+\d*|\+\d+|-\d+)?(?::\d+)?\]"
)

# covalent radii (Angstrom) for the 3D embedding's bond lengths
_RCOV = {1: 0.31, 5: 0.84, 6: 0.76, 7: 0.71, 8: 0.66, 9: 0.57, 14: 1.11,
         15: 1.07, 16: 1.05, 17: 1.02, 33: 1.19, 34: 1.20, 35: 1.20, 53: 1.39}


class SmilesError(ValueError):
    pass


def parse_smiles(s: str):
    """Parse a SMILES string.

    Returns ``(symbols, aromatic, charges, explicit_h, bonds)`` where bonds
    is a list of ``(i, j, order)`` (order 1.5 = aromatic).
    """
    symbols: List[str] = []
    aromatic: List[bool] = []
    charges: List[int] = []
    explicit_h: List[Optional[int]] = []  # None = implicit by valence
    bonds: List[Tuple[int, int, float]] = []
    prev: Optional[int] = None
    stack: List[Optional[int]] = []
    rings: Dict[str, Tuple[int, Optional[float]]] = {}
    pending_bond: Optional[float] = None
    i = 0
    n = len(s)

    def add_atom(sym: str, arom: bool, chg: int = 0, h: Optional[int] = None) -> int:
        symbols.append(sym)
        aromatic.append(arom)
        charges.append(chg)
        explicit_h.append(h)
        return len(symbols) - 1

    def bond_to(idx: int):
        nonlocal pending_bond, prev
        if prev is not None:
            order = pending_bond
            if order is None:
                order = 1.5 if (aromatic[prev] and aromatic[idx]) else 1.0
            bonds.append((prev, idx, order))
        pending_bond = None
        prev = idx

    while i < n:
        ch = s[i]
        if ch == "(":
            stack.append(prev)
            i += 1
        elif ch == ")":
            if not stack:
                raise SmilesError(f"unbalanced ')' in {s!r}")
            prev = stack.pop()
            i += 1
        elif ch in "-=#:":
            pending_bond = {"-": 1.0, "=": 2.0, "#": 3.0, ":": 1.5}[ch]
            i += 1
        elif ch in "/\\":
            i += 1  # cis/trans stereo: topology-irrelevant, skip
        elif ch == ".":
            prev = None  # disconnected component
            pending_bond = None
            i += 1
        elif ch.isdigit() or ch == "%":
            if ch == "%":
                label = s[i + 1:i + 3]
                i += 3
            else:
                label = ch
                i += 1
            if prev is None:
                raise SmilesError(f"ring closure before any atom in {s!r}")
            if label in rings:
                j, open_order = rings.pop(label)
                order = pending_bond or open_order
                if order is None:
                    order = 1.5 if (aromatic[prev] and aromatic[j]) else 1.0
                bonds.append((j, prev, order))
                pending_bond = None
            else:
                rings[label] = (prev, pending_bond)
                pending_bond = None
        elif ch == "[":
            m = _BRACKET.match(s, i)
            if not m:
                raise SmilesError(f"bad bracket atom at {i} in {s!r}")
            sym = m.group("sym")
            arom = sym in _AROMATIC
            if arom:
                sym = _AROMATIC[sym]
            h = m.group("h")
            hcount = 0 if h is None else (1 if h == "H" else int(h[1:]))
            chg_s = m.group("chg") or ""
            if chg_s in ("+", "-"):
                chg = 1 if chg_s == "+" else -1
            elif chg_s in ("++", "--"):
                chg = 2 if chg_s == "++" else -2
            elif chg_s:
                chg = int(chg_s[1:]) * (1 if chg_s[0] == "+" else -1)
            else:
                chg = 0
            idx = add_atom(sym, arom, chg, hcount)
            bond_to(idx)
            i = m.end()
        else:
            matched = None
            for sym in _ORGANIC:
                if s.startswith(sym, i):
                    matched = sym
                    break
            if matched:
                idx = add_atom(matched, False)
                bond_to(idx)
                i += len(matched)
            elif ch in _AROMATIC:
                idx = add_atom(_AROMATIC[ch], True)
                bond_to(idx)
                i += 1
            else:
                raise SmilesError(f"unexpected {ch!r} at {i} in {s!r}")
    if stack:
        raise SmilesError(f"unbalanced '(' in {s!r}")
    if rings:
        raise SmilesError(f"unclosed ring bond(s) {sorted(rings)} in {s!r}")
    return symbols, aromatic, charges, explicit_h, bonds


def _implicit_h(sym: str, arom: bool, charge: int, bond_order_sum: float) -> int:
    val = _VALENCE.get(sym)
    if val is None:
        return 0
    if sym == "N" and charge > 0:
        val = 4
    elif sym == "O" and charge > 0:
        val = 3
    elif charge < 0:
        val = max(val + charge, 0)
    used = int(round(bond_order_sum)) if not arom else int(np.ceil(bond_order_sum))
    return max(val - used, 0)


def _embed_3d(z: np.ndarray, bonds: List[Tuple[int, int, float]],
              seed: int = 0) -> np.ndarray:
    """Place atoms so bonded pairs sit near the sum of covalent radii:
    breadth-first placement with short steric relaxation. Not a
    conformer generator — just enough geometry for radius-based models."""
    rng = np.random.default_rng(seed)
    n = z.shape[0]
    pos = np.zeros((n, 3))
    adj: List[List[Tuple[int, float]]] = [[] for _ in range(n)]
    for a, b, _ in bonds:
        d = _RCOV.get(int(z[a]), 0.8) + _RCOV.get(int(z[b]), 0.8)
        adj[a].append((b, d))
        adj[b].append((a, d))
    placed = np.zeros(n, bool)
    for root in range(n):
        if placed[root]:
            continue
        pos[root] = rng.normal(0, 4.0, 3)
        placed[root] = True
        queue = [root]
        while queue:
            cur = queue.pop()
            for nb, d in adj[cur]:
                if placed[nb]:
                    continue
                direction = rng.normal(0, 1, 3)
                direction /= np.linalg.norm(direction)
                pos[nb] = pos[cur] + direction * d
                placed[nb] = True
                queue.append(nb)
    # relaxation: push non-bonded close pairs apart while springs keep
    # bonded pairs at their covalent distance
    bonded = {(min(a, b), max(a, b)) for a, b, _ in bonds}
    bond_idx = np.asarray([[a, b] for a, b, _ in bonds], np.int64).reshape(-1, 2)
    bond_len = np.asarray(
        [_RCOV.get(int(z[a]), 0.8) + _RCOV.get(int(z[b]), 0.8) for a, b, _ in bonds]
    )
    for _ in range(80):
        diff = pos[:, None, :] - pos[None, :, :]
        dist = np.linalg.norm(diff, axis=-1) + np.eye(n)
        push = np.maximum(1.4 - dist, 0.0)
        for (a, b) in bonded:
            push[a, b] = push[b, a] = 0.0
        force = (push[:, :, None] * diff / dist[:, :, None]).sum(axis=1)
        if bond_idx.size:
            bvec = pos[bond_idx[:, 0]] - pos[bond_idx[:, 1]]
            bdist = np.maximum(np.linalg.norm(bvec, axis=1), 1e-9)
            stretch = (bdist - bond_len) / bdist  # >0 too long, <0 too short
            pull = stretch[:, None] * bvec
            np.add.at(force, bond_idx[:, 0], -pull)
            np.add.at(force, bond_idx[:, 1], pull)
        if np.abs(force).max() < 1e-3:
            break
        pos += 0.3 * force
    return pos


# width of the node-feature table smiles_to_graph emits:
# [Z, degree, charge, aromatic, n_H, sp, sp2, sp3]
N_NODE_FEATURE_COLS = 8


def columnar_schema_current(path: str) -> bool:
    """True iff the columnar dataset at ``path`` was written with the
    CURRENT SMILES feature table (x width ``N_NODE_FEATURE_COLS``).

    For example drivers that cache `build_dataset` output: a dataset from
    an older table (e.g. the 5-column pre-hybridization layout) must be
    rebuilt or the config's ``input_node_features`` indexes columns the
    arrays don't have. Raises (rather than reporting stale) when the
    metadata cannot be read — a transient read failure must not trigger a
    delete-and-rebuild of real data.
    """
    import json as _json

    meta_path = os.path.join(path, "shard00000", "meta.json")
    with open(meta_path) as f:  # OSError propagates: do NOT rebuild blindly
        meta = _json.load(f)
    try:
        return meta["fields"]["x"]["suffix"] == [N_NODE_FEATURE_COLS]
    except KeyError:
        return False  # a meta without an x field IS a schema mismatch


def _hybridization(z: int, aromatic: bool, charge: int,
                   sigma: int, order_sum: float) -> Tuple[int, int, int]:
    """(sp, sp2, sp3) one-hot, rdkit-free.

    The reference one-hot encodes HybridizationType SP/SP2/SP3 per atom
    (smiles_utils.py:58-70). Without rdkit the same labels follow from
    bond structure: pi = total bond order minus sigma bonds (aromatic
    bonds contribute 0.5 each); >=2 pi -> SP, 1 pi or aromatic -> SP2,
    otherwise the VSEPR steric number (sigma bonds + lone pairs, lone
    pairs from the valence-electron count) picks 4 -> SP3, 3 -> SP2,
    2 -> SP. Hydrogen and bare ions are unhybridized (all zeros), like
    rdkit's HybridizationType.S.
    """
    if z == 1 or sigma == 0:
        return 0, 0, 0
    pi = int(round(order_sum - sigma))
    if aromatic:
        return 0, 1, 0
    if pi >= 2:
        return 1, 0, 0
    if pi == 1:
        return 0, 1, 0
    ve = {5: 3, 6: 4, 7: 5, 8: 6, 9: 7, 15: 5, 16: 6, 17: 7, 35: 7, 53: 7}
    lone = max(0, (ve.get(z, 4) - charge - int(round(order_sum)))) // 2
    steric = sigma + lone
    if steric >= 4:
        return 0, 0, 1
    if steric == 3:
        return 0, 1, 0
    return 1, 0, 0


def smiles_to_graph(
    s: str,
    add_hydrogens: bool = True,
    embed_3d: bool = True,
    graph_y: Optional[np.ndarray] = None,
    seed: int = 0,
) -> Graph:
    """SMILES -> ``Graph`` with the reference's feature-table convention
    (smiles_utils.py: one-hot atom type + degree + H-count columns,
    IsAromatic + HSP/HSP2/HSP3 hybridization one-hots, smiles_utils.py:19-70).

    Node feature table columns: ``[Z, degree, charge, aromatic, n_H,
    sp, sp2, sp3]`` (hybridization appended last so pre-round-4 column
    indices remain valid); bonds become bidirectional edges with
    ``edge_attr = [bond_order]``.
    """
    symbols, aromatic, charges, explicit_h, bonds = parse_smiles(s)
    order_sum = np.zeros(len(symbols))
    for a, b, o in bonds:
        order_sum[a] += o
        order_sum[b] += o
    n_h = [
        h if h is not None else _implicit_h(sym, ar, chg, osum)
        for sym, ar, chg, h, osum in zip(
            symbols, aromatic, charges, explicit_h, order_sum
        )
    ]
    unknown = sorted({sym for sym in symbols if sym not in _Z})
    if unknown:
        raise SmilesError(
            f"unsupported element(s) {unknown} in {s!r} (supported: "
            f"{sorted(_Z)})"
        )
    z = [_Z[sym] for sym in symbols]
    deg = np.zeros(len(symbols))
    for a, b, _ in bonds:
        deg[a] += 1
        deg[b] += 1
    if add_hydrogens:
        heavy_n = len(symbols)
        for i in range(heavy_n):
            for _ in range(int(n_h[i])):
                z.append(1)
                charges.append(0)
                aromatic.append(False)
                bonds.append((i, len(z) - 1, 1.0))
                deg[i] += 1
        deg = np.concatenate([deg[:heavy_n], np.ones(len(z) - heavy_n)])
        n_h = list(n_h) + [0] * (len(z) - heavy_n)
    z_arr = np.asarray(z, np.int32)
    # hybridization from the full bond structure (sigma = bonded neighbors
    # incl. hydrogens = deg; order_sum recomputed over the final bond list)
    full_order = np.zeros(len(z))
    for a, b, o in bonds:
        full_order[a] += o
        full_order[b] += o
    imp_h = np.zeros(len(z)) if add_hydrogens else np.asarray(n_h, float)
    hyb = np.asarray(
        [
            _hybridization(
                int(z_arr[i]), bool(aromatic[i]), int(charges[i]),
                int(deg[i] + imp_h[i]), float(full_order[i] + imp_h[i]),
            )
            for i in range(len(z))
        ],
        np.float32,
    )
    x = np.stack([
        z_arr.astype(np.float32),
        deg.astype(np.float32),
        np.asarray(charges, np.float32),
        np.asarray(aromatic, np.float32),
        np.asarray(n_h, np.float32),
    ], axis=1)
    x = np.concatenate([x, hyb], axis=1)
    senders, receivers, orders = [], [], []
    for a, b, o in bonds:
        senders += [a, b]
        receivers += [b, a]
        orders += [o, o]
    pos = (
        _embed_3d(z_arr, bonds, seed=seed)
        if embed_3d
        else np.zeros((len(z), 3))
    )
    return Graph(
        x=x,
        pos=pos.astype(np.float32),
        senders=np.asarray(senders, np.int32),
        receivers=np.asarray(receivers, np.int32),
        edge_attr=np.asarray(orders, np.float32)[:, None],
        graph_y=None if graph_y is None else np.asarray(graph_y, np.float32),
        z=z_arr,
    )


# drug-like fragments used by the shaped SMILES datasets (valid SMILES,
# composable by string concatenation at the chain level)
_FRAGMENTS = [
    "CC", "CCC", "C(C)C", "CO", "CN", "C=O", "CCl", "CF", "CS",
    "c1ccccc1", "c1ccncc1", "c1ccoc1", "c1ccsc1", "C1CCCCC1", "C1CCNCC1",
    "C(=O)O", "C(=O)N", "C#N", "OC", "N(C)C",
]


def random_drug_smiles(rng: np.random.Generator, n_frag: int = 3) -> str:
    """A random valid drug-like SMILES string by fragment chaining."""
    return "".join(
        _FRAGMENTS[int(rng.integers(len(_FRAGMENTS)))]
        for _ in range(max(1, n_frag))
    )


def smiles_table_dataset(
    number_configurations: int = 256,
    target_fn=None,
    seed: int = 61,
) -> List[Graph]:
    """CSCE/OGB-*shaped*: random drug-like SMILES parsed through the real
    SMILES path, graph target = ``target_fn(graph)`` (default: a
    closed-form electronic-gap-like function of composition and bond
    orders, learnable from the feature table). Reference:
    examples/csce/train_gap.py and examples/ogb/train_gap.py, which read
    SMILES CSVs and train a gap regression."""
    rng = np.random.default_rng(seed)
    if target_fn is None:
        from .shaped import _en_of

        def target_fn(g: Graph) -> float:
            arom_frac = float(g.x[:, 3].mean())
            return float(
                _en_of(g.z).mean() + 0.8 * arom_frac - 0.01 * g.num_nodes
            )
    graphs: List[Graph] = []
    while len(graphs) < number_configurations:
        s = random_drug_smiles(rng, int(rng.integers(2, 5)))
        try:
            g = smiles_to_graph(s, seed=int(rng.integers(2**31)))
        except SmilesError:
            continue
        g.graph_y = np.asarray([target_fn(g)], np.float32)
        graphs.append(g)
    return graphs
