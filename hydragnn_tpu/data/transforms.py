"""Load-time geometric transforms: rotational normalization, edge-length
descriptors with global-max normalization, spherical coordinates, and
point-pair features.

TPU-native equivalent of the reference's serialized-loader transform chain
(reference: hydragnn/preprocess/serialized_dataset_loader.py:130-180, which
applies torch_geometric ``NormalizeRotation``, ``Distance(norm=False,
cat=True)``, a distributed global-max edge normalization, and the
``Spherical`` / ``PointPairFeatures`` descriptors). Everything here is
host-side numpy preprocessing — it runs once per sample, never inside the
jitted step loop.

Order of application (mirroring the reference loader):
  1. ``normalize_rotation``        (before edge construction)
  2. radius graph                  (data/neighbors.py)
  3. ``add_edge_lengths``
  4. ``normalize_edge_attr``       (divide by global max, all processes agree)
  5. ``add_spherical_descriptors`` / ``add_point_pair_features``
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from .graph import Graph
from .neighbors import edge_vectors_and_lengths


# ---------------------------------------------------------------------------
# rotational normalization
# ---------------------------------------------------------------------------


def principal_rotation(pos: np.ndarray) -> np.ndarray:
    """Rotation matrix onto the principal axes of a point set's covariance.

    Same construction as torch_geometric ``NormalizeRotation(max_points=-1,
    sort=False)`` used by the reference (serialized_dataset_loader.py:130-132):
    eigenvectors of the centered scatter matrix ``P^T P`` (ascending
    eigenvalue order), applied to the *uncentered* positions. On top of the
    PyG behavior the eigenvector signs are fixed deterministically via a
    tie-robust odd functional, so for structures with distinct covariance
    eigenvalues the frame is fully canonical (PyG is only canonical up to
    axis sign). Structures with a *degenerate* spectrum (e.g. perfectly
    cubic/isotropic) keep an arbitrary basis of the degenerate subspace —
    inherent to any PCA frame, same as the reference.
    """
    pos = np.asarray(pos, np.float64)
    centered = pos - pos.mean(axis=0, keepdims=True)
    scatter = centered.T @ centered
    _, vecs = np.linalg.eigh(scatter)  # columns = eigenvectors, ascending
    proj = centered @ vecs
    # Deterministic sign: a rotation of the input flips each projected column
    # at most globally (nodes are not permuted), so any odd functional of the
    # column fixes the sign. A fixed pseudo-random weighting is tie-robust
    # where plain argmax is not (symmetric lattices have exactly-tied |proj|
    # entries whose argmax is decided by rounding noise).
    weights = np.cos(1.0 + np.arange(proj.shape[0], dtype=np.float64))
    for c in range(proj.shape[1]):
        col = proj[:, c]
        s = float(weights @ col)
        if abs(s) <= 1e-9 * (np.linalg.norm(col) + 1e-30):
            idx = int(np.argmax(np.abs(col)))
            s = float(col[idx])
        if s < 0:
            vecs[:, c] = -vecs[:, c]
    return vecs


def normalize_rotation_pos(pos: np.ndarray) -> np.ndarray:
    """Rotate positions into their principal-axis frame."""
    return (np.asarray(pos, np.float64) @ principal_rotation(pos)).astype(np.float32)


# node-target names that are cartesian vectors and must co-rotate with the
# geometry (forces transform covariantly: E invariant => F' = F R)
_VECTOR_NODE_TARGETS = ("forces",)


def normalize_rotation(graph: Graph) -> Graph:
    """Rotate one graph into its canonical frame.

    Positions, PBC shift vectors, the lattice cell, and vector-valued node
    targets (forces) all rotate with the same matrix, so edge displacements
    (``pos[r] - pos[s] - shift``) and the force/energy relationship
    ``F = -dE/dpos`` are preserved exactly — the transform is therefore safe
    whether applied before or after edge construction (the reference only
    supports before, serialized_dataset_loader.py:130-134).
    """
    rot = principal_rotation(graph.pos)
    rep = {"pos": (np.asarray(graph.pos, np.float64) @ rot).astype(np.float32)}
    if graph.edge_shifts is not None:
        rep["edge_shifts"] = (
            np.asarray(graph.edge_shifts, np.float64) @ rot
        ).astype(np.float32)
    if graph.cell is not None:
        rep["cell"] = (np.asarray(graph.cell, np.float64) @ rot).astype(np.float32)
    if graph.node_targets:
        nt = dict(graph.node_targets)
        for key in _VECTOR_NODE_TARGETS:
            if key in nt and nt[key].shape[-1] == 3:
                nt[key] = (np.asarray(nt[key], np.float64) @ rot).astype(np.float32)
        rep["node_targets"] = nt
    return dataclasses.replace(graph, **rep)


# ---------------------------------------------------------------------------
# edge-length descriptor + global-max normalization
# ---------------------------------------------------------------------------


def _cat_edge_attr(graph: Graph, cols: np.ndarray) -> Graph:
    cols = np.asarray(cols, np.float32)
    if graph.edge_attr is None:
        attr = cols
    else:
        attr = np.concatenate([np.asarray(graph.edge_attr, np.float32), cols], axis=1)
    return dataclasses.replace(graph, edge_attr=attr)


def _graph_edge_geometry(graph: Graph):
    """(vec, length) for a graph's edges, shift-aware."""
    return edge_vectors_and_lengths(
        graph.pos, graph.senders, graph.receivers, graph.edge_shifts
    )


def add_edge_lengths(graph: Graph, vec_length=None) -> Graph:
    """Append the edge length as an edge-attribute column.

    Equivalent of ``Distance(norm=False, cat=True)`` on the reference's
    non-PBC path (serialized_dataset_loader.py:154-156); PBC shifts are
    honored when present (the reference attaches PBC lengths during graph
    construction instead).
    """
    _, length = vec_length if vec_length is not None else _graph_edge_geometry(graph)
    return _cat_edge_attr(graph, length[:, None])


def global_max_edge_attr(graphs: Sequence[Graph]) -> float:
    """Max entry of ``edge_attr`` across all graphs and all processes.

    The reference reduces this max with ``torch.distributed.all_reduce(MAX)``
    (serialized_dataset_loader.py:157-170); here the cross-host reduction
    rides jax's DCN client when more than one process is attached.
    """
    local = float("-inf")
    for g in graphs:
        if g.edge_attr is not None and g.edge_attr.size:
            local = max(local, float(np.max(g.edge_attr)))
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(np.asarray(local, np.float32))
        local = float(np.max(gathered))
    return local


def normalize_edge_attr(
    graphs: Sequence[Graph], max_value: Optional[float] = None
) -> List[Graph]:
    """Divide every graph's full ``edge_attr`` by the global max entry
    (reference: serialized_dataset_loader.py:171-173 divides the whole
    edge_attr tensor, not just the length column)."""
    if max_value is None:
        max_value = global_max_edge_attr(graphs)
    if not np.isfinite(max_value) or max_value == 0.0:
        return list(graphs)
    return [
        dataclasses.replace(g, edge_attr=np.asarray(g.edge_attr, np.float32) / max_value)
        if g.edge_attr is not None
        else g
        for g in graphs
    ]


# ---------------------------------------------------------------------------
# spherical coordinates
# ---------------------------------------------------------------------------


def add_spherical_descriptors(
    graph: Graph, rho_max: Optional[float] = None, vec_length=None
) -> Graph:
    """Append per-edge spherical coordinates ``[rho, theta, phi]``.

    Semantics of torch_geometric ``Spherical(norm=True, cat=True)`` — the
    descriptor the reference requests via ``Dataset.Descriptors.
    SphericalCoordinates`` (serialized_dataset_loader.py:66-74,176-177):
    rho = edge length scaled to [0, 1] by the max length in the graph,
    theta = azimuth / 2*pi wrapped to [0, 1], phi = inclination / pi.
    Displacements are sender->receiver and PBC-shift aware.
    """
    vec, length = vec_length if vec_length is not None else _graph_edge_geometry(graph)
    rho = length.copy()
    scale = rho_max if rho_max is not None else (np.max(rho) if rho.size else 1.0)
    if scale > 0:
        rho = rho / scale
    theta = np.arctan2(vec[:, 1], vec[:, 0])
    theta = theta + (theta < 0) * (2.0 * np.pi)
    theta = theta / (2.0 * np.pi)
    with np.errstate(invalid="ignore", divide="ignore"):
        phi = np.arccos(np.clip(vec[:, 2] / np.maximum(length, 1e-12), -1.0, 1.0))
    phi = phi / np.pi
    return _cat_edge_attr(graph, np.stack([rho, theta, phi], axis=1))


# ---------------------------------------------------------------------------
# point-pair features
# ---------------------------------------------------------------------------


def estimate_normals(
    pos: np.ndarray,
    senders: np.ndarray,
    receivers: np.ndarray,
    edge_shifts: Optional[np.ndarray] = None,
    vec: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Per-node unit normals from local-neighborhood PCA.

    Atomistic samples carry no surface normals (the torch_geometric
    ``PointPairFeatures`` transform the reference names requires
    ``data.normal``), so normals are estimated the standard point-cloud way:
    the smallest-eigenvalue eigenvector of the neighbor-displacement
    covariance, with a deterministic sign. Displacements are PBC-shift
    aware. Nodes with fewer than 2 incoming edges get the z unit vector.
    """
    n = pos.shape[0]
    normals = np.zeros((n, 3), np.float64)
    normals[:, 2] = 1.0
    if senders.size == 0:
        return normals.astype(np.float32)
    pos = np.asarray(pos, np.float64)
    # displacement node -> neighbor image, shift-corrected; grouped per
    # receiver in (receiver, sender) order so the result is independent of
    # the builder's edge emission order, in O(E log E) not O(N*E)
    if vec is None:
        vec, _ = edge_vectors_and_lengths(pos, senders, receivers, edge_shifts)
    disp_all = -np.asarray(vec, np.float64)
    order = np.lexsort((senders, receivers))
    r_sorted = receivers[order]
    disp_sorted = disp_all[order]
    starts = np.searchsorted(r_sorted, np.arange(n), side="left")
    ends = np.searchsorted(r_sorted, np.arange(n), side="right")
    for i in range(n):
        disp = disp_sorted[starts[i] : ends[i]]
        if disp.shape[0] < 2:
            continue
        cov = disp.T @ disp
        _, vecs = np.linalg.eigh(cov)
        nrm = vecs[:, 0]  # smallest-variance direction
        # deterministic, rotation-stable sign: an odd functional of the
        # neighbor displacements projected on the normal (neighbors are
        # sorted by node id, so the projection flips exactly with the
        # normal under any rotation). If one weighting cancels to ~0 —
        # where rounding could flip the sign — try the next.
        proj = disp @ nrm
        # proj is meaningful only when the out-of-plane extent is a real
        # feature of the neighborhood, not rounding noise of the eigensolve
        scale = np.linalg.norm(proj) + 1e-30
        s = 0.0
        if np.linalg.norm(proj) > 1e-6 * np.linalg.norm(disp):
            for k in (1.0, 2.0, 3.0):
                cand = float(np.cos(k * (1.0 + np.arange(proj.size))) @ proj)
                if abs(cand) > 1e-6 * scale:
                    s = cand
                    break
        if s == 0.0:
            # exactly coplanar neighborhood: the projections carry no sign
            # information at all. det(d_a, d_b, n) is odd in n, invariant
            # under proper rotations, and maximal precisely in the flat case.
            for a in range(disp.shape[0] - 1):
                cand = float(np.dot(np.cross(disp[a], disp[a + 1]), nrm))
                if abs(cand) > 1e-9 * (
                    np.linalg.norm(disp[a]) * np.linalg.norm(disp[a + 1]) + 1e-30
                ):
                    s = cand
                    break
            else:
                s = 1.0
        if s > 0:
            nrm = -nrm  # point away from the (weighted) neighborhood
        normals[i] = nrm
    return normals.astype(np.float32)


def _angle(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    cross = np.linalg.norm(np.cross(a, b), axis=1)
    dot = np.sum(a * b, axis=1)
    return np.arctan2(cross, dot)


def add_point_pair_features(
    graph: Graph, normals: Optional[np.ndarray] = None, vec_length=None
) -> Graph:
    """Append PPF columns ``[||d||, ang(n1,d), ang(n2,d), ang(n1,n2)]``.

    Semantics of torch_geometric ``PointPairFeatures(cat=True)`` (requested
    via ``Dataset.Descriptors.PointPairFeatures``,
    serialized_dataset_loader.py:75-80,179-180), with normals estimated by
    ``estimate_normals`` when the sample does not provide any.
    """
    vec, length = vec_length if vec_length is not None else _graph_edge_geometry(graph)
    if normals is None:
        normals = estimate_normals(
            graph.pos, graph.senders, graph.receivers, graph.edge_shifts, vec=vec
        )
    n1 = np.asarray(normals, np.float64)[graph.senders]
    n2 = np.asarray(normals, np.float64)[graph.receivers]
    cols = np.stack(
        [length, _angle(n1, vec), _angle(n2, vec), _angle(n1, n2)], axis=1
    )
    return _cat_edge_attr(graph, cols)


# ---------------------------------------------------------------------------
# config-driven orchestration
# ---------------------------------------------------------------------------


def descriptor_edge_dim(dataset_cfg: dict) -> int:
    """Number of edge-attribute columns the model will see: one per
    ``edge_features`` entry ("lengths" is computed by the transform chain,
    any other name declares a column already stored in the dataset's
    edge_attr), +3 for SphericalCoordinates, +4 for PointPairFeatures.
    ``apply_post_edge_transforms`` checks the declaration against the actual
    data and raises on mismatch."""
    feats = dataset_cfg.get("edge_features") or []
    dim = len(feats)
    desc = dataset_cfg.get("Descriptors", {})
    if desc.get("SphericalCoordinates"):
        dim += 3
    if desc.get("PointPairFeatures"):
        dim += 4
    return dim


def wants_transforms(dataset_cfg: dict) -> bool:
    """True when the Dataset config requests any load-time transform."""
    return bool(
        dataset_cfg.get("rotational_invariance")
        or dataset_cfg.get("edge_features")
        or dataset_cfg.get("Descriptors")
    )


def apply_dataset_transforms(
    dataset_cfg: dict, *splits: Sequence[Graph]
) -> List[List[Graph]]:
    """Run the full transform chain over one or more dataset splits.

    Splits are concatenated for the edge-length normalization so all of them
    share one global max (the reference computes the max over the whole
    dataset before splitting, serialized_dataset_loader.py:157-173).
    """
    sizes = [len(s) for s in splits]
    combined: List[Graph] = [g for s in splits for g in s]
    combined = apply_pre_edge_transforms(combined, dataset_cfg)
    combined = apply_post_edge_transforms(combined, dataset_cfg)
    out, off = [], 0
    for sz in sizes:
        out.append(combined[off : off + sz])
        off += sz
    return out


def apply_pre_edge_transforms(
    graphs: Sequence[Graph], dataset_cfg: dict
) -> List[Graph]:
    """Transforms that must run before radius-graph construction."""
    if dataset_cfg.get("rotational_invariance"):
        graphs = [normalize_rotation(g) for g in graphs]
    return list(graphs)


def apply_post_edge_transforms(
    graphs: Sequence[Graph], dataset_cfg: dict
) -> List[Graph]:
    """Edge-descriptor chain, applied after edges exist.

    ``Dataset.edge_features: ["lengths"]`` attaches edge lengths normalized
    by the cross-process global max (serialized_dataset_loader.py:154-173);
    ``Dataset.Descriptors`` adds the Spherical / PointPairFeatures columns."""
    graphs = list(graphs)
    feats = dataset_cfg.get("edge_features") or []
    desc = dataset_cfg.get("Descriptors", {})
    if not (
        feats or desc.get("SphericalCoordinates") or desc.get("PointPairFeatures")
    ):
        return graphs
    # edge_features contract: "lengths" is computed here; any other name
    # declares a column the dataset must already carry in edge_attr
    stored = [f for f in feats if f != "lengths"]
    for g in graphs:
        have = 0 if g.edge_attr is None else int(g.edge_attr.shape[1])
        if have != len(stored):
            raise ValueError(
                f"Dataset.edge_features declares {len(stored)} stored "
                f"column(s) {stored} but a graph carries edge_attr with "
                f"{have} column(s); only 'lengths' is computed at load time"
            )
    # geometry is shared by every descriptor in the chain: compute once per
    # graph (positions/edges never change below this point)
    geos = [_graph_edge_geometry(g) for g in graphs]
    if feats:
        if "lengths" in feats:
            graphs = [add_edge_lengths(g, vl) for g, vl in zip(graphs, geos)]
        graphs = normalize_edge_attr(graphs)
    if desc.get("SphericalCoordinates"):
        graphs = [
            add_spherical_descriptors(g, vec_length=vl)
            for g, vl in zip(graphs, geos)
        ]
    if desc.get("PointPairFeatures"):
        graphs = [
            add_point_pair_features(g, vec_length=vl) for g, vl in zip(graphs, geos)
        ]
    return graphs
