"""LSMS physics utilities: total energy -> formation enthalpy / Gibbs free
energy conversion and compositional histogram downselection.

TPU-native equivalents of the reference's LSMS preprocessing tools
(reference: hydragnn/utils/lsms/convert_total_energy_to_formation_gibbs.py
and hydragnn/utils/lsms/compositional_histogram_cutoff.py). These are
host-side dataset preparation steps that rewrite/downselect raw LSMS text
files before graph construction — numpy-only, nothing device-side.

LSMS raw file layout (one configuration per file): a single header line
whose first token is the total energy (Rydberg), then one line per atom
whose first column is the atomic number (reference: read_file,
convert_total_energy_to_formation_gibbs.py:22-27). Both utilities support
binary alloys only, like the reference.
"""

from __future__ import annotations

import dataclasses
import math
import os
import shutil
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# LSMS energies are in Rydberg (reference:
# convert_total_energy_to_formation_gibbs.py:176-179)
_KB_JOULE_PER_KELVIN = 1.380649e-23
_JOULE_TO_RYDBERG = 4.5874208973812e17
KB_RYDBERG_PER_KELVIN = _KB_JOULE_PER_KELVIN * _JOULE_TO_RYDBERG


def read_lsms_file(path: str) -> Tuple[float, np.ndarray, List[str]]:
    """(total_energy, atom_table, raw_lines) of one LSMS configuration
    (reference: read_file, convert_total_energy_to_formation_gibbs.py:22-27)."""
    with open(path, encoding="utf-8") as f:
        lines = f.readlines()
    total_energy = float(lines[0].split()[0])
    atoms = np.loadtxt(lines[1:], ndmin=2)
    return total_energy, atoms, lines


def _lsms_files(dir: str) -> List[str]:
    """Sorted LSMS sample filenames — one filtering rule shared with the
    raw loaders (data/raw.py: raw_sample_files)."""
    from .raw import raw_sample_files

    return raw_sample_files(dir)


def _read_energy_and_z(path: str) -> Tuple[float, np.ndarray]:
    """Header energy + Z column only — cheap first-pass parse."""
    with open(path, encoding="utf-8") as f:
        lines = f.readlines()
    total_energy = float(lines[0].split()[0])
    zs = np.array(
        [float(l.split()[0]) for l in lines[1:] if l.strip()], np.float64
    )
    return total_energy, zs


def _binary_composition(
    z: np.ndarray, elements_list: Sequence[float]
) -> Tuple[float, int, int]:
    """(fraction of the first element, count of first element, num atoms)
    with the reference's pure-component fixup
    (convert_total_energy_to_formation_gibbs.py:151-162)."""
    elements_list = sorted(elements_list)
    elements, counts = np.unique(z, return_counts=True)
    for e in elements:
        if e not in elements_list:
            raise ValueError(
                f"sample contains element {e} not in the binary {elements_list}"
            )
    count_map = dict(zip(elements.tolist(), counts.tolist()))
    n0 = int(count_map.get(elements_list[0], 0))
    num_atoms = int(z.shape[0])
    return n0 / num_atoms, n0, num_atoms


def mixing_entropy(num_atoms: int, count_first: int) -> float:
    """Ideal-mixing (thermodynamic) entropy Kb * ln C(n, k) in Rydberg/K.

    Same quantity as the reference (:180-183), computed with ``lgamma`` so
    it stays finite for configurations large enough to overflow a direct
    binomial coefficient.
    """
    log_comb = (
        math.lgamma(num_atoms + 1)
        - math.lgamma(count_first + 1)
        - math.lgamma(num_atoms - count_first + 1)
    )
    return KB_RYDBERG_PER_KELVIN * log_comb


def compute_formation_enthalpy(
    z: np.ndarray,
    total_energy: float,
    elements_list: Sequence[float],
    pure_elements_energy: Dict[float, float],
) -> Tuple[float, float, float, float]:
    """(composition, linear_mixing_energy, formation_enthalpy, entropy) for a
    binary-alloy configuration (reference: compute_formation_enthalpy,
    convert_total_energy_to_formation_gibbs.py:141-185).

    ``pure_elements_energy`` maps element -> per-atom energy of the pure
    phase; the formation enthalpy is the total energy minus the linear
    mixing of the pure-phase energies at this composition.
    """
    elements_list = sorted(elements_list)
    composition, n0, num_atoms = _binary_composition(z, elements_list)
    linear_mixing_energy = (
        pure_elements_energy[elements_list[0]] * composition
        + pure_elements_energy[elements_list[1]] * (1.0 - composition)
    ) * num_atoms
    formation_enthalpy = total_energy - linear_mixing_energy
    entropy = mixing_entropy(num_atoms, n0)
    return composition, linear_mixing_energy, formation_enthalpy, entropy


@dataclasses.dataclass
class GibbsConversionResult:
    """Per-file statistics of a conversion run, for inspection/plots."""

    files: List[str]
    compositions: np.ndarray
    total_energies: np.ndarray
    linear_mixing_energies: np.ndarray
    formation_enthalpies: np.ndarray
    formation_gibbs_energies: np.ndarray
    output_dir: str


def convert_total_energy_to_formation_gibbs(
    dir: str,
    elements_list: Sequence[float],
    temperature_kelvin: float = 0.0,
    overwrite_data: bool = False,
    create_plots: bool = False,
) -> GibbsConversionResult:
    """Rewrite every LSMS file in ``dir`` with the total energy replaced by
    the formation Gibbs energy ``dH - T*S`` into ``<dir>_gibbs_energy/``
    (reference: convert_raw_data_energy_to_gibbs,
    convert_total_energy_to_formation_gibbs.py:30-139).

    Pure-element reference energies are discovered from the single-element
    configurations in the directory (two are required, binary alloys only).
    """
    dir = dir.rstrip("/")
    new_dir = dir + "_gibbs_energy"
    if os.path.exists(new_dir):
        if overwrite_data:
            shutil.rmtree(new_dir)
        else:
            # refusing beats silently mixing stale conversions (possibly
            # anchored on different pure-phase energies) into the output
            raise FileExistsError(new_dir)
    os.makedirs(new_dir)

    elements_list = sorted(elements_list)
    all_files = _lsms_files(dir)

    # pass 1: per-atom energies of the pure-element configurations (:52-63).
    # Light parse (header + Z column only) — the full atom table is only
    # needed by pass 2, so large directories are not loadtxt'd twice.
    pure_elements_energy: Dict[float, float] = {}
    for filename in all_files:
        total_energy, zs = _read_energy_and_z(os.path.join(dir, filename))
        pure = np.unique(zs)
        if len(pure) == 1:
            pure_elements_energy[float(pure[0])] = total_energy / zs.shape[0]
    if len(pure_elements_energy) != 2:
        raise ValueError(
            f"need exactly two single-element files to anchor the binary; "
            f"found pure phases for {sorted(pure_elements_energy)}"
        )

    # pass 2: formation enthalpy -> Gibbs, rewrite header (:75-107)
    n = len(all_files)
    comps = np.zeros(n)
    totals = np.zeros(n)
    linmix = np.zeros(n)
    enthalpy = np.zeros(n)
    gibbs = np.zeros(n)
    for i, filename in enumerate(all_files):
        path = os.path.join(dir, filename)
        total_energy, atoms, lines = read_lsms_file(path)
        comp, lm, dh, entropy = compute_formation_enthalpy(
            atoms[:, 0], total_energy, elements_list, pure_elements_energy
        )
        g = dh - temperature_kelvin * entropy
        comps[i], totals[i], linmix[i], enthalpy[i], gibbs[i] = (
            comp, total_energy, lm, dh, g,
        )
        header_tok = lines[0].split()[0]
        lines[0] = lines[0].replace(header_tok, repr(g), 1)
        with open(os.path.join(new_dir, filename), "w", encoding="utf-8") as f:
            f.write("".join(lines))

    result = GibbsConversionResult(
        files=all_files,
        compositions=comps,
        total_energies=totals,
        linear_mixing_energies=linmix,
        formation_enthalpies=enthalpy,
        formation_gibbs_energies=gibbs,
        output_dir=new_dir,
    )
    if create_plots:
        _plot_conversion(result)
    return result


def _plot_conversion(result: GibbsConversionResult) -> None:
    """Scatter plots of the conversion (reference: :111-139)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    # plots live next to the converted data, so runs on different datasets
    # from one cwd don't overwrite each other
    for fname, xs, ys, xl, yl in (
        ("linear_mixing_energy.png", result.total_energies,
         result.linear_mixing_energies, "Total energy (Rydberg)",
         "Linear mixing energy (Rydberg)"),
        ("formation_enthalpy.png", result.compositions,
         result.formation_enthalpies, "Concentration",
         "Formation enthalpy (Rydberg)"),
        ("formation_gibbs_energy.png", result.compositions,
         result.formation_gibbs_energies, "Concentration",
         "Formation Gibbs energy (Rydberg)"),
    ):
        plt.figure()
        plt.scatter(xs, ys, edgecolor="b", facecolor="none")
        plt.xlabel(xl)
        plt.ylabel(yl)
        plt.savefig(os.path.join(result.output_dir, fname))
        plt.close()


def find_bin(comp: float, nbins: int) -> int:
    """Composition -> histogram bin: ``nbins`` equal half-open bins over
    [0, 1], with comp == 1.0 in the last bin.

    Deviates deliberately from the reference (compositional_histogram_cutoff
    .py:8-13), whose strict-inequality scan drops every on-edge composition —
    including both pure endpoints 0.0 and 1.0 — into the last bin, making
    the endmembers share one bin budget.
    """
    return min(int(np.floor(comp * nbins)), nbins - 1)


def compositional_histogram_cutoff(
    dir: str,
    elements_list: Sequence[float],
    histogram_cutoff: int,
    num_bins: int,
    overwrite_data: bool = False,
    link: bool = True,
) -> List[str]:
    """Downselect LSMS files to at most ``histogram_cutoff - 1`` samples per
    composition bin, linking the keepers into ``<dir>_histogram_cutoff/``
    (reference: compositional_histogram_cutoff.py:16-75, which keeps a
    sample while its bin count is strictly below the cutoff *after*
    increment). ``link=False`` copies instead of symlinking (for
    filesystems without symlink support). Returns the kept filenames.
    """
    dir = dir.rstrip("/")
    new_dir = dir + "_histogram_cutoff"
    if os.path.exists(new_dir):
        if overwrite_data:
            shutil.rmtree(new_dir)
        else:
            raise FileExistsError(new_dir)
    os.makedirs(new_dir)

    kept: List[str] = []
    bin_counts = np.zeros(num_bins, np.int64)
    for filename in _lsms_files(dir):
        path = os.path.join(dir, filename)
        _, zs = _read_energy_and_z(path)
        comp, _, _ = _binary_composition(zs, elements_list)
        b = find_bin(comp, num_bins)
        bin_counts[b] += 1
        if bin_counts[b] < histogram_cutoff:
            kept.append(filename)
            new_path = os.path.join(new_dir, filename)
            if link:
                os.symlink(os.path.abspath(path), new_path)
            else:
                shutil.copyfile(path, new_path)
    return kept
