"""Geometry -> molecule perception: covalent-radius connectivity + integer
bond-order assignment + formal charges.

Compact, dependency-free behavioral analog of the reference's vendored
xyz2mol (reference: hydragnn/utils/descriptors_and_embeddings/
xyz2mol.py:1-1007, the Kim & Kim / Jensen-group algorithm wrapped around
rdkit). rdkit is not available in this image, so the useful subset is
implemented directly:

1. connectivity from covalent radii (bond when the distance is below
   ``tolerance * (r_i + r_j)`` — xyz2mol's own criterion),
2. integer bond orders by iterative saturation of free valences
   (double/triple bonds where both partners still have capacity),
3. formal charges from leftover (under/over)-saturation against the
   element's neutral valence.

Covers the organic set (H C N O F Si P S Cl Br I) the reference's pipeline
targets, including resonance-structure enumeration
(``resonance_structures``: all maximal bond-order assignments, filtered by
the minimal-|formal-charge| valence criterion — benzene yields its Kekulé
pair) and charged-fragment resolution (a declared net charge is matched
against the enumeration, the reference's ``charged_fragments=True``).
Output converts to a framework ``Graph`` with the bond order as the edge
attribute.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..ops.radial import COVALENT_RADII
from .graph import Graph

# neutral valences; first entry is preferred, later entries are permitted
# expansions (S 4/6, P 5) — mirrors xyz2mol's atomic_valence table
_VALENCES = {
    1: (1,),
    5: (3,),
    6: (4,),
    7: (3,),
    8: (2,),
    9: (1,),
    14: (4,),
    15: (3, 5),
    16: (2, 4, 6),
    17: (1,),
    35: (1,),
    53: (1,),
}


@dataclasses.dataclass
class Molecule:
    """Perceived molecule: atoms, integer-order bonds, formal charges."""

    z: np.ndarray  # [n] atomic numbers
    pos: np.ndarray  # [n, 3]
    bonds: List[Tuple[int, int, int]]  # (i, j, order), i < j
    formal_charges: np.ndarray  # [n] int

    @property
    def num_atoms(self) -> int:
        return int(self.z.shape[0])

    def to_graph(self) -> Graph:
        """Directed framework Graph; edge_attr = bond order (one column)."""
        senders, receivers, orders = [], [], []
        for i, j, o in self.bonds:
            senders += [i, j]
            receivers += [j, i]
            orders += [o, o]
        return Graph(
            x=self.z[:, None].astype(np.float32),
            pos=self.pos.astype(np.float32),
            senders=np.asarray(senders, np.int32),
            receivers=np.asarray(receivers, np.int32),
            edge_attr=np.asarray(orders, np.float32)[:, None],
            z=self.z.copy(),
        )


def connectivity(
    z: np.ndarray, pos: np.ndarray, tolerance: float = 1.3
) -> List[Tuple[int, int]]:
    """Single-bond skeleton: pairs closer than tolerance * sum of covalent
    radii (reference: xyz2mol get_AC, the adjacency-matrix construction)."""
    z = np.asarray(z)
    pos = np.asarray(pos, np.float64)
    radii = np.asarray([COVALENT_RADII[int(zz)] for zz in z])
    pairs = []
    n = z.shape[0]
    for i in range(n):
        d = np.linalg.norm(pos[i + 1 :] - pos[i], axis=1)
        cut = tolerance * (radii[i] + radii[i + 1 :])
        for off in np.nonzero(d < cut)[0]:
            pairs.append((i, int(i + 1 + off)))
    return pairs


def _formal_charges(z: np.ndarray, order: dict) -> np.ndarray:
    """Formal charge per atom for a bond-order assignment: deviation from
    the closest permitted valence (under-saturated O -> -1, four-bonded
    N -> +1, saturated atoms -> 0)."""
    formal = np.zeros(z.shape[0], np.int64)
    bo = np.zeros(z.shape[0], np.int64)
    for (a, b), o in order.items():
        bo[a] += o
        bo[b] += o
    for i in range(z.shape[0]):
        if int(z[i]) in _VALENCES:
            allowed = _VALENCES[int(z[i])]
            best = min(allowed, key=lambda v: abs(v - int(bo[i])))
            formal[i] = int(bo[i]) - best
    return formal


def enumerate_bond_orders(
    z: np.ndarray,
    skeleton: List[Tuple[int, int]],
    max_structures: int = 64,
) -> List[dict]:
    """All distinct MAXIMAL integer bond-order assignments over a bond
    skeleton — the resonance-structure enumeration of the reference's
    vendored xyz2mol (its BO-matrix search over unsaturated-atom
    combinations, hydragnn/utils/descriptors_and_embeddings/
    xyz2mol.py:1-1007). DFS over promotion choices with memoized states;
    ``max_structures`` bounds the (worst-case exponential) walk — aromatic
    rings yield their Kekulé alternatives well within it."""
    return _enumerate_bond_orders(z, skeleton, max_structures)[0]


def _enumerate_bond_orders(
    z: np.ndarray,
    skeleton: List[Tuple[int, int]],
    max_structures: int = 64,
) -> Tuple[List[dict], bool]:
    """(results, truncated): ``truncated`` tells the caller the walk hit its
    state bound, so an empty/short result list may be incomplete rather than
    exhaustive (perceive_molecule escalates the bound before declaring a
    declared charge unreachable)."""
    base = {tuple(p): 1 for p in skeleton}
    caps = {i: max(_VALENCES.get(int(zz), (4,))) for i, zz in enumerate(z)}

    def bo_sums(order):
        s = {i: 0 for i in range(z.shape[0])}
        for (a, b), o in order.items():
            s[a] += o
            s[b] += o
        return s

    results: List[dict] = []
    seen_states = set()
    # bound the WALK, not just the accepted results: large conjugated
    # systems have few maximal assignments but exponentially many partial
    # states, and an unbounded DFS would hang after finding them all
    max_states = 512 * max_structures
    truncated = False
    stack = [base]
    while stack and len(results) < max_structures:
        order = stack.pop()
        key = tuple(sorted(order.items()))
        if key in seen_states:
            continue
        if len(seen_states) >= max_states:
            truncated = True
            break
        seen_states.add(key)
        s = bo_sums(order)
        cands = [
            p
            for p, o in order.items()
            if o < 3 and caps[p[0]] - s[p[0]] > 0 and caps[p[1]] - s[p[1]] > 0
        ]
        if not cands:
            results.append(dict(order))
            continue
        for p in cands:
            nxt = dict(order)
            nxt[p] += 1
            stack.append(nxt)
    if stack and len(results) >= max_structures:
        truncated = True
    return results, truncated


def resonance_structures(
    z: Sequence[int],
    pos: np.ndarray,
    tolerance: float = 1.3,
    max_structures: int = 64,
) -> List[Molecule]:
    """Every distinct maximal bond-order assignment as a Molecule (the
    reference returns one rdkit mol per resonance structure). The DFS also
    reaches stuck assignments (promotions alternated such that leftover
    free valences are non-adjacent); like the reference's BO_is_OK valence
    filter, only assignments with the minimal total |formal charge| are
    kept — for benzene that is exactly the Kekulé pair."""
    z = np.asarray(z, np.int64)
    pos = np.asarray(pos, np.float64)
    skeleton = connectivity(z, pos, tolerance)
    scored = []
    for order in enumerate_bond_orders(z, skeleton, max_structures):
        formal = _formal_charges(z, order)
        scored.append((int(np.abs(formal).sum()), order, formal))
    if not scored:
        return []
    best = min(s for s, _, _ in scored)
    mols = []
    for s, order, formal in scored:
        if s != best:
            continue
        bonds = sorted((a, b, o) for (a, b), o in order.items())
        mols.append(Molecule(z=z, pos=pos, bonds=bonds, formal_charges=formal))
    return mols


def perceive_molecule(
    z: Sequence[int],
    pos: np.ndarray,
    charge: Optional[int] = None,
    tolerance: float = 1.3,
) -> Molecule:
    """Bond orders + formal charges from geometry.

    Free valence = preferred valence - current bond-order sum; bonds where
    both partners have free valence are promoted (double, then triple), most
    -saturable pairs first — the saturation loop at the core of xyz2mol's
    BO-matrix search, without the resonance enumeration. Whatever
    unsaturation remains becomes formal charge (O with one single bond ->
    O^-, N with four bonds -> N^+), and the total is checked against
    ``charge`` when provided.
    """
    z = np.asarray(z, np.int64)
    pos = np.asarray(pos, np.float64)
    skeleton = connectivity(z, pos, tolerance)
    order = {p: 1 for p in skeleton}

    def allowed(i):
        return _VALENCES.get(int(z[i]), (4,))

    def bo_sum(i):
        return sum(o for (a, b), o in order.items() if a == i or b == i)

    def free(i):
        # highest permitted valence still reachable counts as capacity,
        # preferred valence drives the promotion priority
        return max(allowed(i)) - bo_sum(i)

    changed = True
    while changed:
        changed = False
        # promote the pair whose partners are both most unsaturated
        candidates = [
            (min(free(a), free(b)), (a, b))
            for (a, b) in order
            if free(a) > 0 and free(b) > 0 and order[(a, b)] < 3
        ]
        if not candidates:
            break
        candidates.sort(key=lambda t: (-t[0], t[1]))
        _, pair = candidates[0]
        order[pair] += 1
        changed = True

    formal = np.zeros(z.shape[0], np.int64)
    for i in range(z.shape[0]):
        s = bo_sum(i)
        if int(z[i]) in _VALENCES:
            # deviation from the closest permitted valence is the formal
            # charge: under-saturated O -> -1 (hydroxide), over-saturated
            # N -> +1 (ammonium), saturated atoms -> 0
            best = min(allowed(i), key=lambda v: abs(v - s))
            formal[i] = s - best
    if charge is not None and int(formal.sum()) != charge:
        # charged-fragment resolution (reference: xyz2mol
        # charged_fragments=True): among all enumerated assignments whose
        # formal charges sum to the declared total, pick the one with the
        # minimal total |formal charge| — the same valence criterion the
        # resonance filter applies, so the result is chemically sensible
        # and independent of DFS enumeration order
        # the walk bound can hide the matching assignment on large
        # conjugated systems — escalate it before declaring the charge
        # unreachable (each retry is 16x more visited states)
        truncated = False
        for bound in (64, 1024, 16384):
            matches = []
            alts, truncated = _enumerate_bond_orders(z, skeleton, bound)
            for alt in alts:
                alt_formal = _formal_charges(z, alt)
                if int(alt_formal.sum()) == charge:
                    matches.append(
                        (int(np.abs(alt_formal).sum()), alt, alt_formal)
                    )
            if matches or not truncated:
                break
        if matches:
            _, alt, alt_formal = min(
                matches, key=lambda t: (t[0], sorted(t[1].items()))
            )
            bonds = sorted((a, b, o) for (a, b), o in alt.items())
            return Molecule(
                z=z, pos=pos, bonds=bonds, formal_charges=alt_formal
            )
        raise ValueError(
            f"perceived total formal charge {int(formal.sum())} != declared "
            f"charge {charge} in any "
            + ("ENUMERATED (walk bound hit — result incomplete) "
               if truncated else "")
            + f"resonance structure; geometry may be mis-bonded at "
            f"tolerance={tolerance}"
        )
    bonds = sorted((a, b, o) for (a, b), o in order.items())
    return Molecule(z=z, pos=pos, bonds=bonds, formal_charges=formal)


def xyz_to_graph(
    z: Sequence[int], pos: np.ndarray, charge: Optional[int] = None
) -> Graph:
    """Geometry -> bonded Graph with bond-order edge attributes (the
    endpoint the reference reaches through rdkit mol objects)."""
    return perceive_molecule(z, pos, charge).to_graph()
