"""Sample validation and quarantine — the ingest gate of the fault-tolerant
data plane (docs/ROBUSTNESS.md "Data plane").

The foundation-model workload streams tens of heterogeneous chemistry
datasets through the loader (SURVEY §0, pillar 2); at that scale dirty
samples are the common case, and a single NaN feature or out-of-range edge
index must not kill a multi-day run *or* poison it silently (one NaN sample
reaching ``MinMax.fit`` NaNs the normalization of every sample). This module
provides:

- ``validate_graph``: one sample -> rejection reason or None. Checks every
  numeric channel for non-finite values (``Graph.float_channels`` is the
  field census), edge indices for range/degeneracy (senders/receivers
  outside ``[0, num_nodes)``, self-loop-only connectivity), empty graphs,
  and optional node/edge pad-budget caps.
- ``SampleValidator``: applies ``Dataset.bad_sample_policy`` to every
  rejection — ``error`` raises a ``BadSampleError`` naming the sample,
  ``warn_skip`` (default) drops it with a per-reason structured count,
  ``quarantine`` additionally records it in a run-dir JSONL manifest
  (``quarantine/manifest.jsonl``: index, dataset_id, reason, sizes) so the
  bad samples are findable without a bisect. The per-reason tally is logged
  by the epoch loop (train/loop.py) — silent data loss is impossible.

Validation runs at *ingestion* (api.prepare_data filters the raw dataset
before normalization/splitting) and again structurally at *batch* time (the
pack-mode budget check in data/pipeline.py consults the same validator, so
a budget-overflow graph is skipped-and-counted instead of killing the run).

Exercised by fault injection (utils/faultinject.py:
``HYDRAGNN_FAULT_SAMPLE_NAN`` / ``HYDRAGNN_FAULT_CORRUPT_SAMPLE``) in
tests/test_data_plane.py and run-scripts/data_chaos_smoke.py.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional, Sequence

import numpy as np

from .graph import Graph

POLICIES = ("error", "warn_skip", "quarantine")

# rejection reasons (the keys of the structured skip tally)
R_NONFINITE = "nonfinite_features"  # any non-finite numeric channel
R_BAD_EDGE = "bad_edge_index"  # sender/receiver outside [0, num_nodes)
R_SELF_LOOP = "self_loop_only"  # every edge is a self loop
R_EMPTY = "empty_graph"  # zero nodes
R_BUDGET = "budget_overflow"  # exceeds the pad/pack budget
R_CORRUPT = "corrupt_sample"  # bytes failed to deserialize
R_CHANNELS = "channel_mismatch"  # feature channel layout != the served model's

# human-readable expansion of each rejection reason — shared by the data
# plane's skip log and the serving plane's typed per-request errors
# (serve/errors.InvalidRequestError), so both surfaces describe a bad
# sample in the same words
REASON_MESSAGES = {
    R_NONFINITE: "a numeric channel contains NaN/Inf values",
    R_BAD_EDGE: "edge sender/receiver indices fall outside [0, num_nodes)",
    R_SELF_LOOP: "every edge is a self loop (degenerate connectivity)",
    R_EMPTY: "the graph has zero nodes",
    R_BUDGET: "the graph exceeds the pad/pack budget (nodes or edges)",
    R_CORRUPT: "stored sample bytes failed to deserialize",
    R_CHANNELS: (
        "the feature channels present (or their widths) do not match the "
        "layout the model was trained and warmed with"
    ),
}


def describe_reason(reason: str) -> str:
    """Human-readable expansion of a rejection-reason key."""
    return REASON_MESSAGES.get(reason, reason)


class BadSampleError(ValueError):
    """A sample failed validation under ``bad_sample_policy: error``."""


class CorruptSampleError(ValueError):
    """Stored sample bytes failed to deserialize (bit rot / torn write /
    wire corruption). Raised by the blob-store datasets (data/ddstore.py)
    with the store name and sample id, so the bad blob is findable."""


def validate_graph(
    g: Graph,
    max_nodes: Optional[int] = None,
    max_edges: Optional[int] = None,
) -> Optional[str]:
    """Return the rejection reason for ``g``, or None when it is clean.

    Cheap and numpy-only (one ``isfinite`` reduction per channel); order is
    most-diagnostic first, so a sample that is broken several ways reports
    its most actionable defect."""
    n = g.num_nodes
    if n == 0:
        return R_EMPTY
    e = g.num_edges
    if e:
        s = np.asarray(g.senders, np.int64)
        r = np.asarray(g.receivers, np.int64)
        if int(s.min()) < 0 or int(r.min()) < 0 or int(s.max()) >= n or int(r.max()) >= n:
            return R_BAD_EDGE
        if bool(np.all(s == r)):
            return R_SELF_LOOP
    for _name, arr in g.float_channels():
        if np.issubdtype(arr.dtype, np.floating) and not bool(
            np.isfinite(arr).all()
        ):
            return R_NONFINITE
    if max_nodes is not None and n > int(max_nodes):
        return R_BUDGET
    if max_edges is not None and e > int(max_edges):
        return R_BUDGET
    return None


class SampleValidator:
    """Policy + structured bookkeeping for rejected samples.

    One validator instance spans a run's whole data plane (ingest filter +
    every loader), so ``stats()`` is the run-level tally the epoch loop
    logs. Rejections are deduplicated on (source, index, reason): batch-time
    re-checks (the pack path re-packs every epoch) never inflate the counts
    past the injection/ingest plan.
    """

    # individually reported rejects before falling back to the tally only
    _VERBOSE_LIMIT = 3

    def __init__(
        self,
        policy: str = "warn_skip",
        quarantine_dir: Optional[str] = None,
    ):
        if policy not in POLICIES:
            raise ValueError(
                f"bad_sample_policy {policy!r} must be one of {POLICIES}"
            )
        if policy == "quarantine" and quarantine_dir is None:
            raise ValueError(
                "bad_sample_policy 'quarantine' needs a quarantine_dir (the "
                "run-dir manifest location)"
            )
        self.policy = policy
        self.quarantine_dir = quarantine_dir
        if policy == "quarantine":
            # one validator spans one run: start a fresh manifest so the
            # file always describes THIS run's quarantined samples (a stale
            # manifest from a previous run over the same log name would
            # silently double the apparent rejects)
            try:
                os.unlink(self.manifest_path)
            except OSError:
                pass
        self.checked = 0
        self.counts: Dict[str, int] = {}
        self._seen = set()  # (source, index, reason) dedup
        self._reported = 0

    # -- manifest -----------------------------------------------------------
    @property
    def manifest_path(self) -> Optional[str]:
        if self.quarantine_dir is None:
            return None
        return os.path.join(self.quarantine_dir, "manifest.jsonl")

    def _quarantine(self, entry: Dict) -> None:
        os.makedirs(self.quarantine_dir, exist_ok=True)
        with open(self.manifest_path, "a", encoding="utf-8") as f:
            f.write(json.dumps(entry) + "\n")
            f.flush()

    def set_quarantine_dir(self, quarantine_dir: str) -> None:
        """Retarget the manifest location, carrying any already-written
        entries along. api.prepare_data needs this: the validator is created
        (and ingest rejects recorded) before config completion fills the
        defaults the run name is derived from, so the final run-dir location
        is only known later. Clears a stale manifest at the new location
        first — fresh-run semantics hold wherever the manifest ends up."""
        if quarantine_dir == self.quarantine_dir:
            return
        old = self.manifest_path
        old_dir = self.quarantine_dir
        self.quarantine_dir = quarantine_dir
        if self.policy != "quarantine":
            return
        try:
            os.unlink(self.manifest_path)
        except OSError:
            pass
        if old and os.path.exists(old):
            os.makedirs(self.quarantine_dir, exist_ok=True)
            os.replace(old, self.manifest_path)
            try:
                os.rmdir(old_dir)
            except OSError:
                pass

    # -- rejection ----------------------------------------------------------
    def reject(self, g: Optional[Graph], index: int, reason: str,
               source: str = "dataset", detail: str = "") -> None:
        """Record (or raise, under ``error``) one rejected sample."""
        ds_id = int(getattr(g, "dataset_id", 0) or 0) if g is not None else -1
        if self.policy == "error":
            raise BadSampleError(
                f"sample {index} (dataset_id {ds_id}, source {source!r}) "
                f"rejected: {reason}"
                + (f" — {detail}" if detail else "")
                + ". Set Dataset.bad_sample_policy to 'warn_skip' or "
                "'quarantine' to drop bad samples instead of failing."
            )
        key = (source, int(index), reason)
        if key in self._seen:
            return
        self._seen.add(key)
        self.counts[reason] = self.counts.get(reason, 0) + 1
        # typed incident record (obs/events.py) — quarantine/skip verdicts
        # land in the flight-recorder window with their reason attached
        try:
            from ..obs.events import EV_DATA_SKIP
            from ..obs.events import emit as _emit_event

            _emit_event(
                EV_DATA_SKIP,
                severity="warn",
                reason=reason,
                source=source,
                index=int(index),
                quarantined=self.policy == "quarantine",
            )
        except Exception:
            pass
        entry = {
            "index": int(index),
            "dataset_id": ds_id,
            "reason": reason,
            "source": source,
            "num_nodes": g.num_nodes if g is not None else None,
            "num_edges": g.num_edges if g is not None else None,
        }
        if detail:
            entry["detail"] = detail
        if self.policy == "quarantine":
            self._quarantine(entry)
        if self._reported < self._VERBOSE_LIMIT:
            self._reported += 1
            print(
                f"[hydragnn_tpu.data] skipping bad sample {index} "
                f"(dataset_id {ds_id}, source {source!r}): {reason}"
                + (f" — {detail}" if detail else ""),
                file=sys.stderr,
            )

    # -- checking / filtering ----------------------------------------------
    def check(self, g: Graph, index: int, source: str = "dataset",
              max_nodes: Optional[int] = None,
              max_edges: Optional[int] = None) -> Optional[str]:
        """Validate one sample; record the rejection per policy. Returns the
        reason (the caller must skip the sample) or None (keep it)."""
        self.checked += 1
        reason = validate_graph(g, max_nodes=max_nodes, max_edges=max_edges)
        if reason is not None:
            self.reject(g, index, reason, source=source)
        return reason

    def filter(self, graphs: Sequence[Graph], source: str = "dataset",
               max_nodes: Optional[int] = None,
               max_edges: Optional[int] = None) -> List[Graph]:
        """Drop every invalid sample of ``graphs`` (recording each), keeping
        order. Indices in the tally/manifest are positions in ``graphs``."""
        return [
            g
            for i, g in enumerate(graphs)
            if self.check(g, i, source=source,
                          max_nodes=max_nodes, max_edges=max_edges) is None
        ]

    # -- reporting ----------------------------------------------------------
    @property
    def skipped_total(self) -> int:
        return sum(self.counts.values())

    def stats(self) -> Dict:
        """Structured loader stats: checked/skipped totals, the per-reason
        skip counts, the active policy and manifest location."""
        return {
            "checked": self.checked,
            "skipped": dict(self.counts),
            "skipped_total": self.skipped_total,
            "policy": self.policy,
            "quarantine_manifest": (
                self.manifest_path
                if self.policy == "quarantine" and self.counts
                else None
            ),
        }

    def tally(self) -> str:
        """One-line human tally for the epoch log."""
        if not self.counts:
            return "no skipped samples"
        parts = ", ".join(
            f"{k}={v}" for k, v in sorted(self.counts.items())
        )
        extra = (
            f" (quarantine manifest: {self.manifest_path})"
            if self.policy == "quarantine"
            else ""
        )
        return f"{self.skipped_total} skipped [{parts}]{extra}"
