"""Laplacian positional encodings for GPS.

(reference: hydragnn/preprocess/serialized_dataset_loader.py:89-94,182-189 —
``AddLaplacianEigenvectorPE(k=pe_dim)`` per graph plus relative edge encoding
``rel_pe = |pe_src - pe_dst|``.)

Host-side preprocessing with numpy/scipy: eigenvectors of the symmetric
normalized Laplacian L = I - D^-1/2 A D^-1/2, skipping the trivial constant
mode, sign-fixed for determinism.

Disk cache: ``np.linalg.eigh`` is O(N^3) per graph and the result depends
only on the graph's topology (the symmetrized adjacency) and ``k`` — so
re-runs, resumes, and repeated experiments over the same dataset can skip
the whole sweep. Results are cached per graph under a sha256 of
``(n, k, senders, receivers)`` (``Dataset.lappe_cache``: true = the default
``./logs/lappe_cache``, false = off, or an explicit directory;
``HYDRAGNN_LAPPE_CACHE`` env overrides — ``0``/``off`` disables, a path
redirects). Writes are atomic (tmp + ``os.replace``); a corrupt or
wrong-shape entry silently recomputes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from typing import List, Optional

import numpy as np

from .graph import Graph
from ..utils import envflags

_CACHE_ENV = "HYDRAGNN_LAPPE_CACHE"
_DEFAULT_CACHE_DIR = os.path.join("logs", "lappe_cache")


def resolve_cache_dir(cache=True) -> Optional[str]:
    """Cache directory from the config knob + env override. ``cache`` is
    ``Dataset.lappe_cache``: True (default dir), False/None (off), or a
    path. The env always wins: ``0``/``off``/``false`` disables, ``1``
    keeps the config resolution, anything else is the directory."""
    env = envflags.env_str(_CACHE_ENV)
    if env is not None:
        s = env.strip()
        if s.lower() in ("0", "off", "false", "none", ""):
            return None
        if s != "1":
            return s
        if cache is False or cache is None:
            cache = True  # env "1": force-on; a config-provided dir still wins
    if cache is False or cache is None:
        return None
    if isinstance(cache, str):
        return cache
    return _DEFAULT_CACHE_DIR


def _topology_key(
    n: int, senders: np.ndarray, receivers: np.ndarray, k: int
) -> str:
    h = hashlib.sha256()
    h.update(np.int64(n).tobytes())
    h.update(np.int64(k).tobytes())
    h.update(np.ascontiguousarray(np.asarray(senders, np.int64)).tobytes())
    h.update(np.ascontiguousarray(np.asarray(receivers, np.int64)).tobytes())
    return h.hexdigest()


def _cache_load(path: str, n: int, k: int) -> Optional[np.ndarray]:
    try:
        pe = np.load(path)
    except Exception:  # missing/corrupt entry: recompute
        return None
    if pe.shape != (n, k) or not np.all(np.isfinite(pe)):
        return None
    return pe.astype(np.float32)


def _cache_store(path: str, pe: np.ndarray) -> None:
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            np.save(f, pe)
        os.replace(tmp, path)
    except OSError:
        pass  # cache is best-effort; the computed result still returns


def laplacian_pe(
    n: int,
    senders: np.ndarray,
    receivers: np.ndarray,
    k: int,
    cache_dir: Optional[str] = None,
) -> np.ndarray:
    """[n, k] eigenvectors for the k smallest non-trivial eigenvalues."""
    path = None
    if cache_dir:
        key = _topology_key(n, senders, receivers, k)
        # shard by hash prefix: GFM-scale datasets are millions of graphs,
        # and a single flat directory with millions of entries degrades
        # lookups on common filesystems (ext4 large-dir scans, NFS)
        path = os.path.join(cache_dir, key[:2], key + ".npy")
        hit = _cache_load(path, n, k)
        if hit is not None:
            return hit
    A = np.zeros((n, n), np.float64)
    A[receivers, senders] = 1.0
    A = np.maximum(A, A.T)  # symmetrize
    deg = A.sum(1)
    dinv = 1.0 / np.sqrt(np.maximum(deg, 1e-12))
    L = np.eye(n) - (dinv[:, None] * A * dinv[None, :])
    w, v = np.linalg.eigh(L)
    order = np.argsort(w)
    pe = v[:, order[1 : k + 1]]  # skip trivial lowest mode
    if pe.shape[1] < k:  # tiny graphs: zero-pad missing modes
        pe = np.concatenate([pe, np.zeros((n, k - pe.shape[1]))], axis=1)
    # deterministic sign: first nonzero entry of each vector positive
    for c in range(pe.shape[1]):
        col = pe[:, c]
        nz = np.flatnonzero(np.abs(col) > 1e-8)
        if nz.size and col[nz[0]] < 0:
            pe[:, c] = -col
    pe = pe.astype(np.float32)
    if path is not None:
        _cache_store(path, pe)
    return pe


def add_graph_pe(
    graph: Graph, pe_dim: int, cache_dir: Optional[str] = None
) -> Graph:
    """Attach ``pe`` [n, pe_dim] and ``rel_pe`` [e, pe_dim] to a graph."""
    pe = laplacian_pe(
        graph.num_nodes, graph.senders, graph.receivers, pe_dim,
        cache_dir=cache_dir,
    )
    rel_pe = np.abs(pe[graph.senders] - pe[graph.receivers])
    return dataclasses.replace(graph, pe=pe, rel_pe=rel_pe)


def add_dataset_pe(graphs: List[Graph], pe_dim: int, cache=True) -> List[Graph]:
    cache_dir = resolve_cache_dir(cache)
    return [add_graph_pe(g, pe_dim, cache_dir=cache_dir) for g in graphs]
