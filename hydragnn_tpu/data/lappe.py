"""Laplacian positional encodings for GPS.

(reference: hydragnn/preprocess/serialized_dataset_loader.py:89-94,182-189 —
``AddLaplacianEigenvectorPE(k=pe_dim)`` per graph plus relative edge encoding
``rel_pe = |pe_src - pe_dst|``.)

Host-side preprocessing with numpy/scipy: eigenvectors of the symmetric
normalized Laplacian L = I - D^-1/2 A D^-1/2, skipping the trivial constant
mode, sign-fixed for determinism.
"""

from __future__ import annotations

import dataclasses
from typing import List

import numpy as np

from .graph import Graph


def laplacian_pe(
    n: int, senders: np.ndarray, receivers: np.ndarray, k: int
) -> np.ndarray:
    """[n, k] eigenvectors for the k smallest non-trivial eigenvalues."""
    A = np.zeros((n, n), np.float64)
    A[receivers, senders] = 1.0
    A = np.maximum(A, A.T)  # symmetrize
    deg = A.sum(1)
    dinv = 1.0 / np.sqrt(np.maximum(deg, 1e-12))
    L = np.eye(n) - (dinv[:, None] * A * dinv[None, :])
    w, v = np.linalg.eigh(L)
    order = np.argsort(w)
    pe = v[:, order[1 : k + 1]]  # skip trivial lowest mode
    if pe.shape[1] < k:  # tiny graphs: zero-pad missing modes
        pe = np.concatenate([pe, np.zeros((n, k - pe.shape[1]))], axis=1)
    # deterministic sign: first nonzero entry of each vector positive
    for c in range(pe.shape[1]):
        col = pe[:, c]
        nz = np.flatnonzero(np.abs(col) > 1e-8)
        if nz.size and col[nz[0]] < 0:
            pe[:, c] = -col
    return pe.astype(np.float32)


def add_graph_pe(graph: Graph, pe_dim: int) -> Graph:
    """Attach ``pe`` [n, pe_dim] and ``rel_pe`` [e, pe_dim] to a graph."""
    pe = laplacian_pe(graph.num_nodes, graph.senders, graph.receivers, pe_dim)
    rel_pe = np.abs(pe[graph.senders] - pe[graph.receivers])
    return dataclasses.replace(graph, pe=pe, rel_pe=rel_pe)


def add_dataset_pe(graphs: List[Graph], pe_dim: int) -> List[Graph]:
    return [add_graph_pe(g, pe_dim) for g in graphs]
