"""Shaped dataset generators for the reference's remaining example families.

The reference ships 25 example dirs whose datasets are multi-GB downloads
(ANI-1x, QM7-X, Transition1x, Alexandria, OMat24, OMol25, OC20/22, ODAC23,
ZINC, OGB, CSCE, DFTB UV spectra, NiNb EAM). None are downloadable in this
image (zero egress), so each family gets a *shaped* generator here: a
synthetic dataset matching the real one's size/composition/degree statistics
with physically-consistent, closed-form targets — so the example drivers
exercise exactly the training path the real data would, and accuracy on the
closed-form targets is a meaningful signal.

Reference builders these mirror (all under /root/reference/examples/):
ani1_x/train.py, qm7x/train.py, transition1x/train.py + dataloader.py,
alexandria/train.py, open_materials_2024/omat24.py,
open_molecules_2025/train.py, open_catalyst_2022/train.py,
open_direct_air_capture_2023/train.py, eam/eam.py,
dftb_uv_spectrum/train_smooth_uv_spectrum.py, zinc/zinc.py.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .graph import Graph
from .neighbors import radius_graph, radius_graph_pbc
from .synthetic import (
    _lj_targets,
    _symmetrize_edges,
    grow_molecule as _grow_molecule,
    supercell_frac,
)

# electronegativity table (Pauling) for the charge-like closed-form targets
_EN = {1: 2.20, 6: 2.55, 7: 3.04, 8: 3.44, 9: 3.98, 16: 2.58, 17: 3.16,
       3: 0.98, 11: 0.93, 13: 1.61, 14: 1.90, 15: 2.19, 19: 0.82, 20: 1.00,
       22: 1.54, 25: 1.55, 26: 1.83, 28: 1.91, 29: 1.90, 30: 1.65,
       34: 2.55, 35: 2.96, 41: 1.60, 53: 2.66}


def _en_of(z: np.ndarray) -> np.ndarray:
    return np.asarray([_EN.get(int(v), 1.8) for v in z], np.float64)


def _molecule_forces_family(
    number_configurations: int,
    heavy_choices: Sequence[int],
    heavy_probs: Sequence[float],
    n_heavy_range: Sequence[int],
    h_rate: float,
    radius: float,
    max_neighbours: int,
    seed: int,
    epsilon: float = 0.2,
    sigma: float = 1.2,
    per_atom_energy: bool = False,
) -> List[Graph]:
    """Shared builder for the molecular energy+force families: variable-size
    organic molecules, LJ energy (graph) + forces (node), node feature table
    ``[Z, fx, fy, fz]`` so force targets are selectable as table columns
    (the reference's packed-y convention) *and* ride ``node_targets`` for the
    ``compute_grad_energy`` path."""
    rng = np.random.default_rng(seed)
    heavy_choices = np.asarray(heavy_choices)
    heavy_probs = np.asarray(heavy_probs, np.float64)
    heavy_probs = heavy_probs / heavy_probs.sum()
    graphs: List[Graph] = []
    for _ in range(number_configurations):
        n_heavy = int(rng.integers(n_heavy_range[0], n_heavy_range[1] + 1))
        n_h = int(np.clip(rng.poisson(h_rate * n_heavy),
                          2 if n_heavy < 2 else 0, 3 * n_heavy + 2))
        z = np.concatenate([
            rng.choice(heavy_choices, size=n_heavy, p=heavy_probs),
            np.ones(n_h, np.int64),
        ]).astype(np.int32)
        pos = _grow_molecule(rng, z.shape[0])
        z = z[: pos.shape[0]]
        n = pos.shape[0]
        senders, receivers = radius_graph(pos, radius, max_neighbours)
        senders, receivers = _symmetrize_edges(senders, receivers)
        energy, forces = _lj_targets(pos, senders, receivers, epsilon, sigma)
        if per_atom_energy:
            energy = energy / n
        x = np.concatenate(
            [z[:, None].astype(np.float32), forces.astype(np.float32)], axis=1
        )
        graphs.append(Graph(
            x=x,
            pos=pos.astype(np.float32),
            senders=senders,
            receivers=receivers,
            graph_y=np.asarray([energy], np.float32),
            graph_targets={"energy": np.asarray([energy], np.float32)},
            node_targets={"forces": forces.astype(np.float32)},
            z=z.copy(),
        ))
    # reference-energy centering (standard atomization-energy shift)
    e_mean = float(np.mean([g.graph_y[0] for g in graphs]))
    for g in graphs:
        g.graph_y = (g.graph_y - e_mean).astype(np.float32)
        g.graph_targets["energy"] = g.graph_y.copy()
    return graphs


def ani1x_shaped_dataset(number_configurations: int = 256, radius: float = 5.0,
                         max_neighbours: int = 32, seed: int = 11) -> List[Graph]:
    """ANI-1x-*shaped*: C/H/N/O molecules, 2-~30 atoms (the ANI-1x organic
    range), energy + force targets (reference: examples/ani1_x/train.py,
    ani1x_energy.json / ani1x_forces.json)."""
    return _molecule_forces_family(
        number_configurations, [6, 7, 8], [0.7, 0.15, 0.15], (1, 8), 1.4,
        radius, max_neighbours, seed,
    )


def transition1x_shaped_dataset(number_configurations: int = 256,
                                radius: float = 5.0, max_neighbours: int = 32,
                                seed: int = 29) -> List[Graph]:
    """Transition1x-*shaped*: reaction-path configurations — pairs of
    perturbed endpoint geometries of one molecule linearly interpolated with
    an activation-barrier energy bump at the midpoint, the structure of the
    real NEB-sampled dataset (reference: examples/transition1x/train.py,
    transition1x_energy.json; energy-only graph target)."""
    rng = np.random.default_rng(seed)
    graphs: List[Graph] = []
    n_paths = max(1, number_configurations // 8)
    # distribute the remainder so exactly number_configurations come back
    per_path_counts = np.full(n_paths, number_configurations // n_paths)
    per_path_counts[: number_configurations - int(per_path_counts.sum())] += 1
    for per_path in per_path_counts:
        n_heavy = int(rng.integers(2, 8))
        n_h = int(np.clip(rng.poisson(1.3 * n_heavy), 0, 16))
        z = np.concatenate([
            rng.choice([6, 7, 8], size=n_heavy, p=[0.7, 0.15, 0.15]),
            np.ones(n_h, np.int64),
        ]).astype(np.int32)
        reactant = _grow_molecule(rng, z.shape[0])
        z = z[: reactant.shape[0]]
        product = reactant + rng.normal(0.0, 0.35, reactant.shape)
        barrier = float(rng.uniform(0.5, 2.0))
        for _ in range(int(per_path)):
            lam = float(rng.uniform(0.0, 1.0))
            pos = (1 - lam) * reactant + lam * product
            pos = pos + rng.normal(0.0, 0.03, pos.shape)
            senders, receivers = radius_graph(pos, radius, max_neighbours)
            senders, receivers = _symmetrize_edges(senders, receivers)
            energy, _ = _lj_targets(pos, senders, receivers, 0.2, 1.2)
            energy += 4.0 * barrier * lam * (1.0 - lam)  # NEB-like bump
            graphs.append(Graph(
                x=z[:, None].astype(np.float32),
                pos=pos.astype(np.float32),
                senders=senders,
                receivers=receivers,
                graph_y=np.asarray([energy], np.float32),
                z=z.copy(),
            ))
    e_mean = float(np.mean([g.graph_y[0] for g in graphs]))
    for g in graphs:
        g.graph_y = (g.graph_y - e_mean).astype(np.float32)
    return graphs


def qm7x_shaped_dataset(number_configurations: int = 256, radius: float = 5.0,
                        max_neighbours: int = 32, seed: int = 13) -> List[Graph]:
    """QM7-X-*shaped*: up-to-7-heavy-atom molecules (C/N/O/S/Cl + H) with the
    reference's five-target multitask surface (examples/qm7x/qm7x.json):
    graph HLGAP + node forces/hCHG/hVDIP/hRAT. Closed forms, all learnable
    from geometry+species: HLGAP = softened inverse of the per-atom LJ
    energy; hCHG = electronegativity imbalance vs bonded neighbours;
    hVDIP = local asymmetry (norm of the mean neighbour unit vector);
    hRAT = degree / max_neighbours. Node feature table:
    ``[Z, fx, fy, fz, hCHG, hVDIP, hRAT]``, graph table ``[HLGAP]``."""
    rng = np.random.default_rng(seed)
    graphs: List[Graph] = []
    for _ in range(number_configurations):
        n_heavy = int(rng.integers(1, 8))  # QM7-X: max 7 heavy atoms
        n_h = int(np.clip(rng.poisson(1.5 * n_heavy), 2 if n_heavy < 2 else 0, 18))
        z = np.concatenate([
            rng.choice([6, 7, 8, 16, 17], size=n_heavy,
                       p=[0.62, 0.14, 0.14, 0.06, 0.04]),
            np.ones(n_h, np.int64),
        ]).astype(np.int32)
        pos = _grow_molecule(rng, z.shape[0])
        z = z[: pos.shape[0]]
        n = pos.shape[0]
        senders, receivers = radius_graph(pos, radius, max_neighbours)
        senders, receivers = _symmetrize_edges(senders, receivers)
        energy, forces = _lj_targets(pos, senders, receivers, 0.2, 1.2)
        en = _en_of(z)
        deg = np.bincount(receivers, minlength=n).astype(np.float64)
        safe_deg = np.maximum(deg, 1.0)
        # neighbour-mean electronegativity -> charge-like imbalance
        en_sum = np.zeros(n)
        np.add.at(en_sum, receivers, en[senders])
        hchg = (en - en_sum / safe_deg) * 0.3
        # local asymmetry: norm of the mean bond unit vector
        diff = pos[senders] - pos[receivers]
        unit = diff / np.maximum(np.linalg.norm(diff, axis=1, keepdims=True), 1e-9)
        acc = np.zeros((n, 3))
        np.add.at(acc, receivers, unit)
        hvdip = np.linalg.norm(acc / safe_deg[:, None], axis=1)
        hrat = deg / max_neighbours
        hlgap = 2.0 / (1.0 + np.exp(energy / n))  # smooth, bounded, geometric
        x = np.concatenate([
            z[:, None].astype(np.float32),
            forces.astype(np.float32),
            hchg[:, None].astype(np.float32),
            hvdip[:, None].astype(np.float32),
            hrat[:, None].astype(np.float32),
        ], axis=1)
        graphs.append(Graph(
            x=x,
            pos=pos.astype(np.float32),
            senders=senders,
            receivers=receivers,
            graph_y=np.asarray([hlgap], np.float32),
            z=z.copy(),
        ))
    return graphs


def omol25_shaped_dataset(number_configurations: int = 128, radius: float = 5.0,
                          max_neighbours: int = 32, seed: int = 31) -> List[Graph]:
    """OMol25-*shaped*: larger organic/organometallic molecules (mean ~40
    atoms, elements incl. S/P/halogens/a few metals), energy + forces
    (reference: examples/open_molecules_2025/train.py)."""
    return _molecule_forces_family(
        number_configurations,
        [6, 7, 8, 15, 16, 17, 30, 26], [0.55, 0.12, 0.12, 0.05, 0.07, 0.04, 0.02, 0.03],
        (6, 24), 1.2, radius, max_neighbours, seed,
    )


def periodic_crystal_shaped_dataset(
    number_configurations: int = 128,
    element_pool: Sequence[int] = (3, 8, 13, 14, 22, 26, 28, 29),
    n_species: int = 2,
    reps_range: Sequence[int] = (2, 3),  # inclusive
    lattice_range: Sequence[float] = (3.4, 4.4),
    rattle: float = 0.08,
    radius: float = 5.0,
    max_neighbours: int = 20,
    seed: int = 23,
) -> List[Graph]:
    """Perturbed periodic crystals: random SC/BCC/FCC supercells, random
    ``n_species``-ary composition from ``element_pool``, PBC radius graphs
    with shift vectors, LJ energy-per-atom (graph) + forces (node) on the
    periodic displacements. The generalized form of the MPTrj generator
    covering the Alexandria and OMat24 families (reference:
    examples/alexandria/train.py, examples/open_materials_2024/omat24.py).
    Node feature table ``[Z, fx, fy, fz]``."""
    rng = np.random.default_rng(seed)
    bases = {
        "sc": np.zeros((1, 3)),
        "bcc": np.array([[0, 0, 0], [0.5, 0.5, 0.5]], np.float64),
        "fcc": np.array(
            [[0, 0, 0], [0.5, 0.5, 0], [0.5, 0, 0.5], [0, 0.5, 0.5]], np.float64
        ),
    }
    element_pool = np.asarray(element_pool)
    graphs: List[Graph] = []
    for _ in range(number_configurations):
        kind = ("sc", "bcc", "fcc")[int(rng.integers(3))]
        basis = bases[kind]
        a = float(rng.uniform(*lattice_range))
        # inclusive range, like n_heavy_range in _molecule_forces_family
        reps = int(rng.integers(reps_range[0], reps_range[1] + 1))
        frac = supercell_frac(basis, reps)
        cell = np.diag([a * reps] * 3)
        pos = frac @ cell + rng.normal(0.0, rattle, (frac.shape[0], 3))
        n = pos.shape[0]
        k = int(np.clip(n_species, 1, element_pool.shape[0]))
        zs = rng.choice(element_pool, size=k, replace=False)
        z = zs[rng.integers(0, k, n)].astype(np.int32)
        senders, receivers, shifts = radius_graph_pbc(pos, cell, radius, max_neighbours)
        sigma = a / np.sqrt(2.0) / 2.0 ** (1.0 / 6.0)
        energy, forces = _lj_targets(pos, senders, receivers, 0.5, sigma, shifts=shifts)
        x = np.concatenate(
            [z[:, None].astype(np.float32), forces.astype(np.float32)], axis=1
        )
        graphs.append(Graph(
            x=x,
            pos=pos.astype(np.float32),
            senders=senders,
            receivers=receivers,
            edge_shifts=shifts.astype(np.float32),
            cell=cell.astype(np.float32),
            graph_y=np.asarray([energy / n], np.float32),
            graph_targets={"energy": np.asarray([energy / n], np.float32)},
            node_targets={"forces": forces.astype(np.float32)},
            z=z.copy(),
        ))
    return graphs


def alexandria_shaped_dataset(number_configurations: int = 128, **kw) -> List[Graph]:
    """Alexandria-*shaped*: ternary oxide-like periodic crystals
    (reference: examples/alexandria/train.py + find_json_files.py)."""
    kw.setdefault("element_pool", (8, 3, 13, 14, 20, 22, 26, 30))
    kw.setdefault("n_species", 3)
    kw.setdefault("seed", 37)
    return periodic_crystal_shaped_dataset(number_configurations, **kw)


def omat24_shaped_dataset(number_configurations: int = 128, **kw) -> List[Graph]:
    """OMat24-*shaped*: rattled inorganic crystals at larger perturbation
    (the real OMat24 samples far-from-equilibrium configurations;
    reference: examples/open_materials_2024/omat24.py)."""
    kw.setdefault("element_pool", (8, 13, 14, 22, 25, 26, 28, 29, 41))
    kw.setdefault("n_species", 2)
    kw.setdefault("rattle", 0.16)
    kw.setdefault("seed", 41)
    return periodic_crystal_shaped_dataset(number_configurations, **kw)


def odac23_shaped_dataset(number_configurations: int = 96, radius: float = 5.0,
                          max_neighbours: int = 20, seed: int = 43) -> List[Graph]:
    """ODAC23-*shaped*: sparse MOF-like frameworks with a CO2 adsorbate —
    an open metal-organic lattice (larger lattice constant than a metal
    slab) plus one CO2 molecule placed in a pore; energy+forces
    (reference: examples/open_direct_air_capture_2023/train.py)."""
    rng = np.random.default_rng(seed)
    graphs: List[Graph] = []
    for _ in range(number_configurations):
        reps = int(rng.integers(2, 4))
        a = float(rng.uniform(5.2, 6.2))  # open-framework spacing
        # framework: metal node at corner + organic linker atoms on edges
        linker_basis = np.array(
            [[0, 0, 0], [0.5, 0, 0], [0, 0.5, 0], [0, 0, 0.5]], np.float64
        )
        frame_frac = supercell_frac(linker_basis, reps)
        cell = np.diag([a * reps] * 3)
        pos = frame_frac @ cell + rng.normal(0.0, 0.06, (frame_frac.shape[0], 3))
        n_frame = pos.shape[0]
        # atoms are cell-major (4 basis sites per cell): site 0 is the
        # metal node, sites 1-3 the organic linkers
        z = rng.choice([6, 8], size=n_frame).astype(np.int32)
        z[0::4] = rng.choice([29, 30, 26])  # metal nodes
        # CO2 adsorbate in a pore center
        center = np.array([0.25, 0.25, 0.25]) @ cell + rng.normal(0, 0.4, 3)
        axis = rng.normal(0, 1, 3)
        axis /= np.linalg.norm(axis)
        co2 = np.stack([center - 1.16 * axis, center, center + 1.16 * axis])
        pos = np.concatenate([pos, co2])
        z = np.concatenate([z, np.array([8, 6, 8], np.int32)])
        senders, receivers, shifts = radius_graph_pbc(pos, cell, radius, max_neighbours)
        energy, forces = _lj_targets(pos, senders, receivers, 0.3, 2.6, shifts=shifts)
        x = np.concatenate(
            [z[:, None].astype(np.float32), forces.astype(np.float32)], axis=1
        )
        graphs.append(Graph(
            x=x,
            pos=pos.astype(np.float32),
            senders=senders,
            receivers=receivers,
            edge_shifts=shifts.astype(np.float32),
            cell=cell.astype(np.float32),
            graph_y=np.asarray([energy / pos.shape[0]], np.float32),
            graph_targets={"energy": np.asarray([energy / pos.shape[0]], np.float32)},
            node_targets={"forces": forces.astype(np.float32)},
            z=z.copy(),
        ))
    return graphs


def eam_bulk_dataset(number_configurations: int = 128, radius: float = 3.6,
                     max_neighbours: int = 32, seed: int = 47) -> List[Graph]:
    """NiNb-EAM-*shaped*: binary Ni/Nb BCC bulk supercells with
    Finnis-Sinclair embedded-atom energies — per-atom energy (node),
    total energy (graph), analytic forces (node)
    (reference: examples/eam/eam.py + NiNb_EAM_*.json configs; the real
    data comes from LAMMPS EAM tables). Node feature table
    ``[Z, atomic_energy, fx, fy, fz]``, graph table ``[total_energy]``."""
    rng = np.random.default_rng(seed)
    basis = np.array([[0, 0, 0], [0.5, 0.5, 0.5]], np.float64)
    graphs: List[Graph] = []
    for _ in range(number_configurations):
        reps = int(rng.integers(2, 4))
        a = float(rng.uniform(3.1, 3.4))  # Ni/Nb BCC lattice range
        frac = supercell_frac(basis, reps)
        cell = np.diag([a * reps] * 3)
        pos = frac @ cell + rng.normal(0.0, 0.05, (frac.shape[0], 3))
        n = pos.shape[0]
        frac_nb = float(rng.uniform(0.1, 0.5))
        z = np.where(rng.random(n) < frac_nb, 41, 28).astype(np.int32)
        senders, receivers, shifts = radius_graph_pbc(pos, cell, radius, max_neighbours)
        atomic_energy, forces = _fs_eam_targets_pbc(
            pos, senders, receivers, z, radius, shifts
        )
        x = np.concatenate([
            z[:, None].astype(np.float32),
            atomic_energy[:, None].astype(np.float32),
            forces.astype(np.float32),
        ], axis=1)
        graphs.append(Graph(
            x=x,
            pos=pos.astype(np.float32),
            senders=senders,
            receivers=receivers,
            edge_shifts=shifts.astype(np.float32),
            cell=cell.astype(np.float32),
            graph_y=np.asarray([atomic_energy.sum()], np.float32),
            z=z.copy(),
        ))
    return graphs


def _fs_eam_targets_pbc(pos, senders, receivers, z, cutoff, shifts):
    """PBC-aware Finnis-Sinclair per-atom energies and analytic forces."""
    A = np.where(z == 28, 1.2, 1.6)
    B = 0.25
    diff = pos[receivers] - pos[senders]
    if shifts is not None:
        diff = diff - shifts
    r = np.linalg.norm(diff, axis=1)
    w = np.maximum(cutoff - r, 0.0)
    n = pos.shape[0]
    rho = np.zeros(n)
    np.add.at(rho, receivers, w**2)
    rho = np.maximum(rho, 1e-12)
    atomic_energy = -A * np.sqrt(rho)
    np.add.at(atomic_energy, receivers, 0.5 * B * w**2)
    demb = -A / (2.0 * np.sqrt(rho))
    # edge j->i: rho_i gains w^2 -> d rho_i/dx_i = 2 w * (-1) * diff/r.
    # The twin edge i->j handles rho_j, so each edge only carries its
    # receiver's embedding derivative. Pair: 0.5 B w^2 per direction; its
    # gradient per edge w.r.t. x_i is B w * (-1) * diff/r * 0.5 * 2.
    dEdr = demb[receivers] * 2.0 * w * (-1.0) - B * w
    dEdr = dEdr * (w > 0)
    unit = diff / np.maximum(r, 1e-12)[:, None]
    grad_edge = dEdr[:, None] * unit
    forces = np.zeros_like(pos)
    np.add.at(forces, receivers, -grad_edge)
    np.add.at(forces, senders, grad_edge)
    return atomic_energy, forces


def uv_spectrum_shaped_dataset(
    number_configurations: int = 256,
    num_bins: int = 37,
    smooth: bool = True,
    radius: float = 7.0,
    max_neighbours: int = 10,
    seed: int = 53,
) -> List[Graph]:
    """DFTB-UV-spectrum-*shaped*: small organic molecules whose graph target
    is a ``num_bins``-dim spectrum — Gaussian-broadened (smooth) or binned
    (discrete) intensity over a fixed energy grid, with excitation energies
    derived from the molecular geometry's pair-distance spectrum so the
    target is learnable (reference: examples/dftb_uv_spectrum/
    train_smooth_uv_spectrum.py and train_discrete_uv_spectrum.py; the real
    smooth target is a 37,500-point grid — configurable here, default kept
    small for CI)."""
    rng = np.random.default_rng(seed)
    grid = np.linspace(0.0, 1.0, num_bins)
    graphs: List[Graph] = []
    for _ in range(number_configurations):
        n_heavy = int(rng.integers(2, 9))
        n_h = int(np.clip(rng.poisson(1.3 * n_heavy), 0, 16))
        z = np.concatenate([
            rng.choice([6, 7, 8], size=n_heavy, p=[0.7, 0.15, 0.15]),
            np.ones(n_h, np.int64),
        ]).astype(np.int32)
        pos = _grow_molecule(rng, z.shape[0])
        z = z[: pos.shape[0]]
        senders, receivers = radius_graph(pos, radius, max_neighbours)
        senders, receivers = _symmetrize_edges(senders, receivers)
        # "excitations": normalized inverse pair distances along edges
        d = np.linalg.norm(pos[senders] - pos[receivers], axis=1)
        exc = 1.0 / (1.0 + d)  # in (0, 1)
        inten = _en_of(z)[senders] * 0.2
        spectrum = np.zeros(num_bins)
        if smooth:
            width = 0.04
            spectrum = np.sum(
                inten[:, None]
                * np.exp(-0.5 * ((grid[None, :] - exc[:, None]) / width) ** 2),
                axis=0,
            )
        else:
            idx = np.clip((exc * num_bins).astype(int), 0, num_bins - 1)
            np.add.at(spectrum, idx, inten)
        spectrum = spectrum / max(len(d), 1)
        graphs.append(Graph(
            x=z[:, None].astype(np.float32),
            pos=pos.astype(np.float32),
            senders=senders,
            receivers=receivers,
            graph_y=spectrum.astype(np.float32),
            z=z.copy(),
        ))
    return graphs


def zinc_shaped_dataset(number_configurations: int = 512, radius: float = 7.0,
                        max_neighbours: int = 5, seed: int = 59) -> List[Graph]:
    """ZINC-*shaped*: drug-like organic molecules (9-37 atoms, the ZINC-
    subset range) with a penalized-logP-like closed-form graph target
    (hydrophobicity sum minus a size penalty plus a geometry term), node
    feature = atom-type index like the real ZINC's 28-type vocabulary
    (reference: examples/zinc/zinc.py; free-energy graph target)."""
    rng = np.random.default_rng(seed)
    # type vocabulary: common ZINC heavy atoms + H; index is the feature
    vocab = np.array([1, 6, 7, 8, 9, 15, 16, 17, 35, 53])
    logp_w = np.array([0.1, 0.5, -0.3, -0.4, 0.2, 0.1, 0.4, 0.7, 0.9, 1.1])
    graphs: List[Graph] = []
    for _ in range(number_configurations):
        n_heavy = int(rng.integers(8, 28))
        n_h = int(np.clip(rng.poisson(1.1 * n_heavy), 0, 24))
        type_idx = np.concatenate([
            rng.choice(len(vocab) - 1, size=n_heavy,
                       p=[0.55, 0.14, 0.14, 0.04, 0.02, 0.05, 0.04, 0.01, 0.01]) + 1,
            np.zeros(n_h, np.int64),  # type 0 = H
        ])
        z = vocab[type_idx].astype(np.int32)
        pos = _grow_molecule(rng, z.shape[0])
        type_idx = type_idx[: pos.shape[0]]
        z = z[: pos.shape[0]]
        senders, receivers = radius_graph(pos, radius, max_neighbours)
        senders, receivers = _symmetrize_edges(senders, receivers)
        d = np.linalg.norm(pos[senders] - pos[receivers], axis=1)
        target = (
            float(np.sum(logp_w[type_idx]))
            - 0.05 * pos.shape[0]
            + 0.1 * float(np.mean(d))
        )
        graphs.append(Graph(
            x=type_idx[:, None].astype(np.float32),
            pos=pos.astype(np.float32),
            senders=senders,
            receivers=receivers,
            graph_y=np.asarray([target], np.float32),
            z=z.copy(),
        ))
    return graphs
