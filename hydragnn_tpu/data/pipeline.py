"""Dataset -> model-ready pipeline: feature selection, split, minmax, loader.

Covers the responsibilities of the reference's serialized loader and splitting
utilities (hydragnn/preprocess/serialized_dataset_loader.py:110-212,
hydragnn/preprocess/load_data.py:225-438) in a TPU-friendly way: everything
here is host-side numpy; the output of ``GraphLoader`` is a statically padded
``GraphBatch`` ready for ``jit``.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .graph import (
    Graph,
    GraphBatch,
    PadSpec,
    SpecLadder,
    _round_up,
    _triplet_count,
    batch_graphs,
    batch_graphs_np,
    graph_batch_from_np,
)

# prefetch watchdog cadence: how often the consumer wakes to check producer
# liveness / the stall clock, and how long the teardown join waits before
# declaring the producer thread leaked (both module-level so tests can pin)
_WATCHDOG_TICK_S = 0.1
_PRODUCER_JOIN_TIMEOUT_S = 2.0


class LoaderStallError(RuntimeError):
    """The prefetch producer thread died without delivering its end-of-epoch
    sentinel, or produced nothing for longer than
    ``Training.loader_stall_timeout`` — a wedged worker (deadlocked fetch,
    hung filesystem) that would otherwise hang the run forever on a bare
    queue get. The message names the batch cursor so the stall is
    attributable."""


def _pack_spec(
    graphs: Sequence[Graph], per_shard: int, with_triplets: bool = False
) -> PadSpec:
    """Budget spec for packed batching: mean-size * per_shard (+5% headroom),
    never below the largest single graph, with 2x graph slots so bins of
    small graphs aren't cut short by the slot cap. ``with_triplets`` also
    budgets the DimeNet triplet channel (counted per graph, O(E) each)."""
    ns = np.asarray([g.num_nodes for g in graphs])
    es = np.asarray([g.num_edges for g in graphs])
    budget_n = max(int(ns.mean() * per_shard * 1.05) + 2, int(ns.max()) + 2)
    budget_e = max(int(es.mean() * per_shard * 1.05) + 1, int(es.max()) + 1)
    n_triplets = 0
    if with_triplets:
        ts = np.asarray([_triplet_count(g) for g in graphs])
        n_triplets = _round_up(
            max(int(ts.mean() * per_shard * 1.05) + 1, int(ts.max()) + 1), 128
        )
    return PadSpec(
        n_nodes=_round_up(budget_n, 8),
        n_edges=_round_up(budget_e, 128),
        n_graphs=2 * per_shard + 1,
        n_triplets=n_triplets,
    )


def selectable_levels(
    graphs: Sequence[Graph],
    ladder: SpecLadder,
    trip_count_of=None,
) -> List[Tuple[int, Graph]]:
    """(level index, one fitting graph) for every ladder level the graphs
    can land in. A level no single graph fits can never be selected by
    ``SpecLadder.select`` (every batch total is >= its smallest member), so
    this census is exactly the set of specializations batching over
    ``graphs`` can produce — the shared coverage primitive of the training
    compile plane, the serving plane, the branch-routed loader's per-branch
    ladders (parallel/branch.py), and the mixture plane (mix/plane.py).
    ``trip_count_of`` overrides the per-graph triplet counter (the loader
    passes its memoized table)."""
    tcf = trip_count_of if trip_count_of is not None else _triplet_count
    out: List[Tuple[int, Graph]] = []
    for li, spec in enumerate(ladder.specs):
        need_t = bool(spec.n_triplets)
        g = next(
            (
                c
                for c in graphs
                if c.num_nodes <= spec.n_nodes - 1
                and c.num_edges <= spec.n_edges
                and (not need_t or tcf(c) <= spec.n_triplets)
            ),
            None,
        )
        if g is not None:
            out.append((li, g))
    return out


def spec_template_batches(
    graphs: Sequence[Graph],
    ladder: SpecLadder,
    sort_edges: bool = False,
    trip_count_of=None,
) -> List[Tuple[PadSpec, GraphBatch]]:
    """One template ``GraphBatch`` per ladder level the dataset can emit —
    the warm-up inputs of both the training compile plane
    (train/compile_plane.py) and the serving plane (serve/server.py).

    Batch array SHAPES are fully determined by the pad spec plus the
    dataset's feature widths, so a single fitting graph padded to the level
    is abstractly identical to any real batch at that level; unreachable
    levels are skipped (``selectable_levels``) — warm-up covers exactly the
    specializations batching can produce, no more."""
    return [
        (
            ladder.specs[li],
            batch_graphs([g], ladder.specs[li], sort_edges=sort_edges),
        )
        for li, g in selectable_levels(graphs, ladder, trip_count_of)
    ]


def stack_shard_batches(
    shards: Sequence[Sequence[Graph]],
    spec: PadSpec,
    num_shards: int,
    sort_edges: bool = False,
) -> GraphBatch:
    """Stack per-shard padded batches into a leading device axis; missing
    shards become all-padding rows (padding edges point at the dummy node
    slot, padding nodes at the dummy graph slot). Shared by the stacked
    ``GraphLoader``, the mixture plane (mix/plane.py), and the
    branch-routed loaders (parallel/routing.py)."""
    arrs = [
        batch_graphs_np(list(s), spec, sort_edges=sort_edges)
        for s in shards
        if s
    ]
    template = {k: np.zeros_like(v) for k, v in arrs[0].items()}
    # padding edges must still point at the dummy node slot
    template["senders"] = np.full_like(arrs[0]["senders"], spec.n_nodes - 1)
    template["receivers"] = template["senders"].copy()
    template["node_graph"] = np.full_like(
        arrs[0]["node_graph"], spec.n_graphs - 1
    )
    while len(arrs) < num_shards:
        arrs.append(template)
    stacked = {k: np.stack([a[k] for a in arrs]) for k in arrs[0]}
    return graph_batch_from_np(stacked)


@dataclasses.dataclass
class VariablesOfInterest:
    """Selection of model inputs and per-head targets from raw feature tables.

    Mirrors config ``NeuralNetwork.Variables_of_interest`` +
    ``Dataset.{node,graph}_features`` (reference:
    hydragnn/utils/input_config_parsing/config_utils.py:219-260).
    """

    input_node_features: Sequence[int]
    output_names: Sequence[str]
    output_types: Sequence[str]  # "graph" | "node"
    output_index: Sequence[int]
    node_feature_dims: Sequence[int]
    graph_feature_dims: Sequence[int]

    def node_feature_slice(self, idx: int) -> slice:
        off = int(np.sum(self.node_feature_dims[:idx]))
        return slice(off, off + self.node_feature_dims[idx])

    def graph_feature_slice(self, idx: int) -> slice:
        off = int(np.sum(self.graph_feature_dims[:idx]))
        return slice(off, off + self.graph_feature_dims[idx])

    @property
    def input_dim(self) -> int:
        return int(sum(self.node_feature_dims[i] for i in self.input_node_features))

    def head_dims(self) -> List[int]:
        dims = []
        for t, i in zip(self.output_types, self.output_index):
            dims.append(
                self.graph_feature_dims[i] if t == "graph" else self.node_feature_dims[i]
            )
        return dims


def select_input_columns(graph: Graph, voi: VariablesOfInterest) -> Graph:
    """Keep only the configured input node-feature columns of ``graph.x``."""
    in_cols = np.concatenate(
        [np.arange(voi.node_feature_slice(i).start, voi.node_feature_slice(i).stop)
         for i in voi.input_node_features]
    )
    return dataclasses.replace(graph, x=np.asarray(graph.x)[:, in_cols])


def extract_variables(graph: Graph, voi: VariablesOfInterest) -> Graph:
    """Produce a model-ready graph: input columns + per-head target dicts."""
    graph_targets: Dict[str, np.ndarray] = {}
    node_targets: Dict[str, np.ndarray] = {}
    for name, t, idx in zip(voi.output_names, voi.output_types, voi.output_index):
        if t == "graph":
            graph_targets[name] = np.asarray(graph.graph_y)[voi.graph_feature_slice(idx)]
        else:
            node_targets[name] = np.asarray(graph.x)[:, voi.node_feature_slice(idx)]
    return dataclasses.replace(
        select_input_columns(graph, voi),
        graph_targets=graph_targets,
        node_targets=node_targets,
    )


@dataclasses.dataclass
class MinMax:
    """Per-column min/max used for feature/target normalization to [0, 1].

    The reference normalizes raw features in ``AbstractRawDataset.__normalize_dataset``
    and denormalizes predictions with ``output_denormalize``
    (hydragnn/postprocess/postprocess.py:13-26).
    """

    x_min: np.ndarray
    x_max: np.ndarray
    y_min: np.ndarray
    y_max: np.ndarray
    node_y_min: Optional[np.ndarray] = None
    node_y_max: Optional[np.ndarray] = None

    @staticmethod
    def fit(graphs: List[Graph]) -> "MinMax":
        xs = np.concatenate([g.x for g in graphs], axis=0)
        x_min, x_max = xs.min(0), xs.max(0)
        if graphs[0].graph_y is not None:
            ys = np.stack([np.asarray(g.graph_y) for g in graphs])
            y_min, y_max = ys.min(0), ys.max(0)
        else:
            y_min = y_max = np.zeros((0,), np.float32)
        return MinMax(x_min, x_max, y_min, y_max, x_min, x_max)

    def apply(self, graphs: List[Graph]) -> List[Graph]:
        out = []
        xr = np.where(self.x_max > self.x_min, self.x_max - self.x_min, 1.0)
        yr = np.where(self.y_max > self.y_min, self.y_max - self.y_min, 1.0)
        for g in graphs:
            x = (g.x - self.x_min) / xr
            gy = None if g.graph_y is None else (g.graph_y - self.y_min) / yr
            out.append(dataclasses.replace(g, x=x.astype(np.float32), graph_y=gy))
        return out

    def denormalize_graph(self, y: np.ndarray, idx: slice) -> np.ndarray:
        return y * (self.y_max[idx] - self.y_min[idx]) + self.y_min[idx]

    def denormalize_node(self, y: np.ndarray, idx: slice) -> np.ndarray:
        """Node heads are extracted from (normalized) ``graph.x`` columns, so
        their scale is the x min/max (reference: output_denormalize covers
        every head, hydragnn/postprocess/postprocess.py:13-26)."""
        lo = (self.node_y_min if self.node_y_min is not None else self.x_min)[idx]
        hi = (self.node_y_max if self.node_y_max is not None else self.x_max)[idx]
        rng = np.where(hi > lo, hi - lo, 1.0)
        return y * rng + lo


def branch_sample_weights(
    graphs: Sequence[Graph], branch_weights: Dict[int, float]
) -> np.ndarray:
    """Per-sample draw weights giving each dataset branch a total sampling
    share proportional to ``branch_weights[dataset_id]``.

    The SPMD analog of the reference's *uneven* branch process groups
    (examples/multibranch/train.py:166-213 sizes each branch's rank count
    by its dataset; MultiTaskModelMP then trains them in parallel): here
    one merged loader draws with replacement, and these weights set how
    much step budget each branch receives regardless of dataset size —
    e.g. weights {0: 1, 1: 1} equalize a large and a small dataset.
    """
    ids = np.asarray([g.dataset_id for g in graphs], np.int64)
    uncovered = sorted(set(ids.tolist()) - set(branch_weights))
    if uncovered:
        raise ValueError(f"dataset_id(s) {uncovered} not in branch_weights")
    w = np.zeros(ids.shape[0], np.float64)
    for ds_id, share in branch_weights.items():
        if share <= 0:
            raise ValueError(
                f"branch_weights[{ds_id}] must be positive, got {share}"
            )
        mask = ids == ds_id
        count = int(mask.sum())
        if count == 0:
            raise ValueError(f"no samples with dataset_id {ds_id}")
        w[mask] = float(share) / count
    return w


def split_dataset(
    graphs: List[Graph],
    perc_train: float,
    seed: int = 0,
    stratified: bool = False,
) -> Tuple[List[Graph], List[Graph], List[Graph]]:
    """Random train/val/test split; val and test share the remainder equally.

    (reference: hydragnn/preprocess/load_data.py:329-349; the compositional
    stratified variant lives in utils/datasets/compositional_data_splitting.py
    and is approximated here by stratifying on the node-type multiset hash.)
    """
    rng = np.random.default_rng(seed)
    idx = np.arange(len(graphs))
    if stratified:
        # group indices by composition signature, deal each group round-robin
        from collections import defaultdict

        groups = defaultdict(list)
        for i, g in enumerate(graphs):
            key = tuple(np.bincount(np.asarray(g.z, np.int64) if g.z is not None else [0]))
            groups[key].append(i)
        order = []
        for key in sorted(groups):
            sub = np.array(groups[key])
            rng.shuffle(sub)
            order.append(sub)
        idx = np.concatenate(order) if order else idx
        # interleave groups so each split sees every composition
        idx = idx[_deal_order(len(idx))]
    else:
        rng.shuffle(idx)
    n_train = int(len(idx) * perc_train)
    n_val = (len(idx) - n_train) // 2
    tr = [graphs[i] for i in idx[:n_train]]
    va = [graphs[i] for i in idx[n_train : n_train + n_val]]
    te = [graphs[i] for i in idx[n_train + n_val :]]
    return tr, va, te


def _deal_order(n: int) -> np.ndarray:
    """Round-robin dealing permutation: 0, k, 2k, ..., 1, k+1, ... with k=10."""
    k = 10
    cols = [np.arange(s, n, k) for s in range(k)]
    return np.concatenate(cols)


class GraphLoader:
    """Shuffling, statically-padded batch iterator over a list of graphs.

    Replaces DataLoader+DistributedSampler (reference: load_data.py:225-326).
    ``host_count``/``host_index`` shard samples across hosts for multi-host DP
    (DistributedSampler semantics: each host sees 1/host_count of the samples).
    """

    def __init__(
        self,
        graphs: List[Graph],
        batch_size: int,
        spec: Optional[PadSpec] = None,
        shuffle: bool = True,
        seed: int = 0,
        host_count: int = 1,
        host_index: int = 0,
        drop_last: bool = False,
        num_shards: int = 1,
        num_buckets: int = 1,
        oversampling: bool = False,
        num_samples: Optional[int] = None,
        sample_weights: Optional[np.ndarray] = None,
        sort_edges: bool = False,
        max_in_degree: Optional[int] = None,
        prefetch: int = 0,
        size_bucketing: bool = False,
        bucket_window: int = 16,
        pack: bool = False,
        with_triplets: bool = False,
        validator=None,
        source: str = "dataset",
        stall_timeout: float = 600.0,
    ):
        """``num_shards`` > 1 emits *stacked* batches with a leading device
        axis [num_shards, ...]: each shard is an independent padded batch with
        local indices, ready for ``shard_map`` data parallelism (``spec`` then
        describes one shard of batch_size/num_shards graphs).

        ``spec`` may be a single ``PadSpec`` (every batch padded to it) or a
        ``SpecLadder`` (each batch padded to the smallest fitting level);
        ``num_buckets`` > 1 with ``spec=None`` builds a ladder from the data
        (the variable-graph-size strategy, SURVEY §5.7).

        ``validator`` (data/validate.SampleValidator) gates bad samples at
        construction per ``Dataset.bad_sample_policy`` — non-finite
        channels, degenerate edge indices, and (under a fixed ``spec``)
        budget-overflow graphs are dropped-and-counted or raised instead of
        crashing mid-epoch; ``source`` labels this loader's rejects in the
        tally/manifest. ``stall_timeout`` (seconds; 0 disables) bounds how
        long the prefetch consumer waits on a silent producer before
        raising ``LoaderStallError``."""
        self.validator = validator
        self.source = source
        self.stall_timeout = float(stall_timeout or 0.0)
        if validator is not None:
            # content checks always; budget caps only when the spec is fixed
            # (auto-built ladders/budgets are derived from the data below and
            # fit every sample by construction)
            worst = (
                spec.specs[-1] if isinstance(spec, SpecLadder) else spec
            )
            graphs = validator.filter(
                graphs,
                source=source,
                max_nodes=worst.n_nodes - 1 if worst is not None else None,
                max_edges=worst.n_edges if worst is not None else None,
            )
        self.graphs = graphs
        self.batch_size = batch_size
        self.num_shards = num_shards
        if num_shards > 1 and batch_size % num_shards != 0:
            raise ValueError(
                f"batch_size {batch_size} must be divisible by num_shards "
                f"{num_shards} (each device takes batch_size/num_shards graphs)"
            )
        per_shard = max(batch_size // num_shards, 1)
        # packed mode: batches are formed by greedy bin-packing into ONE
        # fixed node/edge budget with a VARIABLE real-graph count (graph
        # slots are padded and masked like everything else). One PadSpec =
        # one jit specialization — no ladder, no per-level recompiles —
        # at ~the same occupancy the ladder reaches (docs/PERFORMANCE.md).
        self.pack = bool(pack)
        self._pack_cache = None  # (seed, epoch) -> (bins, agreed length)
        # per-graph triplet counts, computed at most ONCE per loader:
        # _triplet_count is O(E) interpreted python per graph, and the
        # packing/ladder paths would otherwise recompute it every epoch
        # (times host_count lockstep simulations) and again per batch
        self._trip_counts: Optional[np.ndarray] = None
        self._trip_by_id: Dict[int, int] = {}
        if self.pack:
            if isinstance(spec, SpecLadder):
                spec = spec.specs[-1]
            # with_triplets must reach the auto budget: a directly
            # constructed DimeNet pack loader would otherwise get
            # n_triplets=0 batches (the api.prepare_data path always
            # passes a spec)
            self.ladder = SpecLadder(
                (spec if spec is not None
                 else _pack_spec(graphs, per_shard,
                                 with_triplets=with_triplets),)
            )
        elif spec is None:
            self.ladder = SpecLadder.for_dataset(
                graphs,
                per_shard,
                num_buckets=num_buckets,
                # levels must be quantiles of the totals the active batch-
                # composition policy actually produces
                size_bucketing=size_bucketing,
                bucket_window=bucket_window,
                with_triplets=with_triplets,
            )
        elif isinstance(spec, SpecLadder):
            self.ladder = spec
        else:
            self.ladder = SpecLadder((spec,))
        # worst-case spec, kept for callers sizing buffers off loader.spec
        self.spec = self.ladder.specs[-1]
        self.shuffle = shuffle
        self.seed = seed
        self.host_count = host_count
        self.host_index = host_index
        self.drop_last = drop_last
        # RandomSampler-with-replacement / fixed-draw loader modes
        # (reference: create_dataloaders oversampling + num_samples,
        # hydragnn/preprocess/load_data.py:237-274)
        self.oversampling = oversampling
        self.num_samples = num_samples
        # per-sample draw weights (uneven-branch analog, see
        # branch_sample_weights); only meaningful with oversampling
        if sample_weights is not None:
            if not oversampling:
                raise ValueError("sample_weights requires oversampling=True")
            w = np.asarray(sample_weights, np.float64)
            if w.shape != (len(graphs),):
                raise ValueError(
                    f"sample_weights shape {w.shape} != ({len(graphs)},)"
                )
            sample_weights = w / w.sum()
        self.sample_weights = sample_weights
        # receiver-sorted edges (the Pallas sorted-segment-sum precondition,
        # ops/pallas_segment.py; also scatter-friendlier for XLA)
        self.sort_edges = sort_edges
        # the Pallas kernel leaves over-degree segments UNSPECIFIED
        # (ops/pallas_segment.py); fail loudly at loader build instead of
        # risking silently wrong aggregation sums on device
        if sort_edges and max_in_degree:
            for gi, g in enumerate(graphs):
                if g.num_edges:
                    top = int(
                        np.bincount(
                            np.asarray(g.receivers), minlength=g.num_nodes
                        ).max()
                    )
                    if top > int(max_in_degree):
                        raise ValueError(
                            f"graph {gi} (dataset_id "
                            f"{int(getattr(g, 'dataset_id', 0) or 0)}) has "
                            f"in-degree {top} > max_in_degree "
                            f"{max_in_degree}; raise Architecture.max_in_degree "
                            "(the Pallas sorted-segment kernel would produce "
                            "unspecified sums for over-degree nodes)"
                        )
        # background-thread batch building: host batching overlaps device
        # compute (the reference's HydraDataLoader thread-pool loader,
        # hydragnn/preprocess/load_data.py:93-203; its core-affinity pinning
        # has no analog here — XLA owns the host threads)
        self.prefetch = int(prefetch)
        # size-bucketed batch composition: batches drawn from a shuffled
        # window sorted by node count, so per-batch node totals concentrate
        # near window-median * batch_size instead of spreading over the full
        # batch-total distribution — most batches then *fill* their ladder
        # level and padding waste drops (the big padding-cost lever at
        # OC20-like size spreads; see docs/PERFORMANCE.md). Batch ORDER is
        # re-shuffled so SGD still sees random batch sequencing.
        self.size_bucketing = bool(size_bucketing)
        self.bucket_window = int(bucket_window)
        self._node_counts = (
            np.asarray([g.num_nodes for g in graphs], np.int64)
            if self.size_bucketing
            else None
        )
        self.epoch = 0
        # mid-epoch resume (docs/ROBUSTNESS.md "Data plane"): start_batch
        # skips the first k batches of the epoch WITHOUT building them — the
        # epoch permutation is a pure function of (seed, epoch), so (epoch,
        # cursor) is the loader's complete state and the remaining batches
        # replay in exactly the order an unkilled run would have seen
        self.start_batch = 0
        self._resume: Optional[Tuple[int, int]] = None

    def set_epoch(self, epoch: int) -> None:
        """Reseed the shuffle per epoch (DistributedSampler.set_epoch analog).

        The first call after ``resume()`` keeps the armed (epoch, cursor)
        instead — the resumed run's first training epoch replays the
        interrupted epoch's tail; later calls behave normally."""
        if self._resume is not None:
            self.epoch, self.start_batch = self._resume
            self._resume = None
        else:
            self.epoch = epoch
            self.start_batch = 0

    def resume(self, epoch: int, next_batch: int) -> None:
        """Arm deterministic mid-epoch resume at (``epoch``, ``next_batch``):
        applied immediately AND kept through the next ``set_epoch`` (the
        training loop's per-epoch reseed), one-shot."""
        self.epoch = int(epoch)
        self.start_batch = int(next_batch)
        self._resume = (int(epoch), int(next_batch))

    def state_dict(self, next_batch: int = 0) -> Dict[str, int]:
        """Loader state for checkpointing: the shuffle RNG is derived from
        (seed, epoch), so these four ints fully determine the remaining
        batch stream (train/checkpoint.py save_loader_state)."""
        return {
            "seed": int(self.seed),
            "epoch": int(self.epoch),
            "next_batch": int(next_batch),
            "num_batches": int(len(self)),
        }

    def __len__(self) -> int:
        if self.pack:
            return self._pack_state()[1]
        n = len(self._local_indices())
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def _count_from_ngroups(self, n_groups: int) -> int:
        """Batch count ``n_groups`` packed bins yield under the current
        shard/drop_last settings."""
        if self.num_shards == 1:
            return max(n_groups - 1, 0) if self.drop_last else n_groups
        if self.drop_last:
            return n_groups // self.num_shards
        return (n_groups + self.num_shards - 1) // self.num_shards

    def _trip_count_table(self) -> np.ndarray:
        """Lazy one-time scan: triplet count per dataset graph (also memoized
        by object id for the _make shard-spec lookup)."""
        if self._trip_counts is None:
            self._trip_counts = np.asarray(
                [_triplet_count(g) for g in self.graphs], np.int64
            )
            self._trip_by_id = {
                id(g): int(c) for g, c in zip(self.graphs, self._trip_counts)
            }
        return self._trip_counts

    def _trip_count_of(self, g: Graph) -> int:
        got = self._trip_by_id.get(id(g))
        return _triplet_count(g) if got is None else got

    def _pack_count_for(self, idx: np.ndarray) -> int:
        """Packed-batch count an index stream yields under current settings."""
        if self.size_bucketing and len(idx) > self.batch_size:
            idx = self._bucket_order(idx)
        return self._count_from_ngroups(len(self._pack_groups(idx)))

    def _pack_state(self) -> Tuple[List[List[int]], int]:
        """(local bins, agreed epoch length), computed once per (seed, epoch).

        The agreed length needs no communication: the epoch permutation is a
        pure function of (seed, epoch), so each host simulates every host's
        packing and takes the min — the packed analog of the equal-shard
        truncation in _global_indices (surplus bins on faster-packing hosts
        are dropped, like DistributedSampler's tail)."""
        key = (self.seed, self.epoch)
        if self._pack_cache is not None and self._pack_cache[0] == key:
            return self._pack_cache[1], self._pack_cache[2]
        idx = self._local_indices()
        if self.size_bucketing and len(idx) > self.batch_size:
            idx = self._bucket_order(idx)
        groups = self._pack_groups(idx)
        counts = [self._count_from_ngroups(len(groups))]
        if self.host_count > 1:
            gidx = self._global_indices()
            counts.extend(
                self._pack_count_for(gidx[h :: self.host_count])
                for h in range(self.host_count)
                if h != self.host_index
            )
        agreed = min(counts)
        self._pack_cache = (key, groups, agreed)
        return groups, agreed

    def _pack_groups(self, idx: np.ndarray) -> List[List[int]]:
        """Greedy stream packing: consecutive samples accumulate into a bin
        until the next one would overflow the node/edge/triplet budget or the
        graph-slot cap. Every bin fits ``self.spec`` by construction."""
        spec = self.spec
        cap_n, cap_e = spec.n_nodes - 1, spec.n_edges  # -1: dummy node slot
        cap_g, cap_t = spec.n_graphs - 1, spec.n_triplets
        trips = self._trip_count_table() if cap_t else None
        groups: List[List[int]] = []
        cur: List[int] = []
        n = e = t = 0
        for i in idx:
            g = self.graphs[i]
            gn, ge = g.num_nodes, g.num_edges
            gt = int(trips[i]) if cap_t else 0
            if gn > cap_n or ge > cap_e or (cap_t and gt > cap_t):
                if self.validator is not None:
                    # warn_skip/quarantine: drop-and-count instead of killing
                    # the run (dedup in the validator keeps the per-epoch
                    # re-pack from inflating the tally); error policy raises
                    # a BadSampleError naming the sample
                    self.validator.reject(
                        g, int(i), "budget_overflow", source=self.source,
                        detail=(
                            f"nodes={gn}, edges={ge}, triplets={gt} vs pack "
                            f"budget {spec}"
                        ),
                    )
                    continue
                raise ValueError(
                    f"graph {i} (dataset_id "
                    f"{int(getattr(g, 'dataset_id', 0) or 0)}, nodes={gn}, "
                    f"edges={ge}"
                    + (f", triplets={gt}" if cap_t else "")
                    + f") exceeds the pack budget {spec}; pass a larger spec "
                    "or set Dataset.bad_sample_policy to warn_skip/quarantine "
                    "to drop oversized samples"
                )
            if cur and (
                n + gn > cap_n
                or e + ge > cap_e
                or len(cur) >= cap_g
                or (cap_t and t + gt > cap_t)
            ):
                groups.append(cur)
                cur, n, e, t = [], 0, 0, 0
            cur.append(int(i))
            n, e, t = n + gn, e + ge, t + gt
        if cur:
            groups.append(cur)
        return groups

    def _global_indices(self) -> np.ndarray:
        """The full (permuted) epoch index stream BEFORE host slicing —
        identical on every host, which is what makes both the equal-shard
        truncation and the packed-mode lockstep agreement communication-free."""
        rng = np.random.default_rng(self.seed + self.epoch)
        if self.oversampling:
            n = self.num_samples or len(self.graphs)
            idx = rng.choice(
                len(self.graphs), size=n, replace=True, p=self.sample_weights
            )
        else:
            idx = np.arange(len(self.graphs))
            if self.shuffle:
                rng.shuffle(idx)
            if self.num_samples is not None:
                idx = idx[: self.num_samples]
        if self.host_count > 1:
            # equal shard sizes on every host, so multi-host training steps
            # stay in lockstep (a one-sample imbalance would leave one host
            # issuing an extra collective and deadlock the others)
            idx = idx[: len(idx) // self.host_count * self.host_count]
        return idx

    def _local_indices(self) -> np.ndarray:
        return self._global_indices()[self.host_index :: self.host_count]

    def _bucket_order(self, idx: np.ndarray) -> np.ndarray:
        """Reorder ``idx`` so contiguous ``batch_size`` slices are size-
        homogeneous: sort by node count within shuffled windows of
        ``bucket_window * batch_size`` samples (the whole set when not
        shuffling — eval wants maximal packing), then shuffle the order of
        the resulting full batches."""
        bs = self.batch_size
        # the remainder stays OUT of the sorting: a size-sorted tail would
        # make the final (dropped under drop_last) partial batch
        # systematically the largest graphs — the input order's tail is
        # unbiased (shuffled) or matches the plain loader (eval)
        n_full = len(idx) // bs
        head, tail = idx[: n_full * bs], idx[n_full * bs :]
        w = self.bucket_window * bs if self.shuffle else len(head)
        parts = []
        for s in range(0, len(head), max(w, bs)):
            win = head[s : s + max(w, bs)]
            order = np.argsort(self._node_counts[win], kind="stable")
            parts.append(win[order])
        head = np.concatenate(parts) if parts else head
        if self.shuffle and n_full > 1:
            rng = np.random.default_rng((self.seed + self.epoch) ^ 0x5EEDB)
            batch_order = rng.permutation(n_full)
            head = head.reshape(n_full, bs)[batch_order].reshape(-1)
        return np.concatenate([head, tail])

    def _batches(self) -> Iterator[GraphBatch]:
        # mid-epoch resume: the first ``start_batch`` batches of the epoch
        # are skipped WITHOUT being built (the index stream is deterministic
        # in (seed, epoch), so slicing the batch sequence is exact)
        start = max(int(self.start_batch), 0)
        if self.pack:
            yield from self._packed_batches(start)
            return
        idx = self._local_indices()
        if self.size_bucketing and len(idx) > self.batch_size:
            idx = self._bucket_order(idx)
        bs = self.batch_size
        n_full = len(idx) // bs
        for b in range(start, n_full):
            yield self._make([self.graphs[i] for i in idx[b * bs : (b + 1) * bs]])
        rem = len(idx) - n_full * bs
        if rem and not self.drop_last and start <= n_full:
            yield self._make([self.graphs[i] for i in idx[n_full * bs :]])

    def _packed_batches(self, start: int = 0) -> Iterator[GraphBatch]:
        # multi-host: stop at the globally agreed count so every host issues
        # the same number of (collective-bearing) steps
        groups, limit = self._pack_state()
        emitted = 0
        if self.num_shards == 1:
            if self.drop_last and len(groups) > 1:
                groups = groups[:-1]  # only the final bin can be sparse
            for grp in groups:
                if emitted >= limit:
                    return
                emitted += 1
                if emitted <= start:
                    continue
                yield batch_graphs(
                    [self.graphs[i] for i in grp],
                    self.spec,
                    sort_edges=self.sort_edges,
                )
            return
        for c in range(0, len(groups), self.num_shards):
            chunk = groups[c : c + self.num_shards]
            if emitted >= limit or (
                len(chunk) < self.num_shards and self.drop_last
            ):
                return
            emitted += 1
            if emitted <= start:
                continue
            yield self._make_stacked(
                [[self.graphs[i] for i in grp] for grp in chunk], self.spec
            )

    def _emit_stall_event(self, cause: str, batch_index: int) -> None:
        """Typed incident record for a stall verdict (obs/events.py) — the
        flight-recorder window sees WHICH batch wedged, not just a counter
        increment. Never allowed to fail the watchdog itself."""
        try:
            from ..obs.events import EV_LOADER_STALL
            from ..obs.events import emit as _emit_event

            _emit_event(
                EV_LOADER_STALL,
                severity="error",
                cause=cause,
                source=self.source,
                batch_index=int(batch_index),
                epoch=int(self.epoch),
            )
        except Exception:
            pass

    def __iter__(self) -> Iterator[GraphBatch]:
        if self.prefetch <= 0:
            yield from self._batches()
            return
        # bounded producer thread: up to ``prefetch`` batches built ahead
        import queue
        import threading

        from ..utils import faultinject

        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        _END, _ERR, _NOTSET = object(), object(), object()
        epoch_start = int(self.start_batch)

        def put_or_stop(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for k, batch in enumerate(self._batches()):
                    # chaos hooks (exact no-ops unarmed): a producer wedged
                    # in a slow build, or dead without its sentinel
                    if faultinject.maybe_loader_fault(epoch_start + k) == "die":
                        return
                    if not put_or_stop(batch):
                        return
                put_or_stop(_END)
            except BaseException as e:  # surfaced in the consumer
                put_or_stop((_ERR, e))

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        # exposed for tests asserting the thread is reaped after errors/break
        self._producer_thread = t
        # telemetry plane (obs/registry.py): prefetch-queue depth is the
        # live H2D-pipeline health signal — a depth pinned at 0 means the
        # device is waiting on host batch-build (the ROADMAP-3 H2D stall
        # axis); stalls are counted where they are raised
        from ..obs.registry import registry as _obs_registry

        g_depth = _obs_registry().gauge(
            "hydragnn_loader_prefetch_depth",
            "Prefetch queue depth observed at each batch handoff",
            labelnames=("source",),
        )
        c_stall = _obs_registry().counter(
            "hydragnn_loader_stalls_total",
            "LoaderStallError raised (dead or wedged prefetch producer)",
            labelnames=("source",),
        )
        c_stall.inc(0, source=self.source)  # materialize the series at 0
        timeout = float(self.stall_timeout or 0.0)
        delivered = 0
        try:
            while True:
                # timed wait + liveness watchdog instead of a bare blocking
                # get: a producer that died without the sentinel, or one
                # stalled past ``stall_timeout``, raises an actionable error
                # instead of hanging the run forever
                item = _NOTSET
                waited = 0.0
                while item is _NOTSET:
                    try:
                        item = q.get(timeout=_WATCHDOG_TICK_S)
                    except queue.Empty:
                        if not t.is_alive():
                            # the producer may have published a final item
                            # between our timeout and the liveness check
                            try:
                                item = q.get_nowait()
                                break
                            except queue.Empty:
                                c_stall.inc(source=self.source)
                                self._emit_stall_event(
                                    "producer_died", epoch_start + delivered
                                )
                                raise LoaderStallError(
                                    "prefetch producer thread exited without "
                                    "an end-of-epoch sentinel after batch "
                                    f"{epoch_start + delivered - 1} (epoch "
                                    f"{self.epoch}); the worker died outside "
                                    "python (or was killed) — restarting the "
                                    "epoch is required"
                                ) from None
                        waited += _WATCHDOG_TICK_S
                        if timeout and waited >= timeout:
                            c_stall.inc(source=self.source)
                            self._emit_stall_event(
                                "producer_wedged", epoch_start + delivered
                            )
                            raise LoaderStallError(
                                "prefetch producer produced nothing for "
                                f"{waited:.1f}s (> loader_stall_timeout="
                                f"{timeout}s) while building batch "
                                f"{epoch_start + delivered} of epoch "
                                f"{self.epoch}; the worker is wedged (hung "
                                "fetch/filesystem?) — raise "
                                "Training.loader_stall_timeout if batches "
                                "legitimately take this long"
                            ) from None
                if item is _END:
                    break
                if isinstance(item, tuple) and len(item) == 2 and item[0] is _ERR:
                    raise item[1]
                delivered += 1
                g_depth.set(q.qsize(), source=self.source)
                yield item
        finally:
            # abandoned mid-epoch (break / exception): release the producer
            # and reap it with a bounded join — a producer blocked inside a
            # slow batch build cannot observe ``stop`` until it finishes, so
            # warn (daemon thread, leaked until process exit) instead of
            # blocking teardown indefinitely
            stop.set()
            t.join(timeout=_PRODUCER_JOIN_TIMEOUT_S)
            if t.is_alive():
                warnings.warn(
                    "prefetch producer thread still alive "
                    f"{_PRODUCER_JOIN_TIMEOUT_S}s after the epoch was "
                    "abandoned (blocked in a batch build?); leaking the "
                    "daemon thread",
                    RuntimeWarning,
                    stacklevel=2,
                )

    def spec_template_batches(self) -> List[Tuple[PadSpec, GraphBatch]]:
        """One template ``GraphBatch`` per ladder level this loader can emit
        — the compile plane's warm-up inputs (see the module-level
        ``spec_template_batches`` for the shape argument). Stacked
        (multi-shard) loaders pad the extra shard rows."""
        if self.num_shards == 1:
            return spec_template_batches(
                self.graphs,
                self.ladder,
                sort_edges=self.sort_edges,
                trip_count_of=self._trip_count_of,
            )
        out: List[Tuple[PadSpec, GraphBatch]] = []
        for li, g in selectable_levels(
            self.graphs, self.ladder, self._trip_count_of
        ):
            spec = self.ladder.specs[li]
            shards = [[g]] + [[] for _ in range(self.num_shards - 1)]
            out.append((spec, self._make_stacked(shards, spec)))
        return out

    def _make(self, graphs: List[Graph]) -> GraphBatch:
        with_trip = bool(self.spec.n_triplets)
        if with_trip:
            self._trip_count_table()  # populate the id memo once
        if self.num_shards == 1:
            spec = self.ladder.select(
                sum(g.num_nodes for g in graphs),
                sum(g.num_edges for g in graphs),
                sum(self._trip_count_of(g) for g in graphs) if with_trip else 0,
            )
            return batch_graphs(graphs, spec, sort_edges=self.sort_edges)
        shards = [graphs[s :: self.num_shards] for s in range(self.num_shards)]
        # one spec for the whole stacked batch: the smallest level fitting
        # the largest shard (all shards must share static shapes)
        spec = self.ladder.select(
            max(sum(g.num_nodes for g in s) for s in shards if s),
            max(sum(g.num_edges for g in s) for s in shards if s),
            max(
                (sum(self._trip_count_of(g) for g in s) for s in shards if s),
                default=0,
            )
            if with_trip
            else 0,
        )
        return self._make_stacked(shards, spec)

    def _make_stacked(
        self, shards: List[List[Graph]], spec: PadSpec
    ) -> GraphBatch:
        """Stack per-shard padded batches into a leading device axis;
        missing shards become all-padding rows."""
        return stack_shard_batches(
            shards, spec, self.num_shards, sort_edges=self.sort_edges
        )
