"""In-memory distributed-style sample store backed by the native C++ arena
(hydragnn_tpu/native/ddstore.cpp) — the pyddstore/DistDataset analog
(reference: hydragnn/utils/datasets/distdataset.py:1-183; train-loop epoch
window brackets train_validate_test.py:480-563).

``DDStore`` is the raw blob store (ctypes over the shared-memory arena);
``DistDataset`` wraps any dataset into it: every sample is serialized once
into the per-host arena (by the creating process) and every loader process
fetches one-sidedly by index. Cross-host scale-out is by per-host dataset
shards (data/columnar.py) rather than the reference's MPI RMA window —
on TPU pods each host only ever feeds its own devices.
"""

from __future__ import annotations

import ctypes
import io
import os
import pickle
from typing import Optional, Sequence

import numpy as np

from .datasets import AbstractBaseDataset
from .graph import Graph


class DDStore:
    """ctypes facade over the native shared-memory blob store."""

    def __init__(
        self,
        name: str,
        capacity_bytes: int = 1 << 28,
        max_items: int = 1 << 20,
        create: bool = True,
        overwrite: bool = False,
    ):
        from ..native.build import build_library

        lib = ctypes.CDLL(build_library("ddstore"))
        lib.dds_unlink.restype = ctypes.c_int
        lib.dds_unlink.argtypes = [ctypes.c_char_p]
        lib.dds_open.restype = ctypes.c_void_p
        lib.dds_open.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int,
        ]
        lib.dds_put.restype = ctypes.c_int
        lib.dds_put.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_void_p,
            ctypes.c_int64,
        ]
        lib.dds_get_size.restype = ctypes.c_int64
        lib.dds_get_size.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.dds_get.restype = ctypes.c_int64
        lib.dds_get.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_void_p,
            ctypes.c_int64,
        ]
        for fn in ("dds_count", "dds_max_items", "dds_used_bytes", "dds_epoch"):
            getattr(lib, fn).restype = ctypes.c_int64
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        for fn in ("dds_epoch_begin", "dds_epoch_end"):
            getattr(lib, fn).restype = None
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        lib.dds_close.restype = None
        lib.dds_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        self._lib = lib
        self.name = name
        if create and overwrite:
            lib.dds_unlink(name.encode())
        self._h = lib.dds_open(
            name.encode(), capacity_bytes, max_items, 1 if create else 0
        )
        if not self._h:
            if create:
                raise FileExistsError(
                    f"shared-memory store {name!r} already exists; pick a "
                    "distinct name or pass overwrite=True to replace a stale "
                    "segment from a crashed run"
                )
            raise OSError(f"cannot attach shared-memory store {name!r}")
        self._owner = create
        self.max_items = int(lib.dds_max_items(self._h))

    def put(self, idx: int, blob: bytes) -> None:
        rc = self._lib.dds_put(self._h, idx, blob, len(blob))
        if rc == -1:
            raise MemoryError("DDStore payload arena full")
        if rc == -2:
            raise IndexError(
                f"id {idx} outside slot table [0, {self.max_items})"
            )
        if rc == -3:
            raise KeyError(f"id {idx} already stored")

    def get(self, idx: int) -> bytes:
        size = self._lib.dds_get_size(self._h, idx)
        if size < 0:
            raise KeyError(idx)
        buf = ctypes.create_string_buffer(size)
        got = self._lib.dds_get(self._h, idx, buf, size)
        assert got == size
        return buf.raw

    def __len__(self) -> int:
        return int(self._lib.dds_count(self._h))

    @property
    def used_bytes(self) -> int:
        return int(self._lib.dds_used_bytes(self._h))

    def epoch_begin(self) -> None:
        self._lib.dds_epoch_begin(self._h)

    def epoch_end(self) -> None:
        self._lib.dds_epoch_end(self._h)

    def close(self, unlink: Optional[bool] = None) -> None:
        if self._h:
            self._lib.dds_close(
                self._h, 1 if (self._owner if unlink is None else unlink) else 0
            )
            self._h = None

    def __del__(self):  # pragma: no cover - interpreter teardown
        try:
            self.close(unlink=False)
        except Exception:
            pass


def _pack_graph(g: Graph) -> bytes:
    out = io.BytesIO()
    pickle.dump(g, out, protocol=pickle.HIGHEST_PROTOCOL)
    return out.getvalue()


class DistDataset(AbstractBaseDataset):
    """Serve any dataset out of the shared arena
    (reference: DistDataset, distdataset.py:26-183).

    The creating process loads/serializes every sample once
    (``populate=True``) and then publishes a manifest blob in the last slot;
    attachers (other loader processes on the same host) construct with
    ``populate=False`` and block until that manifest appears, so they never
    observe a partially populated store (the reference gets the same
    guarantee from its MPI collective construction).
    """

    def __init__(
        self,
        dataset: Optional[Sequence[Graph]] = None,
        name: str = "hydragnn_dds",
        capacity_bytes: int = 1 << 28,
        max_items: int = 1 << 20,
        populate: Optional[bool] = None,
        overwrite: bool = False,
        attach_timeout_s: float = 300.0,
    ):
        import time

        populate = dataset is not None if populate is None else populate
        if populate:
            self.store = DDStore(
                name,
                capacity_bytes=capacity_bytes,
                max_items=max_items,
                create=True,
                overwrite=overwrite,
            )
        else:
            # retry attachment too: a concurrently-starting creator may not
            # have finished dds_open yet (half-initialized header rejected)
            deadline = time.monotonic() + attach_timeout_s
            while True:
                try:
                    self.store = DDStore(
                        name,
                        capacity_bytes=capacity_bytes,
                        max_items=max_items,
                        create=False,
                    )
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.05)
        manifest_id = self.store.max_items - 1
        if populate:
            assert dataset is not None
            n = len(dataset)
            if n > manifest_id:
                raise ValueError(
                    f"dataset has {n} samples but the store holds at most "
                    f"{manifest_id} (the last slot is the manifest); raise "
                    "max_items"
                )
            for i, g in enumerate(dataset):
                self.store.put(i, _pack_graph(g))
            self.store.put(manifest_id, pickle.dumps({"len": n}))
            self._len = n
        else:
            deadline = time.monotonic() + attach_timeout_s
            while True:
                try:
                    manifest = pickle.loads(self.store.get(manifest_id))
                    break
                except KeyError:
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"store {name!r} was never marked fully populated"
                        ) from None
                    time.sleep(0.05)
            self._len = int(manifest["len"])

    def get(self, idx: int) -> Graph:
        return pickle.loads(self.store.get(idx))

    def __len__(self) -> int:
        return self._len

    def epoch_begin(self) -> None:
        self.store.epoch_begin()

    def epoch_end(self) -> None:
        self.store.epoch_end()

    def close(self, unlink: Optional[bool] = None) -> None:
        self.store.close(unlink)
