"""In-memory distributed-style sample store backed by the native C++ arena
(hydragnn_tpu/native/ddstore.cpp) — the pyddstore/DistDataset analog
(reference: hydragnn/utils/datasets/distdataset.py:1-183; train-loop epoch
window brackets train_validate_test.py:480-563).

``DDStore`` is the raw blob store (ctypes over the shared-memory arena);
``DistDataset`` wraps any dataset into it: every sample is serialized once
into the per-host arena (by the creating process) and every loader process
fetches one-sidedly by index.

Cross-host scale-out has two modes:
- per-host dataset shards (data/columnar.py): each host only ever reads its
  own slice — the default on TPU pods;
- ``MultiHostDistDataset``: each host pins only ``1/num_hosts`` of the
  samples in RAM and fetches the rest from the owning host over the
  length-prefixed TCP plane in the C++ store (the DCN analog of the
  reference's MPI one-sided gets, distdataset.py:159-183), for datasets
  larger than one host's memory under *global* shuffling.
"""

from __future__ import annotations

import ctypes
import io
import os
import pickle
from typing import Optional, Sequence

import numpy as np

from .datasets import AbstractBaseDataset
from .graph import Graph
from ..utils import envflags


_LIB = None


def _load_lib():
    """Build/load the native library once with every symbol typed."""
    global _LIB
    if _LIB is not None:
        return _LIB
    from ..native.build import build_library

    lib = ctypes.CDLL(build_library("ddstore"))
    lib.dds_unlink.restype = ctypes.c_int
    lib.dds_unlink.argtypes = [ctypes.c_char_p]
    lib.dds_open.restype = ctypes.c_void_p
    lib.dds_open.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int,
    ]
    lib.dds_put.restype = ctypes.c_int
    lib.dds_put.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_void_p,
        ctypes.c_int64,
    ]
    lib.dds_get_size.restype = ctypes.c_int64
    lib.dds_get_size.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.dds_get.restype = ctypes.c_int64
    lib.dds_get.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_void_p,
        ctypes.c_int64,
    ]
    for fn in ("dds_count", "dds_max_items", "dds_used_bytes", "dds_epoch"):
        getattr(lib, fn).restype = ctypes.c_int64
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    for fn in ("dds_epoch_begin", "dds_epoch_end"):
        getattr(lib, fn).restype = None
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    lib.dds_close.restype = None
    lib.dds_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.dds_serve_start.restype = ctypes.c_void_p
    lib.dds_serve_start.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_int64,
    ]
    lib.dds_serve_stop.restype = None
    lib.dds_serve_stop.argtypes = [ctypes.c_void_p]
    lib.dds_connect.restype = ctypes.c_void_p
    lib.dds_connect.argtypes = [ctypes.c_char_p, ctypes.c_int]
    # timeout-aware connect + per-connection socket timeouts: feature-detect
    # so a stale prebuilt .so (no compiler on the host to rebuild from the
    # updated source) degrades to the historical blocking behavior instead
    # of failing to load
    try:
        lib.dds_connect_t.restype = ctypes.c_void_p
        lib.dds_connect_t.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.c_int,
        ]
        lib.dds_set_timeout.restype = None
        lib.dds_set_timeout.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib._has_timeouts = True
    except AttributeError:  # pragma: no cover - stale binary only
        lib._has_timeouts = False
    lib.dds_fetch.restype = ctypes.c_int64
    lib.dds_fetch.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.dds_fetch_read.restype = ctypes.c_int64
    lib.dds_fetch_read.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
    ]
    lib.dds_disconnect.restype = None
    lib.dds_disconnect.argtypes = [ctypes.c_void_p]
    _LIB = lib
    return lib


class DDStore:
    """ctypes facade over the native shared-memory blob store."""

    def __init__(
        self,
        name: str,
        capacity_bytes: int = 1 << 28,
        max_items: int = 1 << 20,
        create: bool = True,
        overwrite: bool = False,
    ):
        lib = _load_lib()
        self._lib = lib
        self.name = name
        if create and overwrite:
            lib.dds_unlink(name.encode())
        self._h = lib.dds_open(
            name.encode(), capacity_bytes, max_items, 1 if create else 0
        )
        if not self._h:
            if create:
                raise FileExistsError(
                    f"shared-memory store {name!r} already exists; pick a "
                    "distinct name or pass overwrite=True to replace a stale "
                    "segment from a crashed run"
                )
            raise OSError(f"cannot attach shared-memory store {name!r}")
        self._owner = create
        self.max_items = int(lib.dds_max_items(self._h))

    def put(self, idx: int, blob: bytes) -> None:
        rc = self._lib.dds_put(self._h, idx, blob, len(blob))
        if rc == -1:
            raise MemoryError("DDStore payload arena full")
        if rc == -2:
            raise IndexError(
                f"id {idx} outside slot table [0, {self.max_items})"
            )
        if rc == -3:
            raise KeyError(f"id {idx} already stored")

    def get(self, idx: int) -> bytes:
        size = self._lib.dds_get_size(self._h, idx)
        if size < 0:
            raise KeyError(idx)
        buf = ctypes.create_string_buffer(size)
        got = self._lib.dds_get(self._h, idx, buf, size)
        assert got == size
        return buf.raw

    def __len__(self) -> int:
        return int(self._lib.dds_count(self._h))

    @property
    def used_bytes(self) -> int:
        return int(self._lib.dds_used_bytes(self._h))

    def epoch_begin(self) -> None:
        self._lib.dds_epoch_begin(self._h)

    def epoch_end(self) -> None:
        self._lib.dds_epoch_end(self._h)

    def serve(self, port: int, id_offset: int = 0) -> None:
        """Serve published slots on ``port``; wire ids are global
        (local slot = id - id_offset). The accept loop runs on a C++
        thread — no GIL involvement on the hot path."""
        if getattr(self, "_server", None):
            raise RuntimeError("already serving")
        srv = self._lib.dds_serve_start(self._h, port, id_offset)
        if not srv:
            raise OSError(f"cannot listen on port {port}")
        self._server = srv

    def stop_serving(self) -> None:
        if getattr(self, "_server", None):
            self._lib.dds_serve_stop(self._server)
            self._server = None

    def close(self, unlink: Optional[bool] = None) -> None:
        self.stop_serving()
        if self._h:
            self._lib.dds_close(
                self._h, 1 if (self._owner if unlink is None else unlink) else 0
            )
            self._h = None

    def __del__(self):  # pragma: no cover - interpreter teardown
        try:
            self.close(unlink=False)
        except Exception:
            pass


class RemoteStoreClient:
    """Persistent TCP connection fetching blobs from a serving DDStore on
    another host (the MPI one-sided get analog, distdataset.py:159-183).

    Hardened for the multi-day-run regime (docs/ROBUSTNESS.md "Data
    plane"): the socket carries send/receive timeouts from creation (a
    server that accepts but never responds can no longer wedge the loader
    forever), and ``get`` absorbs transient connection failures with
    reconnect + exponential backoff + jitter, bounded by
    ``HYDRAGNN_DDSTORE_RETRIES`` attempts (base delay
    ``HYDRAGNN_DDSTORE_RETRY_BASE`` seconds — tests pin 0 so nothing
    sleeps; socket timeout ``HYDRAGNN_DDSTORE_TIMEOUT`` seconds). The
    terminal error names host, port, global id and attempt count so a dead
    peer is attributable from the traceback alone.

    Not thread-safe (the request/response protocol shares one socket and
    one scratch buffer); fork-safe — a forked loader worker detects the
    inherited connection via the pid and opens its own, so parent and
    child never interleave requests on one fd.
    """

    def __init__(
        self,
        host: str,
        port: int,
        timeout_s: Optional[float] = None,
        retries: Optional[int] = None,
        retry_base: Optional[float] = None,
    ):
        self._lib = _load_lib()
        self.host, self.port = host, port
        self.timeout_s = (
            envflags.env_float("HYDRAGNN_DDSTORE_TIMEOUT", 30.0)
            if timeout_s is None
            else float(timeout_s)
        )
        self.retries = max(
            envflags.env_int("HYDRAGNN_DDSTORE_RETRIES", 4)
            if retries is None
            else int(retries),
            1,
        )
        self.retry_base = (
            envflags.env_float("HYDRAGNN_DDSTORE_RETRY_BASE", 0.25)
            if retry_base is None
            else float(retry_base)
        )
        self._c = None
        self._connect()

    def _connect(self) -> None:
        self._drop()
        timeout_ms = int(self.timeout_s * 1000)
        if getattr(self._lib, "_has_timeouts", False):
            self._c = self._lib.dds_connect_t(
                self.host.encode(), self.port, timeout_ms
            )
        else:  # pragma: no cover - stale binary only
            self._c = self._lib.dds_connect(self.host.encode(), self.port)
        if not self._c:
            self._c = None
            raise ConnectionError(f"cannot connect to {self.host}:{self.port}")
        # only a successful connect updates the pid: a failed reconnect must
        # leave get() retrying _connect, never fetching on a NULL handle
        self._pid = os.getpid()

    def _drop(self) -> None:
        """Discard the current connection, swallowing teardown errors (the
        socket may already be dead — that is why we are dropping it)."""
        c, self._c = getattr(self, "_c", None), None
        if c:
            try:
                self._lib.dds_disconnect(c)
            except Exception:
                pass

    def _fetch_once(self, global_id: int) -> bytes:
        from ..utils import faultinject

        # chaos hook: an exact no-op unless HYDRAGNN_FAULT_SOCKET_DROP arms
        # a drop on this call — then it raises the same ConnectionError a
        # real peer reset produces, exercising the reconnect path below
        faultinject.maybe_socket_drop("ddstore_get")
        if self._c is None or os.getpid() != self._pid:
            # inherited across fork, or a previous reconnect failed: the
            # parent still owns the old socket / there is nothing to fetch on
            self._connect()
        n = self._lib.dds_fetch(self._c, global_id)
        if n == -2:
            raise ConnectionError(
                f"connection to {self.host}:{self.port} lost (or timed out "
                f"after {self.timeout_s}s) fetching id {global_id}"
            )
        if n < 0:
            raise KeyError(global_id)
        buf = ctypes.create_string_buffer(int(n))
        got = self._lib.dds_fetch_read(self._c, buf, n)
        assert got == n
        return buf.raw

    def get(self, global_id: int) -> bytes:
        """Fetch one blob, reconnecting with exponential backoff + jitter on
        transient connection failures. ``KeyError`` (the server answered:
        id not held) is authoritative and never retried."""
        import random
        import time

        last: Optional[ConnectionError] = None
        for attempt in range(self.retries):
            try:
                return self._fetch_once(global_id)
            except ConnectionError as e:
                last = e
                # the stream is dead or desynced either way: drop it so the
                # next attempt reconnects from scratch
                self._drop()
                if attempt + 1 < self.retries and self.retry_base > 0:
                    delay = self.retry_base * (2.0**attempt)
                    time.sleep(delay * (1.0 + 0.25 * random.random()))
        raise ConnectionError(
            f"remote store {self.host}:{self.port} unreachable fetching "
            f"global_id {global_id} after {self.retries} attempts "
            "(HYDRAGNN_DDSTORE_RETRIES; socket timeout "
            f"{self.timeout_s}s via HYDRAGNN_DDSTORE_TIMEOUT): {last}"
        ) from last

    def close(self) -> None:
        self._drop()

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


def _pack_graph(g: Graph) -> bytes:
    out = io.BytesIO()
    pickle.dump(g, out, protocol=pickle.HIGHEST_PROTOCOL)
    return out.getvalue()


def _unpack_graph(blob: bytes, idx: int, where: str) -> Graph:
    """Deserialize a fetched sample, turning any failure into a typed
    ``CorruptSampleError`` naming the sample and its store — bit rot or wire
    corruption must be attributable (and skippable under
    ``Dataset.bad_sample_policy``), not an anonymous UnpicklingError killing
    the run. The chaos hook flips the leading byte when
    HYDRAGNN_FAULT_CORRUPT_SAMPLE arms this id (utils/faultinject.py)."""
    from ..utils import faultinject

    from .validate import CorruptSampleError

    blob = faultinject.corrupt_blob(blob, idx)
    try:
        return pickle.loads(blob)
    except Exception as e:  # noqa: BLE001 — any decode failure is corruption
        raise CorruptSampleError(
            f"sample {idx} from {where} failed to deserialize "
            f"({type(e).__name__}: {e}); the stored bytes are corrupt — "
            "repopulate the store, or let the sample validator quarantine it"
        ) from e


class DistDataset(AbstractBaseDataset):
    """Serve any dataset out of the shared arena
    (reference: DistDataset, distdataset.py:26-183).

    The creating process loads/serializes every sample once
    (``populate=True``) and then publishes a manifest blob in the last slot;
    attachers (other loader processes on the same host) construct with
    ``populate=False`` and block until that manifest appears, so they never
    observe a partially populated store (the reference gets the same
    guarantee from its MPI collective construction).
    """

    def __init__(
        self,
        dataset: Optional[Sequence[Graph]] = None,
        name: str = "hydragnn_dds",
        capacity_bytes: int = 1 << 28,
        max_items: int = 1 << 20,
        populate: Optional[bool] = None,
        overwrite: bool = False,
        attach_timeout_s: float = 300.0,
    ):
        import time

        populate = dataset is not None if populate is None else populate
        if populate:
            self.store = DDStore(
                name,
                capacity_bytes=capacity_bytes,
                max_items=max_items,
                create=True,
                overwrite=overwrite,
            )
        else:
            # retry attachment too: a concurrently-starting creator may not
            # have finished dds_open yet (half-initialized header rejected)
            deadline = time.monotonic() + attach_timeout_s
            while True:
                try:
                    self.store = DDStore(
                        name,
                        capacity_bytes=capacity_bytes,
                        max_items=max_items,
                        create=False,
                    )
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.05)
        manifest_id = self.store.max_items - 1
        if populate:
            assert dataset is not None
            n = len(dataset)
            if n > manifest_id:
                raise ValueError(
                    f"dataset has {n} samples but the store holds at most "
                    f"{manifest_id} (the last slot is the manifest); raise "
                    "max_items"
                )
            for i, g in enumerate(dataset):
                self.store.put(i, _pack_graph(g))
            self.store.put(manifest_id, pickle.dumps({"len": n}))
            self._len = n
        else:
            deadline = time.monotonic() + attach_timeout_s
            while True:
                try:
                    manifest = pickle.loads(self.store.get(manifest_id))
                    break
                except KeyError:
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"store {name!r} was never marked fully populated"
                        ) from None
                    time.sleep(0.05)
            self._len = int(manifest["len"])

    def get(self, idx: int) -> Graph:
        return _unpack_graph(
            self.store.get(idx), idx, f"shared-memory store {self.store.name!r}"
        )

    def __len__(self) -> int:
        return self._len

    def epoch_begin(self) -> None:
        self.store.epoch_begin()

    def epoch_end(self) -> None:
        self.store.epoch_end()

    def close(self, unlink: Optional[bool] = None) -> None:
        self.store.close(unlink)


class MultiHostDistDataset(AbstractBaseDataset):
    """Dataset bigger than one host: each host pins a contiguous block of
    samples in its local shared-memory arena and serves it over TCP; reads
    outside the local block fetch from the owning host (the DCN analog of
    the reference's MPI one-sided DDStore window, distdataset.py:26-183,
    with ``ddstore_width`` replaced by the block partition).

    ``hosts`` lists every host's fetch endpoint in rank order, e.g.
    ``[("10.0.0.1", 7311), ("10.0.0.2", 7311)]``; ``my_rank`` picks which
    block this process owns and must populate (``shard`` — the samples whose
    global ids are ``block_start(my_rank) + i``).
    """

    def __init__(
        self,
        shard: Sequence[Graph],
        total_len: int,
        hosts: Sequence,
        my_rank: int,
        name: str = "hydragnn_mhdds",
        capacity_bytes: int = 1 << 28,
        overwrite: bool = False,
    ):
        n_hosts = len(hosts)
        block = (total_len + n_hosts - 1) // n_hosts
        # clamp both ends: with a ceil block, trailing ranks can own an
        # empty range (e.g. 9 samples on 8 hosts leaves rank 5+ nothing)
        lo = min(my_rank * block, total_len)
        hi = min(lo + block, total_len)
        if len(shard) != hi - lo:
            raise ValueError(
                f"rank {my_rank} owns global ids [{lo}, {hi}) = {hi - lo} "
                f"samples, got a shard of {len(shard)}"
            )
        self._total = total_len
        self._block = block
        self._lo = lo
        self._hosts = list(hosts)
        self._rank = my_rank
        self.store = DDStore(
            name,
            capacity_bytes=capacity_bytes,
            max_items=max(len(shard), 1),
            create=True,
            overwrite=overwrite,
        )
        for i, g in enumerate(shard):
            self.store.put(i, _pack_graph(g))
        self.store.serve(int(self._hosts[my_rank][1]), id_offset=lo)
        self._clients = {}

    def _client(self, owner: int) -> RemoteStoreClient:
        c = self._clients.get(owner)
        if c is None:
            host, port = self._hosts[owner]
            c = RemoteStoreClient(host, int(port))
            self._clients[owner] = c
        return c

    def get(self, idx: int) -> Graph:
        if idx < 0:
            idx += self._total
        if not 0 <= idx < self._total:
            raise IndexError(idx)
        owner = idx // self._block
        if owner == self._rank:
            return _unpack_graph(
                self.store.get(idx - self._lo), idx,
                f"shared-memory store {self.store.name!r}",
            )
        where = "host {}:{}".format(*self._hosts[owner])
        try:
            return _unpack_graph(self._client(owner).get(idx), idx, where)
        except ConnectionError:
            # the client already retried with backoff internally; evict the
            # dead connection and rebuild once more — a transient reset
            # (peer restart, network blip) must not poison the cache forever
            c = self._clients.pop(owner, None)
            if c is not None:
                c.close()
            return _unpack_graph(self._client(owner).get(idx), idx, where)

    def __len__(self) -> int:
        return self._total

    def epoch_begin(self) -> None:
        self.store.epoch_begin()

    def epoch_end(self) -> None:
        self.store.epoch_end()

    def close(self, unlink: Optional[bool] = None) -> None:
        for c in self._clients.values():
            c.close()
        self._clients = {}
        self.store.close(unlink)
