"""Per-element reference-energy regression.

Total DFT energies are dominated by per-atom offsets that differ by
element and by dataset; subtracting a least-squares fit of
``E_total ~ sum_z n_z * e_z`` (atom counts times per-element reference
energies) leaves the chemically meaningful interaction energy, which is
orders of magnitude better conditioned as a regression target. The
reference runs exactly this as a preprocessing step for GFM training
(examples/multidataset/energy_linear_regression.py and
energy_per_atom_linear_regression.py); here it is a library utility used
by the multidataset flow and available to every example.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .graph import Graph


def _energy_of(g: Graph) -> Tuple[float, str]:
    """(energy value, field it came from) — the ONE extraction rule shared
    by fit and subtract so both support exactly the same Graph shapes."""
    if g.graph_targets and "energy" in g.graph_targets:
        return float(g.graph_targets["energy"][0]), "graph_targets"
    if g.graph_y is not None and len(np.asarray(g.graph_y)):
        return float(np.asarray(g.graph_y)[0]), "graph_y"
    raise ValueError(
        "graph has no energy target: expected graph_targets['energy'] or a "
        "non-empty graph_y"
    )


def _composition_matrix(graphs: Sequence[Graph], species: np.ndarray):
    a = np.zeros((len(graphs), species.shape[0]), np.float64)
    index = {int(z): i for i, z in enumerate(species)}
    for row, g in enumerate(graphs):
        zs, counts = np.unique(np.asarray(g.z), return_counts=True)
        for z, c in zip(zs, counts):
            a[row, index[int(z)]] = c
    return a


def _fit_one(graphs, energies, per_atom) -> Dict[int, float]:
    if energies is None:
        energies = np.asarray([_energy_of(g)[0] for g in graphs], np.float64)
    else:
        energies = np.asarray(energies, np.float64)
    if per_atom:
        energies = energies * np.asarray([g.num_nodes for g in graphs])
    species = np.unique(np.concatenate([np.asarray(g.z) for g in graphs]))
    a = _composition_matrix(graphs, species)
    coef, *_ = np.linalg.lstsq(a, energies, rcond=None)
    return {int(z): float(e) for z, e in zip(species, coef)}


def fit_reference_energies(
    graphs: Sequence[Graph],
    energies: Optional[np.ndarray] = None,
    per_atom: bool = False,
    by_dataset: bool = False,
):
    """Least-squares per-element reference energies ``{Z: e_Z}``.

    ``energies`` defaults to each graph's energy target (the same
    extraction rule ``subtract_reference_energies`` uses). ``per_atom=True``
    treats the energies as per-atom values (multiplied back to totals
    before fitting — the energy_per_atom_linear_regression variant).

    ``by_dataset=True`` fits ONE TABLE PER ``dataset_id`` and returns
    ``{dataset_id: {Z: e_Z}}``: reference offsets differ between datasets
    computed with different DFT settings, so a shared element across
    families has no single e_Z (the reference fits per dataset for the
    same reason, examples/multidataset/energy_linear_regression.py).
    Fit on the TRAIN split only to keep held-out metrics honest.
    """
    if not graphs:
        return {}
    if not by_dataset:
        return _fit_one(graphs, energies, per_atom)
    if energies is not None:
        raise ValueError("by_dataset=True derives energies from the graphs")
    tables: Dict[int, Dict[int, float]] = {}
    ids = sorted({g.dataset_id for g in graphs})
    for ds_id in ids:
        group = [g for g in graphs if g.dataset_id == ds_id]
        tables[ds_id] = _fit_one(group, None, per_atom)
    return tables


def subtract_reference_energies(
    graphs: Sequence[Graph],
    table,
    per_atom: bool = False,
) -> List[Graph]:
    """Replace each graph's energy target with the residual after removing
    ``sum_z n_z e_z`` (elements missing from the table contribute 0).

    ``table`` is either a flat ``{Z: e_Z}`` or the ``by_dataset`` form
    ``{dataset_id: {Z: e_Z}}`` (a graph whose dataset_id has no table is
    passed through unchanged). The residual is written back to the field
    the energy came from; ``per_atom=True`` divides the offset by the atom
    count, matching per-atom targets."""
    nested = bool(table) and isinstance(next(iter(table.values())), dict)
    out = []
    for g in graphs:
        t = table.get(g.dataset_id) if nested else table
        if not t:
            out.append(g)
            continue
        e, field = _energy_of(g)
        zs, counts = np.unique(np.asarray(g.z), return_counts=True)
        offset = float(
            sum(t.get(int(z), 0.0) * int(c) for z, c in zip(zs, counts))
        )
        resid = e - (offset / g.num_nodes if per_atom else offset)
        if field == "graph_targets":
            tgt = dict(g.graph_targets)
            tgt["energy"] = np.asarray([resid], np.float32)
            out.append(dataclasses.replace(g, graph_targets=tgt))
        else:
            gy = np.asarray(g.graph_y, np.float32).copy()
            gy[0] = resid
            out.append(dataclasses.replace(g, graph_y=gy))
    return out
