"""Sequence(node)-parallel execution: one giant graph sharded over devices.

The reference's GPS attention is dense per-graph on one device
(hydragnn/globalAtt/gps.py:125-141) — a graph must fit a single GPU. This
module removes that bound the TPU way for the long-context regime
(mesoscale supercells, periodic assemblies):

- the batch's node/edge axes are sharded ``P('data')`` over a 1-D mesh;
- GPS global attention (``global_attn_type: "ring"``) computes the exact
  softmax attention with ring-rotated K/V blocks over ICI
  (parallel/ring_attention.py) — per-device memory stays
  O(n_local * n_local) per block instead of O(N^2);
- every other op (convs, segment sums, norms, decoders) is auto-partitioned
  by XLA GSPMD from the input shardings — linear memory, collectives
  inserted by the compiler.

The model is built once; the SP context (set while the jitted step traces)
tells the ring-attention module which mesh axis shards the node dimension.
Without a context the same module falls back to dense masked attention —
bitwise the same math — so one checkpoint serves both execution modes.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..data.graph import GraphBatch

SP_AXIS = "data"

_ctx = threading.local()


def current_sp() -> Tuple[Optional[Mesh], str]:
    """(mesh, axis) of the active SP context, or (None, axis) outside one.
    Read at TRACE time by the ring-attention module."""
    return getattr(_ctx, "mesh", None), getattr(_ctx, "axis", SP_AXIS)


@contextlib.contextmanager
def sp_context(mesh: Mesh, axis: str = SP_AXIS):
    prev = current_sp()
    _ctx.mesh, _ctx.axis = mesh, axis
    try:
        yield
    finally:
        _ctx.mesh, _ctx.axis = prev


def make_sp_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices() if n_devices is None else jax.devices()[:n_devices]
    return Mesh(np.asarray(devs), (SP_AXIS,))


def shard_sp_batch(batch: GraphBatch, mesh: Mesh) -> GraphBatch:
    """Place node/edge-leading arrays sharded P(SP_AXIS); everything whose
    leading dim does not divide the mesh stays replicated. The pad spec must
    make n_nodes and n_edges multiples of the mesh size."""
    sh = NamedSharding(mesh, P(SP_AXIS))
    rep = NamedSharding(mesh, P())
    n = mesh.size

    def place(x):
        if hasattr(x, "ndim") and x.ndim >= 1 and x.shape[0] % n == 0:
            return jax.device_put(x, sh)
        return jax.device_put(x, rep)

    return jax.tree_util.tree_map(place, batch)


def make_sp_train_step(model, tx, mesh: Mesh, compute_grad_energy: bool = False):
    """Jitted node-sharded train step for one spanning graph batch: params
    replicated, batch node/edge axes P('data'); GPS ring attention exact,
    the rest GSPMD-partitioned. Mirrors train.loop.make_train_step."""
    import optax

    from ..train.loss import compute_loss

    cfg = model.cfg

    def loss_fn(params, batch_stats, batch, rng):
        variables = {"params": params, "batch_stats": batch_stats}
        with sp_context(mesh):
            tot, tasks, mutated, _ = compute_loss(
                model, variables, batch, cfg, True, rng, compute_grad_energy
            )
        return tot.astype(jnp.float32), (tasks, mutated)

    from functools import partial

    @partial(jax.jit, donate_argnums=0)
    def step(state, batch, rng):
        (tot, (tasks, mutated)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params, state.batch_stats, batch, rng)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            state.replace(
                params=params,
                opt_state=opt_state,
                batch_stats=mutated.get("batch_stats", state.batch_stats),
                step=state.step + 1,
            ),
            tot,
            tasks,
        )

    return step


def make_sp_eval_step(model, mesh: Mesh, compute_grad_energy: bool = False):
    from ..train.loss import compute_loss

    cfg = model.cfg

    @jax.jit
    def evalf(state, batch):
        variables = state.variables()
        with sp_context(mesh):
            tot, tasks, _, outputs = compute_loss(
                model, variables, batch, cfg, False, None, compute_grad_energy
            )
        return tot, tasks, outputs

    return evalf
