"""Ring self-attention: exact attention over a node axis sharded across a
mesh axis — sequence/context parallelism for graphs too large for one chip.

The reference has no long-context machinery (its GPS attention is dense
per-graph on one device, hydragnn/globalAtt/gps.py:125-141, and molecular
graphs are small). This module goes beyond parity: for *giant* graphs —
periodic supercells, mesoscale assemblies — whose node set must be sharded
over devices, global attention still needs every (query, key) pair. Ring
attention computes it exactly:

- every device holds its local query/key/value block ([n_local, ...]);
- K/V blocks rotate around the mesh axis via ``ppermute`` (ICI
  neighbor-to-neighbor traffic, no all-gather memory spike);
- softmax is accumulated *online* (flash-attention style running max /
  denominator), so the full [N, N] score matrix never materializes.

After ``n_devices`` rotations each query block has attended to every key
block; results are exact (up to float reassociation) vs dense softmax
attention — asserted by tests/test_ring_attention.py on the virtual
8-device CPU mesh.

Use inside ``shard_map`` over the mesh axis that shards nodes, e.g.::

    out = shard_map(
        lambda q, k, v, m: ring_self_attention(q, k, v, m, axis_name="data"),
        mesh=mesh,
        in_specs=(P("data"), P("data"), P("data"), P("data")),
        out_specs=P("data"),
    )(q, k, v, mask)
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _block_attend(q, k, v, kmask, m, denom, acc, scale, use_flash=False):
    """One online-softmax accumulation step against a K/V block.

    q: [n_q, H, dh]; k/v: [n_k, H, dh]; kmask: [n_k] bool;
    m/denom: [n_q, H]; acc: [n_q, H, dh].

    ``use_flash``: compute the block's (max, denom, acc) partial with the
    segment-masked flash kernel's inner loop
    (ops/pallas_flash_attention.py ``flash_block_summary`` — the local
    score block stays in VMEM) and merge it here in plain jnp; the dense
    einsum below is the identical math and the off-TPU route.
    """
    if use_flash:
        from ..ops.pallas_flash_attention import flash_block_summary
        from ..tune.runtime import tile_plan

        # ring blocks are their own ladder slot: same kernel inner loop,
        # different shape regime (local queries vs one rotating K/V block),
        # so the key carries a role marker and never collides with the
        # GPS batch slots (tune/runtime.py)
        plan = tile_plan("flash_attention", {
            "nodes": q.shape[0], "heads": q.shape[1],
            "head_dim": q.shape[2], "max_nodes_per_graph": 0,
            "role": "block_summary",
        }, q.dtype)
        m_b, l_b, acc_b = flash_block_summary(
            q, k, v, kmask, block_q=plan["block_q"],
            block_k=plan["block_k"],
            interpret=jax.default_backend() != "tpu",
        )
        new_m = jnp.maximum(m, m_b)
        corr = jnp.exp(m - new_m)
        corr_b = jnp.exp(m_b - new_m)
        denom = denom * corr + l_b * corr_b
        acc = acc * corr[..., None] + acc_b * corr_b[..., None]
        return new_m.astype(m.dtype), denom, acc
    # [n_q, H, n_k]
    logits = jnp.einsum("qhd,khd->qhk", q, k) * scale
    neg = jnp.finfo(logits.dtype).min
    logits = jnp.where(kmask[None, None, :], logits, neg)
    blk_max = jnp.max(logits, axis=-1)  # [n_q, H]
    new_m = jnp.maximum(m, blk_max)
    # correction of previously accumulated terms; exp(neg - new_m) underflows
    # to 0 for fully-masked blocks, keeping denom/acc unchanged
    corr = jnp.exp(m - new_m)
    p = jnp.exp(logits - new_m[..., None])  # [n_q, H, n_k]
    p = jnp.where(kmask[None, None, :], p, 0.0)
    denom = denom * corr + jnp.sum(p, axis=-1)
    acc = acc * corr[..., None] + jnp.einsum("qhk,khd->qhd", p, v)
    return new_m, denom, acc


def ring_self_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    key_mask: Optional[jnp.ndarray],
    axis_name: str,
    use_flash: bool = False,
) -> jnp.ndarray:
    """Exact multi-head self-attention with the key/value blocks ring-rotated
    around ``axis_name``. Must run inside ``shard_map``/``pmap`` over that
    axis.

    Shapes (per device): q/k/v ``[n_local, H, dh]``; ``key_mask``
    ``[n_local]`` bool marking real (non-padding) keys, or None.
    Returns ``[n_local, H, dh]`` — each local query attended over the
    GLOBAL key set. ``use_flash`` routes each per-chip block-attend through
    the Pallas flash inner loop when the route is enabled
    (ops/pallas_flash_attention.py ``_flash_route_enabled``); the math is
    identical, the local score block just never leaves VMEM.
    """
    from ..ops.pallas_flash_attention import _flash_route_enabled

    use_flash = use_flash and _flash_route_enabled()
    n_dev = jax.lax.psum(1, axis_name)
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    if key_mask is None:
        key_mask = jnp.ones(k.shape[:1], bool)

    # initial carries derived from q so shard_map types them as varying
    # along the mesh axis (a bare constant would be axis-invariant and
    # mismatch the scan carry after the first ppermute step)
    m = jnp.full_like(q[..., 0], jnp.finfo(q.dtype).min)  # [n_q, H]
    denom = jnp.zeros_like(q[..., 0])
    acc = jnp.zeros_like(q)

    # neighbor ring: device i receives from i+1 (send left) every step, so
    # after s steps it holds block (i + s) mod n_dev
    perm = [(s, (s - 1) % n_dev) for s in range(n_dev)]

    def step(carry, _):
        k_blk, v_blk, kmask, m, denom, acc = carry
        m, denom, acc = _block_attend(
            q, k_blk, v_blk, kmask, m, denom, acc, scale, use_flash
        )
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        kmask = jax.lax.ppermute(kmask, axis_name, perm)
        return (k_blk, v_blk, kmask, m, denom, acc), None

    # n_dev - 1 attend+rotate steps, then the final block without the
    # rotation: the last ppermute would only complete the ring back to the
    # start, a full K+V shard of wasted ICI traffic per call
    if n_dev > 1:
        (k, v, key_mask, m, denom, acc), _ = jax.lax.scan(
            step, (k, v, key_mask, m, denom, acc), None, length=n_dev - 1
        )
    m, denom, acc = _block_attend(
        q, k, v, key_mask, m, denom, acc, scale, use_flash
    )
    return acc / jnp.maximum(denom, 1e-30)[..., None]


def sharded_global_attention(mesh, axis_name: str = "data",
                             use_flash: bool = False):
    """A jitted callable computing exact global self-attention over arrays
    whose leading (node) axis is sharded on ``axis_name`` of ``mesh``:
    (q, k, v, key_mask) -> out, all ``[N_global, H, dh]`` sharded the same
    way. The convenience wrapper around ``ring_self_attention`` for the
    giant-graph regime (docs/MULTIHOST.md)."""
    from .mesh import compat_shard_map as shard_map
    from jax.sharding import PartitionSpec as P

    fn = shard_map(
        lambda q, k, v, mask: ring_self_attention(
            q, k, v, mask, axis_name, use_flash=use_flash
        ),
        mesh=mesh,
        in_specs=(P(axis_name), P(axis_name), P(axis_name), P(axis_name)),
        out_specs=P(axis_name),
        # pallas_call has no replication rule (same reason the GPS module's
        # shard_map disables the check, models/gps.py)
        check_vma=False,
    )
    return jax.jit(fn)
