"""Declarative sharding: ordered regex -> PartitionSpec rule tables.

ROADMAP item 1 (the dp/zero/branch unification): instead of three bespoke
step builders each hand-placing state, a *rule table* names the placement
of every state leaf — ordered regexes matched against the '/'-joined
param-tree path, first match wins, unmatched leaves fall back to an
explicit replicated default *with an audit finding* (obs/sharding.py).
The pattern is the GSPMD-style declarative sharding of every modern JAX
LLM trainer (SNIPPETS.md [3], fmengine's ``match_partition_rules``:
``re.search(rule, name)`` over the tree paths, scalars unpartitioned),
extended with the predicates the ZeRO and branch placements need:

- ``min_size`` — ZeRO thresholds as rule predicates (a rule passes over
  leaves smaller than the threshold instead of failing them);
- leading-axis divisibility — a rule whose spec shards the leading dim
  over a mesh axis passes over leaves whose leading dim does not divide
  it (the old ``_zero_leaf_eligible`` semantics, now per-rule);
- ``leading_eq`` — branch decoder banks match only at their exact
  ``[num_branches]`` leading extent (the old ``_path_branch_specs``
  predicate);
- ``scope`` — which state trees the rule covers: ``params`` /
  ``opt_state`` / ``batch_stats`` place between steps, ``grads``
  constrains inside the jitted step (the ZeRO-2 reduce-scatter site).

Axes are LOGICAL ("data" / "model") and resolve to the concrete mesh
axis names at build time, so one table drives both the legacy
``(branch, data)`` mesh (via the deprecation shims in dp.py/branch.py)
and the engine's 2D ``(data, model)`` mesh (parallel/engine.py).

ZeRO-1/2/3 and the reference's ``MultiTaskModelMP`` task-parallel mode
(PAPER.md §0.2) ship as presets; ``Parallel.rules`` in the run config
selects a preset by name or supplies an inline table (docs/PARALLELISM.md).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

# logical axis tokens — resolved to concrete mesh axis names at build time
DATA = "data"
MODEL = "model"
_AXIS_TOKENS = (DATA, MODEL)

# state trees a rule may cover; "grads" is the in-step constraint scope
SCOPES = ("params", "opt_state", "batch_stats", "grads")
# the between-steps placement scopes (place_state walks exactly these)
PLACED_SCOPES = ("params", "opt_state", "batch_stats")

# decoder-bank top-level collection keys (models/base.py setup:
# self.graph_shared / self.heads_NN list / MACE per-layer readouts) — the
# one model-family fact the branch/mp presets encode
DECODER_PATTERN = r"(^|/)(graph_shared|heads_NN|readout)"

# default ZeRO eligibility threshold (parallel/mesh.py historical default)
DEFAULT_MIN_SIZE = 1024


@dataclasses.dataclass(frozen=True)
class Rule:
    """One ordered entry: regex over the '/'-joined tree path -> logical
    PartitionSpec, gated by size/shape predicates. ``axes=()`` is an
    explicit replicated placement (distinct from *unmatched*, which is
    replicated-with-audit)."""

    pattern: str
    axes: Tuple[Optional[str], ...] = ()
    scope: Tuple[str, ...] = ("params",)
    min_size: int = 0
    leading_eq: Optional[int] = None
    reason: str = ""

    def compiled(self) -> "re.Pattern[str]":
        return re.compile(self.pattern)

    def admits(self, leaf: Any, axis_sizes: Dict[str, int]) -> bool:
        """Shape/size predicate (the regex already matched): scalars never
        shard, ``min_size`` thresholds pass over small leaves, and a spec
        sharding the leading dim requires divisibility (exact extent when
        ``leading_eq`` is set)."""
        ndim = getattr(leaf, "ndim", 0)
        if self.axes and not ndim:
            return False
        if self.min_size and getattr(leaf, "size", 0) < self.min_size:
            return False
        if self.leading_eq is not None and (
            not ndim or leaf.shape[0] != self.leading_eq
        ):
            return False
        if self.axes and self.axes[0] is not None:
            n = axis_sizes.get(self.axes[0], 1)
            if not ndim or leaf.shape[0] % max(n, 1) != 0:
                return False
        return True

    def to_config(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "pattern": self.pattern,
            "spec": list(self.axes),
            "scope": list(self.scope),
        }
        if self.min_size:
            out["min_size"] = int(self.min_size)
        if self.leading_eq is not None:
            out["leading_eq"] = int(self.leading_eq)
        if self.reason:
            out["reason"] = self.reason
        return out


@dataclasses.dataclass(frozen=True)
class RuleTable:
    """An ordered rule list plus the mesh/step semantics it requires:
    ``model_size`` is the model-axis extent the mesh must provide (1 =
    pure data parallelism), ``routed`` selects the branch-routed step
    (per-branch data routing + decoder gradients reduced over ``data``
    only — the ``MultiTaskModelMP`` semantics, parallel/engine.py)."""

    name: str
    rules: Tuple[Rule, ...] = ()
    model_size: int = 1
    routed: bool = False

    # -- queries -------------------------------------------------------------

    def rules_for(self, scope: str) -> Tuple[Rule, ...]:
        return tuple(r for r in self.rules if scope in r.scope)

    def shards(self, scope: str) -> bool:
        """Whether any rule can place a non-replicated spec in ``scope``."""
        return any(r.axes for r in self.rules_for(scope))

    def to_config(self) -> Dict[str, Any]:
        """JSON-serializable form, recorded into the run config so
        checkpoint restore replays the identical placement."""
        return {
            "name": self.name,
            "model_size": int(self.model_size),
            "routed": bool(self.routed),
            "rules": [r.to_config() for r in self.rules],
        }


class RuleError(ValueError):
    """An invalid rule table — raised eagerly at resolve time, never from
    inside a traced step."""


# ---------------------------------------------------------------------------
# path rendering + matching
# ---------------------------------------------------------------------------


def path_str(path: Sequence[Any]) -> str:
    """'/'-joined tree path: dict keys, attr names (optax NamedTuple
    states), and sequence indices — ``0/mu/graph_shared0/Dense_0/kernel``.
    The string the rule regexes search (fmengine joins with '/' too)."""
    import jax

    parts: List[str] = []
    for p in path:
        if isinstance(p, jax.tree_util.DictKey):
            parts.append(str(p.key))
        elif isinstance(p, jax.tree_util.GetAttrKey):
            parts.append(str(p.name))
        elif isinstance(p, jax.tree_util.SequenceKey):
            parts.append(str(p.idx))
        elif isinstance(p, jax.tree_util.FlattenedIndexKey):
            parts.append(str(p.key))
        else:  # future key types: their repr is still matchable
            parts.append(str(p))
    return "/".join(parts)


def match_rule(
    table: RuleTable,
    path: str,
    leaf: Any,
    scope: str,
    axis_sizes: Dict[str, int],
) -> Tuple[Optional[Rule], Tuple[Optional[str], ...]]:
    """First-match-wins lookup: ``(rule, logical_axes)``. Scalars are
    unpartitioned without consulting the table (they match implicitly —
    no audit). ``(None, ())`` means *unmatched*: the caller places the
    leaf replicated and must surface the audit finding."""
    if not getattr(leaf, "ndim", 0):
        return None, ()  # scalar: implicit replicated, audited by nobody
    for rule in table.rules_for(scope):
        if rule.compiled().search(path) and rule.admits(leaf, axis_sizes):
            return rule, rule.axes
    return None, ()


def spec_tree(tree: Any, table: RuleTable, scope: str, axis_map, axis_sizes):
    """Per-leaf concrete PartitionSpec pytree for ``tree`` (shard_map
    in/out specs and device placement share this one resolver) plus the
    audit list of unmatched non-scalar leaf paths."""
    import jax
    from jax.sharding import PartitionSpec as P

    unmatched: List[str] = []

    def spec_of(path, leaf):
        p = path_str(path)
        rule, axes = match_rule(table, p, leaf, scope, axis_sizes)
        if rule is None and getattr(leaf, "ndim", 0):
            unmatched.append(f"{scope}/{p}")
        return P(*[axis_map[a] if a is not None else None for a in axes])

    specs = jax.tree_util.tree_map_with_path(spec_of, tree)
    return specs, unmatched


def resolve_axes(mesh) -> Dict[str, str]:
    """Logical axis token -> concrete mesh axis name. Accepts both the
    legacy ``(branch, data)`` mesh (shims, existing tests) and the
    engine's ``(data, model)`` mesh; a missing model axis maps onto the
    data axis's complement only when one exists."""
    names = list(mesh.axis_names)
    out: Dict[str, str] = {}
    if DATA in names:
        out[DATA] = DATA
    else:
        raise RuleError(
            f"mesh axes {tuple(names)} carry no 'data' axis — the engine "
            "needs one (parallel/mesh.py make_mesh2d)"
        )
    model = next((n for n in (MODEL, "branch") if n in names), None)
    if model is not None:
        out[MODEL] = model
    return out


# ---------------------------------------------------------------------------
# validation (eager — api.py runs this before any jit is touched)
# ---------------------------------------------------------------------------


def validate_table(table: RuleTable) -> RuleTable:
    """Raise ``RuleError`` on the first structural problem: a bad regex,
    an unknown axis token or scope, an impossible predicate. Returns the
    table so callers can chain."""
    if not isinstance(table.name, str) or not table.name:
        raise RuleError("rule table needs a non-empty name")
    if int(table.model_size) < 1:
        raise RuleError(
            f"rule table {table.name!r}: model_size {table.model_size} < 1"
        )
    for i, rule in enumerate(table.rules):
        where = f"rule table {table.name!r} rule[{i}] ({rule.pattern!r})"
        try:
            re.compile(rule.pattern)
        except re.error as e:
            raise RuleError(f"{where}: bad regex: {e}") from None
        for a in rule.axes:
            if a is not None and a not in _AXIS_TOKENS:
                raise RuleError(
                    f"{where}: unknown axis {a!r} (use "
                    f"{'/'.join(_AXIS_TOKENS)} or null)"
                )
        if not rule.scope:
            raise RuleError(f"{where}: empty scope")
        for s in rule.scope:
            if s not in SCOPES:
                raise RuleError(
                    f"{where}: unknown scope {s!r} (use {'/'.join(SCOPES)})"
                )
        if rule.min_size < 0:
            raise RuleError(f"{where}: min_size {rule.min_size} < 0")
        if rule.leading_eq is not None and rule.leading_eq < 1:
            raise RuleError(f"{where}: leading_eq {rule.leading_eq} < 1")
        if "grads" in rule.scope and any(a == MODEL for a in rule.axes):
            raise RuleError(
                f"{where}: 'grads' scope cannot shard over the model axis "
                "(decoder gradients stay model-sharded by propagation; "
                "the grads scope is the ZeRO-2 data-axis constraint site)"
            )
    if table.routed and table.model_size < 2:
        raise RuleError(
            f"rule table {table.name!r}: routed (branch/mp) tables need "
            f"model_size >= 2 (have {table.model_size})"
        )
    if table.routed and not any(
        any(a == MODEL for a in r.axes) for r in table.rules
    ):
        raise RuleError(
            f"rule table {table.name!r}: routed tables must shard at "
            "least one rule over the model axis (the decoder banks)"
        )
    return table


# ---------------------------------------------------------------------------
# shipped presets
# ---------------------------------------------------------------------------

# explicit replicated default — the last rule of every preset, so a preset
# never produces *unmatched* leaves (the audit is for hand-written tables
# that forgot coverage, not for the shipped placements)
def _replicated_default() -> Rule:
    return Rule(
        pattern=r".*",
        axes=(),
        scope=PLACED_SCOPES,
        reason="explicit replicated default",
    )


def _zero_rules(stage: int, min_size: int) -> Tuple[Rule, ...]:
    out: List[Rule] = [
        Rule(
            pattern=r".*",
            axes=(DATA,),
            scope=("opt_state",),
            min_size=min_size,
            reason="ZeRO-1: optimizer moments sharded over data",
        )
    ]
    if stage >= 2:
        out.append(
            Rule(
                pattern=r".*",
                axes=(DATA,),
                scope=("grads",),
                min_size=min_size,
                reason="ZeRO-2: gradient reduce-scatter over data",
            )
        )
    if stage >= 3:
        out.append(
            Rule(
                pattern=r".*",
                axes=(DATA,),
                scope=("params",),
                min_size=min_size,
                reason="ZeRO-3: params stored sharded between steps",
            )
        )
    out.append(_replicated_default())
    return tuple(out)


def _branch_rules(num_branches: int) -> Tuple[Rule, ...]:
    return (
        Rule(
            pattern=DECODER_PATTERN,
            axes=(MODEL,),
            scope=PLACED_SCOPES,
            leading_eq=num_branches,
            reason=(
                "decoder banks [num_branches, ...] sharded over the model "
                "axis (MultiTaskModelMP task parallelism)"
            ),
        ),
        _replicated_default(),
    )


PRESET_NAMES = ("dp", "zero1", "zero2", "zero3", "branch", "mp")


def preset(
    name: str,
    min_size: int = DEFAULT_MIN_SIZE,
    num_branches: Optional[int] = None,
) -> RuleTable:
    """Build a shipped preset table. ``branch`` and ``mp`` are the same
    placement (``mp`` is the reference-facing name for the
    ``MultiTaskModelMP`` encoder-replicated / decoder-model-sharded
    mode); both need ``num_branches``."""
    if name == "dp":
        return validate_table(RuleTable("dp", (_replicated_default(),)))
    if name in ("zero1", "zero2", "zero3"):
        stage = int(name[-1])
        return validate_table(
            RuleTable(name, _zero_rules(stage, int(min_size)))
        )
    if name in ("branch", "mp"):
        if not num_branches or num_branches < 2:
            raise RuleError(
                f"preset {name!r} needs num_branches >= 2 "
                f"(have {num_branches}) — a single-branch model has no "
                "decoder bank to shard"
            )
        return validate_table(
            RuleTable(
                name,
                _branch_rules(int(num_branches)),
                model_size=int(num_branches),
                routed=True,
            )
        )
    raise RuleError(
        f"unknown Parallel.rules preset {name!r}; shipped presets: "
        f"{', '.join(PRESET_NAMES)} (or an inline rule list — "
        "docs/PARALLELISM.md)"
    )


# ---------------------------------------------------------------------------
# config surface (Parallel section; api.py resolves this eagerly)
# ---------------------------------------------------------------------------


def table_from_config(spec: Any, section: Dict[str, Any]) -> RuleTable:
    """Inline-table parse: ``Parallel.rules`` as a list of rule dicts
    (``{pattern, spec, scope, min_size, leading_eq}``), with
    ``Parallel.model_size`` / ``Parallel.routed`` alongside."""
    if not isinstance(spec, (list, tuple)):
        raise RuleError(
            f"Parallel.rules must be a preset name or a rule list, got "
            f"{type(spec).__name__}"
        )
    rules: List[Rule] = []
    for i, entry in enumerate(spec):
        if not isinstance(entry, dict):
            raise RuleError(
                f"Parallel.rules[{i}] must be an object, got "
                f"{type(entry).__name__}"
            )
        unknown = set(entry) - {
            "pattern", "spec", "scope", "min_size", "leading_eq", "reason",
        }
        if unknown:
            raise RuleError(
                f"Parallel.rules[{i}]: unknown keys {sorted(unknown)}"
            )
        if "pattern" not in entry:
            raise RuleError(f"Parallel.rules[{i}]: missing 'pattern'")
        axes = entry.get("spec", [])
        if isinstance(axes, str):
            axes = [axes]
        scope = entry.get("scope", ["params"])
        if isinstance(scope, str):
            scope = [scope]
        rules.append(
            Rule(
                pattern=str(entry["pattern"]),
                axes=tuple(a if a is not None else None for a in axes),
                scope=tuple(str(s) for s in scope),
                min_size=int(entry.get("min_size", 0)),
                leading_eq=(
                    int(entry["leading_eq"])
                    if entry.get("leading_eq") is not None
                    else None
                ),
                reason=str(entry.get("reason", "")),
            )
        )
    return validate_table(
        RuleTable(
            name=str(section.get("name", "inline")),
            rules=tuple(rules),
            model_size=int(section.get("model_size", 1)),
            routed=bool(section.get("routed", False)),
        )
    )


def resolve(config: Dict[str, Any]) -> RuleTable:
    """The one resolution path (api.py): an explicit ``Parallel.rules``
    (preset name or inline list) wins; otherwise the table is derived
    from the legacy ``Training`` keys (``Optimizer.zero_stage`` /
    ``use_zero_redundancy`` / ``branch_parallel``) so every existing
    config keeps its exact placement. Conflicts between an explicit
    table and contradicting legacy keys raise eagerly."""
    training = config.get("NeuralNetwork", {}).get("Training", {})
    section = config.get("Parallel") or {}
    min_size = int(section.get("min_size", DEFAULT_MIN_SIZE))
    num_branches = _num_branches_of(config)
    opt = training.get("Optimizer", {})
    zero_stage = int(
        opt.get("zero_stage", 1 if opt.get("use_zero_redundancy") else 0)
    )
    branch_parallel = bool(training.get("branch_parallel", False))
    spec = section.get("rules")
    if spec is None:
        if branch_parallel and zero_stage >= 2:
            raise RuleError(
                "Optimizer.zero_stage >= 2 is not supported together with "
                "Training.branch_parallel (the branch table shards decoder "
                "banks, not gradients/moments); drop one of the two, or "
                "write an explicit Parallel.rules table"
            )
        if branch_parallel:
            return preset("branch", num_branches=num_branches)
        if zero_stage >= 1:
            return preset(f"zero{min(zero_stage, 3)}", min_size=min_size)
        return preset("dp")
    if isinstance(spec, str):
        table = preset(spec, min_size=min_size, num_branches=num_branches)
    else:
        table = table_from_config(spec, section)
    # explicit table + contradicting legacy keys: refuse, don't guess
    if branch_parallel and not table.routed:
        raise RuleError(
            f"Parallel.rules={table.name!r} is not a routed (branch/mp) "
            "table but Training.branch_parallel is set; drop "
            "branch_parallel or pick the 'branch'/'mp' preset"
        )
    if zero_stage >= 2 and not table.shards("grads"):
        raise RuleError(
            f"Parallel.rules={table.name!r} has no 'grads'-scope rule but "
            f"Optimizer.zero_stage={zero_stage} asks for gradient "
            "sharding; align the two (the zero2/zero3 presets carry it)"
        )
    return table


def table_from_recorded(recorded: Dict[str, Any]) -> RuleTable:
    """Rebuild a table from the ``Parallel.resolved_rules`` block a run
    config recorded (checkpoint restore replays the identical placement)."""
    return table_from_config(
        recorded.get("rules", []),
        {
            "name": recorded.get("name", "recorded"),
            "model_size": recorded.get("model_size", 1),
            "routed": recorded.get("routed", False),
        },
    )


def _num_branches_of(config: Dict[str, Any]) -> Optional[int]:
    arch = config.get("NeuralNetwork", {}).get("Architecture", {})
    try:
        from ..models.create import num_branches_from

        return int(num_branches_from(arch))
    except Exception:
        heads = arch.get("output_heads")
        return len(heads) if isinstance(heads, dict) else None
