"""DEPRECATION SHIM — branch-parallel decoders live in the rule engine.

The bespoke ``MultiTaskModelMP``-style step builder this module used to
hold was retired into ``parallel/engine.py`` (ROADMAP item 1): decoder
banks shard over the model axis via the ``branch``/``mp`` rule preset
(``parallel/rules.py``, ``DECODER_PATTERN`` + ``leading_eq=num_branches``),
and the routed data path moved to ``parallel/routing.py``. Bit-identical
train loss against the retired builder is asserted in
tests/test_sharding_rules.py. These wrappers keep the historical call
signatures; new code uses ``engine.make_mesh_train_step(Objective(...),
rules.preset("branch", num_branches=B), mesh)``.
"""

from __future__ import annotations

import warnings

import jax
from jax.sharding import Mesh, PartitionSpec as P

from ..models.base import HydraModel
from ..train.state import TrainState
from . import rules as R
from .engine import Objective, make_mesh_eval_step, make_mesh_train_step
from .engine import place_state as _engine_place_state
from .mesh import BRANCH_AXIS
from .routing import BranchRoutedLoader  # noqa: F401  (re-export)

# top-level variable-collection keys holding branch-banked decoder leaves
# (models/base.py setup) — kept for callers; the engine derives the same
# set from the rule table's DECODER_PATTERN
_DECODER_PREFIXES = ("graph_shared", "heads_NN", "readout")


def _warn(name: str) -> None:
    warnings.warn(
        f"parallel.branch.{name} is a deprecation shim over "
        "parallel.engine; build steps via engine.make_mesh_train_step("
        "Objective(...), rules.preset('branch', num_branches=B), mesh) "
        "(docs/PARALLELISM.md)",
        DeprecationWarning,
        stacklevel=3,
    )


def _is_decoder_key(top_key: str) -> bool:
    return any(top_key.startswith(p) for p in _DECODER_PREFIXES)


def branch_specs(tree, branched=P(BRANCH_AXIS), replicated=P()):
    """PartitionSpec pytree for a params/batch_stats collection: decoder-
    bank subtrees get ``branched``, everything else ``replicated``.
    (Engine-internal spec building goes through the rule table now; this
    stays for external callers.)"""
    if not isinstance(tree, dict):
        return jax.tree_util.tree_map(lambda _: replicated, tree)
    return {
        k: jax.tree_util.tree_map(
            lambda _: branched if _is_decoder_key(k) else replicated, v
        )
        for k, v in tree.items()
    }


def _bank_size(params) -> int:
    """num_branches, read off a decoder-bank leaf's leading axis."""
    for k, sub in params.items():
        if _is_decoder_key(k):
            return int(jax.tree_util.tree_leaves(sub)[0].shape[0])
    raise ValueError(
        f"no decoder bank ({'/'.join(_DECODER_PREFIXES)}) in params"
    )


def place_branch_state(state: TrainState, tx, mesh: Mesh) -> TrainState:
    """Legacy signature -> engine placement: decoder param/stat leaves
    (and the matching optimizer-moment leaves — preserved, NOT
    re-initialized, so ``Training.continue`` resumes with its restored
    Adam moments) sharded over the model/branch axis; everything else
    replicated."""
    _warn("place_branch_state")
    del tx  # kept for API stability; moments are placed, not re-created
    table = R.preset("branch", num_branches=_bank_size(state.params))
    return _engine_place_state(state, table, mesh)


def make_branch_parallel_train_step(
    model: HydraModel,
    tx,
    mesh: Mesh,
    compute_grad_energy: bool = False,
    mixed_precision: bool = False,
    guard=None,
    numerics=None,
):
    """Legacy signature -> engine: DP over ``data`` x decoder-sharded
    model axis; the stacked batch must be branch-routed
    (``routing.BranchRoutedLoader``)."""
    _warn("make_branch_parallel_train_step")
    return make_mesh_train_step(
        Objective(
            model=model,
            tx=tx,
            compute_grad_energy=compute_grad_energy,
            mixed_precision=mixed_precision,
            guard=guard,
            numerics=numerics,
        ),
        R.preset("branch", num_branches=model.cfg.num_branches),
        mesh,
    )


def make_branch_parallel_eval_step(
    model: HydraModel,
    mesh: Mesh,
    compute_grad_energy: bool = False,
    mixed_precision: bool = False,
):
    _warn("make_branch_parallel_eval_step")
    return make_mesh_eval_step(
        Objective(
            model=model,
            compute_grad_energy=compute_grad_energy,
            mixed_precision=mixed_precision,
        ),
        R.preset("branch", num_branches=model.cfg.num_branches),
        mesh,
    )
