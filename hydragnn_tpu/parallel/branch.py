"""Branch-parallel decoders: shard decoder params/compute over the mesh's
``branch`` axis.

The reference's ``MultiTaskModelMP`` deletes the branches a rank does not own
and DDPs each decoder over its branch's process subgroup
(hydragnn/models/MultiTaskModelMP.py:203-230): decoder memory and FLOPs per
device stay constant as branches grow, while the shared encoder synchronizes
globally. The TPU-native equivalent built here:

- ``HydraModel`` decoders are *branch banks* (models/base.py `_branch_bank`):
  every decoder parameter (and running-stat) leaf carries a leading
  ``[num_branches]`` axis;
- those leaves are sharded ``P('branch')`` over the mesh, so a device stores
  only ``num_branches / branch_axis_size`` branch slices;
- inside the ``shard_map`` step each device applies a *local* model built for
  its ``B_local`` branch slice on data routed to its branch block
  (``BranchRoutedLoader``), so decoder FLOPs per device are independent of
  the total branch count;
- encoder gradients ``pmean`` over the whole mesh (DDP analog), decoder
  gradients ``pmean`` over the ``data`` axis only (the reference's per-branch
  DDP subgroup) — each branch's decoder trains on the mean loss of *its*
  dataset, exactly the reference's semantics (which differ from the dense
  masked decode by a per-branch normalization factor).

Both ``HydraModel`` heads and ``MACEModel`` per-layer readouts are
branch-banked, so every conv type — MACE included — runs branch-parallel.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from .mesh import compat_shard_map as shard_map

from ..models.base import HydraModel
from ..train.loss import compute_loss
from ..train.state import TrainState
from .mesh import BRANCH_AXIS, DATA_AXIS

_BOTH = (BRANCH_AXIS, DATA_AXIS)

# top-level variable-collection keys holding branch-banked decoder leaves
# (models/base.py setup: self.graph_shared, self.heads_NN list)
_DECODER_PREFIXES = ("graph_shared", "heads_NN", "readout")


def _is_decoder_key(top_key: str) -> bool:
    return any(top_key.startswith(p) for p in _DECODER_PREFIXES)


def branch_specs(tree, branched=P(BRANCH_AXIS), replicated=P()):
    """PartitionSpec pytree for a params/batch_stats collection: decoder-bank
    subtrees get ``branched`` (leading [B] axis over the branch mesh axis),
    everything else ``replicated``."""
    if not isinstance(tree, dict):
        return jax.tree_util.tree_map(lambda _: replicated, tree)
    return {
        k: jax.tree_util.tree_map(
            lambda _: branched if _is_decoder_key(k) else replicated, v
        )
        for k, v in tree.items()
    }


def _path_branch_specs(tree, num_branches: int):
    """Per-leaf PartitionSpec for an ARBITRARY pytree (optimizer state
    included): a leaf whose path passes through a decoder-bank dict key and
    whose leading dim equals ``num_branches`` gets P('branch'). Optax moment
    trees mirror the param structure, so the decoder param paths appear as
    sub-paths inside e.g. ScaleByAdamState.mu."""

    def spec_of(path, leaf):
        on_decoder = any(
            isinstance(p, jax.tree_util.DictKey) and _is_decoder_key(str(p.key))
            for p in path
        )
        if (
            on_decoder
            and getattr(leaf, "ndim", 0) >= 1
            and leaf.shape[0] == num_branches
        ):
            return P(BRANCH_AXIS)
        return P()

    return jax.tree_util.tree_map_with_path(spec_of, tree)


def place_branch_state(state: TrainState, tx, mesh: Mesh) -> TrainState:
    """Place a TrainState for branch-parallel training: decoder param/stat
    leaves (and the matching optimizer-moment leaves — preserved, NOT
    re-initialized, so ``Training.continue`` resumes with its restored Adam
    moments) sharded over ``branch``; everything else replicated."""
    del tx  # kept for API stability; moments are placed, not re-created
    num_branches = _bank_size(state.params)

    def put(tree):
        specs = _path_branch_specs(tree, num_branches)
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs
        )

    return state.replace(
        params=put(state.params),
        batch_stats=put(state.batch_stats),
        opt_state=put(state.opt_state),
    )


def _bank_size(params) -> int:
    """num_branches, read off a decoder-bank leaf's leading axis."""
    for k, sub in params.items():
        if _is_decoder_key(k):
            return int(jax.tree_util.tree_leaves(sub)[0].shape[0])
    raise ValueError(
        f"no decoder bank ({'/'.join(_DECODER_PREFIXES)}) in params"
    )


def _local_model(model, b_local: int):
    """Rebuild the model for a local branch slice. Works for any model whose
    decoders are branch BANKS (HydraModel heads, MACEModel readouts) —
    identical module tree, bank leaves sliced by the shard_map specs.
    Branch-loss balancing is stripped from the LOCAL cfg: the global weight
    vector does not slice with the remapped local dataset ids, so the mesh
    step applies balancing to the decoder gradient scales instead (the
    per-branch effective-LR equivalent; see make_branch_parallel_train_step)."""
    cfg = dataclasses.replace(
        model.cfg, num_branches=b_local,
        branch_loss_weights=None, branch_loss_metrics=False,
    )
    return type(model)(cfg=cfg)


def make_branch_parallel_train_step(
    model: HydraModel,
    tx,
    mesh: Mesh,
    compute_grad_energy: bool = False,
    mixed_precision: bool = False,
    guard=None,
    numerics=None,
):
    """Jitted (state, stacked_batch, rng) -> (state, loss, tasks): DP over
    ``data`` x decoder-sharded ``branch``. The stacked batch must be
    branch-routed (``BranchRoutedLoader``): shard row r carries graphs of
    branch ``r // data_axis_size`` only."""
    cfg = model.cfg
    bsize = mesh.shape[BRANCH_AXIS]
    assert cfg.num_branches % bsize == 0, (
        f"num_branches {cfg.num_branches} not divisible by branch axis {bsize}"
    )
    b_local = cfg.num_branches // bsize
    local = _local_model(model, b_local)
    lcfg = local.cfg
    # resolve at BUILD time like the other step builders (dp.py, loop.py):
    # the env default must freeze when the step is constructed, not when it
    # first traces, and guard=True/False gives programmatic A/B control
    from ..obs import numerics as obs_numerics
    from ..obs import sharding as obs_sharding
    from ..train.guard import guard_enabled

    # sharding-inspector provenance (obs/sharding.py): the branch builder's
    # decoder banks are the one placement the replication audit must NOT
    # flag as accidental — the report names the owner
    obs_sharding.note_builder(
        "branch_parallel_train_step", dict(mesh.shape),
        branches=int(cfg.num_branches),
    )
    use_guard = guard_enabled(guard)
    # Telemetry.numerics (obs/numerics.py): probes tap the LOCAL branch
    # slice's modules per device; activation stats merge across the mesh
    # inside the shard_map, so one census covers every branch
    use_numerics = obs_numerics.numerics_enabled(numerics)
    meta = {"act_names": None, "grad_names": None}

    def per_device_loss(params, batch_stats, batch, rng):
        if mixed_precision:
            from ..train.loop import mp_cast, mp_restore_stats

            params, batch = mp_cast(params, batch, compute_grad_energy)
        variables = {"params": params, "batch_stats": batch_stats}
        (tot, tasks, mutated, _), acts = obs_numerics.run_probed(
            use_numerics, meta,
            lambda: compute_loss(
                local, variables, batch, lcfg, True, rng, compute_grad_energy
            ),
        )
        if mixed_precision:
            mutated = mp_restore_stats(mutated)
        return tot.astype(jnp.float32), (tasks, mutated, acts)

    if cfg.conv_checkpointing:
        from ..ops.remat import loss_remat

        per_device_loss = loss_remat(per_device_loss, cfg.remat_policy)

    def _mixed_pmean(tree, scale_enc, scale_dec_vec):
        """pmean with decoder subtrees reduced over data only (per-BRANCH
        weighted mean — ``scale_dec_vec`` is a [b_local] vector applied
        along the leading bank axis), encoder subtrees over the whole mesh
        (global mean)."""
        out = {}
        for k, v in tree.items():
            if _is_decoder_key(k):

                def dec_scale(g):
                    s = scale_dec_vec.reshape(
                        (b_local,) + (1,) * (g.ndim - 1)
                    )
                    return g * s

                out[k] = jax.lax.pmean(
                    jax.tree_util.tree_map(dec_scale, v), DATA_AXIS
                )
            else:
                out[k] = jax.lax.pmean(
                    jax.tree_util.tree_map(lambda g: g * scale_enc, v), _BOTH
                )
        return out

    def sharded_grads(params, batch_stats, batch, rng):
        batch = jax.tree_util.tree_map(lambda x: x[0], batch)
        # graphs arrive with GLOBAL dataset ids; remap to this device's
        # local branch-slice index (padding rows clip harmlessly — their
        # loss terms are masked out)
        br = jax.lax.axis_index(BRANCH_AXIS)
        local_ds = jnp.clip(
            batch.dataset_id.astype(jnp.int32) - br * b_local, 0, b_local - 1
        )
        batch = batch.replace(dataset_id=local_ds)
        (tot, (tasks, mutated, acts)), grads = jax.value_and_grad(
            per_device_loss, has_aux=True
        )(params, batch_stats, batch, rng)
        gm = batch.graph_mask.astype(jnp.float32)
        n = jnp.sum(gm)
        # encoder: weighted mean over every shard (DDP analog)
        n_tot = jax.lax.psum(n, _BOTH)
        scale_enc = n * mesh.size / jnp.maximum(n_tot, 1.0)
        # decoder: weighted mean over each BRANCH's graphs (the reference's
        # per-branch DDP subgroup, MultiTaskModelMP.py:230). The per-device
        # loss averages over its shard, so slice j's raw gradient carries a
        # factor n_j_shard/n_shard; rescaling by n_shard * D / n_j_total
        # before the data-axis pmean yields exactly the per-branch weighted
        # mean — also correct when several branches share a device block
        # (b_local > 1), where a single block-mass scale would train each
        # branch at ~1/b_local effective LR.
        branch_mass = jax.ops.segment_sum(
            gm, batch.dataset_id, num_segments=b_local
        )  # [b_local] real graphs per local branch slice on this shard
        branch_tot = jax.lax.psum(branch_mass, DATA_AXIS)
        scale_dec_vec = (
            n * mesh.shape[DATA_AXIS] / jnp.maximum(branch_tot, 1.0)
        )
        if cfg.branch_loss_weights:
            # static per-branch loss balancing (Mixture.branch_loss_weights,
            # mix/balance.py): scale each branch's decoder gradient by its
            # weight — this device's b_local-slice of the global vector
            w_all = jnp.asarray(cfg.branch_loss_weights, jnp.float32)
            w_local = jax.lax.dynamic_slice(w_all, (br * b_local,), (b_local,))
            scale_dec_vec = scale_dec_vec * w_local
        grads = _mixed_pmean(grads, scale_enc, scale_dec_vec)
        tot = jax.lax.pmean(tot * scale_enc, _BOTH)
        tasks = jax.lax.pmean(
            jax.tree_util.tree_map(lambda t: t * scale_enc, tasks), _BOTH
        )
        stats = mutated.get("batch_stats", batch_stats)
        new_stats = _mixed_pmean(stats, scale_enc, scale_dec_vec)
        if use_numerics:
            acts = obs_numerics.cross_device_reduce(acts, _BOTH)
            return grads, tot, tasks, new_stats, acts
        return grads, tot, tasks, new_stats

    rep = P()

    def _specs_like(tree):
        return branch_specs(tree)

    from ..train.compile_plane import note_trace

    def step(state: TrainState, batch, rng):
        # retrace sentinel: one execution per jit trace (compile_plane.py)
        note_trace("branch_train_step", (state, batch, rng))
        grad_map = shard_map(
            sharded_grads,
            mesh=mesh,
            in_specs=(
                _specs_like(state.params),
                _specs_like(state.batch_stats),
                P(_BOTH),
                rep,
            ),
            out_specs=(
                _specs_like(state.params),
                rep,
                rep,
                _specs_like(state.batch_stats),
            ) + ((rep,) if use_numerics else ()),
            check_vma=False,
        )
        acts = None
        if use_numerics:
            grads, tot, tasks, new_stats, acts = grad_map(
                state.params, state.batch_stats, batch, rng
            )
        else:
            grads, tot, tasks, new_stats = grad_map(
                state.params, state.batch_stats, batch, rng
            )
        # chaos-test hook + non-finite step guard (train/guard.py): the
        # decision rides the reduced loss/grads, so every device agrees
        from ..train.guard import guarded_update, step_ok
        from ..utils import faultinject

        grads = faultinject.poison_grads(
            grads, state.step, faultinject.lr_of(state.opt_state)
        )
        numer = None
        if use_numerics:
            # branch-sharded decoder grad leaves reduce to replicated
            # scalars under the outer jit (GSPMD inserts the collectives)
            gnames, gstats = obs_numerics.grad_group_stats(grads)
            meta["grad_names"] = gnames
            numer = {"ok": step_ok(tot, grads), "act": acts, "grad": gstats}

        # optimizer update under the outer jit: decoder grads/moments stay
        # branch-sharded by propagation, encoder leaves replicated
        def do_update():
            updates, opt_state = tx.update(
                grads, state.opt_state, state.params
            )
            return optax.apply_updates(state.params, updates), opt_state

        if use_guard:
            new_state = guarded_update(
                state,
                numer["ok"] if numer is not None else step_ok(tot, grads),
                do_update,
                new_stats,
            )
        else:
            params, opt_state = do_update()
            new_state = state.replace(
                params=params,
                opt_state=opt_state,
                batch_stats=new_stats,
                step=state.step + 1,
            )
        if use_numerics:
            return new_state, tot, tasks, numer
        return new_state, tot, tasks

    jitted = jax.jit(step, donate_argnums=0)
    if not use_numerics:
        return jitted
    # numerics build: AOT-reachable jit + name tables + NaN drill-down;
    # the diagnostic runs the GLOBAL (dense-decode) objective per shard
    # row — branch ids stay global there, so no local remap is needed
    return obs_numerics.numerics_step_wrapper(
        jitted, meta, model, compute_grad_energy, mixed_precision
    )


def make_branch_parallel_eval_step(
    model: HydraModel,
    mesh: Mesh,
    compute_grad_energy: bool = False,
    mixed_precision: bool = False,
):
    cfg = model.cfg
    bsize = mesh.shape[BRANCH_AXIS]
    b_local = cfg.num_branches // bsize
    local = _local_model(model, b_local)
    lcfg = local.cfg

    def sharded_eval(params, batch_stats, batch):
        batch = jax.tree_util.tree_map(lambda x: x[0], batch)
        br = jax.lax.axis_index(BRANCH_AXIS)
        local_ds = jnp.clip(
            batch.dataset_id.astype(jnp.int32) - br * b_local, 0, b_local - 1
        )
        batch = batch.replace(dataset_id=local_ds)
        variables = {"params": params, "batch_stats": batch_stats}
        if mixed_precision:
            from ..train.loop import mp_cast_eval

            variables, batch = mp_cast_eval(
                variables, batch, compute_grad_energy
            )
        tot, tasks, _, _ = compute_loss(
            local, variables, batch, lcfg, False, None, compute_grad_energy
        )
        n = jnp.sum(batch.graph_mask.astype(jnp.float32))
        n_tot = jax.lax.psum(n, _BOTH)
        scale = n * mesh.size / jnp.maximum(n_tot, 1.0)
        tot = jax.lax.pmean(tot * scale, _BOTH)
        tasks = jax.lax.pmean(
            jax.tree_util.tree_map(lambda t: t * scale, tasks), _BOTH
        )
        return tot, tasks

    rep = P()
    from ..train.compile_plane import note_trace

    def evalf(state: TrainState, batch):
        note_trace("branch_eval_step", (state, batch))
        mapped = shard_map(
            sharded_eval,
            mesh=mesh,
            in_specs=(
                branch_specs(state.params),
                branch_specs(state.batch_stats),
                P(_BOTH),
            ),
            out_specs=(rep, rep),
            check_vma=False,
        )
        return mapped(state.params, state.batch_stats, batch)

    return jax.jit(evalf)


class BranchRoutedLoader:
    """Stacked-batch loader whose shard rows are grouped by branch block.

    Wraps one ``GraphLoader`` per branch (each over that branch's graphs,
    with ``rows = num_shards / branch_count`` device rows) and stacks their
    rows in branch-major order — matching the (branch, data) mesh
    flattening, so shard row ``r`` lands on mesh position
    ``(r // data_size, r % data_size)``.

    ``spec`` may be a single worst-case ``PadSpec`` (every batch padded to
    it — the pre-r10 behavior) or a ``SpecLadder``: each batch is then
    padded to the smallest level fitting its LARGEST row, so small-graph
    steps stop paying worst-case padding. Single-host only — every row of
    a batch must share one static shape, and on multi-host runs the level
    choice would have to agree across processes without a collective, so
    ``host_count > 1`` collapses the ladder to its worst level.

    The analog of the reference's per-branch datasets + uneven process
    groups (examples/multibranch/train.py:166-213).

    Batches are always full (``drop_last``) so every host steps in lockstep:
    up to ``batch_size-1`` tail graphs per branch are excluded per epoch —
    the same trade the reference's DistributedSampler makes. The epoch
    length is the MAX over branches (globally agreed); rows whose branch is
    exhausted emit all-padding batches, so uneven branch sizes neither
    truncate the larger branches' metrics nor desynchronize the collective
    step (empty rows carry zero loss weight).
    """

    def __init__(
        self,
        graphs: Sequence,
        batch_size: int,
        branch_count: int,
        num_shards: int,
        seed: int = 0,
        shuffle: bool = True,
        sort_edges: bool = False,
        oversampling: bool = True,
        host_count: int = 1,
        host_index: int = 0,
        spec=None,
    ):
        """``num_shards``/``batch_size`` are per-host (local rows / local
        graphs per step). Globally there are ``host_count * num_shards``
        rows; row ``g`` serves branch ``g // (global_rows/branch_count)``,
        so one host may serve several branches (many local rows per branch)
        or one branch may span several hosts (the sub-loader then shards its
        branch's graphs across exactly those hosts)."""
        from ..data.graph import SpecLadder
        from ..data.pipeline import GraphLoader

        L = num_shards
        G = host_count * L
        assert G % branch_count == 0, (
            f"{G} global rows not divisible by {branch_count} branches"
        )
        R = G // branch_count  # global rows per branch
        # a host's rows must not straddle a branch boundary: either whole
        # branches fit in a host (L % R == 0) or whole hosts fit in a branch
        # (R % L == 0) — otherwise per-host shards would overlap and step
        # counts diverge (deadlock in the collective train step)
        assert (R >= L and R % L == 0) or (R < L and L % R == 0), (
            f"branch rows R={R} and host rows L={L} misaligned: "
            f"host_count*local_devices ({G}) must tile branch_count "
            f"({branch_count}) without a host straddling a branch boundary"
        )
        ids = sorted({g.dataset_id for g in graphs})
        assert len(ids) == branch_count, (
            f"dataset ids {ids} != branch_count {branch_count}"
        )
        # branch of each of this host's local rows (branch-major global order)
        row_branch = [(host_index * L + r) // R for r in range(L)]
        served = sorted(set(row_branch))
        by_branch = {i: [g for g in graphs if g.dataset_id == i] for i in ids}
        n_max = max(len(b) for b in by_branch.values())
        # per-shard graph count is identical for every row by construction.
        # Callers building train/val/test loaders should pass ONE ``spec``
        # (ladder) computed over all splits so eval reuses the train step's
        # compilations.
        assert batch_size % L == 0
        per_row_bs = batch_size // L
        if spec is None:
            spec = SpecLadder.for_dataset(
                list(graphs), max(per_row_bs, 1), num_buckets=1
            )
        if not isinstance(spec, SpecLadder):
            spec = SpecLadder((spec,))
        if host_count > 1 and len(spec.specs) > 1:
            # per-batch level selection is a per-host decision; across hosts
            # the collective step needs identical global shapes, and
            # agreeing on max-over-all-hosts would cost a collective per
            # batch — multi-host keeps the worst-case single level
            spec = SpecLadder((spec.specs[-1],))
        self.ladder = spec
        spec = spec.specs[-1]  # worst case: sub-loader budget + validator cap
        self.loaders: List = []
        for b in served:
            rows_b = row_branch.count(b)  # local rows serving branch b
            hosts_b = max(R // rows_b, 1)  # hosts sharing branch b
            # this host's rank within branch b's host group
            first_global_row = b * R
            host_rank_b = (host_index * L - first_global_row) // L if hosts_b > 1 else 0
            bgraphs = by_branch[ids[b]]
            over = oversampling and len(bgraphs) < n_max
            self.loaders.append(
                GraphLoader(
                    bgraphs,
                    per_row_bs * rows_b,
                    shuffle=shuffle,
                    seed=seed + 17 * b,
                    num_shards=rows_b,
                    spec=spec,
                    sort_edges=sort_edges,
                    oversampling=over,
                    num_samples=n_max if over else None,
                    drop_last=True,
                    host_count=hosts_b,
                    host_index=host_rank_b,
                )
            )
        self.graphs = list(graphs)
        # per-graph triplet counts, memoized by id (DimeNet ladders budget
        # the triplet channel; _triplet_count is O(E) interpreted python)
        self._trip_memo: dict = {}
        self.batch_size = batch_size
        self.num_shards = L
        self.host_count = host_count
        self.host_index = host_index
        self.sort_edges = sort_edges
        self.spec = spec
        # GLOBALLY agreed step count: every host computes the same MAX over
        # ALL branches (not just the ones it serves) from the full graph
        # list — hosts serving different branches would otherwise disagree
        # on epoch length and deadlock in the collective step. Exhausted
        # branches fill their rows with all-padding batches (zero weight).
        steps = []
        for b in range(branch_count):
            nb = len(by_branch[ids[b]])
            rows_srv = min(R, L)
            hosts_b = max(R // rows_srv, 1)
            n_eff = n_max if (oversampling and nb < n_max) else nb
            steps.append((n_eff // hosts_b) // (per_row_bs * rows_srv))
        self._len = max(steps)
        self._templates: dict = {}

    def _trip_count_of(self, g) -> int:
        from ..data.graph import _triplet_count

        got = self._trip_memo.get(id(g))
        if got is None:
            got = _triplet_count(g)
            self._trip_memo[id(g)] = got
        return got

    def _filler_arrs(self, spec):
        """One all-padding row's array dict at ``spec``: masks false,
        edges/nodes parked on the dummy slots (the GraphLoader stacked-path
        template convention, data/pipeline.py _make_stacked)."""
        from ..data.graph import batch_graphs_np

        key = spec
        if key not in self._templates:
            g = next(
                (
                    c
                    for c in self.graphs
                    if c.num_nodes <= spec.n_nodes - 1
                    and c.num_edges <= spec.n_edges
                ),
                self.graphs[0],
            )
            arrs = batch_graphs_np([g], spec)
            z = {k: np.zeros_like(v) for k, v in arrs.items()}
            z["senders"] = np.full_like(arrs["senders"], spec.n_nodes - 1)
            z["receivers"] = z["senders"].copy()
            z["node_graph"] = np.full_like(arrs["node_graph"], spec.n_graphs - 1)
            self._templates[key] = z
        return self._templates[key]

    def _stack_rows(self, rows, spec):
        """Stack per-row padded batches (branch-major row order preserved);
        empty rows become all-padding fillers at the same spec."""
        from ..data.graph import batch_graphs_np, graph_batch_from_np

        arr_list = [
            batch_graphs_np(r, spec, sort_edges=self.sort_edges)
            if r
            else self._filler_arrs(spec)
            for r in rows
        ]
        stacked = {
            k: np.stack([a[k] for a in arr_list]) for k in arr_list[0]
        }
        return graph_batch_from_np(stacked)

    def spec_template_batches(self):
        """Compile-plane warm-up templates (train/compile_plane.py): one
        stacked specialization per ladder level ANY branch can land a row
        in. Pre-r10 this was the single worst-case spec for all branches —
        warm-up then missed every smaller level a branch's batches actually
        select, and the first small-graph step of each level retraced.
        Filler rows fit any level, so the cover is the UNION of the
        per-branch selectable sets (data/pipeline.selectable_levels)."""
        from ..data.pipeline import selectable_levels

        by_level = {}
        for l in self.loaders:
            for li, g in selectable_levels(l.graphs, self.ladder):
                by_level.setdefault(li, g)
        out = []
        for li in sorted(by_level):
            spec = self.ladder.specs[li]
            rows = [[by_level[li]]] + [[] for _ in range(self.num_shards - 1)]
            out.append((spec, self._stack_rows(rows, spec)))
        return out

    def set_epoch(self, epoch: int) -> None:
        for l in self.loaders:
            l.set_epoch(epoch)

    def __len__(self) -> int:
        return self._len

    def __iter__(self) -> Iterator:
        # sub-loaders contribute their deterministic (seed, epoch) index
        # streams; rows are built HERE so one ladder level can be selected
        # per stacked batch (the smallest level fitting the largest row)
        streams = []
        for l in self.loaders:
            idx = l._local_indices()
            streams.append((l, idx, len(idx) // l.batch_size))
        for step in range(len(self)):
            rows = []
            for l, idx, n_full in streams:
                rows_b = l.num_shards
                if step < n_full:
                    sl = idx[step * l.batch_size : (step + 1) * l.batch_size]
                    graphs = [l.graphs[i] for i in sl]
                    rows.extend(graphs[s::rows_b] for s in range(rows_b))
                else:  # branch exhausted: zero-weight filler rows
                    rows.extend([] for _ in range(rows_b))
            spec = self.ladder.select(
                max((sum(g.num_nodes for g in r) for r in rows if r), default=0),
                max((sum(g.num_edges for g in r) for r in rows if r), default=0),
                max(
                    (sum(self._trip_count_of(g) for g in r) for r in rows if r),
                    default=0,
                )
                if self.spec.n_triplets
                else 0,
            )
            yield self._stack_rows(rows, spec)
