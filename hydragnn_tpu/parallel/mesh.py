"""Device mesh + sharding layer: the TPU replacement for torch DDP/NCCL.

The reference scales with ``DistributedDataParallel`` over NCCL/RCCL/oneCCL
process groups plus an mpi4py side plane (hydragnn/utils/distributed/
distributed.py:119-351, SURVEY §5.8). The TPU-native design is
single-controller SPMD:

- one ``jax.sharding.Mesh`` with axes ``("branch", "data")`` replaces process
  groups; pure data parallelism is the degenerate branch=1 case;
- batches are sharded over ``data`` (the ``GraphBatch`` leading axes), params
  are replicated; ``jax.jit`` then inserts the gradient ``psum`` over ICI
  automatically during backward — the analog of DDP's bucketed all-reduce,
  overlapped with compute by XLA's async collectives;
- the multi-branch task parallelism of ``MultiTaskModelMP``
  (hydragnn/models/MultiTaskModelMP.py:172-230) maps to the ``branch`` axis:
  each branch submesh consumes its own dataset shard, encoder gradients psum
  over the full mesh, decoder gradients over the branch submesh — expressed
  by the same jit program because unused branches contribute zero gradients
  under the dense masked-branch decoding (models/base.py _graph_head).

Multi-host: ``jax.distributed.initialize`` + per-host data sharding via
``GraphLoader(host_count, host_index)``; collectives ride ICI within a slice
and DCN across slices, chosen by XLA from the mesh axis order.
"""

from __future__ import annotations

import os
import warnings
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..utils import envflags

DATA_AXIS = "data"
BRANCH_AXIS = "branch"
MODEL_AXIS = "model"


def compat_shard_map(*args, **kwargs):
    """``jax.shard_map`` across jax versions: the public name lived in
    ``jax.experimental.shard_map`` before 0.5, and the replication-check
    kwarg was renamed ``check_rep`` -> ``check_vma``. Callers use the NEW
    spelling; this translates for older runtimes by inspecting the actual
    signature (import location alone doesn't pin the kwarg name)."""
    try:
        from jax import shard_map as _sm

        old_location = False
    except ImportError:  # jax < 0.5 keeps shard_map in experimental
        from jax.experimental.shard_map import shard_map as _sm

        old_location = True
    if "check_vma" in kwargs:
        import inspect

        try:
            params = inspect.signature(_sm).parameters
        except (TypeError, ValueError):  # pragma: no cover - C callables
            params = None
        if params is not None:
            if "check_vma" not in params and "check_rep" in params:
                kwargs["check_rep"] = kwargs.pop("check_vma")
        elif old_location:
            # uninspectable + experimental location: the old spelling is
            # the only one that can exist there
            kwargs["check_rep"] = kwargs.pop("check_vma")
        # uninspectable at the NEW location: keep the new spelling — that
        # is the environment the callers are written for
    return _sm(*args, **kwargs)


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    branch_size: int = 1,
) -> Mesh:
    """Build a (branch, data) mesh over the available devices.

    branch_size=1 -> pure DP. Mirrors the 2-D ``init_device_mesh`` of the
    reference's task-parallel path (examples/multibranch/train.py:216-251).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    assert n % branch_size == 0, f"{n} devices not divisible by branch={branch_size}"
    arr = np.asarray(devices).reshape(branch_size, n // branch_size)
    return Mesh(arr, (BRANCH_AXIS, DATA_AXIS))


def make_mesh2d(
    devices: Optional[Sequence[jax.Device]] = None,
    model_size: int = 1,
) -> Mesh:
    """Build the engine's 2D ``(data, model)`` mesh (parallel/engine.py).

    Subsumes ``make_mesh``: ``model_size`` is the model/task-parallel
    extent (num_branches in the routed presets, 1 for pure DP/ZeRO).
    Device (d, m) is ``devices[m * data_n + d]`` — the transpose of the
    legacy ``(branch, data)`` layout — so the *physical* device holding
    (branch=m, data=d) work is identical between the two constructors and
    the engine's steps are bit-identical to the retired builders'.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    assert (
        n % model_size == 0
    ), f"{n} devices not divisible by model={model_size}"
    arr = np.asarray(devices).reshape(model_size, n // model_size)
    return Mesh(arr.transpose(1, 0), (DATA_AXIS, MODEL_AXIS))


def data_axis_size(mesh: Mesh) -> int:
    return int(dict(mesh.shape).get(DATA_AXIS, 1))


def batch_axes(mesh: Mesh) -> Tuple[str, ...]:
    """Mesh axes the GraphBatch leading dim shards over, in shard-row
    order: legacy meshes stack (branch-major, data-minor); the 2D mesh
    keeps the same row order as (model, data) so a given shard index
    lands on the same physical device under both constructors."""
    names = mesh.axis_names
    if MODEL_AXIS in names:
        return (MODEL_AXIS, DATA_AXIS)
    if BRANCH_AXIS in names:
        return (BRANCH_AXIS, DATA_AXIS)
    return (DATA_AXIS,)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for GraphBatch leaves: leading (node/edge/graph) axis over
    the mesh's batch axes (``batch_axes`` — model/branch-major, data-minor,
    identical shard->device mapping under both mesh constructors).
    Requires padded sizes divisible by the mesh size."""
    return NamedSharding(mesh, P(batch_axes(mesh)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh):
    """Place a GraphBatch with leading axes sharded across the mesh."""
    sh = batch_sharding(mesh)
    rep = replicated(mesh)

    def place(x):
        if hasattr(x, "ndim") and x.ndim >= 1 and x.shape[0] % mesh.size == 0:
            return jax.device_put(x, sh)
        return jax.device_put(x, rep)

    return jax.tree_util.tree_map(place, batch)


def promote_batch(batch, mesh: Mesh):
    """Host-local stacked GraphBatch ``[local_shards, ...]`` -> global array
    ``[global_shards, ...]`` sharded over the mesh's (branch, data) leading
    axis — the multi-controller input path: each process contributes the
    shards its own ``GraphLoader(host_count, host_index)`` built, and the
    shard_map'd step sees one coherent global batch (the DistributedSampler
    + DDP input contract, reference: load_data.py:256-274).

    No-op on single-process runs (the batch is already addressable).
    """
    if jax.process_count() == 1:
        return batch
    sharding = batch_sharding(mesh)

    def prom(x):
        return jax.make_array_from_process_local_data(sharding, np.asarray(x))

    return jax.tree_util.tree_map(prom, batch)


def replicate_state(state, mesh: Mesh):
    rep = replicated(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, rep), state)


def _zero_leaf_eligible(x, data_n: int, min_size: int) -> bool:
    """Shared ZeRO eligibility predicate: large leaves whose leading dim
    divides the data axis. ONE definition for both the stage-1 moment
    placement and the stage-2 gradient constraint so their slices always
    line up (a de-synced pair would leave some moment leaves sharded with
    replicated gradients, defeating the reduce-scatter lowering)."""
    return (
        hasattr(x, "ndim")
        and x.ndim >= 1
        and x.size >= min_size
        and x.shape[0] % data_n == 0
    )


def shard_optimizer_state(state, mesh: Mesh, min_size: int = 1024):
    """ZeRO-1 analog: shard large optimizer-moment arrays over the data axis
    (reference capability: DeepSpeed ZeRO stage 1 / ZeroRedundancyOptimizer,
    optimizer.py:43-101). Parameters stay replicated; only optimizer state
    pytree leaves whose leading dim divides the data axis are sharded."""
    data_n = mesh.shape[DATA_AXIS]
    sharded = NamedSharding(mesh, P(DATA_AXIS))
    rep = replicated(mesh)

    def place(x):
        if _zero_leaf_eligible(x, data_n, min_size):
            return jax.device_put(x, sharded)
        return jax.device_put(x, rep)

    return jax.tree_util.tree_map(place, state)


def shard_params_zero3(params, mesh: Mesh, min_size: int = 1024):
    """ZeRO-3/FSDP analog: store large PARAMETER leaves sharded ``P(data)``
    between steps (reference capability: DeepSpeed ZeRO stage 3, accepted
    by run_training.py:136-149).

    The mesh step's shard_map consumes params at spec ``P()`` — XLA
    inserts the transient all-gather at the program boundary (the FSDP
    gather-at-use), and the step's output constraint re-shards the
    updated params, so full parameters exist only inside one step's
    lifetime. Same eligibility predicate as the stage-1/2 placements so
    param, gradient, and moment slices all line up."""
    data_n = mesh.shape[DATA_AXIS]
    sharded = NamedSharding(mesh, P(DATA_AXIS))
    rep = replicated(mesh)

    def place(x):
        if _zero_leaf_eligible(x, data_n, min_size):
            return jax.device_put(x, sharded)
        return jax.device_put(x, rep)

    return jax.tree_util.tree_map(place, params)


def zero3_param_constraint(params, mesh: Mesh, min_size: int = 1024):
    """In-jit counterpart of ``shard_params_zero3``: pin updated parameter
    leaves back to ``P(data)`` at the end of the step so XLA frees the
    gathered full copies instead of keeping params replicated."""
    data_n = mesh.shape[DATA_AXIS]
    sharded = NamedSharding(mesh, P(DATA_AXIS))

    def place(x):
        if _zero_leaf_eligible(x, data_n, min_size):
            return jax.lax.with_sharding_constraint(x, sharded)
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P()))

    return jax.tree_util.tree_map(place, params)


def zero2_grad_constraint(grads, mesh: Mesh, min_size: int = 1024):
    """ZeRO-2 analog: constrain large gradient leaves to ``P(data)`` sharding
    inside the jitted step (reference capability: DeepSpeed ZeRO stage 2,
    accepted by run_training.py:136-149).

    Applied between the gradient ``pmean`` and the optimizer update, XLA
    lowers the reduce+constraint pair to a reduce-scatter: each device then
    holds only its 1/data_n gradient slice, updates the matching ZeRO-1
    moment slice, and the replicated-params output constraint turns the
    param update into the all-gather — the full ZeRO-2 exchange, expressed
    as shardings instead of hand-written collectives. Eligibility matches
    ``shard_optimizer_state`` so gradient and moment slices line up.
    """
    sharded = NamedSharding(mesh, P(DATA_AXIS))
    data_n = mesh.shape[DATA_AXIS]

    def place(g):
        if _zero_leaf_eligible(g, data_n, min_size):
            return jax.lax.with_sharding_constraint(g, sharded)
        return g

    return jax.tree_util.tree_map(place, grads)


def leaf_sharding_info(x) -> Optional[dict]:
    """Placement facts of one state leaf for the sharding inspector
    (obs/sharding.py): PartitionSpec string, replicated-vs-sharded, total
    and per-device bytes. Pure metadata — no transfers, no compute. None
    for non-array leaves; host numpy arrays report as replicated with a
    ``host`` spec (every process holds the full copy, which is what the
    audit cares about)."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        return None
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        return None
    total = int(np.prod(shape, dtype=np.int64)) * itemsize if shape else itemsize
    sharding = getattr(x, "sharding", None)
    if sharding is None:
        return {
            "spec": "host", "replicated": True, "total_bytes": total,
            "per_device_bytes": total, "devices": 1,
            "dtype": str(np.dtype(dtype)), "shape": tuple(shape),
        }
    if isinstance(sharding, NamedSharding):
        spec = str(sharding.spec)
    else:
        spec = type(sharding).__name__
    replicated = bool(getattr(sharding, "is_fully_replicated", True))
    per_device = total
    try:
        shard_shape = sharding.shard_shape(tuple(shape))
        per_device = (
            int(np.prod(shard_shape, dtype=np.int64)) * itemsize
            if shard_shape
            else itemsize
        )
    except Exception:
        pass
    try:
        devices = len(sharding.device_set)
    except Exception:
        devices = 1
    return {
        "spec": spec, "replicated": replicated, "total_bytes": total,
        "per_device_bytes": per_device, "devices": devices,
        "dtype": str(np.dtype(dtype)), "shape": tuple(shape),
    }


def materialize_replicated(tree):
    """Host-local numpy copy of a (possibly sharded) global-state pytree.

    Sharded leaves (ZeRO-1 moments, branch-parallel decoder banks) are
    re-replicated with a jitted identity first — fetching them directly
    would fail because they span non-addressable devices. COLLECTIVE on
    multi-host runs: every process must call it, in the same tree order.
    """

    def loc(x):
        if (
            isinstance(x, jax.Array)
            and hasattr(x, "sharding")
            and not x.sharding.is_fully_replicated
        ):
            # eager resharding device_put: no per-leaf trace/compile (a
            # jitted identity here would recompile for every leaf shape at
            # every checkpoint save)
            x = jax.device_put(x, NamedSharding(x.sharding.mesh, P()))
        return np.asarray(x)

    return jax.tree_util.tree_map(loc, tree)


def _scheduler_host_info() -> Tuple[int, int]:
    """(host_count, host_index) from scheduler envs only — safe before the
    XLA backend exists (the reference parses the same envs, SLURM/OMPI,
    distributed.py:86-103)."""
    # Cloud TPU pod VMs expose the slice topology in TPU_* envs. A
    # single-name value (e.g. "localhost" on one-host setups) carries no
    # multi-host information — fall through to the scheduler envs then.
    hosts = [h for h in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",") if h]
    if len(hosts) > 1:
        return len(hosts), int(os.environ.get("TPU_WORKER_ID", 0))
    for count_key, rank_key in (
        ("SLURM_NTASKS", "SLURM_PROCID"),
        ("OMPI_COMM_WORLD_SIZE", "OMPI_COMM_WORLD_RANK"),
        ("WORLD_SIZE", "RANK"),
    ):
        if count_key in os.environ:
            return int(os.environ[count_key]), int(os.environ.get(rank_key, 0))
    return 1, 0


# set when setup_distributed had to skip rendezvous (backend already
# initialized): the scheduler envs then over-report the connected world
_rendezvous_skipped = False


def local_host_info() -> Tuple[int, int]:
    """(host_count, host_index) for data sharding across hosts: the live JAX
    distributed runtime when attached, scheduler envs otherwise. After a
    skipped rendezvous this reports (1, 0) — the process really is alone, so
    sharding by the scheduler's world size would silently train on a
    fraction of the data with no gradient sync."""
    if jax.process_count() > 1:
        return jax.process_count(), jax.process_index()
    if _rendezvous_skipped:
        return 1, 0
    return _scheduler_host_info()


def setup_distributed() -> None:
    """Initialize the multi-host JAX runtime when launched under a scheduler
    (the analog of setup_ddp's rendezvous, distributed.py:119-198). No-op for
    single-process runs.

    Rendezvous resolution order (cf. the reference's master-addr discovery
    for Summit/SLURM, distributed.py:143-159):
    1. explicit ``HYDRAGNN_COORDINATOR`` / ``JAX_COORDINATOR_ADDRESS`` plus
       the scheduler's world size/rank envs,
    2. bare ``jax.distributed.initialize()`` auto-detection — covers GCE TPU
       pods (metadata server) and SLURM/OpenMPI clusters JAX knows natively.

    Must run before anything touches the XLA backend — including
    ``jax.process_count()`` — so the already-initialized guard uses
    ``jax.distributed.is_initialized()``, which doesn't (older jaxlibs
    lack the helper entirely: treat that as not-initialized).
    """
    if getattr(jax.distributed, "is_initialized", lambda: False)():
        return
    coord = envflags.env_str("HYDRAGNN_COORDINATOR") or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    count, index = _scheduler_host_info()
    try:
        if coord and count > 1:
            jax.distributed.initialize(
                coordinator_address=coord, num_processes=count, process_id=index
            )
        elif count > 1:
            jax.distributed.initialize()
    except RuntimeError as e:
        if "must be called before" not in str(e):
            # genuine rendezvous failure (unreachable coordinator, mismatch):
            # abort — N silently-independent "replicas" would clobber shared
            # checkpoints and fake the scaling result
            raise
        # the XLA backend was touched before run_training (interactive use,
        # tests): train single-host rather than crash, but say so
        global _rendezvous_skipped
        _rendezvous_skipped = True
        warnings.warn(f"multi-host rendezvous skipped: {e}")


def gather_across_hosts(values):
    """Concatenate per-host arrays across every process: dict of
    [n_local, ...] -> dict of [n_global, ...], ragged-safe (each host may
    hold a different sample count — pad to the max, then slice per the
    gathered counts). The analog of the reference's padded all-gather of
    test predictions (gather_tensor_ranks,
    hydragnn/train/train_validate_test.py:410-448). Identity on one host.
    """

    if jax.process_count() == 1:
        return values
    from jax.experimental import multihost_utils

    out = {}
    for k, v in values.items():
        v = np.asarray(v)
        counts = np.asarray(
            multihost_utils.process_allgather(
                np.asarray([v.shape[0]], np.int64)
            )
        ).reshape(-1)
        max_n = int(counts.max())
        pad = np.zeros((max_n - v.shape[0],) + v.shape[1:], v.dtype)
        stacked = np.asarray(
            multihost_utils.process_allgather(np.concatenate([v, pad]))
        )
        out[k] = np.concatenate(
            [stacked[p, : int(counts[p])] for p in range(stacked.shape[0])]
        )
    return out
