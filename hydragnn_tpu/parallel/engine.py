"""The one mesh-step builder: ``(objective, rule_table, mesh)`` -> steps.

ROADMAP item 1. The dp / ZeRO-1/2/3 / branch-parallel trio
(parallel/dp.py, parallel/branch.py, the constraint paths in
parallel/mesh.py) collapses into this module, driven by a declarative
rule table (parallel/rules.py):

- ``place_state``        — between-steps placement: every params /
  opt_state / batch_stats leaf device_put by its first-matching rule;
  non-scalar leaves NO rule matches are placed replicated and audited
  (obs/sharding.py ``record_unmatched``).
- ``make_mesh_train_step`` / ``make_mesh_eval_step`` — the train/eval
  steps every caller uses. The guard (train/guard.py), numerics probes
  (obs/numerics.py), retrace sentinel (``note_trace``), fault-injection
  hook, and donate/jit plumbing are threaded through ONCE here instead
  of per-builder.

Two step families remain — selected by ``table.routed``, not by caller:

- **unrouted** (dp, zero1/2/3): params consumed replicated inside the
  shard_map (ZeRO-3's between-steps ``P(data)`` storage all-gathers at
  the program boundary), gradients pmean over the whole mesh; the
  table's ``grads``-scope rules become in-step ``with_sharding_
  constraint`` pins between the pmean and the optimizer update (the
  reduce-scatter lowering, ex-``zero2_grad_constraint``), its
  ``params``-scope rules the step-output constraint
  (ex-``zero3_param_constraint``).
- **routed** (branch / mp): decoder-bank leaves (the table's model-axis
  rules) shard over the model axis, batches arrive branch-routed
  (parallel/routing.py BranchRoutedLoader), decoder gradients pmean
  over ``data`` only — the reference's ``MultiTaskModelMP`` per-branch
  DDP subgroup semantics, ported verbatim from the retired branch.py.

The math in both families is a line-for-line port of the retired
builders (bit-identical train loss on the same mesh is asserted in
tests/test_sharding_rules.py), with the mesh axis names resolved from
the table's logical ``data``/``model`` axes so the engine runs on both
the legacy ``(branch, data)`` mesh (deprecation shims) and the 2D
``(data, model)`` mesh (``make_mesh2d``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.base import HydraModel
from ..train.loss import compute_loss
from ..train.state import TrainState
from . import rules as R
from .mesh import DATA_AXIS, batch_axes, compat_shard_map as shard_map


@dataclasses.dataclass
class Objective:
    """What to optimize, independent of placement: the model + optimizer
    and the step-level switches every retired builder accepted. One
    objective builds steps under any rule table."""

    model: HydraModel
    tx: Any = None
    compute_grad_energy: bool = False
    mixed_precision: bool = False
    guard: Optional[bool] = None
    numerics: Optional[bool] = None


def ensure_stacked(batch):
    """Guarantee the leading device axis the shard_map steps expect.

    ``GraphLoader(num_shards=1)`` emits unstacked batches (the plain-jit
    contract); a 1-device mesh still wants ``[1, ...]``. Keeping the shim
    here keeps the [D, ...] contract in one place for every consumer.
    """
    if batch.graph_mask.ndim == 1:
        return jax.tree_util.tree_map(lambda x: jnp.asarray(x)[None], batch)
    return batch


# ---------------------------------------------------------------------------
# table -> concrete mesh resolution
# ---------------------------------------------------------------------------


def _resolved(table: R.RuleTable, mesh: Mesh):
    """(axis_map, logical axis sizes, concrete model axis name or None)."""
    amap = R.resolve_axes(mesh)
    shape = dict(mesh.shape)
    sizes = {tok: int(shape[ax]) for tok, ax in amap.items()}
    return amap, sizes, amap.get(R.MODEL)


def _section_specs(tree, table: R.RuleTable, scope: str, amap, sizes):
    return R.spec_tree(tree, table, scope, amap, sizes)


def place_state(
    state: TrainState, table: R.RuleTable, mesh: Mesh
) -> TrainState:
    """Place a TrainState per the rule table: replicate everything (step
    counter included), then device_put each params / opt_state /
    batch_stats leaf at its matched spec. Optimizer moments are PLACED,
    not re-initialized, so ``Training.continue`` resumes with its
    restored Adam state. Unmatched non-scalar leaves land replicated and
    are reported to the sharding audit."""
    from ..obs import sharding as obs_sharding
    from .mesh import replicate_state

    amap, sizes, _ = _resolved(table, mesh)
    state = replicate_state(state, mesh)
    unmatched: List[str] = []

    def put(tree, scope):
        specs, miss = _section_specs(tree, table, scope, amap, sizes)
        unmatched.extend(miss)
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            tree,
            specs,
        )

    state = state.replace(
        params=put(state.params, "params"),
        batch_stats=put(state.batch_stats, "batch_stats"),
        opt_state=put(state.opt_state, "opt_state"),
    )
    obs_sharding.record_unmatched(table.name, unmatched)
    return state


def _constrain(tree, table, scope, mesh, amap, sizes, default_explicit):
    """In-jit counterpart of ``place_state`` for one scope: matched
    leaves pinned to their rule's spec with ``with_sharding_constraint``.
    ``default_explicit=True`` pins unmatched/replicated leaves to an
    explicit ``P()`` too (the params/ZeRO-3 output contract: GSPMD must
    not be free to leave merged params sharded); ``False`` leaves them
    untouched (the grads/ZeRO-2 contract)."""

    def pin(path, leaf):
        p = R.path_str(path)
        _, axes = R.match_rule(table, p, leaf, scope, sizes)
        if axes:
            spec = P(*[amap[a] if a is not None else None for a in axes])
            return jax.lax.with_sharding_constraint(
                leaf, NamedSharding(mesh, spec)
            )
        if default_explicit:
            return jax.lax.with_sharding_constraint(
                leaf, NamedSharding(mesh, P())
            )
        return leaf

    return jax.tree_util.tree_map_with_path(pin, tree)


def _routed_model(model, table: R.RuleTable, mesh: Mesh):
    """(local model slice, b_local, model axis name) for a routed table.

    The model is rebuilt for the device-local branch slice: identical
    module tree, bank leaves sliced by the shard_map specs. Branch-loss
    balancing is stripped from the LOCAL cfg — the global weight vector
    does not slice with the remapped local dataset ids, so the step
    applies balancing to the decoder gradient scales instead (the
    per-branch effective-LR equivalent)."""
    _, _, model_ax = _resolved(table, mesh)
    if model_ax is None:
        raise R.RuleError(
            f"rule table {table.name!r} is routed but mesh axes "
            f"{tuple(mesh.axis_names)} carry no model/branch axis "
            "(parallel/mesh.py make_mesh2d(model_size=...))"
        )
    cfg = model.cfg
    msize = int(dict(mesh.shape)[model_ax])
    assert cfg.num_branches % msize == 0, (
        f"num_branches {cfg.num_branches} not divisible by model axis "
        f"{msize}"
    )
    b_local = cfg.num_branches // msize
    lcfg = dataclasses.replace(
        cfg, num_branches=b_local,
        branch_loss_weights=None, branch_loss_metrics=False,
    )
    return type(model)(cfg=lcfg), b_local, model_ax


def _routed_top_keys(tree, table, scope, amap, sizes, model_ax):
    """Top-level collection keys whose subtree carries model-axis-sharded
    leaves — the decoder banks. Drives the mixed (per-branch vs global)
    gradient reduction; derived from the TABLE so reduction and placement
    can never disagree."""
    keys = set()
    if not isinstance(tree, dict):
        return keys
    specs, _ = _section_specs(tree, table, scope, amap, sizes)
    for k, sub in specs.items():
        for spec in jax.tree_util.tree_leaves(
            sub, is_leaf=lambda x: isinstance(x, P)
        ):
            if isinstance(spec, P) and model_ax in tuple(spec):
                keys.add(k)
                break
    return keys


# ---------------------------------------------------------------------------
# the one train-step builder
# ---------------------------------------------------------------------------


def make_mesh_train_step(
    objective: Objective, table: R.RuleTable, mesh: Mesh
):
    """Jitted (state, stacked_batch, rng) -> (state, loss, tasks) under
    ``table``'s placement on ``mesh``. The only train-step builder —
    dp/zero/branch are rule presets, not code paths."""
    R.validate_table(table)
    model, tx = objective.model, objective.tx
    compute_grad_energy = objective.compute_grad_energy
    mixed_precision = objective.mixed_precision
    cfg = model.cfg
    from ..obs import numerics as obs_numerics
    from ..obs import sharding as obs_sharding
    from ..train.compile_plane import note_trace
    from ..train.guard import guard_enabled, guarded_update, step_ok
    from ..utils import faultinject

    amap, sizes, model_ax = _resolved(table, mesh)
    routed = table.routed
    # ZeRO staging read off the table, not caller flags: any non-replicated
    # grads-scope rule arms the in-step grad pin (stage 2), any params-scope
    # rule the step-output param constraint (stage 3)
    pin_grads = table.shards("grads")
    pin_params = table.shards("params") and not routed
    if routed:
        local, b_local, model_ax = _routed_model(model, table, mesh)
        lcfg = local.cfg
        sentinel, builder = "branch_train_step", "branch_parallel_train_step"
        obs_sharding.note_builder(
            builder, dict(mesh.shape),
            rules=table.name, branches=int(cfg.num_branches),
        )
    else:
        local, lcfg = model, cfg
        sentinel, builder = "parallel_train_step", "parallel_train_step"
        obs_sharding.note_builder(
            builder, dict(mesh.shape),
            rules=table.name, zero2=pin_grads, zero3=pin_params,
        )
    # resolve at BUILD time like every step builder (loop.py): the env
    # default freezes when the step is constructed, not at first trace
    use_guard = guard_enabled(objective.guard)
    use_numerics = obs_numerics.numerics_enabled(objective.numerics)
    meta = {"act_names": None, "grad_names": None}
    _both = batch_axes(mesh)  # model/branch-major — legacy reduce order

    def per_device_loss(params, batch_stats, batch, rng):
        if mixed_precision:
            from ..train.loop import mp_cast, mp_restore_stats

            params, batch = mp_cast(params, batch, compute_grad_energy)
        variables = {"params": params, "batch_stats": batch_stats}
        (tot, tasks, mutated, _), acts = obs_numerics.run_probed(
            use_numerics, meta,
            lambda: compute_loss(
                local, variables, batch, lcfg, True, rng, compute_grad_energy
            ),
        )
        if mixed_precision:
            mutated = mp_restore_stats(mutated)
        return tot.astype(jnp.float32), (tasks, mutated, acts)

    if cfg.conv_checkpointing:
        from ..ops.remat import loss_remat

        per_device_loss = loss_remat(per_device_loss, cfg.remat_policy)

    # -- routed reduction: decoder subtrees pmean over data only ------------

    def _mixed_pmean(tree, scale_enc, scale_dec_vec, dec_keys):
        """pmean with decoder subtrees reduced over data only (per-BRANCH
        weighted mean — ``scale_dec_vec`` is a [b_local] vector applied
        along the leading bank axis), encoder subtrees over the whole
        mesh (global mean)."""
        out = {}
        for k, v in tree.items():
            if k in dec_keys:

                def dec_scale(g):
                    s = scale_dec_vec.reshape(
                        (b_local,) + (1,) * (g.ndim - 1)
                    )
                    return g * s

                out[k] = jax.lax.pmean(
                    jax.tree_util.tree_map(dec_scale, v), DATA_AXIS
                )
            else:
                out[k] = jax.lax.pmean(
                    jax.tree_util.tree_map(lambda g: g * scale_enc, v),
                    _both,
                )
        return out

    def routed_grads(dec_params, dec_stats):
        def sharded_grads(params, batch_stats, batch, rng):
            batch = jax.tree_util.tree_map(lambda x: x[0], batch)
            # graphs arrive with GLOBAL dataset ids; remap to this
            # device's local branch-slice index (padding rows clip
            # harmlessly — their loss terms are masked out)
            br = jax.lax.axis_index(model_ax)
            local_ds = jnp.clip(
                batch.dataset_id.astype(jnp.int32) - br * b_local,
                0,
                b_local - 1,
            )
            batch = batch.replace(dataset_id=local_ds)
            (tot, (tasks, mutated, acts)), grads = jax.value_and_grad(
                per_device_loss, has_aux=True
            )(params, batch_stats, batch, rng)
            gm = batch.graph_mask.astype(jnp.float32)
            n = jnp.sum(gm)
            # encoder: weighted mean over every shard (DDP analog)
            n_tot = jax.lax.psum(n, _both)
            scale_enc = n * mesh.size / jnp.maximum(n_tot, 1.0)
            # decoder: weighted mean over each BRANCH's graphs (the
            # reference's per-branch DDP subgroup). The per-device loss
            # averages over its shard, so slice j's raw gradient carries
            # a factor n_j_shard/n_shard; rescaling by n_shard * D /
            # n_j_total before the data-axis pmean yields exactly the
            # per-branch weighted mean — also correct when several
            # branches share a device block (b_local > 1), where a single
            # block-mass scale would train each branch at ~1/b_local
            # effective LR.
            branch_mass = jax.ops.segment_sum(
                gm, batch.dataset_id, num_segments=b_local
            )
            branch_tot = jax.lax.psum(branch_mass, DATA_AXIS)
            scale_dec_vec = (
                n * sizes[R.DATA] / jnp.maximum(branch_tot, 1.0)
            )
            if cfg.branch_loss_weights:
                # static per-branch loss balancing: scale each branch's
                # decoder gradient by its weight — this device's
                # b_local-slice of the global vector
                w_all = jnp.asarray(cfg.branch_loss_weights, jnp.float32)
                w_local = jax.lax.dynamic_slice(
                    w_all, (br * b_local,), (b_local,)
                )
                scale_dec_vec = scale_dec_vec * w_local
            grads = _mixed_pmean(
                grads, scale_enc, scale_dec_vec, dec_params
            )
            tot = jax.lax.pmean(tot * scale_enc, _both)
            tasks = jax.lax.pmean(
                jax.tree_util.tree_map(lambda t: t * scale_enc, tasks),
                _both,
            )
            stats = mutated.get("batch_stats", batch_stats)
            new_stats = _mixed_pmean(
                stats, scale_enc, scale_dec_vec, dec_stats
            )
            if use_numerics:
                acts = obs_numerics.cross_device_reduce(acts, _both)
                return grads, tot, tasks, new_stats, acts
            return grads, tot, tasks, new_stats

        return sharded_grads

    def unrouted_grads(params, batch_stats, batch, rng):
        # batch leaves arrive with leading axis [D_local=1, ...] inside
        # the shard; drop it to recover the per-device batch.
        batch = jax.tree_util.tree_map(lambda x: x[0], batch)
        (tot, (tasks, mutated, acts)), grads = jax.value_and_grad(
            per_device_loss, has_aux=True
        )(params, batch_stats, batch, rng)
        # weight each shard by its real-graph count so empty/remainder
        # shards neither dilute gradients nor corrupt running batch-norm
        # statistics
        n = jnp.sum(batch.graph_mask.astype(jnp.float32))
        n_tot = jax.lax.psum(n, _both)
        scale = n * mesh.size / jnp.maximum(n_tot, 1.0)
        # gradient all-reduce over the whole mesh (DDP analog)
        grads = jax.lax.pmean(
            jax.tree_util.tree_map(lambda g: g * scale, grads), _both
        )
        tot = jax.lax.pmean(tot * scale, _both)
        tasks = jax.lax.pmean(
            jax.tree_util.tree_map(lambda t: t * scale, tasks), _both
        )
        stats = mutated.get("batch_stats", batch_stats)
        new_stats = jax.lax.pmean(
            jax.tree_util.tree_map(lambda s: s * scale, stats), _both
        )
        if use_numerics:
            acts = obs_numerics.cross_device_reduce(acts, _both)
            return grads, tot, tasks, new_stats, acts
        return grads, tot, tasks, new_stats

    rep = P()
    if not routed:
        # params consumed replicated: under ZeRO-3 storage XLA inserts the
        # transient all-gather at the program boundary (gather-at-use)
        grad_map = shard_map(
            unrouted_grads,
            mesh=mesh,
            in_specs=(rep, rep, P(_both), rep),
            out_specs=(rep, rep, rep, rep)
            + ((rep,) if use_numerics else ()),
            check_vma=False,
        )

    def _pin_out_params(params):
        """The step-output param contract: ZeRO-3 re-shards updated
        params (transient full copies); ZeRO-2 pins them replicated so
        the sharded updates all-gather HERE instead of falling back to
        full-grad replication upstream. No-op for dp/routed tables."""
        if pin_params:
            return _constrain(
                params, table, "params", mesh, amap, sizes,
                default_explicit=True,
            )
        if pin_grads:
            return jax.tree_util.tree_map(
                lambda x: jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, P())
                ),
                params,
            )
        return params

    def step(state: TrainState, batch, rng):
        # retrace sentinel: one execution per jit trace (compile_plane.py)
        note_trace(sentinel, (state, batch, rng))
        if routed:
            # specs depend on the state's tree structure -> built per trace
            pspecs, _ = _section_specs(
                state.params, table, "params", amap, sizes
            )
            sspecs, _ = _section_specs(
                state.batch_stats, table, "batch_stats", amap, sizes
            )
            dec_p = _routed_top_keys(
                state.params, table, "params", amap, sizes, model_ax
            )
            dec_s = _routed_top_keys(
                state.batch_stats, table, "batch_stats", amap, sizes,
                model_ax,
            )
            gmap = shard_map(
                routed_grads(dec_p, dec_s),
                mesh=mesh,
                in_specs=(pspecs, sspecs, P(_both), rep),
                out_specs=(pspecs, rep, rep, sspecs)
                + ((rep,) if use_numerics else ()),
                check_vma=False,
            )
        else:
            gmap = grad_map
        acts = None
        if use_numerics:
            grads, tot, tasks, new_stats, acts = gmap(
                state.params, state.batch_stats, batch, rng
            )
        else:
            grads, tot, tasks, new_stats = gmap(
                state.params, state.batch_stats, batch, rng
            )
        # chaos-test hook: exact no-op unless a fault is armed. AFTER the
        # pmean, so the poison (like the real failure it models) is
        # identical on every device and the guard decision agrees.
        grads = faultinject.poison_grads(
            grads, state.step, faultinject.lr_of(state.opt_state)
        )
        numer = None
        if use_numerics:
            # gradient stats on the reduced (and possibly poisoned) grads:
            # replicated values, so the census agrees across the mesh
            gnames, gstats = obs_numerics.grad_group_stats(grads)
            meta["grad_names"] = gnames
            numer = {"ok": step_ok(tot, grads), "act": acts, "grad": gstats}

        # The optimizer update runs OUTSIDE the shard_map, under the outer
        # jit: with replicated state this is byte-identical to an in-map
        # update; with ZeRO-1 moments (P(data) placed) XLA partitions the
        # elementwise update by the moments' sharding; with routed tables
        # decoder grads/moments stay model-sharded by propagation.
        def do_update():
            g = grads
            if pin_grads:
                # ZeRO-2 site: pinned between the pmean and the update,
                # XLA lowers the reduce+constraint pair to reduce-scatter
                g = _constrain(
                    g, table, "grads", mesh, amap, sizes,
                    default_explicit=False,
                )
            updates, opt_state = tx.update(g, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return _pin_out_params(params), opt_state

        if use_guard:
            # ok is computed from the reduced loss/grads — replicated
            # values, so the guard's select agrees across the whole mesh
            new_state = guarded_update(
                state,
                numer["ok"] if numer is not None else step_ok(tot, grads),
                do_update,
                new_stats,
            )
            # the guard's per-leaf select merges old and new params, which
            # does not preserve do_update's output constraint — re-apply
            # the output contract on the merged params or GSPMD is free to
            # leave them sharded
            if pin_params or pin_grads:
                new_state = new_state.replace(
                    params=_pin_out_params(new_state.params)
                )
        else:
            params, opt_state = do_update()
            new_state = state.replace(
                params=params,
                opt_state=opt_state,
                batch_stats=new_stats,
                step=state.step + 1,
            )
        if use_numerics:
            return new_state, tot, tasks, numer
        return new_state, tot, tasks

    # donate the incoming state so params/opt-state update in place in HBM
    jitted = jax.jit(step, donate_argnums=0)
    if not use_numerics:
        return jitted
    # numerics build: keep the jit AOT-reachable and carry the host-side
    # name tables + NaN drill-down (the diagnostic runs the replicated
    # single-device GLOBAL objective per shard row — obs/numerics.py; in
    # routed mode branch ids stay global there, so no local remap)
    return obs_numerics.numerics_step_wrapper(
        jitted, meta, model, compute_grad_energy, mixed_precision
    )


# ---------------------------------------------------------------------------
# the one eval-step builder
# ---------------------------------------------------------------------------


def make_mesh_eval_step(objective: Objective, table: R.RuleTable, mesh: Mesh):
    """Jitted (state, stacked_batch) -> (loss, tasks) under the table's
    placement — the eval twin of ``make_mesh_train_step``."""
    R.validate_table(table)
    model = objective.model
    compute_grad_energy = objective.compute_grad_energy
    mixed_precision = objective.mixed_precision
    cfg = model.cfg
    from ..train.compile_plane import note_trace

    amap, sizes, model_ax = _resolved(table, mesh)
    _both = batch_axes(mesh)
    rep = P()

    if table.routed:
        local, b_local, model_ax = _routed_model(model, table, mesh)
        lcfg = local.cfg

        def sharded_eval(params, batch_stats, batch):
            batch = jax.tree_util.tree_map(lambda x: x[0], batch)
            br = jax.lax.axis_index(model_ax)
            local_ds = jnp.clip(
                batch.dataset_id.astype(jnp.int32) - br * b_local,
                0,
                b_local - 1,
            )
            batch = batch.replace(dataset_id=local_ds)
            variables = {"params": params, "batch_stats": batch_stats}
            if mixed_precision:
                from ..train.loop import mp_cast_eval

                variables, batch = mp_cast_eval(
                    variables, batch, compute_grad_energy
                )
            tot, tasks, _, _ = compute_loss(
                local, variables, batch, lcfg, False, None,
                compute_grad_energy,
            )
            n = jnp.sum(batch.graph_mask.astype(jnp.float32))
            n_tot = jax.lax.psum(n, _both)
            scale = n * mesh.size / jnp.maximum(n_tot, 1.0)
            tot = jax.lax.pmean(tot * scale, _both)
            tasks = jax.lax.pmean(
                jax.tree_util.tree_map(lambda t: t * scale, tasks), _both
            )
            return tot, tasks

        def eval_step(state: TrainState, batch):
            note_trace("branch_eval_step", (state, batch))
            pspecs, _ = _section_specs(
                state.params, table, "params", amap, sizes
            )
            sspecs, _ = _section_specs(
                state.batch_stats, table, "batch_stats", amap, sizes
            )
            mapped = shard_map(
                sharded_eval,
                mesh=mesh,
                in_specs=(pspecs, sspecs, P(_both)),
                out_specs=(rep, rep),
                check_vma=False,
            )
            return mapped(state.params, state.batch_stats, batch)

        return jax.jit(eval_step)

    def sharded_eval(state: TrainState, batch):
        batch = jax.tree_util.tree_map(lambda x: x[0], batch)
        variables = state.variables()
        if mixed_precision:
            # keep eval numerics identical to the single-host eval step
            from ..train.loop import mp_cast_eval

            variables, batch = mp_cast_eval(
                variables, batch, compute_grad_energy
            )
        tot, tasks, _, _ = compute_loss(
            model, variables, batch, cfg, False, None, compute_grad_energy
        )
        # weight by real graphs so padded shards don't skew the mean
        n = jnp.sum(batch.graph_mask.astype(jnp.float32))
        n_tot = jax.lax.psum(n, _both)
        scale = n * mesh.size / jnp.maximum(n_tot, 1.0)
        tot = jax.lax.pmean(tot * scale, _both)
        tasks = jax.lax.pmean(
            jax.tree_util.tree_map(lambda t: t * scale, tasks), _both
        )
        return tot, tasks

    mapped = shard_map(
        sharded_eval,
        mesh=mesh,
        in_specs=(rep, P(_both)),
        out_specs=(rep, rep),
        check_vma=False,
    )

    def eval_step(state: TrainState, batch):
        note_trace("parallel_eval_step", (state, batch))
        return mapped(state, batch)

    return jax.jit(eval_step)
