"""DEPRECATION SHIM — the dp/ZeRO step builders live in the rule engine.

The bespoke data-parallel step builder this module used to hold was
retired into ``parallel/engine.py`` (ROADMAP item 1): the dp and
ZeRO-2/3 placements are now rule presets (``parallel/rules.py``) driving
the ONE mesh-step builder, with bit-identical train loss asserted in
tests/test_sharding_rules.py. These wrappers keep the historical call
signatures for existing callers (tests, run-scripts, examples); new code
uses ``engine.make_mesh_train_step(Objective(...), table, mesh)``.
"""

from __future__ import annotations

import warnings

from jax.sharding import Mesh

from ..models.base import HydraModel
from . import rules as R
from .engine import Objective, ensure_stacked  # noqa: F401  (re-export)
from .engine import make_mesh_eval_step, make_mesh_train_step


def _warn(name: str) -> None:
    warnings.warn(
        f"parallel.dp.{name} is a deprecation shim over parallel.engine; "
        "build steps via engine.make_mesh_train_step(Objective(...), "
        "rule_table, mesh) (docs/PARALLELISM.md)",
        DeprecationWarning,
        stacklevel=3,
    )


def _table(zero2: bool, zero3: bool, min_size: int) -> R.RuleTable:
    """The legacy flag pair as a rule table. Flags stay independent (a
    direct caller could ask zero3 without zero2), so the table is built
    from the flags rather than naming a preset."""
    rules = []
    if zero2:
        rules.append(
            R.Rule(
                pattern=r".*",
                axes=(R.DATA,),
                scope=("grads",),
                min_size=min_size,
                reason="ZeRO-2: gradient reduce-scatter over data",
            )
        )
    if zero3:
        rules.append(
            R.Rule(
                pattern=r".*",
                axes=(R.DATA,),
                scope=("params",),
                min_size=min_size,
                reason="ZeRO-3: params stored sharded between steps",
            )
        )
    rules.append(
        R.Rule(
            pattern=r".*",
            axes=(),
            scope=R.PLACED_SCOPES,
            reason="explicit replicated default",
        )
    )
    name = "zero3" if zero3 else ("zero2" if zero2 else "dp")
    return R.validate_table(R.RuleTable(name, tuple(rules)))


def make_parallel_train_step(
    model: HydraModel,
    tx,
    mesh: Mesh,
    compute_grad_energy: bool = False,
    mixed_precision: bool = False,
    zero2: bool = False,
    zero2_min_size: int = 1024,
    zero3: bool = False,
    guard=None,
    numerics=None,
):
    """Legacy signature -> engine: jitted (state, stacked_batch, rng) ->
    (state, loss, tasks) over ``mesh``, ZeRO flags as grads/params rules."""
    _warn("make_parallel_train_step")
    return make_mesh_train_step(
        Objective(
            model=model,
            tx=tx,
            compute_grad_energy=compute_grad_energy,
            mixed_precision=mixed_precision,
            guard=guard,
            numerics=numerics,
        ),
        _table(zero2, zero3, zero2_min_size),
        mesh,
    )


def make_parallel_eval_step(
    model: HydraModel,
    mesh: Mesh,
    compute_grad_energy: bool = False,
    mixed_precision: bool = False,
):
    _warn("make_parallel_eval_step")
    return make_mesh_eval_step(
        Objective(
            model=model,
            compute_grad_energy=compute_grad_energy,
            mixed_precision=mixed_precision,
        ),
        _table(False, False, 0),
        mesh,
    )
