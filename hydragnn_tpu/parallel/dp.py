"""SPMD data-parallel training step.

DDP-equivalent semantics on a mesh: every device holds a replica of the
params and consumes its own statically-padded micro-batch (local node/edge
indices — no cross-device gathers in message passing), gradients are
``psum``-ed over the mesh (ICI) exactly where DDP's bucketed NCCL all-reduce
sits in the reference (loss.backward() inside train(),
hydragnn/train/train_validate_test.py:534; DDP wrap distributed.py:332-351).

Implementation: ``shard_map`` over a ``(branch, data)`` mesh; the loader emits
batches with a leading device axis (``GraphLoader(num_shards=D)``), sharded
over both axes. Metrics are ``pmean``-ed in the same program — the analog of
``reduce_values_ranks`` (train_validate_test.py:382-407) at zero extra cost.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from .mesh import compat_shard_map as shard_map

from ..models.base import HydraModel
from ..train.loss import compute_loss
from ..train.state import TrainState
from .mesh import BRANCH_AXIS, DATA_AXIS

_BOTH = (BRANCH_AXIS, DATA_AXIS)


def ensure_stacked(batch):
    """Guarantee the leading device axis the shard_map steps expect.

    ``GraphLoader(num_shards=1)`` emits unstacked batches (the plain-jit
    contract); a 1-device mesh still wants ``[1, ...]``. Keeping the shim
    here keeps the [D, ...] contract in one place for every consumer.
    """
    if batch.graph_mask.ndim == 1:
        return jax.tree_util.tree_map(lambda x: jnp.asarray(x)[None], batch)
    return batch


def make_parallel_train_step(
    model: HydraModel,
    tx,
    mesh: Mesh,
    compute_grad_energy: bool = False,
    mixed_precision: bool = False,
    zero2: bool = False,
    zero2_min_size: int = 1024,
    zero3: bool = False,
    guard=None,
    numerics=None,
):
    """Jitted (state, stacked_batch, rng) -> (state, loss, tasks) over mesh.

    ``zero2=True`` shards the gradient leaves over the data axis between the
    gradient reduction and the optimizer update (ZeRO-2 analog — see
    mesh.zero2_grad_constraint); compose with ``shard_optimizer_state`` on
    the state (same ``min_size``) for the full stage-2 memory profile
    (sharded grads + moments, replicated params). ``zero3=True`` (with
    ``shard_params_zero3`` applied to the state) additionally keeps the
    UPDATED params sharded ``P(data)`` at step output — the FSDP profile:
    full params exist only transiently inside the step. ``guard`` (default
    on): non-finite step guard, computed on the pmean'd loss/gradients so
    every device and host takes the same branch (train/guard.py).
    ``numerics`` (default off; ``Telemetry.numerics``): in-graph layer/
    gradient statistics ride the step as a 4th output — activation stats
    reduce across the mesh inside the shard_map (pmax/psum), gradient
    stats are computed on the already-pmean'd grads under the outer jit
    (obs/numerics.py; same contract as train/loop.make_train_step)."""
    cfg = model.cfg
    from ..obs import numerics as obs_numerics
    from ..obs import sharding as obs_sharding
    from ..train.guard import guard_enabled, guarded_update, step_ok
    from ..utils import faultinject

    # sharding-inspector provenance: the report names the builder + mesh
    # that own the live placement (obs/sharding.py)
    obs_sharding.note_builder(
        "parallel_train_step", dict(mesh.shape), zero2=zero2, zero3=zero3,
    )
    use_guard = guard_enabled(guard)
    use_numerics = obs_numerics.numerics_enabled(numerics)
    meta = {"act_names": None, "grad_names": None}

    def per_device_loss(params, batch_stats, batch, rng):
        if mixed_precision:
            from ..train.loop import mp_cast, mp_restore_stats

            params, batch = mp_cast(params, batch, compute_grad_energy)
        variables = {"params": params, "batch_stats": batch_stats}
        (tot, tasks, mutated, _), acts = obs_numerics.run_probed(
            use_numerics, meta,
            lambda: compute_loss(
                model, variables, batch, cfg, True, rng, compute_grad_energy
            ),
        )
        if mixed_precision:
            mutated = mp_restore_stats(mutated)
        return tot.astype(jnp.float32), (tasks, mutated, acts)

    if cfg.conv_checkpointing:
        from ..ops.remat import loss_remat

        per_device_loss = loss_remat(per_device_loss, cfg.remat_policy)

    def sharded_grads(params, batch_stats, batch, rng):
        # batch leaves arrive with leading axis [D_local=1, ...] inside the
        # shard; drop it to recover the per-device batch.
        batch = jax.tree_util.tree_map(lambda x: x[0], batch)
        (tot, (tasks, mutated, acts)), grads = jax.value_and_grad(
            per_device_loss, has_aux=True
        )(params, batch_stats, batch, rng)
        # weight each shard by its real-graph count so empty/remainder shards
        # neither dilute gradients nor corrupt running batch-norm statistics
        n = jnp.sum(batch.graph_mask.astype(jnp.float32))
        n_tot = jax.lax.psum(n, _BOTH)
        scale = n * mesh.size / jnp.maximum(n_tot, 1.0)
        # gradient all-reduce over the whole mesh (DDP analog)
        grads = jax.lax.pmean(
            jax.tree_util.tree_map(lambda g: g * scale, grads), _BOTH
        )
        tot = jax.lax.pmean(tot * scale, _BOTH)
        tasks = jax.lax.pmean(
            jax.tree_util.tree_map(lambda t: t * scale, tasks), _BOTH
        )
        stats = mutated.get("batch_stats", batch_stats)
        new_stats = jax.lax.pmean(
            jax.tree_util.tree_map(lambda s: s * scale, stats), _BOTH
        )
        if use_numerics:
            # activation stats merge across the mesh with the same
            # semantics the host uses across window steps: max / sums
            acts = obs_numerics.cross_device_reduce(acts, _BOTH)
            return grads, tot, tasks, new_stats, acts
        return grads, tot, tasks, new_stats

    rep = P()
    grad_map = shard_map(
        sharded_grads,
        mesh=mesh,
        in_specs=(rep, rep, P(_BOTH), rep),
        out_specs=(rep, rep, rep, rep) + ((rep,) if use_numerics else ()),
        check_vma=False,
    )

    from ..train.compile_plane import note_trace

    def step(state: TrainState, batch, rng):
        # retrace sentinel: one execution per jit trace (compile_plane.py)
        note_trace("parallel_train_step", (state, batch, rng))
        acts = None
        if use_numerics:
            grads, tot, tasks, new_stats, acts = grad_map(
                state.params, state.batch_stats, batch, rng
            )
        else:
            grads, tot, tasks, new_stats = grad_map(
                state.params, state.batch_stats, batch, rng
            )
        # chaos-test hook: exact no-op unless a fault is armed (trace-time).
        # AFTER the pmean, so the poison (like the real failure it models)
        # is identical on every device and the guard decision agrees.
        grads = faultinject.poison_grads(
            grads, state.step, faultinject.lr_of(state.opt_state)
        )
        numer = None
        if use_numerics:
            # gradient stats on the pmean'd (and possibly poisoned) grads:
            # replicated values, so the census agrees across the mesh
            gnames, gstats = obs_numerics.grad_group_stats(grads)
            meta["grad_names"] = gnames
            numer = {"ok": step_ok(tot, grads), "act": acts, "grad": gstats}

        # The optimizer update runs OUTSIDE the shard_map, under the outer
        # jit: with replicated optimizer state this is byte-identical to the
        # old in-map update, and with ZeRO-1 state (shard_optimizer_state:
        # moment leaves NamedSharding'd P(data)) XLA partitions the
        # elementwise update by the moments' sharding — each device updates
        # only its moment slice, and the params' replicated output sharding
        # makes XLA all-gather the updates, which IS the ZeRO-1 exchange
        # (reference: ZeroRedundancyOptimizer / DeepSpeed stage 1,
        # hydragnn/utils/optimizer/optimizer.py:43-101).
        def do_update():
            g = grads
            if zero2:
                from .mesh import zero2_grad_constraint

                g = zero2_grad_constraint(g, mesh, min_size=zero2_min_size)
            updates, opt_state = tx.update(g, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            if zero3:
                # FSDP output contract: updated params leave the step
                # sharded, so the gathered full copies are transient
                # step-local buffers
                from .mesh import zero3_param_constraint

                params = zero3_param_constraint(
                    params, mesh, min_size=zero2_min_size
                )
            elif zero2:
                # pin the post-update params back to replicated: the sharded
                # updates make XLA all-gather here (the ZeRO-2 param
                # exchange) instead of falling back to full-grad replication
                # upstream
                params = jax.lax.with_sharding_constraint(
                    params, NamedSharding(mesh, P())
                )
            return params, opt_state

        if use_guard:
            # ok is computed from the pmean'd loss/grads — replicated
            # values, so the guard's select agrees across the whole mesh
            new_state = guarded_update(
                state,
                numer["ok"] if numer is not None else step_ok(tot, grads),
                do_update,
                new_stats,
            )
            # the guard's per-leaf select merges old and new params,
            # which does not preserve do_update's output constraint —
            # re-apply the ZeRO output contract on the merged params or
            # GSPMD is free to leave them sharded
            if zero3:
                from .mesh import zero3_param_constraint

                new_state = new_state.replace(
                    params=zero3_param_constraint(
                        new_state.params, mesh, min_size=zero2_min_size
                    )
                )
            elif zero2:
                new_state = new_state.replace(
                    params=jax.lax.with_sharding_constraint(
                        new_state.params, NamedSharding(mesh, P())
                    )
                )
        else:
            params, opt_state = do_update()
            new_state = state.replace(
                params=params,
                opt_state=opt_state,
                batch_stats=new_stats,
                step=state.step + 1,
            )
        if use_numerics:
            return new_state, tot, tasks, numer
        return new_state, tot, tasks

    # donate the incoming state so params/opt-state update in place in HBM
    jitted = jax.jit(step, donate_argnums=0)
    if not use_numerics:
        return jitted
    # numerics build: keep the jit AOT-reachable and carry the host-side
    # name tables + NaN drill-down (the diagnostic runs the replicated
    # single-device objective per shard row — obs/numerics.py)
    return obs_numerics.numerics_step_wrapper(
        jitted, meta, model, compute_grad_energy, mixed_precision
    )


def make_parallel_eval_step(
    model: HydraModel,
    mesh: Mesh,
    compute_grad_energy: bool = False,
    mixed_precision: bool = False,
):
    cfg = model.cfg

    def sharded_eval(state: TrainState, batch):
        batch = jax.tree_util.tree_map(lambda x: x[0], batch)
        variables = state.variables()
        if mixed_precision:
            # keep eval numerics identical to the single-host eval step
            from ..train.loop import mp_cast_eval

            variables, batch = mp_cast_eval(
                variables, batch, compute_grad_energy
            )
        tot, tasks, _, _ = compute_loss(
            model, variables, batch, cfg, False, None, compute_grad_energy
        )
        # weight by real graphs so padded shards don't skew the mean
        n = jnp.sum(batch.graph_mask.astype(jnp.float32))
        n_tot = jax.lax.psum(n, _BOTH)
        scale = n * mesh.size / jnp.maximum(n_tot, 1.0)
        tot = jax.lax.pmean(tot * scale, _BOTH)
        tasks = jax.lax.pmean(
            jax.tree_util.tree_map(lambda t: t * scale, tasks), _BOTH
        )
        return tot, tasks

    rep = P()
    mapped = shard_map(
        sharded_eval,
        mesh=mesh,
        in_specs=(rep, P(_BOTH)),
        out_specs=(rep, rep),
        check_vma=False,
    )
    from ..train.compile_plane import note_trace

    def eval_step(state: TrainState, batch):
        note_trace("parallel_eval_step", (state, batch))
        return mapped(state, batch)

    return jax.jit(eval_step)
