"""Branch-routed data feeding for the routed (branch/mp) rule tables.

The routed mesh step (parallel/engine.py, ``RuleTable.routed``) consumes
stacked batches whose shard rows are grouped by branch block: row ``r``
carries graphs of branch ``r // data_axis_size`` only, matching the
model/branch-major row order of ``mesh.batch_axes``. ``BranchRoutedLoader``
builds exactly that — one ``GraphLoader`` per branch, rows stacked in
branch-major order. Moved here from the retired parallel/branch.py
(which re-exports it for compatibility).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np


class _RowStacker:
    """Shared row-stacking machinery of the branch-routed feeders: padded
    per-row batches stacked into the leading device axis, all-padding
    filler rows for empty slots, and the memoized triplet counter the
    DimeNet ladders budget with. Subclasses provide ``graphs``,
    ``sort_edges``, ``_templates`` and ``_trip_memo``."""

    def _trip_count_of(self, g) -> int:
        from ..data.graph import _triplet_count

        got = self._trip_memo.get(id(g))
        if got is None:
            got = _triplet_count(g)
            self._trip_memo[id(g)] = got
        return got

    def _filler_arrs(self, spec):
        """One all-padding row's array dict at ``spec``: masks false,
        edges/nodes parked on the dummy slots (the GraphLoader stacked-path
        template convention, data/pipeline.stack_shard_batches)."""
        from ..data.graph import batch_graphs_np

        key = spec
        if key not in self._templates:
            g = next(
                (
                    c
                    for c in self.graphs
                    if c.num_nodes <= spec.n_nodes - 1
                    and c.num_edges <= spec.n_edges
                ),
                self.graphs[0],
            )
            arrs = batch_graphs_np([g], spec)
            z = {k: np.zeros_like(v) for k, v in arrs.items()}
            z["senders"] = np.full_like(arrs["senders"], spec.n_nodes - 1)
            z["receivers"] = z["senders"].copy()
            z["node_graph"] = np.full_like(arrs["node_graph"], spec.n_graphs - 1)
            self._templates[key] = z
        return self._templates[key]

    def _stack_rows(self, rows, spec):
        """Stack per-row padded batches (branch-major row order preserved);
        empty rows become all-padding fillers at the same spec."""
        from ..data.graph import batch_graphs_np, graph_batch_from_np

        arr_list = [
            batch_graphs_np(r, spec, sort_edges=self.sort_edges)
            if r
            else self._filler_arrs(spec)
            for r in rows
        ]
        stacked = {
            k: np.stack([a[k] for a in arr_list]) for k in arr_list[0]
        }
        return graph_batch_from_np(stacked)


class BranchRoutedLoader(_RowStacker):
    """Stacked-batch loader whose shard rows are grouped by branch block.

    Wraps one ``GraphLoader`` per branch (each over that branch's graphs,
    with ``rows = num_shards / branch_count`` device rows) and stacks their
    rows in branch-major order — matching the mesh's model/branch-major
    batch-axis flattening (parallel/mesh.py ``batch_axes``), so shard row
    ``r`` lands on mesh position ``(r // data_size, r % data_size)`` of
    the model x data grid.

    ``spec`` may be a single worst-case ``PadSpec`` (every batch padded to
    it — the pre-r10 behavior) or a ``SpecLadder``: each batch is then
    padded to the smallest level fitting its LARGEST row, so small-graph
    steps stop paying worst-case padding. Single-host only — every row of
    a batch must share one static shape, and on multi-host runs the level
    choice would have to agree across processes without a collective, so
    ``host_count > 1`` collapses the ladder to its worst level.

    The analog of the reference's per-branch datasets + uneven process
    groups (examples/multibranch/train.py:166-213).

    Batches are always full (``drop_last``) so every host steps in lockstep:
    up to ``batch_size-1`` tail graphs per branch are excluded per epoch —
    the same trade the reference's DistributedSampler makes. The epoch
    length is the MAX over branches (globally agreed); rows whose branch is
    exhausted emit all-padding batches, so uneven branch sizes neither
    truncate the larger branches' metrics nor desynchronize the collective
    step (empty rows carry zero loss weight).
    """

    def __init__(
        self,
        graphs: Sequence,
        batch_size: int,
        branch_count: int,
        num_shards: int,
        seed: int = 0,
        shuffle: bool = True,
        sort_edges: bool = False,
        oversampling: bool = True,
        host_count: int = 1,
        host_index: int = 0,
        spec=None,
    ):
        """``num_shards``/``batch_size`` are per-host (local rows / local
        graphs per step). Globally there are ``host_count * num_shards``
        rows; row ``g`` serves branch ``g // (global_rows/branch_count)``,
        so one host may serve several branches (many local rows per branch)
        or one branch may span several hosts (the sub-loader then shards its
        branch's graphs across exactly those hosts)."""
        from ..data.graph import SpecLadder
        from ..data.pipeline import GraphLoader

        L = num_shards
        G = host_count * L
        assert G % branch_count == 0, (
            f"{G} global rows not divisible by {branch_count} branches"
        )
        R = G // branch_count  # global rows per branch
        # a host's rows must not straddle a branch boundary: either whole
        # branches fit in a host (L % R == 0) or whole hosts fit in a branch
        # (R % L == 0) — otherwise per-host shards would overlap and step
        # counts diverge (deadlock in the collective train step)
        assert (R >= L and R % L == 0) or (R < L and L % R == 0), (
            f"branch rows R={R} and host rows L={L} misaligned: "
            f"host_count*local_devices ({G}) must tile branch_count "
            f"({branch_count}) without a host straddling a branch boundary"
        )
        ids = sorted({g.dataset_id for g in graphs})
        assert len(ids) == branch_count, (
            f"dataset ids {ids} != branch_count {branch_count}"
        )
        # branch of each of this host's local rows (branch-major global order)
        row_branch = [(host_index * L + r) // R for r in range(L)]
        served = sorted(set(row_branch))
        by_branch = {i: [g for g in graphs if g.dataset_id == i] for i in ids}
        n_max = max(len(b) for b in by_branch.values())
        # per-shard graph count is identical for every row by construction.
        # Callers building train/val/test loaders should pass ONE ``spec``
        # (ladder) computed over all splits so eval reuses the train step's
        # compilations.
        assert batch_size % L == 0
        per_row_bs = batch_size // L
        if spec is None:
            spec = SpecLadder.for_dataset(
                list(graphs), max(per_row_bs, 1), num_buckets=1
            )
        if not isinstance(spec, SpecLadder):
            spec = SpecLadder((spec,))
        if host_count > 1 and len(spec.specs) > 1:
            # per-batch level selection is a per-host decision; across hosts
            # the collective step needs identical global shapes, and
            # agreeing on max-over-all-hosts would cost a collective per
            # batch — multi-host keeps the worst-case single level
            spec = SpecLadder((spec.specs[-1],))
        self.ladder = spec
        spec = spec.specs[-1]  # worst case: sub-loader budget + validator cap
        self.loaders: List = []
        for b in served:
            rows_b = row_branch.count(b)  # local rows serving branch b
            hosts_b = max(R // rows_b, 1)  # hosts sharing branch b
            # this host's rank within branch b's host group
            first_global_row = b * R
            host_rank_b = (host_index * L - first_global_row) // L if hosts_b > 1 else 0
            bgraphs = by_branch[ids[b]]
            over = oversampling and len(bgraphs) < n_max
            self.loaders.append(
                GraphLoader(
                    bgraphs,
                    per_row_bs * rows_b,
                    shuffle=shuffle,
                    seed=seed + 17 * b,
                    num_shards=rows_b,
                    spec=spec,
                    sort_edges=sort_edges,
                    oversampling=over,
                    num_samples=n_max if over else None,
                    drop_last=True,
                    host_count=hosts_b,
                    host_index=host_rank_b,
                )
            )
        self.graphs = list(graphs)
        # per-graph triplet counts, memoized by id (DimeNet ladders budget
        # the triplet channel; _triplet_count is O(E) interpreted python)
        self._trip_memo: dict = {}
        self.batch_size = batch_size
        self.num_shards = L
        self.host_count = host_count
        self.host_index = host_index
        self.sort_edges = sort_edges
        self.spec = spec
        # GLOBALLY agreed step count: every host computes the same MAX over
        # ALL branches (not just the ones it serves) from the full graph
        # list — hosts serving different branches would otherwise disagree
        # on epoch length and deadlock in the collective step. Exhausted
        # branches fill their rows with all-padding batches (zero weight).
        steps = []
        for b in range(branch_count):
            nb = len(by_branch[ids[b]])
            rows_srv = min(R, L)
            hosts_b = max(R // rows_srv, 1)
            n_eff = n_max if (oversampling and nb < n_max) else nb
            steps.append((n_eff // hosts_b) // (per_row_bs * rows_srv))
        self._len = max(steps)
        self._templates: dict = {}

    def spec_template_batches(self):
        """Compile-plane warm-up templates (train/compile_plane.py): one
        stacked specialization per ladder level ANY branch can land a row
        in. Pre-r10 this was the single worst-case spec for all branches —
        warm-up then missed every smaller level a branch's batches actually
        select, and the first small-graph step of each level retraced.
        Filler rows fit any level, so the cover is the UNION of the
        per-branch selectable sets (data/pipeline.selectable_levels)."""
        from ..data.pipeline import selectable_levels

        by_level = {}
        for l in self.loaders:
            for li, g in selectable_levels(l.graphs, self.ladder):
                by_level.setdefault(li, g)
        out = []
        for li in sorted(by_level):
            spec = self.ladder.specs[li]
            rows = [[by_level[li]]] + [[] for _ in range(self.num_shards - 1)]
            out.append((spec, self._stack_rows(rows, spec)))
        return out

    def set_epoch(self, epoch: int) -> None:
        for l in self.loaders:
            l.set_epoch(epoch)

    def __len__(self) -> int:
        return self._len

    def __iter__(self) -> Iterator:
        # sub-loaders contribute their deterministic (seed, epoch) index
        # streams; rows are built HERE so one ladder level can be selected
        # per stacked batch (the smallest level fitting the largest row)
        streams = []
        for l in self.loaders:
            idx = l._local_indices()
            streams.append((l, idx, len(idx) // l.batch_size))
        for step in range(len(self)):
            rows = []
            for l, idx, n_full in streams:
                rows_b = l.num_shards
                if step < n_full:
                    sl = idx[step * l.batch_size : (step + 1) * l.batch_size]
                    graphs = [l.graphs[i] for i in sl]
                    rows.extend(graphs[s::rows_b] for s in range(rows_b))
                else:  # branch exhausted: zero-weight filler rows
                    rows.extend([] for _ in range(rows_b))
            spec = self.ladder.select(
                max((sum(g.num_nodes for g in r) for r in rows if r), default=0),
                max((sum(g.num_edges for g in r) for r in rows if r), default=0),
                max(
                    (sum(self._trip_count_of(g) for g in r) for r in rows if r),
                    default=0,
                )
                if self.spec.n_triplets
                else 0,
            )
            yield self._stack_rows(rows, spec)


class BranchRoutedMixture(_RowStacker):
    """Branch-routed mixture feeder: one ``MixturePlane`` per served branch,
    rows stacked branch-major for the routed mesh step — the mixture
    counterpart of ``BranchRoutedLoader``.

    Row geometry is identical to the loader (``L = num_shards`` local rows,
    ``G = host_count * L`` global rows, ``R = G / branch_count`` rows per
    branch, local row ``r`` serves branch ``(host_index*L + r) // R``). Each
    served branch gets a ``MixturePlane`` over that branch's sources with
    the branch's HOST GROUP as its draw stripe (``host_count = hosts_b``,
    ``host_index = host_rank_b``), so per-branch draw sequences divide
    deterministically across the hosts sharing the branch with zero
    collectives — the same purity argument as the flat multi-host mixture
    (mix/plane.py "host loss").

    Mixture sources cycle (cursors re-permute per pass), so unlike the
    loader there are no exhausted-branch filler rows: the globally agreed
    epoch length is the MAX over all branches of their draw-budget step
    count, computed from the full source list on every host.

    ``Mixture.draws_per_epoch`` is a GLOBAL budget: each branch plane gets
    an equal ``draws_per_epoch / branch_count`` share.
    """

    # loader-compat surface consumed by the loop / api
    pack = False

    def __init__(
        self,
        sources: Sequence,
        batch_size: int,
        settings: Dict[str, Any],
        branch_count: int,
        num_shards: int,
        spec=None,
        seed: int = 0,
        sort_edges: bool = False,
        validator=None,
        num_buckets: int = 1,
        host_count: int = 1,
        host_index: int = 0,
    ):
        from ..data.graph import SpecLadder
        from ..mix.plane import MixturePlane

        L = num_shards
        G = host_count * L
        assert G % branch_count == 0, (
            f"{G} global rows not divisible by {branch_count} branches"
        )
        R = G // branch_count
        assert (R >= L and R % L == 0) or (R < L and L % R == 0), (
            f"branch rows R={R} and host rows L={L} misaligned: "
            f"host_count*local_devices ({G}) must tile branch_count "
            f"({branch_count}) without a host straddling a branch boundary"
        )
        assert batch_size % L == 0
        per_row_bs = batch_size // L
        all_graphs = [g for s in sources for g in s.graphs]
        ids = sorted({g.dataset_id for g in all_graphs})
        assert len(ids) == branch_count, (
            f"dataset ids {ids} != branch_count {branch_count}"
        )
        # a mixture source feeds exactly one decoder branch (its dataset)
        by_branch: Dict[int, list] = {i: [] for i in ids}
        for s in sources:
            sids = {g.dataset_id for g in s.graphs}
            if len(sids) != 1:
                raise ValueError(
                    f"mixture source {s.name!r} spans dataset ids "
                    f"{sorted(sids)}; branch-parallel routing needs one "
                    "dataset id per source (one decoder branch each)"
                )
            by_branch[sids.pop()].append(s)
        row_branch = [(host_index * L + r) // R for r in range(L)]
        served = sorted(set(row_branch))
        base_seed = int(
            settings.get("seed") if settings.get("seed") is not None else seed
        )
        dpe = int(settings.get("draws_per_epoch", 0) or 0)
        if spec is None:
            spec = SpecLadder.for_dataset(
                all_graphs, max(per_row_bs, 1), num_buckets=max(num_buckets, 1)
            )
        if not isinstance(spec, SpecLadder):
            spec = SpecLadder((spec,))
        if host_count > 1 and len(spec.specs) > 1:
            # same rule as BranchRoutedLoader: level choice cannot agree
            # across hosts without a collective — keep the worst level
            spec = SpecLadder((spec.specs[-1],))
        self.ladder = spec
        self.spec = spec.specs[-1]
        self.planes: List[MixturePlane] = []
        self._plane_rows: List[int] = []
        self._served_ids: List[int] = []
        for b in served:
            rows_b = row_branch.count(b)
            hosts_b = max(R // rows_b, 1)
            host_rank_b = (
                (host_index * L - b * R) // L if hosts_b > 1 else 0
            )
            bsources = by_branch[ids[b]]
            bset = dict(settings)
            bset["seed"] = base_seed + 17 * b
            if dpe > 0:
                bset["draws_per_epoch"] = max(dpe // branch_count, 1)
            if settings.get("weights"):
                names = {s.name for s in bsources}
                bset["weights"] = {
                    k: v
                    for k, v in settings["weights"].items()
                    if k in names
                }
            self.planes.append(
                MixturePlane(
                    bsources,
                    per_row_bs * rows_b,
                    bset,
                    spec=self.ladder,
                    sort_edges=sort_edges,
                    validator=validator,
                    host_count=hosts_b,
                    host_index=host_rank_b,
                )
            )
            self._plane_rows.append(rows_b)
            self._served_ids.append(ids[b])
        self.graphs = all_graphs
        self.batch_size = batch_size
        self.num_shards = L
        self.host_count = host_count
        self.host_index = host_index
        self.sort_edges = sort_edges
        self.seed = base_seed
        self._trip_memo: dict = {}
        self._templates: dict = {}
        # GLOBALLY agreed step count from the FULL source list: for every
        # branch the per-step global sample take is per_row_bs * R
        # (rows_served * hosts_b == R), so hosts serving different branches
        # still agree without a collective
        steps = []
        for b in range(branch_count):
            bdpe = max(dpe // branch_count, 1) if dpe > 0 else 0
            budget = bdpe or sum(len(s.graphs) for s in by_branch[ids[b]])
            steps.append(max(budget // (per_row_bs * R), 1))
        self._len = max(steps)

    # -- loader surface ------------------------------------------------------

    @property
    def epoch(self) -> int:
        return self.planes[0].epoch

    def set_epoch(self, epoch: int) -> None:
        for p in self.planes:
            p.set_epoch(epoch)

    def __len__(self) -> int:
        return self._len

    def resume(self, epoch: int, next_batch: int) -> None:
        for p in self.planes:
            p.resume(epoch, next_batch)

    def state_dict(self, next_batch: int = 0) -> Dict[str, Any]:
        return {
            "seed": int(self.seed),
            "epoch": int(self.epoch),
            "next_batch": int(next_batch),
            "num_batches": int(len(self)),
            "mixture": self.mixture_state_dict(next_batch=int(next_batch)),
        }

    def mixture_state_dict(
        self, next_batch: Optional[int] = None
    ) -> Dict[str, Any]:
        """Per-branch snapshots keyed by dataset id, wrapped with the row
        layout that wrote them — each host persists exactly the branches it
        serves (its own sidecar restores them on the same layout)."""
        return {
            "routed": True,
            "epoch": int(self.epoch),
            "next_batch": int(next_batch) if next_batch is not None else None,
            "host_count": int(self.host_count),
            "host_index": int(self.host_index),
            "num_shards": int(self.num_shards),
            "branches": {
                str(bid): p.mixture_state_dict(next_batch=next_batch)
                for bid, p in zip(self._served_ids, self.planes)
            },
        }

    def restore_mixture(
        self, snap: Dict[str, Any], mid_epoch: bool = False
    ) -> None:
        if not snap:
            return
        if not snap.get("routed"):
            raise ValueError(
                "mixture snapshot was written by a non-routed (flat) "
                "mixture run but this run is branch-parallel routed; "
                "finish the restart on the original layout or delete the "
                "mixture sidecar to start fresh"
            )
        same_layout = (
            int(snap.get("host_count", 1)) == self.host_count
            and int(snap.get("host_index", 0)) == self.host_index
            and int(snap.get("num_shards", self.num_shards))
            == self.num_shards
        )
        if mid_epoch and not same_layout:
            raise ValueError(
                "branch-routed mixture cannot resume MID-EPOCH across a "
                f"row-layout change (snapshot host {snap.get('host_index')}"
                f"/{snap.get('host_count')} x {snap.get('num_shards')} "
                f"rows, this run host {self.host_index}/{self.host_count} "
                f"x {self.num_shards} rows): per-branch host groups would "
                "need each other's sidecars. Restart on the original "
                "layout, or drop Parallel.branch_parallel for the elastic "
                "leg — the flat multi-host mixture re-deals stripes across "
                "layout changes"
            )
        branches = snap.get("branches") or {}
        for bid, p in zip(self._served_ids, self.planes):
            sub = branches.get(str(bid))
            if sub:
                p.restore_mixture(sub, mid_epoch=mid_epoch)

    def batch_sources(self, b) -> Optional[List[int]]:
        out: set = set()
        for p in self.planes:
            got = p.batch_sources(b)
            if got:
                out.update(got)
        return sorted(out) if out else None

    def mixture_epoch_hook(self, epoch: int, tasks: Dict[str, float],
                           writer=None, verbosity: int = 0,
                           log_name: str = "run") -> None:
        for bid, p in zip(self._served_ids, self.planes):
            p.mixture_epoch_hook(
                epoch, tasks, writer=writer, verbosity=verbosity,
                log_name=f"{log_name}/branch{bid}",
            )

    def spec_template_batches(self):
        """Union of the per-branch selectable ladder levels, stacked with
        filler rows (the BranchRoutedLoader warm-up contract)."""
        from ..data.pipeline import selectable_levels

        by_level: dict = {}
        for p in self.planes:
            for li, g in selectable_levels(
                p.graphs, self.ladder, p._trip_count_of
            ):
                by_level.setdefault(li, g)
        out = []
        for li in sorted(by_level):
            spec = self.ladder.specs[li]
            rows = [[by_level[li]]] + [
                [] for _ in range(self.num_shards - 1)
            ]
            out.append((spec, self._stack_rows(rows, spec)))
        return out

    def __iter__(self) -> Iterator:
        # every plane starts at the same (possibly resumed) batch index, so
        # zip keeps them in lockstep and ends the epoch together
        gens = [p._iter_raw(self._len) for p in self.planes]
        for parts in zip(*gens):
            rows: List[list] = []
            for (_, graphs, _sids), rows_b in zip(parts, self._plane_rows):
                rows.extend(graphs[s::rows_b] for s in range(rows_b))
            spec = self.ladder.select(
                max((sum(g.num_nodes for g in r) for r in rows if r),
                    default=0),
                max((sum(g.num_edges for g in r) for r in rows if r),
                    default=0),
                max(
                    (sum(self._trip_count_of(g) for g in r)
                     for r in rows if r),
                    default=0,
                )
                if self.spec.n_triplets
                else 0,
            )
            yield self._stack_rows(rows, spec)
