"""Branch-routed data feeding for the routed (branch/mp) rule tables.

The routed mesh step (parallel/engine.py, ``RuleTable.routed``) consumes
stacked batches whose shard rows are grouped by branch block: row ``r``
carries graphs of branch ``r // data_axis_size`` only, matching the
model/branch-major row order of ``mesh.batch_axes``. ``BranchRoutedLoader``
builds exactly that — one ``GraphLoader`` per branch, rows stacked in
branch-major order. Moved here from the retired parallel/branch.py
(which re-exports it for compatibility).
"""

from __future__ import annotations

from typing import Iterator, List, Sequence

import numpy as np


class BranchRoutedLoader:
    """Stacked-batch loader whose shard rows are grouped by branch block.

    Wraps one ``GraphLoader`` per branch (each over that branch's graphs,
    with ``rows = num_shards / branch_count`` device rows) and stacks their
    rows in branch-major order — matching the mesh's model/branch-major
    batch-axis flattening (parallel/mesh.py ``batch_axes``), so shard row
    ``r`` lands on mesh position ``(r // data_size, r % data_size)`` of
    the model x data grid.

    ``spec`` may be a single worst-case ``PadSpec`` (every batch padded to
    it — the pre-r10 behavior) or a ``SpecLadder``: each batch is then
    padded to the smallest level fitting its LARGEST row, so small-graph
    steps stop paying worst-case padding. Single-host only — every row of
    a batch must share one static shape, and on multi-host runs the level
    choice would have to agree across processes without a collective, so
    ``host_count > 1`` collapses the ladder to its worst level.

    The analog of the reference's per-branch datasets + uneven process
    groups (examples/multibranch/train.py:166-213).

    Batches are always full (``drop_last``) so every host steps in lockstep:
    up to ``batch_size-1`` tail graphs per branch are excluded per epoch —
    the same trade the reference's DistributedSampler makes. The epoch
    length is the MAX over branches (globally agreed); rows whose branch is
    exhausted emit all-padding batches, so uneven branch sizes neither
    truncate the larger branches' metrics nor desynchronize the collective
    step (empty rows carry zero loss weight).
    """

    def __init__(
        self,
        graphs: Sequence,
        batch_size: int,
        branch_count: int,
        num_shards: int,
        seed: int = 0,
        shuffle: bool = True,
        sort_edges: bool = False,
        oversampling: bool = True,
        host_count: int = 1,
        host_index: int = 0,
        spec=None,
    ):
        """``num_shards``/``batch_size`` are per-host (local rows / local
        graphs per step). Globally there are ``host_count * num_shards``
        rows; row ``g`` serves branch ``g // (global_rows/branch_count)``,
        so one host may serve several branches (many local rows per branch)
        or one branch may span several hosts (the sub-loader then shards its
        branch's graphs across exactly those hosts)."""
        from ..data.graph import SpecLadder
        from ..data.pipeline import GraphLoader

        L = num_shards
        G = host_count * L
        assert G % branch_count == 0, (
            f"{G} global rows not divisible by {branch_count} branches"
        )
        R = G // branch_count  # global rows per branch
        # a host's rows must not straddle a branch boundary: either whole
        # branches fit in a host (L % R == 0) or whole hosts fit in a branch
        # (R % L == 0) — otherwise per-host shards would overlap and step
        # counts diverge (deadlock in the collective train step)
        assert (R >= L and R % L == 0) or (R < L and L % R == 0), (
            f"branch rows R={R} and host rows L={L} misaligned: "
            f"host_count*local_devices ({G}) must tile branch_count "
            f"({branch_count}) without a host straddling a branch boundary"
        )
        ids = sorted({g.dataset_id for g in graphs})
        assert len(ids) == branch_count, (
            f"dataset ids {ids} != branch_count {branch_count}"
        )
        # branch of each of this host's local rows (branch-major global order)
        row_branch = [(host_index * L + r) // R for r in range(L)]
        served = sorted(set(row_branch))
        by_branch = {i: [g for g in graphs if g.dataset_id == i] for i in ids}
        n_max = max(len(b) for b in by_branch.values())
        # per-shard graph count is identical for every row by construction.
        # Callers building train/val/test loaders should pass ONE ``spec``
        # (ladder) computed over all splits so eval reuses the train step's
        # compilations.
        assert batch_size % L == 0
        per_row_bs = batch_size // L
        if spec is None:
            spec = SpecLadder.for_dataset(
                list(graphs), max(per_row_bs, 1), num_buckets=1
            )
        if not isinstance(spec, SpecLadder):
            spec = SpecLadder((spec,))
        if host_count > 1 and len(spec.specs) > 1:
            # per-batch level selection is a per-host decision; across hosts
            # the collective step needs identical global shapes, and
            # agreeing on max-over-all-hosts would cost a collective per
            # batch — multi-host keeps the worst-case single level
            spec = SpecLadder((spec.specs[-1],))
        self.ladder = spec
        spec = spec.specs[-1]  # worst case: sub-loader budget + validator cap
        self.loaders: List = []
        for b in served:
            rows_b = row_branch.count(b)  # local rows serving branch b
            hosts_b = max(R // rows_b, 1)  # hosts sharing branch b
            # this host's rank within branch b's host group
            first_global_row = b * R
            host_rank_b = (host_index * L - first_global_row) // L if hosts_b > 1 else 0
            bgraphs = by_branch[ids[b]]
            over = oversampling and len(bgraphs) < n_max
            self.loaders.append(
                GraphLoader(
                    bgraphs,
                    per_row_bs * rows_b,
                    shuffle=shuffle,
                    seed=seed + 17 * b,
                    num_shards=rows_b,
                    spec=spec,
                    sort_edges=sort_edges,
                    oversampling=over,
                    num_samples=n_max if over else None,
                    drop_last=True,
                    host_count=hosts_b,
                    host_index=host_rank_b,
                )
            )
        self.graphs = list(graphs)
        # per-graph triplet counts, memoized by id (DimeNet ladders budget
        # the triplet channel; _triplet_count is O(E) interpreted python)
        self._trip_memo: dict = {}
        self.batch_size = batch_size
        self.num_shards = L
        self.host_count = host_count
        self.host_index = host_index
        self.sort_edges = sort_edges
        self.spec = spec
        # GLOBALLY agreed step count: every host computes the same MAX over
        # ALL branches (not just the ones it serves) from the full graph
        # list — hosts serving different branches would otherwise disagree
        # on epoch length and deadlock in the collective step. Exhausted
        # branches fill their rows with all-padding batches (zero weight).
        steps = []
        for b in range(branch_count):
            nb = len(by_branch[ids[b]])
            rows_srv = min(R, L)
            hosts_b = max(R // rows_srv, 1)
            n_eff = n_max if (oversampling and nb < n_max) else nb
            steps.append((n_eff // hosts_b) // (per_row_bs * rows_srv))
        self._len = max(steps)
        self._templates: dict = {}

    def _trip_count_of(self, g) -> int:
        from ..data.graph import _triplet_count

        got = self._trip_memo.get(id(g))
        if got is None:
            got = _triplet_count(g)
            self._trip_memo[id(g)] = got
        return got

    def _filler_arrs(self, spec):
        """One all-padding row's array dict at ``spec``: masks false,
        edges/nodes parked on the dummy slots (the GraphLoader stacked-path
        template convention, data/pipeline.py _make_stacked)."""
        from ..data.graph import batch_graphs_np

        key = spec
        if key not in self._templates:
            g = next(
                (
                    c
                    for c in self.graphs
                    if c.num_nodes <= spec.n_nodes - 1
                    and c.num_edges <= spec.n_edges
                ),
                self.graphs[0],
            )
            arrs = batch_graphs_np([g], spec)
            z = {k: np.zeros_like(v) for k, v in arrs.items()}
            z["senders"] = np.full_like(arrs["senders"], spec.n_nodes - 1)
            z["receivers"] = z["senders"].copy()
            z["node_graph"] = np.full_like(arrs["node_graph"], spec.n_graphs - 1)
            self._templates[key] = z
        return self._templates[key]

    def _stack_rows(self, rows, spec):
        """Stack per-row padded batches (branch-major row order preserved);
        empty rows become all-padding fillers at the same spec."""
        from ..data.graph import batch_graphs_np, graph_batch_from_np

        arr_list = [
            batch_graphs_np(r, spec, sort_edges=self.sort_edges)
            if r
            else self._filler_arrs(spec)
            for r in rows
        ]
        stacked = {
            k: np.stack([a[k] for a in arr_list]) for k in arr_list[0]
        }
        return graph_batch_from_np(stacked)

    def spec_template_batches(self):
        """Compile-plane warm-up templates (train/compile_plane.py): one
        stacked specialization per ladder level ANY branch can land a row
        in. Pre-r10 this was the single worst-case spec for all branches —
        warm-up then missed every smaller level a branch's batches actually
        select, and the first small-graph step of each level retraced.
        Filler rows fit any level, so the cover is the UNION of the
        per-branch selectable sets (data/pipeline.selectable_levels)."""
        from ..data.pipeline import selectable_levels

        by_level = {}
        for l in self.loaders:
            for li, g in selectable_levels(l.graphs, self.ladder):
                by_level.setdefault(li, g)
        out = []
        for li in sorted(by_level):
            spec = self.ladder.specs[li]
            rows = [[by_level[li]]] + [[] for _ in range(self.num_shards - 1)]
            out.append((spec, self._stack_rows(rows, spec)))
        return out

    def set_epoch(self, epoch: int) -> None:
        for l in self.loaders:
            l.set_epoch(epoch)

    def __len__(self) -> int:
        return self._len

    def __iter__(self) -> Iterator:
        # sub-loaders contribute their deterministic (seed, epoch) index
        # streams; rows are built HERE so one ladder level can be selected
        # per stacked batch (the smallest level fitting the largest row)
        streams = []
        for l in self.loaders:
            idx = l._local_indices()
            streams.append((l, idx, len(idx) // l.batch_size))
        for step in range(len(self)):
            rows = []
            for l, idx, n_full in streams:
                rows_b = l.num_shards
                if step < n_full:
                    sl = idx[step * l.batch_size : (step + 1) * l.batch_size]
                    graphs = [l.graphs[i] for i in sl]
                    rows.extend(graphs[s::rows_b] for s in range(rows_b))
                else:  # branch exhausted: zero-weight filler rows
                    rows.extend([] for _ in range(rows_b))
            spec = self.ladder.select(
                max((sum(g.num_nodes for g in r) for r in rows if r), default=0),
                max((sum(g.num_edges for g in r) for r in rows if r), default=0),
                max(
                    (sum(self._trip_count_of(g) for g in r) for r in rows if r),
                    default=0,
                )
                if self.spec.n_triplets
                else 0,
            )
            yield self._stack_rows(rows, spec)
