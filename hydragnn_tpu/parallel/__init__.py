from .ring_attention import ring_self_attention, sharded_global_attention
from .mesh import (
    BRANCH_AXIS,
    DATA_AXIS,
    batch_sharding,
    gather_across_hosts,
    local_host_info,
    make_mesh,
    promote_batch,
    replicate_state,
    replicated,
    setup_distributed,
    shard_batch,
    shard_optimizer_state,
    zero2_grad_constraint,
)

__all__ = [
    "BRANCH_AXIS",
    "DATA_AXIS",
    "batch_sharding",
    "gather_across_hosts",
    "ring_self_attention",
    "sharded_global_attention",
    "local_host_info",
    "make_mesh",
    "promote_batch",
    "replicate_state",
    "replicated",
    "setup_distributed",
    "shard_batch",
    "shard_optimizer_state",
    "zero2_grad_constraint",
]
