"""CGCNN (crystal graph) convolution.

(reference: hydragnn/models/CGCNNStack.py:20-113 wrapping PyG ``CGConv`` with
aggr='add', batch_norm=False; dimension-preserving, so the config pins
hidden_dim = input_dim unless GPS is on, config_utils.py:80-87.)

x_i' = x_i + sum_j sigmoid(z_ij W_f + b_f) * softplus(z_ij W_s + b_s),
z_ij = [x_i, x_j(, e_ij)].
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from ..ops.segment import segment_sum
from .base import register_conv
from .layers import hoisted_pair_dense


class CGConv(nn.Module):
    output_dim: int  # must equal input dim (dimension-preserving residual)
    edge_dim: int = 0
    sorted_agg: bool = False
    max_in_degree: int = 0

    @nn.compact
    def __call__(self, inv, equiv, batch, train: bool = False):
        # both z-projections distributed over the concat and hoisted before
        # the edge gather (node matmuls on [N, C], not [E, 2C]; same
        # function class as Dense(concat[x_i, x_j, e]))
        def z_proj(name):
            terms = (
                [(f"{name}_edge", batch.edge_attr)]
                if self.edge_dim and batch.edge_attr is not None
                else []
            )
            return hoisted_pair_dense(
                self.output_dim, inv, batch, f"{name}_recv", f"{name}_send",
                terms,
            )

        gate = nn.sigmoid(z_proj("gate"))
        core = nn.softplus(z_proj("core"))
        agg = segment_sum(gate * core, batch.receivers, batch.num_nodes,
                          batch.edge_mask, sorted_ids=self.sorted_agg,
                          max_degree=self.max_in_degree)
        return inv + agg, equiv


@register_conv("CGCNN", is_edge_model=True)
def make_cgcnn(cfg, in_dim, out_dim, last_layer):
    return CGConv(output_dim=out_dim, edge_dim=cfg.edge_dim,
                  sorted_agg=cfg.sorted_aggregation,
                  max_in_degree=cfg.max_in_degree)
