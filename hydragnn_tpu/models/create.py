"""Model factory: completed JSON config -> ``HydraModel`` + initial variables.

TPU analog of the reference factory (hydragnn/models/create.py:35-519). The
reference's giant per-model switch with PyG ``Sequential`` arg-strings is
replaced by the conv registry (models/base.py): each model file registers a
constructor; everything else (heads, GPS wrapping, checkpointing) is uniform
in ``HydraModel``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..data.graph import GraphBatch
from .base import (
    GraphHeadConfig,
    HydraModel,
    ModelConfig,
    NodeHeadConfig,
    conv_registry,
)

# import model files for their registry side effects
from . import cgcnn as _cgcnn  # noqa: F401
from . import dimenet as _dimenet  # noqa: F401
from . import egnn as _egnn  # noqa: F401
from . import gat as _gat  # noqa: F401
from . import gin as _gin  # noqa: F401
from . import mfc as _mfc  # noqa: F401
from . import painn as _painn  # noqa: F401
from . import pna as _pna  # noqa: F401
from . import pna_eq as _pna_eq  # noqa: F401
from . import pna_plus as _pna_plus  # noqa: F401
from . import sage as _sage  # noqa: F401
from . import schnet as _schnet  # noqa: F401


def normalize_output_heads(heads: Dict[str, Any]) -> Dict[str, List[Dict[str, Any]]]:
    """Upgrade legacy single-branch head configs to the multibranch list form
    (reference: update_multibranch_heads, hydragnn/utils/model/model.py:152-187)."""
    out: Dict[str, List[Dict[str, Any]]] = {}
    for key, val in heads.items():
        if isinstance(val, list):
            out[key] = val
        else:
            out[key] = [{"type": "branch-0", "architecture": dict(val)}]
    return out


def num_branches_from(arch: Dict[str, Any]) -> int:
    """Branch count as the model factory derives it (list-form graph heads;
    single source of truth for loader routing and model construction)."""
    heads = normalize_output_heads(arch.get("output_heads", {}))
    return len(heads["graph"]) if "graph" in heads else 1


def model_config_from(config: Dict[str, Any]) -> ModelConfig:
    """Build the frozen ModelConfig from a *completed* config dict
    (i.e. after ``hydragnn_tpu.config.update_config``)."""
    nn_cfg = config["NeuralNetwork"]
    arch = nn_cfg["Architecture"]
    training = nn_cfg["Training"]
    var = nn_cfg["Variables_of_interest"]

    heads = normalize_output_heads(arch["output_heads"])
    graph_head = None
    node_head = None
    num_branches = 1
    if "graph" in heads:
        num_branches = len(heads["graph"])
        a = heads["graph"][0]["architecture"]
        graph_head = GraphHeadConfig(
            num_sharedlayers=a.get("num_sharedlayers", 2),
            dim_sharedlayers=a.get("dim_sharedlayers", 10),
            num_headlayers=a.get("num_headlayers", 2),
            dim_headlayers=tuple(a.get("dim_headlayers", (10, 10))),
        )
    if "node" in heads:
        a = heads["node"][0]["architecture"]
        node_head = NodeHeadConfig(
            nn_type=a.get("type", "mlp"),
            num_headlayers=a.get("num_headlayers", 2),
            dim_headlayers=tuple(a.get("dim_headlayers", (10, 10))),
        )

    loss_type = training.get("loss_function_type", "mse")
    return ModelConfig(
        mpnn_type=arch["mpnn_type"],
        input_dim=int(arch["input_dim"]),
        hidden_dim=int(arch["hidden_dim"]),
        num_conv_layers=int(arch["num_conv_layers"]),
        output_names=tuple(var["output_names"]),
        output_dim=tuple(int(d) for d in arch["output_dim"]),
        output_type=tuple(arch["output_type"]),
        task_weights=tuple(float(w) for w in arch["task_weights"]),
        graph_head=graph_head,
        node_head=node_head,
        num_branches=num_branches,
        branch_loss_weights=(
            tuple(float(w) for w in arch["branch_loss_weights"])
            if arch.get("branch_loss_weights")
            else None
        ),
        branch_loss_metrics=bool(arch.get("branch_loss_metrics", False)),
        activation=arch.get("activation_function", "relu"),
        loss_function_type=loss_type,
        global_attn_engine=arch.get("global_attn_engine") or "",
        global_attn_type=arch.get("global_attn_type") or "",
        global_attn_heads=int(arch.get("global_attn_heads") or 0),
        pe_dim=int(arch.get("pe_dim") or 0),
        max_nodes_per_graph=int(arch.get("max_nodes_per_graph") or 0),
        use_flash_attention=bool(arch.get("use_flash_attention", False)),
        # `or 0.25` would turn an intentional 0.0 into the default; only
        # null/absent falls back (the GPSConv/attention dropout rate —
        # bench's GPS A/B cells pin it 0 so the attention route is the
        # only moving part)
        dropout=float(
            0.25 if arch.get("dropout") is None else arch["dropout"]
        ),
        edge_dim=int(arch.get("edge_dim") or 0),
        radius=arch.get("radius"),
        num_gaussians=arch.get("num_gaussians"),
        num_filters=arch.get("num_filters"),
        num_radial=arch.get("num_radial"),
        num_spherical=arch.get("num_spherical"),
        envelope_exponent=arch.get("envelope_exponent"),
        radial_type=arch.get("radial_type"),
        distance_transform=arch.get("distance_transform"),
        basis_emb_size=arch.get("basis_emb_size"),
        int_emb_size=arch.get("int_emb_size"),
        out_emb_size=arch.get("out_emb_size"),
        num_before_skip=arch.get("num_before_skip"),
        num_after_skip=arch.get("num_after_skip"),
        pna_deg=tuple(arch.get("pna_deg") or ()),
        avg_num_neighbors=arch.get("avg_num_neighbors"),
        max_ell=arch.get("max_ell"),
        node_max_ell=arch.get("node_max_ell"),
        correlation=arch.get("correlation"),
        equivariance=bool(arch.get("equivariance", False)),
        num_nodes=arch.get("num_nodes"),
        var_output=loss_type == "GaussianNLLLoss",
        conv_checkpointing=bool(training.get("conv_checkpointing", False)),
        remat_policy=str(training.get("remat_policy", "full")),
        freeze_conv_layers=bool(arch.get("freeze_conv_layers", False)),
        sorted_aggregation=bool(arch.get("use_sorted_aggregation", False)),
        max_in_degree=int(arch.get("max_in_degree") or 0),
        fused_edge_kernel=bool(arch.get("use_fused_edge_kernel", False)),
        decoder_mirror_init=bool(
            True if arch.get("decoder_mirror_init") is None
            else arch["decoder_mirror_init"]
        ),
        # `or 0.1` would turn an intentional 0.0 into 0.1; only null/absent
        # falls back to the default
        decoder_recovery_slope=float(
            0.1 if arch.get("decoder_recovery_slope") is None
            else arch["decoder_recovery_slope"]
        ),
        initial_bias=arch.get("initial_bias"),
        periodic_boundary_conditions=bool(arch.get("periodic_boundary_conditions", False)),
        max_neighbours=arch.get("max_neighbours"),
    )


def create_model(config: Dict[str, Any]):
    """Completed config dict -> flax model (reference: create_model_config,
    create.py:35-82). MACE gets its own module class because its n-body
    per-layer readout structure replaces the shared encoder/decoder split
    (reference: create.py:473-512 -> MACEStack)."""
    cfg = model_config_from(config)
    if cfg.mpnn_type == "MACE":
        from .mace import MACEModel

        assert cfg.radius is not None, "MACE requires radius"
        assert cfg.num_radial is not None, "MACE requires num_radial"
        assert (cfg.max_ell or 0) >= 1, "MACE requires max_ell >= 1"
        assert (cfg.node_max_ell or 0) >= 1, "MACE requires node_max_ell >= 1"
        assert not cfg.use_global_attn, (
            "GPS global attention is not supported with MACE"
        )
        return MACEModel(cfg=cfg)
    return HydraModel(cfg=cfg)


def init_model(
    model: HydraModel, sample_batch: GraphBatch, seed: int = 0
) -> Dict[str, Any]:
    """Initialize variables deterministically (reference seeds construction
    with torch.manual_seed(0), create.py:131)."""
    rngs = {"params": jax.random.PRNGKey(seed), "dropout": jax.random.PRNGKey(seed + 1)}
    return model.init(rngs, sample_batch, train=False)


def available_models() -> Tuple[str, ...]:
    return conv_registry() + ("MACE",)
