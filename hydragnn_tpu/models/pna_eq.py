"""PNAEq: equivariant PNA (PaiNN-style vector channel + PNA scalar aggregation).

TPU re-design of the reference's PNAEqStack (hydragnn/models/PNAEqStack.py:
224-493): scalar messages go through PNA pre-MLP + degree-scaler aggregation,
gated by a Bessel radial projection split three ways (scalar message / vector
gate / edge-vector gate); vector messages aggregate by sum; a PaiNN update
block follows.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from ..ops.radial import bessel_basis_enveloped, edge_vectors
from ..ops.segment import segment_sum
from .base import register_conv
from .layers import MLP, hoisted_pair_dense
from .painn import _vector_state, painn_update
from .pna import pna_aggregate


class PNAEqConv(nn.Module):
    node_size: int
    deg_hist: tuple
    num_radial: int
    radius: float
    edge_dim: int = 0
    last_layer: bool = False
    sorted_agg: bool = False
    max_in_degree: int = 0
    # multi-output fused aggregation (cfg.fused_edge_kernel): the scalar
    # message here is post-MLP/post-gate (not factorable into the kernel's
    # in-kernel gather), so [E, C] exists once — but the four aggregation
    # moments still fuse into ONE pass over it instead of four separate
    # segment reductions re-reading it (ops/pallas_multi_agg.py)
    multi_agg: bool = False
    remat_policy: str = "full"

    @nn.compact
    def __call__(self, inv, equiv, batch, train: bool = False):
        n = batch.num_nodes
        x = inv
        if x.shape[-1] != self.node_size:
            x = nn.Dense(self.node_size, name="x_proj")(x)
        v = _vector_state(equiv, n, self.node_size)

        vec, length = edge_vectors(batch.pos, batch.senders, batch.receivers,
                                   batch.edge_shifts)
        r = length[:, 0]
        unit = vec / length
        rbf = bessel_basis_enveloped(r, self.radius, self.num_radial)

        # pre-MLP over [x_i, x_j, rbf_emb(, edge)] (PNAEqStack.py:268-344),
        # distributed over the concat and hoisted before the edge gather
        # (node matmuls on [N, C], not [E, 2C]; same function class)
        terms = [("pre_rbf", nn.tanh(nn.Dense(self.node_size)(rbf)))]
        if self.edge_dim and batch.edge_attr is not None:
            terms.append(("pre_attr", nn.Dense(self.node_size)(batch.edge_attr)))
        msg = hoisted_pair_dense(
            self.node_size, x, batch, "pre_recv", "pre_send", terms
        )
        msg = MLP((self.node_size, self.node_size, 3 * self.node_size),
                  "silu")(nn.tanh(msg))
        # Hadamard with rbf projection, then split for scalar/vector duty
        msg = msg * nn.Dense(3 * self.node_size, use_bias=False)(rbf)
        gate_v, gate_edge, msg_s = jnp.split(msg, 3, axis=-1)

        msg_v = v[batch.senders] * gate_v[:, None, :]
        msg_v = msg_v + gate_edge[:, None, :] * unit[:, :, None]
        v = v + segment_sum(msg_v, batch.receivers, n, batch.edge_mask)

        # PNA aggregation of scalar messages (aggregators x scalers)
        scaled = pna_aggregate(msg_s, batch, self.deg_hist,
                               self.sorted_agg, self.max_in_degree,
                               multi_agg=self.multi_agg,
                               remat_policy=self.remat_policy)
        delta = nn.Dense(self.node_size)(jnp.concatenate([x, scaled], axis=-1))
        x = x + delta

        # PaiNN-style update block (PNAEqStack.py:400-470)
        x, v = painn_update(x, v, self.node_size, self.last_layer)
        return x, v


@register_conv("PNAEq", is_edge_model=True)
def make_pna_eq(cfg, in_dim, out_dim, last_layer):
    return PNAEqConv(
        node_size=out_dim,
        deg_hist=cfg.pna_deg,
        num_radial=cfg.num_radial or 5,
        radius=cfg.radius or 5.0,
        edge_dim=cfg.edge_dim,
        last_layer=last_layer,
        sorted_agg=cfg.sorted_aggregation,
        max_in_degree=cfg.max_in_degree,
        multi_agg=cfg.fused_edge_kernel,
        remat_policy=cfg.remat_policy,
    )
