"""PaiNN: polarizable atom interaction network.

TPU re-design of the reference's PAINNStack (hydragnn/models/PAINNStack.py:
194-343). Each conv layer = message block (sinc radial filter x cosine cutoff
gating scalar MLP; vector messages mix neighbor vectors and unit edge vectors)
followed by an update block (U/V channel mixings, gated scalar/vector
residuals).

State threading: scalar features ride the ``inv`` slot; per-node vector
features [N, 3, F] ride the ``equiv`` slot. The first layer receives positions
[N, 3] there and bootstraps v = 0 (the reference does the same in its
``_embedding``, PAINNStack.py:190).
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from ..ops.radial import cosine_cutoff, edge_vectors, sinc_expansion
from ..ops.segment import segment_sum
from .base import register_conv
from .layers import MLP


def _vector_state(equiv, n, features):
    """equiv slot -> [N, 3, F] vector features (bootstrapping from pos)."""
    if equiv is None or equiv.ndim == 2:
        return jnp.zeros((n, 3, features))
    if equiv.shape[-1] != features:
        # equivariant channel mixing to the layer's width
        return nn.Dense(features, use_bias=False, name="v_proj")(equiv)
    return equiv


def painn_update(x, v, node_size, last_layer):
    """PaiNN update block: U/V channel mixings, gated scalar/vector residuals
    (reference: PainnUpdate, PAINNStack.py:266-316). On the last layer only
    the scalar stream is updated. Shared by PAINN and PNAEq. Must be called
    from inside a ``@nn.compact`` ``__call__``."""
    uv = nn.Dense(node_size, use_bias=False)(v)
    vv = nn.Dense(node_size, use_bias=False)(v)
    vv_norm = jnp.sqrt(jnp.sum(vv * vv, axis=1) + 1e-12)
    widths = 2 if last_layer else 3
    out = MLP((node_size, widths * node_size), "silu")(
        jnp.concatenate([vv_norm, x], axis=-1)
    )
    inner = jnp.sum(uv * vv, axis=1)
    # residual clamp: the scalar/vector PRODUCT streams can overflow f32
    # when eval-mode batch-norm statistics are still stale (early epochs) —
    # inf - inf then poisons everything downstream as NaN. The reference
    # guards its own product stream the same way ("just in case it
    # explodes", torch.clamp in SCFStack.py:248-250); 1e6 never activates
    # in healthy training (values are O(10)).
    _clamp = lambda t: jnp.clip(t, -1e6, 1e6)
    if last_layer:
        a_sv, a_ss = jnp.split(out, 2, axis=-1)
        return x + _clamp(a_sv * inner + a_ss), v
    a_vv, a_sv, a_ss = jnp.split(out, 3, axis=-1)
    return (
        x + _clamp(a_sv * inner + a_ss),
        v + _clamp(a_vv[:, None, :] * uv),
    )


class PainnConv(nn.Module):
    node_size: int
    num_radial: int
    radius: float
    edge_dim: int = 0
    last_layer: bool = False
    sorted_agg: bool = False
    max_in_degree: int = 0

    @nn.compact
    def __call__(self, inv, equiv, batch, train: bool = False):
        n = batch.num_nodes
        x = inv
        if x.shape[-1] != self.node_size:
            x = nn.Dense(self.node_size, name="x_proj")(x)
        v = _vector_state(equiv, n, self.node_size)

        vec, length = edge_vectors(batch.pos, batch.senders, batch.receivers,
                                   batch.edge_shifts)
        r = length[:, 0]
        unit = vec / length

        # ---- message block (PainnMessage, PAINNStack.py:194-264)
        filt = nn.Dense(3 * self.node_size)(
            sinc_expansion(r, self.radius, self.num_radial)
        )
        filt = filt * cosine_cutoff(r, self.radius)[:, None]
        if self.edge_dim and batch.edge_attr is not None:
            filt = filt * MLP((self.node_size, 3 * self.node_size), "silu")(
                batch.edge_attr
            )
        scal = MLP((self.node_size, 3 * self.node_size), "silu")(x)
        filter_out = filt * scal[batch.senders]
        gate_v, gate_edge, msg_s = jnp.split(filter_out, 3, axis=-1)

        msg_v = v[batch.senders] * gate_v[:, None, :]
        msg_v = msg_v + gate_edge[:, None, :] * unit[:, :, None]

        x = x + segment_sum(msg_s, batch.receivers, n, batch.edge_mask,
                            sorted_ids=self.sorted_agg,
                            max_degree=self.max_in_degree)
        v = v + segment_sum(msg_v, batch.receivers, n, batch.edge_mask)

        x, v = painn_update(x, v, self.node_size, self.last_layer)
        return x, v


@register_conv("PAINN", is_edge_model=True)
def make_painn(cfg, in_dim, out_dim, last_layer):
    return PainnConv(
        node_size=out_dim,
        num_radial=cfg.num_radial or 20,
        radius=cfg.radius or 5.0,
        edge_dim=cfg.edge_dim,
        last_layer=last_layer,
        sorted_agg=cfg.sorted_aggregation,
        max_in_degree=cfg.max_in_degree,
    )
