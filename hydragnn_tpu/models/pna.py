"""Principal Neighbourhood Aggregation convolution.

(reference: hydragnn/models/PNAStack.py:19-71 wrapping PyG ``PNAConv`` with
aggregators [mean, min, max, std], scalers [identity, amplification,
attenuation, linear], degree histogram from the dataset, pre_layers=1,
post_layers=1, towers=1, divide_input=False.)

Message: pre-MLP over [x_i, x_j(, edge)] -> aggregate 4 ways -> scale by 3
degree scalers (+identity) -> post-MLP over [x_i, scaled] -> out.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax.numpy as jnp
from flax import linen as nn

from ..ops.segment import (
    segment_count,
    segment_max,
    segment_mean,
    segment_min,
    segment_std,
)
from .base import register_conv
from .layers import hoisted_pair_dense


def _avg_deg_stats(deg_hist: Tuple[int, ...]) -> Tuple[float, float]:
    """(avg_log_deg, avg_lin_deg) from the dataset degree histogram, the
    normalizers PyG precomputes from ``deg``."""
    if not deg_hist:
        return 1.0, 1.0
    total = float(sum(deg_hist)) or 1.0
    avg_log = sum(n * math.log(d + 1) for d, n in enumerate(deg_hist)) / total
    avg_lin = sum(n * d for d, n in enumerate(deg_hist)) / total
    return max(avg_log, 1e-6), max(avg_lin, 1e-6)


def pna_aggregate(msg, batch, deg_hist, sorted_agg=False, max_in_degree=0):
    """PNA aggregate-and-scale: [mean,min,max,std] aggregation x
    [identity, amplification, attenuation, linear] degree scalers.
    Shared by PNA / PNAPlus / PNAEq (reference: DegreeScalerAggregation)."""
    n = batch.num_nodes
    aggs = [
        segment_mean(msg, batch.receivers, n, batch.edge_mask,
                     sorted_ids=sorted_agg, max_degree=max_in_degree),
        segment_min(msg, batch.receivers, n, batch.edge_mask),
        segment_max(msg, batch.receivers, n, batch.edge_mask),
        segment_std(msg, batch.receivers, n, batch.edge_mask),
    ]
    agg = jnp.concatenate(aggs, axis=-1)
    avg_log, avg_lin = _avg_deg_stats(deg_hist)
    deg = segment_count(batch.receivers, n, batch.edge_mask)[:, None]
    log_deg = jnp.log(deg + 1.0)
    return jnp.concatenate(
        [agg, agg * (log_deg / avg_log),
         agg * (avg_log / jnp.maximum(log_deg, 1e-6)),
         agg * (deg / avg_lin)],
        axis=-1,
    )


class PNAConv(nn.Module):
    output_dim: int
    deg_hist: Tuple[int, ...]
    edge_dim: int = 0
    sorted_agg: bool = False
    max_in_degree: int = 0

    @nn.compact
    def __call__(self, inv, equiv, batch, train: bool = False):
        # pre-MLP (pre_layers=1) as a matmul-before-gather layer
        # (layers.hoisted_pair_dense; reference post-concat: PNAStack.py)
        f_in = inv.shape[-1]
        terms = (
            [("pre_edge", batch.edge_attr)]
            if self.edge_dim and batch.edge_attr is not None
            else []
        )
        msg = hoisted_pair_dense(f_in, inv, batch, "pre_recv", "pre_send", terms)

        # NOT fused into the gather->dense->segment-sum Pallas kernel
        # (cfg.fused_edge_kernel, layers.fused_pair_dense_sum): PNA's
        # messages are multiply-consumed — max/min/std need the full [E, C]
        # message array in HBM regardless, so fusing the sum component
        # would add kernel FLOPs without removing any memory traffic. The
        # mean's underlying segment sums still ride the sorted Pallas
        # route (pna_aggregate -> ops/segment.py).
        scaled = pna_aggregate(msg, batch, self.deg_hist,
                               self.sorted_agg, self.max_in_degree)
        # post-MLP, post_layers=1, then final linear projection
        out = nn.Dense(self.output_dim)(jnp.concatenate([inv, scaled], axis=-1))
        out = nn.Dense(self.output_dim)(out)
        return out, equiv


@register_conv("PNA", is_edge_model=True)
def make_pna(cfg, in_dim, out_dim, last_layer):
    return PNAConv(output_dim=out_dim, deg_hist=cfg.pna_deg,
                   edge_dim=cfg.edge_dim, sorted_agg=cfg.sorted_aggregation,
                   max_in_degree=cfg.max_in_degree)
