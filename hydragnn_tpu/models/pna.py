"""Principal Neighbourhood Aggregation convolution.

(reference: hydragnn/models/PNAStack.py:19-71 wrapping PyG ``PNAConv`` with
aggregators [mean, min, max, std], scalers [identity, amplification,
attenuation, linear], degree histogram from the dataset, pre_layers=1,
post_layers=1, towers=1, divide_input=False.)

Message: pre-MLP over [x_i, x_j(, edge)] -> aggregate 4 ways -> scale by 3
degree scalers (+identity) -> post-MLP over [x_i, scaled] -> out.

The message is kept FACTORED at the call sites — receiver projection
node-sized ([N, C], never gathered by the model), sender projection + edge
terms as one edge-aligned operand — so the multi-output moment kernel
(ops/pallas_multi_agg.py, routed by ``pna_aggregate`` below when
``use_fused_edge_kernel`` rides sorted aggregation) can run the receiver
gather in-kernel and emit all four aggregation moments in one pass: the
[E, C] messages never round-trip HBM. The dense spelling (gather + four
segment reductions) stays as the oracle and the fallback.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax.numpy as jnp
from flax import linen as nn

from ..ops.remat import kernel_remat, tag as remat_tag
from ..ops.segment import (
    multi_moment_agg,
    segment_count,
    segment_max,
    segment_mean,
    segment_min,
    segment_std,
)
from .base import register_conv
from .layers import pair_message_factored


def _avg_deg_stats(deg_hist: Tuple[int, ...]) -> Tuple[float, float]:
    """(avg_log_deg, avg_lin_deg) from the dataset degree histogram, the
    normalizers PyG precomputes from ``deg``."""
    if not deg_hist:
        return 1.0, 1.0
    total = float(sum(deg_hist)) or 1.0
    avg_log = sum(n * math.log(d + 1) for d, n in enumerate(deg_hist)) / total
    avg_lin = sum(n * d for d, n in enumerate(deg_hist)) / total
    return max(avg_log, 1e-6), max(avg_lin, 1e-6)


def pna_pre_message(dim, inv, batch, edge_terms=()):
    """PNA's pre-MLP (pre_layers=1) in FACTORED form
    (layers.pair_message_factored — the one spelling of the
    recv-bias/send-no-bias convention): the receiver projection stays
    node-sized ([N, C] — gathered in-kernel by the fused route, or by
    ``pna_aggregate``'s dense branch), the sender projection and the
    edge-local terms collapse into one edge-aligned operand. Same
    parameter names and tree as the old ``hoisted_pair_dense`` spelling,
    so checkpoints are interchangeable."""
    return pair_message_factored(
        dim, inv, batch, "pre_recv", "pre_send", edge_terms
    )


def pna_aggregate(msg, batch, deg_hist, sorted_agg=False, max_in_degree=0,
                  node_recv=None, gate=None, multi_agg=False,
                  remat_policy="full"):
    """PNA aggregate-and-scale: [mean,min,max,std] aggregation x
    [identity, amplification, attenuation, linear] degree scalers.
    Shared by PNA / PNAPlus / PNAEq (reference: DegreeScalerAggregation).

    The per-edge message is ``(node_recv[recv] + msg) * gate`` with
    ``node_recv``/``gate`` optional. With ``multi_agg`` (the
    ``use_fused_edge_kernel`` route) on a sorted, degree-bounded batch,
    all four aggregators derive from ONE fused multi-moment pass
    (ops/segment.py ``multi_moment_agg`` -> ops/pallas_multi_agg.py):
    mean = sum/count, std via the zero-clamped E[x²]−E[x]² form — the
    same guard ``segment_std`` applies — and the op is remat-wrapped per
    ``remat_policy`` so the backward recomputes the messages instead of
    storing [E, C] residuals. Otherwise the dense oracle runs: gather +
    the four masked segment reductions, exactly the historical spelling.
    """
    n = batch.num_nodes
    if multi_agg and sorted_agg and max_in_degree > 0:
        def moments(edge_in, nrecv, g):
            return remat_tag(multi_moment_agg(
                edge_in, batch.receivers, n, node_recv=nrecv, gate=g,
                sorted_ids=True, max_degree=max_in_degree,
            ), "multi_agg_moments")

        s, cnt, mn, mx, ssq = kernel_remat(moments, remat_policy)(
            msg, node_recv, gate
        )
        cnt1 = jnp.maximum(cnt, 1.0)[:, None]
        mean = s / cnt1
        var = jnp.maximum(ssq / cnt1 - mean**2, 0.0)
        std = jnp.sqrt(var + 1e-5)
        aggs = [a.astype(msg.dtype) for a in (mean, mn, mx, std)]
        deg = cnt[:, None]
    else:
        if node_recv is not None:
            msg = node_recv[batch.receivers] + msg
        if gate is not None:
            msg = msg * gate
        aggs = [
            segment_mean(msg, batch.receivers, n, batch.edge_mask,
                         sorted_ids=sorted_agg, max_degree=max_in_degree),
            segment_min(msg, batch.receivers, n, batch.edge_mask),
            segment_max(msg, batch.receivers, n, batch.edge_mask),
            segment_std(msg, batch.receivers, n, batch.edge_mask),
        ]
        deg = segment_count(batch.receivers, n, batch.edge_mask)[:, None]
    agg = jnp.concatenate(aggs, axis=-1)
    avg_log, avg_lin = _avg_deg_stats(deg_hist)
    log_deg = jnp.log(deg + 1.0)
    return jnp.concatenate(
        [agg, agg * (log_deg / avg_log),
         agg * (avg_log / jnp.maximum(log_deg, 1e-6)),
         agg * (deg / avg_lin)],
        axis=-1,
    )


class PNAConv(nn.Module):
    output_dim: int
    deg_hist: Tuple[int, ...]
    edge_dim: int = 0
    sorted_agg: bool = False
    max_in_degree: int = 0
    # multi-output fused aggregation (cfg.fused_edge_kernel): one Pallas
    # pass emits (sum, count, min, max, sumsq) per node — the r6 "four
    # consumers need [E, C] in HBM" decision record is retired
    multi_agg: bool = False
    remat_policy: str = "full"

    @nn.compact
    def __call__(self, inv, equiv, batch, train: bool = False):
        # pre-MLP (pre_layers=1), factored: node-sized receiver projection
        # + one edge-aligned operand (pna_pre_message; reference computes
        # the same layer post-concat, PNAStack.py)
        f_in = inv.shape[-1]
        terms = (
            [("pre_edge", batch.edge_attr)]
            if self.edge_dim and batch.edge_attr is not None
            else []
        )
        node_recv, edge_in = pna_pre_message(f_in, inv, batch, terms)

        scaled = pna_aggregate(
            edge_in, batch, self.deg_hist, self.sorted_agg,
            self.max_in_degree, node_recv=node_recv,
            multi_agg=self.multi_agg, remat_policy=self.remat_policy,
        )
        # post-MLP, post_layers=1, then final linear projection
        out = nn.Dense(self.output_dim)(jnp.concatenate([inv, scaled], axis=-1))
        out = nn.Dense(self.output_dim)(out)
        return out, equiv


@register_conv("PNA", is_edge_model=True)
def make_pna(cfg, in_dim, out_dim, last_layer):
    return PNAConv(output_dim=out_dim, deg_hist=cfg.pna_deg,
                   edge_dim=cfg.edge_dim, sorted_agg=cfg.sorted_aggregation,
                   max_in_degree=cfg.max_in_degree,
                   multi_agg=cfg.fused_edge_kernel,
                   remat_policy=cfg.remat_policy)
