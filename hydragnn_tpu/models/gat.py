"""GATv2 convolution.

(reference: hydragnn/models/GATStack.py:20-208 wrapping PyG ``GATv2Conv``;
factory hardcodes heads=6, negative_slope=0.05, create.py:220-222. Hidden
layers concatenate heads (width hidden*heads); the final layer averages heads,
GATStack._init_conv.)

GATv2 attention: e_ij = a^T LeakyReLU(W_l x_i + W_r x_j (+ W_e e_ij)),
alpha = softmax_i(e_ij), out_i = sum_j alpha_ij (W_r x_j).
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from ..ops.segment import segment_softmax, segment_sum
from .base import register_conv


class GATv2Conv(nn.Module):
    output_dim: int
    heads: int = 6
    concat: bool = True
    negative_slope: float = 0.05
    edge_dim: int = 0
    sorted_agg: bool = False
    max_in_degree: int = 0

    @nn.compact
    def __call__(self, inv, equiv, batch, train: bool = False):
        H, C = self.heads, self.output_dim
        x_l = nn.Dense(H * C)(inv).reshape(-1, H, C)  # target/query side
        x_r = nn.Dense(H * C)(inv).reshape(-1, H, C)  # source/value side
        g = x_l[batch.receivers] + x_r[batch.senders]
        if self.edge_dim and batch.edge_attr is not None:
            g = g + nn.Dense(H * C)(batch.edge_attr).reshape(-1, H, C)
        g = nn.leaky_relu(g, negative_slope=self.negative_slope)
        att = self.param("att", nn.initializers.glorot_uniform(), (1, H, C))
        logits = jnp.sum(g * att, axis=-1)  # [E, H]
        alpha = segment_softmax(
            logits, batch.receivers, batch.num_nodes, batch.edge_mask
        )
        msg = x_r[batch.senders] * alpha[..., None]  # [E, H, C]
        # flatten heads so the 2-D sorted-segment kernel can take the sum
        out = segment_sum(
            msg.reshape(-1, H * C), batch.receivers, batch.num_nodes,
            batch.edge_mask, sorted_ids=self.sorted_agg,
            max_degree=self.max_in_degree,
        ).reshape(-1, H, C)
        if self.concat:
            return out.reshape(-1, H * C), equiv
        return out.mean(axis=1), equiv


@register_conv("GAT", is_edge_model=True)
def make_gat(cfg, in_dim, out_dim, last_layer):
    # last conv averages heads (concat=False), hidden convs concatenate
    # (reference: GATStack._init_conv, GATStack.py:117-175)
    return GATv2Conv(
        output_dim=out_dim,
        heads=6,
        concat=not last_layer,
        negative_slope=0.05,
        edge_dim=cfg.edge_dim,
        sorted_agg=cfg.sorted_aggregation,
        max_in_degree=cfg.max_in_degree,
    )
