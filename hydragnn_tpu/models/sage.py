"""GraphSAGE convolution (reference: hydragnn/models/SAGEStack.py:18-53).

x_i' = W_root x_i + W_neigh mean_{j in N(i)} x_j  (PyG SAGEConv defaults:
mean aggregation, root weight, bias on the root projection).
"""

from __future__ import annotations

from flax import linen as nn

from ..ops.segment import segment_mean
from .base import register_conv


class SAGEConv(nn.Module):
    output_dim: int
    sorted_agg: bool = False
    max_in_degree: int = 0

    @nn.compact
    def __call__(self, inv, equiv, batch, train: bool = False):
        agg = segment_mean(
            inv[batch.senders], batch.receivers, batch.num_nodes,
            batch.edge_mask, sorted_ids=self.sorted_agg,
            max_degree=self.max_in_degree,
        )
        h = nn.Dense(self.output_dim, use_bias=True)(agg) + nn.Dense(
            self.output_dim, use_bias=False
        )(inv)
        return h, equiv


@register_conv("SAGE", is_edge_model=False)
def make_sage(cfg, in_dim, out_dim, last_layer):
    return SAGEConv(output_dim=out_dim, sorted_agg=cfg.sorted_aggregation,
                    max_in_degree=cfg.max_in_degree)
