"""PNAPlus: PNA aggregation with Bessel radial-basis edge conditioning.

TPU re-design of the reference's PNAPlusStack (hydragnn/models/PNAPlusStack.py:
144-304): the PNA message pre-MLP consumes [x_i, x_j, rbf_emb (+edge)] and is
Hadamard-gated by a linear projection of the enveloped Bessel basis of the
edge length; aggregation/scaling matches PNA (mean/min/max/std x identity/
amplification/attenuation/linear).
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from ..ops.radial import bessel_basis_enveloped, edge_vectors
from .base import register_conv
from .pna import pna_aggregate, pna_pre_message


class PNAPlusConv(nn.Module):
    output_dim: int
    deg_hist: tuple
    radius: float
    num_radial: int = 5
    envelope_exponent: int = 5
    edge_dim: int = 0
    sorted_agg: bool = False
    max_in_degree: int = 0
    # multi-output fused aggregation (cfg.fused_edge_kernel): the gated
    # message and all four aggregation moments run in one Pallas pass —
    # the rbf Hadamard gate rides the kernel's ``gate`` operand, so the
    # gated [E, C] message never exists in HBM (ops/pallas_multi_agg.py)
    multi_agg: bool = False
    remat_policy: str = "full"

    @nn.compact
    def __call__(self, inv, equiv, batch, train: bool = False):
        _, length = edge_vectors(equiv, batch.senders, batch.receivers,
                                 batch.edge_shifts)
        rbf = bessel_basis_enveloped(
            length[:, 0], self.radius, self.num_radial, self.envelope_exponent
        )
        f_in = inv.shape[-1]
        rbf_emb = nn.relu(nn.Dense(f_in)(rbf))
        if self.edge_dim and batch.edge_attr is not None:
            e = nn.Dense(f_in)(jnp.concatenate([batch.edge_attr, rbf_emb], axis=-1))
        else:
            e = rbf_emb
        # pre-MLP (pre_layers=1), factored so the fused route can gather
        # the receiver projection in-kernel (models/pna.py pna_pre_message)
        node_recv, edge_in = pna_pre_message(
            f_in, inv, batch, [("pre_edge", e)]
        )
        # Hadamard gate by the raw rbf projection (PNAPlusStack.py:268-276),
        # applied inside pna_aggregate: the fused route streams it as the
        # kernel's gate operand, the dense oracle multiplies post-gather
        gate = nn.Dense(f_in, use_bias=False)(rbf)

        scaled = pna_aggregate(
            edge_in, batch, self.deg_hist, self.sorted_agg,
            self.max_in_degree, node_recv=node_recv, gate=gate,
            multi_agg=self.multi_agg, remat_policy=self.remat_policy,
        )
        out = nn.Dense(self.output_dim)(jnp.concatenate([inv, scaled], axis=-1))
        out = nn.Dense(self.output_dim)(out)
        return out, equiv


@register_conv("PNAPlus", is_edge_model=True)
def make_pna_plus(cfg, in_dim, out_dim, last_layer):
    return PNAPlusConv(
        output_dim=out_dim,
        deg_hist=cfg.pna_deg,
        radius=cfg.radius or 5.0,
        num_radial=cfg.num_radial or 5,
        envelope_exponent=cfg.envelope_exponent or 5,
        edge_dim=cfg.edge_dim,
        sorted_agg=cfg.sorted_aggregation,
        max_in_degree=cfg.max_in_degree,
        multi_agg=cfg.fused_edge_kernel,
        remat_policy=cfg.remat_policy,
    )
