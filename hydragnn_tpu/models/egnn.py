"""E(n)-equivariant graph conv (EGNN).

TPU re-design of the reference's EGCLStack (hydragnn/models/EGCLStack.py:175-298):
message MLP over [h_i, h_j, |x_i-x_j| (, e_ij)], sum aggregation, node MLP over
[h, agg]; the equivariant variant also displaces coordinates along normalized
edge vectors gated by a small MLP (tanh-bounded, mean-aggregated).

The coordinate path reads/writes the ``equiv`` slot so stacked layers see the
updated positions (reference recomputes distances from the running ``coord``
each layer). PBC shifts are honored only in the invariant path, matching the
reference's zero-shift override for positional updates (EGCLStack.py:278-281).
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from ..ops.radial import edge_vectors
from ..ops.segment import segment_mean, segment_sum
from .base import register_conv
from .layers import MLP, fused_pair_dense_sum, hoisted_pair_dense


def coordinate_displacement(unit, gate_feat, batch, hidden_dim, tanh=False,
                            sorted_agg=False, max_in_degree=0):
    """Mean-aggregated coordinate displacement along (normalized) edge vectors,
    gated by a small MLP whose final layer starts near zero (gain 0.001).
    Shared by EGNN and equivariant SchNet (reference: E_GCL.coord_model,
    EGCLStack.py:263-271; CFConv.coord_model, SCFStack.py:243-254).
    Must be called from inside a ``@nn.compact`` ``__call__``."""
    coef = MLP((hidden_dim,), "relu", final_activation=True)(gate_feat)
    coef = nn.Dense(
        1, use_bias=False,
        kernel_init=nn.initializers.variance_scaling(0.001, "fan_avg", "uniform"),
    )(coef)
    if tanh:
        # bounded displacement with a learnable range (E_GCL tanh mode)
        coef = jnp.tanh(coef)
    trans = jnp.clip(unit * coef, -100.0, 100.0)
    return segment_mean(trans, batch.receivers, batch.num_nodes,
                        batch.edge_mask, sorted_ids=sorted_agg,
                        max_degree=max_in_degree)


class EGCL(nn.Module):
    output_dim: int
    hidden_dim: int
    edge_dim: int = 0
    equivariant: bool = False
    tanh: bool = True
    # Pallas sorted-segment aggregation (cfg.sorted_aggregation)
    sorted_agg: bool = False
    max_in_degree: int = 0
    # fully fused edge hot path (cfg.fused_edge_kernel): gather -> edge
    # dense -> segment sum in one VMEM-resident Pallas kernel
    # (layers.fused_pair_dense_sum). Applies only when the per-edge
    # messages have a SINGLE consumer — the aggregation. Equivariant
    # layers feed edge_feat to the coordinate gate too, so they keep the
    # materialized path (see the ceiling analysis in docs/PERFORMANCE.md).
    fused_edge: bool = False
    # Training.remat_policy save rule at the kernel call site (ops/remat.py)
    remat_policy: str = "full"

    @nn.compact
    def __call__(self, inv, equiv, batch, train: bool = False):
        pos = equiv
        # The reference zeroes PBC shifts inside every E_GCL layer — positional
        # update models have no PBC support (EGCLStack.py:278-281) — so edge
        # vectors come from bare positions for all layers.
        vec, length = edge_vectors(pos, batch.senders, batch.receivers)
        # normalize=True with eps=1.0 (reference E_GCL norm_diff, operations.py)
        unit = vec / (length + 1.0)

        terms = [("edge_lin_len", length)]
        if self.edge_dim and batch.edge_attr is not None:
            terms.append(("edge_lin_attr", batch.edge_attr))

        if (self.fused_edge and self.sorted_agg and self.max_in_degree > 0
                and not self.equivariant):
            # one fused op for the whole edge path — per-edge messages never
            # touch HBM; identical function and parameter tree to the
            # unfused spelling below (asserted by tests/test_fused_edge.py)
            agg = fused_pair_dense_sum(
                self.hidden_dim, inv, batch, "edge_lin_recv",
                "edge_lin_send", "edge_lin2", terms,
                max_in_degree=self.max_in_degree,
                remat_policy=self.remat_policy,
            )
        else:
            # matmul-before-gather first edge-MLP layer
            # (layers.hoisted_pair_dense; reference computes the same layer
            # post-concat, EGCLStack.py:238-247)
            pre = hoisted_pair_dense(
                self.hidden_dim, inv, batch, "edge_lin_recv",
                "edge_lin_send", terms
            )
            act = nn.relu
            edge_feat = act(
                nn.Dense(self.hidden_dim, name="edge_lin2")(act(pre))
            )

            if self.equivariant:
                delta = coordinate_displacement(
                    unit, edge_feat, batch, self.hidden_dim, tanh=self.tanh,
                    sorted_agg=self.sorted_agg,
                    max_in_degree=self.max_in_degree,
                )
                if self.tanh:
                    rng_scale = self.param(
                        "coords_range", nn.initializers.ones, (1,)
                    )
                    delta = delta * rng_scale * 3.0
                pos = pos + delta

            agg = segment_sum(edge_feat, batch.receivers, batch.num_nodes,
                              batch.edge_mask, sorted_ids=self.sorted_agg,
                              max_degree=self.max_in_degree)
        out = MLP((self.hidden_dim, self.output_dim), "relu")(
            jnp.concatenate([inv, agg], axis=-1)
        )
        return out, pos


@register_conv("EGNN", is_edge_model=True)
def make_egnn(cfg, in_dim, out_dim, last_layer):
    return EGCL(
        output_dim=out_dim,
        hidden_dim=cfg.hidden_dim,
        edge_dim=cfg.edge_dim,
        equivariant=cfg.equivariance and not last_layer,
        sorted_agg=cfg.sorted_aggregation,
        max_in_degree=cfg.max_in_degree,
        fused_edge=cfg.fused_edge_kernel,
        remat_policy=cfg.remat_policy,
    )
