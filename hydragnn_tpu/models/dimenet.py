"""DimeNet++ directional message passing.

TPU re-design of the reference's DIMEStack (hydragnn/models/DIMEStack.py:34-305
wrapping PyG's DimeNet++ blocks). Each conv layer = node-linear -> embedding
block (edge messages from [x_i, x_j, rbf(, e)]) -> interaction block
(triplet-directional update gated by the spherical basis) -> output block
(edge-to-node aggregation).

Triplets k->j->i are statically padded host-side by the loader
(``GraphBatch.trip_kj/trip_ji/trip_mask``) instead of the reference's
per-batch SparseTensor construction on device (DIMEStack.py:233-258) — a
data-dependent-shape op that cannot live inside jit. Angles are recomputed on
device from positions, so force training differentiates through them.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from ..ops.radial import bessel_basis_enveloped, edge_vectors
from ..ops.sbf import spherical_basis
from ..ops.segment import segment_sum
from .base import register_conv
from .layers import MLP


class DimeNetConv(nn.Module):
    output_dim: int
    hidden_dim: int
    num_radial: int = 6
    num_spherical: int = 7
    basis_emb_size: int = 8
    int_emb_size: int = 64
    out_emb_size: int = 128
    num_before_skip: int = 1
    num_after_skip: int = 2
    envelope_exponent: int = 5
    radius: float = 5.0
    edge_dim: int = 0
    sorted_agg: bool = False
    max_in_degree: int = 0

    @nn.compact
    def __call__(self, inv, equiv, batch, train: bool = False):
        assert batch.trip_kj is not None, (
            "DimeNet requires triplet indices; build loaders with "
            "PadSpec.for_dataset(..., with_triplets=True)"
        )
        act = nn.silu
        hidden = self.hidden_dim
        vec, length = edge_vectors(batch.pos, batch.senders, batch.receivers,
                                   batch.edge_shifts)
        dist = length[:, 0]
        rbf = bessel_basis_enveloped(dist, self.radius, self.num_radial,
                                     self.envelope_exponent)
        # zero padding-edge rows at the source: their eps-clamped lengths
        # produce a ~5e6 envelope spike (and the sbf recurrence below
        # amplifies to ~1e38) that downstream masks hide from the loss but
        # not from XLA's fused backward — see ops/sbf.py spherical_basis
        rbf = jnp.where(batch.edge_mask[:, None], rbf, 0.0)

        # angle at j between edges ji and ki = kj + ji (DIMEStack.py:179-186:
        # vectors added separately for PBC correctness)
        pos_ji = vec[batch.trip_ji]
        pos_kj = vec[batch.trip_kj]
        pos_ki = pos_kj + pos_ji
        a = jnp.sum(pos_ji * pos_ki, axis=-1)
        cross = jnp.cross(pos_ji, pos_ki)
        # smoothed norm: keeps d(angle)/d(pos) finite at collinear and
        # zero-length (padding) triplets, which energy-force training
        # differentiates through (plain norm() has a NaN gradient at 0)
        b = jnp.sqrt(jnp.sum(cross * cross, axis=-1) + 1e-12)
        angle = jnp.arctan2(b, a)

        sbf = spherical_basis(dist, angle, batch.trip_kj, self.radius,
                              self.num_spherical, self.num_radial,
                              self.envelope_exponent,
                              edge_mask=batch.edge_mask)

        # ---- node lin + embedding block (HydraEmbeddingBlock,
        # DIMEStack.py:260-305)
        x = nn.Dense(hidden)(inv)
        parts = [x[batch.receivers], x[batch.senders],
                 act(nn.Dense(hidden)(rbf))]
        if self.edge_dim and batch.edge_attr is not None:
            parts.append(act(nn.Dense(hidden)(batch.edge_attr)))
        m = act(nn.Dense(hidden)(jnp.concatenate(parts, axis=-1)))  # [E, H]

        # ---- interaction block (PyG InteractionPPBlock semantics)
        x_ji = act(nn.Dense(hidden)(m))
        x_kj = act(nn.Dense(hidden)(m))
        rbf_w = nn.Dense(self.basis_emb_size, use_bias=False)(rbf)
        rbf_w = nn.Dense(hidden, use_bias=False)(rbf_w)
        x_kj = x_kj * rbf_w
        x_kj = act(nn.Dense(self.int_emb_size)(x_kj))  # down-project
        sbf_w = nn.Dense(self.basis_emb_size, use_bias=False)(sbf)
        sbf_w = nn.Dense(self.int_emb_size, use_bias=False)(sbf_w)
        t_msg = x_kj[batch.trip_kj] * sbf_w  # [T, int_emb]
        agg = segment_sum(t_msg, batch.trip_ji, batch.num_edges, batch.trip_mask)
        x_kj = act(nn.Dense(hidden)(agg))  # up-project
        h = x_ji + x_kj
        for _ in range(self.num_before_skip):
            h = h + act(nn.Dense(hidden)(act(nn.Dense(hidden)(h))))
        h = act(nn.Dense(hidden)(h)) + m
        for _ in range(self.num_after_skip):
            h = h + act(nn.Dense(hidden)(act(nn.Dense(hidden)(h))))

        # ---- output block (PyG OutputPPBlock): edges -> nodes
        g = nn.Dense(hidden, use_bias=False)(rbf) * h
        node = segment_sum(g, batch.receivers, batch.num_nodes,
                           batch.edge_mask, sorted_ids=self.sorted_agg,
                           max_degree=self.max_in_degree)
        node = nn.Dense(self.out_emb_size, use_bias=False)(node)
        node = act(nn.Dense(self.out_emb_size)(node))
        out = nn.Dense(self.output_dim, use_bias=False)(node)
        return out, equiv


@register_conv("DimeNet", is_edge_model=True)
def make_dimenet(cfg, in_dim, out_dim, last_layer):
    # hidden = out_dim when input is scalar, else in_dim (DIMEStack.py:97-100)
    hidden = out_dim if in_dim == 1 else in_dim
    assert hidden > 1, (
        "DimeNet requires more than one hidden dimension between "
        "input_dim and output_dim."
    )
    return DimeNetConv(
        output_dim=out_dim,
        hidden_dim=hidden,
        num_radial=cfg.num_radial or 6,
        num_spherical=cfg.num_spherical or 7,
        basis_emb_size=cfg.basis_emb_size or 8,
        int_emb_size=cfg.int_emb_size or 64,
        out_emb_size=cfg.out_emb_size or 128,
        num_before_skip=cfg.num_before_skip if cfg.num_before_skip is not None else 1,
        num_after_skip=cfg.num_after_skip if cfg.num_after_skip is not None else 2,
        envelope_exponent=cfg.envelope_exponent or 5,
        radius=cfg.radius or 5.0,
        edge_dim=cfg.edge_dim,
        sorted_agg=cfg.sorted_aggregation,
        max_in_degree=cfg.max_in_degree,
    )
