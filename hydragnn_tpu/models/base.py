"""Multi-headed encoder/decoder base model (flax).

TPU-native re-design of the reference's ``Base`` torch module
(hydragnn/models/Base.py:31-752): a functional flax module over statically
padded ``GraphBatch``es. Key departures from the reference, chosen for XLA:

- branch selection (``data.dataset_name`` masking, Base.py:486-570) is done as
  *dense* compute-all-branches + ``jnp.where`` select — boolean indexing is a
  dynamic shape, masked select is one fused elementwise op;
- batch norm is the masked variant (padding rows excluded from statistics);
- the conv stack and heads are built from a frozen ``ModelConfig`` so the
  whole model hashes/stages cleanly under ``jax.jit``.

Every conv layer implements ``(inv, equiv, batch, train) -> (inv, equiv)``
mirroring the reference's ``inv_node_feat/equiv_node_feat`` plumbing
(Base.py:452-458).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..data.graph import GraphBatch
from ..obs.numerics import probe
from ..ops.segment import masked_global_mean_pool
from .layers import MLP, MaskedBatchNorm, get_activation


@dataclasses.dataclass(frozen=True)
class GraphHeadConfig:
    """One graph-level output branch head (reference: output_heads.graph)."""

    num_sharedlayers: int = 2
    dim_sharedlayers: int = 10
    num_headlayers: int = 2
    dim_headlayers: Tuple[int, ...] = (10, 10)


@dataclasses.dataclass(frozen=True)
class NodeHeadConfig:
    """Node-level output head (reference: output_heads.node)."""

    nn_type: str = "mlp"  # mlp | mlp_per_node | conv
    num_headlayers: int = 2
    dim_headlayers: Tuple[int, ...] = (10, 10)


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Frozen hyperparameter record driving model construction.

    Field names track the reference's Architecture config keys
    (config_utils.py:25-161) so the JSON surface maps 1:1.
    """

    mpnn_type: str
    input_dim: int
    hidden_dim: int
    num_conv_layers: int
    output_names: Tuple[str, ...]
    output_dim: Tuple[int, ...]
    output_type: Tuple[str, ...]
    task_weights: Tuple[float, ...]
    graph_head: Optional[GraphHeadConfig] = None
    node_head: Optional[NodeHeadConfig] = None
    num_branches: int = 1
    # static per-branch loss balancing (GFM mixture training, mix/balance.py;
    # planted into the Architecture section by the Mixture config section):
    # every graph's loss contribution is weighted by its branch's entry
    # (normalized to mean 1), and branch_loss_metrics adds per-branch loss
    # scalars (`branch<i>` task entries) for the drift monitor
    branch_loss_weights: Optional[Tuple[float, ...]] = None
    branch_loss_metrics: bool = False
    activation: str = "relu"
    loss_function_type: str = "mse"
    # --- GPS global attention
    global_attn_engine: str = ""
    global_attn_type: str = ""
    global_attn_heads: int = 0
    pe_dim: int = 0
    # static bound on nodes per graph (data-derived); >0 lets GPS multihead
    # attention use the per-graph dense [B, Nmax] layout instead of the
    # batch-wide [N, N] mask
    max_nodes_per_graph: int = 0
    # segment-masked Pallas flash attention for GPS global attention
    # (Architecture.use_flash_attention; auto-on for TPU jit targets in
    # config completion): online-softmax tiling over the flat node array,
    # cross-graph tiles never visited, logits never in HBM
    # (ops/pallas_flash_attention.py). Consumed by the multihead and ring
    # attention types; the dense layouts stay as the equivalence oracle.
    use_flash_attention: bool = False
    dropout: float = 0.25
    # --- geometry / radial basis
    edge_dim: int = 0
    radius: Optional[float] = None
    num_gaussians: Optional[int] = None
    num_filters: Optional[int] = None
    num_radial: Optional[int] = None
    num_spherical: Optional[int] = None
    envelope_exponent: Optional[int] = None
    radial_type: Optional[str] = None
    distance_transform: Optional[str] = None
    basis_emb_size: Optional[int] = None
    int_emb_size: Optional[int] = None
    out_emb_size: Optional[int] = None
    num_before_skip: Optional[int] = None
    num_after_skip: Optional[int] = None
    # --- PNA / MACE
    pna_deg: Tuple[int, ...] = ()
    avg_num_neighbors: Optional[float] = None
    max_ell: Optional[int] = None
    node_max_ell: Optional[int] = None
    correlation: Optional[int] = None
    # --- misc
    equivariance: bool = False
    num_nodes: Optional[int] = None
    var_output: bool = False
    conv_checkpointing: bool = False
    freeze_conv_layers: bool = False
    initial_bias: Optional[float] = None
    periodic_boundary_conditions: bool = False
    max_neighbours: Optional[int] = None
    # receiver-sorted edge arrays + static in-degree bound: lets the TPU
    # backend aggregate messages with the Pallas MXU kernel instead of a
    # scatter (ops/segment.py segment_sum; loader sort_edges=True)
    sorted_aggregation: bool = False
    max_in_degree: int = 0
    # fused edge-hot-path Pallas kernels (Architecture.use_fused_edge_kernel;
    # auto-on with sorted aggregation in config completion). Consumed by the
    # EGNN stack's single-consumer messages (gather -> dense -> segment sum,
    # ops/pallas_fused_edge.py) AND by the PNA family's multi-consumer
    # messages through the multi-output moment kernel
    # (ops/pallas_multi_agg.py — one pass emits sum/count/min/max/sumsq, so
    # "four aggregators need [E, C] in HBM" no longer holds). Gated
    # two-projection convs (CGCNN) still materialize messages for their
    # second consumer, so the flag is inert there.
    fused_edge_kernel: bool = False
    # Training.remat_policy (none|dots|names|full): the save rule every
    # remat wrap uses — kernel call sites and the whole-loss
    # conv_checkpointing wrap (ops/remat.py). 'full' = the historical bare
    # jax.checkpoint at every site.
    remat_policy: str = "full"
    # --- decoder seed-robustness knobs (Architecture.decoder_mirror_init /
    # Architecture.decoder_recovery_slope). Defaults are the seed-robust
    # behavior (mirrored (w,-w) decoder init + leaky-ReLU(0.1) decoder hidden
    # activations); set mirror_init=False, recovery_slope=0.0 for exact
    # parity with the reference's plain-ReLU MLP decoders (Base.py:372-392,
    # 692-752). See layers.MLP and docs/MIGRATION.md.
    decoder_mirror_init: bool = True
    decoder_recovery_slope: float = 0.1

    @property
    def num_heads(self) -> int:
        return len(self.output_dim)

    @property
    def normalized_task_weights(self) -> Tuple[float, ...]:
        """Weights normalized by abs-sum (reference: Base.py:112-115)."""
        s = sum(abs(w) for w in self.task_weights)
        return tuple(w / s for w in self.task_weights)

    @property
    def use_edge_attr(self) -> bool:
        return self.edge_dim is not None and self.edge_dim > 0

    @property
    def use_global_attn(self) -> bool:
        return bool(self.global_attn_engine)


# conv registry: mpnn_type -> (is_edge_model, ctor(cfg, in_dim, out_dim, last_layer) -> nn.Module)
_CONV_REGISTRY: Dict[str, Tuple[bool, Callable]] = {}


def register_conv(name: str, is_edge_model: bool = False):
    def deco(ctor):
        _CONV_REGISTRY[name] = (is_edge_model, ctor)
        return ctor

    return deco


def get_conv_ctor(name: str):
    try:
        return _CONV_REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"Unknown mpnn_type {name!r}; registered: {sorted(_CONV_REGISTRY)}"
        )


def conv_registry() -> Tuple[str, ...]:
    return tuple(sorted(_CONV_REGISTRY))


def _branch_bank(module_cls, num_branches: int, in_axes):
    """A module class lifted over the branch axis: parameters (and running
    batch-norm statistics) gain a leading [num_branches] axis, each branch
    initialized with its own rng (matching the per-branch modules of the
    reference, MultiTaskModelMP.py:172-201). ``in_axes`` follows jax.vmap:
    ``None`` broadcasts an argument to every branch, ``0`` maps a stacked
    per-branch input."""
    return nn.vmap(
        module_cls,
        in_axes=in_axes,
        out_axes=0,
        variable_axes={"params": 0, "batch_stats": 0},
        split_rngs={"params": True, "dropout": True},
        axis_size=num_branches,
    )


class NodeConvHead(nn.Module):
    """One branch's conv-chain node head: hidden convs + output conv, each
    followed by masked batch norm (reference: Base._init_node_conv,
    Base.py:260-341). Lifted over branches by ``_branch_bank``."""

    cfg: "ModelConfig"
    out_dim: int

    @nn.compact
    def __call__(self, x, equiv, batch: GraphBatch, train: bool):
        cfg = self.cfg
        _, ctor = get_conv_ctor(cfg.mpnn_type)
        act = get_activation(cfg.activation)
        nh = cfg.node_head or NodeHeadConfig()
        inv, eq = x, equiv
        in_d = cfg.hidden_dim
        dims = tuple(nh.dim_headlayers) + (self.out_dim,)
        for i, hd in enumerate(dims):
            conv = ctor(cfg, in_d, hd, i == len(dims) - 1)
            inv, eq = conv(inv, eq, batch, train)
            inv = act(MaskedBatchNorm()(inv, batch.node_mask, train))
            in_d = hd
        return inv


class HydraModel(nn.Module):
    """Encoder (conv stack (+GPS)) + multi-head, multi-branch decoders.

    ``__call__(batch, train)`` returns ``{head_name: predictions}`` with graph
    heads shaped [G, d] and node heads [N, d] (padding rows are garbage;
    always reduce with the batch masks). When ``cfg.var_output`` the dict also
    contains ``f"{name}__var"`` entries (reference outputs_var, Base.py:568).
    """

    cfg: ModelConfig

    def setup(self):
        cfg = self.cfg
        is_edge_model, ctor = get_conv_ctor(cfg.mpnn_type)
        self.is_edge_model = is_edge_model

        embed_dim = cfg.hidden_dim if cfg.use_global_attn else cfg.input_dim
        convs = []
        for i in range(cfg.num_conv_layers):
            in_dim = embed_dim if i == 0 else cfg.hidden_dim
            # Under GPS every conv output must match `channels` (the residual
            # in GPSConv), so width-expanding convs (GAT concat) take their
            # final-layer form; otherwise only the last layer does.
            final_form = cfg.use_global_attn or i == cfg.num_conv_layers - 1
            mpnn = ctor(cfg, in_dim, cfg.hidden_dim, final_form)
            if cfg.use_global_attn:
                from .gps import GPSConv

                mpnn = GPSConv(
                    channels=cfg.hidden_dim,
                    conv=mpnn,
                    heads=cfg.global_attn_heads,
                    dropout=cfg.dropout,
                    attn_type=cfg.global_attn_type or "multihead",
                    max_nodes_per_graph=cfg.max_nodes_per_graph,
                    use_flash_attention=cfg.use_flash_attention,
                    remat_policy=cfg.remat_policy,
                )
            convs.append(mpnn)
        self.graph_convs = convs
        self.feature_layers = [MaskedBatchNorm() for _ in range(cfg.num_conv_layers)]

        # learnable embeddings for GPS (reference: Base.py:160-174)
        if cfg.use_global_attn:
            self.pos_emb = nn.Dense(cfg.hidden_dim, use_bias=False)
            if cfg.input_dim:
                self.node_emb = nn.Dense(cfg.hidden_dim, use_bias=False)
                self.node_lin = nn.Dense(cfg.hidden_dim, use_bias=False)
            if is_edge_model:
                self.rel_pos_emb = nn.Dense(cfg.hidden_dim, use_bias=False)
                if cfg.use_edge_attr:
                    self.edge_emb = nn.Dense(cfg.hidden_dim, use_bias=False)
                    self.edge_lin = nn.Dense(cfg.hidden_dim, use_bias=False)

        # ---- decoders (reference: Base._multihead, Base.py:342-440)
        # Every decoder is a BRANCH BANK: one flax module whose parameter
        # (and batch_stats) leaves carry a leading [num_branches] axis,
        # built with nn.vmap over the branch dimension. Dense decode stays
        # the default (compute all branches + masked select), but the
        # stacked leaves are what makes decoder params/compute shardable
        # over the mesh's `branch` axis (parallel/branch.py — the analog of
        # the reference's MultiTaskModelMP decoder groups,
        # hydragnn/models/MultiTaskModelMP.py:203-230).
        B = cfg.num_branches
        if any(t == "graph" for t in cfg.output_type):
            gh = cfg.graph_head or GraphHeadConfig()
            self.graph_shared = _branch_bank(MLP, B, in_axes=(None,))(
                (gh.dim_sharedlayers,) * gh.num_sharedlayers,
                cfg.activation,
                final_activation=True,
                mirror_init=cfg.decoder_mirror_init,
                recovery_slope=cfg.decoder_recovery_slope,
            )
        heads = []
        for ihead, (t, d) in enumerate(zip(cfg.output_type, cfg.output_dim)):
            out_d = d * (2 if cfg.var_output else 1)
            if t == "graph":
                gh = cfg.graph_head or GraphHeadConfig()
                heads.append(
                    _branch_bank(MLP, B, in_axes=(0,))(
                        tuple(gh.dim_headlayers) + (out_d,),
                        cfg.activation,
                        mirror_init=cfg.decoder_mirror_init,
                        recovery_slope=cfg.decoder_recovery_slope,
                    )
                )
            elif t == "node":
                nh = cfg.node_head or NodeHeadConfig()
                if nh.nn_type in ("mlp", "mlp_per_node"):
                    heads.append(
                        _branch_bank(MLPNode, B, in_axes=(None, None))(
                            output_dim=out_d,
                            hidden_dims=tuple(nh.dim_headlayers),
                            nn_type=nh.nn_type,
                            num_nodes=cfg.num_nodes or 0,
                            activation=cfg.activation,
                            mirror_init=cfg.decoder_mirror_init,
                            recovery_slope=cfg.decoder_recovery_slope,
                        )
                    )
                elif nh.nn_type == "conv":
                    heads.append(
                        _branch_bank(
                            NodeConvHead, B, in_axes=(None, None, None, None)
                        )(cfg=cfg, out_dim=out_d)
                    )
                else:
                    raise ValueError(f"unknown node head type {nh.nn_type!r}")
            else:
                raise ValueError(f"unknown head type {t!r}")
        self.heads_NN = heads

    def _embedding(self, batch: GraphBatch):
        """(reference: Base._embedding, Base.py:217-245)"""
        cfg = self.cfg
        x = batch.x
        edge_attr = batch.edge_attr if cfg.use_edge_attr else None
        if cfg.use_global_attn:
            pe = self.pos_emb(batch.pe)
            if cfg.input_dim:
                pe = self.node_lin(jnp.concatenate([self.node_emb(x), pe], axis=1))
            x = pe
            if self.is_edge_model:
                e = self.rel_pos_emb(batch.rel_pe)
                if cfg.use_edge_attr:
                    e = self.edge_lin(
                        jnp.concatenate([self.edge_emb(batch.edge_attr), e], axis=1)
                    )
                edge_attr = e
        if edge_attr is not None:
            batch = batch.replace(edge_attr=edge_attr)
        return x, batch.pos, batch

    def encode(self, batch: GraphBatch, train: bool = False):
        """Conv stack -> final invariant node features [N, hidden]."""
        cfg = self.cfg
        act = get_activation(cfg.activation)
        inv, equiv, batch = self._embedding(batch)
        # numerics taps (obs/numerics.py): named intermediates for the
        # in-graph layer statistics + NaN provenance drill-down. Exact
        # no-ops (absent from the jaxpr) unless a collection context is
        # active at trace time — i.e. unless Telemetry.numerics is on.
        # Masked: padding rows carry garbage by contract (see class doc).
        probe("embedding", inv, batch.node_mask)
        # Activation rematerialization (the reference's per-conv torch
        # checkpoint, Base.py:459-465) is applied by the training step via
        # jax.checkpoint over the whole loss when cfg.conv_checkpointing.
        for i, (conv, feat_layer) in enumerate(
            zip(self.graph_convs, self.feature_layers)
        ):
            inv, equiv = conv(inv, equiv, batch, train)
            inv = act(feat_layer(inv, batch.node_mask, train))
            probe(f"conv{i}", inv, batch.node_mask)
        return inv, equiv, batch

    def __call__(self, batch: GraphBatch, train: bool = False):
        cfg = self.cfg
        x, equiv, batch = self.encode(batch, train)
        x_graph = masked_global_mean_pool(
            x, batch.node_graph, batch.num_graphs, batch.node_mask
        )
        probe("pooled", x_graph, batch.graph_mask)

        outputs: Dict[str, jnp.ndarray] = {}
        for ihead, (name, t, d) in enumerate(
            zip(cfg.output_names, cfg.output_type, cfg.output_dim)
        ):
            if t == "graph":
                out = self._graph_head(ihead, x_graph, batch.dataset_id)
            else:
                out = self._node_head(ihead, x, equiv, batch, train)
            outputs[name] = out[..., :d]
            probe(
                f"head:{name}",
                outputs[name],
                batch.graph_mask if t == "graph" else batch.node_mask,
            )
            if cfg.var_output:
                outputs[f"{name}__var"] = out[..., d:] ** 2
        return outputs

    def _graph_head(self, ihead, x_graph, dataset_id):
        """Dense all-branch compute + mask select (vs reference's boolean
        indexing per dataset ID, Base.py:495-509). The branch bank computes
        every branch in one vmapped call over stacked [B, ...] params."""
        cfg = self.cfg
        shared = self.graph_shared(x_graph)  # [B, G, ds]
        stacked = self.heads_NN[ihead](shared)  # [B, G, d]
        if cfg.num_branches == 1:
            return stacked[0]
        return jnp.take_along_axis(
            stacked, dataset_id[None, :, None].astype(jnp.int32), axis=0
        )[0]

    def _node_head(self, ihead, x, equiv, batch, train):
        cfg = self.cfg
        nh = cfg.node_head or NodeHeadConfig()
        if nh.nn_type == "conv":
            stacked = self.heads_NN[ihead](x, equiv, batch, train)  # [B, N, d]
        else:
            stacked = self.heads_NN[ihead](x, batch)  # [B, N, d]
        if cfg.num_branches == 1:
            return stacked[0]
        node_ds = batch.dataset_id[batch.node_graph]
        return jnp.take_along_axis(
            stacked, node_ds[None, :, None].astype(jnp.int32), axis=0
        )[0]


class MLPNode(nn.Module):
    """Per-node MLP head (reference: MLPNode, Base.py:692-752).

    ``mlp`` shares one MLP across all nodes; ``mlp_per_node`` keeps one MLP per
    node index (only valid for fixed-size graphs) — implemented as vmapped
    per-node parameter banks.
    """

    output_dim: int
    hidden_dims: Tuple[int, ...]
    nn_type: str
    num_nodes: int
    activation: str = "relu"
    mirror_init: bool = True
    recovery_slope: float = 0.1

    @nn.compact
    def __call__(self, x, batch: GraphBatch):
        feats = tuple(self.hidden_dims) + (self.output_dim,)
        if self.nn_type == "mlp":
            return MLP(feats, self.activation, mirror_init=self.mirror_init,
                       recovery_slope=self.recovery_slope)(x)
        # mlp_per_node: a separate MLP per node position within each graph
        assert self.num_nodes > 0, "mlp_per_node requires fixed graph size"
        node_pos = _node_position_in_graph(batch)
        mlps = nn.vmap(
            MLP,
            in_axes=0,
            out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
        )(feats, self.activation, mirror_init=self.mirror_init,
          recovery_slope=self.recovery_slope)
        # evaluate all per-node MLPs on gathered inputs ordered by node pos
        onehot = jax.nn.one_hot(node_pos % self.num_nodes, self.num_nodes, axis=0)
        xs = jnp.einsum("pn,nf->pnf", onehot, x)
        ys = mlps(xs)  # [num_nodes, N, out]
        return jnp.einsum("pn,pnf->nf", onehot, ys)


def _node_position_in_graph(batch: GraphBatch) -> jnp.ndarray:
    """Index of each node within its own graph (0..n_g-1)."""
    n = batch.num_nodes
    idx = jnp.arange(n, dtype=jnp.int32)
    seg_start = jnp.full((batch.num_graphs,), n, jnp.int32)
    seg_start = seg_start.at[batch.node_graph].min(idx, mode="drop")
    return idx - seg_start[batch.node_graph]
