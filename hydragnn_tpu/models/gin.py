"""GIN convolution (reference: hydragnn/models/GINStack.py:20-60).

x_i' = MLP((1 + eps) * x_i + sum_{j in N(i)} x_j) with a 2-layer MLP
(Linear-ReLU-Linear) and a *learnable* eps initialized to 100.0, matching the
reference's ``GINConv(..., eps=100.0, train_eps=True)``.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from ..ops.segment import segment_sum
from .base import register_conv


class GINConv(nn.Module):
    output_dim: int
    eps_init: float = 100.0
    sorted_agg: bool = False
    max_in_degree: int = 0

    @nn.compact
    def __call__(self, inv, equiv, batch, train: bool = False):
        eps = self.param("eps", lambda _: jnp.asarray(self.eps_init, jnp.float32))
        agg = segment_sum(
            inv[batch.senders], batch.receivers, batch.num_nodes,
            batch.edge_mask, sorted_ids=self.sorted_agg,
            max_degree=self.max_in_degree,
        )
        h = (1.0 + eps) * inv + agg
        h = nn.Dense(self.output_dim)(h)
        h = nn.relu(h)
        h = nn.Dense(self.output_dim)(h)
        return h, equiv


@register_conv("GIN", is_edge_model=False)
def make_gin(cfg, in_dim, out_dim, last_layer):
    return GINConv(output_dim=out_dim, sorted_agg=cfg.sorted_aggregation,
                   max_in_degree=cfg.max_in_degree)
