"""Shared building blocks: MLP, masked batch norm, activation resolver."""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.remat import kernel_remat, tag as remat_tag
from ..ops.segment import fused_edge_message_sum as _fused_edge_message_sum


def mirrored_lecun_normal():
    """LeCun-normal kernel init with columns drawn in ``(w, -w)`` pairs.

    For a ReLU layer whose inputs are nonnegative (everything downstream of
    a ReLU encoder — exactly the decoder-head position), a zero-bias unit is
    dead on the WHOLE dataset iff ``w·x < 0`` for every sample; with few
    units the probability that every unit draws dead is seed-visible (a
    hidden-8 matrix run measured GIN/EGNN stalled at the conv-free minimum
    at Training.seed=0). Pairing each column with its negation guarantees
    that for any input with ``w·x != 0`` one unit of the pair is active, so
    no seed can produce a fully dead layer and gradients always flow.
    The ReLU gates break the pair symmetry after the first update, and the
    per-column scale is the usual lecun_normal (same as flax's default), so
    trained behavior is unchanged. This replaces the round-3 workaround of
    pinning a measured healthy seed.
    """

    base = nn.initializers.lecun_normal()

    def init(key, shape, dtype=jnp.float_):
        if len(shape) != 2:
            return base(key, shape, dtype)
        fan_in, fan_out = shape
        half = (fan_out + 1) // 2
        w = base(key, (fan_in, half), dtype)
        return jnp.concatenate([w, -w[:, : fan_out - half]], axis=1)

    return init

ACTIVATIONS = {
    "relu": nn.relu,
    "gelu": nn.gelu,
    "silu": nn.silu,
    "swish": nn.silu,
    "tanh": jnp.tanh,
    "sigmoid": nn.sigmoid,
    "elu": nn.elu,
    "leaky_relu": nn.leaky_relu,
    "softplus": nn.softplus,
    "identity": lambda x: x,
}


def get_activation(name: str) -> Callable:
    """(reference activation selection: hydragnn/utils/model/model.py and
    loss/activation test, tests/test_loss_and_activation_functions.py)"""
    try:
        return ACTIVATIONS[name.lower()]
    except KeyError:
        raise ValueError(f"unknown activation {name!r}; known: {sorted(ACTIVATIONS)}")


class MLP(nn.Module):
    """Dense stack with activation between layers, none after the last
    (matches the reference's Sequential(Linear, act, ..., Linear) head MLPs,
    Base.py:372-392)."""

    features: Sequence[int]
    activation: str = "relu"
    final_activation: bool = False
    # decoder-position MLPs (nonnegative inputs) use the mirrored init so no
    # rng draw can produce a fully ReLU-dead layer; see mirrored_lecun_normal
    mirror_init: bool = False
    # recovery slope for narrow decoder MLPs: with plain ReLU a dead unit
    # has exactly zero gradient forever, and a 4-10 unit decoder measurably
    # dies DURING training at some seeds (alive at init, killed by early
    # updates + weight decay; the run then sits at the constant-prediction
    # floor while the encoder still carries 0.9-correlated features).
    # Call sites pass 0.1: it keeps every unit recoverable within an
    # early-stopping patience window (0.01 measured too slow — a
    # soft-dead layer's 100x attenuation left gradients under the
    # recovery rate). Applied only when the configured activation is
    # relu, to every activation this MLP applies (including the
    # final_activation=True one of shared decoder stacks — those feed
    # further head layers, so slightly-negative features are benign).
    recovery_slope: float = 0.0

    @nn.compact
    def __call__(self, x):
        act = get_activation(self.activation)
        if self.recovery_slope and self.activation.lower() == "relu":
            slope = self.recovery_slope
            act = lambda v: nn.leaky_relu(v, negative_slope=slope)
        for i, f in enumerate(self.features):
            last = i == len(self.features) - 1
            if self.mirror_init and (not last or self.final_activation):
                x = nn.Dense(f, kernel_init=mirrored_lecun_normal())(x)
            else:
                x = nn.Dense(f)(x)
            if not last or self.final_activation:
                x = act(x)
        return x


class MaskedBatchNorm(nn.Module):
    """BatchNorm1d over *real* nodes only.

    The reference applies torch BatchNorm1d after every conv (Base.py:214,466).
    With padded static batches the statistics must exclude padding rows, hence
    this masked variant; running stats live in the ``batch_stats`` collection.
    """

    momentum: float = 0.9
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x, mask: Optional[jnp.ndarray] = None, train: bool = True):
        features = x.shape[-1]
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((features,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((features,), jnp.float32)
        )
        ra_count = self.variable(
            "batch_stats", "count", lambda: jnp.zeros((), jnp.float32)
        )
        scale = self.param("scale", nn.initializers.ones, (features,))
        bias = self.param("bias", nn.initializers.zeros, (features,))

        if train:
            if mask is None:
                n = jnp.asarray(float(x.shape[0]), x.dtype)
                mean = jnp.mean(x, axis=0)
                var = jnp.var(x, axis=0)
            else:
                m = mask[:, None].astype(x.dtype)
                n = jnp.maximum(jnp.sum(m), 1.0)
                mean = jnp.sum(x * m, axis=0) / n
                var = jnp.sum(((x - mean) ** 2) * m, axis=0) / n
            if not self.is_initializing():
                # count-weighted EMA: a remainder batch with few real rows
                # moves the running stats proportionally less (plain
                # equal-weight EMA lets one tiny ragged batch poison eval
                # statistics; for constant batch sizes this reduces exactly
                # to the torch BatchNorm1d update the reference relies on)
                c_new = self.momentum * ra_count.value + (1 - self.momentum) * n
                w_old = self.momentum * ra_count.value / jnp.maximum(c_new, 1e-8)
                w_new = 1.0 - w_old
                ra_mean.value = w_old * ra_mean.value + w_new * mean
                ra_var.value = w_old * ra_var.value + w_new * var
                ra_count.value = c_new
        else:
            mean, var = ra_mean.value, ra_var.value

        y = (x - mean) / jnp.sqrt(var + self.epsilon)
        y = y * scale + bias
        # numerics tap (obs/numerics.py): the pre-activation normalized
        # output, named by module path — a no-op unless Telemetry.numerics
        # armed a collection context at trace time. Batch norm is the first
        # place a collapsing variance shows (1/sqrt(var) blowing up), one
        # layer before the activation probe in models/base.py sees it.
        from ..obs.numerics import collection_active, probe

        if collection_active():
            try:
                pname = "/".join(str(p) for p in self.path)
            except Exception:
                pname = self.name or "batchnorm"
            probe(f"bn:{pname}", y, mask)
        return y


def pair_message_factored(dim, inv, batch, name_recv, name_send, edge_terms=()):
    """The factored first edge-MLP layer, distributed over its concat
    inputs: a NODE-sized receiver projection (``[N, C]``, carrying the one
    bias — same total as the post-concat layer) and ONE edge-aligned
    operand (bias-free sender projection gathered by ``senders``, plus a
    bias-free projection per ``edge_terms`` entry). Returns
    ``(node_recv [N, C], edge_in [E, C])``.

    This is the SINGLE spelling of the recv-bias/send-no-bias parameter
    convention — ``hoisted_pair_dense``, ``fused_pair_dense_sum`` and the
    PNA family's pre-message (models/pna.py) all build on it, which is
    what keeps their parameter trees checkpoint-interchangeable. Keeping
    ``node_recv`` un-gathered is what lets the fused kernels run the
    receiver gather in-register (ops/pallas_fused_edge.py,
    ops/pallas_multi_agg.py)."""
    node_recv = nn.Dense(dim, name=name_recv)(inv)
    edge_in = nn.Dense(dim, use_bias=False, name=name_send)(inv)[batch.senders]
    for name, arr in edge_terms:
        edge_in = edge_in + nn.Dense(dim, use_bias=False, name=name)(arr)
    return node_recv, edge_in


def hoisted_pair_dense(dim, inv, batch, name_recv, name_send, edge_terms=()):
    """First edge-MLP layer distributed over its concat inputs and computed
    on node-sized operands BEFORE the edge gather:

        Dense(concat[x_i, x_j, e...]) == Dense_r(x)_i + Dense_s(x)_j
                                          + sum_k Dense_k(e_k)

    (parameters via ``pair_message_factored`` above). The node-side
    matmuls run on [N, C] instead of [E, 2C]: at degree ~20 that is ~20x
    fewer MXU FLOPs and half the gather bytes for this layer, with
    identical function class to the reference's post-concat edge MLPs
    (e.g. EGCLStack.py:238-247, PNAPlusStack.py:268).

    ``edge_terms`` is an iterable of (name, [E, d] array) extra edge-aligned
    operands, each getting its own bias-free projection.

    When the downstream consumer is relu -> Dense -> relu -> segment_sum and
    nothing else reads the per-edge messages, prefer
    ``fused_pair_dense_sum`` below: same parameters, but the whole chain
    runs in one VMEM-resident Pallas kernel on TPU.
    """
    node_recv, edge_in = pair_message_factored(
        dim, inv, batch, name_recv, name_send, edge_terms
    )
    return node_recv[batch.receivers] + edge_in


class _FusedEdgeDense(nn.Module):
    """Params of the second edge-dense layer (``kernel``/``bias``, named
    and initialized exactly like ``nn.Dense`` so the fused and unfused
    routes share one checkpoint format) + the fused Pallas/dense call.

    The op is remat-wrapped per ``Training.remat_policy`` (ops/remat.py;
    default ``full`` = the historical bare ``jax.checkpoint``) so the
    plain-jnp tangent rule's residuals (pre-activation, relu masks —
    [E, C] arrays) are recomputed in the backward instead of materialized
    in the forward: the training forward stays VMEM-resident, which is
    the point of the fusion. The output carries the ``fused_edge_sum``
    checkpoint-name tag for the ``names`` policy's save set.
    """

    features: int
    max_in_degree: int
    remat_policy: str = "full"

    @nn.compact
    def __call__(self, node_recv, edge_in, receivers, num_segments):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (edge_in.shape[-1], self.features),
        )
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        dtype = jnp.result_type(node_recv, edge_in, kernel, bias)
        max_degree = self.max_in_degree

        def call(nr, ei, w, b):
            return remat_tag(_fused_edge_message_sum(
                nr.astype(dtype), ei.astype(dtype), w.astype(dtype),
                b.astype(dtype), receivers, num_segments, max_degree,
            ), "fused_edge_sum")

        return kernel_remat(call, self.remat_policy)(
            node_recv, edge_in, kernel, bias
        )


def fused_pair_dense_sum(dim, inv, batch, name_recv, name_send, name_out,
                         edge_terms=(), max_in_degree: int = 0,
                         remat_policy: str = "full"):
    """Fused counterpart of the whole EGNN-style edge hot path:

        hoisted_pair_dense -> relu -> Dense(name_out) -> relu -> segment_sum

    in ONE op (ops/segment.py fused_edge_message_sum; the Pallas kernel on
    TPU keeps per-edge messages VMEM-resident). Same parameter tree as the
    unfused spelling — ``name_recv``/``name_send``/``edge_terms`` denses
    here, ``kernel``/``bias`` under ``name_out`` — so checkpoints and
    A/B inits are interchangeable between routes.

    The receiver projection stays NODE-sized ([N, C], gathered in-kernel by
    the receiver-sorted one-hot); the sender projection and the edge-local
    terms collapse into the single edge-aligned operand the kernel streams.
    Requires receiver-sorted batches and a static in-degree bound, like
    ``segment_sum(sorted_ids=True)``; padding edges land on the dummy node,
    whose garbage row every consumer already masks (data/graph.py).
    """
    node_recv, edge_in = pair_message_factored(
        dim, inv, batch, name_recv, name_send, edge_terms
    )
    return _FusedEdgeDense(dim, max_in_degree, remat_policy, name=name_out)(
        node_recv, edge_in, batch.receivers, batch.num_nodes
    )
