from .base import (
    GraphHeadConfig,
    HydraModel,
    ModelConfig,
    NodeHeadConfig,
    conv_registry,
    register_conv,
)
from .create import (
    available_models,
    create_model,
    init_model,
    model_config_from,
    normalize_output_heads,
)

__all__ = [
    "GraphHeadConfig",
    "HydraModel",
    "ModelConfig",
    "NodeHeadConfig",
    "available_models",
    "conv_registry",
    "create_model",
    "init_model",
    "model_config_from",
    "normalize_output_heads",
    "register_conv",
]
