"""MFC (molecular fingerprint) convolution.

(reference: hydragnn/models/MFCStack.py:20-60 wrapping PyG ``MFConv`` with
max_degree = config max_neighbours, create.py:248-256.)

Duvenaud-style conv with degree-specific weights:
x_i' = W_root^{(d_i)} x_i + W_nbr^{(d_i)} sum_j x_j, d_i capped at max_degree.
Implemented as a one-hot degree select over stacked weight banks — a dense
einsum instead of PyG's per-degree index_select, which maps onto the MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.segment import segment_count, segment_sum
from .base import register_conv


class MFConv(nn.Module):
    output_dim: int
    max_degree: int = 10
    sorted_agg: bool = False
    max_in_degree: int = 0

    @nn.compact
    def __call__(self, inv, equiv, batch, train: bool = False):
        D = self.max_degree + 1
        f_in = inv.shape[-1]
        w_root = self.param(
            "w_root", nn.initializers.glorot_uniform(), (D, f_in, self.output_dim)
        )
        w_nbr = self.param(
            "w_nbr", nn.initializers.glorot_uniform(), (D, f_in, self.output_dim)
        )
        bias = self.param("bias", nn.initializers.zeros, (D, self.output_dim))
        agg = segment_sum(
            inv[batch.senders], batch.receivers, batch.num_nodes,
            batch.edge_mask, sorted_ids=self.sorted_agg,
            max_degree=self.max_in_degree,
        )
        deg = segment_count(batch.receivers, batch.num_nodes, batch.edge_mask)
        deg = jnp.clip(deg.astype(jnp.int32), 0, self.max_degree)
        onehot = jax.nn.one_hot(deg, D, dtype=inv.dtype)  # [N, D]
        # select per-node weights by degree and apply: MXU-friendly einsums
        out = jnp.einsum("nd,nf,dfo->no", onehot, inv, w_root)
        out = out + jnp.einsum("nd,nf,dfo->no", onehot, agg, w_nbr)
        out = out + onehot @ bias
        return out, equiv


@register_conv("MFC", is_edge_model=False)
def make_mfc(cfg, in_dim, out_dim, last_layer):
    max_deg = cfg.max_neighbours if cfg.max_neighbours is not None else 10
    return MFConv(output_dim=out_dim, max_degree=int(max_deg),
                  sorted_agg=cfg.sorted_aggregation,
                  max_in_degree=cfg.max_in_degree)
