"""SchNet continuous-filter convolution (SCF).

TPU re-design of the reference's SCFStack (hydragnn/models/SCFStack.py:34-293):
Gaussian-smeared interatomic distances feed a filter MLP; messages are
``x_j * W(edge)`` with a cosine-cutoff envelope, sum-aggregated. The optional
equivariant mode updates positions from filter features like EGNN
(SCFStack.py:243-254). Distances are recomputed from positions each call, so
force training differentiates straight through.
"""

from __future__ import annotations

from flax import linen as nn
import jax.numpy as jnp

from ..ops.radial import cosine_cutoff, edge_vectors, gaussian_basis
from ..ops.segment import segment_sum
from .base import register_conv
from .egnn import coordinate_displacement
from .layers import MLP


class CFConv(nn.Module):
    output_dim: int
    num_filters: int
    num_gaussians: int
    radius: float
    edge_dim: int = 0
    equivariant: bool = False
    sorted_agg: bool = False
    max_in_degree: int = 0

    @nn.compact
    def __call__(self, inv, equiv, batch, train: bool = False):
        # The reference computes the rbf once from the *original* positions in
        # ``_embedding`` and feeds the same values to every layer
        # (SCFStack.py:164-179); only the coordinate-update path below sees the
        # running (updated) positions. PBC shifts are honored in the invariant
        # path and dropped for coordinate updates (SCFStack.py:166-169).
        _, length0 = edge_vectors(batch.pos, batch.senders, batch.receivers,
                                  batch.edge_shifts)
        r = length0[:, 0]
        rbf = gaussian_basis(r, self.radius, self.num_gaussians)
        filt_in = rbf
        if self.edge_dim and batch.edge_attr is not None:
            filt_in = jnp.concatenate([rbf, batch.edge_attr], axis=-1)
        w = MLP((self.num_filters, self.num_filters), "softplus",
                final_activation=False)(filt_in)
        w = w * cosine_cutoff(r, self.radius)[:, None]

        h = nn.Dense(self.num_filters, use_bias=False)(inv)
        msg = h[batch.senders] * w
        agg = segment_sum(msg, batch.receivers, batch.num_nodes,
                          batch.edge_mask, sorted_ids=self.sorted_agg,
                          max_degree=self.max_in_degree)
        out = nn.Dense(self.output_dim)(agg)
        # Residual interaction update (original SchNet, Schütt et al. 2017:
        # x^{l+1} = x^l + v^l, with an atom-embedding layer mapping inputs
        # to hidden width BEFORE the first interaction). The reference's
        # SCFStack drops this self path (CFConv returns lin2(aggregate)
        # only, SCFStack.py:259-290), which makes the receiving node's own
        # features unrecoverable except through closed 2-hop paths —
        # measured as a ~0.24-RMSE floor on the pointwise vector-output CI
        # task. Width-matching layers add the identity residual; the first
        # layer (input_dim -> hidden) adds a learned embedding of the input
        # instead, exactly the paper's embedding-then-residual structure.
        if inv.shape[-1] == self.output_dim:
            out = out + inv
        else:
            out = out + nn.Dense(self.output_dim, use_bias=False)(inv)

        if self.equivariant:
            # Coordinate update from the *running* positions, normalize=True
            # eps=1.0 (SCFStack.py:243-246). Note: as in the reference, the
            # scalar stream keeps reading the fixed original-position rbf, so
            # the updated coordinates only surface through the returned equiv
            # slot (conv node heads / downstream consumers).
            vec, length = edge_vectors(equiv, batch.senders, batch.receivers)
            unit = vec / (length + 1.0)
            equiv = equiv + coordinate_displacement(
                unit, w, batch, self.num_filters
            )
        return out, equiv


@register_conv("SchNet", is_edge_model=True)
def make_schnet(cfg, in_dim, out_dim, last_layer):
    return CFConv(
        output_dim=out_dim,
        num_filters=cfg.num_filters or 126,
        num_gaussians=cfg.num_gaussians or 50,
        radius=cfg.radius or 5.0,
        edge_dim=cfg.edge_dim,
        # last layer stays invariant so node outputs are E(3)-invariant
        # (reference: SCFStack equivariant=self.equivariance and not last_layer)
        equivariant=cfg.equivariance and not last_layer,
        sorted_agg=cfg.sorted_aggregation,
        max_in_degree=cfg.max_in_degree,
    )
