"""MACE: higher-order E(3)-equivariant message passing, TPU-native.

Re-design of the reference's MACE stack (hydragnn/models/MACEStack.py, which
adapts ACEsuit/MACE via e3nn) in terms of dense uniform-multiplicity irreps
arrays ``[N, C, (L+1)^2]`` and host-precomputed real CG tensors (ops/o3.py):

- node attributes are one-hot atomic numbers Z in [1,118]
  (MACEStack.py:123-126), embedded to C scalar channels;
- each layer runs an attention-style residual interaction
  (mace_utils/modules/blocks.py:286-390: linear_up, radial MLP over
  [bessel, scalars_down[sender], scalars_down[receiver]] producing per-path
  per-channel tensor-product weights, CG coupling with edge spherical
  harmonics, receiver segment-sum / avg_num_neighbors, linear, plus an
  equivariant skip connection) followed by the symmetric product basis
  (blocks.py:166-204 -> symmetric_contraction.py): here the n-body product is
  built recursively — B_1 = A, B_{k+1} = CG(B_k (x) A) — with per-element,
  per-channel weights at every order, which spans the same n-body feature
  space as the reference's U-matrix formulation without e3nn codegen;
- predictions are an n-body expansion: a readout per layer (plus one on the
  raw one-hot attributes), all summed (MACEStack.py:21-28, forward
  :367-400). The last layer contracts to scalars and decodes nonlinearly.

Everything is einsum over static slices — XLA maps the channel dimension onto
the MXU; no data-dependent shapes anywhere. Spherical harmonics act on edge
vectors only, which are translation invariant, so the reference's per-graph
position centering (MACEStack.py:405-418) is unnecessary here.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..data.graph import GraphBatch
from ..ops.o3 import (
    combined_cg,
    couple,
    irrep_slice,
    real_sph_harm,
    sh_dim,
    summed_cg,
    tp_paths,
)
from ..ops.radial import RadialEmbedding, edge_vectors
from ..ops.segment import segment_sum
from ..ops.segment import masked_global_mean_pool
from .base import ModelConfig, NodeHeadConfig, _branch_bank
from .layers import MLP, get_activation
from ..utils import envflags

NUM_ELEMENTS = 118


def _dense_cg_enabled() -> bool:
    """Fused-CG compute path: the per-path couple() chains contract a single
    block CG tensor instead (ops/o3.py combined_cg/summed_cg) — identical
    math, dot_general-shaped for the MXU. Pure compute-path choice:
    parameters and outputs are unchanged (pinned by
    tests/test_mace.py::pytest_mace_dense_cg_path_matches_loop).

    Default ON for TPU (r5 live A/B: +22% on top of the scatter-free build,
    481.3/502.9 vs 393.0/411.0 graphs/sec/chip — logs/ab_matrix.jsonl
    mace_dcg*), OFF elsewhere (the dense contraction trades more FLOPs for
    MXU shape, the wrong trade off-TPU). Evaluated at trace time like
    ops/segment._pallas_route_enabled, so the backend exists by then;
    HYDRAGNN_MACE_DENSE_CG=0/1 overrides."""
    pref = envflags.env_force("HYDRAGNN_MACE_DENSE_CG")
    if pref is not None:
        return pref
    return jax.default_backend() == "tpu"


def _concat_by_l(by_l, leading, c, dtype):
    """Concatenate per-l partial-sum lists into one [..., (L+1)^2] irreps
    array (l blocks in increasing-l order = the irrep_slice layout). The
    scatter-free alternative to a .at[irrep_slice(l)].add per path, which
    lowers to a chain of unfused full-array dynamic-update-slices."""
    return jnp.concatenate(
        [
            sum(blocks) if blocks else jnp.zeros((*leading, c, 2 * l + 1), dtype)
            for l, blocks in enumerate(by_l)
        ],
        axis=-1,
    )


class EquivariantLinear(nn.Module):
    """Per-l channel mixing [N, C_in, (Lin+1)^2] -> [N, C_out, (Lout+1)^2].

    The analog of e3nn ``o3.Linear`` on uniform-multiplicity irreps: one
    weight matrix per l (shared across the 2l+1 components, which is exactly
    what keeps it equivariant); bias only on l=0.
    """

    features: int
    lmax_out: int

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        n, c_in, d_in = x.shape
        lmax_in = int(math.isqrt(d_in)) - 1
        outs = []
        for l in range(self.lmax_out + 1):
            if l <= lmax_in:
                w = self.param(
                    f"w{l}",
                    nn.initializers.lecun_normal(),
                    (c_in, self.features),
                    x.dtype,
                )
                block = jnp.einsum("ncm,cf->nfm", x[:, :, irrep_slice(l)], w)
                if l == 0:
                    b = self.param(
                        "b0", nn.initializers.zeros, (self.features,), x.dtype
                    )
                    block = block + b[None, :, None]
            else:
                block = jnp.zeros((n, self.features, 2 * l + 1), x.dtype)
            outs.append(block)
        return jnp.concatenate(outs, axis=-1)


class MACEInteraction(nn.Module):
    """Residual attention-style interaction block
    (reference: RealAgnosticAttResidualInteractionBlock, blocks.py:286-390)."""

    features: int
    max_ell: int  # lmax of edge spherical harmonics and messages
    node_max_ell: int  # lmax of node features / skip connection
    avg_num_neighbors: float
    sorted_agg: bool = False
    max_in_degree: int = 0
    last_layer: bool = False

    @nn.compact
    def __call__(
        self,
        h: jnp.ndarray,  # [N, C, (lin+1)^2]
        sh: jnp.ndarray,  # [E, (max_ell+1)^2]
        radial: jnp.ndarray,  # [E, B]
        batch: GraphBatch,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        c = self.features
        lmax_in = int(math.isqrt(h.shape[-1])) - 1
        sc_lmax = 0 if self.last_layer else self.node_max_ell
        sc = EquivariantLinear(c, sc_lmax, name="skip")(h)
        h_up = EquivariantLinear(c, lmax_in, name="linear_up")(h)
        scalars_down = nn.Dense(c, name="linear_down")(h[:, :, 0])

        edge_in = [radial, scalars_down[batch.senders], scalars_down[batch.receivers]]
        if batch.edge_attr is not None:
            edge_in.append(batch.edge_attr)
        edge_in = jnp.concatenate(edge_in, axis=-1)

        paths = tp_paths(lmax_in, self.max_ell, self.max_ell)
        tp_w = MLP(
            (c, c, c, len(paths) * c), activation="silu", name="conv_tp_weights"
        )(edge_in).reshape(-1, len(paths), c)

        hs = h_up[batch.senders]  # [E, C, (lin+1)^2]
        # per output-l partial sums, concatenated once (_concat_by_l): +50%
        # measured on the MACE cell vs the scatter chain (393.0 vs 261.8
        # graphs/sec/chip, logs/ab_matrix.jsonl r5 mace_dense2)
        by_l3 = [[] for _ in range(self.max_ell + 1)]
        if _dense_cg_enabled():
            # fused path: ONE contraction over the block CG tensor computes
            # every couple() of the loop below, then per-path weights apply
            # on Q-axis slices (same values, dot_general-shaped)
            G, g_paths, offs = combined_cg(lmax_in, self.max_ell, self.max_ell)
            assert g_paths == tuple(paths)
            raw = jnp.einsum(
                "ecm,en,mnq->ecq", hs, sh, jnp.asarray(G, h.dtype)
            )
            for p, (l1, l2, l3) in enumerate(paths):
                blk = raw[:, :, offs[p] : offs[p] + 2 * l3 + 1]
                by_l3[l3].append(blk * tp_w[:, p, :, None])
        else:
            for p, (l1, l2, l3) in enumerate(paths):
                contrib = couple(
                    hs[:, :, irrep_slice(l1)],
                    sh[:, None, irrep_slice(l2)],
                    l1,
                    l2,
                    l3,
                )
                by_l3[l3].append(contrib * tp_w[:, p, :, None])
        msg = _concat_by_l(by_l3, (sh.shape[0],), c, h.dtype)

        msg = msg * batch.edge_mask.astype(h.dtype)[:, None, None]
        # channel x irrep axes flattened so the 2-D sorted-segment kernel
        # can take the receiver sum on TPU (ops/segment.py)
        agg = segment_sum(
            msg.reshape(msg.shape[0], -1), batch.receivers, h.shape[0],
            sorted_ids=self.sorted_agg, max_degree=self.max_in_degree,
        ).reshape(h.shape[0], c, sh_dim(self.max_ell)) / self.avg_num_neighbors
        agg = EquivariantLinear(c, self.max_ell, name="linear")(agg)
        return agg, sc


class SymmetricProduct(nn.Module):
    """n-body product basis with per-element weights
    (reference: EquivariantProductBasisBlock -> SymmetricContraction,
    blocks.py:166-204, symmetric_contraction.py:29-238).

    Recursive construction: B_1 = A, B_{k+1}[l3] = sum_paths CG(B_k[l1],
    A[l2]); the output is sum_k W_k(Z) (.) B_k projected to l <= lmax_out.
    """

    features: int
    lmax_out: int
    correlation: int
    lmax_keep: int  # intermediate lmax retained during recursion

    @nn.compact
    def __call__(self, a: jnp.ndarray, node_attrs: jnp.ndarray) -> jnp.ndarray:
        c = self.features
        n = a.shape[0]
        lmax_a = int(math.isqrt(a.shape[-1])) - 1
        # same scatter-free per-l accumulate + single concat pattern as the
        # interaction's message build (_concat_by_l)
        out_by_l = [[] for _ in range(self.lmax_out + 1)]
        b = a
        lmax_b = lmax_a
        for k in range(1, self.correlation + 1):
            if k > 1:
                new_lmax = min(self.lmax_keep, lmax_b + lmax_a)
                if _dense_cg_enabled():
                    # unweighted path-sum -> one contraction with the
                    # accumulated block CG tensor (exactly the loop's sum)
                    b = jnp.einsum(
                        "ncm,ncj,mjk->nck",
                        b,
                        a,
                        jnp.asarray(summed_cg(lmax_b, lmax_a, new_lmax), a.dtype),
                    )
                else:
                    nb_by_l = [[] for _ in range(new_lmax + 1)]
                    for l1, l2, l3 in tp_paths(lmax_b, lmax_a, new_lmax):
                        nb_by_l[l3].append(
                            couple(
                                b[:, :, irrep_slice(l1)],
                                a[:, :, irrep_slice(l2)],
                                l1,
                                l2,
                                l3,
                            )
                        )
                    b = _concat_by_l(nb_by_l, (n,), c, a.dtype)
                lmax_b = new_lmax
            for l in range(min(self.lmax_out, lmax_b) + 1):
                w = self.param(
                    f"w{k}_{l}",
                    nn.initializers.normal(1.0 / math.sqrt(NUM_ELEMENTS)),
                    (NUM_ELEMENTS, c),
                    a.dtype,
                )
                wn = node_attrs @ w  # [N, C] element-dependent mixing
                out_by_l[l].append(wn[:, :, None] * b[:, :, irrep_slice(l)])
        return _concat_by_l(out_by_l, (n,), c, a.dtype)


class MACEConv(nn.Module):
    """One interaction + product layer mapping node irreps
    [N, C, *] -> [N, C, (lmax_out+1)^2]."""

    features: int
    max_ell: int
    node_max_ell: int
    avg_num_neighbors: float
    correlation: int
    last_layer: bool = False
    sorted_agg: bool = False
    max_in_degree: int = 0

    @nn.compact
    def __call__(self, h, sh, radial, node_attrs, batch):
        lmax_out = 0 if self.last_layer else self.node_max_ell
        agg, sc = MACEInteraction(
            self.features,
            self.max_ell,
            self.node_max_ell,
            self.avg_num_neighbors,
            last_layer=self.last_layer,
            sorted_agg=self.sorted_agg,
            max_in_degree=self.max_in_degree,
            name="interaction",
        )(h, sh, radial, batch)
        prod = SymmetricProduct(
            self.features,
            lmax_out,
            self.correlation,
            lmax_keep=self.max_ell,
            name="product",
        )(agg, node_attrs)
        prod = EquivariantLinear(self.features, lmax_out, name="sizing")(prod)
        return prod + sc


class MACEModel(nn.Module):
    """Full MACE model with HydraGNN-style multihead decoding
    (reference: MACEStack.forward, MACEStack.py:367-400; multihead decoders
    blocks.py:417-899). Output contract matches ``HydraModel``: a dict of
    head-name -> [G, d] or [N, d], so every train/eval/loss path is shared.
    """

    cfg: ModelConfig

    @nn.compact
    def __call__(self, batch: GraphBatch, train: bool = False):
        cfg = self.cfg
        c = cfg.hidden_dim
        max_ell = int(cfg.max_ell or 3)
        node_max_ell = int(cfg.node_max_ell or 1)
        correlation = int(cfg.correlation or 2)
        avg_num_neighbors = float(cfg.avg_num_neighbors or 1.0)
        n_layers = cfg.num_conv_layers

        assert batch.z is not None, "MACE requires atomic numbers (batch.z)"
        z = jnp.clip(batch.z.astype(jnp.int32), 0, NUM_ELEMENTS)
        z_idx = jnp.clip(z - 1, 0, NUM_ELEMENTS - 1)  # one-hot slot for Z
        node_attrs = jax.nn.one_hot(z_idx, NUM_ELEMENTS, dtype=batch.pos.dtype)
        node_attrs = node_attrs * batch.node_mask.astype(batch.pos.dtype)[:, None]

        vec, length = edge_vectors(
            batch.pos, batch.senders, batch.receivers, batch.edge_shifts
        )
        sh = real_sph_harm(vec, max_ell)
        radial = RadialEmbedding(
            r_max=float(cfg.radius or 5.0),
            num_basis=int(cfg.num_radial or 8),
            radial_type=cfg.radial_type or "bessel",
            envelope_exponent=int(cfg.envelope_exponent or 5),
            distance_transform=cfg.distance_transform,
            name="radial_embedding",
        )(length, z=z, senders=batch.senders, receivers=batch.receivers)

        # outputs start from the 1-body readout on the one-hot attributes
        # (MACEStack.py:372-375)
        outputs = self._readout(node_attrs, batch, nonlinear=False, idx=0)

        h = nn.Dense(c, name="node_embedding")(node_attrs)[:, :, None]
        for i in range(n_layers):
            last = i == n_layers - 1
            h = MACEConv(
                c,
                max_ell,
                node_max_ell,
                avg_num_neighbors,
                correlation,
                last_layer=last,
                sorted_agg=cfg.sorted_aggregation,
                max_in_degree=cfg.max_in_degree,
                name=f"conv{i}",
            )(h, sh, radial, node_attrs, batch)
            layer_out = self._readout(
                h[:, :, 0], batch, nonlinear=last, idx=i + 1
            )
            outputs = {k: outputs[k] + v for k, v in layer_out.items()}
        return outputs

    def _readout(
        self, scalars: jnp.ndarray, batch: GraphBatch, nonlinear: bool, idx: int
    ) -> Dict[str, jnp.ndarray]:
        """Per-layer multihead decode of node scalars; graph heads pool first
        (reference: Linear/NonLinearMultiheadDecoderBlock, blocks.py:417-899)."""
        cfg = self.cfg
        B = cfg.num_branches
        outputs: Dict[str, jnp.ndarray] = {}
        pooled = None
        for ihead, (name, t, d) in enumerate(
            zip(cfg.output_names, cfg.output_type, cfg.output_dim)
        ):
            d_out = d * 2 if cfg.var_output else d
            prefix = f"readout{idx}_head{ihead}"
            # branch BANK: one module with stacked [B, ...] param leaves
            # (models/base.py _branch_bank) — same dense decode, but the
            # banks shard P('branch') under parallel/branch.py like the
            # HydraModel decoders
            if t == "graph":
                if pooled is None:
                    pooled = masked_global_mean_pool(
                        scalars,
                        batch.node_graph,
                        batch.num_graphs,
                        batch.node_mask,
                    )
                inp = pooled
            else:
                inp = scalars
            if nonlinear:
                if t == "graph":
                    gh = cfg.graph_head
                    dims = tuple(
                        gh.dim_headlayers if gh else (scalars.shape[-1],)
                    )
                else:
                    nh = cfg.node_head or NodeHeadConfig()
                    dims = tuple(nh.dim_headlayers)
                stacked = _branch_bank(MLP, B, in_axes=(None,))(
                    dims + (d_out,), cfg.activation, name=prefix
                )(inp)
            else:
                stacked = _branch_bank(nn.Dense, B, in_axes=(None,))(
                    d_out, name=prefix
                )(inp)
            if B == 1:
                out = stacked[0]
            else:
                ds = (
                    batch.dataset_id
                    if t == "graph"
                    else batch.dataset_id[batch.node_graph]
                )
                out = jnp.take_along_axis(
                    stacked, ds[None, :, None].astype(jnp.int32), axis=0
                )[0]
            if cfg.var_output:
                outputs[name] = out[..., :d]
                outputs[f"{name}__var"] = out[..., d:] ** 2
            else:
                outputs[name] = out
        return outputs
