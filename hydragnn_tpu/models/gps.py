"""GPS (GraphGPS) global attention layer.

(reference: hydragnn/globalAtt/gps.py:32-159 — local MPNN + residual + norm,
dense-batch global attention via ``to_dense_batch``/``key_padding_mask``, sum
of local+global, 2-layer MLP block, three norms.)

TPU re-design: ``to_dense_batch`` produces a data-dependent [B, Nmax, C]
layout; here attention runs directly over the flat padded node array with a
*same-graph* mask (node i attends to j iff node_graph[i] == node_graph[j] and
both are real). Static shapes, one fused masked attention per batch instead of
per-graph dense repacking. The ``performer`` variant exploits the
block-diagonal structure exactly: linear attention's KV moments are
segment-sums per graph, giving O(N) work with no [N, N] materialization.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..data.graph import GraphBatch
from ..ops.segment import segment_sum
from .layers import MaskedBatchNorm


class MultiheadSelfAttention(nn.Module):
    """torch.nn.MultiheadAttention equivalent (in-proj QKV, out-proj),
    masked to same-graph pairs."""

    channels: int
    heads: int
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, batch: GraphBatch, train: bool = False):
        H = self.heads
        C = self.channels
        assert C % H == 0, f"channels {C} not divisible by heads {H}"
        d = C // H
        qkv = nn.Dense(3 * C)(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(-1, H, d)
        k = k.reshape(-1, H, d)
        v = v.reshape(-1, H, d)
        # same-graph attention mask [N, N]
        same = (batch.node_graph[:, None] == batch.node_graph[None, :]) & (
            batch.node_mask[:, None] & batch.node_mask[None, :]
        )
        logits = jnp.einsum("ihd,jhd->hij", q, k) / jnp.sqrt(d).astype(x.dtype)
        logits = jnp.where(same[None], logits, jnp.finfo(x.dtype).min)
        probs = jax.nn.softmax(logits, axis=-1)
        # rows with no valid key (padding nodes) produce uniform garbage;
        # they are masked out downstream.
        if self.dropout > 0 and train:
            probs = nn.Dropout(self.dropout, deterministic=not train)(probs)
        out = jnp.einsum("hij,jhd->ihd", probs, v).reshape(-1, C)
        return nn.Dense(C)(out)


class PerformerSelfAttention(nn.Module):
    """Linear (Performer-style) attention per graph segment.

    (reference option: PyG PerformerAttention, gps.py:62-67.) Uses the relu
    feature map; per-graph KV moments via segment_sum — O(N d^2), no softmax
    matrix. Exact for the block-diagonal same-graph mask.
    """

    channels: int
    heads: int

    @nn.compact
    def __call__(self, x, batch: GraphBatch, train: bool = False):
        H = self.heads
        C = self.channels
        d = C // H
        q = nn.relu(nn.Dense(C)(x)).reshape(-1, H, d) + 1e-6
        k = nn.relu(nn.Dense(C)(x)).reshape(-1, H, d) + 1e-6
        v = nn.Dense(C)(x).reshape(-1, H, d)
        kv = jnp.einsum("nhd,nhe->nhde", k, v)  # [N, H, d, d]
        G = batch.num_graphs
        kv_sum = segment_sum(kv, batch.node_graph, G, batch.node_mask)
        k_sum = segment_sum(k, batch.node_graph, G, batch.node_mask)
        num = jnp.einsum("nhd,nhde->nhe", q, kv_sum[batch.node_graph])
        den = jnp.einsum("nhd,nhd->nh", q, k_sum[batch.node_graph])
        out = num / jnp.maximum(den[..., None], 1e-6)
        return nn.Dense(C)(out.reshape(-1, C))


class GPSConv(nn.Module):
    """(reference: GPSConv.forward, gps.py:103-151)"""

    channels: int
    conv: Optional[Any]
    heads: int = 1
    dropout: float = 0.0
    attn_type: str = "multihead"

    @nn.compact
    def __call__(self, inv, equiv, batch: GraphBatch, train: bool = False):
        hs = []
        # local MPNN + dropout + residual + norm1
        if self.conv is not None:
            h, equiv = self.conv(inv, equiv, batch, train)
            h = nn.Dropout(self.dropout, deterministic=not train)(h)
            h = h + inv
            h = MaskedBatchNorm()(h, batch.node_mask, train)
            hs.append(h)

        # global attention + dropout + residual + norm2
        if self.attn_type == "performer":
            h = PerformerSelfAttention(self.channels, self.heads)(inv, batch, train)
        elif self.attn_type == "multihead":
            h = MultiheadSelfAttention(self.channels, self.heads, self.dropout)(
                inv, batch, train
            )
        else:
            raise ValueError(f"attn_type {self.attn_type!r} not supported")
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        h = h + inv
        h = MaskedBatchNorm()(h, batch.node_mask, train)
        hs.append(h)

        out = sum(hs)
        # MLP block + norm3
        mlp = nn.Sequential(
            [
                nn.Dense(2 * self.channels),
                nn.relu,
                nn.Dropout(self.dropout, deterministic=not train),
                nn.Dense(self.channels),
                nn.Dropout(self.dropout, deterministic=not train),
            ]
        )
        out = out + mlp(out)
        out = MaskedBatchNorm()(out, batch.node_mask, train)
        return out, equiv
