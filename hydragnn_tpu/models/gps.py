"""GPS (GraphGPS) global attention layer.

(reference: hydragnn/globalAtt/gps.py:32-159 — local MPNN + residual + norm,
dense-batch global attention via ``to_dense_batch``/``key_padding_mask``, sum
of local+global, 2-layer MLP block, three norms.)

TPU re-design: attention is block-diagonal over graphs. With a static
per-graph node bound ``max_nodes_per_graph`` (data-derived at config
completion, like the reference's ``to_dense_batch`` Nmax) the multihead path
gathers nodes into a per-graph dense ``[G, Nmax, C]`` layout inside jit —
cost G*Nmax^2, matching the reference's per-graph dense attention
(gps.py:125-141) — then scatters back to the flat node array. Shapes stay
static because graphs are laid out contiguously by the batcher. Without the
bound it falls back to one masked attention over the flat padded batch
(cost N^2). The ``performer`` variant exploits the block-diagonal structure
exactly: linear attention's KV moments are segment-sums per graph, giving
O(N) work with no attention matrix at all.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..data.graph import GraphBatch
from ..ops.segment import segment_sum
from .layers import MaskedBatchNorm


class MultiheadSelfAttention(nn.Module):
    """torch.nn.MultiheadAttention equivalent (in-proj QKV, out-proj),
    restricted to same-graph pairs.

    With ``max_nodes_per_graph > 0`` the block-diagonal structure is
    exploited: nodes are gathered per graph into [G, Nmax, H, d] and dense
    attention runs within each graph — B*Nmax^2 work, the reference's
    ``to_dense_batch`` semantics (gps.py:125-141). The gather/scatter indices
    derive from ``node_graph`` alone (graphs are contiguous in the flat
    layout), so everything stays static-shaped under jit. Numerics match the
    flat-masked fallback exactly: every real node attends to exactly the real
    nodes of its own graph either way.

    ``use_flash_attention`` (Architecture.use_flash_attention, auto-on for
    TPU jit targets in config completion) routes the same math through the
    segment-masked Pallas flash kernel (ops/pallas_flash_attention.py):
    online-softmax tiling over the flat node array with a block-sparse
    schedule — cross-graph tiles are never visited and the score matrix
    never touches HBM. The dense layouts below stay as the equivalence
    oracle (and the route wherever the kernel cannot engage:
    ``HYDRAGNN_PALLAS_FLASH=0``, no static node bound, or an attention-prob
    dropout request — the probabilities the dropout would mask never exist
    on the flash path, so flash configs carry prob-dropout 0 on EVERY
    backend; GPSConv's output dropout is unchanged).
    """

    channels: int
    heads: int
    dropout: float = 0.0
    max_nodes_per_graph: int = 0
    use_flash_attention: bool = False
    # Training.remat_policy save rule at the kernel call site (ops/remat.py)
    remat_policy: str = "full"

    @nn.compact
    def __call__(self, x, batch: GraphBatch, train: bool = False):
        H = self.heads
        C = self.channels
        assert C % H == 0, f"channels {C} not divisible by heads {H}"
        d = C // H
        qkv = nn.Dense(3 * C)(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        scale = jnp.sqrt(d).astype(x.dtype)

        from ..ops.pallas_flash_attention import _flash_route_enabled

        prob_dropout = self.dropout > 0 and train
        if (
            self.use_flash_attention
            and self.max_nodes_per_graph > 0
            and not prob_dropout
            and _flash_route_enabled()
        ):
            from ..ops.pallas_flash_attention import flash_self_attention

            N = x.shape[0]
            Nmax = self.max_nodes_per_graph
            interpret = jax.default_backend() != "tpu"

            # block constants via the tuned-table lookup (tuned entry ->
            # swept winner, none -> pinned defaults; tune/runtime.py)
            from ..tune.runtime import tile_plan

            plan = tile_plan("flash_attention", {
                "nodes": N, "heads": H, "head_dim": d,
                "max_nodes_per_graph": Nmax,
            }, x.dtype)

            # remat per Training.remat_policy (ops/remat.py; default =
            # bare jax.checkpoint) keeps the tangent rule's residuals
            # (per-graph probability blocks) out of the training forward:
            # the forward stays VMEM-resident, the backward recomputes
            # gathered-dense
            from ..ops.remat import kernel_remat, tag as remat_tag

            def attend(qf, kf, vf):
                return remat_tag(flash_self_attention(
                    qf, kf, vf, batch.node_graph, batch.node_mask,
                    batch.num_graphs, Nmax, block_q=plan["block_q"],
                    block_k=plan["block_k"], interpret=interpret,
                ), "flash_attention_out")

            out = kernel_remat(attend, self.remat_policy)(
                q.reshape(N, H, d), k.reshape(N, H, d), v.reshape(N, H, d)
            ).reshape(N, C)
            # same poison contract as the gathered layout below: a graph
            # past the static bound under-covers its key window — surface
            # as NaN loss, never as silently wrong numbers
            overflow = jnp.any(
                (batch.nodes_per_graph > Nmax) & batch.graph_mask
            )
            out = jnp.where(overflow, jnp.nan, out)
        elif self.max_nodes_per_graph > 0:
            N = x.shape[0]
            G = batch.num_graphs
            Nmax = self.max_nodes_per_graph
            counts = batch.nodes_per_graph  # [G]
            starts = jnp.concatenate(
                [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)[:-1].astype(jnp.int32)]
            )
            slot = jnp.arange(Nmax, dtype=jnp.int32)
            valid = (slot[None, :] < counts[:, None]) & batch.graph_mask[:, None]
            # flat node id of slot r in graph g; invalid slots hit the last
            # node, which the pad spec guarantees is a padding node
            idx = jnp.where(valid, starts[:, None] + slot[None, :], N - 1)
            qg = q[idx].reshape(G, Nmax, H, d)
            kg = k[idx].reshape(G, Nmax, H, d)
            vg = v[idx].reshape(G, Nmax, H, d)
            logits = jnp.einsum("gihd,gjhd->ghij", qg, kg) / scale
            logits = jnp.where(
                valid[:, None, None, :], logits, jnp.finfo(x.dtype).min
            )
            probs = jax.nn.softmax(logits, axis=-1)
            if self.dropout > 0 and train:
                probs = nn.Dropout(self.dropout, deterministic=not train)(probs)
            og = jnp.einsum("ghij,gjhd->gihd", probs, vg).reshape(G * Nmax, C)
            out = jnp.zeros((N, C), x.dtype).at[idx.reshape(-1)].add(
                og * valid.reshape(-1, 1)
            )
            # a real graph larger than the static bound would be silently
            # truncated (its overflow nodes never gathered); poison the output
            # instead so the error surfaces as NaN loss, not wrong numbers
            overflow = jnp.any((counts > Nmax) & batch.graph_mask)
            out = jnp.where(overflow, jnp.nan, out)
        else:
            qf = q.reshape(-1, H, d)
            kf = k.reshape(-1, H, d)
            vf = v.reshape(-1, H, d)
            # same-graph attention mask [N, N]
            same = (batch.node_graph[:, None] == batch.node_graph[None, :]) & (
                batch.node_mask[:, None] & batch.node_mask[None, :]
            )
            logits = jnp.einsum("ihd,jhd->hij", qf, kf) / scale
            logits = jnp.where(same[None], logits, jnp.finfo(x.dtype).min)
            probs = jax.nn.softmax(logits, axis=-1)
            # rows with no valid key (padding nodes) produce uniform garbage;
            # they are masked out downstream.
            if self.dropout > 0 and train:
                probs = nn.Dropout(self.dropout, deterministic=not train)(probs)
            out = jnp.einsum("hij,jhd->ihd", probs, vf).reshape(-1, C)
        return nn.Dense(C)(out)


class RingSelfAttention(nn.Module):
    """Global attention for ONE graph spanning the device mesh
    (``global_attn_type: "ring"``): exact softmax attention with K/V blocks
    ring-rotated over the SP mesh axis (parallel/ring_attention.py), so the
    [N, N] score matrix never materializes on any one chip — node counts are
    bounded by total-mesh HBM, not one chip's (the reference's dense
    per-graph attention requires the whole graph on one device,
    hydragnn/globalAtt/gps.py:125-141).

    Inside a ``parallel.sp.sp_context`` the node axis is sharded and the
    ring runs over ICI; outside one it falls back to the SAME math computed
    densely (one device), so a checkpoint moves freely between modes.
    Restriction: attention spans every real node in the batch (no per-graph
    block mask) — the batch must hold a single real graph, the SP regime.

    With ``use_flash_attention`` the per-chip block-attend inside the ring
    runs the flash kernel's inner loop (ops/pallas_flash_attention.py
    ``flash_block_summary``) instead of a dense einsum: the local
    [n_q, n_k] score block stays in VMEM, and the online-softmax merge
    across ring steps happens in plain jnp (parallel/ring_attention.py).
    """

    channels: int
    heads: int
    use_flash_attention: bool = False

    @nn.compact
    def __call__(self, x, batch: GraphBatch, train: bool = False):
        from ..parallel.sp import current_sp

        H, C = self.heads, self.channels
        d = C // H
        qkv = nn.Dense(3 * C)(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(-1, H, d)
        k = k.reshape(-1, H, d)
        v = v.reshape(-1, H, d)
        mesh, axis = current_sp()
        if mesh is not None:
            from ..parallel.mesh import compat_shard_map as shard_map
            from jax.sharding import PartitionSpec as P

            from ..parallel.ring_attention import ring_self_attention

            use_flash = self.use_flash_attention
            # graftlint: disable=sharding_rules -- ring attention's collective lives with the model's attention math, not the state-placement rule table
            out = shard_map(
                lambda q_, k_, v_, m_: ring_self_attention(
                    q_, k_, v_, m_, axis_name=axis, use_flash=use_flash
                ),
                mesh=mesh,
                in_specs=(P(axis), P(axis), P(axis), P(axis)),
                out_specs=P(axis),
                check_vma=False,
            )(q, k, v, batch.node_mask)
        else:
            # dense fallback: same numbers as the ring (up to reassociation)
            scale = 1.0 / jnp.sqrt(jnp.asarray(d, x.dtype))
            logits = jnp.einsum("ihd,jhd->hij", q, k) * scale
            logits = jnp.where(
                batch.node_mask[None, None, :], logits, jnp.finfo(x.dtype).min
            )
            probs = jax.nn.softmax(logits, axis=-1)
            out = jnp.einsum("hij,jhd->ihd", probs, v)
        # ring attention spans EVERY real node — correct only for a batch
        # holding one real graph (the SP spanning-graph regime). A
        # multi-graph batch would silently mix molecules, so poison the
        # output and let the error surface as NaN loss (the house pattern
        # for silent-wrong-number risks, cf. the Nmax overflow above).
        multi = jnp.sum(batch.graph_mask.astype(jnp.int32)) > 1
        out = jnp.where(multi, jnp.nan, out)
        return nn.Dense(C)(out.reshape(-1, C))


class PerformerSelfAttention(nn.Module):
    """Linear (Performer-style) attention per graph segment.

    (reference option: PyG PerformerAttention, gps.py:62-67.) Uses the relu
    feature map; per-graph KV moments via segment_sum — O(N d^2), no softmax
    matrix. Exact for the block-diagonal same-graph mask.
    """

    channels: int
    heads: int

    @nn.compact
    def __call__(self, x, batch: GraphBatch, train: bool = False):
        H = self.heads
        C = self.channels
        d = C // H
        q = nn.relu(nn.Dense(C)(x)).reshape(-1, H, d) + 1e-6
        k = nn.relu(nn.Dense(C)(x)).reshape(-1, H, d) + 1e-6
        v = nn.Dense(C)(x).reshape(-1, H, d)
        kv = jnp.einsum("nhd,nhe->nhde", k, v)  # [N, H, d, d]
        G = batch.num_graphs
        kv_sum = segment_sum(kv, batch.node_graph, G, batch.node_mask)
        k_sum = segment_sum(k, batch.node_graph, G, batch.node_mask)
        num = jnp.einsum("nhd,nhde->nhe", q, kv_sum[batch.node_graph])
        den = jnp.einsum("nhd,nhd->nh", q, k_sum[batch.node_graph])
        out = num / jnp.maximum(den[..., None], 1e-6)
        return nn.Dense(C)(out.reshape(-1, C))


class GPSConv(nn.Module):
    """(reference: GPSConv.forward, gps.py:103-151)"""

    channels: int
    conv: Optional[Any]
    heads: int = 1
    dropout: float = 0.0
    attn_type: str = "multihead"
    max_nodes_per_graph: int = 0
    use_flash_attention: bool = False
    remat_policy: str = "full"

    @nn.compact
    def __call__(self, inv, equiv, batch: GraphBatch, train: bool = False):
        hs = []
        # local MPNN + dropout + residual + norm1
        if self.conv is not None:
            h, equiv = self.conv(inv, equiv, batch, train)
            h = nn.Dropout(self.dropout, deterministic=not train)(h)
            h = h + inv
            h = MaskedBatchNorm()(h, batch.node_mask, train)
            hs.append(h)

        # global attention + dropout + residual + norm2
        if self.attn_type == "performer":
            h = PerformerSelfAttention(self.channels, self.heads)(inv, batch, train)
        elif self.attn_type == "ring":
            h = RingSelfAttention(
                self.channels,
                self.heads,
                use_flash_attention=self.use_flash_attention,
            )(inv, batch, train)
        elif self.attn_type == "multihead":
            h = MultiheadSelfAttention(
                self.channels,
                self.heads,
                # attention-PROB dropout is incompatible with the flash
                # kernel (the probabilities never exist to mask); flash
                # configs zero it on every backend so the Pallas route and
                # the dense oracle train identically — the module-output
                # dropout below regularizes either way
                0.0 if self.use_flash_attention else self.dropout,
                self.max_nodes_per_graph,
                use_flash_attention=self.use_flash_attention,
                remat_policy=self.remat_policy,
            )(inv, batch, train)
        else:
            raise ValueError(f"attn_type {self.attn_type!r} not supported")
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        h = h + inv
        h = MaskedBatchNorm()(h, batch.node_mask, train)
        hs.append(h)

        out = sum(hs)
        # MLP block + norm3
        mlp = nn.Sequential(
            [
                nn.Dense(2 * self.channels),
                nn.relu,
                nn.Dropout(self.dropout, deterministic=not train),
                nn.Dense(self.channels),
                nn.Dropout(self.dropout, deterministic=not train),
            ]
        )
        out = out + mlp(out)
        out = MaskedBatchNorm()(out, batch.node_mask, train)
        return out, equiv
