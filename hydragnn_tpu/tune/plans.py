"""Tile plans: the tunable block/grid parameters of each Pallas kernel.

A *tile plan* is a plain ``{param: int}`` dict naming exactly the block
constants a kernel entry point takes as ``custom_jvp`` nondiff arguments
(``block_rows``/``block_edges``/... — ops/pallas_*.py). This module is the
registry of what is tunable: per kernel its pinned defaults (the values the
kernel signatures carry, so a missing tuned-table entry reproduces today's
behavior bit-identically), its candidate grid for sweeps, and its
normalization — the same clamp the kernel applies internally, applied
BEFORE a plan becomes a jit-specialization or tuned-table key.

Normalization is load-bearing twice over:

- ops/pallas_multi_agg.py clamps ``block_cols`` to the lane-padded channel
  width *inside* ``_forward``, but the nondiff argnums (and hence the jit
  executable cache) key on the caller's *unclamped* value — two requests
  that run the identical program used to compile twice. Each kernel now
  exports its clamp as ``normalize_tiles`` and the routing layer funnels
  every plan through :func:`normalize` first, so equivalent plans share
  one executable.
- the tuned table (tune/table.py) stores normalized plans under keys of
  normalized shapes: a sweep cannot record two entries that differ only in
  how far past the clamp they asked.

``KERNELS`` keys are the tuned-table kernel ids; versions come from each
kernel module's ``KERNEL_VERSION`` so a schedule change invalidates its
tuned entries by construction.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Dict, Iterable, List, Tuple

SEGMENT = "segment_sum"
FUSED_EDGE = "fused_edge"
MULTI_AGG = "multi_agg"
FLASH = "flash_attention"
INT8_DOT = "int8_dot"


@dataclasses.dataclass(frozen=True)
class KernelSpec:
    """What is tunable about one kernel: its tuned-table id, parameter
    names (the nondiff kwargs of the entry point), pinned defaults, and
    the sweep's candidate grid per parameter."""

    kernel: str
    params: Tuple[str, ...]
    defaults: Dict[str, int]
    grid: Dict[str, Tuple[int, ...]]

    @property
    def version(self) -> int:
        return kernel_version(self.kernel)


KERNELS: Dict[str, KernelSpec] = {
    SEGMENT: KernelSpec(
        kernel=SEGMENT,
        params=("block_rows", "block_edges", "block_cols"),
        defaults={"block_rows": 128, "block_edges": 512, "block_cols": 512},
        grid={
            "block_rows": (64, 128, 256),
            "block_edges": (256, 512, 1024),
            "block_cols": (128, 256, 512),
        },
    ),
    FUSED_EDGE: KernelSpec(
        kernel=FUSED_EDGE,
        params=("block_rows", "block_edges", "block_cols"),
        defaults={"block_rows": 128, "block_edges": 512, "block_cols": 512},
        grid={
            "block_rows": (64, 128, 256),
            "block_edges": (256, 512, 1024),
            "block_cols": (256, 512, 1024),
        },
    ),
    MULTI_AGG: KernelSpec(
        kernel=MULTI_AGG,
        params=("block_rows", "block_edges", "block_cols", "chunk_edges"),
        defaults={
            "block_rows": 128, "block_edges": 512, "block_cols": 128,
            "chunk_edges": 32,
        },
        grid={
            "block_rows": (64, 128, 256),
            "block_edges": (256, 512, 1024),
            "block_cols": (128, 256),
            "chunk_edges": (16, 32, 64),
        },
    ),
    FLASH: KernelSpec(
        kernel=FLASH,
        params=("block_q", "block_k"),
        defaults={"block_q": 128, "block_k": 128},
        grid={
            "block_q": (64, 128, 256),
            "block_k": (128, 256, 512),
        },
    ),
    # int8 inference matmul (ops/quant.py int8_matmul): its own table axis
    # keyed under dtype="int8" so quantized executables are tuned and
    # looked up separately from the f32/bf16 plans for the same shapes
    INT8_DOT: KernelSpec(
        kernel=INT8_DOT,
        params=("block_m", "block_n", "block_k"),
        defaults={"block_m": 128, "block_n": 128, "block_k": 128},
        grid={
            "block_m": (64, 128, 256),
            "block_n": (128, 256),
            "block_k": (128, 256, 512),
        },
    ),
}


def kernel_version(kernel: str) -> int:
    """The kernel module's ``KERNEL_VERSION`` — imported lazily so plan
    bookkeeping (table keys, CLI listings) does not pull jax in first."""
    if kernel == SEGMENT:
        from ..ops import pallas_segment as m
    elif kernel == FUSED_EDGE:
        from ..ops import pallas_fused_edge as m
    elif kernel == MULTI_AGG:
        from ..ops import pallas_multi_agg as m
    elif kernel == FLASH:
        from ..ops import pallas_flash_attention as m
    elif kernel == INT8_DOT:
        from ..ops import quant as m
    else:
        raise KeyError(f"unknown kernel {kernel!r}")
    return int(m.KERNEL_VERSION)


def normalize(kernel: str, plan: Dict[str, int],
              shapes: Dict[str, Any]) -> Dict[str, int]:
    """Clamp ``plan`` exactly the way the kernel's ``_forward`` will, via
    the kernel module's own ``normalize_tiles`` (one clamp site — the
    routing layer, the table keys and the kernel cannot drift apart).

    ``shapes`` carries the operand facts each clamp needs:
    ``channels`` (segment/multi_agg), ``ci``/``co`` (fused_edge),
    ``dtype`` (fused_edge/multi_agg VMEM estimates, a numpy dtype name),
    ``has_recv``/``has_gate`` (multi_agg operand census).
    """
    p = {**KERNELS[kernel].defaults, **{k: int(v) for k, v in plan.items()}}
    if kernel == SEGMENT:
        from ..ops.pallas_segment import normalize_tiles

        nb, eb, cb = normalize_tiles(
            int(shapes["channels"]),
            p["block_rows"], p["block_edges"], p["block_cols"],
        )
        return {"block_rows": nb, "block_edges": eb, "block_cols": cb}
    if kernel == FUSED_EDGE:
        from ..ops.pallas_fused_edge import normalize_tiles

        nb, eb, cb = normalize_tiles(
            int(shapes["ci"]), int(shapes["co"]),
            shapes.get("dtype", "float32"),
            p["block_rows"], p["block_edges"], p["block_cols"],
        )
        return {"block_rows": nb, "block_edges": eb, "block_cols": cb}
    if kernel == MULTI_AGG:
        from ..ops.pallas_multi_agg import normalize_tiles

        nb, eb, cb, chunk = normalize_tiles(
            int(shapes["channels"]), shapes.get("dtype", "float32"),
            bool(shapes.get("has_recv", True)),
            bool(shapes.get("has_gate", False)),
            p["block_rows"], p["block_edges"], p["block_cols"],
            p["chunk_edges"],
        )
        return {"block_rows": nb, "block_edges": eb, "block_cols": cb,
                "chunk_edges": chunk}
    if kernel == FLASH:
        from ..ops.pallas_flash_attention import normalize_tiles

        bq, bk = normalize_tiles(p["block_q"], p["block_k"])
        return {"block_q": bq, "block_k": bk}
    if kernel == INT8_DOT:
        from ..ops.quant import normalize_tiles

        bm, bn, bk = normalize_tiles(
            int(shapes.get("rows", 0)), int(shapes.get("cols", 0)),
            int(shapes.get("k", 0)),
            p["block_m"], p["block_n"], p["block_k"],
        )
        return {"block_m": bm, "block_n": bn, "block_k": bk}
    raise KeyError(f"unknown kernel {kernel!r}")


def default_plan(kernel: str, shapes: Dict[str, Any]) -> Dict[str, int]:
    """The pinned defaults, normalized for these shapes — what a kernel
    with no tuned-table entry runs (bit-identical to the pre-tune-plane
    behavior: the kernel applied the same clamp internally)."""
    return normalize(kernel, KERNELS[kernel].defaults, shapes)


def candidates(kernel: str, shapes: Dict[str, Any],
               budget: int = 0) -> List[Dict[str, int]]:
    """The sweep's candidate plans: the grid's cartesian product,
    normalized and deduplicated (distinct requests that clamp to the same
    program are ONE candidate), pinned defaults first, capped at
    ``budget`` candidates when positive."""
    spec = KERNELS[kernel]
    seen: Dict[Tuple[int, ...], Dict[str, int]] = {}
    pool: Iterable[Tuple[int, ...]] = itertools.product(
        *(spec.grid[p] for p in spec.params)
    )
    plans = [dict(spec.defaults)]
    plans += [dict(zip(spec.params, combo)) for combo in pool]
    for plan in plans:
        norm = normalize(kernel, plan, shapes)
        key = tuple(norm[p] for p in spec.params)
        if key not in seen:
            seen[key] = norm
    out = list(seen.values())
    if budget and budget > 0:
        out = out[: max(1, int(budget))]
    return out
