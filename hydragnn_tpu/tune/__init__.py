"""Kernel autotuning plane (docs/TUNING.md).

Per-(kernel id + version, device kind, ladder spec slot, dtype) tile
sweeps with a content-addressed tuned-table cache, retiring the
hand-picked Pallas block constants:

- tune/plans.py — what is tunable: per-kernel params, pinned defaults,
  candidate grids, and the shared normalization (the kernel's own clamp,
  applied before a plan becomes a jit or table key);
- tune/table.py — the sha256-keyed on-disk table (atomic publishes,
  corrupt entries degrade to defaults);
- tune/sweep.py — the offline sweep: bench-discipline medians over
  normalized candidates on shape-exact synthetic operands;
- tune/runtime.py — the process-global lookup the kernel routing layer
  consults (``tile_plan``), with the choice emitted for the run doctor;
- ``python -m hydragnn_tpu.tune`` — the offline CLI over a config's full
  SpecLadder (interpret-mode on CPU, so CI exercises the plane end to
  end).

``Training.autotune`` (off | cached | sweep) threads the plane through
train warm-up and serve startup (docs/CONFIG.md).
"""

from . import plans, runtime, sweep, table  # noqa: F401
from .plans import KERNELS, candidates, default_plan, normalize  # noqa: F401
from .runtime import deactivate, install, setup_autotune, tile_plan  # noqa: F401
from .table import TunedTable, device_kind, resolve_tune_cache  # noqa: F401
from .sweep import config_slots, sweep_kernel, sweep_slots  # noqa: F401
