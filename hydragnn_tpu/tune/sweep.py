"""The offline tile sweep: measure candidates, persist winners.

Measurement reuses bench.py's timing discipline — jit the kernel call with
the candidate's static block constants, warm it up (compile + first
dispatch excluded), then take the median of k timed dispatches behind
``jax.block_until_ready``. Off-TPU the kernels run in interpret mode, so
CI exercises the whole plane (sweep -> table write -> cache hit -> routed
plan) on CPU; interpret-mode medians are meaningless as *tile* guidance
but key under ``device="cpu"`` and are therefore invisible to TPU runs.

Operands are synthetic but shape-exact: each spec slot's padded sizes,
the model's channel widths, degree-capped sorted segment ids — the same
static facts the routing layer hands :func:`tune.runtime.tile_plan`, so a
sweep's table keys are the keys training will look up.
"""

from __future__ import annotations

import statistics
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import plans
from .table import TunedTable, device_kind

# sweep knobs: medians over K timed dispatches after W warm-ups — small
# because each candidate is one executable of one kernel, not a train step
DEFAULT_TRIALS = 5
DEFAULT_WARMUP = 2


def measure(fn: Callable[[], Any], n_trials: int = DEFAULT_TRIALS,
            n_warmup: int = DEFAULT_WARMUP) -> float:
    """Median wall seconds of ``fn()`` over ``n_trials`` dispatches, after
    ``n_warmup`` untimed ones (compile + first-touch excluded), every
    dispatch fenced by ``block_until_ready`` — bench.py's discipline."""
    import jax

    for _ in range(max(1, n_warmup)):
        jax.block_until_ready(fn())
    times = []
    for _ in range(max(1, n_trials)):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def _sorted_ids(edges: int, num_segments: int, max_degree: int):
    """Degree-capped ascending segment ids: each segment owns
    ``min(max_degree, ceil(edges/num_segments))`` consecutive edges,
    overflow edges land on the final (dummy-node) segment — the same
    layout GraphLoader(sort_edges=True) produces for a padded batch."""
    import numpy as np

    deg = max(1, min(max_degree or 1, -(-edges // max(num_segments, 1))))
    ids = np.minimum(np.arange(edges) // deg, num_segments - 1)
    return ids.astype(np.int32)


def build_call(kernel: str, shapes: Dict[str, Any], dtype: str,
               plan: Dict[str, int],
               interpret: Optional[bool] = None) -> Callable[[], Any]:
    """A zero-arg jitted dispatch of ``kernel`` on synthetic shape-exact
    operands with ``plan``'s block constants baked in as statics."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    rng = np.random.default_rng(0)

    def _arr(shape):
        return jnp.asarray(rng.standard_normal(shape), dtype)

    if kernel == plans.SEGMENT:
        e, c = int(shapes["edges"]), int(shapes["channels"])
        n, d = int(shapes["num_segments"]), int(shapes["max_degree"])
        from ..ops.pallas_segment import sorted_segment_sum

        msg = _arr((e, c))
        ids = jnp.asarray(_sorted_ids(e, n, d))
        fn = jax.jit(lambda m: sorted_segment_sum(
            m, ids, n, d, plan["block_rows"], plan["block_edges"],
            plan["block_cols"], interpret,
        ))
        return lambda: fn(msg)
    if kernel == plans.FUSED_EDGE:
        e, ci, co = int(shapes["edges"]), int(shapes["ci"]), int(shapes["co"])
        n, d = int(shapes["num_segments"]), int(shapes["max_degree"])
        from ..ops.pallas_fused_edge import fused_edge_message_sum

        nrecv, ein = _arr((n, ci)), _arr((e, ci))
        w, b = _arr((ci, co)), _arr((co,))
        ids = jnp.asarray(_sorted_ids(e, n, d))
        fn = jax.jit(lambda nr, x: fused_edge_message_sum(
            nr, x, w, b, ids, n, d, plan["block_rows"],
            plan["block_edges"], plan["block_cols"], interpret,
        ))
        return lambda: fn(nrecv, ein)
    if kernel == plans.MULTI_AGG:
        e, c = int(shapes["edges"]), int(shapes["channels"])
        n, d = int(shapes["num_segments"]), int(shapes["max_degree"])
        from ..ops.pallas_multi_agg import fused_multi_agg

        nrecv = _arr((n, c)) if shapes.get("has_recv", True) else None
        gate = _arr((e, c)) if shapes.get("has_gate", False) else None
        ein = _arr((e, c))
        ids = jnp.asarray(_sorted_ids(e, n, d))
        fn = jax.jit(lambda nr, x, g: fused_multi_agg(
            nr, x, g, ids, n, d, plan["block_rows"], plan["block_edges"],
            plan["block_cols"], plan["chunk_edges"], interpret,
        ))
        return lambda: fn(nrecv, ein, gate)
    if kernel == plans.FLASH:
        n, h, dh = int(shapes["nodes"]), int(shapes["heads"]), int(shapes["head_dim"])
        nmax = int(shapes["max_nodes_per_graph"])
        from ..ops.pallas_flash_attention import flash_self_attention

        q, k, v = _arr((n, h, dh)), _arr((n, h, dh)), _arr((n, h, dh))
        node_graph = jnp.asarray(
            np.minimum(np.arange(n) // max(nmax, 1),
                       max(-(-n // max(nmax, 1)) - 1, 0)).astype(np.int32))
        node_mask = jnp.ones((n,), bool)
        num_graphs = int(node_graph[-1]) + 1 if n else 1
        fn = jax.jit(lambda q_, k_, v_: flash_self_attention(
            q_, k_, v_, node_graph, node_mask, num_graphs, nmax,
            plan["block_q"], plan["block_k"], interpret,
        ))
        return lambda: fn(q, k, v)
    raise KeyError(f"unknown kernel {kernel!r}")


def sweep_kernel(
    kernel: str,
    shapes: Dict[str, Any],
    dtype: str,
    table: TunedTable,
    budget: int = 0,
    trials: int = DEFAULT_TRIALS,
    interpret: Optional[bool] = None,
    force: bool = False,
) -> Dict[str, Any]:
    """Sweep one kernel on one shape signature and publish the winner.

    Returns a result record: ``cached=True`` when the table already held
    this key (nothing measured — the CLI's second invocation is 100% of
    these), else the candidate census, the winning plan, and the
    default-plan/winner medians for the BENCH_TUNE A/B cells.
    Candidates that fail to compile or run are skipped with a warning —
    an over-budget tile on real hardware is a skipped point, not a failed
    sweep.
    """
    from .runtime import _shape_key

    spec = plans.KERNELS[kernel]
    dev = device_kind()
    key_shape = _shape_key(shapes)
    existing = table.lookup(kernel, spec.version, dev, dtype, key_shape)
    if existing is not None and not force:
        return {"kernel": kernel, "cached": True, "plan": existing,
                "shape": key_shape}

    cands = plans.candidates(kernel, shapes, budget)
    default = plans.default_plan(kernel, shapes)
    t_sweep0 = time.perf_counter()
    timed: List[Tuple[float, Dict[str, int]]] = []
    default_s: Optional[float] = None
    for plan in cands:
        try:
            sec = measure(build_call(kernel, shapes, dtype, plan, interpret),
                          n_trials=trials)
        except Exception as e:  # over-budget tile, interpret quirk, ...
            warnings.warn(
                f"tune sweep: candidate {plan} for {kernel} failed ({e}); "
                "skipping",
                RuntimeWarning,
                stacklevel=2,
            )
            continue
        timed.append((sec, plan))
        if plan == default:
            default_s = sec
    if not timed:
        raise RuntimeError(
            f"tune sweep: every candidate failed for kernel {kernel!r} "
            f"shapes {key_shape} — nothing to publish"
        )
    best_s, best = min(timed, key=lambda t: t[0])
    table.store(
        kernel, spec.version, dev, dtype, key_shape, best,
        measured_us=best_s * 1e6,
        meta={
            "candidates": len(timed),
            "default_us": default_s * 1e6 if default_s is not None else None,
            "trials": trials,
        },
    )
    _sweep_gauge().set(time.perf_counter() - t_sweep0, kernel=kernel)
    return {
        "kernel": kernel, "cached": False, "plan": best, "shape": key_shape,
        "candidates": len(timed), "best_us": best_s * 1e6,
        "default_us": default_s * 1e6 if default_s is not None else None,
    }


def _sweep_gauge():
    from ..obs.registry import registry

    return registry().gauge(
        "hydragnn_tune_sweep_seconds",
        "Wall seconds of the last tile sweep per kernel (docs/TUNING.md)",
        labelnames=("kernel",),
    )


def config_slots(config: Dict[str, Any],
                 ladder=None) -> List[Tuple[str, Dict[str, Any], str]]:
    """The (kernel, shapes, dtype) sweep slots a completed config implies:
    one slot per enabled kernel per SpecLadder level, built from the same
    static facts the routing layer will hand ``tile_plan`` at trace time.

    ``ladder`` is the data pipeline's SpecLadder; the CLI obtains it via
    ``api.prepare_data`` (the config alone does not know the pad levels).
    """
    arch = config["NeuralNetwork"]["Architecture"]
    training = config["NeuralNetwork"].get("Training", {})
    hidden = int(arch.get("hidden_dim") or 0)
    max_deg = int(arch.get("max_in_degree") or 0)
    heads = int(arch.get("global_attn_heads") or 0)
    nmax = int(arch.get("max_nodes_per_graph") or 0)
    dtype = "bfloat16" if training.get("mixed_precision") else "float32"
    pna = str(arch.get("mpnn_type", "")).upper().startswith("PNA")
    specs = list(ladder.specs) if ladder is not None else []
    slots: List[Tuple[str, Dict[str, Any], str]] = []
    for ps in specs:
        n, e = int(ps.n_nodes), int(ps.n_edges)
        if arch.get("use_sorted_aggregation") and max_deg:
            slots.append((plans.SEGMENT, {
                "edges": e, "channels": hidden, "num_segments": n,
                "max_degree": max_deg,
            }, dtype))
        if arch.get("use_fused_edge_kernel") and max_deg:
            slots.append((plans.FUSED_EDGE, {
                "edges": e, "ci": hidden, "co": hidden, "num_segments": n,
                "max_degree": max_deg, "dtype": dtype,
            }, dtype))
        if pna and arch.get("use_sorted_aggregation") and max_deg:
            slots.append((plans.MULTI_AGG, {
                "edges": e, "channels": hidden, "num_segments": n,
                "max_degree": max_deg, "has_recv": True, "has_gate": False,
                "dtype": dtype,
            }, dtype))
        if arch.get("use_flash_attention") and heads and nmax:
            slots.append((plans.FLASH, {
                "nodes": n, "heads": heads, "head_dim": hidden // heads,
                "max_nodes_per_graph": nmax,
            }, dtype))
    return slots


def sweep_slots(
    slots: List[Tuple[str, Dict[str, Any], str]],
    table: TunedTable,
    budget: int = 0,
    trials: int = DEFAULT_TRIALS,
    interpret: Optional[bool] = None,
    force: bool = False,
    log: Optional[Callable[[str], None]] = None,
) -> Dict[str, Any]:
    """Sweep every slot into ``table`` (traced as a ``tune_sweep`` span
    when a tracer is live) and return the census the CLI prints:
    ``{"entries": N, "hits": H, "swept": S, "results": [...]}``."""
    from ..obs import trace

    results = []
    tr = trace.active()
    span = (tr.span("tune_sweep", slots=len(slots)) if tr is not None
            else _nullcontext())
    with span:
        for kernel, shapes, dtype in slots:
            res = sweep_kernel(
                kernel, shapes, dtype, table, budget=budget, trials=trials,
                interpret=interpret, force=force,
            )
            results.append(res)
            if log:
                if res.get("cached"):
                    log(f"  {kernel}: HIT (cached) plan={res['plan']}")
                else:
                    d, b = res.get("default_us"), res.get("best_us")
                    gain = f" ({d / b:.2f}x vs default)" if d and b else ""
                    log(f"  {kernel}: swept {res['candidates']} candidates"
                        f" best={b:.1f}us{gain} plan={res['plan']}")
    hits = sum(1 for r in results if r.get("cached"))
    from .runtime import _entries_gauge

    _entries_gauge().set(float(table.size()))
    return {
        "entries": len(results),
        "hits": hits,
        "swept": len(results) - hits,
        "results": results,
    }


class _nullcontext:
    def __enter__(self):
        return None

    def __exit__(self, *a):
        return False
