"""Process-global tuned-table runtime: what the kernel routing layer asks.

The routing layer (ops/segment.py, models/gps.py, parallel/
ring_attention.py) cannot see the config — it runs at trace time inside
jitted model code. So the train/serve entry points *install* the resolved
tuned table here (``train/loop.py`` warm-up, ``serve/server.py`` startup,
the ``python -m hydragnn_tpu.tune`` CLI), and every kernel call site asks
:func:`tile_plan` for its block constants:

    tuned-table entry for (kernel+version, device kind, dtype, shapes)
        -> the swept winner
    no entry / no table / autotune off
        -> the pinned defaults, normalized — bit-identical to the
           pre-tune-plane behavior (the kernel applied the same clamp
           internally; only the jit cache key is now the clamped value)

Either way the choice is emitted once per (key, source) as an
``EV_TILE_PLAN`` telemetry event and counted in
``hydragnn_tune_lookups_total{kernel,source}``, so the run doctor can
flag TPU runs still riding defaults (obs/doctor.py ``untuned_kernel``).
"""

from __future__ import annotations

import json
import threading
from typing import Any, Dict, Optional, Tuple

from . import plans
from .table import TunedTable, device_kind

MODES = ("off", "cached", "sweep")

_lock = threading.Lock()
_active: Optional[TunedTable] = None
_mode: str = "off"
# (sha-key, source) pairs already announced — dedups the choice event and
# counter across retraces/re-lookups of the same specialization
_announced: set = set()


def install(table: Optional[TunedTable], mode: str = "cached") -> None:
    """Make ``table`` the process-wide tuned table (None deactivates).
    Last install wins — one live run per process, the same contract as the
    tracer/event-sink installs."""
    global _active, _mode
    if mode not in MODES:
        raise ValueError(f"autotune mode {mode!r} must be one of {MODES}")
    with _lock:
        _active = table if mode != "off" else None
        _mode = mode
        _announced.clear()
    if table is not None and mode != "off":
        _entries_gauge().set(float(table.size()))


def deactivate() -> None:
    install(None, "off")


def active() -> Optional[TunedTable]:
    return _active


def mode() -> str:
    return _mode


def _entries_gauge():
    from ..obs.registry import registry

    return registry().gauge(
        "hydragnn_tune_table_entries",
        "Tuned-table entries on disk for the installed table "
        "(docs/TUNING.md)",
    )


def _lookup_counter():
    from ..obs.registry import registry

    return registry().counter(
        "hydragnn_tune_lookups_total",
        "Tile-plan lookups by kernel and winning source "
        "(tuned = table entry, default = pinned fallback)",
        labelnames=("kernel", "source"),
    )


def tile_plan(
    kernel: str,
    shapes: Dict[str, Any],
    dtype: Any = "float32",
) -> Dict[str, int]:
    """The block constants this kernel call should run with.

    ``shapes`` is the kernel's shape signature — every static fact that
    distinguishes tuned entries (pad-spec sizes, channel widths, operand
    census; see tune/plans.py ``normalize`` for the per-kernel fields) —
    and doubles as the normalization input. ``dtype`` is the streaming
    operand dtype (its own table axis: bf16 tiles do not transfer to f32).

    Always returns a normalized plan; never raises on table trouble (a
    corrupt entry warns inside TunedTable and falls through to defaults).
    """
    dt = str(dtype)
    spec = plans.KERNELS[kernel]
    table = _active
    tuned: Optional[Dict[str, int]] = None
    if table is not None:
        tuned = table.lookup(
            kernel, spec.version, device_kind(), dt, _shape_key(shapes)
        )
    source = "tuned" if tuned else "default"
    plan = plans.normalize(kernel, tuned or spec.defaults, shapes)
    _announce(kernel, dt, shapes, plan, source)
    return plan


def _shape_key(shapes: Dict[str, Any]) -> Dict[str, Any]:
    """The table-key view of a shape signature: scalars only, canonical
    types (bools stay bools, numbers become ints, anything else strs)."""
    out: Dict[str, Any] = {}
    for k, v in shapes.items():
        if isinstance(v, bool):
            out[k] = v
        elif isinstance(v, (int, float)):
            out[k] = int(v)
        else:
            out[k] = str(v)
    return out


def setup_autotune(config: Dict[str, Any], loader=None,
                   log_name: Optional[str] = None) -> Optional[str]:
    """Resolve and install the run's tuned table per ``Training.autotune``
    — the entry-point hook train warm-up and serve startup call BEFORE any
    jit trace, so every kernel route's ``tile_plan`` lookup sees it.

    ``off`` deactivates (pinned defaults, no lookups); ``cached`` installs
    the resolved table read-only (missing entries fall back to defaults);
    ``sweep`` first fills missing entries for the config's ladder slots
    (budget-capped, ``loader.ladder`` supplies the pad levels) and then
    installs. Returns the active table directory, or None.
    """
    import warnings

    from .table import resolve_tune_cache

    training = config["NeuralNetwork"]["Training"]
    autotune = str(training.get("autotune", "cached"))
    if autotune == "off":
        deactivate()
        return None
    cache_dir = resolve_tune_cache(training, log_name)
    if not cache_dir:
        deactivate()
        return None
    table = TunedTable(cache_dir)
    if autotune == "sweep":
        from .sweep import config_slots, sweep_slots

        ladder = getattr(loader, "ladder", None)
        slots = config_slots(config, ladder) if ladder is not None else []
        if slots:
            try:
                sweep_slots(
                    slots, table,
                    budget=int(training.get("autotune_budget") or 0),
                )
            except Exception as e:
                warnings.warn(
                    f"autotune sweep failed ({e}); continuing with the "
                    "existing tuned table",
                    RuntimeWarning,
                    stacklevel=2,
                )
    install(table, autotune)
    return cache_dir


def _announce(kernel: str, dtype: str, shapes: Dict[str, Any],
              plan: Dict[str, int], source: str) -> None:
    sig: Tuple = (kernel, dtype, tuple(sorted(_shape_key(shapes).items())),
                  source)
    with _lock:
        if sig in _announced:
            return
        _announced.add(sig)
    try:
        from ..obs.events import EV_TILE_PLAN, emit

        emit(
            EV_TILE_PLAN,
            kernel=kernel,
            source=source,
            mode=_mode,
            device=device_kind(),
            dtype=dtype,
            plan=json.dumps(plan, sort_keys=True),
            shape=json.dumps(_shape_key(shapes), sort_keys=True),
        )
        _lookup_counter().inc(kernel=kernel, source=source)
    except Exception:
        pass  # the choice reporter must never fail the kernel call
