"""``python -m hydragnn_tpu.tune`` — offline tile sweeps for a config.

Loads the config, builds its data pipeline (the SpecLadder's pad levels
come from the dataset, exactly as training sees them), derives one sweep
slot per enabled Pallas kernel per ladder level, and sweeps each into the
tuned table. Off-TPU the kernels run in interpret mode: the timings are
not tile guidance (they key under the CPU device kind and a TPU run never
reads them), but CI exercises the full plane — sweep, atomic table write,
and the 100%-cache-hit second invocation.

    python -m hydragnn_tpu.tune config.json
    python -m hydragnn_tpu.tune config.json --budget 8 --trials 3
    python -m hydragnn_tpu.tune config.json --cache-dir /nfs/tuned_table
    python -m hydragnn_tpu.tune config.json --kernels flash_attention

Exit 0 with a per-slot report; the summary line counts entries, cache
hits, and fresh sweeps (docs/TUNING.md runbook).
"""

from __future__ import annotations

import argparse
import sys
from typing import Any, Dict, List, Optional


def main(argv: Optional[List[str]] = None) -> Dict[str, Any]:
    ap = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.tune",
        description="offline Pallas tile sweeps over a config's SpecLadder",
    )
    ap.add_argument("config", help="config JSON path")
    ap.add_argument("--budget", type=int, default=None,
                    help="max candidates per (kernel, slot) sweep "
                         "(default: Training.autotune_budget)")
    ap.add_argument("--trials", type=int, default=None,
                    help="timed dispatches per candidate (median-of-k)")
    ap.add_argument("--cache-dir", default=None,
                    help="tuned-table directory (default: the config's "
                         "Training.autotune_cache_dir resolution)")
    ap.add_argument("--kernels", default=None,
                    help="comma-separated kernel-id filter "
                         "(segment_sum,fused_edge,multi_agg,flash_attention)")
    ap.add_argument("--force", action="store_true",
                    help="re-sweep keys the table already holds")
    args = ap.parse_args(argv)

    from ..api import load_config, prepare_data
    from ..config import get_log_name_config
    from . import sweep as sweep_mod
    from .table import TunedTable, resolve_tune_cache

    config = load_config(args.config)
    config, loaders, _ = prepare_data(config)
    train_loader = loaders[0]
    ladder = getattr(train_loader, "ladder", None)
    if ladder is None:
        print("tune: the config's loader has no SpecLadder; nothing to "
              "sweep", file=sys.stderr)
        return {"entries": 0, "hits": 0, "swept": 0, "results": []}

    training = config["NeuralNetwork"]["Training"]
    cache_dir = args.cache_dir or resolve_tune_cache(
        training, get_log_name_config(config)
    )
    if not cache_dir:
        print("tune: tuned-table cache is disabled "
              "(Training.autotune_cache_dir=false / HYDRAGNN_TUNE_CACHE=off)"
              " — pass --cache-dir to sweep anyway", file=sys.stderr)
        return {"entries": 0, "hits": 0, "swept": 0, "results": []}

    slots = sweep_mod.config_slots(config, ladder)
    if args.kernels:
        keep = {k.strip() for k in args.kernels.split(",") if k.strip()}
        slots = [s for s in slots if s[0] in keep]
    if not slots:
        print("tune: no Pallas kernels enabled by this config "
              "(use_sorted_aggregation / use_fused_edge_kernel / "
              "use_flash_attention all off?)", file=sys.stderr)
        return {"entries": 0, "hits": 0, "swept": 0, "results": []}

    budget = args.budget if args.budget is not None else int(
        training.get("autotune_budget") or 0
    )
    trials = args.trials if args.trials is not None else sweep_mod.DEFAULT_TRIALS
    table = TunedTable(cache_dir)
    print(f"tune: {len(slots)} slot(s) over {len(ladder.specs)} ladder "
          f"level(s) -> {cache_dir}")
    census = sweep_mod.sweep_slots(
        slots, table, budget=budget, trials=trials, force=args.force,
        log=print,
    )
    print(f"tune: {census['entries']} entr{'y' if census['entries'] == 1 else 'ies'}"
          f" ({census['hits']} cache hit(s), {census['swept']} swept)")
    return census


if __name__ == "__main__":
    main()
