"""The tuned-table cache: content-addressed, disk-persistent sweep winners.

One JSON file per tuned entry, named by the sha256 of its canonical key —
``(kernel id, kernel version, device kind, dtype, normalized shape
signature)`` — in a directory that lives next to the compile cache
(default ``./logs/<run>/tuned_table``; ``Training.autotune_cache_dir``
redirects, ``HYDRAGNN_TUNE_CACHE`` env always wins, same grammar as the
compile cache's resolution in train/compile_plane.py).

Invalidation is entirely in the key: a kernel schedule change bumps its
module's ``KERNEL_VERSION``, a different chip generation reports a
different ``device_kind``, a dtype or pad-spec change reshapes the
signature — each lands on a different sha256, so stale entries simply
never match (they are inert files, not wrong answers).

Durability follows the repo's atomic-publish convention (analysis/
atomic_write.py): tmp file in the same directory, fsync, ``os.replace``.
Concurrent sweepers racing on one entry both publish a complete file and
the last replace wins — readers never observe a torn entry. A corrupt or
schema-incompatible file degrades to "no entry" with a warning (the
caller falls back to pinned defaults), never an exception: the tuned
table is an accelerant, not a dependency.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import warnings
from typing import Any, Dict, Optional, Tuple

from ..utils import envflags

# bump when the entry file layout changes incompatibly — old files then
# fail validation and read as "no entry" instead of misparsing
TABLE_SCHEMA_VERSION = 1


def device_kind() -> str:
    """The tuned-table device axis: jax's device kind string ("TPU v4",
    "cpu", ...). Interpret-mode sweeps on CPU key under "cpu" and are
    therefore invisible to a TPU run by construction — timings never
    transfer across device kinds."""
    import jax

    try:
        return str(jax.devices()[0].device_kind)
    except Exception:
        return "unknown"


def entry_key(
    kernel: str,
    version: int,
    device: str,
    dtype: str,
    shape: Dict[str, Any],
) -> str:
    """sha256 of the canonical JSON of the key fields — the entry's
    filename stem. ``shape`` is the kernel's normalized shape signature
    (tune/plans.py ``normalize`` inputs: pad-spec sizes, channel widths,
    operand census), canonicalized by sorted keys."""
    payload = json.dumps(
        {
            "schema": TABLE_SCHEMA_VERSION,
            "kernel": str(kernel),
            "version": int(version),
            "device": str(device),
            "dtype": str(dtype),
            "shape": {str(k): shape[k] for k in sorted(shape)},
        },
        sort_keys=True,
        separators=(",", ":"),
    )
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()


def resolve_tune_cache(
    training: Dict[str, Any], log_name: Optional[str] = None
) -> Optional[str]:
    """Resolve the tuned-table directory, mirroring the compile cache's
    grammar (train/compile_plane.py ``setup_compile_cache``):
    ``HYDRAGNN_TUNE_CACHE`` env (``0``/``off``/``none`` disables, ``1``
    forces the config/default resolution back on, a path overrides), then
    ``Training.autotune_cache_dir`` (``false`` disables, a path
    overrides), else ``./logs/<run>/tuned_table`` next to the compile
    cache. Returns the directory, or None when disabled."""
    env = envflags.env_str("HYDRAGNN_TUNE_CACHE")
    cfg = training.get("autotune_cache_dir")
    if env is not None:
        s = env.strip()
        if s.lower() in ("0", "off", "none", "false", ""):
            return None
        if s != "1":
            cfg = s  # an explicit path beats the config
        elif cfg is False or (
            isinstance(cfg, str) and cfg.strip().lower() in ("off", "none")
        ):
            cfg = None  # "1": force-on with the config/default resolution
    if cfg is False or (
        isinstance(cfg, str) and cfg.strip().lower() in ("off", "none")
    ):
        return None
    if isinstance(cfg, str) and cfg:
        return cfg
    return os.path.join("./logs", log_name or "run", "tuned_table")


class TunedTable:
    """Reader/writer over one tuned-table directory, with an in-process
    memo so the routing layer's trace-time lookups are dict reads after
    the first touch of each key."""

    def __init__(self, cache_dir: str):
        self.cache_dir = str(cache_dir)
        self._lock = threading.Lock()
        # memo maps key -> plan dict or None (known miss); store() updates
        # it so a sweep's own process sees its writes without re-reading
        self._memo: Dict[str, Optional[Dict[str, int]]] = {}

    def _path(self, key: str) -> str:
        return os.path.join(self.cache_dir, key + ".json")

    # -- read ---------------------------------------------------------------

    def lookup(
        self,
        kernel: str,
        version: int,
        device: str,
        dtype: str,
        shape: Dict[str, Any],
    ) -> Optional[Dict[str, int]]:
        """The tuned plan for this key, or None (missing OR unreadable —
        a corrupt entry warns once and reads as absent; the caller's
        pinned-defaults fallback is always available)."""
        key = entry_key(kernel, version, device, dtype, shape)
        with self._lock:
            if key in self._memo:
                plan = self._memo[key]
                return dict(plan) if plan else None
        plan = self._read(key, kernel)
        with self._lock:
            self._memo[key] = dict(plan) if plan else None
        return plan

    def _read(self, key: str, kernel: str) -> Optional[Dict[str, int]]:
        path = self._path(key)
        try:
            with open(path, "r") as fh:
                entry = json.load(fh)
        except FileNotFoundError:
            return None
        except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
            warnings.warn(
                f"tuned-table entry {path} is unreadable ({e}); falling "
                f"back to pinned defaults for kernel {kernel!r} — re-run "
                "`python -m hydragnn_tpu.tune` to repair it",
                RuntimeWarning,
                stacklevel=3,
            )
            return None
        plan = self._validate(entry, key)
        if plan is None:
            warnings.warn(
                f"tuned-table entry {path} failed validation; falling back "
                f"to pinned defaults for kernel {kernel!r} — re-run "
                "`python -m hydragnn_tpu.tune` to repair it",
                RuntimeWarning,
                stacklevel=3,
            )
        return plan

    @staticmethod
    def _validate(entry: Any, key: str) -> Optional[Dict[str, int]]:
        """Schema + self-consistency check: the entry must re-derive its
        own filename key from its recorded key fields (a renamed or
        hand-edited file whose fields drifted reads as absent) and carry
        an all-int plan."""
        if not isinstance(entry, dict):
            return None
        if entry.get("schema") != TABLE_SCHEMA_VERSION:
            return None
        fields = entry.get("key_fields")
        plan = entry.get("plan")
        if not isinstance(fields, dict) or not isinstance(plan, dict):
            return None
        try:
            rederived = entry_key(
                fields["kernel"], fields["version"], fields["device"],
                fields["dtype"], fields["shape"],
            )
        except (KeyError, TypeError):
            return None
        if rederived != key:
            return None
        try:
            return {str(k): int(v) for k, v in plan.items()}
        except (TypeError, ValueError):
            return None

    # -- write --------------------------------------------------------------

    def store(
        self,
        kernel: str,
        version: int,
        device: str,
        dtype: str,
        shape: Dict[str, Any],
        plan: Dict[str, int],
        measured_us: Optional[float] = None,
        meta: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Publish one tuned entry atomically (tmp + fsync + replace —
        the blessed torn-state-free pattern; concurrent writers both
        publish whole files, last replace wins). Returns the entry path."""
        key = entry_key(kernel, version, device, dtype, shape)
        entry = {
            "schema": TABLE_SCHEMA_VERSION,
            "key_fields": {
                "kernel": str(kernel),
                "version": int(version),
                "device": str(device),
                "dtype": str(dtype),
                "shape": {str(k): shape[k] for k in sorted(shape)},
            },
            "plan": {str(k): int(v) for k, v in plan.items()},
        }
        if measured_us is not None:
            entry["measured_us"] = float(measured_us)
        if meta:
            entry["meta"] = meta
        path = self._path(key)
        os.makedirs(self.cache_dir, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            with open(tmp, "w") as fh:
                json.dump(entry, fh, sort_keys=True, indent=1)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
        with self._lock:
            self._memo[key] = {str(k): int(v) for k, v in plan.items()}
        return path

    # -- census -------------------------------------------------------------

    def size(self) -> int:
        """Number of entry files on disk (readable or not)."""
        try:
            return sum(
                1 for f in os.listdir(self.cache_dir)
                if f.endswith(".json")
            )
        except OSError:
            return 0

    def has(self, kernel: str, version: int, device: str, dtype: str,
            shape: Dict[str, Any]) -> bool:
        return self.lookup(kernel, version, device, dtype, shape) is not None
