"""obs_contract: the observability plane's vocabularies cannot drift from
their declarations.

PR 14's ``obs/schema.py`` drift test already pins the *record shapes*;
this checker pins the *vocabularies* around them, at review time:

1. ``obs/events.py`` is internally closed: every ``EV_*`` constant is a
   member of ``EVENT_KINDS``, and ``EVENT_KINDS`` and ``DEFAULT_SEVERITY``
   cover exactly the same kinds with severities from ``SEVERITIES`` — an
   event kind without a default severity rank breaks the run doctor's
   incident ordering (the exact gap PR 14 closed by hand).
2. every ``emit(...)`` call site in the package uses a declared kind:
   a string-literal kind must be in ``EVENT_KINDS``; an ``EV_*`` name must
   be one of the declared constants. An undeclared kind is invisible to
   the doctor's rulebook and unrankable by the flight recorder's census.
3. every ``hydragnn_*`` metric series registered via
   ``registry().counter/gauge/histogram`` is named in
   ``docs/OBSERVABILITY.md``'s catalog (brace groups like
   ``hydragnn_fleet_{min,mean,max}`` expand) — a series nobody can find
   in the catalog is a dashboard nobody builds.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Checker, Finding, Repo, call_name, register, str_const, walk_calls

CHECKER_ID = "obs_contract"

EVENTS_MODULE_SUFFIX = "obs/events.py"
_SERIES_METHODS = {"counter", "gauge", "histogram", "summary"}
_BRACE_RE = re.compile(r"\{([a-z0-9_,]+)\}")


def events_vocabulary(repo: Repo) -> Tuple[Optional[str], Dict[str, object]]:
    """Statically parse obs/events.py: EV_* constants, EVENT_KINDS,
    DEFAULT_SEVERITY, SEVERITIES."""
    target = None
    for rel in repo.python_files():
        if rel.replace("\\", "/").endswith(EVENTS_MODULE_SUFFIX):
            target = rel
            break
    out: Dict[str, object] = {
        "consts": {},        # EV_NAME -> kind string
        "kinds_tuple": set(),    # member names of EVENT_KINDS
        "severity_keys": set(),  # member names of DEFAULT_SEVERITY keys
        "severity_vals": {},     # member name -> severity literal
        "severities": set(),
    }
    if target is None:
        return None, out
    tree = repo.source(target).tree
    if tree is None:
        return target, out
    for node in ast.walk(tree):
        # both plain and annotated assignments (DEFAULT_SEVERITY is
        # declared as ``DEFAULT_SEVERITY: Dict[str, str] = {...}``)
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            t = node.target
        else:
            continue
        if not isinstance(t, ast.Name):
            continue
        if t.id.startswith("EV_"):
            s = str_const(node.value)
            if s is not None:
                out["consts"][t.id] = s  # type: ignore[index]
        elif t.id == "EVENT_KINDS" and isinstance(node.value, (ast.Tuple, ast.List)):
            for elt in node.value.elts:
                if isinstance(elt, ast.Name):
                    out["kinds_tuple"].add(elt.id)  # type: ignore[union-attr]
                s = str_const(elt)
                if s is not None:
                    out["kinds_tuple"].add(s)  # type: ignore[union-attr]
        elif t.id == "DEFAULT_SEVERITY" and isinstance(node.value, ast.Dict):
            for k, v in zip(node.value.keys, node.value.values):
                name = k.id if isinstance(k, ast.Name) else str_const(k)
                if name is not None:
                    out["severity_keys"].add(name)  # type: ignore[union-attr]
                    sv = str_const(v)
                    if sv is not None:
                        out["severity_vals"][name] = sv  # type: ignore[index]
        elif t.id == "SEVERITIES" and isinstance(node.value, (ast.Tuple, ast.List)):
            for elt in node.value.elts:
                s = str_const(elt)
                if s is not None:
                    out["severities"].add(s)  # type: ignore[union-attr]
    return target, out


def _doc_series_names(repo: Repo) -> Set[str]:
    """hydragnn_* names in docs/OBSERVABILITY.md, with {a,b,c} brace
    groups expanded (the docs' compact spelling for aggregate families)."""
    text = repo.read_text("docs/OBSERVABILITY.md") or ""
    names: Set[str] = set()
    for raw in re.findall(r"hydragnn_[a-z0-9_{},]*", text):
        raw = raw.rstrip(",_")
        # docs write labeled series as name{label,...} — the name before
        # an unclosed brace group is the series
        if raw.count("{") != raw.count("}"):
            names.add(raw.split("{", 1)[0])
            continue
        expansions: List[List[str]] = [
            m.group(1).split(",") for m in _BRACE_RE.finditer(raw)
        ]
        parts = _BRACE_RE.sub("\0", raw).split("\0")
        combos = [parts[0]]
        for i, opts in enumerate(expansions):
            combos = [c + o + parts[i + 1] for c in combos for o in opts]
        for c in combos:
            names.add(c)
            names.add(c.rstrip("_"))
    return names


def run(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    events_rel, vocab = events_vocabulary(repo)
    consts: Dict[str, str] = vocab["consts"]  # type: ignore[assignment]
    kinds_tuple: Set[str] = vocab["kinds_tuple"]  # type: ignore[assignment]
    severity_keys: Set[str] = vocab["severity_keys"]  # type: ignore[assignment]
    severities: Set[str] = vocab["severities"]  # type: ignore[assignment]
    declared_kind_strings = {consts[n] for n in consts}

    if events_rel is not None and consts:
        for name in sorted(consts):
            if name not in kinds_tuple:
                findings.append(Finding(
                    CHECKER_ID, events_rel, 0,
                    f"event constant {name} is not a member of EVENT_KINDS",
                    hint="add it to the EVENT_KINDS tuple",
                ))
            if name not in severity_keys:
                findings.append(Finding(
                    CHECKER_ID, events_rel, 0,
                    f"event kind {name} has no DEFAULT_SEVERITY entry — "
                    "the doctor/flight-recorder cannot rank its incidents",
                    hint="add the kind to DEFAULT_SEVERITY with its rank",
                ))
        for name in sorted(severity_keys - set(consts)):
            findings.append(Finding(
                CHECKER_ID, events_rel, 0,
                f"DEFAULT_SEVERITY ranks {name!r}, which is not a declared "
                "EV_* constant",
                hint="remove the stale entry (or declare the kind)",
            ))
        for name, sv in sorted(vocab["severity_vals"].items()):  # type: ignore[union-attr]
            if severities and sv not in severities:
                findings.append(Finding(
                    CHECKER_ID, events_rel, 0,
                    f"DEFAULT_SEVERITY[{name}] = {sv!r} is not in SEVERITIES",
                    hint=f"use one of {sorted(severities)}",
                ))

    # contract 2: emit call sites use declared kinds
    if consts:
        for rel in repo.python_files():
            if rel.replace("\\", "/").endswith(EVENTS_MODULE_SUFFIX):
                continue
            src = repo.source(rel)
            if src.tree is None:
                continue
            for call in walk_calls(src.tree):
                fn = call_name(call).rsplit(".", 1)[-1]
                if fn not in ("emit", "_emit") or not call.args:
                    continue
                first = call.args[0]
                lit = str_const(first)
                if lit is not None:
                    if lit not in declared_kind_strings:
                        findings.append(Finding(
                            CHECKER_ID, rel, call.lineno,
                            f"emit() of undeclared event kind {lit!r}",
                            hint="declare the kind in obs/events.py "
                                 "(EV_* constant + EVENT_KINDS + "
                                 "DEFAULT_SEVERITY) and emit the constant",
                        ))
                elif isinstance(first, ast.Name) and first.id.startswith("EV_"):
                    if first.id not in consts:
                        findings.append(Finding(
                            CHECKER_ID, rel, call.lineno,
                            f"emit() of unknown event constant {first.id}",
                            hint="declare it in obs/events.py",
                        ))

    # contract 3: registered hydragnn_* series are in the docs catalog
    if repo.has("docs/OBSERVABILITY.md"):
        documented = _doc_series_names(repo)
        for rel in repo.python_files():
            src = repo.source(rel)
            if src.tree is None:
                continue
            for call in walk_calls(src.tree):
                fn = call_name(call).rsplit(".", 1)[-1]
                if fn not in _SERIES_METHODS or not call.args:
                    continue
                series = str_const(call.args[0])
                if not series or not series.startswith("hydragnn_"):
                    continue
                if series not in documented:
                    findings.append(Finding(
                        CHECKER_ID, rel, call.lineno,
                        f"metric series {series!r} is registered but not "
                        "named in docs/OBSERVABILITY.md",
                        hint="add it to the metrics catalog table "
                             "(docs/OBSERVABILITY.md)",
                    ))
    return findings


register(Checker(
    id=CHECKER_ID,
    title="obs vocabularies: event kinds declared+ranked, series documented",
    rationale=(
        "PR 14 found event kinds without severity ranks while building the "
        "doctor's rulebook, and the fleet/mix/trace series families landed "
        "in code without catalog rows — the schema drift test covers record "
        "shapes but not the vocabularies around them"
    ),
    run=run,
))
