"""threads: every background thread daemonized, every join bounded,
every queue get timed out (or explicitly waived with the reason written
down).

The failure history: the serving plane's wedged-step recovery works
*because* its step runners are daemon threads (an abandoned runner must
not block process exit — serve/server.py); the compile plane's background
warm-up worker and the fleet collector both grew ``join(timeout=...)``
bounds after hangs in teardown paths; and a bare ``q.get()`` is exactly
the shape that wedged the loader before the stall watchdog existed
(docs/ROBUSTNESS.md "Data plane"). The ROADMAP-1 sharding refactor will
rewrite the files these threads live in — this checker keeps the
conventions through that churn.

Rules (package-wide):

- ``threading.Thread(...)`` without ``daemon=True`` — a non-daemon
  background thread can hold the process open past SIGTERM drain;
- ``<thread>.join()`` with no timeout — an unbounded join in a teardown
  path is a hang, not a wait;
- ``<queue>.get()`` with no arguments — dict ``.get()`` always takes
  arguments, so a zero-arg ``.get()`` is a blocking queue read with no
  timeout; a wedged producer turns it into a silent hang. Sites that
  *want* to block forever (a daemon worker's idle loop) carry a waiver
  pragma saying so.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Checker, Finding, Repo, dotted, register, walk_calls

CHECKER_ID = "threads"


def run(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    for rel in repo.python_files():
        src = repo.source(rel)
        if src.tree is None:
            continue
        for call in walk_calls(src.tree):
            name = dotted(call.func)
            tail = name.rsplit(".", 1)[-1]
            if name.endswith("threading.Thread") or name == "Thread":
                kw = {k.arg: k.value for k in call.keywords}
                daemon = kw.get("daemon")
                is_true = (
                    isinstance(daemon, ast.Constant) and daemon.value is True
                )
                if not is_true:
                    findings.append(Finding(
                        CHECKER_ID, rel, call.lineno,
                        "threading.Thread(...) without daemon=True — the "
                        "thread can hold the process open past drain/"
                        "teardown",
                        hint="pass daemon=True (teardown still joins with "
                             "a bound; daemonization is the backstop)",
                    ))
            elif tail == "join" and not call.args and not call.keywords:
                # thread/process join is zero-arg; str.join/os.path.join
                # always take an argument, so no-arg .join() is a join()
                findings.append(Finding(
                    CHECKER_ID, rel, call.lineno,
                    ".join() with no timeout — an unbounded join in a "
                    "teardown path is a hang",
                    hint="join(timeout=<bound>) and handle the "
                         "still-alive case (daemon threads may be "
                         "abandoned)",
                ))
            elif tail == "get" and not call.args and not call.keywords:
                # dict.get() requires an argument — a zero-arg .get() is a
                # queue read that blocks forever
                findings.append(Finding(
                    CHECKER_ID, rel, call.lineno,
                    "bare queue .get() with no timeout — a dead/wedged "
                    "producer turns this into a silent hang",
                    hint="get(timeout=...) in a loop (or waive with the "
                         "reason the block-forever is safe, e.g. a daemon "
                         "worker's idle loop)",
                ))
    return findings


register(Checker(
    id=CHECKER_ID,
    title="threads daemonized, joins bounded, queue gets timed out",
    rationale=(
        "the serve wedge recovery depends on daemon step runners; the "
        "compile-plane worker and fleet collector both grew bounded joins "
        "after teardown hangs; a bare q.get() is the pre-watchdog loader "
        "wedge shape (docs/ROBUSTNESS.md)"
    ),
    run=run,
))
