"""tile_constants: no hand-pinned Pallas block/tile literals outside the
autotuning plane.

The failure history: PR 16 retired the hand-picked block constants the
kernel routing layer had been pinning at every call site (and found, in
the process, that ops/segment.py cached a multi_agg specialization on the
*unclamped* ``block_cols`` — two call sites could request the same
effective tile yet compile twice, or worse, share a table key that the
kernel then clamped differently). The fix is structural: tile choices
route through ``tune.runtime.tile_plan`` — the tuned table when an entry
matches, the pinned defaults (normalized by the kernel's own clamp)
otherwise — so the jit key, the table key, and the kernel's actual tile
are the same value by construction (docs/TUNING.md).

Rule (package-wide, two exemptions):

- a numeric literal passed as a ``block_rows`` / ``block_edges`` /
  ``block_cols`` / ``block_q`` / ``block_k`` / ``chunk_edges`` keyword is
  a finding — route the call through ``tile_plan`` (or waive with the
  reason the pinned value is load-bearing);
- ``ops/pallas_*.py`` is exempt: the kernel modules OWN their pinned
  defaults (the signature defaults the tuner falls back to);
- ``tune/`` is exempt: plans.py owns the candidate grids and default
  plans the plane sweeps over.

Tests and run-scripts are outside the package walk and may pin literals
freely (a test that exercises one specific tile shape is the point).
"""

from __future__ import annotations

import ast
from typing import List

from .core import Checker, Finding, Repo, register, walk_calls

CHECKER_ID = "tile_constants"

# the tile-plan keyword surface across the four Pallas kernels
TILE_KWARGS = frozenset((
    "block_rows", "block_edges", "block_cols",
    "block_q", "block_k", "chunk_edges",
))


def _exempt(rel: str) -> bool:
    norm = rel.replace("\\", "/")
    base = norm.rsplit("/", 1)[-1]
    if base.startswith("pallas_") and "/ops/" in f"/{norm}":
        return True  # kernel modules own their pinned defaults
    return "/tune/" in f"/{norm}"  # plans.py owns grids and defaults


def run(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    for rel in repo.python_files():
        if _exempt(rel):
            continue
        src = repo.source(rel)
        if src.tree is None:
            continue
        for call in walk_calls(src.tree):
            for kw in call.keywords:
                if kw.arg not in TILE_KWARGS:
                    continue
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(
                    v.value, (int, float)
                ) and not isinstance(v.value, bool):
                    findings.append(Finding(
                        CHECKER_ID, rel, v.lineno,
                        f"hand-pinned tile literal {kw.arg}={v.value!r} — "
                        "kernel call sites must route block constants "
                        "through the tuned-table lookup",
                        hint="plan = tune.runtime.tile_plan(<kernel>, "
                             "<shapes>, dtype) and pass "
                             f"{kw.arg}=plan[{kw.arg!r}] (or waive with "
                             "the reason this pinned value is "
                             "load-bearing)",
                    ))
    return findings


register(Checker(
    id=CHECKER_ID,
    title="Pallas tile constants route through tile_plan, not literals",
    rationale=(
        "PR 16's multi_agg bug: a call site pinned an unclamped "
        "block_cols that became the jit specialization key while the "
        "kernel clamped it internally — tile choices must flow through "
        "tune.runtime.tile_plan so jit key, table key and actual tile "
        "agree (docs/TUNING.md)"
    ),
    run=run,
))
