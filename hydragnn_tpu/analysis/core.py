"""graftlint core: the repo-native static-analysis plane's shared machinery.

Fourteen PRs of conventions — every env knob documented and parsed through
one helper, every event kind declared with a severity, no host syncs inside
jitted step builders, every background thread daemonized, every
checkpoint-adjacent write atomic — lived in docstrings and reviewers'
heads. This package turns them into machine-enforced contracts: one
checker per module (analysis/<checker>.py), findings typed with file:line
and a fix hint, pragma-comment waivers with mandatory reasons, JSON output
for CI, and a ``--baseline`` mode kept for local incremental use only (the
CI gate in run-scripts/ci.sh runs baseline-free and must stay at zero).

Checkers are pure host-side AST/text analysis — importing this package
must never import jax (the fixture tests are tier-1 and run with no
accelerator stack at all).

Waiver grammar (docs/ANALYSIS.md "Waivers")::

    some_flagged_line()  # graftlint: disable=checker-id -- why it is OK
    # graftlint: disable=checker-id,other-id -- reason covering both
    some_flagged_line()

A pragma waives matching findings on its own line or the line directly
below it. The reason after ``--`` is mandatory: a reasonless pragma is
itself a ``waiver`` finding, so silence always has a written cost.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import tokenize
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

ANALYSIS_SCHEMA_VERSION = 1

# pragma grammar: "# graftlint: disable=a,b -- reason" (reason mandatory;
# enforced by the built-in `waiver` checker below, not the regex)
_PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*disable=(?P<ids>[a-z0-9_,\-]+)"
    r"(?:\s*--\s*(?P<reason>.*\S))?"
)


@dataclasses.dataclass
class Finding:
    """One typed violation: where, what, and how to fix it."""

    checker: str            # checker id (module name under analysis/)
    path: str               # repo-relative path
    line: int               # 1-based; 0 = whole-file/config-level finding
    message: str            # what is wrong, concretely
    hint: str = ""          # the fix the checker wants (or the waiver shape)
    waived: bool = False    # a pragma with a reason covers this finding
    waive_reason: str = ""  # that pragma's mandatory reason text

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        tail = f" [waived: {self.waive_reason}]" if self.waived else ""
        hint = f"\n    fix: {self.hint}" if self.hint and not self.waived else ""
        return f"{loc}: [{self.checker}] {self.message}{tail}{hint}"


class SourceFile:
    """One parsed python file: text, lines, AST (lazily), pragma map."""

    def __init__(self, root: str, relpath: str):
        self.relpath = relpath
        self.abspath = os.path.join(root, relpath)
        with open(self.abspath, "r", encoding="utf-8") as fh:
            self.text = fh.read()
        self.lines = self.text.splitlines()
        self._tree: Optional[ast.AST] = None
        self._parse_error: Optional[str] = None
        self._pragmas: Optional[Dict[int, List[Tuple[str, str]]]] = None

    @property
    def tree(self) -> Optional[ast.AST]:
        if self._tree is None and self._parse_error is None:
            try:
                self._tree = ast.parse(self.text, filename=self.relpath)
            except SyntaxError as e:
                self._parse_error = str(e)
        return self._tree

    @property
    def parse_error(self) -> Optional[str]:
        _ = self.tree
        return self._parse_error

    def pragmas(self) -> Dict[int, List[Tuple[str, str, bool]]]:
        """line -> [(checker_id, reason, standalone)] from real COMMENT
        tokens (not string literals that merely look like pragmas).
        ``standalone`` is True for comment-only lines — only those waive
        the line BELOW; a trailing comment waives its own line only."""
        if self._pragmas is not None:
            return self._pragmas
        out: Dict[int, List[Tuple[str, str, bool]]] = {}
        try:
            import io

            for tok in tokenize.generate_tokens(io.StringIO(self.text).readline):
                if tok.type != tokenize.COMMENT:
                    continue
                m = _PRAGMA_RE.search(tok.string)
                if not m:
                    continue
                reason = (m.group("reason") or "").strip()
                line_no = tok.start[0]
                standalone = (
                    line_no <= len(self.lines)
                    and self.lines[line_no - 1].lstrip().startswith("#")
                )
                for cid in m.group("ids").split(","):
                    cid = cid.strip().replace("-", "_")
                    if cid:
                        out.setdefault(line_no, []).append(
                            (cid, reason, standalone)
                        )
        except (tokenize.TokenError, IndentationError, SyntaxError):
            pass  # unparseable file: the checker reporting it still runs
        self._pragmas = out
        return out


class Repo:
    """The analysis target: a repo root with a ``hydragnn_tpu`` package,
    ``docs/``, ``tests/`` and ``run-scripts/`` beside it (fixtures build
    the same shape in a tmp dir)."""

    def __init__(self, root: str, package: str = "hydragnn_tpu"):
        self.root = os.path.abspath(root)
        self.package = package
        self._files: Dict[str, SourceFile] = {}

    # -- file discovery ------------------------------------------------------

    def python_files(self) -> List[str]:
        """Repo-relative paths of every package .py file (sorted; the
        analysis plane itself is included — it must obey its own rules)."""
        out = []
        pkg_root = os.path.join(self.root, self.package)
        for dirpath, dirnames, filenames in os.walk(pkg_root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for f in sorted(filenames):
                if f.endswith(".py"):
                    out.append(
                        os.path.relpath(os.path.join(dirpath, f), self.root)
                    )
        return sorted(out)

    def aux_files(self, *subdirs: str, exts: Tuple[str, ...] = (".py", ".sh")) -> List[str]:
        """Non-package evidence files (tests/, run-scripts/, ...)."""
        out = []
        for sub in subdirs:
            base = os.path.join(self.root, sub)
            if not os.path.isdir(base):
                continue
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for f in sorted(filenames):
                    if f.endswith(exts):
                        out.append(
                            os.path.relpath(os.path.join(dirpath, f), self.root)
                        )
        return sorted(out)

    def source(self, relpath: str) -> SourceFile:
        if relpath not in self._files:
            self._files[relpath] = SourceFile(self.root, relpath)
        return self._files[relpath]

    def read_text(self, relpath: str) -> Optional[str]:
        """Raw text of a repo file (docs, shell), or None when absent."""
        p = os.path.join(self.root, relpath)
        try:
            with open(p, "r", encoding="utf-8") as fh:
                return fh.read()
        except OSError:
            return None

    def has(self, relpath: str) -> bool:
        return os.path.exists(os.path.join(self.root, relpath))


# ---------------------------------------------------------------------------
# checker registry
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Checker:
    id: str
    title: str
    rationale: str  # the incident/convention that motivated it (docs/ANALYSIS.md)
    run: Callable[[Repo], List[Finding]]


_CHECKERS: List[Checker] = []


def register(checker: Checker) -> Checker:
    if any(c.id == checker.id for c in _CHECKERS):
        raise ValueError(f"duplicate checker id {checker.id!r}")
    _CHECKERS.append(checker)
    return checker


def checkers() -> List[Checker]:
    """All registered checkers (importing the sibling modules on first use
    — one checker = one module, docs/ANALYSIS.md catalog order)."""
    from . import (  # noqa: F401 — imported for their register() side effect
        atomic_write,
        config_keys,
        env_census,
        error_codes,
        fault_coverage,
        obs_contract,
        sharding_rules,
        threads,
        tile_constants,
        trace_hazard,
    )

    return list(_CHECKERS)


# ---------------------------------------------------------------------------
# shared AST helpers (used by several checkers)
# ---------------------------------------------------------------------------

def call_name(node: ast.Call) -> str:
    """Dotted name of a call target: ``os.environ.get`` -> that string,
    bare ``open`` -> "open". Unresolvable targets (lambdas, subscripts)
    render as ""."""
    return dotted(node.func)


def dotted(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def walk_calls(tree: ast.AST) -> Iterable[ast.Call]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            yield node


def expr_mentions(node: ast.AST, attr_base: str) -> bool:
    """Whether any attribute access on the name ``attr_base`` (e.g.
    ``state``) appears inside ``node``."""
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Attribute)
            and isinstance(sub.value, ast.Name)
            and sub.value.id == attr_base
        ):
            return True
    return False


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

def _apply_waivers(repo: Repo, findings: List[Finding]) -> List[Finding]:
    """Mark findings covered by a same-line or line-above pragma; emit a
    ``waiver`` finding for every reasonless pragma (mandatory reasons)."""
    out: List[Finding] = []
    for f in findings:
        try:
            pragmas = repo.source(f.path).pragmas() if f.path.endswith(".py") else {}
        except OSError:
            pragmas = {}
        for line in (f.line, f.line - 1):
            for cid, reason, standalone in pragmas.get(line, ()):
                if line != f.line and not standalone:
                    continue  # a trailing comment covers its own line only
                if cid in (f.checker, "all") and reason:
                    f.waived, f.waive_reason = True, reason
        out.append(f)
    # reasonless pragmas are findings themselves — a waiver without a
    # written reason is exactly the silent convention-rot this plane exists
    # to stop
    for rel in repo.python_files():
        try:
            src = repo.source(rel)
        except OSError:
            continue
        for line, entries in src.pragmas().items():
            for cid, reason, _standalone in entries:
                if not reason:
                    out.append(Finding(
                        "waiver", rel, line,
                        f"graftlint pragma for {cid!r} has no reason",
                        hint="append ' -- <why this violation is acceptable>'"
                             " to the pragma",
                    ))
    return out


def run_checkers(
    repo: Repo, only: Optional[Iterable[str]] = None
) -> List[Finding]:
    """Run every (or the selected) checker over ``repo`` and apply
    waivers. A checker crash is itself a finding — the gate must never
    silently pass because an analyzer died."""
    findings: List[Finding] = []
    # files that do not parse fail loudly once, here, instead of once per
    # checker
    for rel in repo.python_files():
        src = repo.source(rel)
        if src.parse_error:
            findings.append(Finding(
                "parse", rel, 0, f"file does not parse: {src.parse_error}",
                hint="fix the syntax error",
            ))
    for checker in checkers():
        if only and checker.id not in only:
            continue
        try:
            findings.extend(checker.run(repo))
        except Exception as e:  # noqa: BLE001 — convert to a finding
            findings.append(Finding(
                checker.id, "", 0,
                f"checker crashed: {type(e).__name__}: {e}",
                hint="fix the checker (analysis/"
                     f"{checker.id}.py) — a dead checker gates nothing",
            ))
    findings.sort(key=lambda f: (f.path, f.line, f.checker, f.message))
    return _apply_waivers(repo, findings)


# ---------------------------------------------------------------------------
# baseline (local incremental use ONLY — ci.sh runs baseline-free)
# ---------------------------------------------------------------------------

def baseline_key(f: Finding) -> List[str]:
    # line numbers shift under unrelated edits; (checker, file, message)
    # is stable enough for an incremental burn-down session
    return [f.checker, f.path, f.message]


def apply_baseline(findings: List[Finding], baseline: List[List[str]]) -> List[Finding]:
    known = {tuple(k) for k in baseline}
    return [f for f in findings if tuple(baseline_key(f)) not in known]


def summarize(findings: List[Finding]) -> Dict[str, Any]:
    active = [f for f in findings if not f.waived]
    by_checker: Dict[str, int] = {}
    for f in active:
        by_checker[f.checker] = by_checker.get(f.checker, 0) + 1
    return {
        "v": ANALYSIS_SCHEMA_VERSION,
        "total": len(findings),
        "active": len(active),
        "waived": len(findings) - len(active),
        "by_checker": dict(sorted(by_checker.items())),
        "clean": not active,
    }


def to_json(findings: List[Finding]) -> str:
    return json.dumps(
        {
            "summary": summarize(findings),
            "findings": [f.to_dict() for f in findings],
        },
        indent=2,
    )


def default_root() -> str:
    """The repo root this package sits in (two levels above analysis/)."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))
