"""trace_hazard: no host syncs or counter dtype casts inside the jitted
step builders.

The incident this encodes: in PR 3 an explicit int32 cast on the
TrainState step counter flipped the leaf's weak type, which made every
specialization a *new* trace signature — one silent full XLA recompile
per step, found by hand in round 7 (docs/PERFORMANCE.md "Retrace sentinel
semantics"). The retrace sentinel now catches that class at RUNTIME;
this checker catches it at REVIEW time, before a run is ever launched.

Scope: the step-builder modules and functions only — the bodies that jit
traces (``train/loop.py`` ``make_train_step``/``make_eval_step``, the
rule engine's ``parallel/engine.py`` mesh-step builders, plus the
``parallel/dp.py``/``parallel/branch.py`` deprecation shims over them).
Inside them:

- ``.item()``, ``jax.device_get(...)``, ``np.asarray``/``np.array``:
  host syncs — a device round-trip per step inside what must stay a
  pure traced program;
- ``float(x)`` / ``int(x)`` where ``x`` mentions a ``state.`` attribute:
  concretization of a traced value (raises under jit, or silently hides
  a host pull when applied pre-trace);
- ``.astype(...)`` / ``jnp.asarray(..., dtype=...)`` / ``jnp.int32(...)``
  / ``jnp.int64(...)`` applied to a TrainState counter leaf
  (``state.step`` and the guard's skip counters): the weak-type flip
  itself — the PR 3 cast, verbatim.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Tuple

from .core import Checker, Finding, Repo, dotted, register, walk_calls

CHECKER_ID = "trace_hazard"

# (module path suffix, builder function names) — the jitted-step surface
STEP_BUILDERS: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("train/loop.py", ("make_train_step", "make_eval_step")),
    ("parallel/engine.py", ("make_mesh_train_step", "make_mesh_eval_step")),
    # deprecation shims — scanned so a hazard can't sneak back in via them
    ("parallel/dp.py", ("make_parallel_train_step", "make_parallel_eval_step")),
    ("parallel/branch.py", (
        "make_branch_parallel_train_step", "make_branch_parallel_eval_step",
    )),
)

# TrainState integer counter leaves whose weak type the compile ladder
# depends on (train/state.py; the PR 3 flip was on .step)
COUNTER_ATTRS = ("step", "skipped_steps", "consecutive_skipped", "rollbacks")

_HOST_SYNC_CALLS = ("jax.device_get", "np.asarray", "np.array", "onp.asarray")
_CAST_CALLS = ("jnp.int32", "jnp.int64", "jnp.uint32", "jnp.float32")


def _mentions_counter(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Attribute)
            and sub.attr in COUNTER_ATTRS
            and isinstance(sub.value, ast.Name)
            and sub.value.id in ("state", "new_state", "self")
        ):
            return True
    return False


def _builder_functions(tree: ast.AST, names: Iterable[str]) -> List[ast.FunctionDef]:
    out = []
    wanted = set(names)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in wanted:
                out.append(node)
    return out


def _scan_body(rel: str, fn: ast.FunctionDef) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        tail = name.rsplit(".", 1)[-1]
        # .item() — the canonical per-step host sync
        if tail == "item" and not node.args and isinstance(node.func, ast.Attribute):
            findings.append(Finding(
                CHECKER_ID, rel, node.lineno,
                f".item() inside step builder {fn.name!r} is a host sync "
                "per step",
                hint="keep the value on device (jnp) or move the read to "
                     "the epoch boundary the loop already syncs on",
            ))
            continue
        if name in _HOST_SYNC_CALLS:
            findings.append(Finding(
                CHECKER_ID, rel, node.lineno,
                f"{name}(...) inside step builder {fn.name!r} pulls a "
                "traced value to host",
                hint="use jnp inside the traced body; host-side work "
                     "belongs outside the builder",
            ))
            continue
        if name in ("float", "int") and node.args and _mentions_counter(node.args[0]):
            findings.append(Finding(
                CHECKER_ID, rel, node.lineno,
                f"{name}() on a TrainState counter inside step builder "
                f"{fn.name!r} concretizes a traced value",
                hint="keep the counter traced; read it host-side after "
                     "the step returns",
            ))
            continue
        # the PR 3 weak-type flip: an explicit dtype cast on a counter leaf
        is_astype = (
            tail == "astype"
            and isinstance(node.func, ast.Attribute)
            and _mentions_counter(node.func.value)
        )
        is_ctor_cast = name in _CAST_CALLS and any(
            _mentions_counter(a) for a in node.args
        )
        is_asarray_dtype = (
            tail == "asarray"
            and name.startswith("jnp")
            and (len(node.args) > 1 or any(k.arg == "dtype" for k in node.keywords))
            and any(_mentions_counter(a) for a in node.args)
        )
        if is_astype or is_ctor_cast or is_asarray_dtype:
            findings.append(Finding(
                CHECKER_ID, rel, node.lineno,
                "explicit dtype cast on a TrainState counter inside step "
                f"builder {fn.name!r} flips the leaf's weak type — every "
                "specialization becomes a new trace (the PR 3 silent-"
                "recompile incident)",
                hint="drop the cast: counters stay weakly-typed python "
                     "ints under `state.step + 1` (docs/PERFORMANCE.md "
                     "'Retrace sentinel semantics')",
            ))
    return findings


def run(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    for suffix, names in STEP_BUILDERS:
        for rel in repo.python_files():
            if not rel.replace("\\", "/").endswith(suffix):
                continue
            src = repo.source(rel)
            if src.tree is None:
                continue
            for fn in _builder_functions(src.tree, names):
                findings.extend(_scan_body(rel, fn))
    return findings


register(Checker(
    id=CHECKER_ID,
    title="no host syncs / counter dtype casts in jitted step builders",
    rationale=(
        "the PR 3 weak_type incident: an int32 cast on state.step made "
        "every specialization recompile silently each step; the runtime "
        "retrace sentinel catches it in CI smokes, this catches it in "
        "review before a TPU hour is spent"
    ),
    run=run,
))
