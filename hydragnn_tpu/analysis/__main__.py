"""CLI: ``python -m hydragnn_tpu.analysis [--json] [--baseline FILE]
[--write-baseline FILE] [--only id,...] [--env-table] [--list] [--root DIR]``.

Exit codes: 0 = clean (no unwaived, unbaselined findings), 1 = findings,
2 = usage/environment error — the same contract as config.lint, so CI
and migration scripts branch the same way on both gates.

The ``--baseline`` flag exists for LOCAL incremental burn-downs only:
run-scripts/ci.sh invokes the gate baseline-free, so the committed tree
must stay at zero unwaived findings (docs/ANALYSIS.md "The gate").
"""

from __future__ import annotations

import argparse
import json
import sys

from .core import (
    Repo,
    apply_baseline,
    baseline_key,
    checkers,
    default_root,
    run_checkers,
    summarize,
    to_json,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.analysis",
        description="graftlint: repo-native static analysis "
                    "(docs/ANALYSIS.md has the checker catalog)",
    )
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings (the CI artifact)")
    parser.add_argument("--baseline", metavar="FILE",
                        help="suppress findings recorded in FILE "
                             "(LOCAL incremental use only; CI is baseline-free)")
    parser.add_argument("--write-baseline", metavar="FILE",
                        help="record current unwaived findings to FILE and exit 0")
    parser.add_argument("--only", metavar="IDS",
                        help="comma-separated checker ids to run")
    parser.add_argument("--env-table", action="store_true",
                        help="print the regenerated docs/CONFIG.md env-flag "
                             "table from the census and exit")
    parser.add_argument("--list", action="store_true",
                        help="print the checker catalog and exit")
    parser.add_argument("--root", metavar="DIR", default=None,
                        help="repo root to analyze (default: this checkout)")
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    root = args.root or default_root()
    repo = Repo(root)

    if args.list:
        for c in checkers():
            print(f"{c.id}: {c.title}")
            print(f"    rationale: {c.rationale}")
        return 0

    if args.env_table:
        from .env_census import render_env_table

        print(render_env_table(repo))
        return 0

    only = None
    if args.only:
        only = {s.strip().replace("-", "_") for s in args.only.split(",") if s.strip()}
        known = {c.id for c in checkers()}
        bad = only - known
        if bad:
            print(f"unknown checker id(s): {sorted(bad)}; known: {sorted(known)}",
                  file=sys.stderr)
            return 2

    findings = run_checkers(repo, only=only)

    if args.write_baseline:
        active = [f for f in findings if not f.waived]
        with open(args.write_baseline, "w") as fh:
            json.dump([baseline_key(f) for f in active], fh, indent=2)
        print(f"wrote {len(active)} finding keys to {args.write_baseline}")
        return 0

    if args.baseline:
        try:
            with open(args.baseline) as fh:
                baseline = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            print(f"cannot read baseline {args.baseline}: {e}", file=sys.stderr)
            return 2
        findings = apply_baseline(findings, baseline)

    summary = summarize(findings)
    if args.json:
        print(to_json(findings))
    else:
        for f in findings:
            print(f.render())
        print(
            f"graftlint: {summary['active']} finding(s), "
            f"{summary['waived']} waived"
            + (f" [{args.baseline} applied]" if args.baseline else "")
        )
    return 0 if summary["clean"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
