"""env_census: every ``HYDRAGNN_*`` read goes through utils/envflags.py
and has a docs/CONFIG.md row.

The convention (and its failure history): the ``HYDRAGNN_*`` channel is
the stack's out-of-band control surface — 150+ mentions across the
package vs a docs table that drifted to a third of that, and hand-rolled
``int(os.getenv(...))`` parses that crashed multi-hour runs on a typo'd
value (the PR 4 ``HYDRAGNN_DDSTORE_RETRIES`` incident). Two enforced
contracts:

1. **One parse boundary.** A direct ``os.environ`` / ``os.getenv`` read
   of a ``HYDRAGNN_*`` name anywhere outside ``utils/envflags.py`` is a
   finding — route it through ``env_flag`` / ``env_force`` / ``env_int``
   / ``env_float`` / ``env_str`` so the malformed-value fallback and the
   tri-state grammars cannot drift per module.
2. **Census == docs.** Every ``HYDRAGNN_*`` name the package mentions
   must have a ``docs/CONFIG.md`` env-table row, and every table row must
   name a flag that still exists somewhere in the tree (package, tests,
   run-scripts, bench, examples, native sources) — stale rows are as
   misleading as missing ones.

``python -m hydragnn_tpu.analysis --env-table`` regenerates the docs
table from this census (name, parse helper, default, reading module),
preserving the hand-written Meaning column of existing rows.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from .core import Checker, Finding, Repo, call_name, register, str_const, walk_calls

# a concrete flag name: prefix + a real suffix. The lookahead rejects
# family-prefix mentions ("HYDRAGNN_FAULT_", "HYDRAGNN_FAULT_*") that doc
# prose and remediation strings legitimately use — backtracking would
# otherwise shorten them into phantom flags
ENV_NAME_RE = re.compile(r"HYDRAGNN_[A-Z0-9_]*[A-Z0-9](?![A-Z0-9_*])")

ENVFLAGS_MODULE = "utils/envflags.py"
ENV_HELPERS = ("env_flag", "env_force", "env_int", "env_float", "env_str", "env_set")

# CONFIG.md env table row: "| `HYDRAGNN_X` | parse | default | owner | meaning |"
_DOC_ROW_RE = re.compile(r"^\|\s*`(HYDRAGNN_[A-Z0-9_]+)`\s*\|(.*)$")

CHECKER_ID = "env_census"


def _env_read_calls(tree: ast.AST) -> List[Tuple[int, str, str]]:
    """(line, flag_name, call_spelling) for direct os env reads of
    HYDRAGNN_* literals: os.getenv(...), os.environ.get(...),
    os.environ[...] loads."""
    out = []
    for call in walk_calls(tree):
        name = call_name(call)
        if name.endswith("getenv") or name.endswith("environ.get"):
            key = str_const(call.args[0]) if call.args else None
            if key and key.startswith("HYDRAGNN_"):
                out.append((call.lineno, key, name))
    from .core import dotted

    for node in ast.walk(tree):
        if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
            if dotted(node.value).endswith("environ"):
                key = str_const(node.slice)
                if key and key.startswith("HYDRAGNN_"):
                    out.append((node.lineno, key, "os.environ[...]"))
    return out


def _helper_reads(tree: ast.AST) -> List[Tuple[str, str, Optional[str]]]:
    """(flag_name, helper, default_repr) for envflags helper calls."""
    out = []
    for call in walk_calls(tree):
        name = call_name(call)
        # local aliases keep their helper identity ("from ..obs.telemetry
        # import env_flag as _env_flag" is still the shared parse)
        helper = name.rsplit(".", 1)[-1].lstrip("_")
        if helper not in ENV_HELPERS:
            continue
        key = str_const(call.args[0]) if call.args else None
        if not key or not key.startswith("HYDRAGNN_"):
            continue
        default = None
        if len(call.args) > 1:
            default = ast.unparse(call.args[1])
        out.append((key, helper, default))
    return out


def census(repo: Repo) -> Dict[str, Dict[str, object]]:
    """name -> {helpers: {helper}, defaults: {repr}, modules: {relpath},
    mentions: {relpath}} over the package tree."""
    info: Dict[str, Dict[str, object]] = {}

    def entry(name: str) -> Dict[str, object]:
        return info.setdefault(
            name,
            {"helpers": set(), "defaults": set(), "modules": set(), "mentions": set()},
        )

    for rel in repo.python_files():
        # the analysis plane and the envflags boundary document flags by
        # name without consuming them — their docstrings must not seed
        # phantom census entries
        norm = rel.replace("\\", "/")
        if "/analysis/" in norm or norm.endswith(ENVFLAGS_MODULE):
            continue
        src = repo.source(rel)
        for name in set(ENV_NAME_RE.findall(src.text)):
            entry(name)["mentions"].add(rel)  # type: ignore[union-attr]
        if src.tree is None:
            continue
        for flag, helper, default in _helper_reads(src.tree):
            e = entry(flag)
            e["helpers"].add(helper)  # type: ignore[union-attr]
            if default is not None:
                e["defaults"].add(default)  # type: ignore[union-attr]
            e["modules"].add(rel)  # type: ignore[union-attr]
    return info


def doc_rows(repo: Repo) -> Dict[str, Tuple[int, List[str]]]:
    """CONFIG.md env-table rows: name -> (line, [cells after the name])."""
    text = repo.read_text("docs/CONFIG.md")
    rows: Dict[str, Tuple[int, List[str]]] = {}
    if text is None:
        return rows
    for i, line in enumerate(text.splitlines(), 1):
        m = _DOC_ROW_RE.match(line.strip())
        if m:
            cells = [c.strip() for c in m.group(2).split("|")]
            rows[m.group(1)] = (i, cells)
    return rows


def _tree_mentions(repo: Repo) -> set:
    """Every HYDRAGNN_* name mentioned anywhere evidence can live — the
    stale-docs-row oracle (a row may document a tests-only knob like
    HYDRAGNN_CI_FAST, or a native-launcher one like HYDRAGNN_MASTER_PORT).
    The analysis plane and the envflags boundary are excluded: their
    docstrings catalog flags by name, and a linter whose own prose keeps
    dead flags "alive" can never flag a stale row."""
    names = set()
    for rel in repo.python_files() + repo.aux_files(
        "tests", "run-scripts", "examples", exts=(".py", ".sh", ".sbatch")
    ):
        norm = rel.replace("\\", "/")
        if "/analysis/" in norm or norm.endswith(ENVFLAGS_MODULE):
            continue
        text = repo.read_text(rel)
        if text:
            names.update(ENV_NAME_RE.findall(text))
    for extra in ("bench.py", "__graft_entry__.py"):
        text = repo.read_text(extra)
        if text:
            names.update(ENV_NAME_RE.findall(text))
    native = repo.package + "/native"
    import os as _os

    base = _os.path.join(repo.root, native)
    if _os.path.isdir(base):
        for f in sorted(_os.listdir(base)):
            if f.endswith((".cpp", ".h", ".cc")):
                text = repo.read_text(f"{native}/{f}")
                if text:
                    names.update(ENV_NAME_RE.findall(text))
    return names


def run(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    # contract 1: one parse boundary
    for rel in repo.python_files():
        if rel.replace("\\", "/").endswith(ENVFLAGS_MODULE):
            continue
        src = repo.source(rel)
        if src.tree is None:
            continue
        for line, flag, spelling in _env_read_calls(src.tree):
            findings.append(Finding(
                CHECKER_ID, rel, line,
                f"direct {spelling} read of {flag} bypasses the shared "
                "parse boundary",
                hint="route through utils/envflags.py (env_flag/env_force/"
                     "env_int/env_float/env_str) — the malformed-value "
                     "fallback and tri-state grammars live there",
            ))
    # contract 2: census == docs (only when the repo carries docs at all —
    # fixture trees without a docs/ dir still exercise contract 1)
    if repo.has("docs/CONFIG.md"):
        info = census(repo)
        rows = doc_rows(repo)
        for name in sorted(info):
            if name not in rows:
                mods = sorted(info[name]["modules"] or info[name]["mentions"])  # type: ignore[arg-type]
                findings.append(Finding(
                    CHECKER_ID, mods[0] if mods else "docs/CONFIG.md", 0,
                    f"{name} is read in code but has no docs/CONFIG.md "
                    "env-table row",
                    hint="add the row (python -m hydragnn_tpu.analysis "
                         "--env-table regenerates the table from the census)",
                ))
        known = _tree_mentions(repo)
        for name, (line, _cells) in sorted(rows.items()):
            if name not in known:
                findings.append(Finding(
                    CHECKER_ID, "docs/CONFIG.md", line,
                    f"env-table row documents {name}, which no code in the "
                    "tree mentions any more",
                    hint="delete the stale row (or restore the flag)",
                ))
    return findings


HELPER_GRAMMAR = {
    "env_flag": "on/off (0/off/false/empty = off, else on)",
    "env_force": "force/deny (1 = force, else deny)",
    "env_int": "int (malformed -> default)",
    "env_float": "float (malformed -> default)",
    "env_str": "string",
    "env_set": "armed-if-set",
}


def render_env_table(repo: Repo) -> str:
    """The regenerated CONFIG.md env table: census-derived Flag / Parse /
    Default / Read-by columns, Meaning preserved from the existing table
    (new flags get a placeholder the checker will keep surfacing until a
    human writes the meaning)."""
    info = census(repo)
    rows = doc_rows(repo)
    lines = [
        "| Flag | Parse | Default | Read by | Meaning |",
        "|---|---|---|---|---|",
    ]
    for name in sorted(info):
        e = info[name]
        helpers = sorted(e["helpers"])  # type: ignore[arg-type]
        parse = ", ".join(HELPER_GRAMMAR.get(h, h) for h in helpers) or "—"
        defaults = sorted(e["defaults"])  # type: ignore[arg-type]
        default = ", ".join(f"`{d}`" for d in defaults) or "—"
        modules = sorted(e["modules"]) or sorted(e["mentions"])  # type: ignore[arg-type]
        owner = ", ".join(
            m.split("/", 1)[-1] for m in modules[:3]
        ) + (", …" if len(modules) > 3 else "")
        meaning = "(document me)"
        if name in rows:
            # last non-empty cell (a trailing "|" yields an empty tail cell)
            cells = [c for c in rows[name][1] if c]
            if cells and cells[-1] != "—":
                meaning = cells[-1]
        lines.append(
            f"| `{name}` | {parse} | {default} | {owner or '—'} | {meaning} |"
        )
    return "\n".join(lines)


register(Checker(
    id=CHECKER_ID,
    title="HYDRAGNN_* env reads: one parse boundary, docs row per flag",
    rationale=(
        "PR 4's HYDRAGNN_DDSTORE_RETRIES malformed-value crash (hand-rolled "
        "int(os.getenv()) with no fallback) and a CONFIG.md env table that "
        "had drifted to a fraction of the names the code reads"
    ),
    run=run,
))
