"""error_codes: typed-error ``code`` strings are unique across the
package.

The serving plane's failure vocabulary (serve/errors.py) promises that
clients "branch on the failure *kind* without parsing messages" — every
exception class carries a stable ``code`` string, and the chaos smokes
assert on those codes. That promise dies quietly if two classes ever
claim the same code (a client's ``except``-by-code dispatch silently
handles the wrong failure), and nothing enforced it: the codes are plain
class attributes in whatever module grows the next typed error family
(serve today; the data plane's typed loader errors are the obvious next
one).

Rule: collect every class-level ``code = "<literal>"`` assignment in the
package; two classes sharing a literal is a finding on the second
definition.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from .core import Checker, Finding, Repo, register, str_const

CHECKER_ID = "error_codes"


def run(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    seen: Dict[str, Tuple[str, str, int]] = {}  # code -> (class, rel, line)
    for rel in sorted(repo.python_files()):
        src = repo.source(rel)
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for stmt in node.body:
                if (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "code"
                ):
                    code = str_const(stmt.value)
                    if code is None:
                        continue
                    if code in seen:
                        cls, prel, pline = seen[code]
                        findings.append(Finding(
                            CHECKER_ID, rel, stmt.lineno,
                            f"typed-error code {code!r} on {node.name} is "
                            f"already claimed by {cls} ({prel}:{pline}) — "
                            "clients dispatching by code will handle the "
                            "wrong failure",
                            hint="pick a distinct code string; codes are "
                                 "API, never recycled",
                        ))
                    else:
                        seen[code] = (node.name, rel, stmt.lineno)
    return findings


register(Checker(
    id=CHECKER_ID,
    title="typed-error code strings unique package-wide",
    rationale=(
        "serve/errors.py promises code-string dispatch to clients and the "
        "chaos smokes assert on codes; a duplicated code silently routes "
        "a client's error handling to the wrong failure kind"
    ),
    run=run,
))
