"""graftlint — the repo-native static-analysis plane.

``python -m hydragnn_tpu.analysis`` runs every checker over the repo and
exits nonzero on any unwaived finding (the ci.sh gate). One checker = one
module in this package; docs/ANALYSIS.md is the catalog. Pure host-side
AST/text analysis — importing this package never imports jax.
"""

from .core import (  # noqa: F401
    ANALYSIS_SCHEMA_VERSION,
    Checker,
    Finding,
    Repo,
    apply_baseline,
    baseline_key,
    checkers,
    default_root,
    run_checkers,
    summarize,
    to_json,
)


def analyze(root=None, only=None):
    """Run the full checker suite over ``root`` (default: the repo this
    package sits in). Returns the finding list — the API the run doctor's
    ``static_findings`` record and the fixture tests share with the CLI."""
    repo = Repo(root or default_root())
    return run_checkers(repo, only=only)
